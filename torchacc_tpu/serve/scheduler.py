"""Continuous-batching scheduler over the paged KV cache.

The design mirrors the PR-5 trainer split (train/trainer.py): a
STATELESS JITTED device step over (params, pools, slot state) and a
HOST-SIDE loop that owns every decision — admission into free slots,
which sequence prefills this iteration, eviction of finished sequences,
block free/reuse.  Three compiled programs cover any request mix:

- ``decode_step``: one token for every slot in one batched program.
  Sampling runs ON DEVICE with per-slot traced (temperature, top_k,
  top_p), and the sampled tokens feed the next iteration's input as a
  device array — the token feedback loop never touches the host.
- ``prefill_chunk``: ``serve.prefill_chunk`` tokens of ONE sequence
  (padded; the pad tail writes to the null block), interleaved with
  decode so a long prompt never stalls in-flight decodes.  With
  ``serve.prefill_batch > 1`` one iteration instead prefills up to
  that many chunks from DISTINCT waiting sequences in a single
  dispatched program (rows padded to the [prefill_batch,
  prefill_chunk] geometry — trace count stays 1; the head projects
  only each row's last valid token, the one row whose logits anyone
  reads).
- ``sample_first`` / ``set_slot``: sample the first token from the
  final prefill chunk's logits and splice it into the decode carry —
  tiny jitted ops, no readback.
- ``cow``: copy one pool block's k/v to another across all layers —
  the copy-on-write step behind a fully-cached prompt (see admit()).

Prefix cache (``serve.prefix_cache`` — kv_cache.PrefixIndex): admit()
maps the longest token-hash-chain match of a new prompt onto resident
blocks (refcount++ — zero recompute, zero copies) and starts prefill
past them; when the match covers the WHOLE prompt, the last matched
block is copy-on-written into a private block and only the final
prompt token re-runs (its logits are needed to sample the first output
token; its k/v write lands in the private copy, never the shared
block), so a warm prompt's TTFT is one final-chunk dispatch.  Blocks
register in the index as their prefill chunk completes, which means a
live sequence's prompt blocks are matchable immediately — concurrent
requests behind the same system prompt share from the first one that
prefilled it, not the first one that finished.

Host reads happen only at lag ``serve.decode_depth - 1`` through the
in-flight ring (the PR-5 lagged-readback pattern): iteration i's
sampled tokens are fetched while iteration i+k is dispatching, so the
per-token host sync sits off the critical path.  Consequences the
engine handles:

- a sequence is noticed finished (eos / max_new) up to k iterations
  late; the extra garbage tokens are dropped on the host;
- its blocks are freed DEFERRED — only after every dispatched
  iteration that could still write through the old block table has
  resolved — so a freed block can never alias a live sequence's cache
  (tested: test_block_free_never_aliases_live_blocks).

Admission therefore reserves ``prompt + max_new + decode_depth``
token slots of blocks up front: the overhang covers in-flight
iterations that keep writing after the finish condition.
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from torchacc_tpu.obs import tracing
from torchacc_tpu.ops.paged_attention import paged_attention
from torchacc_tpu.resilience.chaos import failpoint
from torchacc_tpu.serve.kv_cache import (
    BlockPool,
    PrefixIndex,
    blocks_needed,
    make_pools,
)
from torchacc_tpu.utils.logger import logger
from torchacc_tpu.utils.metrics import counters


# every ModelConfig field the paged forward (_layer/_forward) has been
# audited against — the rejection below is effectively an ALLOWLIST: a
# field added to ModelConfig after this audit raises at engine
# construction instead of being silently ignored by the re-implemented
# layer forward (which would decode tokens that diverge from
# generate() with no error).  When auditing a new field, either handle
# it in _layer/_forward, add it to the denylist checks, or confirm it
# cannot affect decode numerics — then add it here.
_AUDITED_MODEL_FIELDS = frozenset({
    "activation", "attention_impl", "attn_dropout", "attn_logit_softcap",
    "cache_len", "context_parallel", "decode", "dtype", "embed_scale",
    "head_bias", "head_dim", "hidden_size", "intermediate_size",
    "layer_pattern", "logical_axis_rules", "logit_scale", "logit_softcap",
    "max_seq_len", "mlp_bias", "moe_capacity_factor", "moe_dispatch",
    "moe_renorm_topk", "norm", "norm_bias", "norm_eps", "norm_placement",
    "num_experts", "num_experts_per_tok", "num_heads", "num_kv_heads",
    "num_layers", "o_bias", "parallel_block",
    "parallel_block_shared_norm", "param_dtype", "partial_rotary",
    "pos_emb", "pp_num_micro", "pp_size", "pp_virtual", "qk_norm",
    "qk_norm_proj", "qkv_bias", "query_scale", "remat", "remat_cls",
    "remat_cnt", "remat_policy", "rope_interleaved", "rope_llama3",
    "rope_local_theta", "rope_longrope", "rope_scale", "rope_theta",
    "rope_yarn", "router_aux_weight", "sandwich_norms", "scan_layers",
    "tie_embeddings", "tp_vocab_head", "vocab_size", "window",
    # PR-7 audit: quant* select TRAIN-forward matmul execution only —
    # the param layout is unchanged and inference runs in the compute
    # dtype (generate() strips quant; PagedDecoder's hand-written
    # layer never quantizes), so a quant-trained model serves exactly
    # like its unquantized twin.  overlap_fsdp only reshapes the train
    # layer loop (scan vs unrolled prefetch); PagedDecoder owns its
    # own loop and never consults it.
    "quant", "quant_sites", "quant_amax_history_len", "quant_impl",
    "overlap_fsdp",
})


def _check_supported(cfg) -> None:
    """The v1 serving surface: standard dense pre-norm decoders (the
    llama/qwen/gpt2/gemma-dense families).  Everything else raises a
    typed error here instead of decoding garbage."""
    import dataclasses
    unknown = ({f.name for f in dataclasses.fields(cfg)}
               - _AUDITED_MODEL_FIELDS)
    if unknown:
        raise NotImplementedError(
            f"ModelConfig grew fields the serving forward has not been "
            f"audited against: {sorted(unknown)}.  Audit their effect "
            f"on PagedDecoder._layer/_forward (scheduler.py) and add "
            f"them to _AUDITED_MODEL_FIELDS.")
    bad = []
    if cfg.num_experts > 0:
        bad.append("MoE (num_experts > 0)")
    if cfg.pp_size > 1:
        bad.append("pipeline parallelism (pp_size > 1)")
    if cfg.context_parallel:
        bad.append("context parallelism")
    if cfg.layer_pattern:
        bad.append("layer_pattern (per-layer sliding windows)")
    if cfg.parallel_block:
        bad.append("parallel_block")
    if cfg.sandwich_norms:
        bad.append("sandwich_norms")
    if cfg.norm_placement != "pre":
        bad.append(f"norm_placement={cfg.norm_placement!r}")
    if cfg.pos_emb == "alibi":
        bad.append("pos_emb='alibi'")
    if tuple(cfg.window) != (-1, -1):
        bad.append(f"sliding window {cfg.window}")
    if bad:
        raise NotImplementedError(
            "the serving engine (torchacc_tpu/serve) does not yet "
            "support: " + ", ".join(bad) + ".  Use models.generate for "
            "these models (batch-synchronous decode covers the full "
            "model zoo).")


class PagedDecoder:
    """The jitted device steps: a raw-params transformer forward over
    the paged pool (the established raw-params idiom of
    models/generate.py `_zoo_embed` / `head_logits`, numerically
    matched to the module's own apply)."""

    def __init__(self, cfg, serve_cfg, attention_impl: Optional[str] = None):
        _check_supported(cfg)
        self.cfg = cfg
        self.serve_cfg = serve_cfg
        self.impl = attention_impl or cfg.attention_impl
        self.block_size = serve_cfg.block_size
        self.chunk = serve_cfg.prefill_chunk
        self.max_slots = serve_cfg.max_slots
        # pools are donated: every step consumes and returns them, so
        # XLA updates the one preallocated buffer in place.  all_greedy
        # is static: the all-greedy trace (the serving default) skips
        # the two full-vocab sampling sorts entirely — argmax only —
        # while the mixed trace keeps the one-program-per-request-mix
        # property; both advance the slot PRNG keys identically, so
        # flipping between variants cannot drift a sampled stream
        self._decode = jax.jit(self._decode_impl, donate_argnums=(1, 2),
                               static_argnums=(9,))
        # is_final is static: the non-final trace skips the vocab head
        # entirely (its logits are discarded), the final trace keeps
        # the full-chunk head so first-token numerics are unchanged
        self._prefill = jax.jit(self._prefill_impl, donate_argnums=(1,),
                                static_argnums=(6,))
        # batched multi-sequence prefill: ONE trace for any mix of
        # final/non-final/padded rows (the head projects only the
        # gathered last-valid row of each sequence — [PB, H] x [H, V],
        # a decode-step-sized matmul, so there is no non-final trace to
        # skip it)
        self._prefill_batch = jax.jit(self._prefill_batch_impl,
                                      donate_argnums=(1,))
        self._sample_first = jax.jit(self._sample_first_impl)
        self._set_slot = jax.jit(self._set_slot_impl, donate_argnums=(0,))
        # copy-on-write: clone one pool block across all layers (the
        # fully-cached-prompt path in Scheduler.admit)
        self._cow = jax.jit(self._cow_impl, donate_argnums=(0,))

    # -- model forward ------------------------------------------------------

    def _dense(self, x, kernel, bias=None):
        cfg = self.cfg
        y = jnp.einsum("bth,h...->bt...", x.astype(cfg.dtype),
                       kernel.astype(cfg.dtype))
        if bias is not None:
            y = y + bias.astype(cfg.dtype)
        return y

    def _layer(self, p, x, positions, pools_l, tables, ctx_lens, blk, off):
        """One decoder layer over the paged cache.  ``blk``/``off``
        [S, T] name the pool slot every token writes its k/v to (the
        null block for masked tokens); ``ctx_lens`` is the post-write
        context length per slot."""
        from torchacc_tpu.models.transformer import Norm, _rope

        cfg = self.cfg
        kp, vp = pools_l
        s_, t_ = x.shape[:2]
        h = Norm(cfg).apply({"params": p["ln1"]}, x)
        attn = p["attn"]
        q = self._dense(h, attn["q_proj"]["kernel"],
                        attn["q_proj"].get("bias"))
        k = self._dense(h, attn["k_proj"]["kernel"],
                        attn["k_proj"].get("bias"))
        v = self._dense(h, attn["v_proj"]["kernel"],
                        attn["v_proj"].get("bias"))
        if cfg.qk_norm:
            if cfg.qk_norm_proj:
                q = Norm(cfg).apply({"params": attn["q_norm"]},
                                    q.reshape(s_, t_, -1)).reshape(q.shape)
                k = Norm(cfg).apply({"params": attn["k_norm"]},
                                    k.reshape(s_, t_, -1)).reshape(k.shape)
            else:
                q = Norm(cfg).apply({"params": attn["q_norm"]}, q)
                k = Norm(cfg).apply({"params": attn["k_norm"]}, k)
        if cfg.pos_emb == "rope":
            rp = (positions.astype(jnp.float32) / cfg.rope_scale
                  if cfg.rope_scale != 1.0 else positions)
            q, k = _rope(q, k, rp, cfg)
        # bank this chunk's (rotated) k / raw v into the pool, THEN
        # attend over the updated pool — same write-before-read order
        # as the module's dense-cache decode branch
        flat_b, flat_o = blk.reshape(-1), off.reshape(-1)
        kh, d = kp.shape[2], kp.shape[3]
        kp = kp.at[flat_b, flat_o].set(
            k.reshape(s_ * t_, kh, d).astype(kp.dtype))
        vp = vp.at[flat_b, flat_o].set(
            v.reshape(s_ * t_, kh, d).astype(vp.dtype))
        out = paged_attention(
            q, kp, vp, tables, ctx_lens, positions[:, 0],
            scale=cfg.query_scale, window=cfg.window,
            logit_softcap=cfg.attn_logit_softcap, impl=self.impl)
        x = x + self._dense(
            out.reshape(s_, t_, -1),
            attn["o_proj"]["kernel"].reshape(-1, cfg.hidden_size),
            attn["o_proj"].get("bias"))
        h2 = Norm(cfg).apply({"params": p["ln2"]}, x)
        mlp = p["mlp"]
        import flax.linen as nn
        if cfg.activation in ("swiglu", "geglu"):
            gate = self._dense(h2, mlp["gate_proj"]["kernel"],
                               mlp["gate_proj"].get("bias"))
            up = self._dense(h2, mlp["up_proj"]["kernel"],
                             mlp["up_proj"].get("bias"))
            act = nn.silu if cfg.activation == "swiglu" else nn.gelu
            ff = act(gate) * up
        else:
            up = self._dense(h2, mlp["up_proj"]["kernel"],
                             mlp["up_proj"].get("bias"))
            if cfg.activation == "relu2":
                ff = jnp.square(nn.relu(up))
            elif cfg.activation == "gelu_exact":
                ff = nn.gelu(up, approximate=False)
            else:
                ff = nn.gelu(up)
        x = x + self._dense(ff, mlp["down_proj"]["kernel"],
                            mlp["down_proj"].get("bias"))
        return x, (kp, vp)

    def _forward(self, params, pools, ids, positions, tables, ctx_lens,
                 blk, off):
        """(pools', hidden [S, T, H]): embed -> layer scan over the
        stacked params + per-layer pools.  The head projection is the
        caller's: decode projects every slot's single row, prefill
        projects ONLY the last valid row (the full-chunk head would be
        a C x hidden x vocab matmul that is discarded for every row
        but one)."""
        from torchacc_tpu.models.generate import _zoo_embed

        x = _zoo_embed(self.cfg, params, ids, positions)
        k_pools, v_pools = pools

        def body(carry, per):
            p_l, kp, vp = per
            y, (kp, vp) = self._layer(p_l["block"], carry, positions,
                                      (kp, vp), tables, ctx_lens, blk, off)
            return y, (kp, vp)

        x, (k_pools, v_pools) = jax.lax.scan(
            body, x, (params["layers"], k_pools, v_pools))
        return (k_pools, v_pools), x

    # -- sampling -----------------------------------------------------------

    def _sample_slots(self, logits, keys, temp, top_k, top_p):
        """Per-slot sampling with TRACED (temperature, top_k, top_p) —
        one compiled program for any request mix (the static-arg
        variant in models/generate._sample would recompile per
        combination).  temperature <= 0 is exact greedy (argmax),
        token-identical to generate()'s."""
        v = logits.shape[-1]
        greedy = jnp.argmax(logits, axis=-1)
        l = logits / jnp.maximum(temp, 1e-6)[:, None]
        # top-k: the k-th largest as cutoff, k <= 0 or >= vocab = off
        sorted_l = jnp.sort(l, axis=-1)[:, ::-1]
        kidx = jnp.clip(
            jnp.where((top_k <= 0) | (top_k >= v), v, top_k) - 1, 0, v - 1)
        kth = jnp.take_along_axis(sorted_l, kidx[:, None], axis=-1)
        l = jnp.where(l < kth, -jnp.inf, l)
        # nucleus on the k-truncated logits (generate._sample order);
        # the argmax is always kept so top_p <= 0 degrades to greedy
        sorted2 = jnp.sort(l, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted2, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep = cum - probs < top_p[:, None]
        keep = keep.at[:, 0].set(True)
        pth = jnp.min(jnp.where(keep, sorted2, jnp.inf), axis=-1,
                      keepdims=True)
        # top_p >= 1 is OFF (generate._sample skips it statically) —
        # without the guard, f32 cumsum rounding to >= 1.0 early can
        # truncate tail tokens even at the default top_p=1.0
        l = jnp.where((l < pth) & (top_p[:, None] < 1.0), -jnp.inf, l)
        sampled = jax.vmap(jax.random.categorical)(keys, l)
        return jnp.where(temp <= 0, greedy, sampled).astype(jnp.int32)

    # -- jitted steps -------------------------------------------------------

    def _decode_impl(self, params, pools, carry, tables, seq_lens, active,
                     temp, top_k, top_p, all_greedy):
        """One decode token for every slot.  ``seq_lens`` is the banked
        length BEFORE this token; free slots (active=False) run on the
        null block and their sampled tokens are ignored by the host."""
        bs = self.block_size
        tok = carry["tok"]
        positions = seq_lens[:, None]
        blk = jnp.where(
            active,
            jnp.take_along_axis(tables, (seq_lens // bs)[:, None],
                                axis=1)[:, 0],
            0)
        off = jnp.where(active, seq_lens % bs, 0)
        ctx = jnp.where(active, seq_lens + 1, 0)
        pools, x = self._forward(params, pools, tok[:, None],
                                 positions, tables, ctx,
                                 blk[:, None], off[:, None])
        from torchacc_tpu.models.transformer import head_logits
        logits = head_logits(self.cfg, params, x)
        split = jax.vmap(jax.random.split)(carry["key"])
        if all_greedy:
            toks = jnp.argmax(logits[:, 0], axis=-1).astype(jnp.int32)
        else:
            toks = self._sample_slots(logits[:, 0], split[:, 1], temp,
                                      top_k, top_p)
        return pools, {"tok": toks, "key": split[:, 0]}, toks

    def _prefill_impl(self, params, pools, table_row, t0, tokens, n_valid,
                      is_final):
        """One chunk of ONE sequence: bank k/v for tokens
        [t0, t0 + n_valid) and return the last valid row's logits (the
        first-token sampling input when this is the final chunk;
        non-final chunks skip the C x hidden x vocab head matmul — its
        output is 100% discarded — and return None).  The pad tail
        writes to the null block and its positions clamp to the newest
        real position (keeps learned-position table lookups in range
        and longrope's max(positions) regime switch exact)."""
        bs, c = self.block_size, self.chunk
        i = jnp.arange(c, dtype=jnp.int32)
        valid = i < n_valid
        pos = t0 + i
        last_pos = jnp.maximum(t0 + n_valid - 1, 0)
        positions = jnp.where(valid, pos, last_pos)[None]          # [1, C]
        blk = jnp.where(valid, table_row[pos // bs], 0)
        off = jnp.where(valid, pos % bs, 0)
        ctx = (t0 + n_valid)[None]
        pools, x = self._forward(params, pools, tokens[None],
                                 positions, table_row[None], ctx,
                                 blk[None], off[None])
        if not is_final:
            return pools, None
        from torchacc_tpu.models.transformer import head_logits
        logits = head_logits(self.cfg, params, x)
        last = jnp.take_along_axis(
            logits[0], jnp.maximum(n_valid - 1, 0)[None, None],
            axis=0)[0]                                             # [V]
        return pools, last

    def _prefill_batch_impl(self, params, pools, table_rows, t0s, tokens,
                            n_valids):
        """One chunk each of up to ``prefill_batch`` DISTINCT sequences
        in one program: ``table_rows`` [PB, MB], ``t0s``/``n_valids``
        [PB] (0 valid = padded row: runs on the null block, output
        discarded), ``tokens`` [PB, C].  Returns the last valid row's
        logits per sequence [PB, V] — the only rows anyone reads (final
        rows sample their first token from them; non-final and padded
        rows are ignored by the host), so the head is a [PB, H] x
        [H, V] matmul, not the full-chunk head, and final-vs-non-final
        needs no static flag: trace count is 1."""
        bs, c = self.block_size, self.chunk
        i = jnp.arange(c, dtype=jnp.int32)[None, :]              # [1, C]
        valid = i < n_valids[:, None]                            # [PB, C]
        pos = t0s[:, None] + i
        last_pos = jnp.maximum(t0s + n_valids - 1, 0)[:, None]
        positions = jnp.where(valid, pos, last_pos)              # [PB, C]
        blk = jnp.where(
            valid, jnp.take_along_axis(table_rows, pos // bs, axis=1), 0)
        off = jnp.where(valid, pos % bs, 0)
        ctx = t0s + n_valids                                     # [PB]
        pools, x = self._forward(params, pools, tokens, positions,
                                 table_rows, ctx, blk, off)
        from torchacc_tpu.models.transformer import head_logits
        last = jnp.take_along_axis(
            x, jnp.maximum(n_valids - 1, 0)[:, None, None], axis=1)
        logits = head_logits(self.cfg, params, last)             # [PB, 1, V]
        return pools, logits[:, 0]

    def _cow_impl(self, pools, src, dst):
        """Copy block ``src``'s k/v into block ``dst`` across every
        layer — the copy-on-write behind a fully-cached prompt: the
        final prompt token must re-run (its logits seed the first
        sampled token) and its k/v write needs a block this sequence
        owns; everything before it stays shared."""
        kp, vp = pools
        kp = kp.at[:, dst].set(kp[:, src])
        vp = vp.at[:, dst].set(vp[:, src])
        return kp, vp

    def _sample_first_impl(self, logits, key, temp, top_k, top_p):
        return self._sample_slots(logits[None], key[None], temp[None],
                                  top_k[None], top_p[None])[0]

    def _set_slot_impl(self, carry, slot, token, key):
        return {"tok": carry["tok"].at[slot].set(token),
                "key": carry["key"].at[slot].set(key)}


@dataclasses.dataclass
class Sequence:
    """Host-side runtime state of one admitted request."""

    sid: int
    prompt: np.ndarray                       # int32 [P]
    max_new: int
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    eos_id: Optional[int] = None
    seed: int = 0
    # 'priority' policy inputs: higher priority = more urgent;
    # deadline is ABSOLUTE host monotonic time (engine.submit converts
    # the request's relative deadline_s), inf = none
    priority: int = 0
    deadline: float = float("inf")
    # streaming: called as on_token(token, t_monotonic) when the lagged
    # ring resolves each token (<= decode_depth - 1 iterations after
    # dispatch) — engine.submit(..., on_token=...) plumbs it here
    on_token: Any = None
    # end-to-end trace id (engine.submit assigns it): rides every serve
    # span this request participates in — `trace` on its own spans
    # (queue/admit/single prefill), `traces` on the batched ones
    # (batched prefill, decode, deliver) — and surfaces in
    # RequestResult.trace_id
    trace_id: str = ""
    # runtime
    slot: int = -1
    blocks: List[int] = dataclasses.field(default_factory=list)
    prefilled: int = 0
    out_tokens: List[int] = dataclasses.field(default_factory=list)
    finished: bool = False
    finish_reason: str = ""
    key: Any = None                          # host-held PRNG key
    # prefix-cache runtime (admit() fills these)
    block_keys: Optional[List[bytes]] = None  # chain key per full block
    registered: int = 0                      # prompt blocks indexed so far
    cached_tokens: int = 0                   # prompt tokens NOT recomputed
    shared_blocks: int = 0                   # blocks reused via refcount
    cow: bool = False                        # fully-cached prompt path
    # metrics timestamps (host wall clock; engine fills t_submit)
    t_submit: float = 0.0
    t_admit: float = 0.0
    t_first_token: float = 0.0
    t_finish: float = 0.0
    token_times: List[float] = dataclasses.field(default_factory=list)

    @property
    def prompt_len(self) -> int:
        return int(self.prompt.shape[0])


def priority_key(seq: "Sequence", now: float, aging_s: float):
    """'priority' policy ordering — the ONE home for the semantics, so
    admission (engine._admit) and prefill order (scheduler.
    _prefill_candidates) can never drift apart: effective class
    descending (declared class + 1 per ``aging_s`` seconds waited — the
    starvation bound: any request eventually outranks any fixed class),
    then earliest deadline, then arrival."""
    eff = seq.priority + (int((now - seq.t_submit) / aging_s)
                          if aging_s > 0 else 0)
    return (-eff, seq.deadline, seq.sid)


@dataclasses.dataclass
class _InFlight:
    """One dispatched-but-unresolved iteration in the readback ring."""

    kind: str                                # 'decode' | 'first'
    tokens: Any                              # device array
    slots: List[Tuple[int, Sequence]] = dataclasses.field(
        default_factory=list)                # decode snapshot
    seq: Optional[Sequence] = None           # 'first' entries
    iter_idx: int = -1                       # decode iteration index
    t_dispatch: float = 0.0


class Scheduler:
    """Slot + block bookkeeping and the iteration loop.

    One ``step()`` = (at most) one prefill chunk + one batched decode
    step + ring resolution down to ``decode_depth - 1`` in flight.
    """

    def __init__(self, model_cfg, params, serve_cfg,
                 attention_impl: Optional[str] = None, blocked=None):
        self.cfg = model_cfg
        self.serve_cfg = serve_cfg
        self.params = params
        self.blocked = blocked               # optional BlockedMeter
        self.decoder = PagedDecoder(model_cfg, serve_cfg, attention_impl)
        # shared-prefix KV reuse: the index maps token-hash chains to
        # resident blocks; the pool refcounts them and parks refcount-0
        # indexed blocks in its cached LRU instead of freeing
        self.prefix = (PrefixIndex(serve_cfg.block_size)
                       if serve_cfg.prefix_cache else None)
        self.pool = BlockPool(serve_cfg.num_blocks, index=self.prefix)
        self.k_pools, self.v_pools = make_pools(model_cfg, serve_cfg)
        s = serve_cfg.max_slots
        # table width bounds the LONGEST admissible sequence, not the
        # pool: the attention cost per decode token scales with table
        # width (the fallback gathers [S, MB*BS] per layer; the kernel
        # runs MB grid steps per slot/head), so sizing it num_blocks-1
        # would make growing the pool for more concurrency inflate
        # every slot's per-token cost.  The model's position reach
        # (max_seq_len) plus the in-flight overhang is the natural
        # bound; submit() rejects anything needing more.
        self.max_blocks_per_seq = min(
            serve_cfg.num_blocks - 1,
            blocks_needed(model_cfg.max_seq_len + serve_cfg.decode_depth,
                          serve_cfg.block_size))
        self.tables = np.zeros((s, self.max_blocks_per_seq), np.int32)
        self.seq_lens = np.zeros((s,), np.int32)
        self.active = np.zeros((s,), bool)
        self.temp = np.zeros((s,), np.float32)
        self.top_k = np.zeros((s,), np.int32)
        self.top_p = np.ones((s,), np.float32)
        self.slot_seq: List[Optional[Sequence]] = [None] * s
        self.carry = {
            "tok": jnp.zeros((s,), jnp.int32),
            "key": jnp.asarray(
                np.stack([np.asarray(jax.random.PRNGKey(i))
                          for i in range(s)]), jnp.uint32),
        }
        self._ring: "collections.deque[_InFlight]" = collections.deque()
        self._iter = 0            # decode iterations dispatched
        self._resolved = 0        # decode iterations resolved
        self._deferred: List[Tuple[int, List[int]]] = []
        # newly finished sequences, drained by the engine each step —
        # completion accounting stays O(finished this step), never a
        # scan over every request the process has served
        self.finished: List[Sequence] = []
        # device copies of the membership-stable host arrays (tables,
        # active, sampling params), re-uploaded only when admission /
        # prefill-completion / eviction dirties them — seq_lens changes
        # every decode iteration and is always uploaded fresh
        self._dev_stable = None

    # -- admission ----------------------------------------------------------

    def blocks_for(self, seq: Sequence) -> int:
        """Blocks reserved at admission: prompt + max_new + the
        in-flight overhang (a finished slot keeps writing for up to
        decode_depth iterations before the host notices)."""
        return blocks_needed(
            seq.prompt_len + seq.max_new + self.serve_cfg.decode_depth,
            self.serve_cfg.block_size)

    def free_slot(self) -> Optional[int]:
        for i, s in enumerate(self.slot_seq):
            if s is None:
                return i
        return None

    def min_fresh_blocks(self, seq: Sequence) -> int:
        """Cheapest POSSIBLE fresh-block need (best case: every full
        prompt block is a prefix hit) — the engine's O(Q) admission
        early-exit bound.  No hashing, so it may be optimistic; only
        ``admit`` itself is authoritative."""
        total = self.blocks_for(seq)
        if self.prefix is None:
            return total
        return max(1, total - seq.prompt_len // self.serve_cfg.block_size)

    def can_admit(self, seq: Sequence) -> bool:
        return (self.free_slot() is not None
                and self.pool.can_alloc(self.blocks_for(seq)))

    def admit(self, seq: Sequence) -> bool:
        """Give ``seq`` a decode slot + its whole block reservation, or
        return False with NO state change (all-or-nothing; the engine
        retries next iteration).  With the prefix cache on, the longest
        token-hash-chain match replaces that many fresh blocks with
        refcounted shared ones and prefill starts past them."""
        if not tracing.enabled():
            return self._admit_impl(seq)
        t0 = time.perf_counter()
        ok = self._admit_impl(seq)
        # spans only for SUCCESSFUL admissions: a saturated engine
        # re-attempts its queue head every iteration, and one
        # admitted=False span per retry would evict the useful spans
        # from the bounded ring exactly when an operator exports it
        # (failed-admission pressure is visible as serve_queue_depth
        # + kv_pool_free_blocks instead)
        if ok:
            now = time.perf_counter()
            tracing.record_span("serve/admit", t0, now, sid=seq.sid,
                                trace=seq.trace_id,
                                cached_tokens=seq.cached_tokens)
            if seq.t_submit:
                # the queue-wait interval, recorded at the only moment
                # both endpoints are known (submit -> slot admission)
                tracing.record_span(
                    "serve/queue",
                    now - max(seq.t_admit - seq.t_submit, 0.0), now,
                    sid=seq.sid, trace=seq.trace_id)
        return ok

    def _admit_impl(self, seq: Sequence) -> bool:
        slot = self.free_slot()
        if slot is None:
            return False
        total = self.blocks_for(seq)
        shared: List[int] = []
        cow_src: Optional[int] = None
        if self.prefix is not None:
            # hash once, not per attempt: a queued request re-attempts
            # admission every engine iteration while it waits for blocks
            if seq.block_keys is None:
                seq.block_keys = self.prefix.keys(seq.prompt)
            shared = self.prefix.match(seq.block_keys)
            if shared and (len(shared) * self.serve_cfg.block_size
                           >= seq.prompt_len):
                # fully cached prompt: the final token must still run
                # (its logits seed the first sampled token) and its k/v
                # write needs a block this sequence owns — copy-on-write
                # the last matched block, share the rest
                cow_src = shared.pop()
        # pin the match BEFORE alloc: alloc may evict cached refcount-0
        # blocks to cover the grant, and it must not reclaim the match
        for b in shared:
            self.pool.share(b)
        if cow_src is not None:
            self.pool.share(cow_src)
        fresh = self.pool.alloc(total - len(shared))
        if fresh is None:
            # roll back the pins — admission never partially grants
            self.pool.free(shared)
            if cow_src is not None:
                self.pool.free([cow_src])
            return False
        blocks = shared + fresh
        seq.slot = slot
        seq.blocks = blocks
        seq.key = jax.random.PRNGKey(seq.seed)
        seq.t_admit = time.monotonic()
        cached = len(shared) * self.serve_cfg.block_size
        if cow_src is not None:
            # dst is fresh[0] == table index len(shared): the copy sits
            # exactly where the popped match sat.  Device program order
            # makes the copy read src before any later program could
            # recycle it, so the pin can drop right after dispatch.
            pools = (self.k_pools, self.v_pools)
            self.k_pools, self.v_pools = self.decoder._cow(
                pools, jnp.asarray(cow_src, jnp.int32),
                jnp.asarray(fresh[0], jnp.int32))
            self.pool.free([cow_src])
            cached = seq.prompt_len - 1
            seq.cow = True
            counters.inc("cow_copies")
        seq.prefilled = cached
        seq.cached_tokens = cached
        seq.shared_blocks = len(shared)
        seq.registered = len(shared)
        if cached:
            # engine.stats() aggregates the per-sequence fields at
            # completion; these global counters are the operator's
            # process-wide degradation/observability surface
            counters.inc("prefix_hits")
            if shared:
                counters.inc("prefix_blocks_reused", len(shared))
        self.slot_seq[slot] = seq
        self.tables[slot, :] = 0
        self.tables[slot, :len(blocks)] = blocks
        self.seq_lens[slot] = cached
        self.active[slot] = False          # decode starts after prefill
        self.temp[slot] = seq.temperature
        self.top_k[slot] = seq.top_k
        self.top_p[slot] = seq.top_p
        self._dev_stable = None
        return True

    def flush_prefix_cache(self) -> int:
        """Drop every cached prefix block + index entry; returns the
        block count.  The weight-swap seam (engine.load_params): k/v
        banked under old weights must never satisfy a prompt served
        under new ones.  The caller guarantees no live sequences."""
        if self.prefix is None:
            return 0
        return self.pool.flush_cached()

    # -- the iteration ------------------------------------------------------

    def _prefill_candidates(self) -> List[Sequence]:
        """Up to ``prefill_batch`` distinct sequences with prompt left
        to prefill, most-urgent first ('priority' policy: class then
        deadline — the same order admission used; otherwise arrival)."""
        cands = [s for s in self.slot_seq
                 if s is not None and not s.finished
                 and s.prefilled < s.prompt_len]
        if not cands:
            return []
        if self.serve_cfg.policy == "priority":
            # the same effective class admission uses, so a request
            # that aged past a higher class keeps its precedence once
            # both occupy slots
            now = time.monotonic()
            aging = self.serve_cfg.priority_aging_s
            cands.sort(key=lambda s: priority_key(s, now, aging))
        else:
            cands.sort(key=lambda s: s.sid)
        return cands[:self.serve_cfg.prefill_batch]

    def step(self) -> bool:
        """One engine iteration.  Returns True when any device work was
        dispatched (False = idle: nothing admitted, prefilling or
        decoding)."""
        did = False
        seqs = self._prefill_candidates()
        if seqs:
            if len(seqs) == 1:
                # a lone prefilling sequence (prefill_batch == 1, or
                # the steady-state trickle under a bigger batch) takes
                # the single-sequence program — no pad rows burning
                # prefill_batch x the FLOPs on the null block
                self._prefill_one(seqs[0])
            else:
                self._prefill_batched(seqs)
            did = True
        if self.active.any():
            self._decode_once()
            did = True
        # lagged resolution: keep at most decode_depth - 1 in flight
        while len(self._ring) >= self.serve_cfg.decode_depth:
            self._resolve_one()
        if not did:
            # nothing in flight can mature on its own — resolve one
            # entry so finishes/evictions make progress
            if self._ring:
                self._resolve_one()
                did = True
        self._release_matured()
        return did

    def _prefill_one(self, seq: Sequence) -> None:
        c = self.serve_cfg.prefill_chunk
        t0 = seq.prefilled
        chunk = seq.prompt[t0:t0 + c]
        n_valid = int(chunk.shape[0])
        if n_valid < c:
            chunk = np.pad(chunk, (0, c - n_valid))
        pools = (self.k_pools, self.v_pools)
        final = (t0 + n_valid) >= seq.prompt_len
        with tracing.span("serve/prefill", sid=seq.sid, t0=t0,
                          tokens=n_valid, batched=False,
                          trace=seq.trace_id):
            pools, last_logits = self.decoder._prefill(
                self.params, pools, jnp.asarray(self.tables[seq.slot]),
                jnp.asarray(t0, jnp.int32), jnp.asarray(chunk, jnp.int32),
                jnp.asarray(n_valid, jnp.int32), final)
        self.k_pools, self.v_pools = pools
        seq.prefilled += n_valid
        self.seq_lens[seq.slot] = seq.prefilled
        self._register_prefix(seq)
        if seq.prefilled >= seq.prompt_len:
            self._seed_first_token(seq, last_logits)

    def _prefill_batched(self, seqs: List[Sequence]) -> None:
        """One chunk each of up to ``prefill_batch`` sequences in a
        single dispatched program.  Short rows pad to [prefill_batch,
        prefill_chunk] (pad rows run on the null block, outputs
        discarded) so the program traces exactly once."""
        pb = self.serve_cfg.prefill_batch
        c = self.serve_cfg.prefill_chunk
        tables = np.zeros((pb, self.max_blocks_per_seq), np.int32)
        t0s = np.zeros((pb,), np.int32)
        toks = np.zeros((pb, c), np.int32)
        n_valids = np.zeros((pb,), np.int32)
        taken = []
        for r, seq in enumerate(seqs):
            t0 = seq.prefilled
            chunk = seq.prompt[t0:t0 + c]
            n = int(chunk.shape[0])
            tables[r] = self.tables[seq.slot]
            t0s[r] = t0
            toks[r, :n] = chunk
            n_valids[r] = n
            taken.append(n)
        pools = (self.k_pools, self.v_pools)
        with tracing.span("serve/prefill", batched=True,
                          sids=[s.sid for s in seqs],
                          traces=[s.trace_id for s in seqs],
                          tokens=int(sum(taken))):
            pools, logits = self.decoder._prefill_batch(
                self.params, pools, jnp.asarray(tables), jnp.asarray(t0s),
                jnp.asarray(toks), jnp.asarray(n_valids))
        self.k_pools, self.v_pools = pools
        for r, seq in enumerate(seqs):
            seq.prefilled += taken[r]
            self.seq_lens[seq.slot] = seq.prefilled
            self._register_prefix(seq)
            if seq.prefilled >= seq.prompt_len:
                self._seed_first_token(seq, logits[r])

    def _register_prefix(self, seq: Sequence) -> None:
        """Index every newly completed FULL prompt block so later (and
        concurrent) prompts can share it.  First writer wins: blocks
        whose chain key is already mapped (the shared match itself, the
        COW copy, a concurrent identical prompt) stay private."""
        if self.prefix is None or not seq.block_keys:
            return
        n_full = min(seq.prefilled, seq.prompt_len) \
            // self.serve_cfg.block_size
        while seq.registered < n_full:
            i = seq.registered
            self.prefix.register(seq.block_keys[i], seq.blocks[i])
            seq.registered += 1

    def _seed_first_token(self, seq: Sequence, last_logits) -> None:
        """Final prefill chunk done: sample the first generated token
        on device and splice it into the decode carry — no readback;
        the host learns it through the ring like any other token."""
        seq.key, sub = jax.random.split(seq.key)
        tok = self.decoder._sample_first(
            last_logits, sub,
            jnp.asarray(seq.temperature, jnp.float32),
            jnp.asarray(seq.top_k, jnp.int32),
            jnp.asarray(seq.top_p, jnp.float32))
        seq.key, slot_key = jax.random.split(seq.key)
        self.carry = self.decoder._set_slot(
            self.carry, jnp.asarray(seq.slot, jnp.int32), tok,
            slot_key.astype(jnp.uint32))
        self.active[seq.slot] = True
        self._dev_stable = None
        self._ring.append(_InFlight(
            kind="first", tokens=tok, seq=seq,
            t_dispatch=time.monotonic()))

    def _dev_stable_arrays(self):
        if self._dev_stable is None:
            self._dev_stable = (
                jnp.asarray(self.tables), jnp.asarray(self.active),
                jnp.asarray(self.temp), jnp.asarray(self.top_k),
                jnp.asarray(self.top_p))
        return self._dev_stable

    def _decode_once(self) -> None:
        # serve chaos seam (resilience/chaos.py): crash-mid-decode
        # (ChaosPlan.kill -> SIGKILL with sequences in flight — the
        # journal-replay gate) and decode-loop hang (ChaosPlan.hang ->
        # the serve_liveness health check flips, the supervisor probe
        # kills).  One global `is None` check when no plan is active.
        failpoint("serve.decode", iter=self._iter)
        snapshot = [(i, s) for i, s in enumerate(self.slot_seq)
                    if self.active[i] and s is not None]
        tables, active, temp, top_k, top_p = self._dev_stable_arrays()
        all_greedy = bool((self.temp[self.active] <= 0.0).all())
        pools = (self.k_pools, self.v_pools)
        # per-request trace ids on the batched span: built only while
        # tracing records (the list comprehension must cost nothing on
        # the disabled hot path)
        _traces = ([s.trace_id for _, s in snapshot]
                   if tracing.enabled() else None)
        with tracing.span("serve/decode", iter=self._iter,
                          slots=len(snapshot), traces=_traces):
            pools, self.carry, toks = self.decoder._decode(
                self.params, pools, self.carry,
                tables, jnp.asarray(self.seq_lens),
                active, temp, top_k, top_p, all_greedy)
        self.k_pools, self.v_pools = pools
        # host mirror: every active slot banked one more token
        self.seq_lens[self.active] += 1
        self._ring.append(_InFlight(
            kind="decode", tokens=toks, slots=snapshot,
            iter_idx=self._iter, t_dispatch=time.monotonic()))
        self._iter += 1

    # -- resolution / eviction ----------------------------------------------

    def _record(self, seq: Sequence, token: int, now: float) -> None:
        if seq.finished:
            return                 # lagged garbage after finish
        if not seq.out_tokens:
            seq.t_first_token = now
        seq.out_tokens.append(token)
        seq.token_times.append(now)
        if seq.on_token is not None:
            # streaming delivery: the callback sees each token at
            # resolution time — <= decode_depth - 1 iterations after
            # its dispatch, never a garbage post-finish token.  A
            # raising callback is disabled, not allowed to corrupt the
            # ring resolution for every other request.
            try:
                seq.on_token(token, now)
            except Exception:
                logger.exception(
                    f"on_token callback for request {seq.sid} raised; "
                    f"disabling the stream callback for this request")
                seq.on_token = None
        if seq.eos_id is not None and token == seq.eos_id:
            self._finish(seq, "eos", now)
        elif len(seq.out_tokens) >= seq.max_new:
            self._finish(seq, "length", now)

    def _finish(self, seq: Sequence, reason: str, now: float) -> None:
        seq.finished = True
        seq.finish_reason = reason
        seq.t_finish = now
        self.finished.append(seq)
        self._evict(seq)

    def preempt(self, seq: Sequence, now: float) -> None:
        """Evict an ADMITTED sequence before its natural finish (the
        engine's opt-in ``serve.preempt_deadlines`` sweep): typed
        ``finish_reason='preempted'`` with whatever tokens resolved so
        far, blocks released through the same deferred-free path as any
        eviction.  Safe mid-flight by the existing machinery: lagged
        ring entries for the evicted slot drop in :meth:`_record`'s
        post-finish guard, and the deferred free holds the blocks until
        every already-dispatched iteration resolves."""
        if seq.finished:
            return
        self._finish(seq, "preempted", now)

    def _evict(self, seq: Sequence) -> None:
        slot = seq.slot
        if slot < 0:
            return
        self.slot_seq[slot] = None
        self.active[slot] = False
        self.tables[slot, :] = 0
        self.seq_lens[slot] = 0
        seq.slot = -1
        self._dev_stable = None
        # DEFERRED free: iterations dispatched before this point may
        # still write through the old table — release only once every
        # decode iteration < self._iter has resolved
        self._deferred.append((self._iter, seq.blocks))
        seq.blocks = []
        self._release_matured()

    def _release_matured(self) -> None:
        ring_empty = not any(e.kind == "decode" for e in self._ring)
        keep = []
        for after, blocks in self._deferred:
            if self._resolved >= after or ring_empty:
                self.pool.free(blocks)
            else:
                keep.append((after, blocks))
        self._deferred = keep

    def _resolve_one(self) -> None:
        entry = self._ring.popleft()
        # stream-delivery span: token readback (the lagged blocking
        # fetch) + per-request recording incl. on_token callbacks
        _traces = None
        if tracing.enabled():
            _traces = ([entry.seq.trace_id] if entry.kind == "first"
                       else [s.trace_id for _, s in entry.slots])
        with tracing.span("serve/deliver", kind=entry.kind,
                          traces=_traces):
            if self.blocked is not None:     # the (only) blocking fetch
                with self.blocked.blocked():
                    toks = np.asarray(entry.tokens)
            else:
                toks = np.asarray(entry.tokens)
            now = time.monotonic()
            if entry.kind == "first":
                self._record(entry.seq, int(toks), now)
            else:
                for slot, seq in entry.slots:
                    self._record(seq, int(toks[slot]), now)
                self._resolved = entry.iter_idx + 1
        self._release_matured()

    def drain(self) -> None:
        """Resolve every in-flight iteration (engine shutdown / idle)."""
        while self._ring:
            self._resolve_one()
        self._release_matured()

    @property
    def pending(self) -> int:
        return len(self._ring)

    def busy(self) -> bool:
        return (any(s is not None for s in self.slot_seq)
                or bool(self._ring))
