"""Paged KV cache: the preallocated block pool + host-side allocator.

Memory layout (the vLLM PagedAttention idea expressed as JAX arrays):
ONE pool per layer of shape ``[num_blocks, block_size, kv_heads,
head_dim]`` for keys and the same for values, stacked over layers into
``[L, NB, BS, KH, D]``.  A sequence's cache is a list of blocks named
by its BLOCK TABLE; sequences of wildly different lengths share the
pool with at most ``block_size - 1`` wasted slots each, and a finished
sequence's blocks return to the free list as soon as every in-flight
iteration that could still write through its table has resolved (at
most ``decode_depth - 1`` iterations — scheduler._release_matured) —
no ``[batch, max_len]`` padding anywhere.

Block 0 is the NULL BLOCK: free decode slots (and masked-out prefill
tail tokens) write their garbage k/v there, so the jitted step needs
no write masking — the standard trick.  It is never handed out by the
allocator.

Prefix sharing (``serve.prefix_cache``): blocks are REFCOUNTED, and a
:class:`PrefixIndex` maps a hash chain over each FULL block of prompt
tokens (``key_i = blake2b(key_{i-1} || tokens[i*bs:(i+1)*bs])`` —
radix-style: position and content are both in the chain) to the pool
block holding that span's k/v.  A new prompt's longest cached prefix
resolves to existing blocks with zero recompute; a block whose last
reference drops moves to a CACHED LRU list instead of the free list,
where it stays matchable until the allocator reclaims it under
pressure.  Eviction only ever takes refcount-0 cached blocks, so the
whole-reservation admission guarantee survives: blocks owned by an
admitted sequence are untouchable until that sequence frees them.

The allocator is deliberately host-side and synchronous: allocation
decisions happen at admission time (serve/engine.py), outside the
jitted hot path, exactly like the trainer's host/device split
(train/trainer.py dispatch vs resolution).
"""

from __future__ import annotations

import collections
import hashlib
from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from torchacc_tpu.utils.metrics import counters


def blocks_needed(num_tokens: int, block_size: int) -> int:
    """Blocks required to hold ``num_tokens`` cache slots."""
    return -(-max(num_tokens, 0) // block_size)


class PrefixIndex:
    """Token-hash prefix index over pool blocks (radix-style chain).

    Each FULL block of a prompt gets a chain key: the blake2b digest of
    the parent block's key concatenated with this block's token ids.
    Chaining makes the key encode the block's absolute position AND the
    entire token prefix before it, so two entries collide only when the
    whole prefix up to and including the block is token-identical —
    exactly the condition under which the banked k/v is reusable
    (deterministic forward, same weights; serve/engine.load_params
    flushes the index on weight swaps).  16-byte digests make an
    accidental collision astronomically unlikely (~2^-128); there is no
    token-level compare on hit, which is the standard vLLM trade.

    The index never owns pool headroom: entries point at blocks that
    are either ALLOCATED (refcount >= 1, some live sequence reads them)
    or CACHED (refcount 0, parked in the pool's LRU).  ``forget`` is
    called by the pool when it evicts a cached block.
    """

    def __init__(self, block_size: int):
        self.block_size = block_size
        self._by_key: Dict[bytes, int] = {}
        self._key_of: Dict[int, bytes] = {}

    def __len__(self) -> int:
        return len(self._by_key)

    def keys(self, prompt: np.ndarray) -> List[bytes]:
        """Chain keys for every FULL block of ``prompt`` (a prompt
        shorter than one block has no keyable span)."""
        bs = self.block_size
        toks = np.ascontiguousarray(np.asarray(prompt, np.int32))
        out: List[bytes] = []
        parent = b""
        for i in range(int(toks.shape[0]) // bs):
            h = hashlib.blake2b(parent, digest_size=16)
            h.update(toks[i * bs:(i + 1) * bs].tobytes())
            parent = h.digest()
            out.append(parent)
        return out

    def match(self, keys: List[bytes]) -> List[int]:
        """The longest resident chain: blocks for keys[0..m) where every
        key hits.  Stops at the first miss — a surviving child whose
        parent was evicted is unreachable (and will age out of the LRU)
        but never wrongly matched."""
        blocks: List[int] = []
        for k in keys:
            b = self._by_key.get(k)
            if b is None:
                break
            blocks.append(b)
        return blocks

    def register(self, key: bytes, block: int) -> bool:
        """Map ``key`` -> ``block``; no-op (False) when the key is
        already mapped (first writer wins — concurrent identical
        prompts keep the earlier block, the later one stays private)
        or the block already carries a key."""
        if key in self._by_key or block in self._key_of:
            return False
        self._by_key[key] = block
        self._key_of[block] = key
        return True

    def owns(self, block: int) -> bool:
        return block in self._key_of

    def forget(self, block: int) -> None:
        k = self._key_of.pop(block, None)
        if k is not None:
            del self._by_key[k]

    def clear(self) -> int:
        """Drop every entry (weight swap / flush); returns the count."""
        n = len(self._by_key)
        self._by_key.clear()
        self._key_of.clear()
        return n


class BlockPool:
    """Refcounted free-list allocator over pool blocks 1..num_blocks-1.

    A block is in exactly one of three states:

    - FREE: on the free list, content garbage;
    - ALLOCATED: refcount >= 1 — handed to one ``alloc`` caller and
      possibly shared into other sequences' tables via :meth:`share`;
    - CACHED: refcount 0 but still holding reusable prefix k/v
      (``index.owns`` it), parked in an LRU from which :meth:`alloc`
      evicts oldest-first when the free list runs dry.

    Invariants (tested in tests/test_serving.py + test_prefix_cache.py):
    - block 0 (the null block) is never handed out;
    - ``free`` of a block with no outstanding reference raises
      (double-free / foreign-block detection — releasing a SHARED block
      once per sharer is legal, once more raises);
    - eviction only ever takes refcount-0 cached blocks, so an admitted
      sequence's reservation can never be reclaimed under it;
    - ``available + in_use == num_blocks - 1`` always (no leak;
      ``available`` counts free + cached since both are allocatable).
    """

    def __init__(self, num_blocks: int, index: Optional[PrefixIndex] = None):
        if num_blocks < 2:
            raise ValueError(
                f"need >= 2 blocks (block 0 is reserved), got {num_blocks}")
        self.num_blocks = num_blocks
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        self._ref: Dict[int, int] = {}
        self._cached: "collections.OrderedDict[int, None]" = \
            collections.OrderedDict()
        self._index = index
        self.evictions = 0

    @property
    def available(self) -> int:
        """Blocks an ``alloc`` could grant: free + evictable cached."""
        return len(self._free) + len(self._cached)

    @property
    def in_use(self) -> int:
        return len(self._ref)

    @property
    def cached(self) -> int:
        return len(self._cached)

    def refcount(self, block: int) -> int:
        return self._ref.get(block, 0)

    def can_alloc(self, n: int) -> bool:
        return n <= self.available

    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` blocks, or None when the pool lacks headroom (the
        admission-control signal — never a partial grant).  Evicts
        cached refcount-0 blocks oldest-first when the free list alone
        cannot cover the grant."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > self.available:
            return None
        while len(self._free) < n:
            b, _ = self._cached.popitem(last=False)      # LRU: oldest out
            if self._index is not None:
                self._index.forget(b)
            self.evictions += 1
            counters.inc("prefix_evictions")
            self._free.append(b)
        blocks = [self._free.pop() for _ in range(n)]
        for b in blocks:
            self._ref[b] = 1
        return blocks

    def share(self, block: int) -> None:
        """Take one more reference on an allocated block, or revive a
        cached one (prefix hit) — the block leaves the LRU and cannot
        be evicted until every reference drops."""
        if block in self._ref:
            self._ref[block] += 1
        elif block in self._cached:
            del self._cached[block]
            self._ref[block] = 1
        else:
            raise ValueError(
                f"share of block {block} which is neither allocated nor "
                f"cached (stale prefix-index entry, or a block this pool "
                f"never handed out)")

    def free(self, blocks: List[int]) -> None:
        """Release one reference per listed block.  The LAST release
        parks a prefix-indexed block in the cached LRU (most-recent
        end) instead of the free list, keeping its k/v matchable."""
        for b in blocks:
            r = self._ref.get(b)
            if r is None:
                raise ValueError(
                    f"free of block {b} which is not allocated (double "
                    f"free, or a block this pool never handed out)")
            if r > 1:
                self._ref[b] = r - 1
                continue
            del self._ref[b]
            if self._index is not None and self._index.owns(b):
                self._cached[b] = None
            else:
                self._free.append(b)

    def flush_cached(self) -> int:
        """Drop every cached refcount-0 block (and its index entries) —
        the weight-swap flush: banked k/v under old weights must never
        match a prompt served under new ones.  Blocks still referenced
        by live sequences are untouched (the caller guarantees there
        are none — serve/engine.load_params requires an idle engine)."""
        n = len(self._cached)
        while self._cached:
            b, _ = self._cached.popitem(last=False)
            if self._index is not None:
                self._index.forget(b)
            self._free.append(b)
        if self._index is not None:
            self._index.clear()
        return n


def make_pools(model_cfg, serve_cfg, dtype=None):
    """(k_pools, v_pools) of shape [L, NB, BS, KH, D] in the model's
    compute dtype, kv heads sharded over 'tp' when a mesh is live (the
    same activation-constraint seam the model layers use, so the TP
    head composes — parallel/sharding.py)."""
    from torchacc_tpu.parallel.sharding import activation_constraint

    shape = (model_cfg.num_layers, serve_cfg.num_blocks,
             serve_cfg.block_size, model_cfg.kv_heads,
             model_cfg.head_size)
    dt = dtype or model_cfg.dtype
    axes = (None, None, None, "heads", None)
    k = activation_constraint(jnp.zeros(shape, dt), axes)
    v = activation_constraint(jnp.zeros(shape, dt), axes)
    return k, v
