"""Paged KV cache: the preallocated block pool + host-side allocator.

Memory layout (the vLLM PagedAttention idea expressed as JAX arrays):
ONE pool per layer of shape ``[num_blocks, block_size, kv_heads,
head_dim]`` for keys and the same for values, stacked over layers into
``[L, NB, BS, KH, D]``.  A sequence's cache is a list of blocks named
by its BLOCK TABLE; sequences of wildly different lengths share the
pool with at most ``block_size - 1`` wasted slots each, and a finished
sequence's blocks return to the free list as soon as every in-flight
iteration that could still write through its table has resolved (at
most ``decode_depth - 1`` iterations — scheduler._release_matured) —
no ``[batch, max_len]`` padding anywhere.

Block 0 is the NULL BLOCK: free decode slots (and masked-out prefill
tail tokens) write their garbage k/v there, so the jitted step needs
no write masking — the standard trick.  It is never handed out by the
allocator.

The allocator is deliberately host-side and synchronous: allocation
decisions happen at admission time (serve/engine.py), outside the
jitted hot path, exactly like the trainer's host/device split
(train/trainer.py dispatch vs resolution).
"""

from __future__ import annotations

from typing import List, Optional

import jax.numpy as jnp


def blocks_needed(num_tokens: int, block_size: int) -> int:
    """Blocks required to hold ``num_tokens`` cache slots."""
    return -(-max(num_tokens, 0) // block_size)


class BlockPool:
    """Free-list allocator over pool blocks 1..num_blocks-1.

    Invariants (tested in tests/test_serving.py):
    - block 0 (the null block) is never allocated;
    - a block is owned by at most one caller at a time (no aliasing);
    - ``free`` of a block not currently allocated raises (double-free /
      foreign-block detection);
    - ``available + len(allocated) == num_blocks - 1`` always (no leak).
    """

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError(
                f"need >= 2 blocks (block 0 is reserved), got {num_blocks}")
        self.num_blocks = num_blocks
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        self._allocated: set = set()

    @property
    def available(self) -> int:
        return len(self._free)

    @property
    def in_use(self) -> int:
        return len(self._allocated)

    def can_alloc(self, n: int) -> bool:
        return n <= len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` blocks, or None when the pool lacks headroom (the
        admission-control signal — never a partial grant)."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            return None
        blocks = [self._free.pop() for _ in range(n)]
        self._allocated.update(blocks)
        return blocks

    def free(self, blocks: List[int]) -> None:
        for b in blocks:
            if b not in self._allocated:
                raise ValueError(
                    f"free of block {b} which is not allocated (double "
                    f"free, or a block this pool never handed out)")
            self._allocated.remove(b)
            self._free.append(b)


def make_pools(model_cfg, serve_cfg, dtype=None):
    """(k_pools, v_pools) of shape [L, NB, BS, KH, D] in the model's
    compute dtype, kv heads sharded over 'tp' when a mesh is live (the
    same activation-constraint seam the model layers use, so the TP
    head composes — parallel/sharding.py)."""
    from torchacc_tpu.parallel.sharding import activation_constraint

    shape = (model_cfg.num_layers, serve_cfg.num_blocks,
             serve_cfg.block_size, model_cfg.kv_heads,
             model_cfg.head_size)
    dt = dtype or model_cfg.dtype
    axes = (None, None, None, "heads", None)
    k = activation_constraint(jnp.zeros(shape, dt), axes)
    v = activation_constraint(jnp.zeros(shape, dt), axes)
    return k, v
