"""Serving subsystem: paged KV cache + continuous batching + front-end.

The training side of this framework mirrors the reference (TorchAcc is
training-only; its accuracy benchmark shells out to vLLM for
inference).  Serving here is native:

- :mod:`torchacc_tpu.serve.kv_cache` — fixed-size KV blocks in a
  preallocated pool with per-sequence block tables (the vLLM
  PagedAttention memory layout as JAX arrays) and the host-side block
  allocator.
- :mod:`torchacc_tpu.serve.scheduler` — the continuous-batching
  scheduler: a stateless jitted decode step over (params, pools, slot
  state), chunked prefill interleaved with decode, and a
  lagged-readback ring (the PR-5 dispatch-pipelining pattern) so
  per-token host sync stays off the critical path.
- :mod:`torchacc_tpu.serve.engine` — the request front-end: queue,
  admission control against KV-pool headroom, per-request SLO metrics
  (TTFT, per-token latency, queue wait) riding utils/metrics.  Also
  the live-weights seam of the checkpoint-free train→serve handoff:
  ``ServeEngine.from_train_state(trainer)`` /
  ``engine.load_params(trainer.serving_params())`` swap weights in
  place through the compiled layout-transfer engine
  (parallel/transfer.py) — no pool reallocation, no checkpoint I/O.

See docs/serving.md for architecture + tuning (and the "Live weight
handoff" section for the fit↔serve loop).
"""

from torchacc_tpu.serve.engine import Request, RequestResult, ServeEngine
from torchacc_tpu.serve.journal import (
    RequestJournal,
    read_journal,
    replay_state,
)
from torchacc_tpu.serve.kv_cache import (
    BlockPool,
    PrefixIndex,
    blocks_needed,
    make_pools,
)
from torchacc_tpu.serve.scheduler import PagedDecoder, Scheduler

__all__ = [
    "BlockPool",
    "PagedDecoder",
    "PrefixIndex",
    "Request",
    "RequestJournal",
    "RequestResult",
    "Scheduler",
    "ServeEngine",
    "blocks_needed",
    "make_pools",
    "read_journal",
    "replay_state",
]
