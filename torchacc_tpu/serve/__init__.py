"""Serving subsystem: paged KV cache + continuous batching + front-end.

The training side of this framework mirrors the reference (TorchAcc is
training-only; its accuracy benchmark shells out to vLLM for
inference).  Serving here is native:

- :mod:`torchacc_tpu.serve.kv_cache` — fixed-size KV blocks in a
  preallocated pool with per-sequence block tables (the vLLM
  PagedAttention memory layout as JAX arrays) and the host-side block
  allocator.
- :mod:`torchacc_tpu.serve.scheduler` — the continuous-batching
  scheduler: a stateless jitted decode step over (params, pools, slot
  state), chunked prefill interleaved with decode, and a
  lagged-readback ring (the PR-5 dispatch-pipelining pattern) so
  per-token host sync stays off the critical path.
- :mod:`torchacc_tpu.serve.engine` — the request front-end: queue,
  admission control against KV-pool headroom, per-request SLO metrics
  (TTFT, per-token latency, queue wait) riding utils/metrics.  Also
  the live-weights seam of the checkpoint-free train→serve handoff:
  ``ServeEngine.from_train_state(trainer)`` /
  ``engine.load_params(trainer.serving_params())`` swap weights in
  place through the compiled layout-transfer engine
  (parallel/transfer.py) — no pool reallocation, no checkpoint I/O.
- :mod:`torchacc_tpu.serve.router` / :mod:`~.serve.router_client` —
  the jax-free routing tier fronting N serve workers (prefix-affinity
  admission, circuit-breaking health, journal-backed failover).

Attribute access is lazy (PEP 562): importing the jax-free members —
``RequestJournal``/``read_journal``/``replay_state`` and the router —
must not drag in the jax-backed engine/scheduler, because the router
and the supervisor-side journal readers run on hosts that never
initialise a device backend.

See docs/serving.md for architecture + tuning (and the "Live weight
handoff" section for the fit↔serve loop, "Router tier" for the front
door).
"""

from typing import TYPE_CHECKING

#: exported name -> defining submodule (resolved on first access)
_EXPORTS = {
    "Request": "engine",
    "RequestResult": "engine",
    "ServeEngine": "engine",
    "RequestJournal": "journal",
    "read_journal": "journal",
    "replay_state": "journal",
    "BlockPool": "kv_cache",
    "PrefixIndex": "kv_cache",
    "blocks_needed": "kv_cache",
    "make_pools": "kv_cache",
    "PagedDecoder": "scheduler",
    "Scheduler": "scheduler",
    "Router": "router",
    "RouterConfig": "router",
    "RouterClient": "router_client",
}

__all__ = sorted(_EXPORTS)

if TYPE_CHECKING:  # pragma: no cover - static analysis only
    from torchacc_tpu.serve.engine import (  # noqa: F401
        Request,
        RequestResult,
        ServeEngine,
    )
    from torchacc_tpu.serve.journal import (  # noqa: F401
        RequestJournal,
        read_journal,
        replay_state,
    )
    from torchacc_tpu.serve.kv_cache import (  # noqa: F401
        BlockPool,
        PrefixIndex,
        blocks_needed,
        make_pools,
    )
    from torchacc_tpu.serve.router import Router, RouterConfig  # noqa: F401
    from torchacc_tpu.serve.router_client import RouterClient  # noqa: F401
    from torchacc_tpu.serve.scheduler import (  # noqa: F401
        PagedDecoder,
        Scheduler,
    )


def __getattr__(name: str):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")
    import importlib
    value = getattr(
        importlib.import_module(f"torchacc_tpu.serve.{mod}"), name)
    globals()[name] = value        # cache: one resolution per process
    return value


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
