"""Fault-tolerant serve routing tier (ROADMAP 1(b)).

A single stateless-looking front door over N supervised serve workers,
built from the same parts as the rest of the control plane — the shared
HTTP client (``utils/http.py``), the telemetry server's strict-JSON
GET/POST seams (``obs/server.py``), the durable request journal
(``serve/journal.py``) and the goodput ledger (``obs/goodput.py``).
Four legs:

- **Prefix-affinity admission** — :func:`chain_keys` reproduces the
  PrefixIndex block-chain hash (``serve/kv_cache.py``) byte-for-byte,
  so same-template traffic lands on the replica already holding the
  warm KV blocks.  With no affinity match the router falls back to
  power-of-two-choices over each worker's ``/admission`` snapshot
  (queue depth + busy slots, then free blocks).
- **Circuit-breaking health** — a per-worker :class:`CircuitBreaker`
  driven by ``/healthz`` probes: closed → open after N consecutive
  transport failures, half-open probe after a cooldown, closed again on
  probe success.  A degraded worker is *deprioritized, never killed* —
  process lifecycle belongs to the supervisor.
- **Journal-backed failover** — the router's own assignment journal IS
  a :class:`~torchacc_tpu.serve.journal.RequestJournal` (``accepted`` =
  assigned, ``completed`` = result harvested, ``shed`` = typed drop),
  so a ``kill -9`` of the router replays to the exact routed set.  When
  a *worker* dies mid-flight the resubmittable remainder is re-derived
  from that worker's journal (``read_journal``/``replay_state``) and
  re-routed to survivors under the original router rids; first terminal
  record wins, so a supervisor-restarted worker replaying the same
  requests can never double-count a completion.
- **Deadline/drain-aware admission** — provably-unmeetable deadlines
  are shed at the front door (typed, journaled), 429 backpressure when
  every breaker is open or all queues exceed the bound, and a ``/drain``
  op for rolling restarts.

The module is jax-free in the sense that matters here: it never imports
``serve.engine``/``serve.scheduler`` (the lazy serve package keeps them
out), initialises no device backend, and talks to workers only over
HTTP and their on-disk journals.
"""

from __future__ import annotations

import hashlib
import random
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from torchacc_tpu.obs import hist as _hist
from torchacc_tpu.obs import server as obs_server
from torchacc_tpu.obs.goodput import GoodputLedger
from torchacc_tpu.resilience.chaos import failpoint
from torchacc_tpu.serve.journal import (RequestJournal, read_journal,
                                        replay_state)
from torchacc_tpu.utils import http as _http
from torchacc_tpu.utils.http import HttpClient
from torchacc_tpu.utils.logger import logger
from torchacc_tpu.utils.metrics import counters


def chain_keys(prompt_ids, block_size: int) -> List[bytes]:
    """The PrefixIndex chain-key recipe (``serve/kv_cache.py``), without
    numpy: blake2b-16 over (parent digest, block of int32 token bytes),
    one key per FULL block.  Must stay byte-identical to
    ``PrefixIndex.keys`` — the router's affinity map and the worker's
    prefix cache hash the same chains or affinity routes cold.  Tokens
    serialise as little-endian int32, matching numpy's ``tobytes()`` on
    every platform this runs on."""
    bs = int(block_size)
    if bs <= 0:
        return []
    toks = [int(t) for t in prompt_ids]
    out: List[bytes] = []
    parent = b""
    for i in range(len(toks) // bs):
        h = hashlib.blake2b(parent, digest_size=16)
        h.update(b"".join(t.to_bytes(4, "little", signed=True)
                          for t in toks[i * bs:(i + 1) * bs]))
        parent = h.digest()
        out.append(parent)
    return out


# The per-worker admission breaker moved to the shared retry core
# (one home, one test); re-exported so router users keep their import.
from torchacc_tpu.utils.retry import CircuitBreaker  # noqa: F401,E402


@dataclass
class WorkerRef:
    """Static registry entry for one serve replica: where to reach it
    and — for journal-backed failover — where its request journal lives
    on the shared filesystem (None disables the harvest path; failover
    then resubmits blind and relies on router-side dedupe)."""
    host: int
    url: str
    journal_dir: Optional[str] = None


@dataclass
class RouterConfig:
    block_size: int = 16             # must match the workers' ServeConfig
    affinity: bool = True            # prefix-affinity routing on/off
    queue_bound: int = 64            # per-worker depth before 429
    breaker_failures: int = 3        # consecutive failures to open
    breaker_cooldown_s: float = 5.0  # open -> half-open probe delay
    probe_timeout_s: float = 1.0     # /healthz probe budget
    http_timeout_s: float = 5.0      # submit/result budget
    admission_ttl_s: float = 0.5     # /admission snapshot reuse window
    health_interval_s: float = 0.5   # health loop cadence
    journal_fsync: bool = True

    def validate(self) -> None:
        if self.block_size <= 0:
            raise ValueError("block_size must be positive")
        if self.queue_bound < 1:
            raise ValueError("queue_bound must be >= 1")


class _Worker:
    """Router-side view of one replica: HTTP client, breaker, and the
    last ``/admission`` snapshot (the p2c load signal)."""

    def __init__(self, ref: WorkerRef, cfg: RouterConfig,
                 clock: Callable[[], float]):
        self.ref = ref
        self.client = HttpClient(ref.url, timeout_s=cfg.http_timeout_s,
                                 retries=0)
        self.breaker = CircuitBreaker(
            failure_threshold=cfg.breaker_failures,
            cooldown_s=cfg.breaker_cooldown_s, clock=clock)
        self.admission: Optional[Dict[str, Any]] = None
        self.admission_at = -1e18
        # two drain sources: the pin set through the router's /drain
        # seam (cleared by an explicit resume — e.g. the supervisor
        # announcing the relaunch) and the worker's own self-reported
        # drain state from /admission
        self.drain_pin = False
        self.reported_draining = False

    @property
    def draining(self) -> bool:
        return self.drain_pin or self.reported_draining

    @property
    def host(self) -> int:
        return self.ref.host

    def load(self) -> Tuple[int, int]:
        """p2c ordering key from the last admission snapshot: fewer
        (queued + busy) first, then more free KV blocks.  An unknown
        snapshot sorts as idle — a fresh worker should attract work,
        not repel it."""
        a = self.admission or {}
        depth = int(a.get("queue_depth", 0)) + int(a.get("slots_busy", 0))
        return (depth, -int(a.get("free_blocks", 1 << 30)))


class Router:
    """The routing tier.  Pure library core — tests drive
    :meth:`route`/:meth:`result`/:meth:`health_check_once` directly with
    injected clocks; :meth:`serve_http` mounts the same methods on the
    telemetry server's JSON seams for the real front door."""

    def __init__(self, journal_dir: str, workers: List[WorkerRef],
                 config: Optional[RouterConfig] = None, *,
                 rng: Optional[random.Random] = None,
                 clock: Callable[[], float] = time.monotonic,
                 wall: Callable[[], float] = time.time):
        self.config = config or RouterConfig()
        self.config.validate()
        self.journal_dir = journal_dir
        self._clock = clock
        self._wall = wall
        self._rng = rng or random.Random(0)
        self._lock = threading.RLock()
        self._workers: Dict[int, _Worker] = {
            w.host: _Worker(w, self.config, clock) for w in workers}
        if len(self._workers) != len(workers):
            raise ValueError("duplicate worker host ids")
        # rid -> {"record": <accepted record>, "worker": host|None,
        #         "wrid": worker-side rid|None}
        self._assign: Dict[int, Dict[str, Any]] = {}
        self._done: Dict[int, Dict[str, Any]] = {}
        self._shed: Dict[int, str] = {}
        self._affinity: Dict[bytes, int] = {}
        self._next_rid = 0
        self._draining = False
        self._ledger = GoodputLedger(clock=clock)
        self._ledger.start()
        self._bucket = "all_healthy"
        self._registered: List[Tuple[str, str, Any]] = []
        self._journal = RequestJournal(journal_dir,
                                       fsync=self.config.journal_fsync)
        self._recover()

    # -- durability -----------------------------------------------------------

    def _recover(self) -> None:
        """Replay the assignment journal (crash-restart path).  Terminal
        records rebuild the done/shed caches; pending rids are
        reconciled against the workers — adopted where a live worker
        already carries them (matched through the ``router-<rid>``
        trace id in ITS journal), harvested where a worker journal
        already holds the completion, resubmitted otherwise.  Replay is
        idempotent: nothing is re-journaled for an already-terminal
        rid."""
        pending, completed, shed = replay_state(read_journal(
            self.journal_dir))
        for rid, rec in completed.items():
            self._done[rid] = {"tokens": rec.get("tokens", []),
                               "finish_reason": rec.get("finish_reason",
                                                        "stop")}
        for rid, rec in shed.items():
            self._shed[rid] = rec.get("reason", "unknown")
        for rid, rec in pending.items():
            self._assign[rid] = {"record": rec,
                                 "worker": rec.get("worker"),
                                 "wrid": None}
        known = (set(pending) | set(completed) | set(shed))
        self._next_rid = (max(known) + 1) if known else 0
        if not pending:
            return
        counters.inc("router_requests_replayed", len(pending))
        logger.info(f"router: replayed {len(pending)} pending "
                    f"assignment(s) from {self.journal_dir}")
        # rebind each pending rid to wherever it actually lives now
        adopted = {}
        for w in self._workers.values():
            adopted.update(self._scan_worker_journal(w))
        for rid in sorted(pending):
            info = adopted.get(rid)
            if info is not None and info["terminal"] == "completed":
                self._complete(rid, info["tokens"], info["finish_reason"])
            elif info is not None and info["terminal"] == "shed":
                self._shed_rid(rid, f"worker:{info.get('reason', 'shed')}")
            elif (info is not None
                  and self._workers[info["host"]].breaker.routable):
                self._assign[rid]["worker"] = info["host"]
                self._assign[rid]["wrid"] = info["wrid"]
            else:
                self._assign[rid]["worker"] = None
                self._reroute(rid, exclude=set())

    def _scan_worker_journal(self, w: _Worker) -> Dict[int, Dict[str, Any]]:
        """Read one worker's on-disk journal and index every record the
        ROUTER placed there (trace ``router-<rid>``) by router rid."""
        if w.ref.journal_dir is None:
            return {}
        try:
            wp, wc, ws = replay_state(read_journal(w.ref.journal_dir))
        except OSError:
            return {}
        out: Dict[int, Dict[str, Any]] = {}
        trace_of = {}
        for wrid, rec in list(wp.items()):
            trace_of[wrid] = rec.get("trace_id", "")
        # replay_state drops accepted payloads for terminal rids; read
        # the raw records once more for their traces
        for rec in read_journal(w.ref.journal_dir):
            if rec.get("kind") == "accepted":
                trace_of.setdefault(int(rec.get("rid", -1)),
                                    rec.get("trace_id", ""))
        for wrid, trace in trace_of.items():
            if not isinstance(trace, str) or not trace.startswith("router-"):
                continue
            try:
                rid = int(trace.split("-", 1)[1])
            except ValueError:
                continue
            if wrid in wc:
                out[rid] = {"host": w.host, "wrid": wrid,
                            "terminal": "completed",
                            "tokens": wc[wrid].get("tokens", []),
                            "finish_reason": wc[wrid].get("finish_reason",
                                                          "stop")}
            elif wrid in ws:
                out[rid] = {"host": w.host, "wrid": wrid,
                            "terminal": "shed",
                            "reason": ws[wrid].get("reason", "shed")}
            else:
                out[rid] = {"host": w.host, "wrid": wrid, "terminal": None}
        return out

    def _complete(self, rid: int, tokens, finish_reason: str) -> bool:
        """Record a terminal completion exactly once.  The duplicate
        path is LOAD-BEARING: after failover the supervisor may restart
        the dead worker, which replays its journal and re-serves the
        same requests the router already moved to a survivor — the
        second completion must count as a dedupe, not a result."""
        if rid in self._done or rid in self._shed:
            counters.inc("router_duplicate_results")
            return False
        self._journal.completed(rid=rid, tokens=tokens,
                                finish_reason=finish_reason)
        self._done[rid] = {"tokens": [int(t) for t in tokens],
                           "finish_reason": finish_reason}
        self._assign.pop(rid, None)
        counters.inc("router_requests_completed")
        return True

    def _shed_rid(self, rid: int, reason: str) -> bool:
        if rid in self._done or rid in self._shed:
            counters.inc("router_duplicate_results")
            return False
        self._journal.shed(rid=rid, reason=reason)
        self._shed[rid] = reason
        self._assign.pop(rid, None)
        counters.inc("router_requests_shed")
        return True

    # -- routing --------------------------------------------------------------

    def _candidates(self, exclude=()) -> List[_Worker]:
        return [w for w in self._workers.values()
                if w.breaker.routable and not w.draining
                and w.host not in exclude]

    def _fresh_admission(self, w: _Worker) -> None:
        if self._clock() - w.admission_at < self.config.admission_ttl_s:
            return
        try:
            code, doc = w.client.get_json("/admission")
            if code == 200 and isinstance(doc, dict):
                w.admission = doc
                w.admission_at = self._clock()
                w.reported_draining = bool(doc.get("draining", False))
        except (OSError, ValueError):
            pass  # the health loop owns failure accounting

    def _pick(self, prompt_ids, exclude=()) -> Tuple[Optional[_Worker], str]:
        """Choose a replica: deepest warm prefix chain first, then
        power-of-two-choices on the admission snapshots."""
        cands = self._candidates(exclude)
        if not cands:
            return None, "none"
        if self.config.affinity:
            keys = chain_keys(prompt_ids, self.config.block_size)
            by_host = {w.host: w for w in cands}
            for key in reversed(keys):        # deepest chain first
                host = self._affinity.get(key)
                if host in by_host:
                    return by_host[host], "affinity"
        if len(cands) == 1:
            self._fresh_admission(cands[0])
            return cands[0], "p2c"
        a, b = self._rng.sample(cands, 2)
        self._fresh_admission(a)
        self._fresh_admission(b)
        return (a if a.load() <= b.load() else b), "p2c"

    def _note_affinity(self, prompt_ids, host: int) -> None:
        if not self.config.affinity:
            return
        for key in chain_keys(prompt_ids, self.config.block_size):
            self._affinity[key] = host

    def _accept_record(self, rid: int, payload: Dict[str, Any],
                       worker: Optional[int]) -> Dict[str, Any]:
        deadline_s = payload.get("deadline_s")
        return {
            "kind": "accepted", "rid": rid,
            "trace_id": str(payload.get("trace_id", "") or f"req-{rid}"),
            "prompt_ids": [int(t) for t in payload["prompt_ids"]],
            "max_new_tokens": int(payload.get("max_new_tokens", 16)),
            "temperature": float(payload.get("temperature", 0.0)),
            "top_k": int(payload.get("top_k", 0)),
            "top_p": float(payload.get("top_p", 1.0)),
            "eos_id": (None if payload.get("eos_id") is None
                       else int(payload["eos_id"])),
            "seed": int(payload.get("seed", 0)),
            "priority": int(payload.get("priority", 0)),
            "deadline_unix": (None if deadline_s is None
                              else self._wall() + float(deadline_s)),
            "t_accept": self._wall(),
            "worker": worker,          # informational; recovery re-derives
        }

    def route(self, payload: Dict[str, Any]):
        """The front door.  Returns a dict (200) or ``(status, dict)``
        — the shape ``obs/server.register_json_post`` providers use."""
        failpoint("router.route", rid=self._next_rid)
        t0 = self._clock()
        prompt = payload.get("prompt_ids")
        if not isinstance(prompt, list) or not prompt:
            return 400, {"error": "prompt_ids must be a non-empty list"}
        with self._lock:
            if self._draining:
                counters.inc("router_429")
                return 429, {"error": "router draining"}
            deadline_s = payload.get("deadline_s")
            if deadline_s is not None and float(deadline_s) <= 0.0:
                # provably unmeetable: journaled like any shed so the
                # request is ACCOUNTED, never silently dropped
                rid = self._next_rid
                self._next_rid += 1
                self._journal.append(self._accept_record(rid, payload, None))
                self._shed_rid(rid, "deadline-unmeetable")
                return {"rid": rid, "status": "shed",
                        "reason": "deadline-unmeetable"}
            worker, how = self._pick(prompt)
            if worker is None:
                counters.inc("router_429")
                return 429, {"error": "no routable workers"}
            bounded = [w for w in self._candidates()
                       if int((w.admission or {}).get("queue_depth", 0))
                       < self.config.queue_bound]
            if not bounded:
                counters.inc("router_429")
                return 429, {"error": "all queues over bound"}
            if worker not in bounded:
                worker = bounded[0]
                how = "p2c"
            rid = self._next_rid
            self._next_rid += 1
            record = self._accept_record(rid, payload, worker.host)
            self._journal.append(record)        # journal-first
            self._assign[rid] = {"record": record, "worker": None,
                                 "wrid": None}
            self._note_affinity(prompt, worker.host)
            ok = self._submit_to(worker, rid, record)
            counters.inc("router_requests_routed")
            if how == "affinity":
                counters.inc("router_affinity_hits")
            _hist.observe("router_route_decision_ms",
                          (self._clock() - t0) * 1e3)
            return {"rid": rid,
                    "worker": worker.host if ok else None,
                    "routed_by": how,
                    "status": "routed" if ok else "queued"}

    def _submit_to(self, w: _Worker, rid: int,
                   record: Dict[str, Any]) -> bool:
        """Push one journaled assignment to a worker.  Failure leaves
        the rid as an ORPHAN (assigned to no one) — the health loop's
        reconcile pass re-places it, so a flaky submit can delay a
        request but never lose it."""
        body = {k: record[k] for k in
                ("prompt_ids", "max_new_tokens", "temperature", "top_k",
                 "top_p", "eos_id", "seed", "priority")}
        body["trace_id"] = f"router-{rid}"
        if record.get("deadline_unix") is not None:
            remaining = record["deadline_unix"] - self._wall()
            if remaining <= 0.0:
                self._shed_rid(rid, "deadline-expired-in-router")
                return False
            body["deadline_s"] = remaining
        try:
            code, doc = w.client.post_json("/submit", body)
        except (OSError, ValueError):
            code, doc = 0, None
        if code != 200 or not isinstance(doc, dict) or "rid" not in doc:
            self._assign[rid]["worker"] = None
            return False
        self._assign[rid]["worker"] = w.host
        self._assign[rid]["wrid"] = int(doc["rid"])
        return True

    # -- results --------------------------------------------------------------

    def result(self, rid: int) -> Dict[str, Any]:
        with self._lock:
            if rid in self._done:
                d = self._done[rid]
                return {"rid": rid, "status": "completed",
                        "tokens": d["tokens"],
                        "finish_reason": d["finish_reason"]}
            if rid in self._shed:
                return {"rid": rid, "status": "shed",
                        "reason": self._shed[rid]}
            a = self._assign.get(rid)
            if a is None:
                return {"rid": rid, "status": "unknown"}
            if a["worker"] is None or a["wrid"] is None:
                return {"rid": rid, "status": "pending", "worker": None}
            w = self._workers[a["worker"]]
            try:
                code, doc = w.client.post_json("/result",
                                               {"rid": a["wrid"]})
            except (OSError, ValueError):
                return {"rid": rid, "status": "pending",
                        "worker": w.host}
            if code == 200 and isinstance(doc, dict):
                if doc.get("status") == "completed":
                    self._complete(rid, doc.get("tokens", []),
                                   doc.get("finish_reason", "stop"))
                    return self.result(rid)
                if doc.get("status") == "shed":
                    self._shed_rid(rid,
                                   f"worker:{doc.get('reason', 'shed')}")
                    return self.result(rid)
            return {"rid": rid, "status": "pending", "worker": w.host}

    # -- health / failover ----------------------------------------------------

    def health_check_once(self) -> Dict[str, str]:
        """One breaker tick: probe every worker that should be probed,
        fail over the assignments of any breaker that OPENS on this
        tick, reconcile orphans, and lap the goodput ledger into
        all_healthy/degraded so breaker flaps show up as attributed
        wall time rather than vanishing."""
        with self._lock:
            for w in self._workers.values():
                if not w.breaker.should_probe():
                    continue
                try:
                    code, _ = _http.request(
                        w.ref.url + "/healthz",
                        timeout_s=self.config.probe_timeout_s)
                    ok = code < 500
                except OSError:
                    ok = False
                if ok:
                    if w.breaker.record_success():
                        counters.inc("router_breaker_closes")
                        logger.info(f"router: worker {w.host} readmitted "
                                    "(breaker closed)")
                    self._fresh_admission(w)
                else:
                    if w.breaker.record_failure():
                        counters.inc("router_breaker_opens")
                        logger.warning(
                            f"router: worker {w.host} breaker OPEN after "
                            f"{w.breaker.failures} consecutive failures — "
                            "failing its in-flight assignments over")
                        self._failover(w.host)
            # orphan reconcile: rids journaled but placed nowhere
            for rid in sorted(self._assign):
                if self._assign[rid]["worker"] is None:
                    self._reroute(rid, exclude=set())
            # ledger: attribute the elapsed tick to the bucket that was
            # in effect, then flip on the breaker edge
            self._ledger.lap(self._bucket)
            self._bucket = ("all_healthy" if all(
                w.breaker.routable for w in self._workers.values())
                else "degraded")
            self._ledger.publish(prefix="router_goodput_")
            return {str(w.host): w.breaker.state
                    for w in self._workers.values()}

    def _failover(self, host: int) -> None:
        """Move every non-terminal assignment off a dead worker.  The
        worker's journal is the source of truth: completions already on
        its disk are harvested (not re-decoded), everything else is
        resubmitted to survivors under the ORIGINAL router rids."""
        dead = self._workers[host]
        harvested = self._scan_worker_journal(dead)
        moved = 0
        for rid in sorted(self._assign):
            if self._assign[rid]["worker"] != host:
                continue
            info = harvested.get(rid)
            if info is not None and info["terminal"] == "completed":
                self._complete(rid, info["tokens"], info["finish_reason"])
                continue
            if info is not None and info["terminal"] == "shed":
                self._shed_rid(rid, f"worker:{info.get('reason', 'shed')}")
                continue
            self._assign[rid]["worker"] = None
            self._assign[rid]["wrid"] = None
            if self._reroute(rid, exclude={host}):
                moved += 1
        if moved:
            counters.inc("router_requests_failover", moved)
            logger.warning(f"router: failed {moved} request(s) over "
                           f"from worker {host}")

    def _reroute(self, rid: int, exclude) -> bool:
        record = self._assign[rid]["record"]
        worker, _ = self._pick(record["prompt_ids"], exclude=exclude)
        if worker is None:
            return False        # orphan; next health tick retries
        self._note_affinity(record["prompt_ids"], worker.host)
        return self._submit_to(worker, rid, record)

    # -- drain ----------------------------------------------------------------

    def drain(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        """Rolling-restart orchestration: ``{"hosts": [..]}`` stops the
        router sending NEW work to those replicas and best-effort
        forwards ``begin_drain`` so they finish what they hold;
        ``{"all": true}`` drains the router's own front door (429 for
        new requests, in-flight unaffected).  ``{"op": "resume", ...}``
        reverses either."""
        resume = payload.get("op") == "resume"
        with self._lock:
            if payload.get("all"):
                self._draining = not resume
            touched = []
            for host in payload.get("hosts", []):
                w = self._workers.get(int(host))
                if w is None:
                    continue
                w.drain_pin = not resume
                touched.append(w.host)
                if not resume:
                    try:
                        w.client.post_json("/admin", {"op": "begin_drain",
                                                      "reason": "router"})
                    except (OSError, ValueError):
                        pass
            return {"draining": touched, "router_draining": self._draining,
                    "resumed": resume}

    # -- views ----------------------------------------------------------------

    def accounting(self) -> Dict[str, Any]:
        """The durability contract, as a dict the gate asserts on:
        every routed rid is pending, completed, or typed-shed."""
        with self._lock:
            return {"routed": self._next_rid,
                    "pending": sorted(self._assign),
                    "completed": len(self._done),
                    "shed": len(self._shed)}

    def state_json(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "workers": [{
                    "host": w.host, "url": w.ref.url,
                    "breaker": w.breaker.state,
                    "failures": w.breaker.failures,
                    "opens": w.breaker.opens,
                    "draining": w.draining,
                    "admission": w.admission,
                } for w in self._workers.values()],
                "accounting": self.accounting(),
                "affinity_keys": len(self._affinity),
                "bucket": self._bucket,
                "goodput": self._ledger.summary(),
            }

    def prometheus_text(self) -> str:
        """Labeled per-worker series for the /metrics page (the scalar
        registries can't carry labels).  Breaker state encodes as
        0=closed 1=half_open 2=open."""
        rank = {CircuitBreaker.CLOSED: 0, CircuitBreaker.HALF_OPEN: 1,
                CircuitBreaker.OPEN: 2}
        lines = ["# TYPE router_breaker_state gauge",
                 "# TYPE router_worker_queue_depth gauge",
                 "# TYPE router_worker_free_blocks gauge"]
        with self._lock:
            for w in self._workers.values():
                a = w.admission or {}
                lab = f'{{host="{w.host}"}}'
                lines.append(f"router_breaker_state{lab} "
                             f"{rank[w.breaker.state]}")
                lines.append(f"router_worker_queue_depth{lab} "
                             f"{int(a.get('queue_depth', 0))}")
                lines.append(f"router_worker_free_blocks{lab} "
                             f"{int(a.get('free_blocks', 0))}")
        return "\n".join(lines) + "\n"

    # -- HTTP front door ------------------------------------------------------

    def serve_http(self, port: int = 0,
                   host: str = "127.0.0.1") -> obs_server.TelemetryServer:
        """Mount the router on the telemetry server: POST /route,
        /result, /drain; GET /router (state) plus the standard /metrics
        and /healthz the fleet scraper consumes."""
        _hist.configure(True)
        srv = obs_server.start(port, host)
        regs = [("json_post", "/route", lambda p: self.route(p)),
                ("json_post", "/result",
                 lambda p: self.result(int(p.get("rid", -1)))),
                ("json_post", "/drain", lambda p: self.drain(p)),
                ("json", "/router", self.state_json),
                ("text", "router", self.prometheus_text),
                ("health", "router_liveness", lambda: ("ok", None))]
        for kind, name, fn in regs:
            getattr(obs_server, f"register_{kind}")(name, fn)
        self._registered = regs
        return srv

    def close(self) -> None:
        for kind, name, fn in self._registered:
            try:
                getattr(obs_server, f"unregister_{kind}")(name, fn)
            except Exception:
                pass
        self._registered = []
        self._ledger.freeze()
        self._journal.close()


def _parse_worker(spec: str) -> WorkerRef:
    """``HOST=URL[;JOURNAL_DIR]`` (';' because URLs carry ':')."""
    host, rest = spec.split("=", 1)
    url, _, jdir = rest.partition(";")
    return WorkerRef(host=int(host), url=url.rstrip("/"),
                     journal_dir=jdir or None)


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    import json as _json
    import signal as _signal

    p = argparse.ArgumentParser(description="torchacc serve router")
    p.add_argument("--port", type=int, required=True)
    p.add_argument("--journal-dir", required=True)
    p.add_argument("--worker", action="append", default=[],
                   metavar="HOST=URL[;JOURNAL_DIR]")
    p.add_argument("--block-size", type=int, default=16)
    p.add_argument("--no-affinity", action="store_true")
    p.add_argument("--queue-bound", type=int, default=64)
    p.add_argument("--breaker-failures", type=int, default=3)
    p.add_argument("--breaker-cooldown-s", type=float, default=2.0)
    p.add_argument("--health-interval-s", type=float, default=0.25)
    p.add_argument("--no-fsync", action="store_true")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--chaos", default=None,
                   help="JSON chaos spec, e.g. "
                        '\'{"kill": {"after": 5}}\' -> SIGKILL self at '
                        "the Nth router.route failpoint")
    args = p.parse_args(argv)

    cfg = RouterConfig(block_size=args.block_size,
                       affinity=not args.no_affinity,
                       queue_bound=args.queue_bound,
                       breaker_failures=args.breaker_failures,
                       breaker_cooldown_s=args.breaker_cooldown_s,
                       health_interval_s=args.health_interval_s,
                       journal_fsync=not args.no_fsync)
    workers = [_parse_worker(s) for s in args.worker]
    if not workers:
        p.error("at least one --worker is required")

    plan = None
    if args.chaos:
        from torchacc_tpu.resilience.chaos import ChaosPlan
        spec = _json.loads(args.chaos)
        plan = ChaosPlan(seed=args.seed)
        if "kill" in spec:
            plan.kill("router.route",
                      after=int(spec["kill"].get("after", 0)))

    router = Router(args.journal_dir, workers, cfg,
                    rng=random.Random(args.seed))
    srv = router.serve_http(args.port)
    stop = threading.Event()
    for sig in (_signal.SIGTERM, _signal.SIGINT):
        _signal.signal(sig, lambda *a: stop.set())
    print(f"ROUTER_READY port={srv.port} journal={args.journal_dir}",
          flush=True)

    def _loop():
        while not stop.wait(cfg.health_interval_s):
            router.health_check_once()

    try:
        if plan is not None:
            with plan:
                _loop()
        else:
            _loop()
    finally:
        router.close()
        obs_server.stop()
    print("ROUTER_DONE", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
