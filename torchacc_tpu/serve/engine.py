"""Request-level serving front-end: queue, admission control, SLO metrics.

``ServeEngine`` is the surface a serving binary drives:

    engine = ServeEngine(model, params, config)
    rid = engine.submit(Request(prompt_ids=[...], max_new_tokens=64))
    while engine.step():
        ...                       # or engine.run() / engine.generate()
    result = engine.result(rid)   # tokens + per-request SLO metrics

Admission control: a request enters a decode slot only when the block
pool has headroom for its WHOLE reservation (prompt + max_new +
in-flight overhang, scheduler.blocks_for) — a sequence admitted is a
sequence that can always finish; there is no mid-decode OOM or
preemption path to handle.  Until then it waits in the queue
(``serve.policy``: 'fcfs' arrival order, 'sjf' shortest prompt first,
'priority' per-request class + earliest-deadline-first within a class,
starvation-bounded by ``serve.priority_aging_s``).

Streaming: ``submit(req, on_token=...)`` invokes the callback as the
lagged decode ring resolves each token, and ``stream(rid)`` is the
pull-style generator over the same seam — tokens surface at most
``decode_depth - 1`` engine iterations after the device produced them
(the documented readback lag; docs/serving.md "Streaming").

Per-request SLO metrics (each ``RequestResult``): queue wait, TTFT
(submit -> first token RESOLVED on the host — readback lag included,
it is real user-visible latency), per-token inter-arrival latencies,
and tokens/s.  Aggregates ride ``utils/metrics``: the shared Counters
(serve_requests_completed, serve_tokens_generated) and an optional
MetricsWriter (``metrics_dir=``) receiving one record per completed
request — the same observability seam the trainer uses.
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import os
import time
from typing import Any, Dict, List, Optional, Sequence as Seq

import numpy as np

from torchacc_tpu.config import Config
from torchacc_tpu.serve.journal import RequestJournal, read_journal, replay_state
from torchacc_tpu.serve.scheduler import Scheduler, Sequence, priority_key
from torchacc_tpu.utils.logger import logger
from torchacc_tpu.utils.metrics import BlockedMeter, counters, open_metrics


@dataclasses.dataclass
class Request:
    """One generation request.  Sampling params default to greedy."""

    prompt_ids: Seq[int]
    max_new_tokens: Optional[int] = None     # None = config.serve default
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    eos_id: Optional[int] = None
    seed: int = 0
    # 'priority' policy inputs (ignored under fcfs/sjf): higher
    # priority = more urgent; deadline_s is seconds from submit() by
    # which the request wants to FINISH — within a priority class the
    # earliest deadline admits first (EDF), and stats()/metrics count
    # the misses.  Neither field drops or preempts work.
    priority: int = 0
    deadline_s: Optional[float] = None
    # end-to-end trace id: threaded through every serve span of this
    # request's lifecycle (queue -> admit -> prefill -> decode ->
    # deliver) so one request's timeline is filterable out of the
    # Chrome-trace export (docs/observability.md "Per-request serve
    # traces").  None = the engine assigns one at submit; a caller
    # propagating an upstream id (gateway, RPC) sets it here.
    trace_id: Optional[str] = None


@dataclasses.dataclass
class RequestResult:
    """Tokens + the per-request SLO metrics (docs/serving.md)."""

    request_id: int
    prompt_ids: List[int]
    tokens: List[int]                        # generated tokens only
    finish_reason: str                       # 'eos' | 'length'
    queue_wait_s: float                      # submit -> slot admission
    ttft_s: float                            # submit -> first token
    total_s: float                           # submit -> finish
    token_latencies_s: List[float]           # inter-token gaps
    tokens_per_sec: float
    # prompt tokens served from the prefix cache (0 = cold / cache off)
    cached_prompt_tokens: int = 0
    # finish beat the request's deadline (None = no deadline given)
    deadline_met: Optional[bool] = None
    # the id every serve span of this request carried (filter the
    # Chrome-trace export on it to see this request's full timeline)
    trace_id: str = ""


def _percentile(xs: List[float], q: float) -> float:
    return float(np.percentile(np.asarray(xs), q)) if xs else 0.0


#: process-global trace-id sequence: request ids restart at 0 per
#: engine, but co-located engines (bench's control engine, an A/B
#: pair) share one tracing ring — ids must be unique per PROCESS or
#: filtering the exported timeline mixes two requests' spans
_trace_seq = itertools.count()

_tpu_block_size_warned = False


def _warn_tpu_block_size(block_size: int, backend: str) -> None:
    """Warn once per process when serving on a real TPU with a block
    size the Pallas paged-attention kernel cannot tile on the 128-lane
    dim (docs/serving.md "ServeConfig tuning")."""
    global _tpu_block_size_warned
    if backend != "tpu" or block_size % 128 == 0 or _tpu_block_size_warned:
        return
    _tpu_block_size_warned = True
    logger.warning(
        f"serve.block_size={block_size} is not a multiple of 128 on a "
        f"TPU backend: the Pallas paged-attention kernel tiles the "
        f"block (lane) dim at 128, so this forces the slower jnp "
        f"gather fallback / padded kernel blocks.  Use 128 (or a "
        f"multiple) on real TPU; small sizes are for CPU tests.")


class ServeEngine:
    """Continuous-batching serving engine over a paged KV cache.

    Parameters
    ----------
    model: a zoo ``TransformerLM`` (or its ``ModelConfig``)
    params: the model's param tree (cast to serving precision by the
        caller — see examples/serve.py)
    config: the framework :class:`Config`; ``config.serve`` is the
        tuning block
    mesh: optional device mesh entered around every dispatch so the
        pool/param shardings resolve (single-chip runs omit it)
    metrics_dir: optional MetricsWriter directory for per-request
        SLO records
    """

    def __init__(self, model, params, config: Optional[Config] = None,
                 mesh=None, metrics_dir: Optional[str] = None):
        import jax
        cfg = getattr(model, "cfg", model)
        config = config or Config()
        config.serve.validate()
        _warn_tpu_block_size(config.serve.block_size, jax.default_backend())
        self.cfg = cfg
        self.config = config
        self.mesh = mesh
        self.blocked = BlockedMeter()
        with self._mesh_ctx():
            self.scheduler = Scheduler(cfg, params, config.serve,
                                       attention_impl=cfg.attention_impl,
                                       blocked=self.blocked)
        self._queue: "collections.deque[Sequence]" = collections.deque()
        self._all: Dict[int, Sequence] = {}
        self._next_id = 0
        # graceful drain (docs/serving.md "Graceful drain"): once set,
        # admission stops; in-flight decodes finish; queued requests
        # are reported unserved — the serving half of preemption.
        # _drain_reported keeps the unserved accounting one-shot: a
        # second run() on a drained engine must not re-count the same
        # ids into serve_requests_unserved
        self._draining = False
        self._drain_reported = False
        self._metrics = open_metrics(metrics_dir)
        self._completed = 0
        # durable request journal + replay (serve/journal.py,
        # docs/serving.md "Serving under the supervisor"): None = off,
        # serve path byte-identical to the journal-free engine
        self._journal = (RequestJournal(
            config.serve.journal_dir,
            fsync=config.serve.journal_fsync,
            rotate_bytes=config.serve.journal_rotate_bytes,
            rotate_age_s=config.serve.journal_rotate_age_s)
            if config.serve.journal_dir else None)
        self._journal_fold = None
        if self._journal is not None:
            # one read at construction serves both consumers: the id
            # reservation here (a submit() BEFORE recover() must never
            # reuse a journaled id — a collision would poison the
            # replay dedupe: a new request's 'completed' record would
            # mark the old one done) and recover()'s replay fold,
            # which consumes and releases it.  Records this engine
            # appends after construction never matter to either — its
            # own requests live in self._all and recover() skips them.
            # read the DIR, not just the active file: a predecessor may
            # have rotated, leaving history in the archive/segments
            pending, completed, shed = replay_state(
                read_journal(self._journal.dir))
            # keep only what recover() needs: the pending records
            # (bounded by outstanding work, not history) and the
            # terminal ID sets — never the terminal bodies (full token
            # payloads) for the lifetime of an engine that may never
            # call recover()
            self._journal_fold = (pending, set(completed), set(shed))
            known = [rid for part in self._journal_fold for rid in part]
            if known:
                self._next_id = max(known) + 1
        self._recovered: Optional[Dict[str, List[int]]] = None
        # recovery progress across recover() RETRIES (a mid-loop
        # journal error leaves the attempt partial): ids the replay
        # loop already enqueued / already shed, so the attempt that
        # finally succeeds reports the full recovery, not its own slice
        self._replay_enqueued: set = set()
        self._replay_shed: set = set()
        self._shed_ids: List[int] = []
        self._preempted_ids: List[int] = []
        # liveness heartbeat for the /healthz serve check: stamped at
        # the end of every engine iteration; _running marks a live
        # run() loop (a paused caller between phases is not a hang)
        self._t_heartbeat = time.monotonic()
        self._running = False
        self._agg = self._fresh_agg()
        self._evict_base = 0                 # pool.evictions at window start
        # telemetry session (docs/observability.md): queue/KV-pool
        # gauges on the HTTP endpoint + TTFT/inter-token histograms.
        # Off by default; never touches the token path.
        self._obs = None
        if getattr(config, "obs", None) is not None and config.obs.enabled:
            from torchacc_tpu.obs.runtime import ServeObs
            self._obs = ServeObs(self, config.obs)

    @staticmethod
    def _fresh_agg() -> Dict:
        return {"ttft": [], "waits": [], "gaps": [], "tokens": 0,
                "requests": 0, "t0": None, "t1": None,
                "prefix_hits": 0, "cached_tokens": 0, "shared_blocks": 0,
                "cow": 0, "deadline_total": 0, "deadline_miss": 0,
                "shed": 0, "preempted": 0}

    def _mesh_ctx(self):
        import contextlib
        import jax
        if self.mesh is None:
            return contextlib.nullcontext()
        return jax.sharding.set_mesh(self.mesh)

    # -- live weights (train -> serve handoff) ------------------------------

    @classmethod
    def from_train_state(cls, trainer, config: Optional[Config] = None, *,
                         dtype: Any = "auto", donate: bool = False,
                         metrics_dir: Optional[str] = None) -> "ServeEngine":
        """Engine over a live ``Trainer``'s weights — the in-memory
        train→serve handoff (docs/serving.md "Live weight handoff").

        ``trainer.serving_params()`` reshards ``state.params`` from the
        train layout (fsdp/tp) into the decode layout through the
        compiled layout-transfer engine (parallel/transfer.py) — no
        checkpoint I/O anywhere on this path; the transfer program
        compiles once per layout pair, so alternating fit()/serve
        phases pay collective time only after the first handoff.
        ``donate=True`` is the terminal handoff (the trainer's state is
        relinquished — see ``Trainer.serving_params``)."""
        config = config or trainer.config
        # validate BEFORE the handoff: a donating handoff relinquishes
        # the training state, and a bad ServeConfig must fail while the
        # state is still intact — not after the buffers are gone
        config.serve.validate()
        params = trainer.serving_params(dtype=dtype, donate=donate)
        return cls(trainer.model, params, config,
                   mesh=trainer.mesh, metrics_dir=metrics_dir)

    def load_params(self, params) -> None:
        """Swap the live weights in place — NO pool reallocation, no
        scheduler rebuild: the paged KV pools, block tables, decode
        carry and every compiled program survive (the params operand is
        traced by shape/dtype, which the handoff preserves).  The
        fit→serve→fit loop hands each new phase's weights here.

        Requires an idle engine (queued-but-unadmitted requests are
        fine): a weight swap under sequences mid-decode would splice
        two models' logits into one stream, so occupied decode slots
        raise instead.  In-flight ring entries are resolved first —
        they were computed under the old weights and their tokens are
        still valid.

        The prefix cache is FLUSHED before the swap: cached blocks hold
        k/v computed under the old weights, and a prefix hit after the
        handoff would splice stale keys/values under every new-weight
        decode step — a correctness bug, not a perf detail
        (regression-tested: a post-handoff warm-prefix request is
        token-identical to a cold one).  ``from_train_state`` builds a
        fresh engine, so its cache starts empty by construction."""
        self.scheduler.drain()
        self._drain_events()
        if self.scheduler.busy():
            # the ring is drained, so busy == sequences occupy slots
            busy = [s.sid for s in self.scheduler.slot_seq if s is not None]
            raise RuntimeError(
                f"cannot swap weights while sequences {busy} occupy "
                f"decode slots — run() the engine to completion (or let "
                f"them finish) first")
        flushed = self.scheduler.flush_prefix_cache()
        if flushed:
            logger.info(
                f"prefix cache flushed on weight swap ({flushed} cached "
                f"blocks dropped: k/v banked under the old weights must "
                f"never serve the new ones)")
        self.scheduler.params = params

    # -- submission ---------------------------------------------------------

    def submit(self, req: Request, on_token=None) -> int:
        """Queue a request; returns its id.  Raises when the request
        can NEVER be served (pool too small, position table exceeded)
        or the queue is full — fail at the front door, not mid-decode.

        ``on_token``: optional ``f(token: int, t_monotonic: float)``
        streaming callback, invoked as the lagged ring resolves each
        token (<= ``decode_depth - 1`` iterations after dispatch; never
        a post-finish garbage token).  Runs inside the engine loop —
        keep it cheap, hand off to a queue/socket for real delivery.

        With ``serve.journal_dir`` set, the accepted request is
        journaled (durably, before this returns) so a process death
        never loses it: a restarted engine's :meth:`recover` re-admits
        it under the same id."""
        serve = self.config.serve
        seq = self._build_seq(req, self._next_id, on_token)
        if len(self._queue) >= serve.max_queue:
            raise RuntimeError(
                f"admission queue full ({serve.max_queue}); shed load "
                f"upstream or raise serve.max_queue")
        seq.t_submit = time.monotonic()
        if req.deadline_s is not None:
            seq.deadline = seq.t_submit + req.deadline_s
        # the id is BURNED from here on, even if the journal append
        # fails: a raise from fsync does not prove the line missed the
        # disk, and reusing the id for a different request would let
        # the phantom 'accepted' record hijack it on replay
        # (replay_state keeps the FIRST accepted record per id)
        self._next_id += 1
        if self._journal is not None:
            # journal BEFORE the engine takes the request: a failed
            # append (disk full) raises with nothing enqueued — the
            # engine never serves a request that has no accepted
            # record, and the caller's retry cannot double-serve.
            # seq.max_new is _build_seq's resolution — the journal
            # must record what will actually be SERVED, or a replay
            # diverges from the original run
            self._journal.accepted(
                rid=seq.sid, trace_id=seq.trace_id,
                prompt_ids=req.prompt_ids, max_new_tokens=seq.max_new,
                temperature=req.temperature, top_k=req.top_k,
                top_p=req.top_p, eos_id=req.eos_id, seed=req.seed,
                priority=req.priority,
                deadline_unix=(None if req.deadline_s is None
                               else time.time() + req.deadline_s))
        self._all[seq.sid] = seq
        self._queue.append(seq)
        counters.inc("serve_requests_submitted")
        return seq.sid

    def _build_seq(self, req: Request, rid: int, on_token) -> Sequence:
        """Validate a request and build its scheduler ``Sequence``
        (shared by :meth:`submit` and journal replay — one home for the
        front-door rules)."""
        prompt = np.asarray(list(req.prompt_ids), np.int32)
        if prompt.ndim != 1 or prompt.shape[0] < 1:
            raise ValueError("prompt_ids must be a non-empty 1-D sequence")
        max_new = (req.max_new_tokens
                   if req.max_new_tokens is not None
                   else self.config.serve.max_new_tokens)
        if max_new < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new} (a decode "
                f"slot always generates at least one token)")
        if req.deadline_s is not None and req.deadline_s <= 0:
            raise ValueError(
                f"deadline_s must be > 0 seconds from submit, got "
                f"{req.deadline_s}")
        # trace id: pid x process-global sequence — unique across
        # processes AND across co-located engines in one process
        trace_id = (req.trace_id if req.trace_id
                    else f"{os.getpid():x}-{next(_trace_seq):x}")
        seq = Sequence(sid=rid, prompt=prompt, max_new=max_new,
                       temperature=req.temperature, top_k=req.top_k,
                       top_p=req.top_p, eos_id=req.eos_id, seed=req.seed,
                       priority=req.priority, on_token=on_token,
                       trace_id=trace_id)
        need = self.scheduler.blocks_for(seq)
        if need > self.scheduler.max_blocks_per_seq:
            raise ValueError(
                f"request needs {need} KV blocks (prompt "
                f"{prompt.shape[0]} + max_new {max_new}) but a sequence "
                f"may own at most {self.scheduler.max_blocks_per_seq} "
                f"(min of pool size serve.num_blocks - 1 and the model's "
                f"position reach max_seq_len); raise serve.num_blocks / "
                f"the model max_seq_len or lower max_new_tokens")
        total = prompt.shape[0] + max_new
        if self.cfg.pos_emb == "learned" and total > self.cfg.max_seq_len:
            raise ValueError(
                f"prompt + max_new_tokens = {total} exceeds the learned "
                f"position table max_seq_len {self.cfg.max_seq_len}")
        return seq

    # -- journal replay ------------------------------------------------------

    def recover(self) -> Dict[str, List[int]]:
        """Re-admit every journaled-but-unfinished request after a
        restart (docs/serving.md "Serving under the supervisor").

        Idempotent: completed/shed ids are deduped (never served
        twice), replayed requests keep their ORIGINAL ids (the id a
        dead incarnation returned to its caller stays valid), and a
        second call is a no-op.  Greedy replays are token-identical by
        construction (same prompt, params, seed); the prefix cache —
        if enabled — re-warms as the replays prefill.  A pending
        request whose ABSOLUTE deadline passed while the process was
        down is shed with a typed result when ``serve.shed_deadlines``
        is on (otherwise it replays and counts as a deadline miss,
        exactly as if it had been served late in one life).

        Returns ``{"replayed": [...], "completed": [...],
        "shed": [...], "shed_on_recovery": [...]}`` (ids).  No journal
        configured -> all empty."""
        if self._journal is None:
            return {"replayed": [], "completed": [], "shed": [],
                    "shed_on_recovery": []}
        if self._recovered is not None:
            return self._recovered
        pending, completed, shed = self._journal_fold
        replayed: List[int] = []
        shed_now: List[int] = []
        now_wall = time.time()
        now_mono = time.monotonic()
        for rid in sorted(pending):
            if rid in self._all:
                # already live: either a PREVIOUS recover() attempt
                # enqueued/shed it before raising (report it — the
                # successful attempt must describe the whole recovery)
                # or this engine accepted it itself (submit() raced
                # ahead of recover(); not a replay)
                if rid in self._replay_enqueued:
                    replayed.append(rid)
                elif rid in self._replay_shed:
                    shed_now.append(rid)
                continue
            rec = pending[rid]
            req = Request(
                prompt_ids=rec["prompt_ids"],
                max_new_tokens=rec.get("max_new_tokens"),
                temperature=rec.get("temperature", 0.0),
                top_k=rec.get("top_k", 0), top_p=rec.get("top_p", 1.0),
                eos_id=rec.get("eos_id"), seed=rec.get("seed", 0),
                priority=rec.get("priority", 0),
                trace_id=rec.get("trace_id") or None)
            try:
                seq = self._build_seq(req, rid, None)
            except (ValueError, RuntimeError) as e:
                # a journaled request this engine can no longer serve
                # (shrunken pool, changed model) is accounted, loudly —
                # never silently dropped.  A stub finished Sequence
                # keeps the result() contract: the caller holding the
                # original id gets the same typed shed result a
                # deadline shed produces, not a KeyError.
                stub = Sequence(
                    sid=rid,
                    prompt=np.asarray(rec.get("prompt_ids") or [],
                                      np.int32),
                    max_new=int(rec.get("max_new_tokens") or 0),
                    trace_id=rec.get("trace_id") or "")
                stub.t_submit = stub.t_admit = now_mono
                stub.t_first_token = now_mono
                # shed (journal-first) BEFORE registering the stub: a
                # failed append leaves no half-shed record for a
                # recover() retry to skip over
                self._shed(stub, f"unservable-after-restart: {e}")
                self._all[rid] = stub
                self._replay_shed.add(rid)
                shed_now.append(rid)
                continue
            # re-anchor the wall-clock deadline onto this process's
            # monotonic clock; queue-wait/TTFT metrics restart at
            # recovery (the dead incarnation's wall time is not
            # observable here — the journal's t_accept is, for audits)
            seq.t_submit = now_mono
            dl = rec.get("deadline_unix")
            if dl is not None:
                seq.deadline = now_mono + (float(dl) - now_wall)
            self._all[seq.sid] = seq
            self._queue.append(seq)
            self._replay_enqueued.add(rid)
            replayed.append(rid)
        if replayed or shed_now:
            logger.warning(
                f"request journal replay: {len(replayed)} request(s) "
                f"re-admitted ({len(completed)} already completed, "
                f"{len(shed)} already shed, {len(shed_now)} shed on "
                f"recovery) from {self._journal.path}")
        # expired deadlines among the replays shed immediately (typed,
        # journaled) instead of waiting for the first step()'s sweep —
        # and they report under shed_on_recovery, not replayed: a
        # consumer resubmitting/accounting off this dict must see them
        # as dropped, not as about-to-be-served
        self._shed_expired()
        still_live = []
        for rid in replayed:
            if self._all[rid].finish_reason == "shed":
                shed_now.append(rid)
            else:
                still_live.append(rid)
        # counted AFTER the expiry sweep so the counter always agrees
        # with the returned "replayed" list (an expired replay is a
        # shed, not a replay)
        counters.inc("serve_requests_replayed", len(still_live))
        self._recovered = {
            "replayed": still_live, "completed": sorted(completed),
            "shed": sorted(shed), "shed_on_recovery": sorted(shed_now),
        }
        # released only on success: a recover() that raised mid-loop
        # (journal disk error while shedding) must stay retryable —
        # the already-enqueued prefix is skipped via the self._all
        # guard above, the remainder replays on the retry
        self._journal_fold = None
        return self._recovered

    # -- deadline shedding ---------------------------------------------------

    def _shed_record(self, rid: int, reason: str) -> None:
        """Journal + count one shed (no Sequence state to finish).
        Journal-first, like submit(): a failed append (disk full)
        raises with NOTHING recorded, so the shed stays retryable and
        the engine never accounts a shed the journal does not have."""
        if self._journal is not None:
            self._journal.shed(rid=rid, reason=reason)
        self._shed_ids.append(rid)
        counters.inc("serve_requests_shed")

    def _shed(self, seq: Sequence, reason: str) -> None:
        """Typed shed result for a QUEUED sequence: finished with
        ``finish_reason='shed'``, zero tokens, deadline_met False —
        counted and journaled, never a silent timeout.  The journal
        append comes FIRST (via _shed_record): if it raises, the
        sequence is untouched and the shed retries cleanly."""
        self._shed_record(seq.sid, reason)
        seq.finished = True
        seq.finish_reason = "shed"
        seq.t_finish = time.monotonic()
        self._agg["shed"] = self._agg.get("shed", 0) + 1
        logger.warning(f"serve: shed request {seq.sid} ({reason})")

    def _shed_expired(self) -> None:
        """Shed every queued request whose deadline has provably
        passed (``serve.shed_deadlines``): it still needs >= 1 decode
        step, so no schedule can meet it — the one case shedding never
        second-guesses a recovery.  In-flight sequences are never shed
        (the whole-reservation guarantee: an admitted request always
        finishes) — relaxing THAT is the separate
        ``serve.preempt_deadlines`` opt-in (:meth:`_preempt_expired`)."""
        if not self.config.serve.shed_deadlines or not self._queue:
            return
        now = time.monotonic()
        expired = [s for s in self._queue
                   if s.deadline != float("inf") and now >= s.deadline]
        for seq in expired:
            # shed first (journal-first append may raise), THEN drop
            # from the queue — a failed append must never leave a
            # request neither queued nor shed
            self._shed(seq, "deadline-unmeetable"
                            + (" (drain)" if self._draining else ""))
            self._queue.remove(seq)

    def _preempt_expired(self) -> None:
        """Opt-in ``serve.preempt_deadlines`` (ROADMAP 3(d)): evict an
        ADMITTED sequence whose absolute deadline has passed — the one
        deliberate exception to the whole-reservation guarantee.  The
        slot and its KV blocks free immediately (deferred-release
        machinery makes mid-ring eviction safe), the request finishes
        with typed ``finish_reason='preempted'`` carrying the partial
        tokens, and :meth:`_drain_events` journals it like a shed so a
        replay never re-serves it.  Never silent: counted
        (``serve_requests_preempted``) and logged."""
        if not self.config.serve.preempt_deadlines:
            return
        now = time.monotonic()
        for seq in self.scheduler.slot_seq:
            if (seq is not None and not seq.finished
                    and seq.deadline != float("inf")
                    and now >= seq.deadline):
                self.scheduler.preempt(seq, now)
                logger.warning(
                    f"serve: preempted in-flight request {seq.sid} "
                    f"(deadline passed; {len(seq.out_tokens)} token(s) "
                    "resolved so far returned as a typed partial)")

    # -- the loop -----------------------------------------------------------

    def _admit(self) -> None:
        """Move queue entries into free slots while headroom lasts.
        'fcfs' preserves arrival order (no request is skipped past);
        'sjf' reorders by prompt length (better mean TTFT under mixed
        lengths); 'priority' orders by effective class then deadline
        (see :meth:`_priority_key`) — both may skip a request that does
        not fit when a later one fits the remaining headroom.
        ``scheduler.admit`` is all-or-nothing with no side effects on
        failure, so attempting it IS the fit check (and the only one
        that sees prefix-cache hits, which shrink the fresh-block
        need)."""
        if self._draining:
            # drain: the queue is frozen — nothing new enters a slot
            return
        if not self._queue or self.scheduler.free_slot() is None:
            # at capacity: don't copy/sort the (possibly thousands
            # deep) queue on the per-token hot loop when nothing can
            # possibly admit
            return
        if self.config.serve.policy == "fcfs":
            # fcfs admits only from the head — stop at the first miss
            while self._queue and self.scheduler.admit(self._queue[0]):
                self._queue.popleft()
                counters.inc("serve_requests_admitted")
            return
        # sjf/priority: one O(Q) min beats the O(Q log Q) sort + scan
        # when even the cheapest BEST-CASE reservation (full prefix
        # hit) cannot fit
        if not self.scheduler.pool.can_alloc(
                min(self.scheduler.min_fresh_blocks(s)
                    for s in self._queue)):
            return
        order = list(self._queue)
        if self.config.serve.policy == "sjf":
            order.sort(key=lambda s: (s.prompt_len, s.sid))
        else:
            # scheduler.priority_key is the ONE home for the effective-
            # class/EDF/aging semantics (prefill ordering uses it too)
            now = time.monotonic()
            aging = self.config.serve.priority_aging_s
            order.sort(key=lambda s: priority_key(s, now, aging))
        admitted = []
        for seq in order:
            if self.scheduler.free_slot() is None:
                break
            if self.scheduler.admit(seq):
                admitted.append(seq)
                counters.inc("serve_requests_admitted")
        for seq in admitted:
            self._queue.remove(seq)

    def step(self) -> bool:
        """One engine iteration (admission + scheduler.step + completion
        accounting).  Returns True while there is work anywhere."""
        self._shed_expired()
        self._preempt_expired()
        with self._mesh_ctx():
            # admission inside the mesh context too: a fully-cached
            # prompt's admit dispatches the copy-on-write program over
            # the (possibly tp-sharded) pools
            self._admit()
            self.scheduler.step()
        self._drain_events()
        # liveness heartbeat (the serve /healthz check): every completed
        # iteration proves the loop is alive; a decode wedged on device
        # blocks INSIDE this method, so the age grows while it hangs
        self._t_heartbeat = time.monotonic()
        # scheduler.busy() == False already implies the ring drained
        # (an empty slot table with entries in flight is impossible:
        # eviction only happens at resolution), so nothing to flush.
        # Draining: queued requests will never admit — only in-flight
        # work counts as "work left"
        if self._draining:
            return self.scheduler.busy()
        return bool(self._queue) or self.scheduler.busy()

    def run(self, max_iters: int = 1_000_000) -> None:
        """Drive until every submitted request completed — or, after a
        preemption signal (SIGTERM) with ``serve.drain_on_preempt``,
        until the in-flight decodes finish (queued requests stay
        unserved and are reported; docs/serving.md "Graceful drain")."""
        watch_preempt = self.config.serve.drain_on_preempt
        if watch_preempt:
            from torchacc_tpu.resilience.preemption import (
                install_preemption_handler,
            )
            install_preemption_handler()
        # re-stamp the heartbeat as the loop STARTS: the liveness age
        # must measure loop progress, not the gap since construction
        # (a long warmup/recover() before run() is not a hang)
        self._t_heartbeat = time.monotonic()
        self._running = True
        try:
            self._run_loop(max_iters, watch_preempt)
        except Exception as e:
            # serve-flavored postmortem through the flight-bundle
            # channel (the supervisor's exit-disposition reader): the
            # bundle rides the abort, never replaces it
            self._emit_disposition(type(e).__name__, err=e)
            raise
        finally:
            self._running = False

    def _run_loop(self, max_iters: int, watch_preempt: bool) -> None:
        idle = 0
        for _ in range(max_iters):
            if watch_preempt and not self._draining:
                from torchacc_tpu.resilience.preemption import (
                    preemption_requested,
                )
                if preemption_requested():
                    self.begin_drain("preemption signal")
            if not self.step():
                if self._draining:
                    self._log_drain_report()
                    self._emit_disposition("preemption")
                return
            # defensive no-progress detection: queued work that can
            # never admit while nothing is running is a config error
            if (self._queue and not self.scheduler.busy()
                    and not self._draining):
                idle += 1
                if idle > 3:
                    raise RuntimeError(
                        "serving stalled: queued requests cannot be "
                        "admitted and no sequence is running (pool "
                        "fragmentation should be impossible — report)")
            else:
                idle = 0
        raise RuntimeError(f"run() exceeded {max_iters} iterations")

    # -- graceful drain ------------------------------------------------------

    def begin_drain(self, reason: str = "") -> None:
        """Stop admission NOW; in-flight decodes run to completion
        (an admitted request always finishes — the whole-reservation
        guarantee), queued requests stay queued and are reported
        unserved.  Idempotent.  The serving-side half of preemption:
        the supervisor's SIGTERM grace window finishes what the users
        are already waiting on, never starts new work."""
        if self._draining:
            return
        self._draining = True
        self._drain_reported = False
        counters.inc("serve_drains")
        logger.warning(
            f"serve engine draining"
            + (f" ({reason})" if reason else "")
            + f": admission stopped with {len(self._queue)} queued, "
            f"{sum(s is not None for s in self.scheduler.slot_seq)} "
            "in flight — in-flight decodes will finish")

    @property
    def draining(self) -> bool:
        return self._draining

    def unserved_ids(self) -> List[int]:
        """Request ids admitted to the QUEUE but never to a decode
        slot (drain report; empty while not draining unless callers
        inspect mid-flight)."""
        return [s.sid for s in self._queue]

    def drain_report(self) -> Dict[str, Any]:
        """The machine-readable drain summary a supervisor (or the
        operator restarting the pod) consumes: what finished, what
        never started — resubmit the unserved ids elsewhere."""
        return {
            "draining": self._draining,
            "completed": self._completed,
            "in_flight": sorted(
                s.sid for s in self.scheduler.slot_seq if s is not None),
            "unserved": self.unserved_ids(),
            "shed": list(self._shed_ids),
            "preempted": list(self._preempted_ids),
            "journal": (self._journal.path if self._journal is not None
                        else None),
        }

    def _emit_disposition(self, reason: str,
                          err: Optional[BaseException] = None
                          ) -> Optional[str]:
        """Write the serve-flavored ``exit_disposition`` flight bundle
        the supervisor's reader consumes (supervisor/policy.py): what
        finished, what is still in flight, what was never admitted,
        what was shed, and where the journal lives — the serving
        equivalent of the trainer's resumable-tiers block.  No-op
        unless the flight recorder is armed and a dump dir is known
        (``obs.flight_dir``, else the journal dir)."""
        obs = getattr(self.config, "obs", None)
        if obs is None or not obs.enabled or not obs.flight_recorder:
            return None
        d = obs.flight_dir or (self._journal.dir
                               if self._journal is not None else None)
        if not d:
            return None
        from torchacc_tpu.obs import flight
        from torchacc_tpu.resilience.coordination import (
            process_count,
            process_index,
        )
        report = self.drain_report()
        disposition = {
            "reason": reason,
            "error_type": type(err).__name__ if err is not None else None,
            "flagged_step": None,
            "hosts": [],
            "resumable": {},
            "quarantine": {},
            "quarantine_delta": [],
            "preempted": reason == "preemption",
            "process_index": process_index(),
            "world_size": process_count(),
            "serve": report,
        }
        return flight.recorder.dump(
            reason, error=err, dump_dir=d,
            filename=f"flight_serve_{os.getpid()}.json",
            extra={"serve": report},
            disposition=disposition)

    def _log_drain_report(self) -> None:
        if self._drain_reported:
            return
        self._drain_reported = True
        r = self.drain_report()
        counters.inc("serve_requests_unserved", len(r["unserved"]))
        logger.warning(
            f"serve drain complete: {r['completed']} request(s) "
            f"finished, {len(r['unserved'])} never admitted "
            f"(unserved ids: {r['unserved']}) — resubmit them on the "
            "replacement pod")

    def generate(self, requests: List[Request]) -> List[RequestResult]:
        """Convenience batch API: submit everything, run to completion,
        return results in submission order."""
        ids = [self.submit(r) for r in requests]
        self.run()
        return [self.result(i) for i in ids]

    def stream(self, request_id: int):
        """Yield request ``request_id``'s tokens as the lagged decode
        ring resolves them, driving the engine loop in between (every
        other queued/running request progresses too — interleave
        multiple ``stream()`` generators or mix with :meth:`step` at
        will).  Each token surfaces at most ``decode_depth - 1`` engine
        iterations after the device produced it — the documented
        readback lag; resolution timestamps feed the same TTFT /
        per-token-gap SLO metrics as non-streamed requests.  Returns
        when the request finishes; its :class:`RequestResult` stays
        available via :meth:`result`.  For push-style delivery use
        ``submit(req, on_token=...)`` instead."""
        seq = self._all[request_id]
        sent = 0
        idle = 0
        while True:
            if sent < len(seq.out_tokens):
                yield seq.out_tokens[sent]
                sent += 1
                continue
            if seq.finished:
                return
            if not self.step():
                raise RuntimeError(
                    f"request {request_id} streamed {sent} tokens but "
                    f"the engine ran out of work before it finished")
            # mirror run()'s no-progress defense: queued work that can
            # never admit while nothing runs is a config error, not a
            # reason to spin forever
            if self._queue and not self.scheduler.busy():
                idle += 1
                if idle > 3:
                    raise RuntimeError(
                        "serving stalled: queued requests cannot be "
                        "admitted and no sequence is running (pool "
                        "fragmentation should be impossible — report)")
            else:
                idle = 0

    # -- results / metrics --------------------------------------------------

    def _drain_events(self) -> None:
        """Account every sequence the scheduler finished since the last
        drain — O(newly finished), never a scan over every request the
        engine has ever served."""
        fin = self.scheduler.finished
        while fin:
            seq = fin.pop()
            if seq.finish_reason == "preempted":
                # deadline preemption terminal: journaled as a shed
                # (same dedupe semantics — replay must never re-serve
                # it), counted separately, partial tokens readable via
                # result() with finish_reason='preempted'
                if self._journal is not None:
                    self._journal.shed(rid=seq.sid, reason="preempted")
                self._preempted_ids.append(seq.sid)
                counters.inc("serve_requests_preempted")
                a = self._agg
                a["preempted"] = a.get("preempted", 0) + 1
                a["deadline_total"] += 1
                a["deadline_miss"] += 1
                if self._obs is not None and seq.out_tokens:
                    # zero-token preempts have no real TTFT — keep the
                    # latency histograms clean of clamped zeros
                    self._obs.on_request_done(seq)
                continue
            self._completed += 1
            counters.inc("serve_requests_completed")
            counters.inc("serve_tokens_generated", len(seq.out_tokens))
            if self._journal is not None:
                # the completion record is the replay dedupe key: once
                # it is durable, no restart ever serves this id again
                self._journal.completed(rid=seq.sid,
                                        tokens=seq.out_tokens,
                                        finish_reason=seq.finish_reason)
            # SLO aggregates accumulate HERE, at completion — stats()
            # stays correct for long-running servers that pop/discard
            # results to bound memory (the aggregate sample lists grow
            # with completed tokens; reset_stats() starts a fresh
            # window)
            a = self._agg
            a["requests"] += 1
            a["tokens"] += len(seq.out_tokens)
            a["ttft"].append(max(seq.t_first_token - seq.t_submit, 0.0))
            a["waits"].append(max(seq.t_admit - seq.t_submit, 0.0))
            a["gaps"].extend(b - x for x, b in
                             zip(seq.token_times, seq.token_times[1:]))
            a["t0"] = (seq.t_submit if a["t0"] is None
                       else min(a["t0"], seq.t_submit))
            a["t1"] = (seq.t_finish if a["t1"] is None
                       else max(a["t1"], seq.t_finish))
            a["prefix_hits"] += 1 if seq.cached_tokens else 0
            a["cached_tokens"] += seq.cached_tokens
            a["shared_blocks"] += seq.shared_blocks
            a["cow"] += 1 if seq.cow else 0
            if seq.deadline != float("inf"):
                a["deadline_total"] += 1
                a["deadline_miss"] += (1 if seq.t_finish > seq.deadline
                                       else 0)
            if self._obs is not None:
                self._obs.on_request_done(seq)
            if self._metrics is not None:
                r = self.result(seq.sid)
                rec = {
                    "serve/ttft_s": r.ttft_s,
                    "serve/queue_wait_s": r.queue_wait_s,
                    "serve/total_s": r.total_s,
                    "serve/tokens": len(r.tokens),
                    "serve/tokens_per_sec": r.tokens_per_sec,
                    "serve/cached_prompt_tokens": r.cached_prompt_tokens,
                }
                if r.deadline_met is not None:
                    rec["serve/deadline_met"] = float(r.deadline_met)
                self._metrics.log(self._completed, rec)

    def result(self, request_id: int, pop: bool = False) -> RequestResult:
        """The finished request's tokens + SLO metrics.  ``pop=True``
        also releases the engine's record of the request — long-running
        servers must pop (or call :meth:`discard`) or completed-request
        state accumulates for the process lifetime."""
        seq = self._all[request_id]
        if not seq.finished:
            raise RuntimeError(f"request {request_id} not finished")
        gaps = [b - a for a, b in zip(seq.token_times, seq.token_times[1:])]
        total = max(seq.t_finish - seq.t_submit, 1e-9)
        r = RequestResult(
            request_id=request_id,
            prompt_ids=[int(t) for t in seq.prompt],
            tokens=list(seq.out_tokens),
            finish_reason=seq.finish_reason,
            queue_wait_s=max(seq.t_admit - seq.t_submit, 0.0),
            ttft_s=max(seq.t_first_token - seq.t_submit, 0.0),
            total_s=total,
            token_latencies_s=gaps,
            tokens_per_sec=len(seq.out_tokens) / total,
            cached_prompt_tokens=seq.cached_tokens,
            deadline_met=(None if seq.deadline == float("inf")
                          else bool(seq.t_finish <= seq.deadline)),
            trace_id=seq.trace_id,
        )
        if pop:
            del self._all[request_id]
        return r

    def discard(self, request_id: int) -> None:
        """Drop a finished request's record without building the
        result (the pop=False counterpart for fire-and-forget calls)."""
        seq = self._all[request_id]
        if not seq.finished:
            raise RuntimeError(f"request {request_id} not finished")
        del self._all[request_id]

    def stats(self) -> Dict[str, float]:
        """Aggregate SLO view over every request completed since the
        engine started (or the last :meth:`reset_stats`) — the
        ``make serve-smoke`` / bench --serve payload.  Accumulated at
        completion time, so popping/discarding results (the documented
        long-running-server hygiene) never shrinks the aggregates."""
        a = self._agg
        if not a["requests"]:
            # a shed-only window (deadline storm, recovery sweep) is
            # exactly what shedding exists to make visible — never
            # collapse it to "nothing happened"
            return {"requests": 0, "shed": a.get("shed", 0),
                    "preempted": a.get("preempted", 0)}
        pool = self.scheduler.pool
        return {
            "requests": a["requests"],
            "tokens": a["tokens"],
            "tokens_per_sec": a["tokens"] / max(a["t1"] - a["t0"], 1e-9),
            # host time spent blocked on token readback since engine
            # construction / reset_stats — collapses toward transfer
            # cost alone when decode_depth > 1 (the lagged ring reads
            # completed values)
            "host_blocked_ms": self.blocked.peek_ms(),
            "ttft_s_p50": _percentile(a["ttft"], 50),
            "ttft_s_p95": _percentile(a["ttft"], 95),
            "queue_wait_s_p50": _percentile(a["waits"], 50),
            "queue_wait_s_p95": _percentile(a["waits"], 95),
            "per_token_s_p50": _percentile(a["gaps"], 50),
            "per_token_s_p95": _percentile(a["gaps"], 95),
            # prefix cache (docs/serving.md "Prefix cache"): all window
            # counts accrue at request COMPLETION except evictions
            # (pool lifetime delta since the window opened)
            "prefix_hits": a["prefix_hits"],
            "prefix_hit_rate": a["prefix_hits"] / a["requests"],
            "prefill_tokens_saved": a["cached_tokens"],
            "prefix_blocks_reused": a["shared_blocks"],
            "cow_copies": a["cow"],
            "prefix_evictions": pool.evictions - self._evict_base,
            "prefix_cached_blocks": pool.cached,
            # 'priority' policy deadline accounting (requests that set
            # deadline_s; misses finished after their deadline)
            "deadline_requests": a["deadline_total"],
            "deadline_misses": a["deadline_miss"],
            # deadline shedding (serve.shed_deadlines): queued requests
            # dropped with a typed result because their deadline had
            # provably passed (this stats window)
            "shed": a.get("shed", 0),
            # deadline preemption (serve.preempt_deadlines): admitted
            # sequences evicted mid-decode with a typed partial result
            "preempted": a.get("preempted", 0),
        }

    def admission_snapshot(self) -> Dict[str, Any]:
        """The strict-JSON ``/admission`` payload (ServeObs registers
        it on the worker's telemetry endpoint): the instantaneous load
        signal the router tier routes on — queue depth, slot and
        KV-block headroom, TTFT p95, drain state — and ROADMAP 1(c)'s
        autoscaling input in the same place."""
        sched = self.scheduler
        pool = sched.pool
        ttft = self._agg["ttft"]
        return {
            "queue_depth": len(self._queue),
            "slots_busy": sum(s is not None for s in sched.slot_seq),
            "slots_total": len(sched.slot_seq),
            "free_blocks": int(pool.available - pool.cached),
            "cached_blocks": int(pool.cached),
            "blocks_in_use": int(pool.in_use),
            "block_size": int(self.config.serve.block_size),
            "ttft_p95_ms": round(_percentile(ttft, 95) * 1e3, 3),
            "draining": bool(self._draining),
            "completed": int(self._completed),
            "shed": len(self._shed_ids),
            "preempted": len(self._preempted_ids),
            # warm-cache evidence for the router's affinity gate: a
            # replica receiving same-template traffic shows hits here
            "requests": int(self._agg["requests"]),
            "prefix_hits": int(self._agg["prefix_hits"]),
            "pid": os.getpid(),
        }

    def reset_stats(self) -> None:
        """Start a fresh stats() window and zero the blocked-time
        meter — call after warmup so compile waits and warmup requests
        never pollute the reported SLOs (bench.py --serve does)."""
        self._agg = self._fresh_agg()
        self._evict_base = self.scheduler.pool.evictions
        self.blocked.take_ms()

    def close(self) -> None:
        self.scheduler.drain()
        self._drain_events()
        if self._obs is not None:
            self._obs.close()
            self._obs = None
        if self._metrics is not None:
            self._metrics.close()
        if self._journal is not None:
            self._journal.close()
        if self._queue:
            logger.warning(
                f"ServeEngine closed with {len(self._queue)} queued "
                f"requests unserved")
