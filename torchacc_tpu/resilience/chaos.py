"""Deterministic fault injection for the resilience subsystem.

Two complementary mechanisms:

**Failpoints** — named hooks compiled into production I/O paths
(``checkpoint.save``, ``checkpoint.restore``, ``loader.fetch``,
``loader.transfer``).  They cost one global ``is None`` check when no
plan is active; with a :class:`ChaosPlan` active they raise configured
exceptions deterministically (fixed hit counts, or a seeded rate — the
same seed always yields the same fault sequence).  This is the
Go-failpoint / TiKV ``fail::fail_point!`` pattern: the injection seam
lives in the real code path, so tests exercise the exact retry/backoff
branches production will take.

**Data-level faults** — :class:`ChaosLoader` wraps a batch stream and
injects (a) NaN losses, via a ``chaos_loss_mul`` scalar the
:func:`chaos_loss` function multiplies into the loss sum (NaN poisons
loss AND gradients, exactly like a real numeric blow-up), (b) simulated
preemptions (``resilience.preemption.request_preemption`` at a chosen
step), and (c) transient fetch errors.  Injection rides the batch dict,
so the jitted program is identical between clean and chaos runs — the
bitwise-equivalence tests in tests/test_resilience.py depend on that.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, Iterator, Optional, Set

from torchacc_tpu.utils.logger import logger

_active: Optional["ChaosPlan"] = None
_lock = threading.Lock()


def failpoint(name: str, **ctx: Any) -> None:
    """Hook compiled into production I/O paths; no-op unless a plan is
    active.  May raise the plan's configured exception."""
    plan = _active
    if plan is not None:
        plan.hit(name, ctx)


def flip_bits_spec() -> Optional[Dict[str, Any]]:
    """The active plan's :meth:`ChaosPlan.flip_bits` rule (or None).
    Hot-path seam for the SDC digest layer; costs one global ``is
    None`` check when no plan is active."""
    plan = _active
    if plan is None:
        return None
    return plan._flip


def maybe_corrupt_batch(batch: Any, index: int) -> Any:
    """Loader hot-path seam for :meth:`ChaosPlan.corrupt_batch`; costs
    one global ``is None`` check when no plan is active."""
    plan = _active
    if plan is None:
        return batch
    return plan.corrupt(batch, index)


@dataclass
class _Rule:
    times: int = 0                 # inject on the first `times` hits ...
    rate: float = 0.0              # ... plus with this seeded probability
    exc: Callable[[str], BaseException] = OSError
    sleep_s: float = 0.0           # > 0: hang (sleep) instead of raising
    kill: bool = False             # SIGKILL the process instead of raising
    after: int = 0                 # skip this many hits before injecting
    raised: int = 0
    hits: int = 0


@dataclass
class ChaosPlan:
    """A seeded set of failpoint rules, activated as a context manager::

        plan = ChaosPlan(seed=0)
        plan.fail("checkpoint.save", times=2, exc=OSError)
        with plan:
            ...   # first two checkpoint saves raise OSError

    The same seed reproduces the same rate-based fault sequence.
    """

    seed: int = 0
    _rules: Dict[str, _Rule] = field(default_factory=dict)
    _rng: random.Random = field(default=None, repr=False)  # type: ignore
    _corrupt: Optional[Dict[str, Any]] = field(default=None, repr=False)
    _flip: Optional[Dict[str, Any]] = field(default=None, repr=False)

    def __post_init__(self):
        self._rng = random.Random(self.seed)

    def fail(self, point: str, *, times: int = 1, rate: float = 0.0,
             after: int = 0,
             exc: Callable[[str], BaseException] = OSError) -> "ChaosPlan":
        """``after``: let the first ``after`` hits of ``point`` pass
        clean before the ``times`` deterministic injections start —
        "crash on the Nth step", not just "crash immediately" (the
        supervisor chaos gate schedules faults mid-run with it)."""
        self._rules[point] = _Rule(times=times, rate=rate, exc=exc,
                                   after=after)
        return self

    def hang(self, point: str, *, seconds: float,
             times: int = 1, after: int = 0) -> "ChaosPlan":
        """Make the first ``times`` hits of ``point`` (past the clean
        ``after`` prefix) SLEEP ``seconds`` instead of raising — a
        deterministic mid-step/mid-fetch hang for exercising the
        watchdog (resilience/watchdog.py) past its deadline.  The
        sleep returns normally: what the run does about the stall is
        entirely the watchdog's decision."""
        self._rules[point] = _Rule(times=times, sleep_s=seconds,
                                   after=after)
        return self

    def kill(self, point: str, *, after: int = 0) -> "ChaosPlan":
        """Make hit ``after + 1`` of ``point`` SIGKILL the process —
        a REAL ``kill -9``: no exception, no drain, no atexit, no
        flight bundle.  The crash-mid-anything failpoint the serve
        chaos gate uses (``serve.decode``: a supervised serving worker
        dies mid-decode and the journal replay must make it whole).
        The injected signal is deterministic (hit-count gated), so the
        same plan kills at the same decode iteration every run."""
        self._rules[point] = _Rule(times=1, kill=True, after=after)
        return self

    def hit(self, point: str, ctx: Dict[str, Any]) -> None:
        rule = self._rules.get(point)
        if rule is None:
            return
        rule.hits += 1
        if rule.hits <= rule.after:
            return
        inject = (rule.raised < rule.times
                  or (rule.rate > 0.0 and self._rng.random() < rule.rate))
        if inject:
            rule.raised += 1
            if rule.kill:
                import os
                import signal as _signal
                logger.warning(
                    f"chaos: SIGKILL self at {point} ({ctx or {}}) — "
                    f"simulated hard crash, no cleanup will run")
                os.kill(os.getpid(), _signal.SIGKILL)
                return                     # unreachable outside tests
            if rule.sleep_s > 0.0:
                logger.warning(
                    f"chaos: injecting {rule.sleep_s:.1f}s hang "
                    f"#{rule.raised} at {point} ({ctx or {}})")
                time.sleep(rule.sleep_s)
                return
            logger.warning(
                f"chaos: injecting fault #{rule.raised} at {point} "
                f"({ctx or {}})")
            raise rule.exc(f"chaos-injected fault at {point} "
                           f"(#{rule.raised}, ctx={ctx})")

    def flip_bits(self, *, host: int, at: int,
                  leaf: Optional[str] = None, where: str = "step",
                  mask: int = 0x0040_0000) -> "ChaosPlan":
        """Deterministic SDC injection (resilience/sdc.py): at step
        index ``at``, flip ``mask``'s bits in the first element of the
        local gradients as seen by the DP replica(s) living on ``host``
        — inside the per-replica digest region of the jitted step, so
        exactly one replica's view of the (logically replicated) grads
        diverges, the way a marginal chip's arithmetic would.

        ``host`` is a JAX process index in multi-process runs; in
        single-process runs each DP replica is its own simulated host.
        ``leaf`` selects one grad leaf by path substring (None = every
        leaf).  ``where``: ``'step'`` corrupts the in-step digest
        (transient fault — the recompute arbiter sees clean bits and
        localizes the host); ``'recompute'`` corrupts the redundant
        re-execution instead.  The default mask flips the mantissa MSB:
        a visible, always-finite perturbation.
        """
        if where not in ("step", "recompute"):
            raise ValueError(f"flip_bits where must be 'step' or "
                             f"'recompute', got {where!r}")
        self._flip = {"host": int(host), "at": int(at), "leaf": leaf,
                      "where": where, "mask": int(mask) & 0xFFFF_FFFF,
                      "hits": 0}
        return self

    def corrupt_batch(self, *, at: Iterable[int] = (), times: int = 0,
                      mode: str = "nonfinite",
                      key: Optional[str] = None) -> "ChaosPlan":
        """Corrupt loader batches in place of raising: the bad-batch
        quarantine seam (``AsyncLoader`` with
        ``resilience.batch_validation``) sees a batch that LOOKS fetched
        but is broken — exactly what a flaky storage backend or a
        corrupted shard produces.

        ``at`` corrupts those 0-based source-batch indices; without
        ``at``, the first ``times`` batches are corrupted.  ``mode``:

        - ``'nonfinite'``: poison the first float leaf (or ``key``)
          with NaN, keeping shape/dtype;
        - ``'shape'``: drop the leading row of one leaf;
        - ``'dtype'``: cast one leaf to a different dtype;
        - ``'drop_key'``: remove one key from the batch dict.
        """
        if mode not in ("nonfinite", "shape", "dtype", "drop_key"):
            raise ValueError(f"unknown corrupt_batch mode {mode!r}")
        self._corrupt = {"at": {int(i) for i in at}, "times": times,
                         "mode": mode, "key": key, "hits": 0,
                         "injected": 0}
        return self

    def corrupt(self, batch: Any, index: int) -> Any:
        """Apply the corrupt_batch rule to ``batch`` (source index
        ``index``); returns the batch unchanged when no rule matches."""
        import numpy as np
        rule = self._corrupt
        if rule is None or not isinstance(batch, dict) or not batch:
            return batch
        rule["hits"] += 1
        if rule["at"]:
            inject = index in rule["at"]
        else:
            inject = rule["injected"] < rule["times"]
        if not inject:
            return batch
        rule["injected"] += 1
        mode = rule["mode"]
        out = dict(batch)
        key = rule["key"]
        if key is None:
            if mode == "nonfinite":
                key = next((k for k, v in out.items()
                            if np.issubdtype(np.asarray(v).dtype,
                                             np.floating)),
                           next(iter(out)))
            else:
                key = next(iter(out))
        logger.warning(f"chaos: corrupting batch {index} "
                       f"(mode={mode}, key={key!r})")
        if mode == "drop_key":
            out.pop(key, None)
            return out
        v = np.asarray(out[key])
        if mode == "nonfinite":
            if np.issubdtype(v.dtype, np.floating):
                v = v.copy()
                v.reshape(-1)[0] = np.nan
            else:  # no float leaf: a NaN float replacement is still bad
                v = np.full(v.shape, np.nan, np.float32)
        elif mode == "shape":
            v = v[1:] if v.shape and v.shape[0] > 1 else np.expand_dims(v, 0)
        elif mode == "dtype":
            v = v.astype(np.float16 if v.dtype != np.float16 else np.int32)
        out[key] = v
        return out

    def stats(self) -> Dict[str, Dict[str, int]]:
        out = {p: {"hits": r.hits, "raised": r.raised}
               for p, r in self._rules.items()}
        if self._corrupt is not None:
            out["batch.corrupt"] = {"hits": self._corrupt["hits"],
                                    "raised": self._corrupt["injected"]}
        if self._flip is not None:
            out["sdc.flip_bits"] = {"hits": self._flip["hits"],
                                    "raised": self._flip["hits"]}
        return out

    def __enter__(self) -> "ChaosPlan":
        global _active
        with _lock:
            if _active is not None:
                raise RuntimeError("a ChaosPlan is already active")
            _active = self
        return self

    def __exit__(self, *exc) -> None:
        global _active
        with _lock:
            _active = None


def chaos_loss():
    """Default-equivalent loss that honours ``chaos_loss_mul``.

    Identical math to the Trainer's default loss (sum/count cross-entropy
    with -100 skip) with the loss sum multiplied by the per-batch
    ``chaos_loss_mul`` scalar ChaosLoader injects (1.0 normally, NaN on
    fault steps — multiplying by 1.0 is bitwise-exact, so clean runs
    through the harness match runs without it).
    """
    def loss(logits, batch):
        from torchacc_tpu.models.transformer import loss_sum_count
        from torchacc_tpu.train.trainer import shift_labels
        s, c = loss_sum_count(
            logits, batch.get("labels", shift_labels(
                batch["input_ids"], batch.get("segment_ids"))))
        mul = batch.get("chaos_loss_mul")
        if mul is not None:
            s = s * mul
        return s, c
    return loss


class ChaosLoader:
    """Deterministic data-level fault injector around a batch iterable.

    Every yielded batch gains a ``chaos_loss_mul`` float32 scalar (1.0,
    or NaN when the 0-based batch index is in ``nan_loss_steps``) —
    pair with :func:`chaos_loss`.  ``preempt_after_step=k`` requests
    preemption while yielding batch ``k`` (the training loop finishes
    step ``k``, then sees the flag at the step boundary — the timing of
    a real SIGTERM).  ``fetch_faults={index: n}`` makes ``__next__``
    raise ``fetch_exc`` ``n`` times before successfully yielding batch
    ``index`` — a transiently flaky source for exercising loader
    retries.  Wrap the *outermost* iterable (inside any AsyncLoader) so
    step indices line up with trainer steps.
    """

    def __init__(self, loader: Iterable[Dict[str, Any]], *,
                 nan_loss_steps: Iterable[int] = (),
                 loss_scale_steps: Optional[Dict[int, float]] = None,
                 preempt_after_step: Optional[int] = None,
                 fetch_faults: Optional[Dict[int, int]] = None,
                 fetch_exc: Callable[[str], BaseException] = OSError):
        self._loader = loader
        self._nan: Set[int] = set(nan_loss_steps)
        # finite multipliers (e.g. 1e4) simulate gradient blow-ups for
        # the spike guard without going non-finite
        self._scale = dict(loss_scale_steps or {})
        self._preempt = preempt_after_step
        self._fetch_faults = dict(fetch_faults or {})
        self._fetch_exc = fetch_exc

    def __iter__(self) -> Iterator[Dict[str, Any]]:
        return _ChaosIterator(self)

    def __len__(self) -> int:
        return len(self._loader)  # type: ignore[arg-type]


class _ChaosIterator:
    def __init__(self, cl: ChaosLoader):
        self._cl = cl
        self._it = iter(cl._loader)
        self._i = 0

    def __iter__(self):
        return self

    def __next__(self) -> Dict[str, Any]:
        import numpy as np
        cl = self._cl
        i = self._i
        pending = cl._fetch_faults.get(i, 0)
        if pending > 0:
            cl._fetch_faults[i] = pending - 1
            raise cl._fetch_exc(
                f"chaos-injected fetch fault at batch {i} "
                f"({pending - 1} remaining)")
        batch = dict(next(self._it))
        if i in cl._nan:
            mul = np.float32("nan")
        else:
            mul = np.float32(cl._scale.get(i, 1.0))
        batch["chaos_loss_mul"] = np.asarray(mul, np.float32)
        if cl._preempt is not None and i == cl._preempt:
            from torchacc_tpu.resilience.preemption import request_preemption
            request_preemption(f"chaos: simulated eviction at step {i}")
        self._i = i + 1
        return batch
