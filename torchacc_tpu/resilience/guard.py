"""Step-level anomaly guards: non-finite loss and gradient-norm spikes.

A single NaN loss (bad shard, numeric edge) or a pathological gradient
spike should cost one skipped batch, not a dead run or a corrupted
optimizer state.  The guard runs *inside* the jitted train step, in the
same shape as the fp16 GradScaler skip (train/amp.py): compute the
candidate update, then ``select_tree`` between candidate and previous
state on a scalar verdict — no host sync is needed to *skip*.

Spike detection keeps an exponentially-weighted mean/variance of the
gradient norm (West's EW update) in a tiny replicated guard-state pytree
threaded through the step; a step whose norm z-score exceeds the
configured threshold after warmup is rejected and does NOT update the
statistics (one spike must not inflate the variance and mask the next).

Aborting after N *consecutive* anomalies is host-side by necessity
(Python must raise): :class:`GuardMonitor` reads the per-step anomaly
verdict — one scalar device fetch per step, the price of the abort
guarantee — and raises :class:`~torchacc_tpu.errors.AnomalyError` with a
diagnosis.  Under dispatch pipelining (``perf.dispatch_depth`` = 1 + k,
train/trainer.py) the monitor observes each step at lag k from the
lagged-readback ring buffer: the fetch then reads an already-completed
scalar instead of serialising dispatch, the anomaly is still attributed
to the step that produced it, and abort-after-N becomes
abort-within-N+k — at the default depth 1 (k = 0) the semantics are
bitwise identical to the unpipelined loop (docs/performance.md).  Guard state is intentionally NOT part of the checkpointed
``TrainState`` (layouts stay unchanged across guard on/off); instead the
EW mean/var/count persist as an advisory ``guard_state.json`` sidecar
with every committed step (``CheckpointManager.save``) and
``fit(resume='auto')`` restores them, so the spike guard no longer
re-warms after resume (the pre-PR-4 non-guarantee, now closed — see
docs/resilience.md).  A checkpoint without the sidecar still resumes;
only the statistics re-warm.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from torchacc_tpu.config import ResilienceConfig
from torchacc_tpu.errors import AnomalyError
from torchacc_tpu.utils.logger import logger

# anomaly kind codes (metrics["anomaly_kind"])
KIND_NONE = 0
KIND_NONFINITE = 1
KIND_SPIKE = 2
_KIND_NAMES = {KIND_NONFINITE: "non-finite loss/grad",
               KIND_SPIKE: "grad-norm spike"}


def guard_init() -> Dict[str, jax.Array]:
    """Fresh EW statistics (replicated scalars)."""
    return {
        "mean": jnp.zeros((), jnp.float32),
        "var": jnp.zeros((), jnp.float32),
        "count": jnp.zeros((), jnp.int32),
    }


def guard_apply(
    gstate: Dict[str, jax.Array],
    loss: jax.Array,
    grad_norm: jax.Array,
    cfg: ResilienceConfig,
    *,
    check_finite: bool = True,
) -> Tuple[jax.Array, jax.Array, Dict[str, jax.Array]]:
    """Judge one step; traced inside the train step.

    Returns ``(ok, kind, new_gstate)`` — ``ok`` is a bool scalar (True =
    apply the update), ``kind`` an int32 anomaly code.  ``check_finite``
    is disabled by the trainer when the fp16 GradScaler already owns
    overflow skipping (a scaler backoff is not an anomaly).
    """
    gn = grad_norm.astype(jnp.float32)
    finite = jnp.isfinite(loss) & jnp.isfinite(gn)
    false = jnp.zeros((), bool)
    nonfinite_anom = (~finite) if (cfg.nan_guard and check_finite) else false
    if cfg.spike_guard:
        warm = gstate["count"] >= cfg.spike_warmup_steps
        std = jnp.sqrt(jnp.maximum(gstate["var"], 0.0))
        z = (gn - gstate["mean"]) / (std + 1e-8)
        spike = warm & finite & (z > cfg.spike_zscore)
    else:
        spike = false
    ok = ~(nonfinite_anom | spike)
    kind = jnp.where(nonfinite_anom, KIND_NONFINITE,
                     jnp.where(spike, KIND_SPIKE, KIND_NONE)).astype(jnp.int32)

    # EW mean/var update on accepted finite steps only.  The FIRST
    # accepted norm seeds the mean outright: an EW climb from a zero
    # init would leave the early mean far below the true norm and the
    # variance dominated by that bias, making healthy steps z-score as
    # spikes right after warmup.
    upd = ok & finite
    first = gstate["count"] == 0
    a = jnp.float32(cfg.spike_ewma_alpha)
    delta = gn - gstate["mean"]
    mean_next = jnp.where(first, gn, gstate["mean"] + a * delta)
    var_next = jnp.where(
        first, 0.0, (1.0 - a) * (gstate["var"] + a * delta * delta))
    new_gstate = {
        "mean": jnp.where(upd, mean_next, gstate["mean"]),
        "var": jnp.where(upd, var_next, gstate["var"]),
        "count": gstate["count"] + upd.astype(jnp.int32),
    }
    return ok, kind, new_gstate


class GuardMonitor:
    """Host-side consecutive-anomaly tracker (abort-after-N).

    ``observe`` fetches the step's anomaly scalar (the one host sync the
    guard costs), increments the ``anomalies_skipped`` counter, and
    raises :class:`AnomalyError` once ``max_consecutive_anomalies``
    anomalous steps occur in a row.
    """

    def __init__(self, cfg: ResilienceConfig):
        self._max = cfg.max_consecutive_anomalies
        self._consec = 0

    @property
    def consecutive(self) -> int:
        return self._consec

    def observe(self, step: int, metrics: Dict[str, jax.Array]) -> bool:
        """Returns True when the step was anomalous (and skipped)."""
        kind = int(metrics.get("anomaly_kind", 0))
        if kind == KIND_NONE:
            self._consec = 0
            return False
        self._consec += 1
        from torchacc_tpu.utils.metrics import counters
        counters.inc("anomalies_skipped")
        loss = float(metrics["loss"])
        gn = float(metrics["grad_norm"])
        logger.warning(
            f"step {step}: anomaly ({_KIND_NAMES[kind]}; loss={loss:.4g} "
            f"grad_norm={gn:.4g}) — update skipped "
            f"({self._consec}/{self._max} consecutive)")
        if self._consec >= self._max:
            raise AnomalyError(
                f"aborting: {self._consec} consecutive anomalous steps "
                f"(last: {_KIND_NAMES[kind]} at step {step}, "
                f"loss={loss:.4g}, grad_norm={gn:.4g}).  The run is "
                "diverging, not glitching — lower the learning rate, "
                "check the data shard, or resume from an earlier "
                "checkpoint.",
                step=step, kind=_KIND_NAMES[kind], consecutive=self._consec,
                loss=loss, grad_norm=gn)
        return True
