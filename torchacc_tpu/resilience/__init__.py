"""Fault-tolerance subsystem: preemption-safe auto-resume, step-level
anomaly guards, retry/backoff for flaky I/O, cross-host coordination,
a hang/straggler watchdog, and a deterministic fault-injection harness.

See docs/resilience.md for the operator-facing contract (what is and is
not guaranteed).  Wiring: ``Config.resilience`` (config.py) configures
the guards, deadlines, and retry policies; ``Trainer.fit(resume='auto')``
(train/trainer.py) is the auto-resume entry point; checkpoint and data
I/O pick up the retry policies automatically.  Multi-host,
``coordination`` keeps save/resume/quarantine decisions identical on
every host and ``watchdog`` turns silent pod hangs into stack dumps,
counters, and (optionally) a typed ``HangError``.
"""

from torchacc_tpu.resilience.chaos import (
    ChaosLoader,
    ChaosPlan,
    chaos_loss,
    failpoint,
    flip_bits_spec,
    maybe_corrupt_batch,
)
from torchacc_tpu.resilience.sdc import (
    SDCMonitor,
    host_digests,
    read_quarantined_hosts,
    record_quarantine,
    replica_digests,
)
from torchacc_tpu.resilience.coordination import (
    all_agree,
    any_host,
    barrier,
    broadcast_from_primary,
    max_over_hosts,
    min_over_hosts,
)
from torchacc_tpu.resilience.guard import GuardMonitor, guard_apply, guard_init
from torchacc_tpu.resilience.preemption import (
    clear_preemption,
    install_preemption_handler,
    preemption_requested,
    request_preemption,
    sync_preemption,
)
from torchacc_tpu.resilience.retry import RetryPolicy, retry_call
from torchacc_tpu.resilience.watchdog import Watchdog, dump_stacks, trip_stall

__all__ = [
    "ChaosLoader",
    "ChaosPlan",
    "chaos_loss",
    "failpoint",
    "flip_bits_spec",
    "maybe_corrupt_batch",
    "SDCMonitor",
    "host_digests",
    "read_quarantined_hosts",
    "record_quarantine",
    "replica_digests",
    "GuardMonitor",
    "guard_apply",
    "guard_init",
    "install_preemption_handler",
    "preemption_requested",
    "request_preemption",
    "clear_preemption",
    "sync_preemption",
    "RetryPolicy",
    "retry_call",
    "all_agree",
    "any_host",
    "barrier",
    "broadcast_from_primary",
    "max_over_hosts",
    "min_over_hosts",
    "Watchdog",
    "dump_stacks",
    "trip_stall",
]
