"""Fault-tolerance subsystem: preemption-safe auto-resume, step-level
anomaly guards, retry/backoff for flaky I/O, and a deterministic
fault-injection harness.

See docs/resilience.md for the operator-facing contract (what is and is
not guaranteed).  Wiring: ``Config.resilience`` (config.py) configures
the guards and retry policies; ``Trainer.fit(resume='auto')``
(train/trainer.py) is the auto-resume entry point; checkpoint and data
I/O pick up the retry policies automatically.
"""

from torchacc_tpu.resilience.chaos import (
    ChaosLoader,
    ChaosPlan,
    chaos_loss,
    failpoint,
)
from torchacc_tpu.resilience.guard import GuardMonitor, guard_apply, guard_init
from torchacc_tpu.resilience.preemption import (
    clear_preemption,
    install_preemption_handler,
    preemption_requested,
    request_preemption,
)
from torchacc_tpu.resilience.retry import RetryPolicy, retry_call

__all__ = [
    "ChaosLoader",
    "ChaosPlan",
    "chaos_loss",
    "failpoint",
    "GuardMonitor",
    "guard_apply",
    "guard_init",
    "install_preemption_handler",
    "preemption_requested",
    "request_preemption",
    "clear_preemption",
    "RetryPolicy",
    "retry_call",
]
