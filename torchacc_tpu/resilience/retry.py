"""Jittered-exponential-backoff retry with a wall-clock deadline.

Flaky storage (GCS 429/503s, NFS hiccups) and transient loader failures
must not kill a multi-host run; MaxText/Orbax production loops wrap every
checkpoint I/O in exactly this shape of retry.  The policy is a frozen
dataclass so call sites can share one instance, and the sleep/rng seams
are injectable so tests run in microseconds and deterministically.

Retries are observable: every retried attempt increments a monotonic
counter (utils/metrics.py) and logs at WARNING, so degradation shows up
in the step log line and metrics.jsonl, not only in a post-mortem.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass
from typing import Any, Callable, Optional, Tuple, Type

from torchacc_tpu.utils.logger import logger


@dataclass(frozen=True)
class RetryPolicy:
    """How to retry a transient failure.

    ``max_retries`` counts *re*-tries: the call is attempted at most
    ``max_retries + 1`` times.  Delay before retry ``k`` (0-based) is
    ``min(base_delay_s * 2**k, max_delay_s)`` scaled by a uniform jitter
    in ``[1 - jitter, 1 + jitter]``.  ``deadline_s`` bounds the *total*
    wall-clock spent (attempts + sleeps): once exceeded, no further
    attempt is made and the last error is re-raised.
    """

    max_retries: int = 3
    base_delay_s: float = 0.5
    max_delay_s: float = 8.0
    deadline_s: Optional[float] = None
    jitter: float = 0.5
    retry_on: Tuple[Type[BaseException], ...] = (Exception,)
    # exceptions that are final even when retry_on matches them (e.g. a
    # typed error raised by the retried callable to mean "do not retry")
    no_retry: Tuple[Type[BaseException], ...] = ()

    def validate(self) -> None:
        if self.max_retries < 0:
            raise ValueError("retry: max_retries must be >= 0")
        if self.base_delay_s < 0 or self.max_delay_s < self.base_delay_s:
            raise ValueError("retry: need 0 <= base_delay_s <= max_delay_s")
        if not 0.0 <= self.jitter <= 1.0:
            raise ValueError("retry: jitter must be in [0, 1]")
        if self.deadline_s is not None and self.deadline_s <= 0:
            raise ValueError("retry: deadline_s must be positive")

    def delay(self, attempt: int, rng: random.Random) -> float:
        base = min(self.base_delay_s * (2.0 ** attempt), self.max_delay_s)
        return base * (1.0 - self.jitter + 2.0 * self.jitter * rng.random())


def retry_call(
    fn: Callable[..., Any],
    *args: Any,
    policy: RetryPolicy = RetryPolicy(),
    description: str = "",
    counter: Optional[str] = None,
    rng: Optional[random.Random] = None,
    sleep: Callable[[float], None] = time.sleep,
    clock: Callable[[], float] = time.monotonic,
    **kwargs: Any,
) -> Any:
    """Call ``fn(*args, **kwargs)``, retrying per ``policy``.

    ``counter`` names a utils/metrics monotonic counter incremented once
    per *retried* attempt.  The last exception is re-raised unchanged
    (with prior attempts visible via ``__context__``) so callers keep
    their own typed wrapping.
    """
    rng = rng if rng is not None else random.Random()
    what = description or getattr(fn, "__name__", "call")
    start = clock()
    for attempt in range(policy.max_retries + 1):
        try:
            return fn(*args, **kwargs)
        except policy.retry_on as e:
            if isinstance(e, policy.no_retry) or attempt >= policy.max_retries:
                raise
            delay = policy.delay(attempt, rng)
            if (policy.deadline_s is not None
                    and clock() - start + delay > policy.deadline_s):
                logger.warning(
                    f"{what}: attempt {attempt + 1} failed ({e!r}) and the "
                    f"{policy.deadline_s:.1f}s retry deadline is exhausted")
                raise
            if counter is not None:
                from torchacc_tpu.utils.metrics import counters
                counters.inc(counter)
            logger.warning(
                f"{what}: attempt {attempt + 1}/{policy.max_retries + 1} "
                f"failed ({e!r}); retrying in {delay:.2f}s")
            sleep(delay)
    raise AssertionError("unreachable")  # pragma: no cover
