"""Back-compat shim: the retry core moved to ``utils/retry.py``.

The jittered-exponential-backoff loop used to live here while the HTTP
client (``utils/http.py``) and the serve router's circuit breaker
carried their own copies of the same semantics.  The one shared home is
now :mod:`torchacc_tpu.utils.retry` — policy, loop, and breaker together
(one home, one test).  Every existing ``resilience.retry`` import keeps
working through this re-export; new code should import from
``torchacc_tpu.utils.retry``.
"""

from __future__ import annotations

from torchacc_tpu.utils.retry import (  # noqa: F401
    CircuitBreaker,
    RetryPolicy,
    retry_call,
)

__all__ = ["RetryPolicy", "retry_call", "CircuitBreaker"]
