"""Preemption awareness: catch the eviction signal, checkpoint, exit clean.

TPU pods are preemptible by design: maintenance events and spot
reclamation deliver SIGTERM with a short grace window.  The pattern
(Orbax emergency checkpointing, MaxText's
``jax.distributed...reached_preemption_sync_point``) is: a signal
handler flips a flag, the step loop polls it at step boundaries, and on
preemption performs one *blocking* save before returning — a resumed job
then loses at most the in-flight step.

State is process-global (a signal is process-global) and thread-safe; a
previously-installed handler is chained, not clobbered.
``request_preemption`` triggers the same path programmatically — the
fault-injection harness (resilience/chaos.py) uses it to simulate
eviction deterministically in tests.
"""

from __future__ import annotations

import signal
import threading
from typing import Iterable, Optional

from torchacc_tpu.utils.logger import logger

_event = threading.Event()
_installed: set = set()
_lock = threading.Lock()


def install_preemption_handler(
        signals: Iterable[int] = (signal.SIGTERM,)) -> bool:
    """Install the flag-setting handler (idempotent, chains any previous
    handler).  Returns False when not callable from this thread (signal
    handlers can only be installed from the main thread)."""
    with _lock:
        todo = [s for s in signals if s not in _installed]
        if not todo:
            return True
        for sig in todo:
            try:
                prev = signal.getsignal(sig)

                def handler(signum, frame, _prev=prev):
                    _event.set()
                    logger.warning(
                        f"received signal {signum}: preemption requested — "
                        "an emergency checkpoint will be written at the "
                        "next step boundary")
                    if callable(_prev) and _prev not in (
                            signal.SIG_IGN, signal.SIG_DFL):
                        _prev(signum, frame)

                signal.signal(sig, handler)
                _installed.add(sig)
            except ValueError:
                # not the main thread — poll-only mode still works via
                # request_preemption()
                logger.debug(
                    "preemption handler not installed (not in main thread)")
                return False
    return True


def preemption_requested() -> bool:
    return _event.is_set()


def sync_preemption(timeout_s: Optional[float] = None) -> bool:
    """Cross-host preemption sync point: True iff ANY host has the flag.

    SIGTERM lands on one host's process; the others must join the
    emergency save at the SAME step boundary or the checkpoint mixes
    steps (the MaxText ``reached_preemption_sync_point`` pattern).
    ``Trainer.fit`` calls this at each step boundary instead of the
    local :func:`preemption_requested`.  Hosts learning of the request
    via the sync set their local flag too, so every host takes the same
    emergency-save branch.  Single-process: exactly the local flag — no
    collective, no timeout armed.  A :class:`CoordinationError` from a
    partitioned pod propagates (fail fast: the next collective would
    hang anyway).
    """
    from torchacc_tpu.resilience.coordination import any_host, process_count

    local = _event.is_set()
    if process_count() == 1:
        return local
    agreed = any_host(local, timeout_s=timeout_s, name="preemption-sync")
    if agreed and not local:
        logger.warning(
            "preemption requested on another host — joining the "
            "emergency save at this step boundary")
        _event.set()
    return agreed


def request_preemption(reason: str = "") -> None:
    """Programmatic preemption (chaos harness, external schedulers)."""
    if reason:
        logger.warning(f"preemption requested: {reason}")
    _event.set()


def clear_preemption() -> None:
    """Reset the flag (tests; or a supervisor that handled the event)."""
    _event.clear()
