"""Silent-data-corruption (SDC) defense: cross-replica divergence
detection, redundant-recompute spot checks, and bad-host quarantine.

A fleet-scale TPU job's nastiest failure does not crash: a marginal chip
silently emits wrong numbers ("Cores that don't count", Hochschild et
al., HotOS'21), and once the gradient all-reduce runs the poison is
replicated into every host — the StepGuard (resilience/guard.py) can see
*that* the loss went bad, never *which host* computed it.  MegaScale
(Jiang et al., NSDI'24) localizes these faults by comparing redundant
computation across replicas; this module is that defense, TPU-native:

**Per-replica digests** (:func:`replica_digests`, traced inside the
jitted train step).  Each gradient leaf is folded to three words — an
XOR fold and a wraparound uint32 sum of the f32 bit patterns (both
order-independent, hence *exact* regardless of reduction order), plus an
f32 sum for human eyes — computed independently by every DP replica
inside a ``shard_map`` manual over the ``dp`` axis.  The grads are
logically replicated across ``dp`` after XLA's psum, so the per-replica
digest rows MUST agree bitwise; physically each device folds its own
copy with its own ALUs, which is exactly where a flaky chip diverges.
The ``[dp, leaves, 3]`` digest matrix is replicated on the way out so
every host fetches identical data and the divergence verdict is
deterministic pod-wide.

**Localization** (:class:`SDCMonitor`, host-side).  Divergent rows are
grouped; with a clear majority the minority replicas are the suspects.
On a tie (dp == 2, or an even split) the arbiter is the **redundant
recompute**: the *same compiled step executable* is re-run on a
donation-safe snapshot of the pre-step state (``checkpoint.io._snapshot``
— the machinery async saves already use), so on healthy hardware the
digests are bitwise identical *by construction* (same executable, same
input bits); a replica whose in-step digest disagrees with its own
re-execution is flaky.  The same recompute, run on a cadence
(``sdc_recompute_interval_steps``), catches single-host SDC that replica
comparison cannot see at dp=1.

**Quarantine**.  A confirmed divergence records the suspect host id(s)
in ``<run_dir>/sdc_quarantine.json`` (primary-gated, merged, atomic) and
raises a typed :class:`~torchacc_tpu.errors.SDCError` naming them — the
supervisor restarts excluding the quarantined host and elastic resume
(docs/resilience.md) restores onto the smaller world.  Counters
``sdc_checks`` / ``sdc_mismatches`` / ``replica_divergences`` ride the
step records and metrics.jsonl.

Chaos: :meth:`ChaosPlan.flip_bits(host=, at=, leaf=, where=)
<torchacc_tpu.resilience.chaos.ChaosPlan.flip_bits>` feeds a per-replica
flip mask through the digest region (the clean path is
``jnp.where(False, ...)`` — bitwise untouched), so the whole pipeline is
provable on the 2-process CPU fixtures in CI.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from torchacc_tpu.errors import SDCError
from torchacc_tpu.resilience.coordination import (
    process_count as _process_count,
)
from torchacc_tpu.utils.logger import logger

#: digest components per leaf (all compared as uint32 bit patterns)
DIGEST_WORDS = ("bits_xor", "bits_sum", "f32_sum")
#: quarantine record written into the run directory on confirmed SDC
QUARANTINE_FILE = "sdc_quarantine.json"


# -- traced digest fold -------------------------------------------------------

def _subsample_strides(shape: Sequence[int], max_elems: int,
                       sharded_dims: Sequence[bool]) -> Tuple[int, ...]:
    """Per-dim strides keeping ``prod(ceil(d/s))`` at most ~max_elems.

    Strides apply to UNSHARDED dims first: a strided slice along an
    unsharded dim is a purely device-local operation on every shard,
    while striding a sharded dim (the last resort, only when the
    unsharded dims cannot absorb the whole bound) makes the partitioner
    move data.  Index 0 of every strided dim is always kept ([::s]
    starts at 0), so the global element (0, ..., 0) — the chaos flip
    site — survives any stride combination."""
    strides = [1] * len(shape)
    order = sorted(range(len(shape)),
                   key=lambda i: (bool(sharded_dims[i]), -shape[i]))
    for i in order:
        kept = 1
        for d, s in zip(shape, strides):
            kept *= -(-d // s)
        if kept <= max_elems:
            break
        factor = -(-kept // max_elems)
        strides[i] = min(shape[i], strides[i] * factor)
    return tuple(strides)


def _leaf_digest(x: jax.Array, hit: jax.Array,
                 xor_mask: jax.Array,
                 max_elems: Optional[int] = None,
                 spec: Any = None) -> jax.Array:
    """Fold one grad leaf to ``[3] uint32``: XOR fold + wraparound sum
    of the f32 bit patterns (order-independent -> exact under any
    reduction order / sharding) + the f32 sum's bit pattern (order-
    dependent; report-only).  ``hit`` conditionally XORs ``xor_mask``
    into the first element first — the chaos seam; when False the value
    is bitwise untouched.

    ``max_elems`` (resilience.sdc_digest_max_elems) bounds the fold's
    read traffic on huge leaves with a deterministic PER-DIM strided
    subsample of at most ~``max_elems`` elements.  The subsample is a
    strided slice per dimension — never a flat reshape (whose global
    linearisation forced GSPMD to GATHER a sharded leaf before
    slicing) — so each device strides its own local shard and the fold
    reduces shard-local partials; digesting a 10B-param fsdp/tp-sharded
    leaf moves digest words, not tensor data.  ``spec`` (the leaf's
    PartitionSpec, passed by the trainer from the param shardings)
    steers strides onto UNSHARDED dims first so the slice itself is
    movement-free too.  Element 0 — the chaos flip site — is always in
    the subsample (every strided dim keeps index 0).  The subsampled
    fold stays exact and order-independent over its (shape+stride-
    determined) subset; bounded digests are not comparable to digests
    taken under a different bound or to the pre-PR-7 flat-stride
    subsample."""
    bits = jax.lax.bitcast_convert_type(x.astype(jnp.float32), jnp.uint32)
    if bits.ndim == 0:
        bits = jnp.where(hit, bits ^ xor_mask, bits)
        xor = bits
        usum = bits
        fsum = jax.lax.bitcast_convert_type(bits, jnp.float32)
    else:
        idx = (0,) * bits.ndim
        b0 = bits[idx]
        bits = bits.at[idx].set(jnp.where(hit, b0 ^ xor_mask, b0))
        if max_elems is not None and bits.size > max_elems:
            parts = tuple(spec) if spec is not None else ()
            sharded = [bool(parts[i]) if i < len(parts) else False
                       for i in range(bits.ndim)]
            strides = _subsample_strides(bits.shape, max_elems, sharded)
            bits = bits[tuple(slice(None, None, s) for s in strides)]
        xor = jax.lax.reduce(bits, jnp.uint32(0), jax.lax.bitwise_xor,
                             tuple(range(bits.ndim)))
        usum = jnp.sum(bits, dtype=jnp.uint32)
        fsum = jnp.sum(jax.lax.bitcast_convert_type(bits, jnp.float32),
                       dtype=jnp.float32)
    return jnp.stack([xor, usum,
                      jax.lax.bitcast_convert_type(fsum, jnp.uint32)])


def replica_digests(grads: Any, flip: Dict[str, jax.Array], *,
                    mesh, axis: str = "dp",
                    max_elems: Optional[int] = None,
                    leaf_specs: Optional[Sequence[Any]] = None
                    ) -> jax.Array:
    """Traced: per-DP-replica digest matrix ``uint32 [dp, leaves, 3]``.

    Runs inside the jitted train step.  ``grads`` is the final gradient
    pytree (replicated over ``axis`` after XLA's all-reduce; other mesh
    axes stay automatic — fsdp/tp-sharded leaves reduce collectively
    per replica, identically on every replica).  ``flip`` is the chaos
    operand built by :func:`flip_operands`: ``mask`` (int32 ``[dp]``,
    nonzero replicas get the bit flip), ``leaf`` (int32 leaf index, -1
    = all), ``xor`` (uint32 mask).  The output is replicated so every
    process can fetch all rows.  ``max_elems`` bounds the per-leaf fold
    on check steps (see :func:`_leaf_digest`) — the 10B+-param digest
    cost knob (resilience.sdc_digest_max_elems).  ``leaf_specs`` (one
    PartitionSpec per leaf, in ``jax.tree.leaves`` order — the trainer
    passes the param shardings) steers the bounded subsample's per-dim
    strides onto unsharded dims so the slice is shard-local
    (:func:`_subsample_strides`); ignored when ``max_elems`` is None.
    """
    leaves = jax.tree.leaves(grads)
    specs = (list(leaf_specs) if leaf_specs is not None
             else [None] * len(leaves))
    if len(specs) != len(leaves):
        raise ValueError(
            f"leaf_specs has {len(specs)} entries for {len(leaves)} "
            "grad leaves")

    def block(flip, *ls):
        r = jax.lax.axis_index(axis)
        hit_r = flip["mask"][r] != 0
        rows = []
        for i, x in enumerate(ls):
            hit = hit_r & ((flip["leaf"] < 0) | (flip["leaf"] == i))
            rows.append(_leaf_digest(x, hit, flip["xor"],
                                     max_elems=max_elems,
                                     spec=specs[i]))
        return jnp.stack(rows)[None]  # [1, leaves, 3] per replica

    digs = jax.shard_map(
        block, mesh=mesh,
        in_specs=(P(),) * (1 + len(leaves)),
        out_specs=P(axis),
        axis_names=frozenset({axis}), check_vma=False,
    )(flip, *leaves)
    # replicate: every host must see every replica's row so the
    # divergence verdict (and any raise) is identical pod-wide
    return jax.lax.with_sharding_constraint(
        digs, NamedSharding(mesh, P()))


# -- host-side topology / chaos plumbing --------------------------------------

def replica_host_map(mesh, axis: str = "dp") -> List[List[int]]:
    """Host id(s) backing each DP replica.  Multi-process: the JAX
    process indices of the replica's devices.  Single-process: each
    replica is its own *simulated* host (replica index == host id), so
    the chaos fixtures and the naming logic behave identically on one
    machine."""
    from torchacc_tpu.resilience.coordination import process_count
    devs = np.asarray(mesh.devices)
    ax = list(mesh.axis_names).index(axis)
    n = devs.shape[ax]
    if process_count() == 1:
        return [[i] for i in range(n)]
    return [sorted({d.process_index
                    for d in np.take(devs, i, axis=ax).ravel()})
            for i in range(n)]


def zero_flip(dp: int) -> Dict[str, np.ndarray]:
    """The no-injection operand (the default every step)."""
    return {"mask": np.zeros((dp,), np.int32),
            "leaf": np.asarray(-1, np.int32),
            "xor": np.asarray(0, np.uint32)}


def flip_operands(step_idx: int, dp: int, replica_hosts: List[List[int]],
                  leaf_paths: Sequence[str], where: str,
                  ) -> Dict[str, np.ndarray]:
    """Build the digest flip operand for this step from the active
    ChaosPlan's ``flip_bits`` rule (zeros when no plan / wrong step /
    wrong ``where``)."""
    from torchacc_tpu.resilience.chaos import flip_bits_spec
    spec = flip_bits_spec()
    if (spec is None or spec["at"] != step_idx
            or spec["where"] != where):
        return zero_flip(dp)
    mask = np.asarray([1 if spec["host"] in hosts else 0
                       for hosts in replica_hosts], np.int32)
    leaf = -1
    if spec["leaf"] is not None:
        matches = [i for i, p in enumerate(leaf_paths)
                   if spec["leaf"] in p]
        if not matches:
            raise ValueError(
                f"ChaosPlan.flip_bits leaf {spec['leaf']!r} matches no "
                f"grad leaf (paths: {list(leaf_paths)[:8]}...)")
        leaf = matches[0]
    if mask.any():
        spec["hits"] += 1
        logger.warning(
            f"chaos: flipping grad bits on simulated host "
            f"{spec['host']} at step {step_idx} "
            f"(where={where}, leaf={'all' if leaf < 0 else leaf_paths[leaf]},"
            f" mask=0x{spec['mask']:08x})")
    return {"mask": mask, "leaf": np.asarray(leaf, np.int32),
            "xor": np.asarray(spec["mask"], np.uint32)}


def leaf_paths_of(tree: Any) -> List[str]:
    """Flatten-order leaf paths (``params/...`` style, matching the
    checkpoint schema's path convention)."""
    from jax.tree_util import tree_flatten_with_path

    from torchacc_tpu.train.state import _path_str
    leaves, _ = tree_flatten_with_path(tree)
    return [_path_str(path) for path, _ in leaves]


# -- quarantine record --------------------------------------------------------

def record_quarantine(run_dir: str, hosts: Sequence[int], *, step: int,
                      kind: str, report: Sequence[str]) -> str:
    """Merge the suspect host(s) into ``<run_dir>/sdc_quarantine.json``
    (atomic replace; evidence accumulates, never overwritten).  Returns
    the file path.  Callers gate on the primary process — the verdict
    is deterministic pod-wide, one writer suffices."""
    os.makedirs(run_dir, exist_ok=True)
    path = os.path.join(run_dir, QUARANTINE_FILE)
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        data = {}
    data.setdefault("hosts", {})
    for h in hosts:
        data["hosts"][str(int(h))] = {
            "step": int(step), "kind": kind, "time": time.time(),
            # pod size at quarantine time: host ids are process
            # indices, which RENUMBER after an elastic shrink — the
            # refuse_quarantined enforcement only fires while the world
            # is still at least this big (a smaller world means the
            # exclusion-and-shrink already happened)
            "world": _process_count(),
            "report": list(report)[:8]}
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(data, f, indent=1)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def read_quarantined_hosts(run_dir: Optional[str]) -> Dict[int, Dict]:
    """Quarantined host ids recorded under ``run_dir`` (empty when none
    / unreadable).  ``fit(resume='auto')`` warns when the restarted pod
    still includes one."""
    if not run_dir:
        return {}
    try:
        with open(os.path.join(run_dir, QUARANTINE_FILE)) as f:
            data = json.load(f)
        return {int(k): v for k, v in (data.get("hosts") or {}).items()}
    except (OSError, ValueError):
        return {}


# -- comparison / reporting ---------------------------------------------------

def _row_key(row: np.ndarray) -> bytes:
    """Comparable bytes of a digest row — the EXACT (order-independent)
    words only.  The f32-sum word is report-only: it is a floating
    reduction whose order the compiler owns, and verdicts must never
    hinge on it (the replay path's agreement check excludes it the same
    way)."""
    return np.ascontiguousarray(row[..., :2]).tobytes()


def compare_replicas(digests: np.ndarray
                     ) -> Tuple[Optional[List[int]], bool]:
    """Group the per-replica digest rows.  Returns ``(suspects, tie)``:
    ``suspects`` is None when all rows agree; with a strict majority it
    is the minority replica indices (``tie`` False); on a tie (dp=2, or
    an even split) it is EVERY replica, with ``tie`` True so the caller
    arbitrates via the redundant recompute."""
    groups: Dict[bytes, List[int]] = {}
    for r in range(digests.shape[0]):
        groups.setdefault(_row_key(digests[r]), []).append(r)
    if len(groups) == 1:
        return None, False
    sizes = sorted((len(v) for v in groups.values()), reverse=True)
    majority_unique = len(sizes) == 1 or sizes[0] > sizes[1]
    if majority_unique:
        majority = max(groups.values(), key=len)
        bad = sorted(r for v in groups.values() if v is not majority
                     for r in v)
        return bad, False
    # even split: every replica is a suspect until arbitrated
    return sorted(r for v in groups.values() for r in v), True


def divergence_report(digests: np.ndarray, reference: np.ndarray,
                      replicas: Sequence[int], leaf_paths: Sequence[str],
                      replica_hosts: List[List[int]]) -> List[str]:
    """Per-replica first-divergence lines: which leaf diverged first,
    its digest words vs the reference, and how many leaves diverged."""
    out = []
    for r in replicas:
        diff = [l for l in range(digests.shape[1])
                if _row_key(digests[r, l]) != _row_key(reference[l])]
        if not diff:
            continue
        l0 = diff[0]
        got, want = digests[r, l0], reference[l0]
        fmt = lambda w: (f"xor=0x{int(w[0]):08x} sum=0x{int(w[1]):08x} "
                         f"f32={float(np.asarray(w[2], np.uint32).view(np.float32)):.6g}")
        out.append(
            f"replica {r} (host {','.join(map(str, replica_hosts[r]))}): "
            f"first divergence at leaf '{leaf_paths[l0]}' "
            f"[{fmt(got)}] != [{fmt(want)}]; "
            f"{len(diff)}/{digests.shape[1]} leaves diverge")
    return out


class SDCMonitor:
    """Host-side SDC verdict engine, driven by ``Trainer.step``.

    Holds the leaf paths, the replica->host map, and the run-dir for
    quarantine records.  :meth:`observe` consumes the step's fetched
    digest matrix (and, when available, the redundant recompute's) and
    raises :class:`SDCError` on a confirmed divergence.  Everything it
    reads (the replicated digest matrix) is identical on every process,
    so the verdict — and the raise — is deterministic pod-wide.
    """

    def __init__(self, cfg, mesh, leaf_paths: Sequence[str],
                 run_dir: Optional[str] = None):
        self._cfg = cfg
        self.replica_hosts = replica_host_map(mesh)
        self.dp = len(self.replica_hosts)
        self.leaf_paths = list(leaf_paths)
        self.run_dir = run_dir
        # the no-injection operand, built once: production steps (no
        # ChaosPlan active — the only non-test state) reuse the same
        # arrays instead of reallocating three per step
        self._zero_flip = zero_flip(self.dp)
        if (self.dp == 1 and cfg.sdc_check_interval_steps is not None
                and cfg.sdc_recompute_interval_steps is None):
            logger.warning(
                "sdc_check_interval_steps is set but dp=1: there is no "
                "peer replica to compare against, so the per-step "
                "digest fold buys nothing — set "
                "sdc_recompute_interval_steps for single-replica SDC "
                "coverage (or drop the check interval)")

    # a 2-replica comparison can only ever tie: the trainer snapshots
    # pre-step state on check steps so the recompute arbiter is
    # available.  dp=1 has nothing to compare (the snapshot would be
    # pure waste — only the spot check applies there); dp>=3 localizes
    # by majority.
    def needs_arbiter(self) -> bool:
        return self.dp == 2

    def flips(self, step_idx: int, where: str) -> Dict[str, np.ndarray]:
        from torchacc_tpu.resilience.chaos import flip_bits_spec
        if flip_bits_spec() is None:
            return self._zero_flip
        return flip_operands(step_idx, self.dp, self.replica_hosts,
                             self.leaf_paths, where)

    def _confirm(self, step_idx: int, kind: str, replicas: Sequence[int],
                 report: List[str], *, localized: bool = True) -> None:
        from torchacc_tpu.resilience.coordination import process_index
        from torchacc_tpu.utils.metrics import counters
        counters.inc("sdc_mismatches")
        hosts = sorted({h for r in replicas
                        for h in self.replica_hosts[r]})
        qpath = None
        if localized and self.run_dir is not None \
                and process_index() == 0:
            # only LOCALIZED verdicts quarantine: an unarbitrated tie
            # names the whole divergent set, and excluding healthy
            # hosts on that basis would shrink the pod for nothing.
            # The record is evidence, not the verdict — a full disk
            # must not turn the SDCError into an untyped crash.
            try:
                qpath = record_quarantine(self.run_dir, hosts,
                                          step=step_idx, kind=kind,
                                          report=report)
            except OSError as e:
                logger.warning(
                    f"could not record SDC quarantine in "
                    f"{self.run_dir}: {e}")
        lines = "\n  ".join(report) or "(no per-leaf report)"
        if not localized:
            action = ("NOT localized to one host (no recompute arbiter "
                      "was available for this tie — no quarantine "
                      "recorded; enable sdc_recompute_interval_steps or "
                      "run dp >= 3 for majority voting)")
        elif qpath:
            action = (f"quarantine recorded at {qpath}; restart "
                      "excluding the quarantined host(s) — elastic "
                      "resume restores onto the remaining world")
        else:
            action = ("restart excluding the suspect host(s) — elastic "
                      "resume restores onto the remaining world (the "
                      "quarantine record is written by the primary "
                      "process when a run dir is set)")
        msg = (f"silent data corruption confirmed at step {step_idx} "
               f"({kind}): suspect host(s) {hosts}.\n  {lines}\n"
               + action + " (docs/resilience.md 'SDC defense').")
        if self._cfg.sdc_abort:
            raise SDCError(msg, step=step_idx, kind=kind, hosts=hosts,
                           report=report)
        logger.error(msg + "  (sdc_abort=False: continuing)")

    def observe(self, step_idx: int, digests: np.ndarray, *,
                check: bool, spot: bool,
                recompute: Optional[Callable[[], np.ndarray]] = None,
                ) -> None:
        """Judge one checked step.

        ``digests``: the fetched ``[dp, leaves, 3]`` matrix from the
        step.  ``check``: compare across replicas.  ``spot``: compare
        against the redundant recompute.  ``recompute``: zero-arg
        callable re-executing the SAME step executable on the pre-step
        snapshot, returning its digest matrix — invoked eagerly on spot
        steps and lazily as the tie arbiter (the decision to call it is
        made from replicated data, so every process enters the
        collective re-execution together).
        """
        from torchacc_tpu.utils.metrics import counters
        counters.inc("sdc_checks")
        digests = np.asarray(digests)
        redo: Optional[np.ndarray] = None
        if spot and recompute is not None:
            redo = np.asarray(recompute())

        bad: List[int] = []
        kind = None
        localized = True
        report: List[str] = []
        if check and self.dp > 1:
            suspects, tie = compare_replicas(digests)
            if suspects is not None:
                counters.inc("replica_divergences")
                kind = "replica"
                if tie and redo is None and recompute is not None:
                    redo = np.asarray(recompute())
                if tie and redo is not None:
                    # self-consistency arbiter: a replica whose in-step
                    # digest disagrees with its own deterministic
                    # re-execution is the flaky one
                    bad = [r for r in suspects
                           if _row_key(digests[r]) != _row_key(redo[r])]
                    if bad:
                        report = divergence_report(
                            digests, redo[bad[0]], bad, self.leaf_paths,
                            self.replica_hosts)
                    elif recompute is not None:
                        # third execution tie-breaker (the dp<=2 even
                        # split): in-step digest and recompute agree
                        # per-replica, so neither can self-localize —
                        # one more execution gives three samples to
                        # majority-vote.  A replica whose three runs
                        # are not unanimous is intermittently flaky
                        # (the two agreeing runs are the majority) and
                        # IS localized; three-way-unanimous replicas
                        # that still diverge across replicas remain
                        # persistent, unattributed corruption.
                        counters.inc("sdc_third_executions")
                        third = np.asarray(recompute())
                        bad = [r for r in suspects
                               if len({_row_key(digests[r]),
                                       _row_key(redo[r]),
                                       _row_key(third[r])}) > 1]
                        if bad:
                            report = divergence_report(
                                digests, third[bad[0]], bad,
                                self.leaf_paths, self.replica_hosts)
                        else:
                            bad = list(suspects)
                            localized = False
                    else:
                        # persistent corruption: both executions equally
                        # wrong — cannot self-localize; name the whole
                        # divergent set, but do NOT quarantine it
                        bad = list(suspects)
                        localized = False
                if not bad:
                    # a tie with no arbiter available (dp >= 3 even
                    # split — no pre-step snapshot was taken): name the
                    # divergent set unattributed
                    bad = list(suspects)
                    localized = not tie
                if not report:
                    # reference = any majority (non-suspect) row, else
                    # the lowest replica outside each suspect
                    good = [r for r in range(self.dp) if r not in bad]
                    ref = digests[good[0]] if good else digests[bad[0]]
                    ref_against = [r for r in bad
                                   if _row_key(digests[r]) != _row_key(ref)]
                    report = divergence_report(
                        digests, ref, ref_against or bad, self.leaf_paths,
                        self.replica_hosts)
        if not bad and redo is not None:
            # recompute spot check (also the dp=1 story): the same
            # executable on the same bits must reproduce the digests
            flaky = [r for r in range(self.dp)
                     if _row_key(digests[r]) != _row_key(redo[r])]
            if flaky:
                kind = "recompute"
                bad = flaky
                report = divergence_report(
                    digests, redo[flaky[0]], flaky, self.leaf_paths,
                    self.replica_hosts)
        if bad:
            self._confirm(step_idx, kind or "replica", bad, report,
                          localized=localized)


# -- offline digests (checkpoint CLI `replay`) --------------------------------

def host_digests(tree: Any) -> Dict[str, Dict[str, Any]]:
    """Numpy digest of a host-side pytree (a restored checkpoint):
    ``{leaf_path: {bits_xor, bits_sum, f32_sum}}``.  The xor/sum words
    are order-independent, so two copies of the same checkpoint digest
    identically on any machine — the offline half of the SDC triage
    runbook."""
    from jax.tree_util import tree_flatten_with_path

    from torchacc_tpu.train.state import _path_str
    leaves, _ = tree_flatten_with_path(tree)
    out: Dict[str, Dict[str, Any]] = {}
    for path, x in leaves:
        p = _path_str(path)
        a = np.asarray(x)
        raw = np.ascontiguousarray(a).tobytes()
        raw += b"\x00" * (-len(raw) % 4)
        b = np.frombuffer(raw, np.uint32)
        fsum = (float(np.sum(a, dtype=np.float64))
                if np.issubdtype(a.dtype, np.floating)
                or np.issubdtype(a.dtype, np.integer) else 0.0)
        out[p] = {
            "bits_xor": f"0x{int(np.bitwise_xor.reduce(b)) if b.size else 0:08x}",
            "bits_sum": f"0x{int(np.sum(b, dtype=np.uint64)) & 0xFFFFFFFF:08x}",
            "f32_sum": fsum,
            "shape": list(a.shape), "dtype": str(a.dtype),
        }
    return out


def format_digest_matrix(digests: np.ndarray, leaf_paths: Sequence[str]
                         ) -> Dict[str, List[Dict[str, Any]]]:
    """JSON-able view of a ``[dp, leaves, 3]`` digest matrix:
    ``{leaf_path: [{replica, bits_xor, bits_sum, f32_sum}, ...]}`` —
    the printable payload of ``fit(replay_step=N)``."""
    digests = np.asarray(digests)
    out: Dict[str, List[Dict[str, Any]]] = {}
    for l, p in enumerate(leaf_paths):
        rows = []
        for r in range(digests.shape[0]):
            w = digests[r, l]
            rows.append({
                "replica": r,
                "bits_xor": f"0x{int(w[0]):08x}",
                "bits_sum": f"0x{int(w[1]):08x}",
                "f32_sum": float(np.asarray(w[2], np.uint32)
                                 .view(np.float32)),
            })
        out[p] = rows
    return out
