"""Timeout-bounded cross-host agreement primitives.

PR 1's fault-tolerance subsystem is per-process: the preemption flag,
checkpoint quarantine, and ``restore_latest_valid`` can each diverge
across the hosts of a pod, and a host that resumes from step 400 while
its neighbours resume from step 500 corrupts the run silently (the first
cross-host collective mixes states from different steps).  This module
provides the small set of host-level agreement primitives the resilience
layer needs — the MaxText/MegaScale pattern of "agree, then act":

- :func:`broadcast_from_primary` — process 0's value everywhere;
- :func:`min_over_hosts` / :func:`max_over_hosts` — reduce a host-local
  integer (e.g. "my newest valid checkpoint step") across hosts;
- :func:`any_host` / :func:`all_agree` — OR / AND over a host-local
  boolean (preemption seen anywhere; restore succeeded everywhere);
- :func:`barrier` — plain rendezvous.

Every primitive is an **exact no-op** when ``jax.process_count() == 1``:
no collective runs, no worker thread is spawned, no timeout is armed —
single-host behaviour and performance are unchanged.  Multi-host, the
underlying device collective (``jax.experimental.multihost_utils``) is
run on a worker thread and bounded by ``timeout_s``: JAX collectives
cannot be cancelled, so on expiry the caller gets a typed
:class:`~torchacc_tpu.errors.CoordinationError` naming the primitive
(the worker thread is abandoned — by then the pod is already wedged and
the process is expected to exit and be restarted).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Optional

import numpy as np

from torchacc_tpu.errors import CoordinationError
from torchacc_tpu.utils.logger import logger

#: Default wall-clock bound when a call site passes ``timeout_s=None``.
#: ``Config.resilience.coord_timeout_s`` overrides this per-run.
DEFAULT_TIMEOUT_S = 120.0


def process_count() -> int:
    """Number of JAX processes (1 before/without distributed init)."""
    import jax
    try:
        return jax.process_count()
    except Exception:  # noqa: BLE001 - backend not initialised yet
        return 1


def process_index() -> int:
    import jax
    try:
        return jax.process_index()
    except Exception:  # noqa: BLE001
        return 0


def _bounded(fn: Callable[[], Any], *, timeout_s: Optional[float],
             name: str) -> Any:
    """Run ``fn`` (a collective) with a wall-clock bound.

    The collective runs on a daemon worker thread; the caller waits at
    most ``timeout_s``.  On expiry a :class:`CoordinationError` is
    raised — the collective itself cannot be cancelled, so the worker
    is left behind (documented at module level: a timed-out agreement
    means the pod is wedged and the process should exit).
    """
    timeout_s = DEFAULT_TIMEOUT_S if timeout_s is None else timeout_s
    box: dict = {}
    done = threading.Event()

    def run():
        try:
            box["value"] = fn()
        except BaseException as e:  # noqa: BLE001 - re-raised on caller
            box["error"] = e
        finally:
            done.set()

    t = threading.Thread(target=run, daemon=True, name=f"coord-{name}")
    t.start()
    if not done.wait(timeout_s):
        raise CoordinationError(
            f"cross-host agreement '{name}' timed out after {timeout_s:.1f}s "
            f"on process {process_index()}/{process_count()} — a host is "
            "down or partitioned; restart the job (resume='auto' recovers "
            "the run)", primitive=name, timeout_s=timeout_s)
    if "error" in box:
        raise CoordinationError(
            f"cross-host agreement '{name}' failed on process "
            f"{process_index()}/{process_count()}: {box['error']!r}",
            primitive=name, timeout_s=timeout_s) from box["error"]
    return box["value"]


def _allgather(value: np.ndarray, *, timeout_s: Optional[float],
               name: str) -> np.ndarray:
    """Gather one small host-local array from every process; shape
    ``(process_count,) + value.shape``."""
    from jax.experimental import multihost_utils

    return _bounded(
        lambda: np.asarray(multihost_utils.process_allgather(value)),
        timeout_s=timeout_s, name=name)


# -- primitives ---------------------------------------------------------------

def broadcast_from_primary(value: Any, *, timeout_s: Optional[float] = None,
                           name: str = "broadcast") -> Any:
    """Process 0's value on every host.

    Accepts scalars and small ndarrays (the values being agreed on are
    step numbers and flags, not tensors).  Single-process: returns
    ``value`` unchanged — no collective, no timeout armed.
    """
    if process_count() == 1:
        return value
    from jax.experimental import multihost_utils

    arr = np.asarray(value)
    out = _bounded(lambda: np.asarray(
        multihost_utils.broadcast_one_to_all(arr)),
        timeout_s=timeout_s, name=name)
    return out.item() if np.ndim(value) == 0 and out.ndim == 0 else out


def broadcast_from_host(tree: Any, *, is_source: bool,
                        timeout_s: Optional[float] = None,
                        name: str = "broadcast-host") -> Any:
    """One host's pytree on every host — ``broadcast_from_primary``
    generalised to an arbitrary donor (the peer-RAM restore seam:
    a restarted host receives a healthy peer's tier-0 snapshot without
    touching storage, checkpoint/tiered.py).

    Exactly ONE host must pass ``is_source=True``; every host must pass
    a tree with the identical structure and per-leaf shapes/dtypes (the
    non-source trees' values are ignored — zeros of the right shape are
    the conventional filler).  Values come back as host numpy arrays.
    Single-process: returns ``tree`` unchanged — no collective, no
    timeout armed."""
    if process_count() == 1:
        return tree
    import jax
    from jax.experimental import multihost_utils

    return _bounded(
        lambda: jax.tree.map(
            np.asarray,
            multihost_utils.broadcast_one_to_all(tree,
                                                 is_source=bool(is_source))),
        timeout_s=timeout_s, name=name)


def min_over_hosts(value: int, *, timeout_s: Optional[float] = None,
                   name: str = "min-over-hosts") -> int:
    """Smallest of the hosts' integers (e.g. the conservative resume
    step).  Single-process: ``int(value)``, no collective."""
    if process_count() == 1:
        return int(value)
    g = _allgather(np.asarray(int(value), np.int64),
                   timeout_s=timeout_s, name=name)
    return int(g.min())


def max_over_hosts(value: int, *, timeout_s: Optional[float] = None,
                   name: str = "max-over-hosts") -> int:
    if process_count() == 1:
        return int(value)
    g = _allgather(np.asarray(int(value), np.int64),
                   timeout_s=timeout_s, name=name)
    return int(g.max())


def any_host(flag: bool, *, timeout_s: Optional[float] = None,
             name: str = "any-host") -> bool:
    """True iff ANY host's flag is set (preemption seen anywhere).
    Single-process: ``bool(flag)``, no collective."""
    if process_count() == 1:
        return bool(flag)
    g = _allgather(np.asarray(bool(flag), np.int32),
                   timeout_s=timeout_s, name=name)
    return bool(g.any())


def all_agree(flag: bool, *, timeout_s: Optional[float] = None,
              name: str = "all-agree") -> bool:
    """True iff EVERY host's flag is set (restore succeeded everywhere).
    Single-process: ``bool(flag)``, no collective."""
    if process_count() == 1:
        return bool(flag)
    g = _allgather(np.asarray(bool(flag), np.int32),
                   timeout_s=timeout_s, name=name)
    return bool(g.all())


def barrier(name: str = "barrier",
            *, timeout_s: Optional[float] = None) -> None:
    """Rendezvous: returns once every host has reached it.
    Single-process: immediate no-op."""
    if process_count() == 1:
        return
    from jax.experimental import multihost_utils

    _bounded(lambda: multihost_utils.sync_global_devices(name),
             timeout_s=timeout_s, name=name)
    logger.debug(f"barrier '{name}' passed on "
                 f"process {process_index()}/{process_count()}")


def allgather_flags(flags: Any, *, timeout_s: Optional[float] = None,
                    name: str = "allgather-flags") -> np.ndarray:
    """Every host's boolean vector, stacked: ``(world, n)`` bool.

    The shard-aware donor-selection primitive (checkpoint/tiered.py):
    each host reports which checkpoint shard regions its RAM snapshot
    holds; the stacked matrix lets every host derive the SAME owner
    assignment deterministically.  Single-process: ``(1, n)``, no
    collective."""
    arr = np.asarray(flags, np.int32)
    if arr.ndim != 1:
        arr = arr.reshape(-1)
    if process_count() == 1:
        return arr[None, :].astype(bool)
    g = _allgather(arr, timeout_s=timeout_s, name=name)
    return g.astype(bool)


# -- coordination-service barrier (NO device collectives) ---------------------
#
# The device barriers above run collectives over the pod's device mesh,
# which makes them unusable in two places the tiered checkpoint path
# needs a rendezvous:
#
# 1. from a background writer thread while the training loop owns the
#    devices (orbax's commit barrier is why tier-1 commits were deferred
#    to pump() on multi-host — a device collective from the writer
#    thread deadlocks against the training collectives);
# 2. during a replacement window, when pod membership is ASYMMETRIC
#    (the dead host's replacement has not joined the mesh yet) — a
#    device collective would hang on capacity that is simply gone.
#
# The filesystem rendezvous below needs only the shared run directory
# (the same medium the commit markers already rely on): each rank drops
# a presence file and polls for the others, bounded by a wall-clock
# timeout with a typed CoordinationError naming the missing ranks.
# It is slower than a device barrier (polling vs interconnect) but it
# is exactly as durable as the checkpoint itself, works from any
# thread, and never touches a device.

_FS_BARRIER_DIRNAME = "_COORD_BARRIERS"


def _safe_key(key: str) -> str:
    import re as _re
    return _re.sub(r"[^a-zA-Z0-9_.-]", "_", str(key))[:200]


def rendezvous_barrier(root: str, key: str, *, world: int, rank: int,
                       timeout_s: Optional[float] = None,
                       poll_s: float = 0.05) -> None:
    """Filesystem rendezvous: block until ``world`` ranks have arrived
    at ``key`` under ``root`` (a shared directory every rank can see).

    Protocol: rank ``r`` atomically creates
    ``<root>/_COORD_BARRIERS/<key>/<r>.ok`` (tmp + rename), then polls
    the directory until ``world`` distinct ``.ok`` files exist.  Keys
    must be fresh per rendezvous (the callers namespace them with the
    step/sequence number); generations are left on disk and pruned
    opportunistically once they are old enough that no straggler can
    still be polling them.

    On expiry: a typed :class:`CoordinationError` naming the barrier
    and the ranks that never arrived — the caller treats it exactly
    like a device-barrier timeout (fail the commit, not the run).
    """
    import os
    import time as _time

    timeout_s = DEFAULT_TIMEOUT_S if timeout_s is None else float(timeout_s)
    world = int(world)
    rank = int(rank)
    if world < 1 or not 0 <= rank < world:
        raise ValueError(f"bad rendezvous membership rank={rank} "
                         f"world={world}")
    base = os.path.join(root, _FS_BARRIER_DIRNAME)
    d = os.path.join(base, _safe_key(key))
    os.makedirs(d, exist_ok=True)
    _prune_old_barriers(base, keep=_safe_key(key),
                        older_than_s=max(4 * timeout_s, 600.0))
    tmp = os.path.join(d, f".{rank}.tmp")
    with open(tmp, "w") as f:
        f.write(str(_time.time()))
    os.replace(tmp, os.path.join(d, f"{rank}.ok"))
    deadline = _time.monotonic() + timeout_s
    while True:
        try:
            present = {int(n[:-3]) for n in os.listdir(d)
                       if n.endswith(".ok") and n[:-3].isdigit()}
        except OSError:
            present = set()
        if len(present & set(range(world))) >= world:
            logger.debug(f"fs barrier '{key}' passed on rank "
                         f"{rank}/{world}")
            return
        if _time.monotonic() >= deadline:
            missing = sorted(set(range(world)) - present)
            raise CoordinationError(
                f"filesystem rendezvous '{key}' timed out after "
                f"{timeout_s:.1f}s on rank {rank}/{world} — rank(s) "
                f"{missing} never arrived (host down, or pod "
                f"membership is asymmetric: a replacement has not "
                f"rejoined yet)", primitive=f"fs-barrier:{key}",
                timeout_s=timeout_s)
        _time.sleep(poll_s)


def _prune_old_barriers(base: str, *, keep: str,
                        older_than_s: float) -> None:
    """Best-effort GC of finished barrier generations: a generation
    untouched for longer than any plausible straggler poll is garbage.
    Never raises (the barrier must not fail over janitorial work)."""
    import os
    import time as _time

    try:
        names = os.listdir(base)
    except OSError:
        return
    now = _time.time()
    for n in names:
        if n == keep:
            continue
        d = os.path.join(base, n)
        try:
            if now - os.path.getmtime(d) < older_than_s:
                continue
            for f in os.listdir(d):
                os.unlink(os.path.join(d, f))
            os.rmdir(d)
        except OSError:
            continue


def fs_barrier_sync_fn(root: str, *, world: Optional[int] = None,
                       rank: Optional[int] = None) -> Callable:
    """An orbax ``BarrierSyncFn`` (``fn(*, key, timeout_ms)``) backed
    by :func:`rendezvous_barrier` — the seam that lets orbax's async
    commit synchronise over the checkpoint directory instead of the
    device mesh (checkpoint/io.py threads it through
    ``AsyncOptions(barrier_sync_fn=...)``).

    ``world``/``rank`` default to the jax process topology at BARRIER
    time (not construction time), so a manager built before
    ``jax.distributed`` initialisation still synchronises correctly.
    Orbax serialises its barrier keys with a per-operation counter, so
    key freshness is guaranteed by the caller."""

    def _sync(*, key: str, timeout_ms: int) -> None:
        w = process_count() if world is None else int(world)
        r = process_index() if rank is None else int(rank)
        if w == 1:
            return
        rendezvous_barrier(root, key, world=w, rank=r,
                           timeout_s=max(timeout_ms, 1) / 1000.0)

    return _sync
