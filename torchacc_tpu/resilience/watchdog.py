"""Hang/straggler watchdog: per-section deadlines, stack dumps, HangError.

A stuck collective, a deadlocked input source, or one straggling host
hangs a multi-host pod *silently*: every other host blocks in the next
collective and the run burns reservation time with zero diagnostics.
The watchdog is the active half of the resilience story (MegaScale §4
"hang diagnosis"): a daemon monitor thread checks an armed deadline; on
expiry it

1. dumps **all-thread stacks** via :mod:`faulthandler` (to a file under
   ``dump_dir`` when set, else stderr) — the artefact that tells you
   *where* the pod wedged without attaching a debugger;
2. increments the ``watchdog_stalls`` counter (utils/metrics.py), which
   rides the step log line and metrics.jsonl;
3. with ``abort_on_hang``, records a typed
   :class:`~torchacc_tpu.errors.HangError` that is raised at the next
   watchdog interaction (``disarm``/``arm``/``beat``) once the stalled
   section returns — a supervisor restarts the job into
   ``fit(resume='auto')``.

A section that never returns cannot have a Python exception delivered
into it (the hang is below the interpreter, in a device wait or a
syscall); for that case the dump + counter are the product, and an
external supervisor timeout is the backstop (docs/resilience.md).

The clock and the monitor thread are injectable/disable-able so unit
tests drive expiry deterministically with a fake clock.
"""

from __future__ import annotations

import contextlib
import faulthandler
import os
import sys
import threading
import time
from typing import Optional

from torchacc_tpu.errors import HangError
from torchacc_tpu.utils.logger import logger

_dump_seq_lock = threading.Lock()
_dump_seq = 0


def dump_stacks(label: str, dump_dir: Optional[str] = None) -> Optional[str]:
    """Write all-thread stacks; returns the file path (None = stderr).

    File names carry the JAX process index, the pid, and a process-wide
    sequence number: a pod-wide stall makes EVERY host dump at once
    into the same shared dump dir, and containerised hosts share pids
    (often 1), so pid+seq alone would clobber the very evidence that
    says which host wedged."""
    global _dump_seq
    if dump_dir:
        try:
            os.makedirs(dump_dir, exist_ok=True)
            with _dump_seq_lock:
                _dump_seq += 1
                seq = _dump_seq
            from torchacc_tpu.resilience.coordination import process_index
            path = os.path.join(
                dump_dir, f"watchdog_{label}_proc{process_index()}"
                          f"_{os.getpid()}_{seq}.txt")
            with open(path, "w") as f:
                f.write(f"watchdog stall: section '{label}' "
                        f"(pid {os.getpid()})\n")
                f.flush()
                faulthandler.dump_traceback(file=f, all_threads=True)
            return path
        except OSError as e:  # unwritable dir — fall through to stderr
            logger.warning(f"watchdog could not write stack dump: {e}")
    try:
        sys.stderr.write(f"watchdog stall: section '{label}' "
                         f"(pid {os.getpid()})\n")
        faulthandler.dump_traceback(all_threads=True)
    except Exception:  # noqa: BLE001 - stderr may be closed at teardown
        pass
    return None


def _stall_event(label: str, waited_s: float, deadline_s: float,
                 dump_dir: Optional[str], abort: bool,
                 note: str = "") -> tuple:
    """The one stall-handling core every trip path shares: count the
    stall, dump all-thread stacks, log, and (when aborting) BUILD the
    typed error — the caller decides whether to raise it now
    (:func:`trip_stall`) or defer it to the next step boundary
    (:class:`Watchdog`).  Returns ``(dump_path, Optional[HangError])``."""
    from torchacc_tpu.utils.metrics import counters

    counters.inc("watchdog_stalls")
    path = dump_stacks(label, dump_dir)
    where = path or "stderr"
    logger.error(
        f"watchdog: '{label}' exceeded its {deadline_s:.1f}s deadline "
        f"(waited {waited_s:.1f}s); all-thread stacks dumped to {where}"
        + note)
    err = None
    if abort:
        err = HangError(
            f"'{label}' exceeded its {deadline_s:.1f}s deadline (waited "
            f"{waited_s:.1f}s; stacks at {where}).  Restart with "
            "resume='auto' to recover the run.",
            label=label, deadline_s=deadline_s, waited_s=waited_s,
            dump_path=path)
    return path, err


def trip_stall(label: str, waited_s: float, deadline_s: float, *,
               dump_dir: Optional[str] = None,
               abort: bool = False) -> Optional[str]:
    """One-shot stall handler for call sites without a Watchdog thread
    (the async loader's consumer wait).  Dumps stacks, counts the stall,
    and raises :class:`HangError` when ``abort`` is set."""
    path, err = _stall_event(label, waited_s, deadline_s, dump_dir, abort)
    if err is not None:
        raise err
    return path


class Watchdog:
    """Arms a deadline around a section of the training loop.

    Usage (what ``Trainer.fit`` does)::

        wd = Watchdog(dump_dir=..., abort_on_hang=True)
        wd.start()
        ...
        wd.arm("data_fetch", 120.0)   # re-arming replaces the deadline
        batch = next(it)
        wd.arm("train_step", 300.0)
        trainer.step(batch)
        wd.disarm()                   # raises a pending HangError here
        ...
        wd.close()

    ``beat()`` resets the armed deadline without changing the label —
    long sections with internal progress (a retry loop) stay "alive" by
    beating, so slow-but-alive never false-positives.  ``clock`` and
    ``poll_interval_s=None`` (no monitor thread; tests call
    :meth:`check_now` directly) make expiry deterministic under test.
    """

    def __init__(self, *, dump_dir: Optional[str] = None,
                 abort_on_hang: bool = False,
                 poll_interval_s: Optional[float] = 0.25,
                 clock=time.monotonic, name: str = "watchdog"):
        self._dump_dir = dump_dir
        self._abort = abort_on_hang
        self._poll = poll_interval_s
        self._clock = clock
        self._name = name
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # armed-section state (all under _lock)
        self._armed = False
        self._label = ""
        self._deadline_s = 0.0
        self._armed_at = 0.0
        self._gen = 0            # bumped on arm/disarm: one trip per arm
        self._tripped_gen = -1
        self._pending: Optional[HangError] = None
        self._last_beat = clock()
        self.stalls = 0
        self.last_dump_path: Optional[str] = None

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "Watchdog":
        if self._poll is not None and self._thread is None:
            self._thread = threading.Thread(
                target=self._monitor, daemon=True, name=self._name)
            self._thread.start()
        return self

    def close(self) -> None:
        """Stop the monitor thread.  Never raises (safe in ``finally``);
        a pending HangError is dropped with a log line."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        with self._lock:
            if self._pending is not None:
                logger.warning(
                    f"watchdog closed with an unraised {self._pending!r}")
                self._pending = None
            self._armed = False

    def _monitor(self) -> None:
        while not self._stop.wait(self._poll):
            try:
                self.check_now()
            except Exception as e:  # noqa: BLE001 - monitor must survive
                logger.warning(f"watchdog monitor error: {e!r}")

    # -- arming -------------------------------------------------------------
    def _take_pending(self) -> Optional[HangError]:
        p, self._pending = self._pending, None
        return p

    def arm(self, label: str, deadline_s: Optional[float]) -> None:
        """Start (or replace) the watched section.  Raises a pending
        HangError from the previous section first, so a stall detected
        mid-step surfaces at the next step boundary."""
        with self._lock:
            p = self._take_pending()
            if p is None:
                self._armed = deadline_s is not None
                self._label = label
                self._deadline_s = deadline_s or 0.0
                now = self._clock()
                self._armed_at = now
                self._last_beat = now
                self._gen += 1
        if p is not None:
            raise p

    def beat(self) -> None:
        """Progress heartbeat: resets the armed deadline."""
        with self._lock:
            now = self._clock()
            self._last_beat = now
            if self._armed:
                self._armed_at = now

    def disarm(self, raise_pending: bool = True) -> None:
        with self._lock:
            self._armed = False
            self._gen += 1
            self._last_beat = self._clock()
            p = self._take_pending() if raise_pending else None
            if not raise_pending:
                self._pending = None
        if p is not None:
            raise p

    @contextlib.contextmanager
    def watch(self, label: str, deadline_s: Optional[float]):
        """Context-manager form of arm/disarm."""
        self.arm(label, deadline_s)
        try:
            yield self
        except BaseException:
            # don't let a pending HangError mask the in-flight exception
            self.disarm(raise_pending=False)
            raise
        self.disarm()

    def heartbeat_age_s(self) -> float:
        """Seconds since the last arm/beat/disarm — the liveness gauge
        the Trainer logs into metrics.jsonl."""
        with self._lock:
            return max(self._clock() - self._last_beat, 0.0)

    # -- expiry -------------------------------------------------------------
    def check_now(self) -> bool:
        """Evaluate the armed deadline (monitor thread; tests call it
        directly after advancing a fake clock).  Returns True when this
        call tripped the stall."""
        with self._lock:
            if not self._armed or self._gen == self._tripped_gen:
                return False
            waited = self._clock() - self._armed_at
            if waited <= self._deadline_s:
                return False
            self._tripped_gen = self._gen
            label, deadline = self._label, self._deadline_s
            self.stalls += 1
        path, err = _stall_event(
            label, waited, deadline, self._dump_dir, self._abort,
            note=("; HangError will be raised at the next step boundary"
                  if self._abort else ""))
        self.last_dump_path = path
        if err is not None:
            with self._lock:
                # only the generation that tripped may raise: if the
                # section was disarmed between the dump and here, the
                # step finished — log-only, no late abort of healthy code
                if self._armed and self._gen == self._tripped_gen:
                    self._pending = err
        return True
