"""Training-metrics observability: JSONL + optional TensorBoard scalars.

Reference: the reference's benchmark loop logs loss/lr/throughput as
TensorBoard scalars behind ``--profile`` (benchmarks/transformer.py:
145-201), and its HF-Trainer path inherits the Trainer's TB logging.
Here the native equivalent is a small writer the Trainer drives:

- ``metrics.jsonl`` — one JSON object per logged step, always written
  (greppable, survives without any viewer installed).
- TensorBoard event files — written when ``torch.utils.tensorboard``
  is importable (torch is a baked-in dependency; the writer degrades
  to JSONL-only otherwise and says so once).

Usage::

    w = MetricsWriter(logdir)
    w.log(step, {"train/loss": 2.17, "train/tokens_per_sec": 1.2e5})
    w.close()

``Trainer.fit(metrics_dir=...)`` wires this in automatically.
"""

from __future__ import annotations

import contextlib
import json
import math
import os
import threading
import time
from typing import Dict, Optional, Union

from torchacc_tpu.utils.logger import logger

Number = Union[int, float]


class BlockedMeter:
    """Host-blocked wall-time accumulator — the ``host_blocked_ms`` seam.

    The hot loop's enemy is the host *waiting on the device*: every
    ``int()``/``float()``/``device_get`` of a step output serialises
    dispatch behind execution.  The trainer wraps each such fetch in
    :meth:`blocked`; ``take_ms()`` pops the accumulated total, so every
    step record quantifies exactly how much host-blocked time its
    interval paid (docs/performance.md "host_blocked_ms triage").  With
    dispatch pipelining (``perf.dispatch_depth > 1``) the fetches hit
    already-completed values and the number collapses toward the
    transfer cost alone.

    Not thread-safe by design: all metered fetches happen on the
    trainer's thread (the async-loader producer never touches it).
    """

    __slots__ = ("_acc",)

    def __init__(self):
        self._acc = 0.0

    @contextlib.contextmanager
    def blocked(self):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self._acc += time.perf_counter() - t0

    def peek_ms(self) -> float:
        return self._acc * 1e3

    def take_ms(self) -> float:
        """Pop the accumulated blocked time (ms) since the last take."""
        v, self._acc = self._acc * 1e3, 0.0
        return v


class Counters:
    """Process-wide monotonic counters for degradation events.

    The resilience subsystem increments these (``anomalies_skipped``,
    ``ckpt_retries``, ``resumes``, ``loader_retries``,
    ``loader_fallbacks``, ``preemptions``, ``emergency_saves``,
    ``watchdog_stalls``, the elastic-resume trio
    ``resume_replayed_batches`` / ``bad_batches_skipped`` /
    ``elastic_reshards``, the SDC-defense trio ``sdc_checks`` /
    ``replica_divergences`` / ``sdc_mismatches``, the
    layout-transfer pair ``transfer_compiles`` /
    ``transfer_cache_hits`` — parallel/transfer.py — the serving
    prefix-cache set ``prefix_hits`` / ``prefix_blocks_reused`` /
    ``prefix_evictions`` / ``cow_copies`` — serve/ — and
    ``metrics_nonfinite_values``, non-finite scalars this writer
    serialised as ``null``) and the Trainer
    surfaces the non-zero ones in
    every step log line AND every metrics.jsonl step record — an
    operator sees a run degrading without grepping worker logs.
    Thread-safe: retries fire from the async-loader producer thread.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._c: Dict[str, int] = {}

    def inc(self, name: str, n: int = 1) -> int:
        with self._lock:
            self._c[name] = self._c.get(name, 0) + n
            return self._c[name]

    def get(self, name: str) -> int:
        with self._lock:
            return self._c.get(name, 0)

    def snapshot(self) -> Dict[str, int]:
        """Non-zero counters, sorted by name."""
        with self._lock:
            return {k: v for k, v in sorted(self._c.items()) if v}

    def reset(self) -> None:
        """Zero everything (tests)."""
        with self._lock:
            self._c.clear()

    def suffix(self) -> str:
        """Log-line suffix like ``" [ckpt_retries=2 resumes=1]"``; empty
        when every counter is zero."""
        snap = self.snapshot()
        if not snap:
            return ""
        return " [" + " ".join(f"{k}={v}" for k, v in snap.items()) + "]"


#: The process-wide instance every subsystem shares.
counters = Counters()


def _process_index() -> int:
    """JAX process index (0 before/without distributed init).  A
    module-level seam — not a direct call site — so tests can
    monkeypatch it; delegates to the one shared implementation in
    resilience/coordination.py (lazy import: metrics must stay cheap to
    import)."""
    from torchacc_tpu.resilience.coordination import process_index
    return process_index()


class MetricsWriter:
    """Scalar metrics sink: JSONL always, TensorBoard when available.

    Multi-host: on a shared filesystem every process appending to the
    same ``metrics.jsonl`` interleaves half-written lines and TensorBoard
    event files shadow each other, so by default only the primary
    process (``jax.process_index() == 0``) writes — the SPMD metrics are
    identical on every host anyway.  ``all_processes=True`` opts
    non-primary processes into their own ``metrics.<process_index>.jsonl``
    (per-host loader/watchdog counters DO differ); TensorBoard stays
    primary-only.  Single-process behaviour is unchanged.
    """

    def __init__(self, logdir: str, *, tensorboard: bool = True,
                 all_processes: bool = False):
        self.logdir = logdir
        idx = _process_index()
        self._jsonl = None
        self._tb = None
        if idx != 0 and not all_processes:
            logger.debug(
                f"metrics writer inactive on process {idx} (primary-only "
                "default; pass all_processes=True for per-process files)")
            return
        os.makedirs(logdir, exist_ok=True)
        fname = "metrics.jsonl" if idx == 0 else f"metrics.{idx}.jsonl"
        self._jsonl = open(os.path.join(logdir, fname), "a", buffering=1)
        if tensorboard and idx == 0:
            try:
                from torch.utils.tensorboard import SummaryWriter
                self._tb = SummaryWriter(log_dir=logdir)
            except Exception as e:  # noqa: BLE001 - degrade, don't fail
                logger.warning(
                    f"TensorBoard writer unavailable ({e}); metrics go to "
                    f"{logdir}/metrics.jsonl only")

    def log(self, step: int, scalars: Dict[str, Number]) -> None:
        if self._jsonl is None:
            return
        # Coerce + validate EVERY value BEFORE touching either sink: a
        # non-numeric value used to raise mid-loop after some TB
        # scalars were already written, leaving the two sinks
        # permanently out of step for that record.  Now the whole
        # record is judged first — on a bad value neither sink writes.
        vals = {k: float(v) for k, v in scalars.items()}
        rec: Dict[str, Optional[float]] = {"step": int(step),
                                           "time": time.time()}
        for k, v in vals.items():
            if math.isfinite(v):
                rec[k] = v
            else:
                # bare NaN/Infinity is a json.dumps extension, NOT
                # standard JSON — strict consumers reject the whole
                # metrics.jsonl for one non-finite loss.  Serialise as
                # null and count the occurrence so the signal (and its
                # frequency) survives the substitution.
                rec[k] = None
                counters.inc("metrics_nonfinite_values")
        self._jsonl.write(json.dumps(rec, allow_nan=False) + "\n")
        if self._tb is not None:
            # TB keeps the raw values (its format handles non-finite)
            for k, v in vals.items():
                self._tb.add_scalar(k, v, int(step))

    def log_text(self, tag: str, text: str, step: int = 0) -> None:
        if self._tb is not None:
            self._tb.add_text(tag, text, int(step))

    def flush(self) -> None:
        if self._jsonl is not None:
            self._jsonl.flush()
        if self._tb is not None:
            self._tb.flush()

    def close(self) -> None:
        self.flush()
        if self._jsonl is not None:
            self._jsonl.close()
        if self._tb is not None:
            self._tb.close()


def open_metrics(logdir: Optional[str],
                 all_processes: bool = False) -> Optional[MetricsWriter]:
    """None-safe constructor for call sites with an optional dir."""
    if not logdir:
        return None
    return MetricsWriter(logdir, all_processes=all_processes)
