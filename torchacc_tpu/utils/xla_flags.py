"""Curated XLA/LIBTPU performance flags (opt-in).

Reference: `_set_env` merges ~20 XLA_FLAGS perf defaults at import time
(torchacc/__init__.py:72-132 — latency-hiding scheduler, async
collectives, combine thresholds).  XLA:TPU already defaults to the
latency-hiding scheduler and async collectives, so this framework sets
NOTHING implicitly; this module provides the same levers explicitly for
tuning runs.  Call BEFORE the first jax import/backend init.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

# conservative, TPU-appropriate tuning set (names are stable XLA flags)
PERFORMANCE_FLAGS: Dict[str, str] = {
    # bigger combined collectives amortise ICI latency (reference sets the
    # GPU analogues of these thresholds)
    "xla_tpu_enable_async_collective_fusion": "true",
    "xla_tpu_enable_async_collective_fusion_fuse_all_gather": "true",
    # overlap-friendly scheduling is default on TPU; listed for visibility
    "xla_tpu_enable_latency_hiding_scheduler": "true",
}


def apply_performance_flags(extra: Optional[Dict[str, str]] = None) -> str:
    """Merge the curated flag set (plus ``extra``) into XLA_FLAGS.

    Returns the resulting XLA_FLAGS string.  Existing user-set flags take
    precedence (mirroring the reference's merge semantics,
    torchacc/__init__.py:93-121).
    """
    flags = dict(PERFORMANCE_FLAGS)
    if extra:
        flags.update(extra)
    current = os.environ.get("XLA_FLAGS", "")
    existing_names = {tok.split("=")[0].lstrip("-")
                      for tok in current.split() if tok.startswith("--")}
    additions = [f"--{k}={v}" for k, v in flags.items()
                 if k not in existing_names]
    merged = " ".join([current] + additions).strip()
    os.environ["XLA_FLAGS"] = merged
    return merged
