"""The ONE retry/backoff/jitter + circuit-breaker core.

Four near-duplicate backoff loops used to live across the tree — the
checkpoint I/O retries (``resilience/retry.py``), the stdlib HTTP
client's transport retries (``utils/http.py``), the serve router's
per-worker admission breaker (``serve/router.py``), and the object-store
shard fetch path (``data/store.py``).  They all share the same contract,
so the contract lives here once:

- :class:`RetryPolicy` — jittered exponential backoff with a wall-clock
  deadline, a frozen dataclass so call sites share one instance;
- :func:`retry_call` — the retry loop itself, with injectable
  ``sleep``/``rng``/``clock`` seams (tests run in microseconds), a
  metrics counter per retried attempt, and an ``on_retry`` hook so
  callers can surface "slow but alive" (the loader's in-retry flag that
  keeps a retrying fetch from tripping the hang watchdog);
- :class:`CircuitBreaker` — closed → open after N consecutive failures
  → half-open probe after a cooldown, the state machine the router uses
  per worker and the streaming data plane uses per source.

``resilience/retry.py`` and ``serve/router.py`` re-export their old
names, so existing imports keep working; new code should import from
here.  Stdlib-only, no jax anywhere — consumers include hosts that
never initialise a device backend.

Flaky storage (GCS 429/503s, NFS hiccups) and transient loader failures
must not kill a multi-host run; MaxText/Orbax production loops wrap every
checkpoint I/O in exactly this shape of retry.  Retries are observable:
every retried attempt increments a monotonic counter (utils/metrics.py)
and logs at WARNING, so degradation shows up in the step log line and
metrics.jsonl, not only in a post-mortem.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass
from typing import Any, Callable, Optional, Tuple, Type

from torchacc_tpu.utils.logger import logger


@dataclass(frozen=True)
class RetryPolicy:
    """How to retry a transient failure.

    ``max_retries`` counts *re*-tries: the call is attempted at most
    ``max_retries + 1`` times.  Delay before retry ``k`` (0-based) is
    ``min(base_delay_s * multiplier**k, max_delay_s)`` scaled by a
    uniform jitter in ``[1 - jitter, 1 + jitter]``.  ``deadline_s``
    bounds the *total* wall-clock spent (attempts + sleeps): once
    exceeded, no further attempt is made and the last error is
    re-raised.
    """

    max_retries: int = 3
    base_delay_s: float = 0.5
    max_delay_s: float = 8.0
    deadline_s: Optional[float] = None
    jitter: float = 0.5
    retry_on: Tuple[Type[BaseException], ...] = (Exception,)
    # exceptions that are final even when retry_on matches them (e.g. a
    # typed error raised by the retried callable to mean "do not retry")
    no_retry: Tuple[Type[BaseException], ...] = ()
    multiplier: float = 2.0

    def validate(self) -> None:
        if self.max_retries < 0:
            raise ValueError("retry: max_retries must be >= 0")
        if self.base_delay_s < 0 or self.max_delay_s < self.base_delay_s:
            raise ValueError("retry: need 0 <= base_delay_s <= max_delay_s")
        if not 0.0 <= self.jitter <= 1.0:
            raise ValueError("retry: jitter must be in [0, 1]")
        if self.deadline_s is not None and self.deadline_s <= 0:
            raise ValueError("retry: deadline_s must be positive")
        if self.multiplier < 1.0:
            raise ValueError("retry: multiplier must be >= 1")

    def delay(self, attempt: int, rng: random.Random) -> float:
        base = min(self.base_delay_s * (self.multiplier ** attempt),
                   self.max_delay_s)
        return base * (1.0 - self.jitter + 2.0 * self.jitter * rng.random())


def retry_call(
    fn: Callable[..., Any],
    *args: Any,
    policy: RetryPolicy = RetryPolicy(),
    description: str = "",
    counter: Optional[str] = None,
    rng: Optional[random.Random] = None,
    sleep: Callable[[float], None] = time.sleep,
    clock: Callable[[], float] = time.monotonic,
    on_retry: Optional[Callable[[int, BaseException, float], None]] = None,
    **kwargs: Any,
) -> Any:
    """Call ``fn(*args, **kwargs)``, retrying per ``policy``.

    ``counter`` names a utils/metrics monotonic counter incremented once
    per *retried* attempt.  ``on_retry(attempt, exc, delay_s)`` fires
    just before each backoff sleep — the seam callers use to surface
    "retrying, not hung" to watchdogs/heartbeats.  The last exception is
    re-raised unchanged (with prior attempts visible via
    ``__context__``) so callers keep their own typed wrapping.
    """
    rng = rng if rng is not None else random.Random()
    what = description or getattr(fn, "__name__", "call")
    start = clock()
    for attempt in range(policy.max_retries + 1):
        try:
            return fn(*args, **kwargs)
        except policy.retry_on as e:
            if isinstance(e, policy.no_retry) or attempt >= policy.max_retries:
                raise
            delay = policy.delay(attempt, rng)
            # a throttling backend (HTTP 429) may name its own pace;
            # honour it when it is longer than the schedule's
            retry_after = getattr(e, "retry_after_s", None)
            if retry_after is not None:
                delay = max(delay, float(retry_after))
            if (policy.deadline_s is not None
                    and clock() - start + delay > policy.deadline_s):
                logger.warning(
                    f"{what}: attempt {attempt + 1} failed ({e!r}) and the "
                    f"{policy.deadline_s:.1f}s retry deadline is exhausted")
                raise
            if counter is not None:
                from torchacc_tpu.utils.metrics import counters
                counters.inc(counter)
            logger.warning(
                f"{what}: attempt {attempt + 1}/{policy.max_retries + 1} "
                f"failed ({e!r}); retrying in {delay:.2f}s")
            if on_retry is not None:
                on_retry(attempt, e, delay)
            sleep(delay)
    raise AssertionError("unreachable")  # pragma: no cover


class CircuitBreaker:
    """Per-dependency admission breaker: ``closed`` (routable) → ``open``
    after ``failure_threshold`` consecutive failures → ``half_open``
    once ``cooldown_s`` has elapsed (exactly one probe allowed) → back to
    ``closed`` on probe success or ``open`` on probe failure.  The clock
    is injectable so the state machine unit-tests run on a fake clock.

    Two instantiations: the serve router holds one per worker (probe
    failures open it, failover fires on the open edge), and the
    streaming data plane holds one per source (quarantined shards open
    it, the source sheds to survivors on the open edge)."""

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(self, *, failure_threshold: int = 3,
                 cooldown_s: float = 5.0,
                 clock: Callable[[], float] = time.monotonic):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.failure_threshold = int(failure_threshold)
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self.state = self.CLOSED
        self.failures = 0          # consecutive
        self.opened_at = 0.0
        self.opens = 0             # transitions into OPEN (flap count)

    @property
    def routable(self) -> bool:
        """Only a closed breaker admits traffic — half-open carries the
        probe, not requests."""
        return self.state == self.CLOSED

    def should_probe(self) -> bool:
        """Health-loop gate: closed and half-open dependencies probe
        every tick; an open one only after the cooldown (that attempt IS
        the half-open transition)."""
        if self.state != self.OPEN:
            return True
        if self._clock() - self.opened_at >= self.cooldown_s:
            self.state = self.HALF_OPEN
            return True
        return False

    def record_success(self) -> bool:
        """Returns True when this success CLOSED a non-closed breaker
        (the readmission edge, so the caller can count/log it)."""
        readmitted = self.state != self.CLOSED
        self.state = self.CLOSED
        self.failures = 0
        return readmitted

    def record_failure(self) -> bool:
        """Returns True when this failure OPENED the breaker (the
        caller triggers failover/shed exactly once per open edge)."""
        self.failures += 1
        if (self.state == self.HALF_OPEN
                or self.failures >= self.failure_threshold):
            opened = self.state != self.OPEN
            if opened:
                self.opens += 1
            self.state = self.OPEN
            self.opened_at = self._clock()
            return opened
        return False
