"""JAX version compatibility shims.

The framework targets current JAX, but containers pin older releases;
hard-failing on a missing alias would brick every trainer path.  Shims
are installed once at ``import torchacc_tpu`` and are no-ops on modern
JAX.
"""

from __future__ import annotations

import jax


def install() -> None:
    """Install all applicable shims (idempotent)."""
    _install_set_mesh()
    _install_get_abstract_mesh()
    _install_shard_map()
    _install_pallas_compiler_params()


def _install_set_mesh() -> None:
    # jax.sharding.set_mesh (the context-manager form every call site
    # here uses) landed after 0.4.x; on older JAX a concrete Mesh is
    # itself a context manager with the same scoping semantics, so
    # delegate to it.
    if hasattr(jax.sharding, "set_mesh"):
        return

    def set_mesh(mesh):
        return mesh

    jax.sharding.set_mesh = set_mesh


def _install_get_abstract_mesh() -> None:
    # jax.sharding.get_abstract_mesh reads the mesh context set_mesh
    # established; the 0.4.x equivalent is the thread-local physical
    # mesh a `with mesh:` block sets.  Call sites guard for None/empty.
    if hasattr(jax.sharding, "get_abstract_mesh"):
        return

    def get_abstract_mesh():
        from jax._src import mesh as mesh_lib
        m = mesh_lib.thread_resources.env.physical_mesh
        return None if m.empty else m

    jax.sharding.get_abstract_mesh = get_abstract_mesh


def _install_shard_map() -> None:
    # jax.shard_map(f, mesh=..., in_specs=..., out_specs=...,
    # check_vma=..., axis_names=...) is the stabilised form of
    # jax.experimental.shard_map.shard_map, whose kwargs differ:
    # check_rep is the old name of check_vma, and `auto` is the
    # complement of axis_names (axes left automatic rather than axes
    # made manual).
    if hasattr(jax, "shard_map"):
        return
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True,
                  axis_names=None):
        auto = frozenset()
        if axis_names is not None:
            auto = frozenset(mesh.axis_names) - frozenset(axis_names)
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=bool(check_vma),
                          auto=auto)

    jax.shard_map = shard_map


def _install_pallas_compiler_params() -> None:
    # pltpu.CompilerParams was named TPUCompilerParams on older releases
    # (same dimension_semantics field).
    try:
        import jax.experimental.pallas.tpu as pltpu
    except Exception:  # noqa: BLE001 - no pallas on this build
        return
    if hasattr(pltpu, "CompilerParams") or \
            not hasattr(pltpu, "TPUCompilerParams"):
        return
    pltpu.CompilerParams = pltpu.TPUCompilerParams
