"""One shared stdlib HTTP client for the jax-free control plane.

Three callers used to carry their own copy of the same semantics —
``supervisor/probe.py`` (ProbeClient's retrying ``_fetch``),
``obs/aggregate.py`` (the fleet scraper's one-shot ``_http_fetch``),
and now ``serve/router.py`` — so the retry/backoff contract lives here
once:

- every request is **timeout-bounded** (a wedged endpoint costs
  ``timeout_s``, never a caller hang);
- an HTTP error status **is an answer** (503 = unhealthy), returned as
  ``(code, body)`` and never retried;
- transport failures (connection refused, reset, timeout) retry with
  **jittered exponential backoff** inside the call, then raise the
  last error when every attempt failed;
- ``sleep``/``rng`` are injectable so backoff schedules are testable
  without wall time.

Stdlib-only (urllib), no jax anywhere: every consumer runs on hosts
that never initialise a device backend.
"""

from __future__ import annotations

import json
import random
import time
import urllib.error
import urllib.request
from typing import Callable, Dict, Optional, Tuple


def request(url: str, *, method: str = "GET",
            data: Optional[bytes] = None,
            headers: Optional[Dict[str, str]] = None,
            timeout_s: float = 2.0) -> Tuple[int, str]:
    """One attempt, no retry: ``(status_code, body)``.

    An HTTP error status is returned, not raised; transport errors
    (``URLError``/``OSError``/``TimeoutError``) propagate to the
    caller — the retrying wrapper is :meth:`HttpClient.request`."""
    req = urllib.request.Request(url, data=data, method=method,
                                 headers=dict(headers or {}))
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


class HttpClient:
    """Timeout-bounded, jitter-retrying client rooted at ``base_url``.

    The retry loop covers transport failures only; any HTTP status is
    a final answer.  ``delay(attempt)`` exposes the backoff schedule
    (exponential from ``backoff_s`` capped at ``max_backoff_s``,
    ±``jitter`` fraction) for callers that pace their own loops."""

    def __init__(self, base_url: str, *, timeout_s: float = 2.0,
                 retries: int = 2, backoff_s: float = 0.2,
                 backoff_multiplier: float = 2.0,
                 max_backoff_s: float = 2.0, jitter: float = 0.5,
                 rng: Optional[random.Random] = None,
                 sleep: Callable[[float], None] = time.sleep):
        self.base_url = base_url.rstrip("/")
        self.timeout_s = float(timeout_s)
        self.retries = int(retries)
        self.backoff_s = float(backoff_s)
        self.backoff_multiplier = float(backoff_multiplier)
        self.max_backoff_s = float(max_backoff_s)
        self.jitter = float(jitter)
        self._rng = rng if rng is not None else random.Random()
        self._sleep = sleep

    def delay(self, attempt: int) -> float:
        base = min(self.backoff_s * (self.backoff_multiplier ** attempt),
                   self.max_backoff_s)
        return max(base * (1.0 + self.jitter
                           * (2.0 * self._rng.random() - 1.0)), 0.0)

    def request(self, path: str, *, method: str = "GET",
                data: Optional[bytes] = None,
                headers: Optional[Dict[str, str]] = None
                ) -> Tuple[int, str]:
        """``(status_code, body)`` with bounded retries; raises the
        last transport error when every attempt failed."""
        last: Optional[BaseException] = None
        for attempt in range(self.retries + 1):
            try:
                return request(self.base_url + path, method=method,
                               data=data, headers=headers,
                               timeout_s=self.timeout_s)
            except (urllib.error.URLError, OSError, TimeoutError) as e:
                last = e
                if attempt < self.retries:
                    self._sleep(self.delay(attempt))
        raise last if last is not None else OSError("unreachable")

    # -- JSON conveniences ----------------------------------------------------

    def get_json(self, path: str) -> Tuple[int, object]:
        """GET ``path`` and parse the body as JSON.  An unparseable
        body raises ``ValueError`` (strict-JSON endpoints never answer
        with prose on success paths)."""
        code, body = self.request(path)
        return code, json.loads(body)

    def post_json(self, path: str, payload: object) -> Tuple[int, object]:
        """POST ``payload`` as JSON, parse the JSON answer."""
        code, body = self.request(
            path, method="POST",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        return code, json.loads(body)
