"""One shared stdlib HTTP client for the jax-free control plane.

Three callers used to carry their own copy of the same semantics —
``supervisor/probe.py`` (ProbeClient's retrying ``_fetch``),
``obs/aggregate.py`` (the fleet scraper's one-shot ``_http_fetch``),
and now ``serve/router.py`` — so the retry/backoff contract lives here
once:

- every request is **timeout-bounded** (a wedged endpoint costs
  ``timeout_s``, never a caller hang);
- an HTTP error status **is an answer** (503 = unhealthy), returned as
  ``(code, body)`` and never retried;
- transport failures (connection refused, reset, timeout) retry with
  **jittered exponential backoff** inside the call, then raise the
  last error when every attempt failed;
- ``sleep``/``rng`` are injectable so backoff schedules are testable
  without wall time.

The backoff schedule and the retry loop are NOT implemented here — they
are the shared core in ``utils/retry.py`` (:class:`RetryPolicy` /
:func:`retry_call`), the same one the checkpoint I/O, the loader, and
the object-store shard fetch path use.  This module only binds it to
urllib transport semantics.

Stdlib-only (urllib), no jax anywhere: every consumer runs on hosts
that never initialise a device backend.
"""

from __future__ import annotations

import json
import random
import time
import urllib.error
import urllib.request
from typing import Callable, Dict, Optional, Tuple

from torchacc_tpu.utils.retry import RetryPolicy, retry_call


def request(url: str, *, method: str = "GET",
            data: Optional[bytes] = None,
            headers: Optional[Dict[str, str]] = None,
            timeout_s: float = 2.0) -> Tuple[int, str]:
    """One attempt, no retry: ``(status_code, body)``.

    An HTTP error status is returned, not raised; transport errors
    (``URLError``/``OSError``/``TimeoutError``) propagate to the
    caller — the retrying wrapper is :meth:`HttpClient.request`."""
    req = urllib.request.Request(url, data=data, method=method,
                                 headers=dict(headers or {}))
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


class HttpClient:
    """Timeout-bounded, jitter-retrying client rooted at ``base_url``.

    The retry loop covers transport failures only; any HTTP status is
    a final answer.  ``delay(attempt)`` exposes the backoff schedule
    (exponential from ``backoff_s`` capped at ``max_backoff_s``,
    ±``jitter`` fraction) for callers that pace their own loops."""

    def __init__(self, base_url: str, *, timeout_s: float = 2.0,
                 retries: int = 2, backoff_s: float = 0.2,
                 backoff_multiplier: float = 2.0,
                 max_backoff_s: float = 2.0, jitter: float = 0.5,
                 rng: Optional[random.Random] = None,
                 sleep: Callable[[float], None] = time.sleep):
        self.base_url = base_url.rstrip("/")
        self.timeout_s = float(timeout_s)
        self.retries = int(retries)
        # the shared backoff core (utils/retry.py) owns the schedule —
        # transport errors only; any HTTP status is a final answer and
        # never reaches the retry loop
        self._policy = RetryPolicy(
            max_retries=int(retries), base_delay_s=float(backoff_s),
            max_delay_s=float(max_backoff_s), jitter=float(jitter),
            multiplier=float(backoff_multiplier),
            retry_on=(urllib.error.URLError, OSError, TimeoutError))
        self._rng = rng if rng is not None else random.Random()
        self._sleep = sleep

    # legacy knob views (callers pace their own loops off these)
    @property
    def backoff_s(self) -> float:
        return self._policy.base_delay_s

    @property
    def max_backoff_s(self) -> float:
        return self._policy.max_delay_s

    @property
    def jitter(self) -> float:
        return self._policy.jitter

    def delay(self, attempt: int) -> float:
        """The backoff schedule (exponential from ``backoff_s`` capped
        at ``max_backoff_s``, ±``jitter`` fraction) for callers that
        pace their own loops."""
        return max(self._policy.delay(attempt, self._rng), 0.0)

    def request(self, path: str, *, method: str = "GET",
                data: Optional[bytes] = None,
                headers: Optional[Dict[str, str]] = None
                ) -> Tuple[int, str]:
        """``(status_code, body)`` with bounded retries; raises the
        last transport error when every attempt failed."""
        return retry_call(
            request, self.base_url + path, method=method, data=data,
            headers=headers, timeout_s=self.timeout_s,
            policy=self._policy, description=f"http {method} {path}",
            rng=self._rng, sleep=self._sleep)

    # -- JSON conveniences ----------------------------------------------------

    def get_json(self, path: str) -> Tuple[int, object]:
        """GET ``path`` and parse the body as JSON.  An unparseable
        body raises ``ValueError`` (strict-JSON endpoints never answer
        with prose on success paths)."""
        code, body = self.request(path)
        return code, json.loads(body)

    def post_json(self, path: str, payload: object) -> Tuple[int, object]:
        """POST ``payload`` as JSON, parse the JSON answer."""
        code, body = self.request(
            path, method="POST",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        return code, json.loads(body)
