"""Profiling hooks.

Reference: ``torch.profiler`` + tensorboard handler behind ``--profile``
(benchmarks/transformer.py:155-160), XLA HLO dumps via ``--xla_dump_to``
(torchacc/__init__.py:122-127), and the buffer-assignment memory plotter
(tools/plot_mem.py).  TPU-native: jax.profiler traces (viewable in
TensorBoard/XProf), a step timer, and compiled-memory stats straight
from the jitted executable — no log scraping.
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Dict, Iterator, Optional

import jax


@contextlib.contextmanager
def trace(logdir: str) -> Iterator[None]:
    """jax.profiler trace context (open the logdir in TensorBoard)."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class StepTimer:
    """Wall-clock per-step timing with warmup discard."""

    def __init__(self, warmup: int = 2):
        self.warmup = warmup
        self.times = []
        self._last: Optional[float] = None
        self._count = 0

    def tick(self) -> None:
        now = time.perf_counter()
        if self._last is not None:
            self._count += 1
            if self._count > self.warmup:
                self.times.append(now - self._last)
        self._last = now

    @property
    def mean(self) -> float:
        return sum(self.times) / len(self.times) if self.times else 0.0


def compiled_memory_stats(fn, *abstract_args) -> Dict[str, Any]:
    """Memory analysis of a jitted function (reference tools/plot_mem.py
    parses XLA buffer-assignment dumps; here it is a first-class API)."""
    lowered = jax.jit(fn).lower(*abstract_args)
    compiled = lowered.compile()
    mem = compiled.memory_analysis()
    if mem is None:
        return {}
    return {
        "argument_bytes": getattr(mem, "argument_size_in_bytes", None),
        "output_bytes": getattr(mem, "output_size_in_bytes", None),
        "temp_bytes": getattr(mem, "temp_size_in_bytes", None),
        "generated_code_bytes": getattr(mem, "generated_code_size_in_bytes",
                                        None),
    }
