"""Profiling hooks.

Reference: ``torch.profiler`` + tensorboard handler behind ``--profile``
(benchmarks/transformer.py:155-160), XLA HLO dumps via ``--xla_dump_to``
(torchacc/__init__.py:122-127), and the buffer-assignment memory plotter
(tools/plot_mem.py).  TPU-native: jax.profiler traces (viewable in
TensorBoard/XProf), a step timer, and compiled-memory stats straight
from the jitted executable — no log scraping.
"""

from __future__ import annotations

import contextlib
import glob
import gzip
import json
import os
import time
from typing import Any, Dict, Iterator, List, Optional, Tuple

import jax


@contextlib.contextmanager
def trace(logdir: str) -> Iterator[None]:
    """jax.profiler trace context (open the logdir in TensorBoard)."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class StepTimer:
    """Wall-clock per-step timing with warmup discard."""

    def __init__(self, warmup: int = 2):
        self.warmup = warmup
        self.times = []
        self._last: Optional[float] = None
        self._count = 0

    def tick(self) -> None:
        now = time.perf_counter()
        if self._last is not None:
            self._count += 1
            if self._count > self.warmup:
                self.times.append(now - self._last)
        self._last = now

    @property
    def mean(self) -> float:
        return sum(self.times) / len(self.times) if self.times else 0.0


def _merge_busy(intervals: List[Tuple[float, float]]
                ) -> Tuple[float, float]:
    """(busy_us, span_us) of a set of [start, end) event intervals —
    busy is the measure of their union, span the hull."""
    if not intervals:
        return 0.0, 0.0
    intervals.sort()
    busy = 0.0
    cur_s, cur_e = intervals[0]
    for s, e in intervals[1:]:
        if s > cur_e:
            busy += cur_e - cur_s
            cur_s, cur_e = s, e
        else:
            cur_e = max(cur_e, e)
    busy += cur_e - cur_s
    return busy, cur_e - intervals[0][0]


def device_idle_from_trace(logdir: str) -> Optional[Dict[str, float]]:
    """Gap-sum between device ops in a ``jax.profiler`` trace.

    Parses the newest ``*.trace.json.gz`` under ``logdir`` (the Chrome
    trace the profiler writes next to the xplane): complete events of
    the DEVICE lanes are union-merged and the idle time is the hull
    minus the union — i.e. the sum of gaps where the device ran
    nothing while the trace window was live.  This is the overlap
    measurement the MFU number cannot give: comms/dispatch stalls show
    up as idle gaps even when every compute op is fast
    (bench.py ``device_idle_ms`` detail row).

    Lane selection: processes named ``/device:*`` (real TPU/GPU
    traces).  XLA:CPU has no device plane — there the XLA execution
    threads (``tf_XLAEigen*`` / ``tf_XLATfrtCpuClient*`` under
    ``/host:CPU``) stand in, which makes the CPU number a host-compute
    proxy, good enough for the smoke gate's plumbing check.

    Returns ``{"device_idle_ms", "device_busy_ms", "span_ms",
    "source"}`` (source 1.0 = device plane, 0.0 = CPU-thread fallback)
    or None when no trace / no usable lane exists — callers emit null
    rather than fail."""
    paths = sorted(glob.glob(os.path.join(
        logdir, "**", "*.trace.json.gz"), recursive=True),
        key=os.path.getmtime)
    if not paths:
        return None
    try:
        with gzip.open(paths[-1], "rt") as f:
            events = json.load(f).get("traceEvents", [])
    except (OSError, ValueError, EOFError):
        # EOFError: a truncated gzip stream (profiler killed mid-write)
        # raises it directly, not as OSError
        return None
    proc_names: Dict[Any, str] = {}
    thread_names: Dict[Tuple[Any, Any], str] = {}
    for e in events:
        if e.get("ph") != "M":
            continue
        if e.get("name") == "process_name":
            proc_names[e.get("pid")] = e.get("args", {}).get("name", "")
        elif e.get("name") == "thread_name":
            thread_names[(e.get("pid"), e.get("tid"))] = \
                e.get("args", {}).get("name", "")
    device_pids = {p for p, n in proc_names.items()
                   if n.startswith("/device:")}
    use_device = bool(device_pids)
    xla_tids = {k for k, n in thread_names.items()
                if n.startswith(("tf_XLAEigen", "tf_XLATfrtCpuClient"))}
    intervals: List[Tuple[float, float]] = []
    for e in events:
        if e.get("ph") != "X" or "ts" not in e:
            continue
        dur = e.get("dur", 0.0)
        if dur <= 0:
            continue
        if use_device:
            if e.get("pid") not in device_pids:
                continue
        elif (e.get("pid"), e.get("tid")) not in xla_tids:
            continue
        intervals.append((float(e["ts"]), float(e["ts"]) + float(dur)))
    busy, span = _merge_busy(intervals)
    if span <= 0:
        return None
    return {
        "device_idle_ms": (span - busy) / 1e3,
        "device_busy_ms": busy / 1e3,
        "span_ms": span / 1e3,
        "source": 1.0 if use_device else 0.0,
    }


def compiled_memory_stats(fn, *abstract_args) -> Dict[str, Any]:
    """Memory analysis of a jitted function (reference tools/plot_mem.py
    parses XLA buffer-assignment dumps; here it is a first-class API)."""
    lowered = jax.jit(fn).lower(*abstract_args)
    compiled = lowered.compile()
    mem = compiled.memory_analysis()
    if mem is None:
        return {}
    return {
        "argument_bytes": getattr(mem, "argument_size_in_bytes", None),
        "output_bytes": getattr(mem, "output_size_in_bytes", None),
        "temp_bytes": getattr(mem, "temp_size_in_bytes", None),
        "generated_code_bytes": getattr(mem, "generated_code_size_in_bytes",
                                        None),
    }
