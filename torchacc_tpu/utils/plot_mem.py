"""Per-buffer memory-lifetime visualisation from XLA buffer assignment.

Reference: ``tools/plot_mem.py`` (1-340) parses a torch_xla buffer-
assignment dump and renders every buffer's live range with the peak
annotated.  TPU-native equivalent: XLA writes the same information for
any jitted program when dumping is enabled —

    XLA_FLAGS="--xla_dump_to=DIR --xla_dump_hlo_as_text" python train.py

produces ``module_*.jit_<name>.*buffer-assignment.txt`` (allocations,
logical values, uses) and ``module_*.jit_<name>.*after_optimizations.txt``
(the scheduled HLO, giving instruction order = the time axis).  This
module parses both and renders the reference-style plot:

  - each temp/output allocation drawn as a rectangle spanning
    [first definition, last use] in instruction order, stacked on a
    bytes axis, colored by kind;
  - the live-bytes step curve with the peak annotated;
  - parameters shown as the always-live baseline.

CLI::

    python -m torchacc_tpu.utils.plot_mem DUMP_DIR -o mem.png
    python -m torchacc_tpu.utils.plot_mem DUMP_DIR --module train_step

Parsing is defensive: anything unrecognised degrades to "no lifetime"
(bar spanning the whole program) rather than an error, so the tool keeps
working across XLA dump-format drift.
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import re
import sys
from typing import Dict, List, Optional, Tuple


@dataclasses.dataclass
class Alloc:
    index: int
    size: int
    kind: str                      # 'parameter' | 'temp' | 'output' | 'constant'
    values: List[str]              # logical value instruction names
    start: Optional[int] = None    # instruction-order live range
    end: Optional[int] = None


_ALLOC_RE = re.compile(r"^allocation (\d+): size (\d+), (.*):$")
_VALUE_RE = re.compile(r"^\s+value: <\d+ ([^ ]+) @\d+>")
_USED_VALUE_RE = re.compile(r"^<\d+ ([^ ]+) @\d+>")
_HLO_INSTR_RE = re.compile(r"^\s+(?:ROOT\s+)?%([^ ]+) = ")


def _alloc_kind(desc: str) -> str:
    if "parameter" in desc:
        return "parameter"
    if "constant" in desc:
        return "constant"
    if "temp" in desc:
        return "temp"
    if "output" in desc or "live-out" in desc:
        return "output"
    return "temp"


def parse_buffer_assignment(text: str) -> List[Alloc]:
    """Allocations with sizes, kinds, and their logical values."""
    allocs: List[Alloc] = []
    cur: Optional[Alloc] = None
    for line in text.splitlines():
        m = _ALLOC_RE.match(line)
        if m:
            cur = Alloc(index=int(m.group(1)), size=int(m.group(2)),
                        kind=_alloc_kind(m.group(3)), values=[])
            allocs.append(cur)
            continue
        if cur is not None:
            mv = _VALUE_RE.match(line)
            if mv:
                cur.values.append(mv.group(1))
            elif line and not line.startswith(" "):
                cur = None  # left the allocation block
    return allocs


def parse_uses(text: str) -> Dict[str, List[str]]:
    """'Used values' section: value instruction name -> using instructions."""
    uses: Dict[str, List[str]] = {}
    cur: Optional[str] = None
    mode = None
    for line in text.splitlines():
        m = _USED_VALUE_RE.match(line)
        if m:
            cur = m.group(1)
            uses[cur] = []
            mode = None
            continue
        s = line.strip()
        if s == "uses:":
            mode = "uses"
            continue
        if s in ("positions:",) or s.startswith("from instruction"):
            mode = None
            continue
        if cur is not None and mode == "uses" and s:
            # e.g. "dot, operand 0" / "fusion, operand 1"
            uses[cur].append(s.split(",")[0].strip())
    return uses


def parse_hlo_order(text: str) -> Dict[str, int]:
    """Instruction name -> position in the (scheduled) HLO text."""
    order: Dict[str, int] = {}
    i = 0
    for line in text.splitlines():
        m = _HLO_INSTR_RE.match(line)
        if m:
            name = m.group(1)
            if name not in order:
                order[name] = i
                i += 1
    return order


def assign_lifetimes(allocs: List[Alloc], uses: Dict[str, List[str]],
                     order: Dict[str, int]) -> int:
    """Fill start/end from instruction order; returns program length."""
    n = max(order.values(), default=0) + 1
    for a in allocs:
        if a.kind == "parameter":
            a.start, a.end = 0, n - 1
            continue
        starts, ends = [], []
        for v in a.values:
            if v in order:
                starts.append(order[v])
                ends.append(order[v])
            for u in uses.get(v, []):
                if u in order:
                    ends.append(order[u])
        a.start = min(starts) if starts else 0
        a.end = max(ends) if ends else n - 1
    return n


def find_dump_files(path: str, module: Optional[str] = None
                    ) -> Tuple[str, Optional[str]]:
    """(buffer_assignment_path, hlo_path) — largest matching module wins."""
    if os.path.isfile(path):
        hlo = path.replace("-buffer-assignment", "")
        return path, hlo if os.path.isfile(hlo) and hlo != path else None
    cands = [f for f in os.listdir(path) if "buffer-assignment" in f]
    if module:
        cands = [f for f in cands if module in f]
    if not cands:
        raise FileNotFoundError(
            f"no *buffer-assignment* file under {path!r}"
            + (f" matching {module!r}" if module else "")
            + " — run with XLA_FLAGS='--xla_dump_to=DIR "
              "--xla_dump_hlo_as_text'")
    best = max(cands, key=lambda f: os.path.getsize(os.path.join(path, f)))
    hlo = os.path.join(path, best.replace("-buffer-assignment", ""))
    return (os.path.join(path, best),
            hlo if os.path.isfile(hlo) else None)


def summarize(allocs: List[Alloc]) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for a in allocs:
        out[a.kind] = out.get(a.kind, 0) + a.size
    out["total"] = sum(a.size for a in allocs)
    return out


def live_curve(allocs: List[Alloc], n: int) -> List[int]:
    """Live bytes at each instruction position (temp/output only)."""
    delta = [0] * (n + 1)
    for a in allocs:
        if a.kind == "parameter" or a.start is None:
            continue
        delta[a.start] += a.size
        delta[min(a.end, n - 1) + 1] -= a.size
    curve, cur = [], 0
    for d in delta[:n]:
        cur += d
        curve.append(cur)
    return curve


def render(allocs: List[Alloc], n: int, out_path: str,
           title: str = "XLA buffer lifetimes") -> None:
    """Reference-style plot: lifetime rectangles + live-bytes curve."""
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    from matplotlib.patches import Rectangle

    colors = {"parameter": "#9aa7b5", "constant": "#c4b391",
              "temp": "#4f81bd", "output": "#5aa469"}
    fig, (ax, ax2) = plt.subplots(
        2, 1, figsize=(12, 8), sharex=True,
        gridspec_kw={"height_ratios": [3, 1]})

    base = sum(a.size for a in allocs if a.kind == "parameter")
    y = base
    shown = [a for a in allocs if a.kind != "parameter" and a.size > 0]
    shown.sort(key=lambda a: (a.start or 0, -(a.size)))
    for a in shown:
        w = max((a.end or n - 1) - (a.start or 0), 1)
        ax.add_patch(Rectangle(((a.start or 0), y), w, a.size,
                               facecolor=colors.get(a.kind, "#999999"),
                               edgecolor="white", linewidth=0.3,
                               alpha=0.85))
        y += a.size
    if base:
        ax.add_patch(Rectangle((0, 0), n, base, facecolor=colors["parameter"],
                               alpha=0.5, edgecolor="none"))
        ax.text(n * 0.01, base / 2, f"parameters {base/2**20:.1f} MiB",
                va="center", fontsize=8)
    ax.set_xlim(0, n)
    ax.set_ylim(0, y * 1.05 if y else 1)
    ax.set_ylabel("bytes (stacked by allocation)")
    ax.set_title(title)

    curve = live_curve(allocs, n)
    peak = max(curve) if curve else 0
    peak_at = curve.index(peak) if curve else 0
    ax2.fill_between(range(n), curve, step="post", alpha=0.6,
                     color="#4f81bd")
    ax2.annotate(f"peak temp {peak/2**20:.1f} MiB",
                 xy=(peak_at, peak), xytext=(min(peak_at + n * 0.05, n * 0.7),
                                             peak),
                 arrowprops=dict(arrowstyle="->"), fontsize=9)
    ax2.set_xlabel("instruction (scheduled order)")
    ax2.set_ylabel("live temp bytes")
    fig.tight_layout()
    fig.savefig(out_path, dpi=120)
    plt.close(fig)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Plot per-buffer lifetimes from an XLA dump "
                    "(reference tools/plot_mem.py equivalent)")
    ap.add_argument("dump", help="dump dir or *buffer-assignment.txt file")
    ap.add_argument("-o", "--out", default="mem.png")
    ap.add_argument("--module", default=None,
                    help="substring selecting the module (default: largest)")
    args = ap.parse_args(argv)

    ba_path, hlo_path = find_dump_files(args.dump, args.module)
    text = open(ba_path).read()
    allocs = parse_buffer_assignment(text)
    uses = parse_uses(text)
    order = parse_hlo_order(open(hlo_path).read()) if hlo_path else {}
    n = assign_lifetimes(allocs, uses, order) if order else 1
    s = summarize(allocs)
    for k in ("parameter", "temp", "output", "constant"):
        if k in s:
            print(f"{k:>10}: {s[k]/2**20:10.2f} MiB")
    print(f"{'total':>10}: {s['total']/2**20:10.2f} MiB  "
          f"({len(allocs)} allocations; module {os.path.basename(ba_path)})")
    render(allocs, max(n, 1), args.out,
           title=os.path.basename(ba_path).split(".")[1])
    print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
