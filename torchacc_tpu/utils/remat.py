"""Rematerialisation (gradient-checkpointing) policies.

Reference: ``MemoryConfig`` gc/gc_cls/gc_cnt (torchacc/config.py:57-88)
driving ``checkpoint_module`` wraps (utils/checkpoint.py:67-81) plus the
CUDA-stream CPU offloader (utils/cpu_offload.py).  On TPU both collapse
into :func:`jax.checkpoint` policies — including host-offload policies
that park residuals in pinned host memory, the XLA-native replacement
for the reference's d2h/h2d stream machinery.
"""

from __future__ import annotations

from typing import Optional

import jax


def _host_memory_available() -> bool:
    """Whether the backend exposes a pinned_host memory space (the
    memories-API target for residual offload).  TPU always does; modern
    XLA:CPU does too, which lets the emulated mesh exercise the REAL
    offload path instead of a fallback."""
    try:
        # local_devices: jax.devices()[0] is non-addressable on
        # processes other than 0 in a multi-process run, and the
        # processes must agree on the answer
        mems = jax.local_devices()[0].addressable_memories()
        return any(m.kind == "pinned_host" for m in mems)
    except Exception:
        return False


def offload_is_live(memory_cfg) -> bool:
    """Single source of truth for 'does this config actually host-offload
    residuals on this backend' — the trainer keys its jit out_shardings
    workaround off this, and it must agree with remat_policy's
    capability fallback."""
    wants = bool(getattr(memory_cfg, "offload_activations", False)
                 or (getattr(memory_cfg, "gc", False)
                     and getattr(memory_cfg, "gc_policy", "")
                     == "offload_dots"))
    return wants and _host_memory_available()


def remat_policy(name: str = "nothing") -> Optional[object]:
    """Map a policy name to a jax.checkpoint policy.

    'nothing'                  save nothing (recompute all)   — max memory win
    'dots'                     save matmul outputs            — cheap recompute
    'dots_with_no_batch_dims'  save contraction-only matmuls  — maxtext default
    'save_attn'                save q/k/v + flash residuals (o, lse) +
                               block outputs; recompute ffn-width tensors —
                               the best memory/flops trade measured on v5e
    'save_attn_mlp'            'save_attn' + the ffn-width gate/up
                               projections; recompute is elementwise-only
                               (near-no-remat speed at ~half its memory)
    'offload_dots'             offload matmul outputs to host — HBM relief with
                               no recompute (reference cpu_offload.py analogue)
    """
    cp = jax.checkpoint_policies
    if name == "nothing":
        return cp.nothing_saveable
    if name == "dots":
        return cp.checkpoint_dots
    if name == "dots_with_no_batch_dims":
        return cp.checkpoint_dots_with_no_batch_dims
    if name == "save_attn":
        return cp.save_only_these_names(
            "qkv_proj", "attn_ctx", "attn_lse", "attn_out", "mlp_out")
    if name == "save_attn_mlp":
        return cp.save_only_these_names(
            "qkv_proj", "attn_ctx", "attn_lse", "attn_out", "mlp_out",
            "mlp_gate_up")
    if name == "offload_dots":
        if not _host_memory_available():
            # backends without a pinned_host memory space cannot place
            # the offloaded residuals
            from torchacc_tpu.utils.logger import logger
            logger.warning("host offload ('offload_dots') requires a "
                           "backend with pinned_host memory; falling "
                           "back to 'dots'")
            return cp.checkpoint_dots
        # names annotated in models/transformer.py Block via checkpoint_name
        return cp.save_and_offload_only_these_names(
            names_which_can_be_saved=[],
            names_which_can_be_offloaded=["attn_out", "mlp_out"],
            offload_src="device", offload_dst="pinned_host",
        )
    raise ValueError(f"unknown remat policy {name!r}")
