from torchacc_tpu.utils.logger import logger

__all__ = ["logger"]
