"""Framework logger.

TPU-native equivalent of the reference's logger subsystem
(torchacc/utils/logger.py:1-15): a single named logger whose level is
controlled by the ``ACC_LOG_LEVEL`` environment variable.
"""

import logging
import os

_LEVELS = {
    "DEBUG": logging.DEBUG,
    "INFO": logging.INFO,
    "WARNING": logging.WARNING,
    "ERROR": logging.ERROR,
    "CRITICAL": logging.CRITICAL,
}


def _build_logger() -> logging.Logger:
    logger = logging.getLogger("TorchAccTPU")
    if not logger.handlers:
        handler = logging.StreamHandler()
        handler.setFormatter(
            logging.Formatter(
                "[%(asctime)s %(name)s %(levelname)s] %(message)s",
                datefmt="%H:%M:%S",
            )
        )
        logger.addHandler(handler)
        logger.propagate = False
    level = os.environ.get("ACC_LOG_LEVEL", "WARNING").upper()
    logger.setLevel(_LEVELS.get(level, logging.WARNING))
    return logger


logger = _build_logger()
