"""Streaming packed-dataset helper: documents -> fixed-shape batches.

Ties the native packer (packing.py) into a batch stream: accumulate
documents, pack into [rows, seq_len] with segment ids/positions, emit
fixed-size batches.  Together with AsyncLoader this is the end-to-end
input pipeline (reference: BucketingParallelLoader + its padding
discipline, core/async_loader.py — packing beats bucketing on both
padding waste and compile count: exactly ONE shape ever reaches XLA).

Durable pipeline state (docs/resilience.md "Elastic resume"): the packed
row stream is a *deterministic* function of (documents, shuffle
permutation, seq_len, buffer_docs), so the whole mid-epoch position is
captured by a handful of integers — ``state_dict()`` /
``load_state_dict()`` make resume O(1) for seekable (Sequence) sources:
seek to the packing group containing the next undelivered row, re-pack
that ONE group, and continue.  Non-seekable sources fall back to
replaying (and discarding) the consumed prefix, loudly
(``resume_replayed_batches`` counter).

Elastic data sharding: ``batch_rows`` is the GLOBAL batch; with
``num_shards``/``shard_index`` set, every host computes the identical
global row stream and emits only its ``batch_rows / num_shards`` row
slice of each batch.  Because the global stream is world-size
independent, a checkpoint saved at N data-parallel hosts resumes at M
with the same global batches — the shard assignment is just recomputed.
"""

from __future__ import annotations

from bisect import bisect_right
from typing import Any, Dict, Iterable, Iterator, List, Optional

import numpy as np

from torchacc_tpu.data.packing import pack_sequences
from torchacc_tpu.errors import DataLoaderError
from torchacc_tpu.utils.logger import logger

#: state_dict keys that pin the packed stream itself — a mismatch means
#: the saved position indexes a DIFFERENT stream and resume would be
#: silently misaligned.
_GEOMETRY_KEYS = ("seq_len", "buffer_docs", "shuffle_seed")


class PackedDataset:
    """Wrap an iterable of token arrays into packed fixed-shape batches.

    Yields {"input_ids", "segment_ids", "positions"} of shape
    [batch_rows / num_shards, seq_len].  Rows are filled by
    first-fit-decreasing packing over a sliding buffer of
    ``buffer_docs`` documents; short final batches are dropped (static
    shapes) unless ``pad_final``.

    ``shuffle_seed`` (seekable sources only) shuffles document order
    per epoch with a permutation keyed on ``(seed, epoch)`` — iterating
    the dataset again after a completed pass advances the epoch.
    ``num_shards``/``shard_index`` slice each global batch for this
    host (see module docstring).  One live iterator per instance: the
    instance tracks that iterator's position for ``state_dict()``.
    """

    def __init__(
        self,
        documents: Iterable[Any],
        seq_len: int,
        batch_rows: int,
        *,
        buffer_docs: int = 512,
        pad_id: int = 0,
        pad_final: bool = False,
        shuffle_seed: Optional[int] = None,
        num_shards: int = 1,
        shard_index: int = 0,
    ):
        if num_shards < 1 or not (0 <= shard_index < num_shards):
            raise ValueError(
                f"need 0 <= shard_index < num_shards, got "
                f"{shard_index}/{num_shards}")
        if batch_rows % num_shards:
            raise ValueError(
                f"batch_rows {batch_rows} not divisible by num_shards "
                f"{num_shards}")
        self._docs = documents
        self.seq_len = seq_len
        self.batch_rows = batch_rows
        self.buffer_docs = buffer_docs
        self.pad_id = pad_id
        self.pad_final = pad_final
        self.shuffle_seed = shuffle_seed
        self.num_shards = num_shards
        self.shard_index = shard_index
        if shuffle_seed is not None and not self._seekable():
            raise ValueError(
                "shuffle_seed requires a seekable (Sequence) document "
                "source — a plain iterator cannot be permuted")
        # live-iterator position (producer side under AsyncLoader; the
        # loader overrides batches_consumed with its consumer-side count)
        self._epoch = 0
        self._batches_emitted = 0
        # set at epoch end instead of bumping _epoch in place: a live
        # state_dict() between the producer finishing the pass and the
        # consumer draining the prefetched tail must still describe the
        # CURRENT epoch (the consumer's position indexes it)
        self._completed = False
        #: cumulative GLOBAL row count after each packed group — the
        #: seek index that makes resume O(1): one bisect + one group
        #: re-pack instead of replaying every consumed batch
        self._group_cum: List[int] = []
        self._resume: Optional[Dict[str, Any]] = None

    # -- durable state -------------------------------------------------------
    def _seekable(self) -> bool:
        return hasattr(self._docs, "__len__") and hasattr(
            self._docs, "__getitem__")

    def state_dict(self) -> Dict[str, Any]:
        """JSON-serialisable mid-epoch position (see module docstring).
        ``batches_consumed`` counts GLOBAL batches — identical on every
        shard, so the state is world-size independent."""
        return {
            "version": 1,
            "kind": "packed_dataset",
            "epoch": self._epoch,
            "batches_consumed": self._batches_emitted,
            "seq_len": self.seq_len,
            "batch_rows": self.batch_rows,
            "buffer_docs": self.buffer_docs,
            "shuffle_seed": self.shuffle_seed,
            "num_shards": self.num_shards,
            "shard_index": self.shard_index,
            "group_cum_rows": list(self._group_cum),
            "seekable": self._seekable(),
        }

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        """Position the NEXT ``iter()`` at the saved mid-epoch point.

        Geometry keys (seq_len/buffer_docs/shuffle_seed) must match —
        they pin the packed stream, and a silent mismatch would deliver
        wrong batches.  ``batch_rows`` must match as global rows.  A
        *shard* change is fine: that is elastic resume, and the
        assignment is recomputed for this instance's
        ``num_shards``/``shard_index``."""
        for k in _GEOMETRY_KEYS:
            if state.get(k) != getattr(self, k):
                raise DataLoaderError(
                    f"loader-state mismatch: saved {k}={state.get(k)!r} "
                    f"but this dataset has {k}={getattr(self, k)!r} — "
                    "the saved position indexes a different packed "
                    "stream")
        if state.get("batch_rows") != self.batch_rows:
            raise DataLoaderError(
                f"loader-state mismatch: saved global batch_rows="
                f"{state.get('batch_rows')} but this dataset has "
                f"{self.batch_rows} — resume requires equal global batch")
        if (state.get("num_shards"), state.get("shard_index")) != (
                self.num_shards, self.shard_index):
            logger.info(
                f"elastic resume: data-shard assignment recomputed "
                f"(saved shard {state.get('shard_index')}/"
                f"{state.get('num_shards')} -> current "
                f"{self.shard_index}/{self.num_shards})")
        self._resume = dict(state)

    # -- iteration -----------------------------------------------------------
    def _perm(self, epoch: int) -> Optional[np.ndarray]:
        if self.shuffle_seed is None:
            return None
        return np.random.default_rng(
            [int(self.shuffle_seed), int(epoch)]).permutation(
                len(self._docs))  # type: ignore[arg-type]

    def _doc_stream(self, epoch: int, start_group: int) -> Iterator[Any]:
        if self._seekable():
            order = self._perm(epoch)
            if order is None:
                order = np.arange(len(self._docs))  # type: ignore[arg-type]
            for i in order[start_group * self.buffer_docs:]:
                yield self._docs[int(i)]  # type: ignore[index]
        else:
            assert start_group == 0, "non-seekable sources cannot seek"
            yield from self._docs

    def _packed_groups(self, epoch: int,
                       start_group: int) -> Iterator[Dict[str, np.ndarray]]:
        """Pack ``buffer_docs``-sized groups from ``start_group`` on,
        maintaining the cumulative-row seek index."""
        buf: List[np.ndarray] = []
        for doc in self._doc_stream(epoch, start_group):
            buf.append(np.asarray(doc, np.int32).reshape(-1))
            if len(buf) >= self.buffer_docs:
                yield self._emit_group(buf)
                buf = []
        if buf:
            yield self._emit_group(buf)

    def _emit_group(self, buf: List[np.ndarray]) -> Dict[str, np.ndarray]:
        packed = pack_sequences(buf, self.seq_len, pad_id=self.pad_id)
        base = self._group_cum[-1] if self._group_cum else 0
        self._group_cum.append(base + packed["input_ids"].shape[0])
        return packed

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        resume, self._resume = self._resume, None
        start_group, skip_rows, start_batch = 0, 0, 0
        if resume is not None:
            epoch = int(resume.get("epoch", 0))
            start_batch = int(resume.get("batches_consumed", 0))
            r0 = start_batch * self.batch_rows
            cum = [int(c) for c in resume.get("group_cum_rows") or []]
            if self._seekable():
                # O(1) seek: bisect to the group holding row r0, re-pack
                # only from there, discard the rows already delivered
                start_group = bisect_right(cum, r0)
                base = cum[start_group - 1] if start_group else 0
                skip_rows = r0 - base
                self._group_cum = cum[:start_group]
            else:
                from torchacc_tpu.utils.metrics import counters
                counters.inc("resume_replayed_batches", start_batch)
                logger.warning(
                    f"resume: document source is not seekable — replaying "
                    f"{start_batch} consumed batches to realign the "
                    "stream (wrap a Sequence source for O(1) resume)")
                skip_rows = r0
                self._group_cum = []
            self._epoch = epoch
        else:
            if self._completed:
                # the previous pass finished: this iteration is a new
                # epoch (fresh shuffle permutation when seeded)
                self._epoch += 1
            epoch = self._epoch
            self._group_cum = []
        self._completed = False
        self._batches_emitted = start_batch
        yield from self._iterate(epoch, start_group, skip_rows, start_batch)

    def _iterate(self, epoch: int, start_group: int, skip_rows: int,
                 start_batch: int) -> Iterator[Dict[str, np.ndarray]]:
        R = self.batch_rows
        per_shard = R // self.num_shards
        lo = self.shard_index * per_shard
        pending: List[Dict[str, np.ndarray]] = []
        n_pending = 0

        def emit(pad: bool = False):
            nonlocal pending, n_pending
            cat = {k: np.concatenate([p[k] for p in pending])
                   for k in pending[0]}
            take = min(R, cat["input_ids"].shape[0])
            batch = {k: v[:take] for k, v in cat.items()}
            if pad and take < R:
                extra = R - take
                batch = {
                    "input_ids": np.concatenate(
                        [batch["input_ids"],
                         np.full((extra, self.seq_len), self.pad_id,
                                 np.int32)]),
                    "segment_ids": np.concatenate(
                        [batch["segment_ids"],
                         np.full((extra, self.seq_len), -1, np.int32)]),
                    "positions": np.concatenate(
                        [batch["positions"],
                         np.zeros((extra, self.seq_len), np.int32)]),
                }
            rest = {k: v[take:] for k, v in cat.items()}
            n_rest = rest["input_ids"].shape[0]
            pending = [rest] if n_rest else []
            n_pending = n_rest
            self._batches_emitted += 1
            return {k: v[lo:lo + per_shard] for k, v in batch.items()}

        for packed in self._packed_groups(epoch, start_group):
            if skip_rows:
                rows = packed["input_ids"].shape[0]
                take = min(skip_rows, rows)
                skip_rows -= take
                if take == rows:
                    continue
                packed = {k: v[take:] for k, v in packed.items()}
            pending.append(packed)
            n_pending += packed["input_ids"].shape[0]
            while n_pending >= R:
                yield emit()
        if n_pending and self.pad_final:
            yield emit(pad=True)
        # a full pass completed: the NEXT plain iteration advances the
        # epoch — deferred (not bumped here) so a state_dict() taken
        # while the consumer drains the prefetched tail still labels
        # the position with the epoch it belongs to
        self._completed = True
