"""Streaming packed-dataset helper: documents -> fixed-shape batches.

Ties the native packer (packing.py) into a batch stream: accumulate
documents, pack into [rows, seq_len] with segment ids/positions, emit
fixed-size batches.  Together with AsyncLoader this is the end-to-end
input pipeline (reference: BucketingParallelLoader + its padding
discipline, core/async_loader.py — packing beats bucketing on both
padding waste and compile count: exactly ONE shape ever reaches XLA).
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, Iterator, List, Optional

import numpy as np

from torchacc_tpu.data.packing import pack_sequences


class PackedDataset:
    """Wrap an iterable of token arrays into packed fixed-shape batches.

    Yields {"input_ids", "segment_ids", "positions"} of shape
    [batch_rows, seq_len].  Rows are filled by first-fit-decreasing
    packing over a sliding buffer of ``buffer_docs`` documents; short
    final batches are dropped (static shapes) unless ``pad_final``.
    """

    def __init__(
        self,
        documents: Iterable[Any],
        seq_len: int,
        batch_rows: int,
        *,
        buffer_docs: int = 512,
        pad_id: int = 0,
        pad_final: bool = False,
    ):
        self._docs = documents
        self.seq_len = seq_len
        self.batch_rows = batch_rows
        self.buffer_docs = buffer_docs
        self.pad_id = pad_id
        self.pad_final = pad_final

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        buf: List[np.ndarray] = []
        pending: List[Dict[str, np.ndarray]] = []
        n_pending = 0
        for doc in self._docs:
            buf.append(np.asarray(doc, np.int32).reshape(-1))
            if len(buf) >= self.buffer_docs:
                packed = pack_sequences(buf, self.seq_len, pad_id=self.pad_id)
                buf = []
                pending.append(packed)
                n_pending += packed["input_ids"].shape[0]
            while n_pending >= self.batch_rows:
                batch, pending, n_pending = self._take(pending)
                yield batch
        if buf:
            packed = pack_sequences(buf, self.seq_len, pad_id=self.pad_id)
            pending.append(packed)
            n_pending += packed["input_ids"].shape[0]
        while n_pending >= self.batch_rows:
            batch, pending, n_pending = self._take(pending)
            yield batch
        if n_pending and self.pad_final:
            batch, pending, n_pending = self._take(pending, pad=True)
            yield batch

    def _take(self, pending, pad: bool = False):
        cat = {k: np.concatenate([p[k] for p in pending])
               for k in pending[0]}
        n = cat["input_ids"].shape[0]
        take = min(self.batch_rows, n)
        batch = {k: v[:take] for k, v in cat.items()}
        if pad and take < self.batch_rows:
            extra = self.batch_rows - take
            batch = {
                "input_ids": np.concatenate(
                    [batch["input_ids"],
                     np.full((extra, self.seq_len), self.pad_id, np.int32)]),
                "segment_ids": np.concatenate(
                    [batch["segment_ids"],
                     np.full((extra, self.seq_len), -1, np.int32)]),
                "positions": np.concatenate(
                    [batch["positions"],
                     np.zeros((extra, self.seq_len), np.int32)]),
            }
        rest = {k: v[take:] for k, v in cat.items()}
        n_rest = rest["input_ids"].shape[0]
        return batch, ([rest] if n_rest else []), n_rest
