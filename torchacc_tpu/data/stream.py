"""Streaming dataset over object-store shards: the ordering half of the
fault-tolerant data plane (``data/store.py`` is the storage half).

:class:`StreamingDataset` lists shards from each source's manifest and
delivers a packed batch stream with the same durable-state contract as
:class:`~torchacc_tpu.data.dataset.PackedDataset` — which it extends, so
the group packing, global-batch sharding, and O(1) bisect resume are the
SAME code path local Sequence training uses.  What this layer adds:

- **Deterministic, world-size-independent order.**  The global document
  stream is a pure function of ``(shuffle_seed, epoch, manifests,
  weights + reweight history, quarantined set, shed history)``: shard
  order per source is a permutation keyed ``(seed, epoch, source)``,
  document order within a shard keyed ``(seed, epoch, source, shard)``
  (the shuffle window IS the shard — bounded memory at any corpus
  size), and sources interleave by smooth weighted round-robin — a
  deterministic deficit scheduler, no RNG in the mixture at all.  Every
  host computes the identical global stream and slices its rows, so a
  checkpoint saved at N hosts resumes at M bitwise (elastic resume).
- **Mixture weights with live re-weighting.**  ``set_weights`` takes
  effect at the next document and is recorded as ``(epoch, doc_index,
  weights)`` in ``state_dict()`` — resume replays the recipe change at
  the same point, so a mid-run recipe shift is as resumable as the
  original recipe.
- **Quarantine instead of crash.**  A shard whose payload stays corrupt
  (checksum/decode) or unfetchable across the retry budget is
  quarantined — written to the quarantine manifest, counted
  (``shards_quarantined``), and skipped.  Shards are resolved eagerly
  when the cursor CROSSES into them (not lazily when a document is
  drawn), which makes quarantine-at-encounter bitwise-equivalent to a
  run constructed with those shards pre-excluded: the interleave never
  observes the bad shard at all.
- **Source shedding.**  Each source feeds a circuit breaker; on the
  open edge the source is shed from the mixture (remaining weights
  renormalize implicitly, ``data_sources_shed``), a typed
  :class:`~torchacc_tpu.errors.DataSourceError` is recorded — and
  raised only when no source remains.  Sheds are recorded with their
  ``(epoch, doc_index)`` so a post-shed checkpoint resumes bitwise: a
  source shed mid-epoch stays in the replayed walk until its recorded
  index (excluding it outright would change the interleave of every
  earlier document), and its manifest doc counts ride ``state_dict()``
  so the replay needs no GET against the — possibly still dead — store.
- **Resume without refetching.**  ``load_state_dict`` seeks by
  replaying the interleave ARITHMETICALLY — manifest document counts
  only, no shard GETs — up to the saved position, then fetches just
  each live source's current shard.  Resume cost is O(delivered docs)
  integer work + one GET per source, independent of corpus size.

Under :class:`~torchacc_tpu.data.async_loader.AsyncLoader` the producer
thread owns all fetching; the loader's prefetch queue is the starvation
buffer and stalls surface in the ``data_wait`` goodput bucket (the data
SLO), not as consumer hangs: retry backoffs raise :attr:`in_retry`,
which the loader's deadline watchdog treats as "slow, not stuck".
"""

from __future__ import annotations

import json
import os
import time
import zlib

try:
    import fcntl
except ImportError:  # pragma: no cover - non-POSIX
    fcntl = None  # type: ignore[assignment]
from typing import (Any, Callable, Dict, Iterable, Iterator, List, Optional,
                    Sequence, Tuple)

import numpy as np

from torchacc_tpu.data.dataset import PackedDataset
from torchacc_tpu.data.store import ShardStore, StoreClient
from torchacc_tpu.errors import (DataLoaderError, DataSourceError,
                                 ShardCorruptionError)
from torchacc_tpu.utils.logger import logger
from torchacc_tpu.utils.metrics import counters
from torchacc_tpu.utils.retry import RetryPolicy

QUARANTINE_FILE = "data_quarantine.json"


class StreamingSource:
    """One named corpus: a :class:`ShardStore` plus its mixture weight.

    ``tokenize`` is required when the store holds ``text`` shards
    (online tokenization happens in the fetch worker, never on the
    consumer thread)."""

    def __init__(self, name: str, store: ShardStore, *,
                 weight: float = 1.0,
                 tokenize: Optional[Callable[[str], Any]] = None):
        if not name or "/" in name:
            raise ValueError(f"illegal source name {name!r}")
        if not weight > 0:
            raise ValueError(f"source {name!r}: weight must be > 0")
        self.name = str(name)
        self.store = store
        self.weight = float(weight)
        self.tokenize = tokenize


class _Run:
    """Per-source walk state for one epoch: the shard cursor, the SWRR
    deficit counter, and (fetch mode only) the resolved current shard."""

    __slots__ = ("name", "entries", "order", "k", "j", "cw", "ew",
                 "cur_docs")

    def __init__(self, name: str, entries: List[Dict[str, Any]],
                 order: np.ndarray, ew: float):
        self.name = name
        self.entries = entries          # manifest order
        self.order = order              # epoch shard permutation
        self.k = 0                      # position in ``order``
        self.j = 0                      # docs delivered from current shard
        self.cw = 0.0                   # SWRR current (deficit) weight
        self.ew = ew                    # SWRR effective weight
        self.cur_docs: Optional[List[np.ndarray]] = None

    def entry(self) -> Dict[str, Any]:
        return self.entries[int(self.order[self.k])]


class StreamingDataset(PackedDataset):
    """Packed batch stream over weighted object-store sources.

    Yields the same ``{"input_ids", "segment_ids", "positions"}``
    batches as :class:`PackedDataset` (shape ``[batch_rows/num_shards,
    seq_len]``) and speaks the same ``state_dict`` protocol — plus the
    mixture/quarantine/shed state described in the module docstring.

    ``quarantined`` pre-excludes ``"source/shard"`` keys (the format the
    quarantine manifest records); ``quarantine_dir`` persists the
    manifest across restarts.  One live iterator per instance, exactly
    as the parent.
    """

    def __init__(
        self,
        sources: Sequence[StreamingSource],
        seq_len: int,
        batch_rows: int,
        *,
        buffer_docs: int = 512,
        pad_id: int = 0,
        pad_final: bool = False,
        shuffle_seed: int = 0,
        num_shards: int = 1,
        shard_index: int = 0,
        quarantined: Iterable[str] = (),
        quarantine_dir: Optional[str] = None,
        failure_budget: int = 3,
        breaker_cooldown_s: float = 30.0,
        retry_policy: Optional[RetryPolicy] = None,
        sleep: Callable[[float], None] = time.sleep,
    ):
        if not sources:
            raise ValueError("need at least one StreamingSource")
        names = [s.name for s in sources]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate source names: {names}")
        super().__init__(
            (), seq_len, batch_rows, buffer_docs=buffer_docs,
            pad_id=pad_id, pad_final=pad_final, shuffle_seed=shuffle_seed,
            num_shards=num_shards, shard_index=shard_index)
        self.sources = {s.name: s for s in sources}
        self._weights0 = {s.name: s.weight for s in sources}
        self._reweights: List[Tuple[int, int, Dict[str, float]]] = []
        self._sheds: List[Tuple[int, int, str]] = []
        # per-source manifest doc counts in manifest order, refreshed
        # each epoch and persisted in state_dict(): resume replays a
        # source shed mid-epoch from these counts alone, even when its
        # manifest is no longer reachable
        self._manifest_docs: Dict[str, List[Tuple[str, int]]] = {}
        self.quarantined = set(quarantined)
        self.quarantine_dir = quarantine_dir
        self.source_errors: List[DataSourceError] = []
        self._heartbeat: Optional[Callable[[], None]] = None
        self._clients = {
            s.name: StoreClient(
                s.store, source=s.name, policy=retry_policy,
                failure_budget=failure_budget,
                breaker_cooldown_s=breaker_cooldown_s,
                tokenize=s.tokenize, sleep=sleep, on_wait=self._on_wait)
            for s in sources}
        # live walk position (producer side) — what set_weights stamps
        self._walk_epoch = 0
        self._walk_idx = 0
        if self.quarantine_dir:
            self._load_quarantine_file()

    # -- plumbing the loader reads --------------------------------------------

    @property
    def in_retry(self) -> bool:
        """True while any source's fetch is inside a retry backoff —
        the loader's stall watchdog defers ``HangError`` while this
        holds (slow-but-retrying is ``data_wait``, not a hang)."""
        return any(c.in_retry for c in self._clients.values())

    def set_stall_heartbeat(self, fn: Optional[Callable[[], None]]) -> None:
        """Called before every retry backoff sleep — wire the trainer's
        watchdog ``beat`` here so long backoffs never look like hangs."""
        self._heartbeat = fn

    def _on_wait(self, seconds: float) -> None:
        hb = self._heartbeat
        if hb is not None:
            try:
                hb()
            except Exception:
                pass

    # -- mixture recipe -------------------------------------------------------

    def set_weights(self, weights: Dict[str, float]) -> None:
        """Re-weight the mixture, effective at the NEXT document.

        Partial dicts re-weight just the named sources.  The change is
        recorded as ``(epoch, doc_index, weights)`` in ``state_dict()``
        so resume replays it at the identical point."""
        unknown = set(weights) - set(self.sources)
        if unknown:
            raise ValueError(f"unknown sources in set_weights: "
                             f"{sorted(unknown)}")
        for name, w in weights.items():
            if not float(w) >= 0:
                raise ValueError(f"weight for {name!r} must be >= 0")
        self._reweights.append(
            (self._walk_epoch, self._walk_idx,
             {k: float(v) for k, v in weights.items()}))
        logger.info(f"data mixture re-weighted at epoch "
                    f"{self._walk_epoch} doc {self._walk_idx}: {weights}")

    # -- durable state --------------------------------------------------------

    def state_dict(self) -> Dict[str, Any]:
        d = super().state_dict()
        d.update({
            "kind": "streaming_dataset",
            "sources": sorted(self.sources),
            "weights": dict(self._weights0),
            "reweights": [[e, i, dict(w)] for e, i, w in self._reweights],
            "sheds": [[e, i, n] for e, i, n in self._sheds],
            "quarantined": sorted(self.quarantined),
            "manifest_docs": {n: [[s, d] for s, d in v]
                              for n, v in self._manifest_docs.items()},
        })
        return d

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        if state.get("kind") not in ("streaming_dataset", None):
            raise DataLoaderError(
                f"loader-state mismatch: saved kind={state.get('kind')!r} "
                "is not a streaming_dataset state")
        saved = state.get("sources")
        if saved is not None and list(saved) != sorted(self.sources):
            raise DataLoaderError(
                f"loader-state mismatch: saved sources {saved} != "
                f"{sorted(self.sources)} — the saved position indexes a "
                "different mixture")
        w0 = state.get("weights")
        if w0 is not None and {k: float(v) for k, v in w0.items()} != \
                self._weights0:
            raise DataLoaderError(
                f"loader-state mismatch: saved base weights {w0} != "
                f"{self._weights0} — change recipes via set_weights(), "
                "which is recorded and resumable")
        self._reweights = [
            (int(e), int(i), {k: float(v) for k, v in w.items()})
            for e, i, w in state.get("reweights") or []]
        self._sheds = [(int(e), int(i), str(n))
                       for e, i, n in state.get("sheds") or []]
        self.quarantined |= set(state.get("quarantined") or [])
        self._manifest_docs = {
            str(n): [(str(s), int(d)) for s, d in v]
            for n, v in (state.get("manifest_docs") or {}).items()}
        super().load_state_dict(state)

    # -- quarantine -----------------------------------------------------------

    @staticmethod
    def _qkey(source: str, shard: str) -> str:
        return f"{source}/{shard}"

    def _load_quarantine_file(self) -> None:
        path = os.path.join(self.quarantine_dir, QUARANTINE_FILE)
        try:
            with open(path) as f:
                doc = json.load(f)
            self.quarantined |= {
                self._qkey(r["source"], r["shard"])
                for r in doc.get("shards", [])}
        except FileNotFoundError:
            pass
        except Exception as e:
            logger.warning(f"quarantine manifest {path} unreadable "
                           f"({e!r}); starting from constructor set")

    def _record_quarantine(self, source: str, shard: str,
                           reason: str) -> None:
        key = self._qkey(source, shard)
        if key in self.quarantined:
            return
        self.quarantined.add(key)
        counters.inc("shards_quarantined")
        logger.warning(f"quarantined shard {key}: {reason}")
        if not self.quarantine_dir:
            return
        os.makedirs(self.quarantine_dir, exist_ok=True)
        path = os.path.join(self.quarantine_dir, QUARANTINE_FILE)
        # quarantine_dir may be shared (several hosts on one filesystem,
        # or a loader thread beside a supervisor): the read-modify-write
        # runs under an exclusive flock so concurrent writers never lose
        # each other's records
        with open(path + ".lock", "w") as lockf:
            if fcntl is not None:
                fcntl.flock(lockf, fcntl.LOCK_EX)
            try:
                with open(path) as f:
                    doc = json.load(f)
            except Exception:
                doc = {"version": 1, "shards": []}
            doc["shards"].append({"source": source, "shard": shard,
                                  "reason": reason,
                                  "epoch": self._walk_epoch})
            tmp = f"{path}.{os.getpid()}.tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1)
            os.replace(tmp, path)

    # -- the deterministic walk -----------------------------------------------

    def _seekable(self) -> bool:
        # manifests give doc counts, so resume seeks arithmetically —
        # always, regardless of the (unused) parent ``documents`` arg
        return True

    def _rng(self, epoch: int, source: str,
             shard: Optional[int] = None) -> np.random.Generator:
        key = [int(self.shuffle_seed or 0), int(epoch),
               zlib.crc32(source.encode())]
        if shard is not None:
            key.append(int(shard))
        return np.random.default_rng(key)

    def _epoch_runs(self, epoch: int) -> Dict[str, _Run]:
        """Fresh per-source walk state at the top of ``epoch``: shard
        permutation over the FULL manifest (quarantined shards are
        skipped at the cursor, keeping the permutation domain stable as
        the quarantine set grows) and the mixture weights with every
        prior-epoch reweight already applied.

        A source shed BEFORE this epoch's first draw (an earlier epoch,
        or doc 0 of this one) is permanent and excluded outright.  One
        shed LATER stays in the walk so the replay pointer
        (``_doc_stream``) removes it at its recorded doc index —
        excluding it here would change the interleave of every earlier
        document and break bitwise resume."""
        ew = dict(self._weights0)
        for e, _i, w in self._reweights:
            if e < epoch:
                ew.update(w)
        shed_before = {n for e, i, n in self._sheds
                       if e < epoch or (e == epoch and i == 0)}
        shed_later = {n: (e, i) for e, i, n in self._sheds
                      if n not in shed_before}
        runs: Dict[str, _Run] = {}
        for name in sorted(self.sources):
            if name in shed_before:
                continue            # a shed is permanent: don't re-probe
            try:
                entries = list(
                    self._clients[name].manifest_entries().values())
            except DataLoaderError as err:
                if name not in shed_later:
                    # the source is down before its first draw (manifest
                    # unreachable through the retry budget) — shed here
                    self._record_shed(name)
                    continue
                # scheduled to shed mid-epoch: the walk only needs its
                # doc counts up to the recorded index, and those persist
                # in state_dict exactly so a now-dead source can still
                # be replayed arithmetically
                saved = self._manifest_docs.get(name)
                if saved is None:
                    raise DataLoaderError(
                        f"source {name!r} was shed mid-epoch at "
                        f"{shed_later[name]} but its manifest is "
                        "unreachable and no saved doc counts exist — "
                        "cannot replay the pre-shed interleave") from err
                entries = [{"name": s, "docs": int(d)} for s, d in saved]
            self._manifest_docs[name] = [
                (str(e["name"]), int(e["docs"])) for e in entries]
            if self.shuffle_seed is None:
                order = np.arange(len(entries))
            else:
                order = self._rng(epoch, name).permutation(len(entries))
            runs[name] = _Run(name, entries, order, float(ew[name]))
        return runs

    def _skip_quarantined(self, run: _Run) -> None:
        while run.k < len(run.order):
            e = run.entry()
            if (self._qkey(run.name, e["name"]) not in self.quarantined
                    and int(e["docs"]) > 0):
                return
            run.k += 1
            run.j = 0

    def _available(self, run: _Run) -> bool:
        self._skip_quarantined(run)
        return run.k < len(run.order)

    def _resolve(self, run: _Run) -> bool:
        """Fetch-mode invariant: make ``run``'s current shard resident
        (documents decoded, in permuted order).  Quarantines past bad
        shards; returns False when the source is exhausted.  A breaker
        open-edge (the source itself is down) raises ``_Shed``."""
        client = self._clients[run.name]
        while True:
            if not self._available(run):
                return False
            if run.cur_docs is not None:
                return True
            e = run.entry()
            name = e["name"]
            try:
                docs = client.get_docs(name)
            except (ShardCorruptionError, OSError) as err:
                # data damage / transport failure only — a config error
                # (DataLoaderError: missing tokenizer, shard absent from
                # the manifest) propagates instead of masquerading as
                # shard loss in the quarantine manifest
                reason = (getattr(err, "reason", None)
                          or f"fetch failed: {err}")
                self._record_quarantine(run.name, name, str(reason))
                if client.record_outcome(False):
                    raise _Shed(run.name,
                                client.breaker.failures) from err
                run.k += 1
                run.j = 0
                continue
            client.record_outcome(True)
            if len(docs) != int(e["docs"]):
                self._record_quarantine(
                    run.name, name,
                    f"manifest says {e['docs']} docs, shard decodes to "
                    f"{len(docs)}")
                run.k += 1
                run.j = 0
                continue
            perm = (np.arange(len(docs)) if self.shuffle_seed is None
                    else self._rng(self._walk_epoch, run.name,
                                   int(run.order[run.k]))
                    .permutation(len(docs)))
            run.cur_docs = [docs[int(p)] for p in perm]
            return True

    def _record_shed(self, name: str) -> None:
        """Permanently drop ``name`` from the mixture: recorded with its
        ``(epoch, doc_index)`` so resume replays the removal at the same
        draw, counted, and kept as a typed error for the operator."""
        self._sheds.append((self._walk_epoch, self._walk_idx, name))
        counters.inc("data_sources_shed")
        err = DataSourceError(
            f"source {name!r} shed at epoch {self._walk_epoch} doc "
            f"{self._walk_idx}: failure budget exhausted (breaker "
            "open); continuing on re-normalized surviving sources",
            source=name,
            consecutive=self._clients[name].breaker.failures)
        self.source_errors.append(err)
        logger.error(str(err))

    def _shed_source(self, live: Dict[str, _Run], name: str) -> None:
        live.pop(name, None)
        if any(n == name for _e, _i, n in self._sheds):
            # a recorded shed for this source is still pending (we are
            # replaying its pre-shed window) and the store failed EARLIER
            # than in the original run: the documents it delivered before
            # the recorded shed cannot be refetched.  Don't record a
            # second shed — the pending record still fires at its index —
            # but say loudly that this replay is no longer bitwise.
            counters.inc("data_replay_shed_early")
            logger.error(
                f"source {name!r} failed during resume replay before its "
                "recorded shed point — pre-shed documents could not be "
                "refetched; the resumed stream may diverge from the "
                "original run")
        else:
            self._record_shed(name)
        if not live:
            raise DataSourceError(
                f"source {name!r} failed and no live source remains — "
                "the data plane is down", source=name)

    def _doc_stream(self, epoch: int, start_group: int) -> Iterator[Any]:
        """The global document stream from document index
        ``start_group * buffer_docs`` on.  The skip prefix is walked
        arithmetically (manifest counts only, zero GETs); delivery then
        proceeds with real fetches under the eager-resolve invariant."""
        skip = start_group * self.buffer_docs
        self._walk_epoch, self._walk_idx = epoch, 0
        runs = self._epoch_runs(epoch)
        live = {n: r for n, r in runs.items() if self._available(r)}
        if not live:
            if self._sheds:
                raise DataSourceError(
                    "every data source shed — the data plane is down",
                    source=self._sheds[-1][2])
            logger.warning("streaming dataset has no deliverable "
                           "documents (all shards empty or quarantined)")
            return
        # pointers over the LIVE lists (set_weights / a breaker shed
        # append mid-iteration; prior-epoch entries were applied at
        # epoch start — sheds excluded from runs, reweights folded into
        # ew — and entries for later epochs must not fire here)
        rw_p = sum(1 for x in self._reweights if x[0] < epoch)
        sh_p = sum(1 for x in self._sheds if x[0] < epoch)

        def apply_recorded() -> None:
            # recorded events fire before the draw at their doc index —
            # called after every walk-index advance so a replayed shed
            # removes its source before the cursor can resolve past it
            nonlocal rw_p, sh_p
            while (sh_p < len(self._sheds)
                   and self._sheds[sh_p][0] == epoch
                   and self._sheds[sh_p][1] <= self._walk_idx):
                name = self._sheds[sh_p][2]
                popped = live.pop(name, None)
                sh_p += 1
                if popped is not None and not live:
                    raise DataSourceError(
                        "every data source shed — the data plane is "
                        "down", source=name)
            while (rw_p < len(self._reweights)
                   and self._reweights[rw_p][0] == epoch
                   and self._reweights[rw_p][1] <= self._walk_idx):
                for n, w in self._reweights[rw_p][2].items():
                    if n in runs:       # a shed source may still be named
                        runs[n].ew = float(w)
                rw_p += 1

        def draw() -> _Run:
            # re-check right before picking: a consumer-side
            # set_weights (or a live shed) may have appended a record
            # since the post-increment apply
            apply_recorded()
            total = sum(r.ew for r in live.values())
            if not total > 0:
                raise DataLoaderError(
                    "all live source weights are 0 — nothing to draw")
            pick: Optional[_Run] = None
            for n in sorted(live):
                r = live[n]
                r.cw += r.ew
                if pick is None or r.cw > pick.cw:
                    pick = r
            pick.cw -= total
            return pick

        # -- arithmetic fast-forward (resume seek): no fetches --------------
        # one draw = one document, advanced by manifest counts alone;
        # O(delivered docs) integer work, zero shard GETs
        apply_recorded()
        while skip > 0:
            r = draw()
            r.j += 1
            self._walk_idx += 1
            skip -= 1
            if r.j >= int(r.entry()["docs"]):
                r.k += 1
                r.j = 0
            apply_recorded()
            if not self._available(r):
                live.pop(r.name, None)
                if not live:
                    return

        # -- delivery: restore the eager-resolve invariant ------------------
        for name in sorted(live):
            r = live[name]
            try:
                if not self._resolve(r):
                    live.pop(name, None)
            except _Shed as s:
                self._shed_source(live, s.source)
        if not live:
            return

        while live:
            r = draw()
            doc = r.cur_docs[r.j]
            r.j += 1
            self._walk_idx += 1
            if r.j >= len(r.cur_docs):
                r.k += 1
                r.j = 0
                r.cur_docs = None
            apply_recorded()
            # eager resolve: quarantine/shed verdicts land HERE, at the
            # cursor crossing, so the interleave below never observes a
            # bad shard (bitwise-equal to pre-excluded).  A source a
            # recorded shed just removed is NOT resolved — the original
            # run never fetched past its shed point either
            try:
                if (r.name in live and r.cur_docs is None
                        and not self._resolve(r)):
                    live.pop(r.name, None)
            except _Shed as s:
                self._shed_source(live, s.source)
            yield doc


class _Shed(Exception):
    """Internal: a source's breaker opened during shard resolution."""

    def __init__(self, source: str, consecutive: int = 0):
        super().__init__(source)
        self.source = source
        self.consecutive = consecutive
