"""Sequence packing: documents -> fixed-length rows + segment ids.

The native C++ core (_native/pack.cc) does first-fit-decreasing bin
packing; this module compiles/loads it via ctypes (g++ is part of the
toolchain) and falls back to a NumPy implementation when no compiler is
available.  Packed rows feed the varlen flash-attention path
(segment-id masking), replacing the reference's cu_seqlens plumbing
(ops/flash_attn.py varlen variants) with static shapes.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from torchacc_tpu.utils.logger import logger

_LIB = None
_LIB_TRIED = False


def _load_native():
    global _LIB, _LIB_TRIED
    if _LIB_TRIED:
        return _LIB
    _LIB_TRIED = True
    src = os.path.join(os.path.dirname(__file__), "_native", "pack.cc")
    # per-user 0700 cache dir (never a shared world-writable path) +
    # compile-to-temp + atomic rename so concurrent processes can't load
    # a half-written library
    cache_dir = os.path.join(
        tempfile.gettempdir(), f"torchacc_tpu_native_{os.getuid()}")
    os.makedirs(cache_dir, mode=0o700, exist_ok=True)
    os.chmod(cache_dir, 0o700)
    lib_path = os.path.join(cache_dir, "libpack.so")
    try:
        if (not os.path.exists(lib_path)
                or os.path.getmtime(lib_path) < os.path.getmtime(src)):
            fd, tmp = tempfile.mkstemp(suffix=".so", dir=cache_dir)
            os.close(fd)
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", "-o", tmp, src],
                check=True, capture_output=True)
            os.replace(tmp, lib_path)
        lib = ctypes.CDLL(lib_path)
        lib.pack_plan.restype = ctypes.c_int64
        lib.pack_plan.argtypes = [
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64)]
        lib.pack_fill.restype = ctypes.c_int64
        lib.pack_fill.argtypes = [
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32)]
        _LIB = lib
        logger.info("native sequence packer loaded")
    except Exception as e:
        logger.warning(f"native packer unavailable ({e}); using NumPy "
                       "fallback")
        _LIB = None
    return _LIB


def _plan_numpy(lengths: np.ndarray, seq_len: int
                ) -> Tuple[int, np.ndarray, np.ndarray]:
    order = np.argsort(-lengths, kind="stable")
    space: List[int] = []
    row_of = np.zeros(len(lengths), np.int64)
    off_of = np.zeros(len(lengths), np.int64)
    for idx in order:
        ln = int(min(lengths[idx], seq_len))
        row = next((r for r, s in enumerate(space) if s >= ln), -1)
        if row < 0:
            row = len(space)
            space.append(seq_len)
        row_of[idx] = row
        off_of[idx] = seq_len - space[row]
        space[row] -= ln
    return len(space), row_of, off_of


def pack_sequences(
    docs: Sequence[np.ndarray],
    seq_len: int,
    pad_id: int = 0,
) -> Dict[str, np.ndarray]:
    """Pack token documents into rows.

    Returns {"input_ids", "segment_ids", "positions"} each [rows, seq_len].
    Padding carries segment id -1 (matches nothing in the attention mask)
    and position 0; labels derivation remains the caller's job.
    """
    docs = [np.asarray(d, np.int32).reshape(-1) for d in docs]
    lengths = np.asarray([len(d) for d in docs], np.int64)
    n = len(docs)
    if n == 0:
        raise ValueError("no documents to pack")
    lib = _load_native()
    row_of = np.zeros(n, np.int64)
    off_of = np.zeros(n, np.int64)
    if lib is not None:
        rows = lib.pack_plan(
            lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            n, seq_len,
            row_of.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            off_of.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
        if rows < 0:
            raise ValueError("pack_plan failed")
    else:
        rows, row_of, off_of = _plan_numpy(lengths, seq_len)

    out_tokens = np.full((rows, seq_len), pad_id, np.int32)
    out_segments = np.full((rows, seq_len), -1, np.int32)
    out_positions = np.zeros((rows, seq_len), np.int32)

    if lib is not None:
        flat = (np.concatenate(docs) if docs else
                np.zeros((0,), np.int32)).astype(np.int32)
        starts = np.zeros(n + 1, np.int64)
        np.cumsum(lengths, out=starts[1:])
        rc = lib.pack_fill(
            flat.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            starts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            n, seq_len,
            row_of.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            off_of.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            out_tokens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            out_segments.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            out_positions.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        if rc != 0:
            raise ValueError("pack_fill failed")
    else:
        for d, doc in enumerate(docs):
            ln = min(len(doc), seq_len)
            r, o = int(row_of[d]), int(off_of[d])
            out_tokens[r, o:o + ln] = doc[:ln]
            out_segments[r, o:o + ln] = d
            out_positions[r, o:o + ln] = np.arange(ln)
    return {"input_ids": out_tokens, "segment_ids": out_segments,
            "positions": out_positions}
