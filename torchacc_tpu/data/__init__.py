from torchacc_tpu.data.async_loader import AsyncLoader
from torchacc_tpu.data.bucketing import closest_bucket, pad_batch
from torchacc_tpu.data.dataset import PackedDataset
from torchacc_tpu.data.packing import pack_sequences

__all__ = ["AsyncLoader", "closest_bucket", "pad_batch", "PackedDataset",
           "pack_sequences"]
