from torchacc_tpu.data.async_loader import AsyncLoader
from torchacc_tpu.data.bucketing import closest_bucket, pad_batch
from torchacc_tpu.data.dataset import PackedDataset
from torchacc_tpu.data.packing import pack_sequences
from torchacc_tpu.data.store import (ChaosStore, LocalShardStore, ShardStore,
                                     StoreClient, write_store)
from torchacc_tpu.data.stream import StreamingDataset, StreamingSource

__all__ = ["AsyncLoader", "closest_bucket", "pad_batch", "PackedDataset",
           "pack_sequences", "ShardStore", "LocalShardStore", "ChaosStore",
           "StoreClient", "write_store", "StreamingDataset",
           "StreamingSource"]
