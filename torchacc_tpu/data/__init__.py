from torchacc_tpu.data.async_loader import AsyncLoader
from torchacc_tpu.data.bucketing import closest_bucket, pad_batch

__all__ = ["AsyncLoader", "closest_bucket", "pad_batch"]
