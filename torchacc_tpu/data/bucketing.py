"""Sequence-length bucketing: recompilation control for the input pipeline.

Reference: ``BucketingParallelLoader`` (core/async_loader.py:14-138) pads
every batch's trailing dimension up to the nearest bucket length so the
XLA program sees only ``num_buckets`` distinct shapes.  Identical concern
under jit: every new shape is a fresh compile, so we pad to a small fixed
set of lengths.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from torchacc_tpu.utils.logger import logger


def closest_bucket(buckets: Sequence[int], length: int) -> int:
    """Smallest bucket >= length; the largest bucket if none fits
    (reference `_get_closet_bucket` core/async_loader.py:20-33)."""
    for b in buckets:
        if b >= length:
            return b
    logger.debug(f"sequence length {length} exceeds largest bucket "
                 f"{buckets[-1]}; truncating")
    return buckets[-1]


def _to_numpy(x: Any) -> np.ndarray:
    if hasattr(x, "detach"):  # torch tensor
        x = x.detach().cpu().numpy()
    return np.asarray(x)


def pad_batch(
    batch: Dict[str, Any],
    buckets: Optional[Sequence[int]],
    pad_value_dict: Optional[Dict[str, Any]] = None,
    seq_axis: int = -1,
) -> Dict[str, np.ndarray]:
    """Pad (or truncate) every array's sequence axis to the common bucket.

    The bucket is chosen from the longest feature in the batch so all
    features stay aligned.  Pad values default to 0 except ``labels``
    (-100, ignored by the loss — the reference's ``pad_value_dict``
    default, core/async_loader.py:109-138) and ``segment_ids`` (-1, the
    framework-wide "matches nothing" id used by packing and the flash-
    attention mask, so padded keys are never attendable and shift_labels
    never trains across the real/pad boundary).
    """
    arrs = {k: _to_numpy(v) for k, v in batch.items()}
    if not buckets:
        return arrs
    pad_values = {"labels": -100, "segment_ids": -1}
    if pad_value_dict:
        pad_values.update(pad_value_dict)
    # Only features with a distinct sequence axis participate: 0/1-D
    # features are per-example scalars/weights, not sequences.
    seq_lens = [a.shape[seq_axis] for a in arrs.values() if a.ndim >= 2]
    if not seq_lens:
        return arrs
    bucket = closest_bucket(buckets, max(seq_lens))
    out = {}
    for k, a in arrs.items():
        if a.ndim < 2:
            out[k] = a
            continue
        axis = seq_axis % a.ndim
        cur = a.shape[axis]
        if cur == bucket:
            out[k] = a
        elif cur > bucket:
            sl = [slice(None)] * a.ndim
            sl[axis] = slice(0, bucket)
            out[k] = a[tuple(sl)]
        else:
            width = [(0, 0)] * a.ndim
            width[axis] = (0, bucket - cur)
            out[k] = np.pad(a, width, constant_values=pad_values.get(k, 0))
    return out
