"""Async host->device input feed with bucketing and fault tolerance.

Reference: ``AsyncLoader`` (core/async_loader.py:159-207) wraps any
DataLoader in background worker threads that bucket, pad, and upload
batches ahead of compute.  TPU-native version: a producer thread buckets
and pads on host, then ``jax.device_put`` with the batch NamedSharding
starts the (async) transfer; a bounded queue of in-flight device batches
gives double buffering so step N+1's upload overlaps step N's compute.

Fault tolerance (resilience subsystem, ``Config.resilience``): batch
fetches and device transfers are retried with jittered exponential
backoff (``loader_retries``, counter ``loader_retries``); when retries
are exhausted in the producer thread and ``loader_sync_fallback`` is
set, the loader degrades to synchronous consumer-thread iteration
instead of killing the run — some sources misbehave precisely *because*
they are driven from a side thread, so the fallback both simplifies the
failure and often clears it.  Fatal failures raise a typed
:class:`~torchacc_tpu.errors.DataLoaderError`.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
from typing import Any, Dict, Iterable, Iterator, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from torchacc_tpu.config import Config
from torchacc_tpu.data.bucketing import pad_batch
from torchacc_tpu.errors import DataLoaderError
from torchacc_tpu.parallel.sharding import batch_spec
from torchacc_tpu.resilience.chaos import failpoint
from torchacc_tpu.resilience.retry import retry_call
from torchacc_tpu.utils.logger import logger

_SENTINEL = object()
_EXHAUSTED = object()


class _Degrade:
    """Producer -> consumer handoff: async loading gave up; the consumer
    continues synchronously from ``it`` (order is preserved because the
    marker rides the same FIFO queue behind already-produced batches).
    ``pending`` is a batch already fetched from ``it`` whose device
    transfer failed — it must be retried by the consumer, not dropped.
    ``err`` is the producer's final exception: the consumer's first
    re-fetch seeds its truncation detector with it, so a generator
    source that died does not read as a clean end-of-stream."""

    def __init__(self, it: Iterator, pending=None, err=None):
        self.it = it
        self.pending = pending
        self.err = err


class AsyncLoader:
    """Wrap an iterable of dict-of-arrays into an async sharded device feed.

    Iterating yields pytrees of committed jax.Arrays laid out with the
    batch sharding (batch dim over data axes, seq dim over 'sp').
    """

    def __init__(
        self,
        loader: Iterable[Dict[str, Any]],
        config: Config,
        mesh: Optional[Mesh] = None,
        sharding: Optional[NamedSharding] = None,
        stall_dump_dir: Optional[str] = None,
    ):
        self._loader = loader
        self._config = config
        mesh = mesh if mesh is not None else config.get_mesh()
        if sharding is None:
            sharding = NamedSharding(mesh, batch_spec(config))
        self._sharding = sharding
        self._buckets = config.data.bucket_sizes()
        self._pad_values = config.data.pad_value_dict
        self._prefetch = max(1, config.data.prefetch)
        res = config.resilience
        # a DataLoaderError raised inside a retried fetch means "this is
        # final" (e.g. a generator source died) — never re-attempted
        self._retry = dataclasses.replace(
            res.retry_policy(res.loader_retries),
            no_retry=(DataLoaderError,))
        self._sync_fallback = res.loader_sync_fallback
        # stall deadline on the consumer's wait for the next device
        # batch: a producer wedged in a source/fetch (not merely failing
        # — failing is the retry path's job) trips the watchdog path
        # (stack dump + watchdog_stalls counter, HangError under
        # abort_on_hang) instead of hanging the step loop forever
        self._stall_deadline = res.loader_deadline_s
        self._abort_on_hang = res.abort_on_hang
        # where stall stack dumps land (pass the run's metrics/
        # checkpoint dir so the evidence sits next to the trainer
        # watchdog's dumps; None = stderr)
        self._stall_dump_dir = stall_dump_dir
        self._rank_shardings: Dict[int, NamedSharding] = {}

    # -- fault-wrapped primitives -------------------------------------------
    def _fetch(self, it: Iterator, prior_err=None):
        """One batch from the source (or _EXHAUSTED), retried on error.

        Retrying ``next()`` is only sound for restartable iterators; a
        plain *generator* that raised is closed, and re-calling it
        yields StopIteration — which would silently truncate the epoch
        (and misalign resume-skip replay).  End-of-stream right after a
        failed attempt (this call's, or ``prior_err`` carried across a
        degrade handoff) is therefore treated as the original failure,
        loudly."""
        state: Dict[str, Any] = {"err": prior_err}

        def once():
            failpoint("loader.fetch")
            try:
                item = next(it)
            except StopIteration:
                if state["err"] is not None:
                    raise DataLoaderError(
                        "batch source ended immediately after a failed "
                        "fetch — generator-backed sources close on error "
                        "and cannot be retried; surfacing the original "
                        "failure instead of a truncated epoch"
                    ) from state["err"]
                return _EXHAUSTED
            except Exception as e:
                state["err"] = e
                raise
            return item
        return retry_call(once, policy=self._retry, counter="loader_retries",
                          description="loader batch fetch")

    def _leaf_sharding(self, leaf) -> NamedSharding:
        """Batch sharding truncated to the leaf's rank (scalars — e.g.
        injected fault markers — replicate), mirroring the trainer's
        per-leaf batch shardings.  Cached per rank: mesh and spec are
        fixed for the loader's lifetime."""
        ndim = getattr(leaf, "ndim", 0)
        full = self._sharding.spec
        if ndim >= len(full):
            return self._sharding
        sh = self._rank_shardings.get(ndim)
        if sh is None:
            sh = NamedSharding(self._sharding.mesh,
                               PartitionSpec(*full[:ndim]))
            self._rank_shardings[ndim] = sh
        return sh

    def _transfer(self, batch) -> Dict[str, jax.Array]:
        """Pad + start the async device transfer, retried on error."""
        def once():
            failpoint("loader.transfer")
            host = pad_batch(batch, self._buckets, self._pad_values)
            # device_put is async: the DMA overlaps compute, and the
            # bounded queue caps in-flight batches (double buffer).
            return {k: jax.device_put(v, self._leaf_sharding(v))
                    for k, v in host.items()}
        return retry_call(once, policy=self._retry, counter="loader_retries",
                          description="loader device transfer")

    def skip_batches(self, n: int) -> Iterator[Dict[str, jax.Array]]:
        """Iterate after fast-forwarding ``n`` source batches WITHOUT
        padding or device-transferring them.  ``Trainer.fit`` uses this
        on auto-resume so realigning the data stream costs host
        iteration only, not ``n`` wasted device uploads."""
        return self._iterate(skip=n)

    def __iter__(self) -> Iterator[Dict[str, jax.Array]]:
        return self._iterate(skip=0)

    def _iterate(self, skip: int) -> Iterator[Dict[str, jax.Array]]:
        q: queue.Queue = queue.Queue(maxsize=self._prefetch)
        err: list = []
        stop = threading.Event()

        def _put(item) -> bool:
            # Bounded put that gives up when the consumer is gone, so an
            # early `break` in the training loop can't leak a thread
            # pinning device batches forever.
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        it = iter(self._loader)

        def produce():
            pending = None
            skipping = False
            try:
                skipping = True
                for _ in range(skip):
                    if stop.is_set() or self._fetch(it) is _EXHAUSTED:
                        return
                skipping = False
                while True:
                    if stop.is_set():
                        return
                    pending = self._fetch(it)
                    if pending is _EXHAUSTED:
                        break
                    dev = self._transfer(pending)
                    pending = None
                    if not _put(dev):
                        return
            except Exception as e:
                # no degrade for (a) failures while replaying the resume
                # prefix — that would silently misalign the data stream
                # against the restored step count — or (b) typed fatal
                # errors (a dead generator source cannot be resumed from
                # the consumer thread either)
                if self._sync_fallback and not skipping \
                        and not isinstance(e, DataLoaderError):
                    # hand the iterator (and any batch whose transfer
                    # failed) back: the consumer retries this position
                    # synchronously (some sources fail only when driven
                    # from a side thread)
                    logger.warning(
                        f"async loading failed after retries ({e!r}); "
                        "degrading to synchronous loading")
                    from torchacc_tpu.utils.metrics import counters
                    counters.inc("loader_fallbacks")
                    # err seeds the consumer's truncation detector only
                    # for FETCH failures; after a transfer failure the
                    # iterator itself is healthy
                    _put(_Degrade(it, pending,
                                  None if pending is not None else e))
                    return
                err.append(e)
                logger.error(f"AsyncLoader producer failed: {e}")
            finally:
                _put(_SENTINEL)

        t = threading.Thread(target=produce, daemon=True, name="async-loader")
        t.start()
        try:
            while True:
                item = self._get_with_stall_deadline(q)
                if item is _SENTINEL:
                    if err:
                        raise DataLoaderError(
                            "input pipeline failed (batch fetch/transfer "
                            "retries exhausted)") from err[0]
                    return
                if isinstance(item, _Degrade):
                    yield from self._iterate_sync(item.it, item.pending,
                                                  item.err)
                    return
                yield item
        finally:
            stop.set()
            # drain the queue so a producer blocked in _put can observe
            # stop, then wait (bounded) for it to leave the runtime — a
            # daemon thread abandoned inside a device transfer trips
            # std::terminate at interpreter teardown
            try:
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass
            t.join(timeout=5.0)

    def _get_with_stall_deadline(self, q: "queue.Queue"):
        """Next queue item; with ``resilience.loader_deadline_s`` set,
        a wait past the deadline trips the watchdog stall path ONCE per
        wait (stack dump + ``watchdog_stalls``; ``HangError`` when
        ``resilience.abort_on_hang``) — otherwise it logs and keeps
        waiting, so an eventually-recovering source only costs the
        diagnostics."""
        deadline = self._stall_deadline
        if not deadline:
            return q.get()
        import time
        start = time.monotonic()
        quantum = min(max(deadline / 4.0, 0.01), 0.5)
        tripped = False
        while True:
            try:
                return q.get(timeout=quantum)
            except queue.Empty:
                waited = time.monotonic() - start
                if waited >= deadline and not tripped:
                    from torchacc_tpu.resilience.watchdog import trip_stall
                    trip_stall("loader.fetch", waited, deadline,
                               dump_dir=self._stall_dump_dir,
                               abort=self._abort_on_hang)
                    tripped = True

    def _iterate_sync(self, it: Iterator, pending=None,
                      prior_err=None) -> Iterator[Dict[str, jax.Array]]:
        """Degraded mode: fetch + transfer inline on the consumer thread
        (no prefetch overlap); errors here are fatal and typed.
        ``pending`` is a batch the producer fetched but failed to
        transfer — it goes first so nothing is dropped."""
        while True:
            try:
                batch = pending if pending is not None \
                    else self._fetch(it, prior_err)
                pending = prior_err = None
                if batch is _EXHAUSTED:
                    return
                yield self._transfer(batch)
            except StopIteration:  # pragma: no cover - defensive
                return
            except Exception as e:
                raise DataLoaderError(
                    "input pipeline failed in synchronous-fallback mode"
                ) from e

    def __len__(self) -> int:
        return len(self._loader)  # type: ignore[arg-type]
