"""Async host->device input feed with bucketing and fault tolerance.

Reference: ``AsyncLoader`` (core/async_loader.py:159-207) wraps any
DataLoader in background worker threads that bucket, pad, and upload
batches ahead of compute.  TPU-native version: a producer thread buckets
and pads on host, then ``jax.device_put`` with the batch NamedSharding
starts the (async) transfer; a bounded queue of in-flight device batches
gives double buffering so step N+1's upload overlaps step N's compute.

Fault tolerance (resilience subsystem, ``Config.resilience``): batch
fetches and device transfers are retried with jittered exponential
backoff (``loader_retries``, counter ``loader_retries``); when retries
are exhausted in the producer thread and ``loader_sync_fallback`` is
set, the loader degrades to synchronous consumer-thread iteration
instead of killing the run — some sources misbehave precisely *because*
they are driven from a side thread, so the fallback both simplifies the
failure and often clears it.  Fatal failures raise a typed
:class:`~torchacc_tpu.errors.DataLoaderError`.

Durable pipeline state: ``state_dict()``/``load_state_dict()`` capture
the consumer-side batch position (authoritative — the producer thread
prefetches ahead of what training has actually consumed) plus the
source's own state when it exposes the same protocol (PackedDataset
does).  Resume is then O(1) for seekable sources; otherwise the loader
falls back to the skip-replay path and counts the waste
(``resume_replayed_batches``).

Bad-batch quarantine (``resilience.batch_validation``): every fetched
batch is validated in the hot path — tree structure and per-leaf
shape/dtype against the first batch, plus non-finite scans of float
leaves.  Offenders are skipped + counted (``bad_batches_skipped``) and
dumped with provenance to ``resilience.quarantine_dir``; after
``max_consecutive_bad_batches`` in a row a typed
:class:`~torchacc_tpu.errors.BadBatchError` aborts the run — a broken
*source*, not a blip.  ``ChaosPlan.corrupt_batch()`` injects offenders
deterministically through the same seam.
"""

from __future__ import annotations

import dataclasses
import json
import os
import queue
import threading
import time
from typing import Any, Callable, Dict, Iterable, Iterator, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from torchacc_tpu.config import Config
from torchacc_tpu.data.bucketing import pad_batch
from torchacc_tpu.errors import BadBatchError, DataLoaderError
from torchacc_tpu.parallel.sharding import batch_spec
from torchacc_tpu.resilience.chaos import failpoint, maybe_corrupt_batch
from torchacc_tpu.resilience.retry import retry_call
from torchacc_tpu.utils.logger import logger

_SENTINEL = object()
_EXHAUSTED = object()

#: a retrying source defers the consumer's hang verdict, but not
#: forever: once the TOTAL wait for one batch exceeds this many
#: deadlines, the watchdog trips even mid-backoff — a source flapping
#: through endless short retries is starvation, not progress
_STALL_DEFER_CAP = 64


class _Degrade:
    """Producer -> consumer handoff: async loading gave up; the consumer
    continues synchronously from ``it`` (order is preserved because the
    marker rides the same FIFO queue behind already-produced batches).
    ``pending`` is a batch already fetched from ``it`` whose device
    transfer failed — it must be retried by the consumer, not dropped.
    ``err`` is the producer's final exception: the consumer's first
    re-fetch seeds its truncation detector with it, so a generator
    source that died does not read as a clean end-of-stream.  ``idx``
    is the source index of ``pending`` (or of the next fetch), so the
    corruption/validation seams stay aligned across the handoff."""

    def __init__(self, it: Iterator, pending=None, err=None, idx: int = 0):
        self.it = it
        self.pending = pending
        self.err = err
        self.idx = idx


class AsyncLoader:
    """Wrap an iterable of dict-of-arrays into an async sharded device feed.

    Iterating yields pytrees of committed jax.Arrays laid out with the
    batch sharding (batch dim over data axes, seq dim over 'sp').
    """

    def __init__(
        self,
        loader: Iterable[Dict[str, Any]],
        config: Config,
        mesh: Optional[Mesh] = None,
        sharding: Optional[NamedSharding] = None,
        stall_dump_dir: Optional[str] = None,
        quarantine_dir: Optional[str] = None,
    ):
        self._loader = loader
        self._config = config
        mesh = mesh if mesh is not None else config.get_mesh()
        if sharding is None:
            sharding = NamedSharding(mesh, batch_spec(config))
        self._sharding = sharding
        self._buckets = config.data.bucket_sizes()
        self._pad_values = config.data.pad_value_dict
        self._prefetch = max(1, config.data.prefetch)
        res = config.resilience
        # a DataLoaderError raised inside a retried fetch means "this is
        # final" (e.g. a generator source died) — never re-attempted
        self._retry = dataclasses.replace(
            res.retry_policy(res.loader_retries),
            no_retry=(DataLoaderError,))
        self._sync_fallback = res.loader_sync_fallback
        # stall deadline on the consumer's wait for the next device
        # batch: a producer wedged in a source/fetch (not merely failing
        # — failing is the retry path's job) trips the watchdog path
        # (stack dump + watchdog_stalls counter, HangError under
        # abort_on_hang) instead of hanging the step loop forever
        self._stall_deadline = res.loader_deadline_s
        self._abort_on_hang = res.abort_on_hang
        # where stall stack dumps land (pass the run's metrics/
        # checkpoint dir so the evidence sits next to the trainer
        # watchdog's dumps; None = stderr)
        self._stall_dump_dir = stall_dump_dir
        self._rank_shardings: Dict[int, NamedSharding] = {}
        # bad-batch quarantine (resilience subsystem): validation is
        # opt-in — the non-finite scan touches every float element
        self._validate_on = res.batch_validation
        self._max_bad = res.max_consecutive_bad_batches
        self._quarantine_dir = quarantine_dir or res.quarantine_dir
        self._ref_spec: Optional[Dict[str, Any]] = None
        self._ref_confirmed = 0  # batches that matched the reference
        self._bad_streak = 0
        # durable pipeline state: consumer-side batches delivered to the
        # training loop (the producer prefetches AHEAD of this), plus
        # the SOURCE position backing the last delivered batch — the two
        # diverge when bad batches are quarantined (skipped batches
        # consume source positions without being delivered), and resume
        # must seek the source, not the delivery count
        self._consumed = 0
        self._src_pos = 0
        self._resume_state: Optional[Dict[str, Any]] = None
        # "slow, not stuck": count of producer-side retry backoffs in
        # flight (fetch/transfer), read by the consumer's stall deadline
        # so a retrying source defers the hang verdict instead of
        # tripping it — retry wait is data_wait (the SLO), not a hang
        self._retrying = 0
        self._stall_heartbeat: Optional[Callable[[], None]] = None

    # -- stall/retry plumbing -------------------------------------------------
    @property
    def in_retry(self) -> bool:
        """True while a producer-side fetch/transfer is inside a retry
        backoff — here or in the wrapped source (e.g. a StreamingDataset
        retrying a store GET)."""
        return (self._retrying > 0
                or bool(getattr(self._loader, "in_retry", False)))

    def set_stall_heartbeat(self, fn: Optional[Callable[[], None]]) -> None:
        """Wire the trainer watchdog's ``beat`` in: it fires before
        every retry backoff sleep (and is forwarded to the wrapped
        source), so a long backoff never reads as a dead section."""
        self._stall_heartbeat = fn
        fwd = getattr(self._loader, "set_stall_heartbeat", None)
        if callable(fwd):
            fwd(fn)

    def _retry_sleep(self, seconds: float) -> None:
        self._retrying += 1
        try:
            hb = self._stall_heartbeat
            if hb is not None:
                try:
                    hb()
                except Exception:
                    pass
            time.sleep(seconds)
        finally:
            self._retrying -= 1

    # -- durable state -------------------------------------------------------
    def state_dict(self) -> Dict[str, Any]:
        """JSON-serialisable resume state.  ``batches_consumed`` is the
        CONSUMER-side count (batches the training loop actually
        received — the producer prefetches ahead of it);
        ``source_position`` is the source index resume must seek to
        (>= batches_consumed once quarantine skipped offenders).  The
        wrapped source's own ``state_dict()`` rides along when it
        exposes one, its producer-side count overridden on restore."""
        src_fn = getattr(self._loader, "state_dict", None)
        return {
            "version": 1,
            "kind": "async_loader",
            "batches_consumed": self._consumed,
            "source_position": self._src_pos,
            "source": src_fn() if callable(src_fn) else None,
        }

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        """Arm the NEXT iteration to resume at the saved position: O(1)
        via the source's own ``load_state_dict`` when available, else a
        logged + counted skip-replay of the consumed prefix."""
        self._resume_state = dict(state)

    # -- fault-wrapped primitives -------------------------------------------
    def _fetch(self, it: Iterator, prior_err=None):
        """One batch from the source (or _EXHAUSTED), retried on error.

        Retrying ``next()`` is only sound for restartable iterators; a
        plain *generator* that raised is closed, and re-calling it
        yields StopIteration — which would silently truncate the epoch
        (and misalign resume-skip replay).  End-of-stream right after a
        failed attempt (this call's, or ``prior_err`` carried across a
        degrade handoff) is therefore treated as the original failure,
        loudly."""
        state: Dict[str, Any] = {"err": prior_err}

        def once():
            failpoint("loader.fetch")
            try:
                item = next(it)
            except StopIteration:
                if state["err"] is not None:
                    raise DataLoaderError(
                        "batch source ended immediately after a failed "
                        "fetch — generator-backed sources close on error "
                        "and cannot be retried; surfacing the original "
                        "failure instead of a truncated epoch"
                    ) from state["err"]
                return _EXHAUSTED
            except Exception as e:
                state["err"] = e
                raise
            return item
        return retry_call(once, policy=self._retry, counter="loader_retries",
                          description="loader batch fetch",
                          sleep=self._retry_sleep)

    def _leaf_sharding(self, leaf) -> NamedSharding:
        """Batch sharding truncated to the leaf's rank (scalars — e.g.
        injected fault markers — replicate), mirroring the trainer's
        per-leaf batch shardings.  Cached per rank: mesh and spec are
        fixed for the loader's lifetime."""
        ndim = getattr(leaf, "ndim", 0)
        full = self._sharding.spec
        if ndim >= len(full):
            return self._sharding
        sh = self._rank_shardings.get(ndim)
        if sh is None:
            sh = NamedSharding(self._sharding.mesh,
                               PartitionSpec(*full[:ndim]))
            self._rank_shardings[ndim] = sh
        return sh

    def _transfer(self, batch) -> Dict[str, jax.Array]:
        """Pad + start the async device transfer, retried on error."""
        def once():
            failpoint("loader.transfer")
            host = pad_batch(batch, self._buckets, self._pad_values)
            # device_put is async: the DMA overlaps compute, and the
            # bounded queue caps in-flight batches (double buffer).
            return {k: jax.device_put(v, self._leaf_sharding(v))
                    for k, v in host.items()}
        return retry_call(once, policy=self._retry, counter="loader_retries",
                          description="loader device transfer",
                          sleep=self._retry_sleep)

    def skip_batches(self, n: int) -> Iterator[Dict[str, jax.Array]]:
        """Iterate after fast-forwarding ``n`` source batches WITHOUT
        padding or device-transferring them.  ``Trainer.fit`` uses this
        on auto-resume so realigning the data stream costs host
        iteration only, not ``n`` wasted device uploads."""
        return self._iterate(skip=n)

    def __iter__(self) -> Iterator[Dict[str, jax.Array]]:
        return self._iterate(skip=0)

    # -- batch validation / quarantine ---------------------------------------
    def _check_batch(self, batch: Any) -> Optional[str]:
        """Reason the batch is bad, or None.  Structure and per-leaf
        shape/dtype are judged against the FIRST batch seen (which can
        only be vetted for non-finite values — there is nothing earlier
        to compare it to); float leaves are scanned for non-finites."""
        if not isinstance(batch, dict):
            return f"batch is {type(batch).__name__}, expected dict"
        spec = {}
        for k, v in batch.items():
            arr_dtype = getattr(v, "dtype", None)
            spec[k] = (tuple(getattr(v, "shape", np.shape(v))),
                       str(arr_dtype if arr_dtype is not None
                           else np.asarray(v).dtype))
        ref = self._ref_spec
        if ref is not None:
            if set(spec) != set(ref):
                return ("tree structure drift (missing "
                        f"{sorted(set(ref) - set(spec))}, extra "
                        f"{sorted(set(spec) - set(ref))})")
            for k in spec:
                if spec[k][0] != ref[k][0]:
                    return (f"leaf {k!r}: shape {spec[k][0]} != expected "
                            f"{ref[k][0]}")
                if spec[k][1] != ref[k][1]:
                    return (f"leaf {k!r}: dtype {spec[k][1]} != expected "
                            f"{ref[k][1]}")
            # this batch agrees with the reference — the reference is
            # corroborated (see the BadBatchError hint below)
            self._ref_confirmed += 1
        for k, v in batch.items():
            arr = np.asarray(v)
            if np.issubdtype(arr.dtype, np.floating) \
                    and not np.isfinite(arr).all():
                return f"leaf {k!r}: non-finite values"
        if ref is None:
            self._ref_spec = spec
        return None

    def _on_bad_batch(self, batch: Any, index: int, reason: str) -> None:
        """Count + dump + (past the consecutive limit) abort typed."""
        from torchacc_tpu.utils.metrics import counters

        self._bad_streak += 1
        counters.inc("bad_batches_skipped")
        dump = self._dump_quarantine(batch, index, reason)
        logger.warning(
            f"bad batch {index} skipped ({reason}); consecutive "
            f"{self._bad_streak}/{self._max_bad}"
            + (f"; quarantined to {dump}" if dump else ""))
        if self._bad_streak >= self._max_bad:
            # shape/dtype drift is judged against the FIRST batch; when
            # nothing else ever matched it, the reference itself may be
            # the outlier — tell the operator (deciding automatically is
            # impossible: K consistent corrupt batches and a corrupt
            # first batch are symmetric)
            hint = ("" if self._ref_confirmed or "non-finite" in reason
                    else " (note: the first batch — the validation "
                         "reference — was never matched by any other "
                         "batch and may itself be the corrupt one)")
            raise BadBatchError(
                f"{self._bad_streak} consecutive batches failed "
                f"validation (last: batch {index}: {reason}) — the "
                f"source is broken, not one batch{hint}",
                index=index, reason=reason, consecutive=self._bad_streak)

    def _dump_quarantine(self, batch: Any, index: int,
                         reason: str) -> Optional[str]:
        """Offending batch + provenance into ``quarantine_dir`` (best
        effort — evidence must never crash the run it documents)."""
        if not self._quarantine_dir:
            return None
        try:
            os.makedirs(self._quarantine_dir, exist_ok=True)
            stem = os.path.join(self._quarantine_dir, f"batch_{index:08d}")
            arrays = ({str(k): np.asarray(v) for k, v in batch.items()}
                      if isinstance(batch, dict) else {})
            np.savez(stem + ".npz", **arrays)
            prov = {"index": index, "reason": reason, "time": time.time(),
                    "keys": sorted(arrays),
                    "source": type(self._loader).__name__}
            with open(stem + ".json", "w") as f:
                json.dump(prov, f)
            return stem + ".npz"
        except Exception as e:  # noqa: BLE001 - evidence is best-effort
            logger.warning(f"could not dump quarantined batch {index}: {e}")
            return None

    def _iterate(self, skip: int) -> Iterator[Dict[str, jax.Array]]:
        resume, self._resume_state = self._resume_state, None
        if resume is not None:
            n = int(resume.get("batches_consumed", 0))
            # seek target: the source index AFTER the last delivered
            # batch (quarantined batches consumed source positions the
            # delivery count never saw); pre-quarantine states carry
            # only batches_consumed, where the two were equal
            spos = int(resume.get("source_position", n))
            src_state = resume.get("source")
            load_fn = getattr(self._loader, "load_state_dict", None)
            if src_state is not None and callable(load_fn):
                # O(1) path: the source repositions itself (seekable),
                # or replays + counts internally (non-seekable).  The
                # consumer-side position overrides the producer-side
                # one recorded in the source state (prefetch skew).
                src_state = dict(src_state)
                src_state["batches_consumed"] = spos
                load_fn(src_state)
            elif spos:
                from torchacc_tpu.utils.metrics import counters
                counters.inc("resume_replayed_batches", spos)
                logger.warning(
                    f"resume: source exposes no durable state — "
                    f"replaying {spos} consumed batches to realign the "
                    "stream")
                skip += spos
            self._consumed = n
            self._src_pos = spos
        else:
            self._consumed = skip
            self._src_pos = skip
        q: queue.Queue = queue.Queue(maxsize=self._prefetch)
        err: list = []
        stop = threading.Event()

        def _put(item) -> bool:
            # Bounded put that gives up when the consumer is gone, so an
            # early `break` in the training loop can't leak a thread
            # pinning device batches forever.
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        it = iter(self._loader)
        # first SOURCE index the producer will deliver: skipped batches
        # occupy the indices before it (plain skip path), or the source
        # was repositioned there (durable-state path)
        base_idx = self._src_pos

        def produce():
            pending = None
            skipping = False
            idx = base_idx
            try:
                skipping = True
                for _ in range(skip):
                    if stop.is_set() or self._fetch(it) is _EXHAUSTED:
                        return
                skipping = False
                while True:
                    if stop.is_set():
                        return
                    pending = self._fetch(it)
                    if pending is _EXHAUSTED:
                        break
                    pending = maybe_corrupt_batch(pending, idx)
                    if self._validate_on:
                        reason = self._check_batch(pending)
                        if reason is not None:
                            bad, pending = pending, None
                            idx += 1
                            self._on_bad_batch(bad, idx - 1, reason)
                            continue
                        self._bad_streak = 0
                    dev = self._transfer(pending)
                    pending = None
                    idx += 1
                    # the source position AFTER this batch rides along:
                    # the consumer records it per delivery, so a saved
                    # state seeks past quarantined (skipped) offenders
                    if not _put((dev, idx)):
                        return
            except Exception as e:
                # no degrade for (a) failures while replaying the resume
                # prefix — that would silently misalign the data stream
                # against the restored step count — or (b) typed fatal
                # errors (a dead generator source cannot be resumed from
                # the consumer thread either; BadBatchError is a verdict
                # on the source, not on this thread)
                if self._sync_fallback and not skipping \
                        and not isinstance(e, DataLoaderError):
                    # hand the iterator (and any batch whose transfer
                    # failed) back: the consumer retries this position
                    # synchronously (some sources fail only when driven
                    # from a side thread)
                    logger.warning(
                        f"async loading failed after retries ({e!r}); "
                        "degrading to synchronous loading")
                    from torchacc_tpu.utils.metrics import counters
                    counters.inc("loader_fallbacks")
                    # err seeds the consumer's truncation detector only
                    # for FETCH failures; after a transfer failure the
                    # iterator itself is healthy
                    _put(_Degrade(it, pending,
                                  None if pending is not None else e,
                                  idx))
                    return
                err.append(e)
                logger.error(f"AsyncLoader producer failed: {e}")
            finally:
                _put(_SENTINEL)

        t = threading.Thread(target=produce, daemon=True, name="async-loader")
        t.start()
        try:
            while True:
                item = self._get_with_stall_deadline(q)
                if item is _SENTINEL:
                    if err:
                        if isinstance(err[0], BadBatchError):
                            raise err[0]  # typed verdict, not I/O failure
                        raise DataLoaderError(
                            "input pipeline failed (batch fetch/transfer "
                            "retries exhausted)") from err[0]
                    return
                if isinstance(item, _Degrade):
                    yield from self._iterate_sync(item.it, item.pending,
                                                  item.err, item.idx)
                    return
                dev, pos = item
                self._consumed += 1
                self._src_pos = pos
                yield dev
        finally:
            stop.set()
            # drain the queue so a producer blocked in _put can observe
            # stop, then wait (bounded) for it to leave the runtime — a
            # daemon thread abandoned inside a device transfer trips
            # std::terminate at interpreter teardown
            try:
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass
            t.join(timeout=5.0)

    def _get_with_stall_deadline(self, q: "queue.Queue"):
        """Next queue item; with ``resilience.loader_deadline_s`` set,
        a wait past the deadline trips the watchdog stall path ONCE per
        wait (stack dump + ``watchdog_stalls``; ``HangError`` when
        ``resilience.abort_on_hang``) — otherwise it logs and keeps
        waiting, so an eventually-recovering source only costs the
        diagnostics.  A source inside a retry backoff (``in_retry``)
        defers the verdict — that wait is the ``data_wait`` SLO, not a
        hang — but only up to ``_STALL_DEFER_CAP`` deadlines of total
        wait: past that, retrying-forever counts as stuck."""
        deadline = self._stall_deadline
        if not deadline:
            return q.get()
        begin = start = time.monotonic()
        quantum = min(max(deadline / 4.0, 0.01), 0.5)
        tripped = False
        deferrals = 0
        # in_retry is sampled every quantum, not just at expiry: between
        # two backoff sleeps the flag is briefly false, and a single
        # unlucky sample must not convert a retrying source into a hang
        last_retry = float("-inf")
        while True:
            try:
                return q.get(timeout=quantum)
            except queue.Empty:
                now = time.monotonic()
                if self.in_retry:
                    last_retry = now
                waited = now - start
                if waited >= deadline and not tripped:
                    total = now - begin
                    if (now - last_retry < deadline
                            and total < deadline * _STALL_DEFER_CAP):
                        # the producer is SLOW, not STUCK: a fetch is
                        # inside a retry backoff (store 429s, transient
                        # errors).  That wait belongs to the data_wait
                        # SLO, not the hang verdict — defer the deadline
                        # until the retrying clears (bounded above)
                        deferrals += 1
                        from torchacc_tpu.utils.metrics import counters
                        counters.inc("loader_stalls_deferred")
                        logger.warning(
                            f"loader stall deadline ({deadline:.1f}s) "
                            "reached while the source is retrying — "
                            f"deferring the hang verdict (deferral "
                            f"{deferrals}, {total:.1f}s waited; trips "
                            f"anyway at {deadline * _STALL_DEFER_CAP:.1f}"
                            "s)")
                        start = time.monotonic()
                        continue
                    from torchacc_tpu.resilience.watchdog import trip_stall
                    trip_stall("loader.fetch", total, deadline,
                               dump_dir=self._stall_dump_dir,
                               abort=self._abort_on_hang)
                    tripped = True

    def _iterate_sync(self, it: Iterator, pending=None, prior_err=None,
                      idx: int = 0) -> Iterator[Dict[str, jax.Array]]:
        """Degraded mode: fetch + transfer inline on the consumer thread
        (no prefetch overlap); errors here are fatal and typed.
        ``pending`` is a batch the producer fetched but failed to
        transfer — it goes first (already corrupted/validated by the
        producer) so nothing is dropped or double-checked."""
        while True:
            try:
                handed = pending is not None
                batch = pending if handed else self._fetch(it, prior_err)
                pending = prior_err = None
                if batch is _EXHAUSTED:
                    return
                if not handed:
                    batch = maybe_corrupt_batch(batch, idx)
                    if self._validate_on:
                        reason = self._check_batch(batch)
                        if reason is not None:
                            bad = batch
                            idx += 1
                            self._on_bad_batch(bad, idx - 1, reason)
                            continue
                        self._bad_streak = 0
                dev = self._transfer(batch)
                idx += 1
                self._consumed += 1
                self._src_pos = idx
                yield dev
            except StopIteration:  # pragma: no cover - defensive
                return
            except BadBatchError:
                raise  # typed verdict on the source — never re-wrapped
            except Exception as e:
                raise DataLoaderError(
                    "input pipeline failed in synchronous-fallback mode"
                ) from e

    def __len__(self) -> int:
        return len(self._loader)  # type: ignore[arg-type]
