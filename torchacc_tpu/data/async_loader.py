"""Async host->device input feed with bucketing.

Reference: ``AsyncLoader`` (core/async_loader.py:159-207) wraps any
DataLoader in background worker threads that bucket, pad, and upload
batches ahead of compute.  TPU-native version: a producer thread buckets
and pads on host, then ``jax.device_put`` with the batch NamedSharding
starts the (async) transfer; a bounded queue of in-flight device batches
gives double buffering so step N+1's upload overlaps step N's compute.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Dict, Iterable, Iterator, Optional

import jax
from jax.sharding import Mesh, NamedSharding

from torchacc_tpu.config import Config
from torchacc_tpu.data.bucketing import pad_batch
from torchacc_tpu.parallel.sharding import batch_spec
from torchacc_tpu.utils.logger import logger

_SENTINEL = object()


class AsyncLoader:
    """Wrap an iterable of dict-of-arrays into an async sharded device feed.

    Iterating yields pytrees of committed jax.Arrays laid out with the
    batch sharding (batch dim over data axes, seq dim over 'sp').
    """

    def __init__(
        self,
        loader: Iterable[Dict[str, Any]],
        config: Config,
        mesh: Optional[Mesh] = None,
        sharding: Optional[NamedSharding] = None,
    ):
        self._loader = loader
        self._config = config
        mesh = mesh if mesh is not None else config.get_mesh()
        if sharding is None:
            sharding = NamedSharding(mesh, batch_spec(config))
        self._sharding = sharding
        self._buckets = config.data.bucket_sizes()
        self._pad_values = config.data.pad_value_dict
        self._prefetch = max(1, config.data.prefetch)

    def __iter__(self) -> Iterator[Dict[str, jax.Array]]:
        q: queue.Queue = queue.Queue(maxsize=self._prefetch)
        err: list = []
        stop = threading.Event()

        def _put(item) -> bool:
            # Bounded put that gives up when the consumer is gone, so an
            # early `break` in the training loop can't leak a thread
            # pinning device batches forever.
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def produce():
            try:
                for batch in self._loader:
                    if stop.is_set():
                        return
                    host = pad_batch(batch, self._buckets, self._pad_values)
                    # device_put is async: the DMA overlaps compute, and the
                    # bounded queue caps in-flight batches (double buffer).
                    dev = {k: jax.device_put(v, self._sharding)
                           for k, v in host.items()}
                    if not _put(dev):
                        return
            except Exception as e:  # surface in the consumer thread
                err.append(e)
                logger.error(f"AsyncLoader producer failed: {e}")
            finally:
                _put(_SENTINEL)

        t = threading.Thread(target=produce, daemon=True, name="async-loader")
        t.start()
        try:
            while True:
                item = q.get()
                if item is _SENTINEL:
                    if err:
                        raise err[0]
                    return
                yield item
        finally:
            stop.set()

    def __len__(self) -> int:
        return len(self._loader)  # type: ignore[arg-type]
