"""Object-store shard abstraction for the streaming data plane.

A trillion-token run does not read local Sequences — it streams shards
from an object store that throttles (HTTP 429), tears reads mid-object,
serves the occasional bit-rotted payload, and sometimes goes away
entirely.  This module is the storage half of that pipeline
(``data/stream.py`` is the ordering/packing half):

- **Manifest + shard codec** — a store is a flat namespace of shard
  blobs plus one ``manifest.json`` naming every shard with its byte
  size, sha256, and document count.  The checksum in the manifest is
  what makes torn reads and corruption *detectable*; the doc counts are
  what make resume seekable without fetching (``stream.py`` walks the
  global document order from counts alone).  The codec is a fixed
  little-endian layout (magic + lengths + payload) so shard bytes — and
  therefore checksums — are identical across hosts and runs.
- :class:`ShardStore` / :class:`LocalShardStore` — the GET surface and
  its local-directory backend (the gs:// backend is the same two
  methods over tensorstore/GCS when a real bucket exists).
- :class:`ChaosStore` — a fault-injecting wrapper with a gs://-shaped
  failure model: transient 5xx-ish errors, 429 throttling with a
  retry-after, latency spikes, torn (short) reads, checksum-corrupted
  payloads, and hard-dead stores.  Faults are a pure function of
  ``(seed, shard name, attempt)`` so the same seed yields the same
  fault schedule regardless of fetch order — the property the bitwise
  chaos gates stand on.
- :class:`StoreClient` — ALL store GETs go through the ONE shared
  retry/verify path (``store/client.py``'s :class:`ObjectStoreClient`
  — the same client checkpoint tier-2 mirrors and journal archives
  write through).  This class is the thin data-plane face over it:
  manifest bookkeeping, decode (→ tokenize for text shards), and the
  per-source breaker surface ``stream.py`` drives.  A GET that stays
  bad across the retry budget raises typed
  :class:`~torchacc_tpu.errors.ShardCorruptionError` /
  ``DataLoaderError`` — the caller (``stream.py``) quarantines the
  shard and moves on.

Since PR 19 the backend interface, the chaos fault model, and the
retry/checksum client live in ``torchacc_tpu/store/``; this module
keeps the shard codec, the manifest layout, and the data-plane names
(``ShardStore`` / ``LocalShardStore`` / ``ChaosStore`` /
``StoreClient``) as thin subclasses so existing imports and tests are
untouched.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence

import numpy as np

from torchacc_tpu.errors import DataLoaderError, ShardCorruptionError
from torchacc_tpu.store.base import (
    LocalObjectStore,
    ObjectStore,
    ThrottleError,
)
from torchacc_tpu.store.chaos import ChaosObjectStore
from torchacc_tpu.store.client import ObjectStoreClient
from torchacc_tpu.utils.retry import RetryPolicy

__all__ = [
    "MANIFEST_NAME", "ThrottleError", "encode_shard", "decode_shard",
    "ShardStore", "LocalShardStore", "write_store", "ChaosStore",
    "StoreClient",
]

_MAGIC = b"TASH1\n"
MANIFEST_NAME = "manifest.json"


# -- shard codec ---------------------------------------------------------------

def encode_shard(docs: Sequence[Any], *, kind: str = "tokens") -> bytes:
    """Serialise documents into one shard blob.

    ``kind='tokens'``: each doc is an int32 token array.  ``'text'``:
    each doc is a str (tokenized online at read time).  Fixed layout —
    magic, kind byte, uint32 ndocs, uint32 lengths, payload — so the
    bytes (and the manifest checksum) are platform-independent."""
    if kind not in ("tokens", "text"):
        raise ValueError(f"unknown shard kind {kind!r}")
    if kind == "tokens":
        payloads = [np.asarray(d, np.int32).reshape(-1).tobytes()
                    for d in docs]
        lengths = [len(p) // 4 for p in payloads]
    else:
        payloads = [str(d).encode("utf-8") for d in docs]
        lengths = [len(p) for p in payloads]
    head = (_MAGIC + (b"T" if kind == "tokens" else b"X")
            + np.uint32(len(docs)).tobytes()
            + np.asarray(lengths, "<u4").tobytes())
    return head + b"".join(payloads)


def decode_shard(data: bytes) -> tuple:
    """``(kind, docs)`` from shard bytes; raises
    :class:`ShardCorruptionError` on any structural damage (bad magic,
    truncation, trailing garbage)."""
    def bad(reason: str) -> ShardCorruptionError:
        return ShardCorruptionError(
            f"shard payload undecodable: {reason}", reason=reason)
    if len(data) < len(_MAGIC) + 5 or not data.startswith(_MAGIC):
        raise bad("bad magic")
    kind_b = data[len(_MAGIC):len(_MAGIC) + 1]
    if kind_b not in (b"T", b"X"):
        raise bad(f"unknown kind byte {kind_b!r}")
    kind = "tokens" if kind_b == b"T" else "text"
    off = len(_MAGIC) + 1
    ndocs = int(np.frombuffer(data[off:off + 4], "<u4")[0])
    off += 4
    if len(data) < off + 4 * ndocs:
        raise bad("truncated length table")
    lengths = np.frombuffer(data[off:off + 4 * ndocs], "<u4").astype(np.int64)
    off += 4 * ndocs
    unit = 4 if kind == "tokens" else 1
    need = off + int(lengths.sum()) * unit
    if len(data) != need:
        raise bad(f"payload is {len(data) - off} bytes, header says "
                  f"{need - off}")
    docs: List[Any] = []
    for ln in lengths:
        n = int(ln) * unit
        chunk = data[off:off + n]
        off += n
        if kind == "tokens":
            docs.append(np.frombuffer(chunk, "<i4").astype(np.int32))
        else:
            try:
                docs.append(chunk.decode("utf-8"))
            except UnicodeDecodeError as e:
                raise bad(f"undecodable text doc: {e}") from e
    return kind, docs


# -- stores --------------------------------------------------------------------

class ShardStore(ObjectStore):
    """The data-plane backend surface: the shared five-verb
    :class:`~torchacc_tpu.store.base.ObjectStore` plus one manifest.
    Implementations raise ``OSError`` (or subclasses like
    :class:`ThrottleError`) for transport failures — the
    :class:`StoreClient` owns retries; stores stay retry-free."""

    def manifest(self) -> Dict[str, Any]:
        raise NotImplementedError


class LocalShardStore(LocalObjectStore, ShardStore):
    """Directory-backed store: shards are files under ``root``,
    ``manifest.json`` beside them (what :func:`write_store` lays
    out).  The five store verbs come from
    :class:`~torchacc_tpu.store.base.LocalObjectStore`; shard GETs
    additionally reject path-shaped names with the data plane's typed
    error."""

    def manifest(self) -> Dict[str, Any]:
        with open(os.path.join(self.root, MANIFEST_NAME)) as f:
            return json.load(f)

    def get(self, name: str) -> bytes:
        if os.sep in name or name.startswith("."):
            raise DataLoaderError(f"illegal shard name {name!r}")
        return LocalObjectStore.get(self, name)


def write_store(root: str, docs: Sequence[Any], *, source: str,
                shard_docs: int = 64, kind: str = "tokens"
                ) -> Dict[str, Any]:
    """Shard ``docs`` into ``root`` and write the manifest; returns the
    manifest dict.  The builder the tests/bench use — a production
    ingest job writes the same layout into a bucket."""
    os.makedirs(root, exist_ok=True)
    shards: List[Dict[str, Any]] = []
    for i in range(0, max(len(docs), 1), shard_docs):
        chunk = docs[i:i + shard_docs]
        if not len(chunk):
            break
        name = f"{source}-{i // shard_docs:05d}.tash"
        blob = encode_shard(chunk, kind=kind)
        with open(os.path.join(root, name), "wb") as f:
            f.write(blob)
        shards.append({
            "name": name, "docs": len(chunk), "bytes": len(blob),
            "sha256": hashlib.sha256(blob).hexdigest(), "kind": kind,
        })
    manifest = {"version": 1, "source": source, "shards": shards}
    tmp = os.path.join(root, MANIFEST_NAME + ".tmp")
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1)
    os.replace(tmp, os.path.join(root, MANIFEST_NAME))
    return manifest


# -- fault injection -----------------------------------------------------------

class ChaosStore(ChaosObjectStore, ShardStore):
    """The data-plane face of the shared
    :class:`~torchacc_tpu.store.chaos.ChaosObjectStore`: the identical
    (seed, shard name, attempt) fault plans — transient / throttle /
    torn / latency / corrupt / dead, plus the PR-19 write-side faults —
    with the manifest verb a :class:`ShardStore` adds.  Kept as its
    own name because the data chaos gates (and their seeds) predate
    the shared plane; ``corrupt_shards`` aliases the generic
    ``corrupt_keys``."""

    def __init__(self, inner: ShardStore, *, seed: int = 0,
                 transient_rate: float = 0.0, throttle_rate: float = 0.0,
                 torn_rate: float = 0.0, corrupt_rate: float = 0.0,
                 corrupt_shards: Iterable[str] = (),
                 latency_s: float = 0.0, latency_rate: float = 0.0,
                 dead: bool = False,
                 sleep: Callable[[float], None] = time.sleep,
                 **write_faults: Any):
        ChaosObjectStore.__init__(
            self, inner, seed=seed, transient_rate=transient_rate,
            throttle_rate=throttle_rate, torn_rate=torn_rate,
            corrupt_rate=corrupt_rate, corrupt_keys=corrupt_shards,
            latency_s=latency_s, latency_rate=latency_rate,
            dead=dead, sleep=sleep, **write_faults)

    @property
    def corrupt_shards(self) -> set:
        return self.corrupt_keys

    def manifest(self) -> Dict[str, Any]:
        if self.dead:
            raise OSError("chaos: store is dead (manifest)")
        return self.inner.manifest()


# -- the one GET path ----------------------------------------------------------

class StoreClient:
    """The data-plane face over the ONE shared retry/verify client
    (:class:`~torchacc_tpu.store.client.ObjectStoreClient`): manifest
    bookkeeping, shard decode (→ tokenize for text shards), and the
    per-source breaker surface ``stream.py`` drives.  Every GET —
    ``store.get`` → sha256 vs manifest → decode — runs inside the
    shared retry core; a checksum/decode failure is retried (torn
    reads are transient), and the LAST failure propagates typed for
    ``stream.py`` to quarantine.

    ``on_wait(seconds)`` fires before every backoff sleep — the
    in-retry heartbeat seam (``AsyncLoader`` reads :attr:`in_retry` so
    a slow-but-retrying source never trips ``HangError``)."""

    def __init__(self, store: ShardStore, *, source: str,
                 policy: Optional[RetryPolicy] = None,
                 failure_budget: int = 3,
                 breaker_cooldown_s: float = 30.0,
                 tokenize: Optional[Callable[[str], Any]] = None,
                 sleep: Callable[[float], None] = time.sleep,
                 on_wait: Optional[Callable[[float], None]] = None):
        self.store = store
        self.source = str(source)
        self.tokenize = tokenize
        self._client = ObjectStoreClient(
            store, destination=f"source {source!r}", policy=policy,
            failure_budget=failure_budget,
            breaker_cooldown_s=breaker_cooldown_s, sleep=sleep,
            on_wait=on_wait, get_retry_counter="shard_fetch_retries")
        self._entries: Optional[Dict[str, Dict[str, Any]]] = None

    @property
    def policy(self) -> RetryPolicy:
        return self._client.policy

    @property
    def breaker(self):
        return self._client.breaker

    @property
    def in_retry(self) -> bool:
        return self._client.in_retry

    def manifest_entries(self) -> Dict[str, Dict[str, Any]]:
        """name -> manifest entry, fetched once through the retry
        core (a dead store fails HERE, typed)."""
        if self._entries is None:
            try:
                man = self._client.retrying(
                    self.store.manifest,
                    description=f"{self.source}: manifest")
            except Exception as e:
                raise DataLoaderError(
                    f"source {self.source!r}: manifest unreadable "
                    f"({e!r})") from e
            self._entries = {s["name"]: s for s in man.get("shards", [])}
        return self._entries

    def get_docs(self, name: str) -> List[Any]:
        """Fetch + verify + decode one shard into its document list.
        Raises :class:`ShardCorruptionError` (persistent corruption) or
        ``OSError`` (transport, retries exhausted); the caller owns the
        quarantine verdict and the breaker's failure edge."""
        entry = self.manifest_entries().get(name)
        if entry is None:
            raise DataLoaderError(
                f"source {self.source!r}: shard {name!r} is not in the "
                "manifest")
        want_sha = entry.get("sha256")

        def mismatch(got: str) -> ShardCorruptionError:
            return ShardCorruptionError(
                f"{self.source}/{name}: sha256 {got[:12]} != "
                f"manifest {want_sha[:12]} (torn read or "
                "corruption)", source=self.source, shard=name,
                reason="checksum mismatch")

        def decode(data: bytes) -> List[Any]:
            kind, docs = decode_shard(data)
            if kind == "text":
                if self.tokenize is None:
                    raise DataLoaderError(
                        f"{self.source}/{name} holds text docs but the "
                        "source has no tokenizer")
                docs = [self.tokenize(d) for d in docs]
            return [np.asarray(d, np.int32).reshape(-1) for d in docs]

        return self._client.get(
            name, sha256=want_sha, decode=decode,
            description=f"{self.source}/{name}: shard fetch",
            mismatch_exc=mismatch)

    def record_outcome(self, ok: bool) -> bool:
        """Feed the per-source breaker; returns True on the OPEN edge
        (the stream sheds the source exactly once)."""
        return self._client.record_outcome(ok)
