"""Object-store shard abstraction for the streaming data plane.

A trillion-token run does not read local Sequences — it streams shards
from an object store that throttles (HTTP 429), tears reads mid-object,
serves the occasional bit-rotted payload, and sometimes goes away
entirely.  This module is the storage half of that pipeline
(``data/stream.py`` is the ordering/packing half):

- **Manifest + shard codec** — a store is a flat namespace of shard
  blobs plus one ``manifest.json`` naming every shard with its byte
  size, sha256, and document count.  The checksum in the manifest is
  what makes torn reads and corruption *detectable*; the doc counts are
  what make resume seekable without fetching (``stream.py`` walks the
  global document order from counts alone).  The codec is a fixed
  little-endian layout (magic + lengths + payload) so shard bytes — and
  therefore checksums — are identical across hosts and runs.
- :class:`ShardStore` / :class:`LocalShardStore` — the GET surface and
  its local-directory backend (the gs:// backend is the same two
  methods over tensorstore/GCS when a real bucket exists).
- :class:`ChaosStore` — a fault-injecting wrapper with a gs://-shaped
  failure model: transient 5xx-ish errors, 429 throttling with a
  retry-after, latency spikes, torn (short) reads, checksum-corrupted
  payloads, and hard-dead stores.  Faults are a pure function of
  ``(seed, shard name, attempt)`` so the same seed yields the same
  fault schedule regardless of fetch order — the property the bitwise
  chaos gates stand on.
- :class:`StoreClient` — ALL store GETs go through this one path: the
  shared retry/backoff core (``utils/retry.py``, the same policy object
  the HTTP client and checkpoint I/O use), checksum verification
  against the manifest, decode, per-source :class:`CircuitBreaker`
  bookkeeping, and the ``store_gets`` / ``shard_fetch_retries``
  counters.  A GET that stays bad across the retry budget raises typed
  :class:`~torchacc_tpu.errors.ShardCorruptionError` /
  ``DataLoaderError`` — the caller (``stream.py``) quarantines the
  shard and moves on.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
import zlib
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence

import numpy as np

from torchacc_tpu.errors import DataLoaderError, ShardCorruptionError
from torchacc_tpu.resilience.chaos import failpoint
from torchacc_tpu.utils.logger import logger
from torchacc_tpu.utils.retry import CircuitBreaker, RetryPolicy, retry_call

_MAGIC = b"TASH1\n"
MANIFEST_NAME = "manifest.json"


class ThrottleError(OSError):
    """An HTTP-429-shaped rejection: the backend is alive but pacing
    us.  ``retry_after_s`` is honoured by the shared retry core (the
    backoff sleep is at least that long)."""

    def __init__(self, message: str, retry_after_s: float = 0.05):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)


# -- shard codec ---------------------------------------------------------------

def encode_shard(docs: Sequence[Any], *, kind: str = "tokens") -> bytes:
    """Serialise documents into one shard blob.

    ``kind='tokens'``: each doc is an int32 token array.  ``'text'``:
    each doc is a str (tokenized online at read time).  Fixed layout —
    magic, kind byte, uint32 ndocs, uint32 lengths, payload — so the
    bytes (and the manifest checksum) are platform-independent."""
    if kind not in ("tokens", "text"):
        raise ValueError(f"unknown shard kind {kind!r}")
    if kind == "tokens":
        payloads = [np.asarray(d, np.int32).reshape(-1).tobytes()
                    for d in docs]
        lengths = [len(p) // 4 for p in payloads]
    else:
        payloads = [str(d).encode("utf-8") for d in docs]
        lengths = [len(p) for p in payloads]
    head = (_MAGIC + (b"T" if kind == "tokens" else b"X")
            + np.uint32(len(docs)).tobytes()
            + np.asarray(lengths, "<u4").tobytes())
    return head + b"".join(payloads)


def decode_shard(data: bytes) -> tuple:
    """``(kind, docs)`` from shard bytes; raises
    :class:`ShardCorruptionError` on any structural damage (bad magic,
    truncation, trailing garbage)."""
    def bad(reason: str) -> ShardCorruptionError:
        return ShardCorruptionError(
            f"shard payload undecodable: {reason}", reason=reason)
    if len(data) < len(_MAGIC) + 5 or not data.startswith(_MAGIC):
        raise bad("bad magic")
    kind_b = data[len(_MAGIC):len(_MAGIC) + 1]
    if kind_b not in (b"T", b"X"):
        raise bad(f"unknown kind byte {kind_b!r}")
    kind = "tokens" if kind_b == b"T" else "text"
    off = len(_MAGIC) + 1
    ndocs = int(np.frombuffer(data[off:off + 4], "<u4")[0])
    off += 4
    if len(data) < off + 4 * ndocs:
        raise bad("truncated length table")
    lengths = np.frombuffer(data[off:off + 4 * ndocs], "<u4").astype(np.int64)
    off += 4 * ndocs
    unit = 4 if kind == "tokens" else 1
    need = off + int(lengths.sum()) * unit
    if len(data) != need:
        raise bad(f"payload is {len(data) - off} bytes, header says "
                  f"{need - off}")
    docs: List[Any] = []
    for ln in lengths:
        n = int(ln) * unit
        chunk = data[off:off + n]
        off += n
        if kind == "tokens":
            docs.append(np.frombuffer(chunk, "<i4").astype(np.int32))
        else:
            try:
                docs.append(chunk.decode("utf-8"))
            except UnicodeDecodeError as e:
                raise bad(f"undecodable text doc: {e}") from e
    return kind, docs


# -- stores --------------------------------------------------------------------

class ShardStore:
    """The GET surface every backend implements: one manifest, byte
    blobs by name.  Implementations raise ``OSError`` (or subclasses
    like :class:`ThrottleError`) for transport failures — the
    :class:`StoreClient` owns retries; stores stay retry-free."""

    def manifest(self) -> Dict[str, Any]:
        raise NotImplementedError

    def get(self, name: str) -> bytes:
        raise NotImplementedError


class LocalShardStore(ShardStore):
    """Directory-backed store: shards are files under ``root``,
    ``manifest.json`` beside them (what :func:`write_store` lays out)."""

    def __init__(self, root: str):
        self.root = str(root)

    def manifest(self) -> Dict[str, Any]:
        with open(os.path.join(self.root, MANIFEST_NAME)) as f:
            return json.load(f)

    def get(self, name: str) -> bytes:
        if os.sep in name or name.startswith("."):
            raise DataLoaderError(f"illegal shard name {name!r}")
        with open(os.path.join(self.root, name), "rb") as f:
            return f.read()


def write_store(root: str, docs: Sequence[Any], *, source: str,
                shard_docs: int = 64, kind: str = "tokens"
                ) -> Dict[str, Any]:
    """Shard ``docs`` into ``root`` and write the manifest; returns the
    manifest dict.  The builder the tests/bench use — a production
    ingest job writes the same layout into a bucket."""
    os.makedirs(root, exist_ok=True)
    shards: List[Dict[str, Any]] = []
    for i in range(0, max(len(docs), 1), shard_docs):
        chunk = docs[i:i + shard_docs]
        if not len(chunk):
            break
        name = f"{source}-{i // shard_docs:05d}.tash"
        blob = encode_shard(chunk, kind=kind)
        with open(os.path.join(root, name), "wb") as f:
            f.write(blob)
        shards.append({
            "name": name, "docs": len(chunk), "bytes": len(blob),
            "sha256": hashlib.sha256(blob).hexdigest(), "kind": kind,
        })
    manifest = {"version": 1, "source": source, "shards": shards}
    tmp = os.path.join(root, MANIFEST_NAME + ".tmp")
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1)
    os.replace(tmp, os.path.join(root, MANIFEST_NAME))
    return manifest


# -- fault injection -----------------------------------------------------------

class ChaosStore(ShardStore):
    """gs://-shaped fault model around any :class:`ShardStore`.

    Per-shard fault plans are derived once from ``(seed, shard name)``
    and consumed per GET *attempt*, so the schedule is deterministic
    under any fetch order and any retry policy:

    - ``transient_rate``: the shard's first 1–2 GETs raise ``OSError``
      (a 5xx / connection reset), then succeed;
    - ``throttle_rate``: the first GET raises :class:`ThrottleError`
      (429 + retry-after), then succeeds;
    - ``torn_rate``: the first GET returns a SHORT read (truncated
      bytes — checksum catches it), then succeeds;
    - ``latency_s`` / ``latency_rate``: the GET sleeps first (the
      ``data_wait`` SLO regression hook);
    - ``corrupt_rate`` / ``corrupt_shards``: the payload is bit-flipped
      on EVERY read — permanent damage, the quarantine path;
    - ``dead``: every GET raises — a source that fell off the network
      (the breaker-shed path).

    A shard draws at most one of transient/throttle/torn (priority in
    that order) so fault budgets stay predictable per shard.
    """

    def __init__(self, inner: ShardStore, *, seed: int = 0,
                 transient_rate: float = 0.0, throttle_rate: float = 0.0,
                 torn_rate: float = 0.0, corrupt_rate: float = 0.0,
                 corrupt_shards: Iterable[str] = (),
                 latency_s: float = 0.0, latency_rate: float = 0.0,
                 dead: bool = False,
                 sleep: Callable[[float], None] = time.sleep):
        self.inner = inner
        self.seed = int(seed)
        self.transient_rate = float(transient_rate)
        self.throttle_rate = float(throttle_rate)
        self.torn_rate = float(torn_rate)
        self.corrupt_rate = float(corrupt_rate)
        self.corrupt_shards = set(corrupt_shards)
        self.latency_s = float(latency_s)
        self.latency_rate = float(latency_rate)
        self.dead = bool(dead)
        self._sleep = sleep
        self._attempts: Dict[str, int] = {}
        self.injected: Dict[str, int] = {}   # fault kind -> count
        self.slept_s = 0.0                   # total injected latency

    def manifest(self) -> Dict[str, Any]:
        if self.dead:
            raise OSError("chaos: store is dead (manifest)")
        return self.inner.manifest()

    def _plan(self, name: str) -> Dict[str, Any]:
        import random as _random
        rng = _random.Random(
            zlib.crc32(f"{self.seed}|{name}".encode()))
        r = rng.random()
        fault, n = None, 0
        if r < self.transient_rate:
            fault, n = "transient", 1 + int(rng.random() * 2)
        elif r < self.transient_rate + self.throttle_rate:
            fault, n = "throttle", 1
        elif r < self.transient_rate + self.throttle_rate + self.torn_rate:
            fault, n = "torn", 1
        return {
            "fault": fault, "n": n,
            "corrupt": (name in self.corrupt_shards
                        or rng.random() < self.corrupt_rate),
            "latency": rng.random() < self.latency_rate,
        }

    def _count(self, kind: str) -> None:
        self.injected[kind] = self.injected.get(kind, 0) + 1

    def get(self, name: str) -> bytes:
        if self.dead:
            self._count("dead")
            raise OSError(f"chaos: store is dead (GET {name})")
        plan = self._plan(name)
        attempt = self._attempts.get(name, 0)
        self._attempts[name] = attempt + 1
        if plan["latency"] and attempt == 0:
            self._count("latency")
            logger.warning(f"chaos: {self.latency_s:.2f}s latency spike "
                           f"on GET {name}")
            self._sleep(self.latency_s)
            self.slept_s += self.latency_s
        if plan["fault"] is not None and attempt < plan["n"]:
            self._count(plan["fault"])
            if plan["fault"] == "transient":
                raise OSError(f"chaos: transient store error on GET "
                              f"{name} (attempt {attempt})")
            if plan["fault"] == "throttle":
                raise ThrottleError(
                    f"chaos: 429 on GET {name} (attempt {attempt})",
                    retry_after_s=0.01)
            data = self.inner.get(name)
            return data[:max(len(data) // 2, 1)]     # torn read
        data = self.inner.get(name)
        if plan["corrupt"]:
            self._count("corrupt")
            buf = bytearray(data)
            buf[len(buf) // 2] ^= 0x40               # one flipped bit
            return bytes(buf)
        return data


# -- the one GET path ----------------------------------------------------------

class StoreClient:
    """Retrying, checksum-verifying, breaker-tracking shard reader for
    ONE source.  Every GET: ``store.get`` → sha256 vs manifest → decode
    (→ tokenize for text shards), all inside the shared retry core; a
    checksum/decode failure is retried (torn reads are transient), and
    the LAST failure propagates typed for ``stream.py`` to quarantine.

    ``on_wait(seconds)`` fires before every backoff sleep — the
    in-retry heartbeat seam (``AsyncLoader`` reads :attr:`in_retry` so
    a slow-but-retrying source never trips ``HangError``)."""

    def __init__(self, store: ShardStore, *, source: str,
                 policy: Optional[RetryPolicy] = None,
                 failure_budget: int = 3,
                 breaker_cooldown_s: float = 30.0,
                 tokenize: Optional[Callable[[str], Any]] = None,
                 sleep: Callable[[float], None] = time.sleep,
                 on_wait: Optional[Callable[[float], None]] = None):
        self.store = store
        self.source = str(source)
        self.policy = policy if policy is not None else RetryPolicy(
            max_retries=3, base_delay_s=0.05, max_delay_s=1.0,
            retry_on=(OSError, ShardCorruptionError))
        self.breaker = CircuitBreaker(failure_threshold=max(
            int(failure_budget), 1), cooldown_s=breaker_cooldown_s)
        self.tokenize = tokenize
        self._sleep = sleep
        self._on_wait = on_wait
        self._retrying = 0           # threads currently inside a backoff
        self._entries: Optional[Dict[str, Dict[str, Any]]] = None

    @property
    def in_retry(self) -> bool:
        return self._retrying > 0

    def manifest_entries(self) -> Dict[str, Dict[str, Any]]:
        """name -> manifest entry, fetched once through the retry
        core (a dead store fails HERE, typed)."""
        if self._entries is None:
            try:
                man = retry_call(self.store.manifest, policy=self.policy,
                                 description=f"{self.source}: manifest",
                                 counter="shard_fetch_retries",
                                 sleep=self._retry_sleep)
            except Exception as e:
                raise DataLoaderError(
                    f"source {self.source!r}: manifest unreadable "
                    f"({e!r})") from e
            self._entries = {s["name"]: s for s in man.get("shards", [])}
        return self._entries

    def _retry_sleep(self, seconds: float) -> None:
        self._retrying += 1
        try:
            if self._on_wait is not None:
                self._on_wait(seconds)
            self._sleep(seconds)
        finally:
            self._retrying -= 1

    def get_docs(self, name: str) -> List[Any]:
        """Fetch + verify + decode one shard into its document list.
        Raises :class:`ShardCorruptionError` (persistent corruption) or
        ``OSError`` (transport, retries exhausted); the caller owns the
        quarantine verdict and the breaker's failure edge."""
        from torchacc_tpu.utils.metrics import counters
        entry = self.manifest_entries().get(name)
        if entry is None:
            raise DataLoaderError(
                f"source {self.source!r}: shard {name!r} is not in the "
                "manifest")
        want_sha = entry.get("sha256")

        def once() -> List[Any]:
            failpoint("store.get", source=self.source, shard=name)
            counters.inc("store_gets")
            data = self.store.get(name)
            if want_sha is not None:
                got = hashlib.sha256(data).hexdigest()
                if got != want_sha:
                    raise ShardCorruptionError(
                        f"{self.source}/{name}: sha256 {got[:12]} != "
                        f"manifest {want_sha[:12]} (torn read or "
                        "corruption)", source=self.source, shard=name,
                        reason="checksum mismatch")
            kind, docs = decode_shard(data)
            if kind == "text":
                if self.tokenize is None:
                    raise DataLoaderError(
                        f"{self.source}/{name} holds text docs but the "
                        "source has no tokenizer")
                docs = [self.tokenize(d) for d in docs]
            return [np.asarray(d, np.int32).reshape(-1) for d in docs]

        return retry_call(
            once, policy=self.policy,
            description=f"{self.source}/{name}: shard fetch",
            counter="shard_fetch_retries", sleep=self._retry_sleep)

    def record_outcome(self, ok: bool) -> bool:
        """Feed the per-source breaker; returns True on the OPEN edge
        (the stream sheds the source exactly once)."""
        if ok:
            self.breaker.record_success()
            return False
        return self.breaker.record_failure()
