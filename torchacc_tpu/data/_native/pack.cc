// Native sequence packer: the hot host-side loop of the input pipeline.
//
// TPU-native counterpart of the reference's varlen machinery: instead of
// CUDA varlen kernels fed by cu_seqlens (reference ops/flash_attn.py
// varlen paths), documents are packed into fixed-length rows with
// segment ids — static shapes for XLA, zero recompiles — and the Pallas
// kernel masks across segment boundaries.  Packing runs per batch on the
// host data path (reference: BucketingParallelLoader worker threads,
// core/async_loader.py), so it is implemented natively.
//
// Algorithm: first-fit-decreasing bin packing over row capacity, stable
// within equal lengths.  Exposed via a C ABI for ctypes.
//
// Build: g++ -O3 -shared -fPIC -o libpack.so pack.cc

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <numeric>
#include <vector>

extern "C" {

// Plan the packing: given doc lengths and row capacity, assign each doc a
// (row, offset).  Returns the number of rows used, or -1 on error.
// docs longer than seq_len are truncated to seq_len.
int64_t pack_plan(const int64_t* lengths, int64_t n_docs, int64_t seq_len,
                  int64_t* row_of_doc, int64_t* offset_of_doc) {
  if (n_docs <= 0 || seq_len <= 0) return -1;
  std::vector<int64_t> order(n_docs);
  std::iota(order.begin(), order.end(), 0);
  std::stable_sort(order.begin(), order.end(),
                   [&](int64_t a, int64_t b) {
                     return lengths[a] > lengths[b];
                   });
  std::vector<int64_t> space;  // free space per row
  for (int64_t idx : order) {
    int64_t len = std::min<int64_t>(lengths[idx], seq_len);
    if (len <= 0) len = 0;
    // first fit
    int64_t row = -1;
    for (size_t r = 0; r < space.size(); ++r) {
      if (space[r] >= len) { row = static_cast<int64_t>(r); break; }
    }
    if (row < 0) {
      row = static_cast<int64_t>(space.size());
      space.push_back(seq_len);
    }
    row_of_doc[idx] = row;
    offset_of_doc[idx] = seq_len - space[row];
    space[row] -= len;
  }
  return static_cast<int64_t>(space.size());
}

// Materialise the packed batch. tokens: concatenated docs; doc_starts has
// n_docs+1 entries.  out_* are [n_rows, seq_len], pre-filled by caller
// with pad_id / -1 / 0.  Returns 0 on success.
int64_t pack_fill(const int32_t* tokens, const int64_t* doc_starts,
                  int64_t n_docs, int64_t seq_len,
                  const int64_t* row_of_doc, const int64_t* offset_of_doc,
                  int32_t* out_tokens, int32_t* out_segments,
                  int32_t* out_positions) {
  for (int64_t d = 0; d < n_docs; ++d) {
    int64_t len = doc_starts[d + 1] - doc_starts[d];
    if (len > seq_len) len = seq_len;
    int64_t row = row_of_doc[d];
    int64_t off = offset_of_doc[d];
    if (off + len > seq_len) return -1;
    int32_t* trow = out_tokens + row * seq_len + off;
    int32_t* srow = out_segments + row * seq_len + off;
    int32_t* prow = out_positions + row * seq_len + off;
    std::memcpy(trow, tokens + doc_starts[d], len * sizeof(int32_t));
    for (int64_t i = 0; i < len; ++i) {
      srow[i] = static_cast<int32_t>(d);
      prow[i] = static_cast<int32_t>(i);
    }
  }
  return 0;
}

}  // extern "C"
