"""Autoregressive generation: KV-cache decode (one jitted scan) with a
full-prefix-recompute fallback.

Beyond the reference (TorchAcc is training-only; its accuracy benchmark
shells out to vLLM for inference).  The cached path runs a prefill
forward that banks every layer's rotated k / raw v into the flax
``cache`` collection, then decodes all ``max_new_tokens`` steps inside
ONE ``lax.scan`` under one jit — no per-token host sync, no prefix
recompute; eos handling is pure masking inside the scan.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp


def _sample(logits, rng, temperature):
    if temperature > 0:
        return jax.random.categorical(rng, logits / temperature)
    return jnp.argmax(logits, axis=-1)


@functools.partial(jax.jit, static_argnames=("model", "dec_model",
                                             "temperature", "max_new",
                                             "eos_id"))
def _generate_cached(model, dec_model, params, prompt_ids, rng,
                     temperature, max_new, eos_id):
    b, p = prompt_ids.shape

    # prefill: logits for the whole prompt + per-layer kv cache
    logits, vars_ = model.apply({"params": params}, prompt_ids,
                                mutable=["cache"])
    cache = vars_["cache"]
    rng, sub = jax.random.split(rng)
    first = _sample(logits[:, p - 1], sub, temperature).astype(jnp.int32)
    done0 = jnp.zeros((b,), jnp.bool_)
    if eos_id is not None:
        done0 = first == eos_id

    def step(carry, pos):
        cache, tok, done, rng = carry
        positions = jnp.broadcast_to(pos[None], (b, 1))
        logits1, upd = dec_model.apply(
            {"params": params, "cache": cache}, tok[:, None],
            positions=positions, mutable=["cache"])
        rng, sub = jax.random.split(rng)
        nxt = _sample(logits1[:, 0], sub, temperature).astype(jnp.int32)
        if eos_id is not None:
            nxt = jnp.where(done, jnp.int32(eos_id), nxt)
            done = done | (nxt == eos_id)
        return (upd["cache"], nxt, done, rng), nxt

    (_, _, _, _), rest = jax.lax.scan(
        step, (cache, first, done0, rng),
        jnp.arange(p, p + max_new - 1, dtype=jnp.int32))
    # the in-scan done-freezing already pins every token after a row's
    # first eos to eos
    toks = jnp.concatenate([first[:, None], rest.T.astype(jnp.int32)],
                           axis=1)
    return jnp.concatenate([prompt_ids, toks], axis=1)


def generate(
    model,
    params,
    prompt_ids: jax.Array,
    *,
    max_new_tokens: int = 32,
    temperature: float = 0.0,
    rng: Optional[jax.Array] = None,
    eos_id: Optional[int] = None,
    use_cache: bool = True,
) -> jax.Array:
    """Decode ``max_new_tokens`` after ``prompt_ids`` [b, p].

    ``use_cache=True`` (default, zoo models): prefill + single-scan
    KV-cache decode — O(n) attention reads, one compile, zero per-token
    host syncs.  ``use_cache=False`` or non-zoo models: full-prefix
    recompute fallback (O(n^2) compute, still one compile).
    temperature 0 = greedy; eos_id freezes finished rows at eos.
    """
    b, p = prompt_ids.shape
    if rng is None:
        rng = jax.random.PRNGKey(0)
    cfg = getattr(model, "cfg", None)
    # window/ALiBi decode runs through the cache branch (q_offset aligns
    # the decode-row geometry); pp/cp decode uses the full-forward
    # fallback (distributed decode is out of the reference's scope too —
    # TorchAcc is training-only and shells out to vLLM for inference)
    can_cache = (use_cache and cfg is not None
                 and getattr(cfg, "pp_size", 1) == 1
                 and not getattr(cfg, "context_parallel", False))
    if max_new_tokens <= 0:
        return prompt_ids
    if can_cache:
        total = p + max_new_tokens
        # only a learned position table genuinely caps the length: the
        # cache itself is sized to `total`, and rope/ALiBi extrapolate
        # (max_seq_len is the trained context, not a hard limit)
        if cfg.pos_emb == "learned" and total > cfg.max_seq_len:
            raise ValueError(
                f"prompt + max_new_tokens = {total} exceeds the learned "
                f"position table max_seq_len {cfg.max_seq_len}")
        from torchacc_tpu.models.transformer import TransformerLM
        # cache_len=total: short generations allocate (and attend over)
        # prompt+new positions, not a max_seq_len-sized cache
        pre_model = TransformerLM(dataclasses.replace(cfg, cache_len=total))
        dec_model = TransformerLM(dataclasses.replace(cfg, decode=True,
                                                      cache_len=total))
        return _generate_cached(pre_model, dec_model, params, prompt_ids,
                                rng, float(temperature),
                                int(max_new_tokens), eos_id)
    return _generate_recompute(model, params, prompt_ids,
                               max_new_tokens=max_new_tokens,
                               temperature=temperature, rng=rng,
                               eos_id=eos_id)


# ---------------------------------------------------------------------------
# fallback: full-prefix recompute (works for any (input_ids)->logits model)
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("model", "temperature"))
def _decode_step(model, params, tokens, cur, rng, temperature):
    b = tokens.shape[0]
    logits = model.apply({"params": params}, tokens)
    # logits at position cur-1 predict token cur
    next_logits = jnp.take_along_axis(
        logits, (cur - 1)[None, None, None].repeat(b, 0), axis=1)[:, 0]
    rng, sub = jax.random.split(rng)
    nxt = _sample(next_logits, sub, temperature)
    return tokens.at[:, cur].set(nxt.astype(jnp.int32)), rng


def _generate_recompute(model, params, prompt_ids, *, max_new_tokens,
                        temperature, rng, eos_id):
    b, p = prompt_ids.shape
    total = p + max_new_tokens
    tokens = jnp.zeros((b, total), jnp.int32)
    tokens = tokens.at[:, :p].set(prompt_ids)

    done = jnp.zeros((b,), jnp.bool_)
    for i in range(max_new_tokens):
        cur = jnp.asarray(p + i)
        new_tokens, rng = _decode_step(model, params, tokens, cur, rng,
                                       temperature)
        if eos_id is not None:
            prev = tokens
            new_col = new_tokens[:, p + i]
            new_col = jnp.where(done, eos_id, new_col)
            done = done | (new_col == eos_id)
            tokens = prev.at[:, p + i].set(new_col)
            if bool(done.all()):
                break
        else:
            tokens = new_tokens
    return tokens
