"""Autoregressive generation: KV-cache decode (one jitted scan) with a
full-prefix-recompute fallback.

Beyond the reference (TorchAcc is training-only; its accuracy benchmark
shells out to vLLM for inference).  The cached path runs a prefill
forward that banks every layer's rotated k / raw v into the flax
``cache`` collection, then decodes all ``max_new_tokens`` steps inside
ONE ``lax.scan`` under one jit — no per-token host sync, no prefix
recompute; eos handling is pure masking inside the scan.  Ragged
batches decode via LEFT-padded prompts + ``prompt_mask``.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp


def _sample(logits, rng, temperature, top_k=0, top_p=1.0):
    """Greedy (temperature 0) or temperature sampling with optional
    top-k / nucleus (top-p) truncation (standard decode controls; the
    reference is training-only and defers generation to vLLM)."""
    if temperature <= 0:
        return jnp.argmax(logits, axis=-1)
    logits = logits / temperature
    if top_k and 0 < top_k < logits.shape[-1]:
        # k-th largest as the cutoff (O(V log k), not a full sort).
        # top_k >= vocab is a no-op by definition (the k-th largest is
        # the global min, so nothing is truncated) — skip the full-width
        # lax.top_k sort entirely rather than pay O(V log V) to mask
        # nothing.  Serving replays rely on top_k=V and top_k=0 tracing
        # to the SAME program, so the sampled stream cannot drift on
        # the guard.
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p < 1.0:
        # keep the smallest prefix of descending-prob tokens with
        # cumulative probability > top_p; the argmax is ALWAYS kept
        # (top_p <= 0 must degrade to greedy, not an all--inf row)
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep_sorted = cum - probs < top_p
        keep_sorted = keep_sorted.at[..., 0].set(True)
        kth = jnp.min(jnp.where(keep_sorted, sorted_logits, jnp.inf),
                      axis=-1, keepdims=True)
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    return jax.random.categorical(rng, logits)


def _prompt_geometry(prompt_ids, prompt_mask):
    """(positions, row_len, seg) for a (possibly LEFT-padded ragged)
    prompt: real tokens are right-aligned, so row i's token at column j
    sits at position j - pad_len_i, and sampling at column p-1 is every
    row's last real token."""
    b, p = prompt_ids.shape
    if prompt_mask is not None:
        mask = prompt_mask.astype(jnp.int32)
        positions = jnp.clip(jnp.cumsum(mask, axis=1) - 1, 0, None)
        return positions, jnp.sum(mask, axis=1), mask
    return None, jnp.full((b,), p, jnp.int32), None


def _drive_decode(logits, cache, step_fn, prompt_ids, row_len, rng,
                  temperature, max_new, eos_id, top_k, top_p):
    """Shared decode-scan driver for every cached path: sample the first
    token from the prefill logits, scan ``step_fn`` for the rest with
    eos freezing, and return [b, p + max_new] tokens.

    ``step_fn(cache, tok, positions1) -> (next_logits [b, V], cache)``
    is the only per-path piece (single-device flax apply vs the pp
    stage ring)."""
    b, p = prompt_ids.shape
    rng, sub = jax.random.split(rng)
    first = _sample(logits[:, p - 1], sub, temperature, top_k,
                    top_p).astype(jnp.int32)
    done0 = jnp.zeros((b,), jnp.bool_)
    if eos_id is not None:
        done0 = first == eos_id

    def step(carry, pos):
        cache, tok, done, rng = carry
        # per-row TRUE position of the token being decoded: the cache
        # slot index is uniform (pos) but row i has pad_len_i pads, so
        # its rope position is pos - pad_len_i
        positions1 = (row_len + (pos - p))[:, None]
        next_logits, cache = step_fn(cache, tok, positions1)
        rng, sub = jax.random.split(rng)
        nxt = _sample(next_logits, sub, temperature, top_k,
                      top_p).astype(jnp.int32)
        if eos_id is not None:
            nxt = jnp.where(done, jnp.int32(eos_id), nxt)
            done = done | (nxt == eos_id)
        return (cache, nxt, done, rng), nxt

    (_, _, _, _), rest = jax.lax.scan(
        step, (cache, first, done0, rng),
        jnp.arange(p, p + max_new - 1, dtype=jnp.int32))
    # the in-scan done-freezing already pins every token after a row's
    # first eos to eos
    toks = jnp.concatenate([first[:, None], rest.T.astype(jnp.int32)],
                           axis=1)
    return jnp.concatenate([prompt_ids, toks], axis=1)


@functools.partial(jax.jit, static_argnames=("model", "dec_model",
                                             "temperature", "max_new",
                                             "eos_id", "top_k", "top_p"))
def _generate_cached(model, dec_model, params, prompt_ids, prompt_mask,
                     rng, temperature, max_new, eos_id, top_k, top_p):
    positions, row_len, seg = _prompt_geometry(prompt_ids, prompt_mask)
    pre_kwargs = ({} if seg is None
                  else dict(positions=positions, segment_ids=seg))
    # prefill: logits for the whole prompt + per-layer kv cache
    logits, vars_ = model.apply({"params": params}, prompt_ids,
                                mutable=["cache"], **pre_kwargs)

    def step_fn(cache, tok, positions1):
        # ragged masking in decode is driven by the banked 'seg' cache
        # (written at prefill), not a segment_ids argument
        logits1, upd = dec_model.apply(
            {"params": params, "cache": cache}, tok[:, None],
            positions=positions1, mutable=["cache"])
        return logits1[:, 0], upd["cache"]

    return _drive_decode(logits, vars_["cache"], step_fn, prompt_ids,
                         row_len, rng, temperature, max_new, eos_id,
                         top_k, top_p)


def generate(
    model,
    params,
    prompt_ids: jax.Array,
    *,
    max_new_tokens: int = 32,
    temperature: float = 0.0,
    rng: Optional[jax.Array] = None,
    eos_id: Optional[int] = None,
    use_cache: bool = True,
    prompt_mask: Optional[jax.Array] = None,
    top_k: int = 0,
    top_p: float = 1.0,
    param_dtype: Optional[Any] = None,
) -> jax.Array:
    """Decode ``max_new_tokens`` after ``prompt_ids`` [b, p].

    ``use_cache=True`` (default, zoo models): prefill + single-scan
    KV-cache decode — O(n) attention reads, one compile, zero per-token
    host syncs.  ``use_cache=False`` or non-zoo models: full-prefix
    recompute fallback.

    ``prompt_mask`` [b, p] (1 = real token) enables RAGGED batches:
    prompts must be LEFT-padded (real tokens right-aligned, the standard
    decode convention).  Positions and attention masking account for
    each row's padding; outputs keep the [b, p + max_new] layout.
    Requires the model to follow the ``(input_ids, positions,
    segment_ids)`` call convention (zoo models and the custom-model
    protocol do; a bare ``(input_ids) -> logits`` model works only
    without ``prompt_mask``).

    temperature 0 = greedy; ``top_k``/``top_p`` truncate the sampling
    distribution (ignored when greedy); eos_id freezes finished rows at
    eos.

    ``param_dtype`` (e.g. ``jnp.bfloat16``): cast floating params ONCE
    before decoding.  Training keeps f32 master weights, so without the
    cast every decode step re-reads the full f32 param set from HBM;
    bf16 storage halves that traffic — decode is memory-bound, so this
    is ~the standard serving-precision speedup.  Applied before every
    dispatch (pp stage-ring, layer_pattern, cp, recompute) so all decode
    paths benefit.  None (default) leaves params untouched.
    """
    b, p = prompt_ids.shape
    if param_dtype is not None:
        params = jax.tree.map(
            lambda x: x.astype(param_dtype)
            if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)
            else x, params)
    if rng is None:
        rng = jax.random.PRNGKey(0)
    if prompt_mask is not None:
        m = jnp.asarray(prompt_mask)
        if m.shape != (b, p):
            raise ValueError(f"prompt_mask shape {m.shape} != {(b, p)}")
        try:  # host-side sanity when concrete: left-padded = non-decreasing
            import numpy as _np
            mm = _np.asarray(m).astype(_np.int32)
            if not (_np.diff(mm, axis=1) >= 0).all():
                raise ValueError(
                    "prompt_mask must be LEFT-padded (real tokens "
                    "right-aligned): found a 0 after a 1")
            if not mm[:, -1].all():
                raise ValueError("prompt_mask: last column must be real "
                                 "(left-padding)")
        except jax.errors.TracerArrayConversionError:
            pass
        prompt_mask = m
    cfg = getattr(model, "cfg", None)
    # quantized matmuls are a TRAIN-step feature (delayed-scaling state
    # threads through the train step); decode runs in the compute dtype
    # — strip quant so a quant-trained model generates unmodified (the
    # param layout is identical either way)
    if cfg is not None and getattr(cfg, "quant", "none") != "none":
        from torchacc_tpu.models.transformer import TransformerLM
        cfg = dataclasses.replace(cfg, quant="none")
        if isinstance(model, TransformerLM):
            model = TransformerLM(cfg)
    # window/ALiBi decode runs through the cache branch (q_offset aligns
    # the decode-row geometry).  pp decode runs the stage-ring cached
    # path (_generate_cached_pp — cache stays stage-local, one ring pass
    # per token).  cp decode runs the NORMAL cached path: prefill banks
    # k/v through the cp-attention forward with the cache's slot dim
    # sharded over ('sp','spu') (models/transformer.py), and the decode
    # step's single-token attention over the sharded slots partitions
    # via GSPMD — no full-prefix recompute in either case.
    def _mesh_extent(*axes):
        mesh = jax.sharding.get_abstract_mesh()
        shape = getattr(mesh, "shape", None) or {}
        ext = 1
        for a in axes:
            ext *= int(shape.get(a, 1) or 1)
        return ext

    # the pp stage ring needs a live 'pp' mesh axis of the configured
    # extent AND the zoo param layout; otherwise (e.g. a pp-trained cfg
    # loaded on one host with no mesh) DEMOTE to a pp_size=1 view — the
    # stacked param layout is identical, so single-device execution is
    # exact
    pp_live = (cfg is not None and getattr(cfg, "pp_size", 1) > 1
               and _mesh_extent("pp") == cfg.pp_size
               and isinstance(params, dict) and "layers" in params
               # the pp stage ring applies ScanBlock uniformly — a
               # layer_pattern model must take the pattern path instead
               # (correct per-layer windows; GSPMD still resolves the
               # pp-sharded param slices)
               and not getattr(cfg, "layer_pattern", None))
    if (cfg is not None and getattr(cfg, "pp_size", 1) > 1
            and not pp_live):
        from torchacc_tpu.models.transformer import TransformerLM
        cfg = dataclasses.replace(cfg, pp_size=1, pp_num_micro=1)
        if isinstance(model, TransformerLM):
            model = TransformerLM(cfg)
    cp_cfg = cfg is not None and getattr(cfg, "context_parallel", False)
    can_cache = use_cache and cfg is not None
    if max_new_tokens <= 0:
        return prompt_ids
    total = p + max_new_tokens
    if (can_cache and cfg.pos_emb == "learned"
            and total > cfg.max_seq_len):
        # only a learned position table genuinely caps the length: the
        # cache is sized to `total`, and rope/ALiBi extrapolate
        raise ValueError(
            f"prompt + max_new_tokens = {total} exceeds the learned "
            f"position table max_seq_len {cfg.max_seq_len}")
    lr = getattr(cfg, "rope_longrope", None) if can_cache else None
    if lr is not None and p <= int(lr[2]) < p + max_new_tokens - 1:
        # Phi-3.5/4 longrope CACHE REBUILD at the original-context
        # crossing: keys banked under the short factors become invalid
        # once the sequence exceeds original_max — phi3's intended
        # behaviour (Phi3ForCausalLM.prepare_inputs_for_generation
        # invalidates past_key_values at input length original_max+1)
        # is to re-run the whole prefix under the LONG factors and
        # continue from that cache.  Decode up to the boundary, then
        # recurse with the tokens so far as the prompt: the re-prefill's
        # seq_len exceeds original_max, so it banks long-roped keys.
        # Hoisted ABOVE the pp / layer_pattern dispatches so every
        # cached path gets the rebuild (each phase re-enters the full
        # dispatch).  (transformers 4.57.6's own rebuild runs with a
        # stale single-element cache_position whose causal mask
        # degenerates to full attention over the re-fed prefix —
        # verified acausal; we implement the INTENDED semantics, which
        # equal HF's correct full forward at every step.)
        old_len = int(lr[2])
        n1 = old_len + 1 - p
        rng, r1, r2 = jax.random.split(rng, 3)
        first = generate(model, params, prompt_ids, max_new_tokens=n1,
                         temperature=temperature, rng=r1, eos_id=eos_id,
                         use_cache=True, prompt_mask=prompt_mask,
                         top_k=top_k, top_p=top_p)
        mask2 = None
        if prompt_mask is not None:
            mask2 = jnp.concatenate(
                [jnp.asarray(prompt_mask, jnp.int32),
                 jnp.ones((b, n1), jnp.int32)], axis=1)
        out = generate(model, params, first,
                       max_new_tokens=max_new_tokens - n1,
                       temperature=temperature, rng=r2, eos_id=eos_id,
                       use_cache=True, prompt_mask=mask2,
                       top_k=top_k, top_p=top_p)
        if eos_id is not None:
            # rows frozen at eos in phase 1 (their last token is eos:
            # freezing pins everything after the first eos) must stay
            # frozen — phase 2 has no done-state and would resume them
            done1 = first[:, -1] == eos_id
            tail = jnp.where(done1[:, None], jnp.int32(eos_id),
                             out[:, p + n1:])
            out = jnp.concatenate([out[:, :p + n1], tail], axis=1)
        return out

    if (can_cache and pp_live
            and (not cp_cfg or _mesh_extent("sp", "spu") > 1)):
        # pp x cp composes: the cp attention shard_map nests inside the
        # pp stage ring exactly as in the training path, and the cache's
        # slot sharding rides through the stage-local layout
        return _generate_cached_pp(cfg, params, prompt_ids, prompt_mask,
                                   rng, float(temperature),
                                   int(max_new_tokens), eos_id,
                                   int(top_k), float(top_p))
    if (can_cache and getattr(cfg, "layer_pattern", None)
            and not pp_live and not cp_cfg
            and isinstance(params, dict) and "layers" in params):
        # layer_pattern models cannot decode through model.apply (the
        # scan path cannot vary the per-layer window; TransformerLM
        # rejects pattern+cache) — use the per-layer pattern loop
        return _generate_cached_pattern(
            cfg, params, prompt_ids, prompt_mask, rng,
            float(temperature), int(max_new_tokens), eos_id,
            int(top_k), float(top_p))
    # a cp cfg without a live sp/spu mesh axis falls back to recompute
    # (the cp attention shard_map needs the axes)
    can_cache = (can_cache and not pp_live
                 and getattr(cfg, "pp_size", 1) == 1
                 and not getattr(cfg, "layer_pattern", None)
                 and (not cp_cfg or _mesh_extent("sp", "spu") > 1))
    if can_cache:
        from torchacc_tpu.models.transformer import TransformerLM

        # cache_len=total: short generations allocate (and attend over)
        # prompt+new positions, not a max_seq_len-sized cache
        pre_model = TransformerLM(dataclasses.replace(cfg, cache_len=total))
        dec_model = TransformerLM(dataclasses.replace(cfg, decode=True,
                                                      cache_len=total))
        return _generate_cached(pre_model, dec_model, params, prompt_ids,
                                prompt_mask, rng, float(temperature),
                                int(max_new_tokens), eos_id,
                                int(top_k), float(top_p))
    return _generate_recompute(model, params, prompt_ids,
                               prompt_mask=prompt_mask,
                               max_new_tokens=max_new_tokens,
                               temperature=temperature, rng=rng,
                               eos_id=eos_id, top_k=int(top_k),
                               top_p=float(top_p))


# ---------------------------------------------------------------------------
# pipeline-parallel KV-cache decode (VERDICT r3 next-7)
# ---------------------------------------------------------------------------

def _zoo_embed(cfg, params, ids, positions):
    from torchacc_tpu.models.transformer import _embed_extras

    emb = params["embed_tokens"]["embedding"]
    return _embed_extras(cfg, emb[ids].astype(cfg.dtype), positions,
                         params.get("pos_embed"))


@functools.partial(jax.jit, static_argnames=(
    "cfg", "temperature", "max_new", "eos_id", "top_k", "top_p"))
def _generate_cached_pp(cfg, params, prompt_ids, prompt_mask, rng,
                        temperature, max_new, eos_id, top_k, top_p):
    """KV-cache decode under pipeline parallelism: the banked cache
    stays STAGE-LOCAL (sharded over 'pp' on the layer-chunk dim); each
    token costs one pass over the stage ring (pp.py
    pp_forward_with_cache) — no full-prefix recompute."""
    from torchacc_tpu.models.transformer import head_logits
    from torchacc_tpu.parallel.pp import pp_forward_with_cache

    b, p = prompt_ids.shape
    total = p + max_new
    # the block cfgs run OUTSIDE the pipeline dispatch (pp_size=1): the
    # pipeline structure lives in pp_forward_with_cache itself
    blk_pre = dataclasses.replace(cfg, decode=False, cache_len=total, pp_size=1)
    blk_dec = dataclasses.replace(cfg, decode=True, cache_len=total, pp_size=1)

    positions, row_len, seg = _prompt_geometry(prompt_ids, prompt_mask)
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(p), (b, p))

    x = _zoo_embed(cfg, params, prompt_ids, positions)
    y, cache = pp_forward_with_cache(
        blk_pre, params["layers"], None, x, positions, seg, cfg.pp_size)
    logits = head_logits(cfg, params, y)

    def step_fn(cache, tok, positions1):
        x1 = _zoo_embed(cfg, params, tok[:, None], positions1)
        y1, cache = pp_forward_with_cache(
            blk_dec, params["layers"], cache, x1, positions1, None,
            cfg.pp_size)
        return head_logits(cfg, params, y1)[:, 0], cache

    return _drive_decode(logits, cache, step_fn, prompt_ids, row_len,
                         rng, temperature, max_new, eos_id, top_k,
                         top_p)


# ---------------------------------------------------------------------------
# heterogeneous-layer (gemma2-style) KV-cache decode
# ---------------------------------------------------------------------------

def _pattern_layers_with_cache(cfg, stacked_params, cache, x, positions,
                               seg):
    """Raw per-layer loop threading the kv cache through the canonical
    [L, ...] stacked layout, with each layer's own pattern cfg — the
    scan path cannot vary a static window per layer.  ``cache=None``
    (prefill) creates the banked cache."""
    from torchacc_tpu.models.transformer import ScanBlock, pattern_cfg

    new_layers = []
    for i in range(cfg.num_layers):
        blk = ScanBlock(pattern_cfg(cfg, i))
        variables = {"params": jax.tree.map(
            lambda a, i=i: a[i], stacked_params)}
        if cache is not None:
            variables["cache"] = jax.tree.map(
                lambda a, i=i: a[i], cache)
        (carry, _), vs = blk.apply(variables, (x, positions, seg), None,
                                   mutable=["cache"])
        x = carry[0]
        new_layers.append(vs["cache"])
    new_cache = jax.tree.map(lambda *xs: jnp.stack(xs), *new_layers)
    return x, new_cache


@functools.partial(jax.jit, static_argnames=(
    "cfg", "temperature", "max_new", "eos_id", "top_k", "top_p"))
def _generate_cached_pattern(cfg, params, prompt_ids, prompt_mask, rng,
                             temperature, max_new, eos_id, top_k, top_p):
    """KV-cache decode for layer_pattern models: same scaffold as the
    other cached paths, with the per-layer pattern loop as forward."""
    from torchacc_tpu.models.transformer import head_logits

    b, p = prompt_ids.shape
    total = p + max_new
    blk_pre = dataclasses.replace(cfg, decode=False, cache_len=total)
    blk_dec = dataclasses.replace(cfg, decode=True, cache_len=total)

    positions, row_len, seg = _prompt_geometry(prompt_ids, prompt_mask)
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(p), (b, p))

    x = _zoo_embed(cfg, params, prompt_ids, positions)
    y, cache = _pattern_layers_with_cache(
        blk_pre, params["layers"], None, x, positions, seg)
    logits = head_logits(cfg, params, y)

    def step_fn(cache, tok, positions1):
        x1 = _zoo_embed(cfg, params, tok[:, None], positions1)
        y1, cache = _pattern_layers_with_cache(
            blk_dec, params["layers"], cache, x1, positions1, None)
        return head_logits(cfg, params, y1)[:, 0], cache

    return _drive_decode(logits, cache, step_fn, prompt_ids, row_len,
                         rng, temperature, max_new, eos_id, top_k,
                         top_p)


# ---------------------------------------------------------------------------
# fallback: full-prefix recompute (works for any (input_ids)->logits model)
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("model", "temperature",
                                             "top_k", "top_p"))
def _decode_step(model, params, tokens, mask_full, cur, rng, temperature,
                 top_k, top_p):
    """One full-prefix recompute step over the fixed [b, total] buffer.

    Positions of slots past the live prefix CLAMP to the current
    position: those slots are causally invisible to the logits read at
    ``cur - 1``, and clamping keeps length-dependent rope variants
    (longrope's short/long regime switch keys off ``max(positions)``)
    seeing the CURRENT sequence length instead of the padded buffer —
    HF full-forward semantics."""
    b, total = tokens.shape
    if mask_full is not None:
        positions = jnp.clip(jnp.cumsum(mask_full, axis=1) - 1, 0, None)
        # per-row cap at the position of the newest live slot (positions
        # are non-decreasing along the row)
        cap = jnp.take_along_axis(
            positions, (cur - 1)[None, None].repeat(b, 0), axis=1)
        positions = jnp.minimum(positions, cap)
        logits = model.apply({"params": params}, tokens,
                             positions=positions, segment_ids=mask_full)
    elif getattr(model, "cfg", None) is not None:
        positions = jnp.minimum(jnp.arange(total), cur - 1)
        positions = jnp.broadcast_to(positions[None], (b, total))
        logits = model.apply({"params": params}, tokens,
                             positions=positions)
    else:
        # bare (input_ids) -> logits models take no positions kwarg
        # (and have no length-dependent rope to clamp for)
        logits = model.apply({"params": params}, tokens)
    # logits at position cur-1 predict token cur
    next_logits = jnp.take_along_axis(
        logits, (cur - 1)[None, None, None].repeat(b, 0), axis=1)[:, 0]
    rng, sub = jax.random.split(rng)
    nxt = _sample(next_logits, sub, temperature, top_k, top_p)
    return tokens.at[:, cur].set(nxt.astype(jnp.int32)), rng


def _generate_recompute(model, params, prompt_ids, *, max_new_tokens,
                        temperature, rng, eos_id, prompt_mask=None,
                        top_k=0, top_p=1.0):
    b, p = prompt_ids.shape
    total = p + max_new_tokens
    tokens = jnp.zeros((b, total), jnp.int32)
    tokens = tokens.at[:, :p].set(prompt_ids)
    mask_full = None
    if prompt_mask is not None:
        # generated tokens are always real
        mask_full = jnp.concatenate(
            [prompt_mask.astype(jnp.int32),
             jnp.ones((b, max_new_tokens), jnp.int32)], axis=1)

    done = jnp.zeros((b,), jnp.bool_)
    for i in range(max_new_tokens):
        cur = jnp.asarray(p + i)
        new_tokens, rng = _decode_step(model, params, tokens, mask_full,
                                       cur, rng, temperature, top_k, top_p)
        if eos_id is not None:
            prev = tokens
            new_col = new_tokens[:, p + i]
            new_col = jnp.where(done, eos_id, new_col)
            done = done | (new_col == eos_id)
            tokens = prev.at[:, p + i].set(new_col)
            if bool(done.all()):
                break
        else:
            tokens = new_tokens
    return tokens
