"""Greedy/sampling text generation (full-prefix recompute, no KV cache).

Beyond the reference (TorchAcc is training-only; its accuracy benchmark
shells out to vLLM for inference).  Each decode step re-runs the padded
forward — O(n^2) compute but a single static shape, so exactly one
compile; right for eval/sanity generation, not for serving.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("model", "temperature"))
def _decode_step(model, params, tokens, cur, rng, temperature):
    b = tokens.shape[0]
    logits = model.apply({"params": params}, tokens)
    # logits at position cur-1 predict token cur
    next_logits = jnp.take_along_axis(
        logits, (cur - 1)[None, None, None].repeat(b, 0), axis=1)[:, 0]
    rng, sub = jax.random.split(rng)
    if temperature > 0:
        nxt = jax.random.categorical(sub, next_logits / temperature)
    else:
        nxt = jnp.argmax(next_logits, axis=-1)
    return tokens.at[:, cur].set(nxt.astype(jnp.int32)), rng


def generate(
    model,
    params,
    prompt_ids: jax.Array,
    *,
    max_new_tokens: int = 32,
    temperature: float = 0.0,
    rng: Optional[jax.Array] = None,
    eos_id: Optional[int] = None,
) -> jax.Array:
    """Autoregressive decoding via full-prefix recompute.

    Simple and correct: each step re-runs the (jitted, padded-to-max)
    forward on the prefix — O(n^2) but static-shaped, so exactly one
    compile.  Returns [batch, prompt+max_new_tokens].  temperature 0 =
    greedy; eos_id stops per-sequence growth (positions after a
    sequence's eos hold eos; once every sequence has finished, the
    remaining tail stays 0-padded).
    """
    b, p = prompt_ids.shape
    total = p + max_new_tokens
    if rng is None:
        rng = jax.random.PRNGKey(0)

    tokens = jnp.zeros((b, total), jnp.int32)
    tokens = tokens.at[:, :p].set(prompt_ids)

    done = jnp.zeros((b,), jnp.bool_)
    for i in range(max_new_tokens):
        cur = jnp.asarray(p + i)
        # module-level jitted step: repeated generate() calls with the
        # same shapes reuse one compiled executable
        new_tokens, rng = _decode_step(model, params, tokens, cur, rng,
                                       temperature)
        if eos_id is not None:
            prev = tokens
            new_col = new_tokens[:, p + i]
            new_col = jnp.where(done, eos_id, new_col)
            done = done | (new_col == eos_id)
            tokens = prev.at[:, p + i].set(new_col)
            if bool(done.all()):
                break
        else:
            tokens = new_tokens
    return tokens
