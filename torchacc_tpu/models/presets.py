"""Model-zoo presets covering the reference's benchmark targets
(BASELINE.json configs: tiny GPT, Llama-3-8B, Llama-3-70B, Qwen-7B,
Mixtral-8x7B)."""

from __future__ import annotations

import jax.numpy as jnp

from torchacc_tpu.models.transformer import ModelConfig


def gpt2_tiny(**kw) -> ModelConfig:
    """The reference's tiny-GPT benchmark model (benchmarks/transformer.py
    --nlayer etc.)."""
    defaults = dict(vocab_size=50257, hidden_size=256, num_layers=4, num_heads=8,
        max_seq_len=512, pos_emb="learned", norm="layernorm",
        activation="gelu", tie_embeddings=True, rope_theta=10000.0)
    defaults.update(kw)
    return ModelConfig(**defaults)


def gpt2(**kw) -> ModelConfig:
    defaults = dict(vocab_size=50257, hidden_size=768, num_layers=12, num_heads=12,
        max_seq_len=1024, pos_emb="learned", norm="layernorm",
        activation="gelu", tie_embeddings=True, rope_theta=10000.0)
    defaults.update(kw)
    return ModelConfig(**defaults)


def llama_tiny(**kw) -> ModelConfig:
    defaults = dict(vocab_size=32000, hidden_size=256, num_layers=4, num_heads=8,
        num_kv_heads=4, intermediate_size=688, max_seq_len=2048)
    defaults.update(kw)
    return ModelConfig(**defaults)


def llama3_8b(**kw) -> ModelConfig:
    defaults = dict(vocab_size=128256, hidden_size=4096, num_layers=32, num_heads=32,
        num_kv_heads=8, intermediate_size=14336, max_seq_len=8192,
        rope_theta=500000.0)
    defaults.update(kw)
    return ModelConfig(**defaults)


def llama3_70b(**kw) -> ModelConfig:
    defaults = dict(vocab_size=128256, hidden_size=8192, num_layers=80, num_heads=64,
        num_kv_heads=8, intermediate_size=28672, max_seq_len=8192,
        rope_theta=500000.0)
    defaults.update(kw)
    return ModelConfig(**defaults)


def qwen2_7b(**kw) -> ModelConfig:
    defaults = dict(vocab_size=152064, hidden_size=3584, num_layers=28, num_heads=28,
        num_kv_heads=4, intermediate_size=18944, max_seq_len=32768,
        qkv_bias=True, rope_theta=1000000.0)
    defaults.update(kw)
    return ModelConfig(**defaults)


def gemma_2b(**kw) -> ModelConfig:
    defaults = dict(vocab_size=256000, hidden_size=2048, num_layers=18,
        num_heads=8, num_kv_heads=1, head_dim=256, intermediate_size=16384,
        max_seq_len=8192, rope_theta=10000.0, norm="rmsnorm1p",
        activation="geglu", embed_scale=True, tie_embeddings=True,
        norm_eps=1e-6)
    defaults.update(kw)
    return ModelConfig(**defaults)


def gemma_7b(**kw) -> ModelConfig:
    defaults = dict(vocab_size=256000, hidden_size=3072, num_layers=28,
        num_heads=16, num_kv_heads=16, head_dim=256, intermediate_size=24576,
        max_seq_len=8192, rope_theta=10000.0, norm="rmsnorm1p",
        activation="geglu", embed_scale=True, tie_embeddings=True,
        norm_eps=1e-6)
    defaults.update(kw)
    return ModelConfig(**defaults)


def gemma2_2b(**kw) -> ModelConfig:
    # HF google/gemma-2-2b config.json (sandwich norms, alternating
    # sliding/global attention, score + logit soft-capping, fixed query
    # scale query_pre_attn_scalar=256)
    defaults = dict(vocab_size=256000, hidden_size=2304, num_layers=26,
        num_heads=8, num_kv_heads=4, head_dim=256, intermediate_size=9216,
        max_seq_len=8192, rope_theta=10000.0, norm="rmsnorm1p",
        activation="geglu", embed_scale=True, tie_embeddings=True,
        norm_eps=1e-6, sandwich_norms=True,
        layer_pattern=("sliding", "global"), window=(4095, -1),
        attn_logit_softcap=50.0, logit_softcap=30.0,
        query_scale=256.0 ** -0.5)
    defaults.update(kw)
    return ModelConfig(**defaults)


def gemma3_1b(**kw) -> ModelConfig:
    # HF google/gemma-3-1b-pt config.json (5:1 sliding/global pattern,
    # dual rope bases, qk-norm; no soft-capping)
    defaults = dict(vocab_size=262144, hidden_size=1152, num_layers=26,
        num_heads=4, num_kv_heads=1, head_dim=256, intermediate_size=6912,
        max_seq_len=32768, rope_theta=1000000.0, rope_local_theta=10000.0,
        norm="rmsnorm1p", activation="geglu", embed_scale=True,
        tie_embeddings=True, norm_eps=1e-6, sandwich_norms=True,
        qk_norm=True, layer_pattern=("sliding",) * 5 + ("global",),
        window=(511, -1), query_scale=256.0 ** -0.5)
    defaults.update(kw)
    return ModelConfig(**defaults)


def mixtral_8x7b(**kw) -> ModelConfig:
    defaults = dict(vocab_size=32000, hidden_size=4096, num_layers=32, num_heads=32,
        num_kv_heads=8, intermediate_size=14336, max_seq_len=32768,
        rope_theta=1000000.0, num_experts=8, num_experts_per_tok=2)
    defaults.update(kw)
    return ModelConfig(**defaults)


PRESETS = {
    "gpt2-tiny": gpt2_tiny,
    "gpt2": gpt2,
    "llama-tiny": llama_tiny,
    "llama3-8b": llama3_8b,
    "llama3-70b": llama3_70b,
    "qwen2-7b": qwen2_7b,
    "gemma-2b": gemma_2b,
    "gemma-7b": gemma_7b,
    "gemma2-2b": gemma2_2b,
    "gemma3-1b": gemma3_1b,
    "mixtral-8x7b": mixtral_8x7b,
}


def get_preset(name: str, **kw) -> ModelConfig:
    if name not in PRESETS:
        raise KeyError(f"unknown preset {name!r}; have {sorted(PRESETS)}")
    return PRESETS[name](**kw)
