"""HuggingFace model ingestion: torch state_dict -> torchacc_tpu params.

The reference accelerates HF models in place via monkeypatching
(utils/patch.py:61-301, qwen_patch.py, accelerate_hf_trainer.py) because
it shares torch's module system.  The TPU-native framework instead
*converts*: an HF checkpoint's weights are mapped onto the zoo's
:class:`TransformerLM` layout (scan-stacked layers), after which every
framework feature (FSDP/TP/PP/CP shardings, Pallas kernels, remat,
checkpointing) applies with zero model-specific code.

Supported families: Llama (1/2/3, incl. 3.1's banded rope scaling),
Qwen2 (qkv bias), Qwen3 (qk-norm), Mistral (sliding window), Gemma v1
(1+w RMSNorm, geglu, scaled embeddings), Gemma2/3 (layer patterns,
sandwich norms, softcaps), Mixtral and Qwen3-MoE (top-k sparse MoE -> models/moe.py, incl. the
un-renormalised combine-weight convention), OLMo2 (post-norm placement,
flat-projection qk-norm), Phi-3/3.5/4-mini (packed qkv/gate_up weights,
longrope, partial rotary) — the reference's patched set
(utils/patch.py:224-301) plus the Qwen3/Gemma/Mixtral/OLMo2/Phi-3
families.  Rope scaling: linear, llama3, longrope, yarn (others fail
loudly).  GPT-2 (the reference's own CLM benchmark model,
benchmarks/transformer.py) converts too: learned positions, biased
LayerNorms, packed Conv1D qkv, gelu_new, tied head — plus llama
attention_bias/mlp_bias variants.
"""

from __future__ import annotations

import re
from typing import Any, Dict, Mapping, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from torchacc_tpu.models.transformer import ModelConfig


def config_from_hf(hf_config: Any, **overrides) -> ModelConfig:
    """ModelConfig from a transformers PretrainedConfig (llama/qwen2/
    mistral/gemma family)."""
    get = lambda n, d=None: getattr(hf_config, n, d)
    mt = get("model_type")
    kw = dict(
        vocab_size=get("vocab_size"),
        hidden_size=get("hidden_size"),
        num_layers=get("num_hidden_layers"),
        num_heads=get("num_attention_heads"),
        num_kv_heads=get("num_key_value_heads", get("num_attention_heads")),
        head_dim=get("head_dim"),
        intermediate_size=get("intermediate_size"),
        max_seq_len=get("max_position_embeddings", 4096),
        rope_theta=float(get("rope_theta", 10000.0)),
        norm_eps=float(get("rms_norm_eps", 1e-5)),
        qkv_bias=bool(get("attention_bias", False) or mt == "qwen2"),
        # llama's attention_bias puts a bias on o_proj too (qwen2's qkv
        # bias does NOT); mlp_bias is llama's separate knob
        o_bias=bool(get("attention_bias", False)),
        mlp_bias=bool(get("mlp_bias", False)),
        tie_embeddings=bool(get("tie_word_embeddings", False)),
    )
    if mt == "gemma":
        # Gemma v1: zero-centred RMSNorm (1 + w), gated tanh-GELU MLP
        # (gelu_pytorch_tanh), sqrt(hidden)-scaled embeddings, explicit
        # head_dim (7b: 256 != hidden/heads), tied head
        kw.update(norm="rmsnorm1p", activation="geglu", embed_scale=True)
    if mt == "gemma2":
        # Gemma2 adds to v1: sandwich norms (post-attention and
        # post-feedforward), alternating sliding/global attention
        # (HF Gemma2Attention: even layers sliding), attention-score
        # soft-capping, and a fixed query scale
        # (query_pre_attn_scalar ** -0.5 instead of head_dim ** -0.5)
        kw.update(
            norm="rmsnorm1p", activation="geglu", embed_scale=True,
            sandwich_norms=True, layer_pattern=("sliding", "global"),
            attn_logit_softcap=float(get("attn_logit_softcapping") or 0.0),
            query_scale=float(get("query_pre_attn_scalar",
                                  kw.get("head_dim") or 256)) ** -0.5)
    if mt in ("gemma3", "gemma3_text"):
        # Gemma3: gemma2's sandwich norms + 5:1 sliding/global pattern,
        # per-layer-type rope bases (local 10k on sliding layers, global
        # rope_theta on full layers), qk-norm, no score soft-capping
        kw.update(
            norm="rmsnorm1p", activation="geglu", embed_scale=True,
            sandwich_norms=True, qk_norm=True,
            layer_pattern=_pattern_from_layer_types(
                get("layer_types"),
                sliding_window_pattern=get("sliding_window_pattern")),
            rope_local_theta=float(get("rope_local_base_freq", 10000.0)),
            query_scale=float(get("query_pre_attn_scalar",
                                  kw.get("head_dim") or 256)) ** -0.5)
        rs = get("rope_scaling")
        if rs:
            rt = rs.get("rope_type", rs.get("type"))
            if rt != "linear":
                raise NotImplementedError(
                    f"gemma3 rope_scaling type {rt!r} is not implemented "
                    "(linear is)")
            # linear scaling on the GLOBAL rotary only (sliding layers
            # reset to 1 in pattern_cfg) — real gemma3 >=4B checkpoints
            # ship factor 8
            kw["rope_scale"] = float(rs["factor"])
    if mt == "gpt2":
        # GPT-2 class: learned positions, biased LayerNorms, gelu_new
        # MLP, packed Conv1D qkv, biases on every projection, tied head.
        # GPT2Config's attribute_map already aliases hidden_size /
        # num_attention_heads / num_hidden_layers /
        # max_position_embeddings onto n_embd / n_head / n_layer /
        # n_positions, so the generic reads above populated them.
        act = get("activation_function", "gelu_new")
        if act not in ("gelu_new", "gelu_pytorch_tanh"):
            # our 'gelu' is the tanh approximation; exact-erf gelu or
            # relu variants would convert silently wrong
            raise NotImplementedError(
                f"gpt2 activation_function {act!r} is not implemented "
                f"(gelu_new is)")
        kw.update(norm="layernorm", activation="gelu",
                  pos_emb="learned", qkv_bias=True, o_bias=True,
                  mlp_bias=True,
                  norm_eps=float(get("layer_norm_epsilon", 1e-5)))
        if get("n_inner"):
            kw["intermediate_size"] = int(get("n_inner"))
    if mt == "starcoder2":
        # StarCoder2 (3B/7B/15B): rope + GQA + biased LayerNorms +
        # NON-gated gelu_pytorch_tanh MLP named c_fc/c_proj + one
        # use_bias knob driving qkv/o/mlp biases; 7B/15B configs carry
        # sliding_window (picked up by the generic read below)
        act = get("hidden_act", "gelu_pytorch_tanh")
        if act not in ("gelu_pytorch_tanh", "gelu_new"):
            raise NotImplementedError(
                f"starcoder2 hidden_act {act!r} is not implemented "
                f"(gelu_pytorch_tanh is)")
        bias = bool(get("use_bias", True))
        kw.update(norm="layernorm", activation="gelu",
                  qkv_bias=bias, o_bias=bias, mlp_bias=bias,
                  norm_eps=float(get("norm_epsilon", 1e-5)))
    if mt == "gpt_neox":
        # GPT-NeoX / Pythia: TWO-norm parallel residual
        # (x + attn(ln1(x)) + mlp(ln2(x)) when use_parallel_residual,
        # the pythia default), packed per-head [q|k|v] attention, exact
        # erf gelu, biases everywhere, partial rotary via rotary_pct
        act = get("hidden_act", "gelu")
        if act not in ("gelu", "gelu_new", "gelu_pytorch_tanh",
                       "gelu_fast"):
            raise NotImplementedError(
                f"gpt_neox hidden_act {act!r} is not implemented")
        nx_bias = bool(get("attention_bias", True))
        kw.update(norm="layernorm",
                  activation="gelu_exact" if act == "gelu" else "gelu",
                  parallel_block=bool(get("use_parallel_residual", True)),
                  parallel_block_shared_norm=False,
                  # attention_bias gates qkv/dense; the MLP linears are
                  # unconditionally biased in HF GPTNeoXMLP
                  qkv_bias=nx_bias, o_bias=nx_bias, mlp_bias=True,
                  norm_eps=float(get("layer_norm_eps", 1e-5)),
                  rope_theta=float(get("rotary_emb_base",
                                       get("rope_theta", 10000.0) or
                                       10000.0) or 10000.0))
        prf = float(get("rotary_pct", 1.0) or 1.0)
        if prf != 1.0:
            kw["partial_rotary"] = prf
    if mt == "nemotron":
        # Nemotron: layernorm1p ((1+w) scale + bias over a mean-centred
        # norm), NON-gated square-relu MLP (up/down names), partial
        # rotary; llama attention names
        act = get("hidden_act", "relu2")
        if act != "relu2":
            raise NotImplementedError(
                f"nemotron hidden_act {act!r} is not implemented "
                f"(relu2 is)")
        kw.update(norm="layernorm1p", activation="relu2",
                  norm_eps=float(get("norm_eps", 1e-5)))
        prf = float(get("partial_rotary_factor", 0.5) or 1.0)
        if prf != 1.0:
            kw["partial_rotary"] = prf
    if mt == "cohere":
        # Cohere / Command-R: PARALLEL residual with ONE shared BIASLESS
        # LayerNorm, gated silu MLP (llama names), tied embeddings, and
        # a logit_scale multiplier (applied by scaling the final-normed
        # hidden — every head path inherits it)
        if get("use_qk_norm", False):
            raise NotImplementedError(
                "cohere use_qk_norm=True (per-head LayerNorm q/k) is "
                "not implemented")
        kw.update(parallel_block=True, norm="layernorm", norm_bias=False,
                  norm_eps=float(get("layer_norm_eps", 1e-5)),
                  logit_scale=float(get("logit_scale", 1.0) or 1.0),
                  rope_interleaved=True)
    if mt == "phi":
        # Phi-1/1.5/2: PARALLEL residual (x + attn(ln(x)) + mlp(ln(x)),
        # one shared biased LayerNorm, no ln2), partial rotary,
        # gelu_new fc1/fc2 MLP, biases everywhere INCLUDING the lm_head
        act = get("hidden_act", "gelu_new")
        if act not in ("gelu_new", "gelu_pytorch_tanh"):
            raise NotImplementedError(
                f"phi hidden_act {act!r} is not implemented (gelu_new is)")
        if kw.get("tie_embeddings"):
            # HF ties only lm_head.weight; its bias would survive in the
            # state dict with no tied-head slot to land in — converting
            # would silently drop it
            raise NotImplementedError(
                "phi with tie_word_embeddings=True is not supported "
                "(the biased lm_head cannot ride the tied head)")
        kw.update(norm="layernorm", activation="gelu", parallel_block=True,
                  qkv_bias=True, o_bias=True, mlp_bias=True, head_bias=True,
                  norm_eps=float(get("layer_norm_eps", 1e-5)),
                  partial_rotary=float(get("partial_rotary_factor", 0.5)))
    if mt == "phi3":
        # Phi-3/3.5/4-mini: llama-style pre-norm block with PACKED
        # qkv_proj / gate_up_proj weights (split at conversion);
        # phi-4-mini's partial rotary and the 128k variants' 'longrope'
        # scaling are both supported (the generic rope chain below)
        prf = float(get("partial_rotary_factor", 1.0) or 1.0)
        if prf != 1.0:
            kw["partial_rotary"] = prf
    if mt == "olmo2":
        # OLMo2 (the modern revision of the reference's example-notebook
        # family, examples/train_olmo.ipynb): llama MLP + POST-norm
        # residual placement (x + norm(f(x)), no pre-norms) and RMSNorm
        # over the FLAT q/k projections
        kw.update(qk_norm=True, qk_norm_proj=True, norm_placement="post")
    if mt == "qwen3":
        # Qwen3: llama layout + per-head-dim RMSNorm on q/k before rope
        # (same q_norm/k_norm tensors as gemma3, but with the standard
        # RMSNorm — cfg.norm stays 'rmsnorm') and explicit head_dim; no
        # qkv bias (unlike qwen2)
        kw.update(qk_norm=True)
    if mt == "qwen3_moe":
        # Qwen3-MoE (30B-A3B family): qwen3 attention + per-expert
        # llama FFNs at moe_intermediate_size; norm_topk_prob picks the
        # combine-weight convention
        if int(get("decoder_sparse_step", 1) or 1) != 1 \
                or get("mlp_only_layers"):
            raise NotImplementedError(
                "qwen3_moe mixed dense/sparse layer schedules "
                "(decoder_sparse_step != 1 / mlp_only_layers) are not "
                "implemented")
        kw.update(
            qk_norm=True,
            num_experts=int(get("num_experts")),
            num_experts_per_tok=int(get("num_experts_per_tok", 2)),
            router_aux_weight=float(get("router_aux_loss_coef", 0.001)),
            intermediate_size=int(get("moe_intermediate_size")),
            moe_renorm_topk=bool(get("norm_topk_prob", False)))
    if mt == "mixtral":
        # Mixtral 8x7B/8x22B: llama attention + top-k sparse MoE MLP.
        # HF routes softmax-then-topk-then-renormalise, which equals the
        # zoo's topk-then-softmax exactly (softmax is monotonic, and
        # renormalising the selected probs reproduces softmax over the
        # selected logits) — so logits match with dense dispatch.
        kw.update(
            num_experts=int(get("num_local_experts")),
            num_experts_per_tok=int(get("num_experts_per_tok", 2)),
            router_aux_weight=float(get("router_aux_loss_coef", 0.01)))
    if mt not in ("gemma3", "gemma3_text"):
        # generic rope_scaling (gemma3 parses its own above): 'linear'
        # divides positions, 'llama3' is Llama-3.1's frequency banding,
        # 'longrope' is Phi-3.5/4's per-dim divisors, 'yarn' is the
        # qwen 128k recipe.  Anything else fails LOUDLY — silently
        # dropping a scaling would make long-context logits quietly
        # wrong.
        rs = get("rope_scaling")
        if rs:
            rt = rs.get("rope_type", rs.get("type", "default"))
            if rt == "linear":
                kw["rope_scale"] = float(rs["factor"])
            elif rt == "llama3":
                kw["rope_llama3"] = (
                    float(rs["factor"]),
                    float(rs["low_freq_factor"]),
                    float(rs["high_freq_factor"]),
                    float(rs["original_max_position_embeddings"]))
            elif rt == "longrope":
                # Phi-3.5/4 128k: per-dim divisors.  HF semantics: the
                # original context comes from the CONFIG ATTR when
                # present (factor = max_pos / orig); otherwise orig =
                # max_pos and the rs-level 'factor' drives the default
                # attention factor.  Compute that default HERE so _rope
                # never has to guess the effective factor.
                import math as _m
                attr_orig = get("original_max_position_embeddings")
                orig = float(attr_orig or kw["max_seq_len"])
                f_eff = (kw["max_seq_len"] / orig if attr_orig
                         else float(rs.get("factor") or 1.0))
                af = rs.get("attention_factor")
                if af is None:
                    af = (1.0 if f_eff <= 1.0
                          else _m.sqrt(1.0 + _m.log(f_eff)
                                       / _m.log(orig)))
                kw["rope_longrope"] = (
                    tuple(float(x) for x in rs["short_factor"]),
                    tuple(float(x) for x in rs["long_factor"]),
                    orig, float(af))
            elif rt == "yarn":
                # qwen 128k variants.  Fallbacks mirror HF
                # _compute_yarn_parameters exactly: original_max falls
                # back to max_position_embeddings (NOT divided by
                # factor, and the top-level config attr is not
                # consulted); beta defaults use `or` (an explicit null
                # still means 32/1)
                orig = float(rs.get("original_max_position_embeddings")
                             or kw["max_seq_len"])
                af = rs.get("attention_factor")
                if rs.get("mscale") or rs.get("mscale_all_dim"):
                    raise NotImplementedError(
                        "yarn mscale variants (deepseek) are not "
                        "implemented")
                kw["rope_yarn"] = (
                    float(rs["factor"]), orig,
                    float(rs.get("beta_fast") or 32.0),
                    float(rs.get("beta_slow") or 1.0),
                    None if af is None else float(af),
                    bool(rs.get("truncate", True)))
            elif rt != "default":
                raise NotImplementedError(
                    f"rope_scaling type {rt!r} is not implemented "
                    f"(linear, llama3, longrope and yarn are)")
    if get("final_logit_softcapping"):
        kw["logit_softcap"] = float(get("final_logit_softcapping"))
    if get("sliding_window") and get("use_sliding_window", True):
        # HF sliding masks attend iff kv > q - sliding_window (inclusive
        # count = sliding_window); our window=(left, right) attends
        # kv >= q - left (count = left + 1) -> left = sliding_window - 1
        kw["window"] = (int(get("sliding_window")) - 1, -1)
    kw.update(overrides)
    return ModelConfig(**kw)


def _pattern_from_layer_types(layer_types,
                              sliding_window_pattern=None
                              ) -> Tuple[str, ...]:
    """Shortest cyclic layer_pattern reproducing HF's per-layer
    ``layer_types`` list (gemma3: 5 sliding + 1 full).  Pre-4.53
    transformers gemma3 configs expose ``sliding_window_pattern=p``
    (every p-th layer global) instead of ``layer_types``."""
    if not layer_types:
        if sliding_window_pattern:
            p = int(sliding_window_pattern)
            return ("sliding",) * (p - 1) + ("global",)
        raise ValueError("layer_types missing from the HF config")
    kinds = tuple("sliding" if t == "sliding_attention" else "global"
                  for t in layer_types)
    n = len(kinds)
    for period in range(1, n):
        if n % period == 0 and kinds == kinds[:period] * (n // period):
            return kinds[:period]
    return kinds  # no shorter period: one full cycle


def _t(x) -> np.ndarray:
    if hasattr(x, "detach"):
        x = x.detach().cpu().float().numpy()
    return np.asarray(x)


def _params_from_gpt2(state_dict, cfg: ModelConfig, dtype):
    """GPT-2 state dict -> TransformerLM params.  GPT-2 uses Conv1D
    layers whose weights are already [in, out] (no transpose), a packed
    c_attn with COLUMNS [q | k | v], and biases everywhere."""
    L, h = cfg.num_layers, cfg.hidden_size
    nh, d = cfg.num_heads, cfg.head_size
    f = cfg.ffn_size

    def get(name):
        for prefix in ("transformer.", ""):
            if prefix + name in state_dict:
                return _t(state_dict[prefix + name])
        raise KeyError(f"missing weight {name!r} in state_dict")

    def stack(fmt, transform):
        return np.stack([transform(get(fmt.format(i=i))) for i in range(L)])

    # one fetch + torch->numpy conversion of each packed c_attn per
    # layer (gpt2-xl's is ~29 MB); slice the cached array three ways
    qw, kw_, vw, qb, kb, vb = ([] for _ in range(6))
    for i in range(L):
        w = get(f"h.{i}.attn.c_attn.weight")   # [h, 3h], cols [q|k|v]
        b = get(f"h.{i}.attn.c_attn.bias")
        qw.append(w[:, :h].reshape(h, nh, d))
        kw_.append(w[:, h:2 * h].reshape(h, nh, d))
        vw.append(w[:, 2 * h:].reshape(h, nh, d))
        qb.append(b[:h].reshape(nh, d))
        kb.append(b[h:2 * h].reshape(nh, d))
        vb.append(b[2 * h:].reshape(nh, d))
    attn = {
        "q_proj": {"kernel": np.stack(qw), "bias": np.stack(qb)},
        "k_proj": {"kernel": np.stack(kw_), "bias": np.stack(kb)},
        "v_proj": {"kernel": np.stack(vw), "bias": np.stack(vb)},
        "o_proj": {"kernel": stack("h.{i}.attn.c_proj.weight",
                                   lambda w: w.reshape(nh, d, h)),
                   "bias": stack("h.{i}.attn.c_proj.bias", lambda b: b)},
    }
    block = {
        "attn": attn,
        "mlp": {
            "up_proj": {"kernel": stack("h.{i}.mlp.c_fc.weight",
                                        lambda w: w.reshape(h, f)),
                        "bias": stack("h.{i}.mlp.c_fc.bias", lambda b: b)},
            "down_proj": {"kernel": stack("h.{i}.mlp.c_proj.weight",
                                          lambda w: w.reshape(f, h)),
                          "bias": stack("h.{i}.mlp.c_proj.bias",
                                        lambda b: b)},
        },
        "ln1": {"scale": stack("h.{i}.ln_1.weight", lambda w: w),
                "bias": stack("h.{i}.ln_1.bias", lambda b: b)},
        "ln2": {"scale": stack("h.{i}.ln_2.weight", lambda w: w),
                "bias": stack("h.{i}.ln_2.bias", lambda b: b)},
    }
    params: Dict[str, Any] = {
        "embed_tokens": {"embedding": get("wte.weight")},
        "pos_embed": get("wpe.weight")[:cfg.max_seq_len],
        "layers": {"block": block},
        "final_norm": {"scale": get("ln_f.weight"),
                       "bias": get("ln_f.bias")},
    }
    import jax
    return jax.tree.map(lambda a: jnp.asarray(a, dtype), params)


def _params_from_neox(state_dict, cfg: ModelConfig, dtype):
    """GPT-NeoX state dict -> TransformerLM params: ``gpt_neox.``
    prefix, packed per-head ``attention.query_key_value`` ([q|k|v] rows
    PER HEAD — not the phi3 whole-tensor split), ``attention.dense``,
    ``mlp.dense_h_to_4h/dense_4h_to_h``, biased LayerNorms, top-level
    ``embed_out`` head."""
    L, h = cfg.num_layers, cfg.hidden_size
    nh, d = cfg.num_heads, cfg.head_size

    def get(name):
        for prefix in ("gpt_neox.", ""):
            if prefix + name in state_dict:
                return _t(state_dict[prefix + name])
        raise KeyError(f"missing weight {name!r} in state_dict")

    def stack(fmt, transform):
        return np.stack([transform(get(fmt.format(i=i))) for i in range(L)])

    qw, kw_, vw, qb, kb, vb = ([] for _ in range(6))
    for i in range(L):
        w = get(f"layers.{i}.attention.query_key_value.weight")
        w3 = w.reshape(nh, 3 * d, h)          # rows per head: [q|k|v]
        # -> [h, nh, d] kernels / [nh, d] biases
        qw.append(w3[:, :d, :].transpose(2, 0, 1))
        kw_.append(w3[:, d:2 * d, :].transpose(2, 0, 1))
        vw.append(w3[:, 2 * d:, :].transpose(2, 0, 1))
        if cfg.qkv_bias:   # attention_bias=False checkpoints ship none
            b3 = get(f"layers.{i}.attention.query_key_value.bias"
                     ).reshape(nh, 3 * d)
            qb.append(b3[:, :d])
            kb.append(b3[:, d:2 * d])
            vb.append(b3[:, 2 * d:])
    attn = {
        "q_proj": {"kernel": np.stack(qw)},
        "k_proj": {"kernel": np.stack(kw_)},
        "v_proj": {"kernel": np.stack(vw)},
        "o_proj": {"kernel": stack("layers.{i}.attention.dense.weight",
                                   lambda w: w.T.reshape(nh, d, h))},
    }
    if cfg.qkv_bias:
        attn["q_proj"]["bias"] = np.stack(qb)
        attn["k_proj"]["bias"] = np.stack(kb)
        attn["v_proj"]["bias"] = np.stack(vb)
    if cfg.o_bias:
        attn["o_proj"]["bias"] = stack(
            "layers.{i}.attention.dense.bias", lambda b: b)
    block = {
        "attn": attn,
        "mlp": {
            "up_proj": {"kernel": stack(
                "layers.{i}.mlp.dense_h_to_4h.weight", lambda w: w.T),
                "bias": stack("layers.{i}.mlp.dense_h_to_4h.bias",
                              lambda b: b)},
            "down_proj": {"kernel": stack(
                "layers.{i}.mlp.dense_4h_to_h.weight", lambda w: w.T),
                "bias": stack("layers.{i}.mlp.dense_4h_to_h.bias",
                              lambda b: b)},
        },
        "ln1": {"scale": stack("layers.{i}.input_layernorm.weight",
                               lambda w: w),
                "bias": stack("layers.{i}.input_layernorm.bias",
                              lambda b: b)},
        "ln2": {"scale": stack(
            "layers.{i}.post_attention_layernorm.weight", lambda w: w),
            "bias": stack("layers.{i}.post_attention_layernorm.bias",
                          lambda b: b)},
    }
    params: Dict[str, Any] = {
        "embed_tokens": {"embedding": get("embed_in.weight")},
        "layers": {"block": block},
        "final_norm": {"scale": get("final_layer_norm.weight"),
                       "bias": get("final_layer_norm.bias")},
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = {"kernel": _t(state_dict["embed_out.weight"]).T}
    import jax
    return jax.tree.map(lambda a: jnp.asarray(a, dtype), params)


#: GPT-NeoX attention tensors live at ``layers.<i>.attention.
#: query_key_value.weight`` (optionally under a ``gpt_neox.`` prefix).
#: Anchoring on the ``layers.<i>.`` prefix matters: Falcon-style
#: checkpoints name theirs ``h.<i>.self_attention.query_key_value.
#: weight``, which a bare ``endswith("attention.query_key_value.
#: weight")`` also matches — dispatching those through the NeoX layout
#: would silently mis-convert (wrong transpose + fused-qkv split).
_NEOX_QKV_RE = re.compile(
    r"(?:^|\.)layers\.\d+\.attention\.query_key_value\.weight$")


def _is_neox_state_dict(state_dict: Mapping[str, Any]) -> bool:
    """True only for the GPT-NeoX tensor layout (see ``_NEOX_QKV_RE``);
    Falcon-style ``self_attention.query_key_value`` keys do NOT
    qualify."""
    return any(_NEOX_QKV_RE.search(k) for k in state_dict)


def params_from_hf_state_dict(
    state_dict: Mapping[str, Any],
    cfg: ModelConfig,
    dtype=None,
) -> Dict[str, Any]:
    """Map an HF llama/qwen2-style state_dict to TransformerLM params.

    HF linear weights are [out, in]; flax kernels are [in, out] (and
    DenseGeneral splits heads), so weights are transposed/reshaped.
    Layers are stacked on a leading dim for scan-over-layers.
    GPT-2 checkpoints (Conv1D packed weights, ``transformer.``-prefixed
    names) take their own mapping.
    """
    dtype = dtype or cfg.param_dtype
    # the Conv1D-packed c_attn is specific to the gpt2 layout (GPT-J /
    # GPT-Neo also have wte but different attention naming — those are
    # unsupported and will fail on their attention tensors loudly)
    if any(k.endswith("attn.c_attn.weight") for k in state_dict):
        return _params_from_gpt2(state_dict, cfg, dtype)
    if _is_neox_state_dict(state_dict):
        return _params_from_neox(state_dict, cfg, dtype)
    L = cfg.num_layers
    h = cfg.hidden_size
    nh, nk, d = cfg.num_heads, cfg.kv_heads, cfg.head_size

    def get(name):
        for prefix in ("model.", ""):
            key = prefix + name
            if key in state_dict:
                return _t(state_dict[key])
        raise KeyError(f"missing weight {name!r} in state_dict")

    def stack(fmt, transform):
        return np.stack([transform(get(fmt.format(i=i))) for i in range(L)])

    qkv = lambda w, heads: w.T.reshape(h, heads, d)

    def has(name):
        return any(p + name in state_dict for p in ("model.", ""))

    if has("layers.0.self_attn.qkv_proj.weight"):
        # Phi-3 packed attention: qkv_proj rows are [q | k | v]
        qr, kr = nh * d, nk * d
        attn = {
            "q_proj": {"kernel": stack(
                "layers.{i}.self_attn.qkv_proj.weight",
                lambda w: qkv(w[:qr], nh))},
            "k_proj": {"kernel": stack(
                "layers.{i}.self_attn.qkv_proj.weight",
                lambda w: qkv(w[qr:qr + kr], nk))},
            "v_proj": {"kernel": stack(
                "layers.{i}.self_attn.qkv_proj.weight",
                lambda w: qkv(w[qr + kr:], nk))},
        }
    else:
        attn = {
            "q_proj": {"kernel": stack("layers.{i}.self_attn.q_proj.weight",
                                       lambda w: qkv(w, nh))},
            "k_proj": {"kernel": stack("layers.{i}.self_attn.k_proj.weight",
                                       lambda w: qkv(w, nk))},
            "v_proj": {"kernel": stack("layers.{i}.self_attn.v_proj.weight",
                                       lambda w: qkv(w, nk))},
        }
    # phi names the output projection self_attn.dense
    o_name = ("dense" if has("layers.0.self_attn.dense.weight")
              else "o_proj")
    attn["o_proj"] = {"kernel": stack(
        f"layers.{{i}}.self_attn.{o_name}.weight",
        lambda w: w.T.reshape(nh, d, h))}
    if cfg.qkv_bias:
        for name, heads in (("q_proj", nh), ("k_proj", nk), ("v_proj", nk)):
            attn[name]["bias"] = stack(
                f"layers.{{i}}.self_attn.{name}.bias",
                lambda b, heads=heads: b.reshape(heads, d))
    if cfg.o_bias:
        attn["o_proj"]["bias"] = stack(
            f"layers.{{i}}.self_attn.{o_name}.bias", lambda b: b)
    if cfg.qk_norm:
        attn["q_norm"] = {"scale": stack(
            "layers.{i}.self_attn.q_norm.weight", lambda w: w)}
        attn["k_norm"] = {"scale": stack(
            "layers.{i}.self_attn.k_norm.weight", lambda w: w)}

    # OLMo2 post-norm placement renames both block norms; decide the
    # source tensors once so pre/post stay in one place
    post = cfg.norm_placement == "post"
    ln1_src = ("layers.{i}.post_attention_layernorm.weight" if post
               else "layers.{i}.input_layernorm.weight")
    ln2_src = ("layers.{i}.post_feedforward_layernorm.weight" if post
               else "layers.{i}.post_attention_layernorm.weight")
    block = {
        "attn": attn,
        "ln1": {"scale": stack(ln1_src, lambda w: w)},
    }
    if cfg.num_experts > 0:
        # Sparse MoE -> MoEMlp: router [e, h] -> [h, e] kernel; expert
        # FFNs stack [L, e, ...] to the zoo's expert-major layout.
        # Mixtral names them block_sparse_moe.{gate, experts.j.w1/w3/w2};
        # qwen3_moe uses mlp.{gate, experts.j.gate_proj/up_proj/down_proj}
        E = cfg.num_experts
        # one detector shared with the streaming path so the two cannot
        # diverge on a future naming style
        from torchacc_tpu.models.hf_stream import _detect_moe_style
        if _detect_moe_style(state_dict) == "qwen":
            moe_mod, wg, wu, wd = ("mlp", "gate_proj", "up_proj",
                                   "down_proj")
        else:
            moe_mod, wg, wu, wd = "block_sparse_moe", "w1", "w3", "w2"

        def experts_stack(wn):
            return np.stack([
                np.stack([
                    get(f"layers.{i}.{moe_mod}.experts.{j}."
                        f"{wn}.weight").T
                    for j in range(E)]) for i in range(L)])

        block["moe"] = {
            "router": {"kernel": stack(
                "layers.{{i}}.{}.gate.weight".format(moe_mod),
                lambda w: w.T)},
            "experts/gate": experts_stack(wg),
            "experts/up": experts_stack(wu),
            "experts/down": experts_stack(wd),
        }
    elif has("layers.0.mlp.gate_up_proj.weight"):
        # Phi-3 packed MLP: gate_up_proj rows are [gate | up]
        inter = cfg.intermediate_size
        block["mlp"] = {
            "gate_proj": {"kernel": stack(
                "layers.{i}.mlp.gate_up_proj.weight",
                lambda w: w[:inter].T)},
            "up_proj": {"kernel": stack(
                "layers.{i}.mlp.gate_up_proj.weight",
                lambda w: w[inter:].T)},
            "down_proj": {"kernel": stack(
                "layers.{i}.mlp.down_proj.weight", lambda w: w.T)},
        }
    elif has("layers.0.mlp.c_fc.weight") or has("layers.0.mlp.fc1.weight"):
        # NON-gated MLPs: StarCoder2 names them c_fc/c_proj, phi fc1/fc2
        # (activation='gelu' builds no gate_proj)
        up_n, dn_n = (("c_fc", "c_proj")
                      if has("layers.0.mlp.c_fc.weight")
                      else ("fc1", "fc2"))
        block["mlp"] = {
            "up_proj": {"kernel": stack(
                f"layers.{{i}}.mlp.{up_n}.weight", lambda w: w.T)},
            "down_proj": {"kernel": stack(
                f"layers.{{i}}.mlp.{dn_n}.weight", lambda w: w.T)},
        }
        if cfg.mlp_bias:
            block["mlp"]["up_proj"]["bias"] = stack(
                f"layers.{{i}}.mlp.{up_n}.bias", lambda b: b)
            block["mlp"]["down_proj"]["bias"] = stack(
                f"layers.{{i}}.mlp.{dn_n}.bias", lambda b: b)
    else:
        # gated (llama) MLPs carry gate/up/down; non-gated models that
        # keep the up/down names (nemotron relu2) just drop the gate
        gated = cfg.activation in ("swiglu", "geglu")
        names = (("gate_proj", "up_proj", "down_proj") if gated
                 else ("up_proj", "down_proj"))
        block["mlp"] = {
            nm: {"kernel": stack(
                f"layers.{{i}}.mlp.{nm}.weight", lambda w: w.T)}
            for nm in names}
        if cfg.mlp_bias:
            for nm in names:
                block["mlp"][nm]["bias"] = stack(
                    f"layers.{{i}}.mlp.{nm}.bias", lambda b: b)
    if cfg.sandwich_norms:
        # gemma2 norm naming: post_attention_layernorm is the POST-attn
        # sandwich norm; the pre-mlp norm is pre_feedforward_layernorm
        block["ln1_post"] = {"scale": stack(
            "layers.{i}.post_attention_layernorm.weight", lambda w: w)}
        block["ln2"] = {"scale": stack(
            "layers.{i}.pre_feedforward_layernorm.weight", lambda w: w)}
        block["ln2_post"] = {"scale": stack(
            "layers.{i}.post_feedforward_layernorm.weight", lambda w: w)}
    elif not cfg.parallel_block:      # phi's parallel block has no ln2
        block["ln2"] = {"scale": stack(ln2_src, lambda w: w)}
    # phi names the final norm final_layernorm
    fn_src = ("final_layernorm" if has("final_layernorm.weight")
              else "norm")
    params: Dict[str, Any] = {
        "embed_tokens": {"embedding": get("embed_tokens.weight")},
        "layers": {"block": block},
        "final_norm": {"scale": get(f"{fn_src}.weight")},
    }
    if cfg.norm in ("layernorm", "layernorm1p") and cfg.norm_bias:
        # biased LayerNorms (StarCoder2/phi): same source names, .bias
        block["ln1"]["bias"] = stack(
            ln1_src.replace(".weight", ".bias"), lambda b: b)
        if "ln2" in block:
            block["ln2"]["bias"] = stack(
                ln2_src.replace(".weight", ".bias"), lambda b: b)
        params["final_norm"]["bias"] = get(f"{fn_src}.bias")
    if not cfg.tie_embeddings:
        # lm_head lives at the top level in HF models
        head = state_dict.get("lm_head.weight")
        if head is None:
            raise KeyError("lm_head.weight missing and tie_embeddings=False")
        params["lm_head"] = {"kernel": _t(head).T}
        if cfg.head_bias:
            params["lm_head"]["bias"] = _t(state_dict["lm_head.bias"])

    import jax
    return jax.tree.map(lambda a: jnp.asarray(a, dtype), params)


def load_hf_model(model_or_path: Any, **config_overrides
                  ) -> Tuple[ModelConfig, Dict[str, Any]]:
    """(ModelConfig, params) from a transformers model instance or a
    local checkpoint path."""
    if isinstance(model_or_path, str):
        import transformers
        model = transformers.AutoModelForCausalLM.from_pretrained(
            model_or_path)
    else:
        model = model_or_path
    cfg = config_from_hf(model.config, **config_overrides)
    params = params_from_hf_state_dict(model.state_dict(), cfg)
    return cfg, params
