"""Parameter-path -> logical-axes resolution.

The reference marks shardings imperatively on live torch tensors
(``xs.mark_sharding`` tp.py:1-5, FSDP auto-wrap by layer-class name
fsdp.py:218-230).  Here sharding metadata is data: a regex table from
flax parameter paths to logical axis tuples, resolved once against the
abstract parameter tree.  This works uniformly for our model zoo and for
HF-ingested checkpoints, with no monkeypatching.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax

AxesRule = Tuple[str, Tuple[Optional[str], ...]]

# First match wins.  Paths are '/'-joined flax param paths; scan-stacked
# layer params carry a leading 'layers' dim.
TRANSFORMER_AXES: Tuple[AxesRule, ...] = (
    (r"embed_tokens/embedding$", ("vocab", "embed")),
    (r"pos_embed$", (None, "embed")),
    (r"(q_proj|k_proj|v_proj)/kernel$", ("embed", "heads", "kv")),
    (r"(q_proj|k_proj|v_proj)/bias$", ("heads", "kv")),
    (r"o_proj/kernel$", ("heads", "kv", "embed")),
    (r"o_proj/bias$", ("embed",)),
    (r"(gate_proj|up_proj)/kernel$", ("embed", "mlp")),
    (r"(gate_proj|up_proj)/bias$", ("mlp",)),
    (r"down_proj/kernel$", ("mlp", "embed")),
    (r"down_proj/bias$", ("embed",)),
    (r"router/kernel$", ("embed", "expert")),
    (r"experts/(gate|up)$", ("expert", "embed", "expert_mlp")),
    (r"experts/down$", ("expert", "expert_mlp", "embed")),
    (r"(ln1|ln2|ln1_post|ln2_post|final_norm|q_norm|k_norm)/(scale|bias)$",
     ("norm",)),
    (r"lm_head/kernel$", ("embed", "vocab")),
    (r"lm_head/bias$", ("vocab",)),
)


def param_axes(
    params: Any,
    rules: Sequence[AxesRule] = TRANSFORMER_AXES,
    extra_leading: Tuple[str, ...] = ("layers",),
) -> Any:
    """Resolve a logical-axes pytree matching ``params``.

    A leaf whose ndim exceeds its rule's length by k gets the first k
    names of ``extra_leading`` prepended (scan-over-layers stacking).
    Unmatched paths raise — silent replication of a large tensor is a
    memory bug, not a default.
    """
    compiled = [(re.compile(pat), axes) for pat, axes in rules]

    def one(path, leaf):
        if leaf is None:
            return None
        pstr = "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                        for k in path)
        for pat, axes in compiled:
            if pat.search(pstr):
                missing = leaf.ndim - len(axes)
                if missing < 0 or missing > len(extra_leading):
                    raise ValueError(
                        f"axes rule {axes} does not fit param {pstr} with "
                        f"shape {leaf.shape}")
                return tuple(extra_leading[:missing]) + tuple(axes)
        raise ValueError(
            f"no logical-axes rule matches param {pstr!r} (shape "
            f"{getattr(leaf, 'shape', '?')}); extend the rules table")

    return jax.tree_util.tree_map_with_path(one, params)
