from torchacc_tpu.models.axes import TRANSFORMER_AXES, param_axes
from torchacc_tpu.models.generate import generate
from torchacc_tpu.models.hf import (
    config_from_hf,
    load_hf_model,
    params_from_hf_state_dict,
)
from torchacc_tpu.models.presets import PRESETS, get_preset
from torchacc_tpu.models.transformer import (
    ModelConfig,
    TransformerLM,
    alibi_slopes,
    loss_fn,
    loss_sum_count,
)

__all__ = [
    "ModelConfig", "TransformerLM", "loss_fn", "loss_sum_count",
    "alibi_slopes", "param_axes", "TRANSFORMER_AXES", "PRESETS",
    "get_preset", "generate", "config_from_hf", "load_hf_model",
    "params_from_hf_state_dict",
]
