from torchacc_tpu.models.axes import TRANSFORMER_AXES, param_axes
from torchacc_tpu.models.presets import PRESETS, get_preset
from torchacc_tpu.models.transformer import ModelConfig, TransformerLM, loss_fn

__all__ = [
    "ModelConfig",
    "TransformerLM",
    "loss_fn",
    "param_axes",
    "TRANSFORMER_AXES",
    "PRESETS",
    "get_preset",
]
