"""Mixture-of-experts MLP with expert parallelism.

Beyond the reference: TorchAcc has no MoE/EP implementation (SURVEY.md
§2.3 — its differentiable all-to-all cp/utils.py:262-299 is the building
block it would need).  Here experts live on an 'expert' logical axis
(sharded over the 'ep' mesh axis); token routing uses a dense
dispatch/combine einsum formulation, which GSPMD lowers to all-to-alls
across 'ep' automatically — the idiomatic TPU MoE (switch-transformer
style) rather than a hand-written NCCL a2a.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp


def _sort_dispatch(xf, sel_f, w_f, e, cap):
    """Scale-proof capacity dispatch: argsort by expert instead of
    one-hot slot tensors.

    The einsum path materialises [n, e, cap] (and transiently
    [n, k, e, cap]) one-hots — tens of GB at Mixtral-8x7B geometry
    (n~8k, e=8, cap~2k, VERDICT r3 weak-4).  Here intermediates are
    O(n*k) index/weight vectors plus the [e*cap, h] expert buffer:

    - flatten (slot, token) claims SLOT-MAJOR, so a stable argsort by
      expert reproduces the switch/GShard drop priority exactly (every
      token's top-1 claim fills before any token's top-2);
    - position inside the expert buffer = sorted index - expert start
      (exclusive cumsum of per-expert counts);
    - dispatch/combine are scatter-add/gather on the flat [e*cap, h]
      buffer — differentiable wrt x and the expert outputs, with the
      integer routing naturally non-differentiable.

    Returns (ex_in [e, cap, h], dest [n*k], tok_sorted [n*k],
    w_keep [n*k] f32 combine weights, zero where dropped).
    """
    n, k = sel_f.shape
    h = xf.shape[1]
    nk = n * k
    sel_sm = sel_f.T.reshape(nk)            # slot-major flatten
    w_sm = w_f.T.reshape(nk)
    tok_sm = jnp.tile(jnp.arange(n, dtype=jnp.int32), k)
    order = jnp.argsort(sel_sm, stable=True)
    e_sorted = sel_sm[order]
    counts = jnp.bincount(sel_sm, length=e)
    starts = jnp.cumsum(counts) - counts
    pos = jnp.arange(nk, dtype=jnp.int32) - starts[e_sorted].astype(jnp.int32)
    keep = pos < cap
    dest = e_sorted * cap + jnp.where(keep, pos, 0)
    tok_sorted = tok_sm[order]
    w_keep = jnp.where(keep, w_sm[order], 0.0).astype(jnp.float32)
    gathered = xf[tok_sorted] * keep[:, None].astype(xf.dtype)
    ex_in = jnp.zeros((e * cap, h), xf.dtype).at[dest].add(gathered)
    return ex_in.reshape(e, cap, h), dest, tok_sorted, w_keep


class MoEMlp(nn.Module):
    """Top-k token-choice MoE: capacity-free dense dispatch, or
    switch-transformer capacity dispatch (``cfg.moe_capacity_factor``).

    For modest expert counts the dense formulation (every token scored
    against every expert, weighted-combined with a top-k mask) is both
    exactly correct (no token dropping) and MXU-friendly; its FLOPs
    scale with e.  The capacity path computes only
    ``C = ceil(cf * k * tokens / e)`` slots per expert — FLOPs
    independent of e (the mixtral-8x7b regime) — at the cost of
    dropping over-capacity tokens (standard switch behaviour).
    """
    cfg: object  # ModelConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        e = cfg.num_experts
        k = cfg.num_experts_per_tok
        h = cfg.hidden_size
        f = cfg.ffn_size
        b, s, _ = x.shape

        if cfg.moe_dispatch not in ("auto", "einsum", "sort"):
            # validate regardless of capacity mode so a typo surfaces at
            # the config that introduced it
            raise ValueError(
                f"moe_dispatch must be 'auto' | 'einsum' | 'sort', "
                f"got {cfg.moe_dispatch!r}")
        router = nn.Dense(e, use_bias=False, name="router",
                          dtype=jnp.float32, param_dtype=cfg.param_dtype,
                          kernel_init=nn.initializers.normal(0.02))
        logits = router(x.astype(jnp.float32))            # [b, s, e]
        if cfg.moe_renorm_topk:
            # mixtral: softmax over the selected logits (== HF's
            # softmax-then-topk-then-renormalise)
            weights, sel = jax.lax.top_k(logits, k)       # [b, s, k]
            weights = jax.nn.softmax(weights, axis=-1)
        else:
            # qwen3-moe norm_topk_prob=false: weights are the plain
            # full-softmax probs of the selected experts (they do NOT
            # sum to 1); top_k on probs picks the same experts
            probs = jax.nn.softmax(logits, axis=-1)
            weights, sel = jax.lax.top_k(probs, k)

        init = nn.initializers.normal(0.02)
        w_gate = self.param("experts/gate", init, (e, h, f), cfg.param_dtype)
        w_up = self.param("experts/up", init, (e, h, f), cfg.param_dtype)
        w_down = self.param("experts/down", init, (e, f, h), cfg.param_dtype)
        xd = x.astype(cfg.dtype)

        def experts(gi, ui):
            # shared expert FFN body: silu(gate) * up -> down
            return jnp.einsum(
                "e...f,efh->e...h", nn.silu(gi) * ui,
                w_down.astype(cfg.dtype))

        if cfg.moe_capacity_factor is None:
            # -- dense dispatch: every token through every expert -------
            combine = jnp.sum(
                jax.nn.one_hot(sel, e, dtype=jnp.float32)
                * weights[..., None], axis=-2)            # [b, s, e]
            gate = jnp.einsum("bsh,ehf->ebsf", xd, w_gate.astype(cfg.dtype))
            up = jnp.einsum("bsh,ehf->ebsf", xd, w_up.astype(cfg.dtype))
            out = experts(gate, up)                       # [e, b, s, h]
            y = jnp.einsum("ebsh,bse->bsh", out.astype(jnp.float32),
                           combine)
        else:
            # -- capacity dispatch (switch-transformer; GSPMD lowers the
            # dispatch/combine to all-to-alls over 'ep') ----------------
            import math
            n = b * s
            cap = max(math.ceil(cfg.moe_capacity_factor * k * n / e), 1)
            sel_f = sel.reshape(n, k)
            w_f = weights.reshape(n, k)
            dispatch = cfg.moe_dispatch
            if dispatch == "auto":
                # the einsum path materialises an [n, e, cap] dispatch
                # tensor (plus its [n, k, e, cap] one-hot ancestor if
                # XLA fails to fuse); above ~2^24 elements switch to the
                # sort path, whose intermediates are O(n*k + e*cap*h)
                dispatch = ("sort" if n * e * cap > (1 << 24)
                            else "einsum")
            if dispatch == "sort":
                ex_in, dest, tok_sorted, w_keep = _sort_dispatch(
                    xd.reshape(n, h), sel_f, w_f, e, cap)
            else:
                # position of each (token, slot) inside its expert's
                # buffer, slot-major priority (switch/GShard
                # convention): every token's top-1 claim fills before
                # any token's top-2, so tight capacity drops secondary
                # routes first
                sel_1h = jax.nn.one_hot(sel_f, e, dtype=jnp.int32)
                slot_totals = jnp.sum(sel_1h, axis=0)           # [k, e]
                prev_slots = (jnp.cumsum(slot_totals, axis=0)
                              - slot_totals)                    # [k, e]
                prev_tokens = jnp.cumsum(sel_1h, axis=0) - sel_1h
                pos = jnp.sum(
                    (prev_slots[None, :, :] + prev_tokens) * sel_1h,
                    axis=-1)                                    # [n, k]
                keep = pos < cap
                # [n, k, e, cap] slot one-hots -> summed over k
                slot_1h = (jax.nn.one_hot(sel_f, e, dtype=jnp.float32)[..., None]
                           * jax.nn.one_hot(jnp.where(keep, pos, 0), cap,
                                            dtype=jnp.float32)[:, :, None, :]
                           * keep[..., None, None])
                disp = jnp.sum(slot_1h, axis=1).astype(xd.dtype)
                comb = jnp.sum(slot_1h * w_f[..., None, None], axis=1)
                ex_in = jnp.einsum("nec,nh->ech", disp, xd.reshape(n, h))
            gate = jnp.einsum("ech,ehf->ecf", ex_in,
                              w_gate.astype(cfg.dtype))
            up = jnp.einsum("ech,ehf->ecf", ex_in, w_up.astype(cfg.dtype))
            out = experts(gate, up)                            # [e, cap, h]
            if dispatch == "sort":
                out_flat = out.reshape(e * cap, h).astype(jnp.float32)
                contrib = out_flat[dest] * w_keep[:, None]     # [n*k, h]
                y = jnp.zeros((n, h), jnp.float32).at[tok_sorted].add(
                    contrib).reshape(b, s, h)
            else:
                y = jnp.einsum("ech,nec->nh", out.astype(jnp.float32),
                               comb).reshape(b, s, h)

        # Load-balancing auxiliary loss (switch/mixtral-style top-k)
        # exposed via sow: count all k selections per token, divided by
        # k, so load on secondary experts feeds the balance signal.
        probs = jax.nn.softmax(logits, axis=-1)
        frac_tokens = jnp.mean(
            jnp.sum(jax.nn.one_hot(sel, e, dtype=jnp.float32), axis=-2),
            axis=(0, 1)) / k
        frac_probs = jnp.mean(probs, axis=(0, 1))
        self.sow("intermediates", "moe_aux_loss",
                 e * jnp.sum(frac_tokens * frac_probs))
        return y.astype(cfg.dtype)
