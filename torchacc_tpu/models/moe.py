"""Mixture-of-experts MLP with expert parallelism.

Beyond the reference: TorchAcc has no MoE/EP implementation (SURVEY.md
§2.3 — its differentiable all-to-all cp/utils.py:262-299 is the building
block it would need).  Here experts live on an 'expert' logical axis
(sharded over the 'ep' mesh axis); token routing uses a dense
dispatch/combine einsum formulation, which GSPMD lowers to all-to-alls
across 'ep' automatically — the idiomatic TPU MoE (switch-transformer
style) rather than a hand-written NCCL a2a.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp


class MoEMlp(nn.Module):
    """Top-k token-choice MoE: capacity-free dense dispatch, or
    switch-transformer capacity dispatch (``cfg.moe_capacity_factor``).

    For modest expert counts the dense formulation (every token scored
    against every expert, weighted-combined with a top-k mask) is both
    exactly correct (no token dropping) and MXU-friendly; its FLOPs
    scale with e.  The capacity path computes only
    ``C = ceil(cf * k * tokens / e)`` slots per expert — FLOPs
    independent of e (the mixtral-8x7b regime) — at the cost of
    dropping over-capacity tokens (standard switch behaviour).
    """
    cfg: object  # ModelConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        e = cfg.num_experts
        k = cfg.num_experts_per_tok
        h = cfg.hidden_size
        f = cfg.ffn_size
        b, s, _ = x.shape

        router = nn.Dense(e, use_bias=False, name="router",
                          dtype=jnp.float32, param_dtype=cfg.param_dtype,
                          kernel_init=nn.initializers.normal(0.02))
        logits = router(x.astype(jnp.float32))            # [b, s, e]
        weights, sel = jax.lax.top_k(logits, k)           # [b, s, k]
        weights = jax.nn.softmax(weights, axis=-1)

        init = nn.initializers.normal(0.02)
        w_gate = self.param("experts/gate", init, (e, h, f), cfg.param_dtype)
        w_up = self.param("experts/up", init, (e, h, f), cfg.param_dtype)
        w_down = self.param("experts/down", init, (e, f, h), cfg.param_dtype)
        xd = x.astype(cfg.dtype)

        def experts(gi, ui):
            # shared expert FFN body: silu(gate) * up -> down
            return jnp.einsum(
                "e...f,efh->e...h", nn.silu(gi) * ui,
                w_down.astype(cfg.dtype))

        if cfg.moe_capacity_factor is None:
            # -- dense dispatch: every token through every expert -------
            combine = jnp.sum(
                jax.nn.one_hot(sel, e, dtype=jnp.float32)
                * weights[..., None], axis=-2)            # [b, s, e]
            gate = jnp.einsum("bsh,ehf->ebsf", xd, w_gate.astype(cfg.dtype))
            up = jnp.einsum("bsh,ehf->ebsf", xd, w_up.astype(cfg.dtype))
            out = experts(gate, up)                       # [e, b, s, h]
            y = jnp.einsum("ebsh,bse->bsh", out.astype(jnp.float32),
                           combine)
        else:
            # -- capacity dispatch (switch-transformer; GSPMD lowers the
            # dispatch/combine einsums to all-to-alls over 'ep') --------
            import math
            n = b * s
            cap = max(math.ceil(cfg.moe_capacity_factor * k * n / e), 1)
            sel_f = sel.reshape(n, k)
            w_f = weights.reshape(n, k)
            # position of each (token, slot) inside its expert's buffer,
            # slot-major priority (switch/GShard convention): every
            # token's top-1 claim fills before any token's top-2, so
            # tight capacity drops secondary routes first
            sel_1h = jax.nn.one_hot(sel_f, e, dtype=jnp.int32)  # [n, k, e]
            slot_totals = jnp.sum(sel_1h, axis=0)               # [k, e]
            prev_slots = (jnp.cumsum(slot_totals, axis=0)
                          - slot_totals)                        # [k, e]
            prev_tokens = jnp.cumsum(sel_1h, axis=0) - sel_1h   # [n, k, e]
            pos = jnp.sum(
                (prev_slots[None, :, :] + prev_tokens) * sel_1h,
                axis=-1)                                        # [n, k]
            keep = pos < cap
            # [n, k, e, cap] slot one-hots -> summed over k to [n, e, cap]
            slot_1h = (jax.nn.one_hot(sel_f, e, dtype=jnp.float32)[..., None]
                       * jax.nn.one_hot(jnp.where(keep, pos, 0), cap,
                                        dtype=jnp.float32)[:, :, None, :]
                       * keep[..., None, None])
            disp = jnp.sum(slot_1h, axis=1).astype(xd.dtype)   # [n, e, cap]
            comb = jnp.sum(slot_1h * w_f[..., None, None], axis=1)
            ex_in = jnp.einsum("nec,nh->ech", disp, xd.reshape(n, h))
            gate = jnp.einsum("ech,ehf->ecf", ex_in,
                              w_gate.astype(cfg.dtype))
            up = jnp.einsum("ech,ehf->ecf", ex_in, w_up.astype(cfg.dtype))
            out = experts(gate, up)                            # [e, cap, h]
            y = jnp.einsum("ech,nec->nh", out.astype(jnp.float32),
                           comb).reshape(b, s, h)

        # Load-balancing auxiliary loss (switch/mixtral-style top-k)
        # exposed via sow: count all k selections per token, divided by
        # k, so load on secondary experts feeds the balance signal.
        probs = jax.nn.softmax(logits, axis=-1)
        frac_tokens = jnp.mean(
            jnp.sum(jax.nn.one_hot(sel, e, dtype=jnp.float32), axis=-2),
            axis=(0, 1)) / k
        frac_probs = jnp.mean(probs, axis=(0, 1))
        self.sow("intermediates", "moe_aux_loss",
                 e * jnp.sum(frac_tokens * frac_probs))
        return y.astype(cfg.dtype)
