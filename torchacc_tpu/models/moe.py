"""Mixture-of-experts MLP with expert parallelism.

Beyond the reference: TorchAcc has no MoE/EP implementation (SURVEY.md
§2.3 — its differentiable all-to-all cp/utils.py:262-299 is the building
block it would need).  Here experts live on an 'expert' logical axis
(sharded over the 'ep' mesh axis); token routing uses a dense
dispatch/combine einsum formulation, which GSPMD lowers to all-to-alls
across 'ep' automatically — the idiomatic TPU MoE (switch-transformer
style) rather than a hand-written NCCL a2a.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp


class MoEMlp(nn.Module):
    """Top-k token-choice MoE with capacity-free dense dispatch.

    For modest expert counts the dense formulation (every token scored
    against every expert, weighted-combined with a top-k mask) is both
    exactly correct (no token dropping) and MXU-friendly.  A capacity-
    based sparse path can replace it without changing the interface.
    """
    cfg: object  # ModelConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        e = cfg.num_experts
        k = cfg.num_experts_per_tok
        h = cfg.hidden_size
        f = cfg.ffn_size
        b, s, _ = x.shape

        router = nn.Dense(e, use_bias=False, name="router",
                          dtype=jnp.float32, param_dtype=cfg.param_dtype,
                          kernel_init=nn.initializers.normal(0.02))
        logits = router(x.astype(jnp.float32))            # [b, s, e]
        weights, sel = jax.lax.top_k(logits, k)           # [b, s, k]
        weights = jax.nn.softmax(weights, axis=-1)
        # [b, s, e] combine weights (zero for unselected experts)
        combine = jnp.sum(
            jax.nn.one_hot(sel, e, dtype=jnp.float32) * weights[..., None],
            axis=-2)

        init = nn.initializers.normal(0.02)
        w_gate = self.param("experts/gate", init, (e, h, f), cfg.param_dtype)
        w_up = self.param("experts/up", init, (e, h, f), cfg.param_dtype)
        w_down = self.param("experts/down", init, (e, f, h), cfg.param_dtype)

        xd = x.astype(cfg.dtype)
        # Dense per-expert compute; GSPMD shards the 'e' dim over the ep
        # mesh axis, turning these einsums into expert-parallel work.
        gate = jnp.einsum("bsh,ehf->ebsf", xd, w_gate.astype(cfg.dtype))
        up = jnp.einsum("bsh,ehf->ebsf", xd, w_up.astype(cfg.dtype))
        act = nn.silu(gate) * up
        out = jnp.einsum("ebsf,efh->ebsh", act, w_down.astype(cfg.dtype))
        y = jnp.einsum("ebsh,bse->bsh", out.astype(jnp.float32), combine)

        # Load-balancing auxiliary loss (switch/mixtral-style top-k)
        # exposed via sow: count all k selections per token, divided by
        # k, so load on secondary experts feeds the balance signal.
        probs = jax.nn.softmax(logits, axis=-1)
        frac_tokens = jnp.mean(
            jnp.sum(jax.nn.one_hot(sel, e, dtype=jnp.float32), axis=-2),
            axis=(0, 1)) / k
        frac_probs = jnp.mean(probs, axis=(0, 1))
        self.sow("intermediates", "moe_aux_loss",
                 e * jnp.sum(frac_tokens * frac_probs))
        return y.astype(cfg.dtype)
