"""Decoder-only transformer LM family (flax.linen).

The reference accelerates existing torch models (GPT-2 via HF CLM
benchmarks/transformer.py:33-220, Llama/Qwen via transformers patches
utils/patch.py:224-301, qwen_patch.py).  The TPU-native framework ships
its own model zoo instead of monkeypatching: one configurable module
covers the GPT-2 class (learned positions, LayerNorm, GELU) and the
Llama/Qwen class (RoPE, RMSNorm, SwiGLU, GQA, optional qkv bias).
HF-trained weights are ingested by the converter in models/hf.py.

Layers are stacked with ``nn.scan`` (single compiled block, layer dim on
every param) — this keeps compile time O(1) in depth and gives pipeline
parallelism a natural stage-stacked layout.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from torchacc_tpu.ops.attn import attention


@dataclass(frozen=True)
class ModelConfig:
    vocab_size: int = 32000
    hidden_size: int = 512
    num_layers: int = 4
    num_heads: int = 8
    num_kv_heads: Optional[int] = None      # None = MHA; < num_heads = GQA
    head_dim: Optional[int] = None          # None = hidden/heads
    intermediate_size: Optional[int] = None  # None = 4x hidden (gelu) / llama rule
    max_seq_len: int = 2048
    pos_emb: str = "rope"                   # 'rope' | 'learned' | 'alibi'
    # 'rmsnorm1p' is the Gemma variant: effective scale is (1 + w) with
    # w zero-initialised (HF GemmaRMSNorm)
    # 'rmsnorm' | 'layernorm' | 'rmsnorm1p' | 'layernorm1p' (nemotron:
    # zero-centred (1+w) scale AND bias over a mean-subtracted norm)
    norm: str = "rmsnorm"
    # 'geglu' is Gemma's gated tanh-GELU (gelu_pytorch_tanh on the gate);
    # 'gelu' (tanh approx), 'gelu_exact' (gpt-neox erf) and 'relu2'
    # (nemotron square-relu) are NON-gated 2-matrix MLPs
    activation: str = "swiglu"  # swiglu | gelu | geglu | relu2 | gelu_exact
    # Gemma multiplies token embeddings by sqrt(hidden_size)
    embed_scale: bool = False
    # Gemma2 final-logit soft-capping: logits = c * tanh(logits / c);
    # 0 disables.  Applied in the plain head, the fused-CE head
    # (ops/fused.py) and the 1F1B last-stage head alike.
    logit_softcap: float = 0.0
    # phi-2-style parallel residual: x + attn(ln1(x)) + mlp(ln1(x)) —
    # ONE shared pre-norm, no ln2 (HF PhiDecoderLayer / CohereDecoderLayer).
    # parallel_block_shared_norm=False is GPT-NeoX's variant: the mlp
    # branch reads its OWN pre-norm (x + attn(ln1(x)) + mlp(ln2(x)))
    parallel_block: bool = False
    parallel_block_shared_norm: bool = True
    head_bias: bool = False                 # bias on the lm_head (phi-2)
    norm_bias: bool = True                  # layernorm bias (False: cohere)
    rope_interleaved: bool = False          # cohere pairwise rope layout
    # Cohere logit multiplier; applied by SCALING the final-normed hidden
    # (logits*s == (x*s)@W), so every head path — plain, fused-CE,
    # tp-vocab-parallel, pp decode — inherits it from one place
    logit_scale: float = 1.0
    qkv_bias: bool = False                  # Qwen2 style
    o_bias: bool = False                    # bias on o_proj (llama
    #                                         attention_bias covers it;
    #                                         qwen2's does not)
    mlp_bias: bool = False                  # biases on the mlp denses
    tie_embeddings: bool = False
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16               # activation dtype
    param_dtype: Any = jnp.float32
    scan_layers: bool = True
    remat: bool = False                     # remat each block (memory.gc)
    remat_policy: str = "nothing"           # see utils/remat.py
    # selective remat (reference gc_cls/gc_cnt, utils/checkpoint.py:67-81):
    # remat_cls picks WHICH submodules remat ('Block' = the whole decoder
    # layer; 'Attention' / 'Mlp' / 'MoEMlp' remat only that part);
    # remat_cnt remats only the first N layers (None = all).
    remat_cls: Optional[Tuple[str, ...]] = None
    remat_cnt: Optional[int] = None
    attention_impl: str = "auto"
    window: Tuple[int, int] = (-1, -1)      # sliding-window attention
    # Gemma2-style attention-score soft-capping: scores = c * tanh(s/c)
    # applied after the q-scale, before mask/softmax; 0 disables.
    # Implemented by both the Pallas kernel and the XLA attention.
    attn_logit_softcap: float = 0.0
    # query scaling override: None = head_dim ** -0.5; Gemma2 sets
    # query_pre_attn_scalar ** -0.5
    query_scale: Optional[float] = None
    # Gemma2 sandwich norms: extra RMSNorms AFTER attention and mlp
    # (HF post_attention_layernorm / post_feedforward_layernorm), adding
    # ln1_post / ln2_post params to each block
    sandwich_norms: bool = False
    # Gemma3 qk-norm: per-head-dim RMSNorm on q and k after projection,
    # before rope (adds q_norm / k_norm params to each attention)
    qk_norm: bool = False
    # OLMo2 variant of qk_norm: the RMSNorm runs over the FLAT q/k
    # projection (heads*head_dim jointly, one scale vector per
    # projection) instead of per-head-dim
    qk_norm_proj: bool = False
    # 'pre' (llama: x + f(norm(x))) or 'post' (OLMo2: x + norm(f(x)));
    # gemma2's sandwich_norms composes with 'pre' only
    norm_placement: str = "pre"
    # Llama-3.1 frequency-banded rope scaling (HF rope_type='llama3'):
    # (factor, low_freq_factor, high_freq_factor, original_max_pos)
    rope_llama3: Optional[Tuple[float, float, float, float]] = None
    # Phi-3.5/4 'longrope': (short_factor, long_factor,
    # original_max_pos, attention_factor) — per-dim inv_freq divisors,
    # long set active once positions exceed original_max_pos, cos/sin
    # scaled by attention_factor (None = HF's sqrt(1+ln(s)/ln(orig)))
    rope_longrope: Optional[Tuple[Tuple[float, ...], Tuple[float, ...],
                                  float, Optional[float]]] = None
    # fraction of head_dim that rotates (phi-4-mini: 0.75); the
    # remaining dims pass through rope untouched
    partial_rotary: float = 1.0
    # YaRN (qwen 128k variants): (factor, original_max_pos, beta_fast,
    # beta_slow, attention_factor, truncate) — NTK-by-parts inv_freq
    # interpolation with a linear ramp between the correction dims,
    # cos/sin scaled by the attention factor (None = HF's
    # 0.1*ln(factor)+1 for factor > 1, else 1)
    rope_yarn: Optional[Tuple[float, float, float, float,
                              Optional[float], bool]] = None
    # Gemma3 dual rope bases: 'sliding' pattern layers use this theta
    # (local 10k) while 'global' layers use cfg.rope_theta (1M);
    # None = every layer uses cfg.rope_theta
    rope_local_theta: Optional[float] = None
    # linear rope position scaling (HF rope_scaling type 'linear'):
    # rope sees positions / rope_scale.  Under a gemma3 layer_pattern
    # the factor applies to GLOBAL layers only (sliding layers reset to
    # 1, matching HF's unscaled local rotary)
    rope_scale: float = 1.0
    # heterogeneous per-layer attention (gemma2/3): a cycle of
    # 'sliding' (uses cfg.window) | 'global' (full attention) applied as
    # layer i -> pattern[i % len]. None = every layer uses cfg.window.
    # Layers stay structurally identical (the pattern is param-free), so
    # the canonical stacked layout and checkpoints are unchanged;
    # execution uses the per-layer loop (scan_layers is ignored).
    layer_pattern: Optional[Tuple[str, ...]] = None
    # KV-cache decode mode (models/generate.py): __call__ consumes one
    # token per step, appending rotated k / raw v into the 'cache'
    # collection and attending over the filled prefix
    decode: bool = False
    # KV-cache length; None = max_seq_len.  generate() sets it to
    # prompt_len + max_new_tokens so short generations do not allocate
    # (or attend over) a max_seq_len-sized cache
    cache_len: Optional[int] = None
    # post-softmax attention dropout (reference flash_attn.py:418-423);
    # active only when the caller passes deterministic=False + a seed
    attn_dropout: float = 0.0
    # quantized forward matmuls (ops/quantized_matmul.py): the selected
    # dense sites run int8/fp8 with delayed per-tensor activation
    # scaling (amax history in the 'quant' collection) + just-in-time
    # per-channel weight scales; 'none' = bitwise legacy semantics.
    # Composes with the scan, unrolled and overlap_fsdp layer paths;
    # NOT with pp, layer_pattern, remat_cnt splits, or decode (the
    # guards in __call__ raise; generate() strips quant — inference
    # runs in the compute dtype).
    quant: str = "none"                     # 'none' | 'int8' | 'fp8'
    quant_sites: Tuple[str, ...] = ("attn", "mlp")
    quant_amax_history_len: int = 16
    quant_impl: str = "auto"                # 'auto' | 'pallas' | 'xla'
    # FSDP comm/compute overlap (PerfConfig.overlap_fsdp): run the
    # layers as the unrolled loop with the all-gather of layer i+1's
    # params issued before layer i's compute consumes its own —
    # decomposing the FSDP boundary so XLA can overlap the gather with
    # the compute ladder (parallel/sharding.fsdp_gather_params)
    overlap_fsdp: bool = False
    # context parallelism: attention runs in a shard_map region with the
    # sequence dim sharded over ('sp', 'spu') — see ops/context_parallel
    context_parallel: bool = False
    # pipeline parallelism: the layer stack runs as a circulating-micro-
    # batch pipeline over the 'pp' mesh axis — see parallel/pp.py
    pp_size: int = 1
    pp_num_micro: int = 1
    # interleaved pipeline: V non-adjacent layer chunks per stage
    # (Megatron virtual pipeline; parallel/pp.py virtual_stages)
    pp_virtual: int = 1
    # logical-axis rule table for activation sharding constraints; None =
    # parallel.sharding.DEFAULT_RULES (accelerate() injects make_rules(cfg))
    logical_axis_rules: Optional[Tuple] = None
    # 1F1B vocab-parallel head (pp_1f1b_forward_sum_count): False
    # restores the round-3 behavior of pinning the head weights
    # replicated inside the pipeline region
    tp_vocab_head: bool = True
    # MoE (0 = dense). See models/moe.py.
    num_experts: int = 0
    num_experts_per_tok: int = 2
    router_aux_weight: float = 0.01   # switch-style load-balance loss weight
    # capacity-dispatch mechanism (models/moe.py): 'einsum' = one-hot
    # dispatch/combine einsums (MXU-friendly at small n*e*cap), 'sort' =
    # argsort/scatter (no [n, e, cap] materialisation — the Mixtral-scale
    # answer), 'auto' = sort above ~2^24 dispatch elements
    moe_dispatch: str = "auto"
    # True (mixtral): softmax over the selected top-k logits (equals
    # HF's softmax-then-topk-then-renormalise).  False (qwen3-moe with
    # norm_topk_prob=false): combine weights are the UN-renormalised
    # full-softmax probs of the selected experts.
    moe_renorm_topk: bool = True
    # None = exact capacity-free dense dispatch (every token through
    # every expert — right for small e).  A float (e.g. 1.25) switches
    # to switch-transformer capacity dispatch: per-expert buffers of
    # ceil(cf * k * tokens / e) slots, FLOPs independent of e; tokens
    # over capacity are dropped (combine weight 0).
    moe_capacity_factor: Optional[float] = None

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads or self.num_heads

    @property
    def head_size(self) -> int:
        return self.head_dim or self.hidden_size // self.num_heads

    @property
    def ffn_size(self) -> int:
        if self.intermediate_size is not None:
            return self.intermediate_size
        if self.activation in ("swiglu", "geglu"):
            # llama sizing: 2/3 * 4h, rounded up to a multiple of 256
            # (keeps the matmul dims MXU-tile friendly).  Pass
            # intermediate_size explicitly to pin an exact width.
            return ((8 * self.hidden_size // 3) + 255) // 256 * 256
        return 4 * self.hidden_size

    def num_params(self) -> int:
        """Analytic parameter count (for MFU math) — exact per family:
        biases (qkv/o/mlp/head), sandwich and qk norms, parallel-block
        norm counts, and biased LayerNorms are all accounted."""
        h, v = self.hidden_size, self.vocab_size
        d = self.head_size
        emb = v * h + (self.max_seq_len * h if self.pos_emb == "learned" else 0)
        attn = h * (self.num_heads * d) + h * (2 * self.kv_heads * d) \
            + (self.num_heads * d) * h
        if self.qkv_bias:
            attn += (self.num_heads + 2 * self.kv_heads) * d
        if self.o_bias:
            attn += h
        if self.qk_norm:
            attn += ((self.num_heads + self.kv_heads) * d
                     if self.qk_norm_proj else 2 * d)
        if self.activation in ("swiglu", "geglu"):
            mlp = 3 * h * self.ffn_size
            if self.mlp_bias:
                mlp += 2 * self.ffn_size + h
        else:
            mlp = 2 * h * self.ffn_size
            if self.mlp_bias:
                mlp += self.ffn_size + h
        if self.num_experts > 0:
            mlp = mlp * self.num_experts + h * self.num_experts
        norm_size = (2 * h
                     if self.norm in ("layernorm", "layernorm1p")
                     and self.norm_bias else h)
        per_block = (1 if self.parallel_block
                     and self.parallel_block_shared_norm
                     else (4 if self.sandwich_norms else 2))
        norms = (per_block * self.num_layers + 1) * norm_size
        out = 0 if self.tie_embeddings else v * h
        if self.head_bias:
            out += v
        return emb + self.num_layers * (attn + mlp) + norms + out


def softcap(logits: jax.Array, cap: float) -> jax.Array:
    """Gemma2 logit soft-capping ``c * tanh(logits / c)``; cap <= 0 is a
    no-op.  The single definition keeps the plain, fused-CE and 1F1B
    heads bit-identical."""
    if cap <= 0.0:
        return logits
    return jnp.tanh(logits / cap) * cap


def scale_hidden(cfg: "ModelConfig", xn: jax.Array) -> jax.Array:
    """Apply cohere's logit_scale to the final-normed hidden
    (logits * s == (x * s) @ W), so every head path — the module tail,
    ``head_logits`` (pp decode), the 1F1B head, and the fused-CE path
    fed by ``return_hidden`` — inherits the multiplier from ONE
    definition (same no-drift rationale as :func:`softcap`)."""
    if cfg.logit_scale == 1.0:
        return xn
    return xn * jnp.asarray(cfg.logit_scale, xn.dtype)


def _rope(q: jax.Array, k: jax.Array, positions: jax.Array,
          cfg: "ModelConfig") -> Tuple[jax.Array, jax.Array]:
    """Rotary embeddings, llama convention (half-split, not interleaved —
    matches HF transformers so converted weights agree).

    Scaling variants (all from the per-layer cfg, so gemma3's dual-base
    pattern composes):

    - ``rope_llama3`` — Llama-3.1 frequency banding: long wavelengths
      divide by ``factor``, short ones stay, the band between
      interpolates smoothly.  Every 3.1+ release ships this.
    - ``rope_longrope`` — Phi-3.5/4: per-dim inv_freq divisors with the
      LONG set activating once any position exceeds the original
      context (a traced switch: both static sets are built, jnp.where
      selects), and cos/sin scaled by the attention factor.  The
      ``jnp.max(positions)`` is a reduction that can lower to a small
      collective when positions are sharded (cp) — measured harmless
      (compiles+runs under pp×dp, 1f1b and cp-ring;
      test_longrope_composes_with_parallelism) and CSE dedupes it in
      the unrolled-layer path; revisit only if a partitioner change
      breaks that test.
    - ``partial_rotary`` < 1 — only the first ``d * partial`` head dims
      rotate (phi-4-mini: 0.75); the rest pass through.
    """
    import math as _math

    d = q.shape[-1]
    rot_d = int(d * cfg.partial_rotary)
    theta = cfg.rope_theta
    freqs = 1.0 / (theta ** (jnp.arange(0, rot_d, 2, dtype=jnp.float32)
                             / rot_d))
    scale = jnp.float32(1.0)
    if cfg.rope_llama3 is not None:
        factor, lo, hi, old_len = cfg.rope_llama3
        wavelen = 2.0 * _math.pi / freqs
        low_wl, high_wl = old_len / lo, old_len / hi
        smooth = (old_len / wavelen - lo) / (hi - lo)
        scaled = jnp.where(wavelen > low_wl, freqs / factor, freqs)
        smoothed = ((1.0 - smooth) / factor + smooth) * freqs
        freqs = jnp.where((wavelen >= high_wl) & (wavelen <= low_wl),
                          smoothed, scaled)
    if cfg.rope_yarn is not None:
        # YaRN NTK-by-parts (HF _compute_yarn_parameters): interpolate
        # per-dim between the original freqs (short wavelengths) and
        # position-interpolated freqs (long), with a linear ramp
        # between the beta_fast/beta_slow correction dims
        factor, old_len, bfast, bslow, attn_f, truncate = cfg.rope_yarn

        def corr_dim(beta):
            return (rot_d * _math.log(old_len / (beta * 2 * _math.pi))
                    / (2 * _math.log(theta)))

        low, high = corr_dim(bfast), corr_dim(bslow)
        if truncate:
            low, high = _math.floor(low), _math.ceil(high)
        low, high = max(low, 0), min(high, rot_d - 1)
        if low == high:
            high += 0.001  # HF's singularity guard
        ramp = jnp.clip(
            (jnp.arange(rot_d // 2, dtype=jnp.float32) - low)
            / (high - low), 0.0, 1.0)
        mask = 1.0 - ramp                       # 1 = keep original
        freqs = (freqs / factor) * (1.0 - mask) + freqs * mask
        if attn_f is None:
            attn_f = (1.0 if factor <= 1.0
                      else 0.1 * _math.log(factor) + 1.0)
        scale = jnp.float32(attn_f)
    if cfg.rope_longrope is not None:
        short_f, long_f, old_len, attn_f = cfg.rope_longrope
        short = freqs / jnp.asarray(short_f, jnp.float32)
        long = freqs / jnp.asarray(long_f, jnp.float32)
        # HF switches factor sets when the sequence grows past the
        # original context; positions are traced, so build both static
        # sets and select (one jnp.where, no retrace)
        is_long = jnp.max(positions) + 1 > old_len
        freqs = jnp.where(is_long, long, short)
        if attn_f is None:
            s = cfg.max_seq_len / old_len
            attn_f = (1.0 if s <= 1.0
                      else _math.sqrt(1.0 + _math.log(s)
                                      / _math.log(old_len)))
        scale = jnp.float32(attn_f)
    angles = positions[..., None].astype(jnp.float32) * freqs  # [b,s,rd/2]
    cos = (jnp.cos(angles) * scale)[:, :, None, :]
    sin = (jnp.sin(angles) * scale)[:, :, None, :]

    def rot(x):
        xf = x.astype(jnp.float32)
        xr, xp = xf[..., :rot_d], xf[..., rot_d:]
        if cfg.rope_interleaved:
            # cohere: dims pair as (even, odd) instead of llama's half
            # split; rotate each pair and restore the interleaving
            x1, x2 = xr[..., 0::2], xr[..., 1::2]
            out = jnp.stack([x1 * cos - x2 * sin, x2 * cos + x1 * sin],
                            axis=-1).reshape(xr.shape)
        else:
            x1, x2 = jnp.split(xr, 2, axis=-1)
            out = jnp.concatenate(
                [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
        if rot_d < d:
            out = jnp.concatenate([out, xp], axis=-1)
        return out.astype(x.dtype)

    return rot(q), rot(k)


class Norm(nn.Module):
    cfg: ModelConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        xf = x.astype(jnp.float32)
        if cfg.norm in ("rmsnorm", "rmsnorm1p"):
            one_p = cfg.norm == "rmsnorm1p"
            # Gemma convention: weight stored as w, effective scale 1 + w,
            # zero-initialised (HF GemmaRMSNorm)
            scale = self.param(
                "scale",
                nn.initializers.zeros if one_p else nn.initializers.ones,
                (x.shape[-1],), cfg.param_dtype)
            y = xf * jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True)
                                   + cfg.norm_eps)
            sf = scale.astype(jnp.float32)
            if one_p:
                sf = 1.0 + sf
            return (y * sf).astype(cfg.dtype)
        one_p = cfg.norm == "layernorm1p"   # nemotron: stored w, scale 1+w
        scale = self.param(
            "scale", nn.initializers.zeros if one_p else nn.initializers.ones,
            (x.shape[-1],), cfg.param_dtype)
        mean = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
        y = (xf - mean) * jax.lax.rsqrt(var + cfg.norm_eps)
        sf = scale.astype(jnp.float32)
        y = y * (1.0 + sf if one_p else sf)
        if cfg.norm_bias:   # cohere's LayerNorm carries no bias
            bias = self.param("bias", nn.initializers.zeros,
                              (x.shape[-1],), cfg.param_dtype)
            y = y + bias.astype(jnp.float32)
        return y.astype(cfg.dtype)


def alibi_slopes(num_heads: int) -> Tuple[float, ...]:
    """Standard ALiBi per-head slopes (geometric 2^(-8i/n) with the
    paper's interpolation for non-power-of-two head counts) — the same
    table the reference's models pass as ``alibi_slopes``."""
    import math

    def pow2(n):
        start = 2.0 ** (-8.0 / n)
        return [start ** (i + 1) for i in range(n)]

    if math.log2(num_heads).is_integer():
        return tuple(pow2(num_heads))
    m = 2 ** math.floor(math.log2(num_heads))
    return tuple(pow2(m) + pow2(2 * m)[0::2][:num_heads - m])


def _layer_seed(dropout_seed, layer_idx):
    """Decorrelate dropout across layers: mix the layer index into the
    seed (the hash itself only sees batch/head/q/k coordinates)."""
    s = jnp.asarray(dropout_seed, jnp.int32).astype(jnp.uint32)
    li = jnp.asarray(layer_idx, jnp.int32).astype(jnp.uint32)
    return (s + li * jnp.uint32(0x9E3779B9)).astype(jnp.int32)


def quant_site_on(cfg: "ModelConfig", site: str) -> bool:
    """Whether a dense ``site`` ('attn' | 'mlp' | 'head') runs the
    quantized matmul.  Decode always runs the plain dense (generate()
    strips quant anyway — inference is compute-dtype); the param
    layouts are identical either way, so this only picks execution."""
    return (cfg.quant != "none" and site in cfg.quant_sites
            and not cfg.decode)


def _quant_dense(cfg: "ModelConfig", name, features, axis, use_bias):
    """The quantized drop-in for an ``nn.DenseGeneral``/``nn.Dense``
    site: identical param names/shapes/init (same RNG stream, same
    checkpoints), quantized forward, delayed-scaling amax history in
    the 'quant' collection (ops/quantized_matmul.QuantDenseGeneral)."""
    from torchacc_tpu.ops.quantized_matmul import QuantDenseGeneral
    return QuantDenseGeneral(
        features=features, axis=axis, use_bias=use_bias, name=name,
        dtype=cfg.dtype, param_dtype=cfg.param_dtype,
        kernel_init=nn.initializers.normal(0.02),
        quant=cfg.quant, quant_impl=cfg.quant_impl,
        amax_history_len=cfg.quant_amax_history_len)


class Attention(nn.Module):
    cfg: ModelConfig

    @nn.compact
    def __call__(self, x, positions, segment_ids=None, dropout_seed=None):
        cfg = self.cfg
        d = cfg.head_size
        if quant_site_on(cfg, "attn"):
            dense = lambda name, heads: _quant_dense(
                cfg, name, (heads, d), -1, cfg.qkv_bias)
        else:
            dense = lambda name, heads: nn.DenseGeneral(
                features=(heads, d), use_bias=cfg.qkv_bias, name=name,
                dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                kernel_init=nn.initializers.normal(0.02))
        from torchacc_tpu.parallel.sharding import (
            DEFAULT_RULES,
            activation_constraint,
        )
        rules = cfg.logical_axis_rules or DEFAULT_RULES
        q = dense("q_proj", cfg.num_heads)(x)
        k = dense("k_proj", cfg.kv_heads)(x)
        v = dense("v_proj", cfg.kv_heads)(x)
        # megatron TP activation layout: heads sharded on 'tp'
        q = activation_constraint(q, ("batch", "seq", "heads", None), rules)
        k = activation_constraint(k, ("batch", "seq", "heads", None), rules)
        v = activation_constraint(v, ("batch", "seq", "heads", None), rules)
        if cfg.qk_norm:
            if cfg.qk_norm_proj:
                # OLMo2: RMSNorm over the FLAT projection (heads*d
                # jointly, scale of nh*d / nk*d) before the head split's
                # rope — HF Olmo2Attention norms the projection output
                bq, sq_ = q.shape[:2]
                q = Norm(cfg, name="q_norm")(
                    q.reshape(bq, sq_, -1)).reshape(q.shape)
                k = Norm(cfg, name="k_norm")(
                    k.reshape(bq, sq_, -1)).reshape(k.shape)
            else:
                # Gemma3/Qwen3: per-head-dim RMSNorm on q and k after
                # projection, BEFORE rope (HF q_norm/k_norm)
                q = Norm(cfg, name="q_norm")(q)
                k = Norm(cfg, name="k_norm")(k)
        if cfg.pos_emb == "rope":
            rp = (positions.astype(jnp.float32) / cfg.rope_scale
                  if cfg.rope_scale != 1.0 else positions)
            q, k = _rope(q, k, rp, cfg)
        # names for the selective-remat policies (utils/remat.py): saving
        # post-rope q/k/v means the backward recomputes only the cheap
        # norms/elementwise ops, never the projections or the rope
        from jax.ad_checkpoint import checkpoint_name
        q = checkpoint_name(q, "qkv_proj")
        k = checkpoint_name(k, "qkv_proj")
        v = checkpoint_name(v, "qkv_proj")
        slopes = (jnp.asarray(alibi_slopes(cfg.num_heads), jnp.float32)
                  if cfg.pos_emb == "alibi" else None)

        # -- KV cache (prefill writes the prompt's k/v; decode appends
        # one position and attends over the filled prefix).  Not created
        # at init so checkpoints/params stay cache-free. ----------------
        if self.has_variable("cache", "k") or (
                self.is_mutable_collection("cache")
                and not self.is_initializing()):
            b, s = x.shape[0], x.shape[1]
            max_len = cfg.cache_len or cfg.max_seq_len
            ck = self.variable("cache", "k", jnp.zeros,
                               (b, max_len, cfg.kv_heads, d), cfg.dtype)
            cv = self.variable("cache", "v", jnp.zeros,
                               (b, max_len, cfg.kv_heads, d), cfg.dtype)
            cidx = self.variable("cache", "idx",
                                 lambda: jnp.zeros((), jnp.int32))
            if cfg.decode:
                pos = cidx.value
                ck.value = jax.lax.dynamic_update_slice(
                    ck.value, k.astype(cfg.dtype), (0, pos, 0, 0))
                cv.value = jax.lax.dynamic_update_slice(
                    cv.value, v.astype(cfg.dtype), (0, pos, 0, 0))
                if cfg.context_parallel:
                    # keep the slot dim sp-sharded through the decode
                    # scan (see the prefill-side constraint below)
                    ck.value = activation_constraint(
                        ck.value, ("batch", "seq", None, None), rules)
                    cv.value = activation_constraint(
                        cv.value, ("batch", "seq", None, None), rules)
                cidx.value = pos + s
                # ragged (left-padded) prompts: prefill banked per-slot
                # validity in the 'seg' cache; decode-appended tokens are
                # always real.  Segment equality masks each row's pad
                # slots out of the attention.
                qseg = kvseg = None
                if self.has_variable("cache", "seg"):
                    cseg = self.variable("cache", "seg", jnp.ones,
                                         (b, max_len), jnp.int32)
                    cseg.value = jax.lax.dynamic_update_slice(
                        cseg.value, jnp.ones((b, s), jnp.int32), (0, pos))
                    qseg = jnp.ones((b, s), jnp.int32)
                    kvseg = cseg.value
                # the query's TRUE position is pos while it sits at row 0
                # of a [1, kv_len] score matrix: q_offset re-aligns the
                # geometry so the shared mask/bias machinery gives exact
                # causal (<= pos), sliding-window, and ALiBi behavior over
                # the filled prefix (positions > pos hold zeros and fall
                # outside the causal mask).  kv_len comes from the LIVE
                # cache (a pre-existing cache may be sized differently
                # than this cfg's cache_len).
                from torchacc_tpu.ops.attention import attention_reference
                kv_len = ck.value.shape[1]
                out = attention_reference(
                    q, ck.value, cv.value, causal=True, window=cfg.window,
                    scale=cfg.query_scale, alibi_slopes=slopes,
                    q_segment_ids=qseg, kv_segment_ids=kvseg,
                    q_offset=pos - (kv_len - s),
                    logit_softcap=cfg.attn_logit_softcap)
                return nn.DenseGeneral(
                    features=cfg.hidden_size, axis=(-2, -1),
                    use_bias=cfg.o_bias,
                    name="o_proj", dtype=cfg.dtype,
                    param_dtype=cfg.param_dtype,
                    kernel_init=nn.initializers.normal(0.02))(out)
            # prefill: bank the prompt's (rotated) k / v, then fall
            # through to the normal attention below
            ck.value = jax.lax.dynamic_update_slice(
                ck.value, k.astype(cfg.dtype), (0, 0, 0, 0))
            cv.value = jax.lax.dynamic_update_slice(
                cv.value, v.astype(cfg.dtype), (0, 0, 0, 0))
            if cfg.context_parallel:
                # long-context decode: the cache's SLOT dim shards over
                # the sequence axes, so per-device cache memory is
                # cache_len/sp — the point of cp decode.  Decode's
                # single-token DUS and the partial-softmax attention
                # over the sharded slots are GSPMD-handled.
                ck.value = activation_constraint(
                    ck.value, ("batch", "seq", None, None), rules)
                cv.value = activation_constraint(
                    cv.value, ("batch", "seq", None, None), rules)
            cidx.value = jnp.asarray(s, jnp.int32)
            if segment_ids is not None:
                # ragged (left-padded) prompts: bank per-slot validity so
                # decode can mask each row's pad slots (slots past the
                # prompt default to 1 = real, written again at decode)
                cseg = self.variable("cache", "seg", jnp.ones,
                                     (b, max_len), jnp.int32)
                cseg.value = jax.lax.dynamic_update_slice(
                    cseg.value, segment_ids.astype(jnp.int32), (0, 0))
        # per-layer decorrelation already happened in TransformerLM
        # (seeds_xs = _layer_seed(seed, arange(L)))
        dropout_p, seed = 0.0, None
        if cfg.attn_dropout > 0.0 and dropout_seed is not None:
            dropout_p = cfg.attn_dropout
            seed = dropout_seed
        if cfg.context_parallel:
            # scale and score softcap are both elementwise on the
            # pre-softmax scores, so the ring/ulysses LSE merge is exact
            # with them (each chunk caps the same per-score values the
            # global computation would)
            from torchacc_tpu.ops.context_parallel import cp_attention
            out = cp_attention(q, k, v, causal=True, window=cfg.window,
                               scale=cfg.query_scale,
                               logit_softcap=cfg.attn_logit_softcap,
                               q_segment_ids=segment_ids,
                               kv_segment_ids=segment_ids,
                               alibi_slopes=slopes, dropout_p=dropout_p,
                               dropout_seed=seed,
                               impl=cfg.attention_impl)
        else:
            out = attention(q, k, v, causal=True, window=cfg.window,
                            scale=cfg.query_scale,
                            q_segment_ids=segment_ids,
                            kv_segment_ids=segment_ids,
                            alibi_slopes=slopes, dropout_p=dropout_p,
                            dropout_seed=seed,
                            impl=cfg.attention_impl,
                            logit_softcap=cfg.attn_logit_softcap)
        if quant_site_on(cfg, "attn"):
            out = _quant_dense(cfg, "o_proj", cfg.hidden_size, (-2, -1),
                               cfg.o_bias)(out)
        else:
            out = nn.DenseGeneral(
                features=cfg.hidden_size, axis=(-2, -1),
                use_bias=cfg.o_bias,
                name="o_proj", dtype=cfg.dtype,
                param_dtype=cfg.param_dtype,
                kernel_init=nn.initializers.normal(0.02))(out)
        return out


class Mlp(nn.Module):
    cfg: ModelConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        if quant_site_on(cfg, "mlp"):
            dense = lambda name, feat: _quant_dense(
                cfg, name, feat, -1, cfg.mlp_bias)
        else:
            dense = lambda name, feat: nn.Dense(
                feat, use_bias=cfg.mlp_bias, name=name, dtype=cfg.dtype,
                param_dtype=cfg.param_dtype,
                kernel_init=nn.initializers.normal(0.02))
        from torchacc_tpu.parallel.sharding import (
            DEFAULT_RULES,
            activation_constraint,
        )
        from jax.ad_checkpoint import checkpoint_name
        if cfg.activation in ("swiglu", "geglu"):
            # named so 'save_attn_mlp' can save the ffn-width projections
            # (recompute becomes elementwise-only) while 'save_attn' leaves
            # them unsaved — they are the dominant activation cost
            gate = checkpoint_name(dense("gate_proj", cfg.ffn_size)(x),
                                   "mlp_gate_up")
            up = checkpoint_name(dense("up_proj", cfg.ffn_size)(x),
                                 "mlp_gate_up")
            # geglu = Gemma's gelu_pytorch_tanh gate (nn.gelu default is
            # the tanh approximation)
            act = nn.silu if cfg.activation == "swiglu" else nn.gelu
            h = act(gate) * up
        else:
            up = checkpoint_name(dense("up_proj", cfg.ffn_size)(x),
                                 "mlp_gate_up")
            if cfg.activation == "relu2":   # nemotron: square(relu(x))
                h = jnp.square(nn.relu(up))
            elif cfg.activation == "gelu_exact":   # gpt-neox erf gelu
                h = nn.gelu(up, approximate=False)
            else:
                h = nn.gelu(up)
        # megatron TP: ffn hidden sharded on 'tp' (column-parallel out)
        h = activation_constraint(h, ("batch", "seq", "mlp"),
                                  cfg.logical_axis_rules or DEFAULT_RULES)
        return dense("down_proj", cfg.hidden_size)(h)


def _sub_remat(cfg: ModelConfig) -> bool:
    """True when remat applies to selected submodules inside the block
    (reference gc_cls semantics, utils/checkpoint.py:67-81) rather than
    to the whole decoder layer."""
    return bool(cfg.remat and cfg.remat_cls and "Block" not in cfg.remat_cls)


def _block_remat(cfg: ModelConfig) -> bool:
    return bool(cfg.remat and not _sub_remat(cfg))


class Block(nn.Module):
    cfg: ModelConfig

    @nn.compact
    def __call__(self, x, positions, segment_ids=None, dropout_seed=None):
        from jax.ad_checkpoint import checkpoint_name
        cfg = self.cfg
        attn_cls, mlp_cls = Attention, Mlp
        if cfg.num_experts > 0:
            from torchacc_tpu.models.moe import MoEMlp
            mlp_cls = MoEMlp
        if _sub_remat(cfg):
            from torchacc_tpu.utils.remat import remat_policy
            pol = remat_policy(cfg.remat_policy)
            if "Attention" in cfg.remat_cls:
                attn_cls = nn.remat(attn_cls, policy=pol, prevent_cse=False)
            if mlp_cls.__name__ in cfg.remat_cls or "Mlp" in cfg.remat_cls:
                mlp_cls = nn.remat(mlp_cls, policy=pol, prevent_cse=False)
        post = cfg.norm_placement == "post"
        if post and cfg.sandwich_norms:
            raise ValueError("norm_placement='post' (OLMo2) does not "
                             "compose with sandwich_norms (gemma2)")
        if cfg.norm_placement not in ("pre", "post"):
            raise ValueError(f"norm_placement must be 'pre' | 'post', "
                             f"got {cfg.norm_placement!r}")
        if cfg.parallel_block:
            # phi-2: both sublayers read ONE shared pre-norm and the
            # residual adds them together; no ln2 exists
            if post or cfg.sandwich_norms:
                raise ValueError("parallel_block (phi) does not compose "
                                 "with norm_placement='post' or "
                                 "sandwich_norms")
            n = Norm(cfg, name="ln1")(x)
            attn_out = attn_cls(cfg, name="attn")(
                n, positions, segment_ids, dropout_seed)
            n_mlp = (n if cfg.parallel_block_shared_norm
                     else Norm(cfg, name="ln2")(x))   # gpt-neox
            mlp_out = mlp_cls(
                cfg, name="moe" if cfg.num_experts > 0 else "mlp")(n_mlp)
            return (x + checkpoint_name(attn_out, "attn_out")
                    + checkpoint_name(mlp_out, "mlp_out"))
        attn_out = attn_cls(cfg, name="attn")(
            x if post else Norm(cfg, name="ln1")(x),
            positions, segment_ids, dropout_seed)
        if cfg.sandwich_norms:
            # Gemma2: post-attention norm before the residual add
            attn_out = Norm(cfg, name="ln1_post")(attn_out)
        if post:
            # OLMo2: the sublayer OUTPUT is normed (no pre-norm at all)
            attn_out = Norm(cfg, name="ln1")(attn_out)
        # names referenced by the 'offload_dots' remat policy (utils/remat.py)
        h = x + checkpoint_name(attn_out, "attn_out")
        mlp_out = mlp_cls(cfg, name="moe" if cfg.num_experts > 0 else "mlp")(
            h if post else Norm(cfg, name="ln2")(h))
        if cfg.sandwich_norms:
            mlp_out = Norm(cfg, name="ln2_post")(mlp_out)
        if post:
            mlp_out = Norm(cfg, name="ln2")(mlp_out)
        return h + checkpoint_name(mlp_out, "mlp_out")


class ScanBlock(nn.Module):
    """Block adapted to nn.scan's (carry, xs) -> (carry, out) signature;
    ``seed`` is the per-layer dropout seed (scanned xs) or None."""
    cfg: ModelConfig

    @nn.compact
    def __call__(self, carry, seed):
        x, positions, segment_ids = carry
        x = Block(self.cfg, name="block")(x, positions, segment_ids,
                                          dropout_seed=seed)
        return (x, positions, segment_ids), None


def pp_block_appliers(cfg: "ModelConfig", wrap):
    """(apply_block_or_slots, unroll_stage) for the pp pipelines.

    Uniform models wrap ONE ``_raw_block_fn``; a ``layer_pattern``
    (gemma2/3) yields one wrapped fn per chunk slot so each slot applies
    its own static config inside the unrolled stage body — the pattern
    period must divide the per-stage chunk (num_layers / pp / virtual)
    so slot j's kind is the same on every stage and virtual chunk.
    ``wrap`` adapts the raw ``fn(p, carry, seed)`` to the pipeline's
    applier signature (the gpipe and 1f1b callers differ)."""
    unroll = not cfg.scan_layers
    if not cfg.layer_pattern:
        return wrap(_raw_block_fn(cfg)), unroll
    plen = len(cfg.layer_pattern)
    per_stage = cfg.num_layers // (cfg.pp_size * cfg.pp_virtual)
    if per_stage % plen:
        raise ValueError(
            f"layer_pattern of period {plen} does not divide the "
            f"per-stage chunk of {per_stage} layers (num_layers "
            f"{cfg.num_layers} / pp {cfg.pp_size} / virtual "
            f"{cfg.pp_virtual}): slot kinds would differ across "
            f"stages.  Choose pp_size x virtual_stages so each chunk "
            f"holds whole pattern repeats.")
    # with plen | per_stage, global layer s*per_stage + j has kind
    # pattern[j % plen] on every stage s — slot fns are stage-invariant
    return tuple(wrap(_raw_block_fn(pattern_cfg(cfg, j)))
                 for j in range(per_stage)), True


def _raw_block_fn(block_cfg):
    """``fn(p, carry, seed) -> (carry, aux)`` applying ONE block via raw
    ``ScanBlock.apply``.  The raw apply drops sown intermediates unless
    the collection is mutable, so the MoE router aux is collected
    explicitly and returned — the single place this subtlety lives (the
    pp / unrolled / split-remat paths all build on it)."""
    def fn(p, carry, s):
        (new_carry, _), vs = ScanBlock(block_cfg).apply(
            {"params": p}, carry, s, mutable=["intermediates"])
        return new_carry, _sown_aux_sum(vs)
    return fn


def _raw_block_fn_quant(block_cfg):
    """quant-threading variant of :func:`_raw_block_fn`:
    ``fn(p, q, carry, s) -> (carry, aux, q_new)``.  The per-layer
    delayed-scaling state goes in and the mutated history comes out, so
    the unrolled / overlap_fsdp paths carry it explicitly (nn.scan's
    ``variable_axes={'quant': 0}`` does the same job on the scan
    path)."""
    def fn(p, q, carry, s):
        (new_carry, _), vs = ScanBlock(block_cfg).apply(
            {"params": p, "quant": q}, carry, s,
            mutable=["intermediates", "quant"])
        return new_carry, _sown_aux_sum(vs), vs["quant"]
    return fn


class TransformerLM(nn.Module):
    """The LM.  ``__call__(input_ids, positions?, segment_ids?) -> logits``.

    positions default to arange; segment_ids enable packed sequences
    (reference varlen-by-position-ids path ops/flash_attn.py:173-216).
    """
    cfg: ModelConfig

    @nn.compact
    def __call__(self, input_ids, positions=None, segment_ids=None,
                 return_hidden=False, dropout_seed=None,
                 moe_aux_row_weights=None):
        """``moe_aux_row_weights`` [B] (MoE x GPipe only): per-row
        weight count_m / count_total of the row's micro-batch.  Rides
        the pipeline ring with its micro so each tick's router aux is
        weighted by its own micro's valid-token share — the SAME
        convention as 1F1B and the grad-accum loop (VERDICT r3 weak-7);
        None keeps the unweighted micro mean."""
        cfg = self.cfg
        # Attention dropout is active iff the caller supplies a seed
        # (train steps do; eval/inference omit it — the deterministic
        # story).  One base seed fans out to per-layer seeds here.
        seeds_xs = None
        if cfg.attn_dropout > 0.0 and dropout_seed is not None:
            seeds_xs = _layer_seed(
                dropout_seed, jnp.arange(cfg.num_layers, dtype=jnp.int32))
        b, s = input_ids.shape
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(s), (b, s))
        emb = nn.Embed(cfg.vocab_size, cfg.hidden_size, name="embed_tokens",
                       dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                       embedding_init=nn.initializers.normal(0.02))
        pos_table = (self.param(
            "pos_embed", nn.initializers.normal(0.02),
            (cfg.max_seq_len, cfg.hidden_size), cfg.param_dtype)
            if cfg.pos_emb == "learned" else None)
        x = _embed_extras(cfg, emb(input_ids), positions, pos_table)

        block_cls = ScanBlock
        if _block_remat(cfg):
            from torchacc_tpu.utils.remat import remat_policy
            block_cls = nn.remat(
                ScanBlock, policy=remat_policy(cfg.remat_policy),
                prevent_cse=False)
        # remat_cnt (reference gc_cnt): remat only the first N layers
        split_n = None
        if (cfg.remat and cfg.remat_cnt is not None
                and 0 <= cfg.remat_cnt < cfg.num_layers and cfg.pp_size == 1):
            split_n = cfg.remat_cnt
        # ONE canonical param layout: layers are always initialised via
        # nn.scan, so the stacked [L, ...] tree (partitioned over the
        # 'layers' logical axis) is the layout regardless of scan_layers
        # — checkpoints are portable between the two execution paths.
        # scan_layers picks how the layers are APPLIED: True = lax.scan
        # over the stack (fast compiles; policy-saved residuals stack
        # [L, ...] via dynamic-update-slice — the scan-stacking tax,
        # docs/PERF.md), False = Python-unrolled loop over static slices
        # (separate per-layer residual buffers; slower compiles,
        # amortised by the persistent compile cache).  The decode/cache
        # path ALWAYS applies via plain scan — the cache collection only
        # flows through scan_mod's variable_axes (raw .apply in the
        # unrolled/split paths would silently drop prefill cache
        # writes), and decode compute is trivial either way.
        cache_live = cfg.decode or self.is_mutable_collection("cache")
        use_scan_apply = cfg.scan_layers or cache_live
        quant_on = cfg.quant != "none"
        if quant_on and not self.is_initializing():
            # the quantized sites' delayed-scaling state threads through
            # the scan / unrolled / overlap paths only; the pp regions
            # and the decode cache path apply blocks via raw param trees
            # that do not carry (or would silently drop) the 'quant'
            # collection — keep those failures loud
            if cfg.pp_size > 1:
                raise NotImplementedError(
                    "quant != 'none' does not compose with pipeline "
                    "parallelism (config.validate rejects it too)")
            if cfg.layer_pattern:
                raise NotImplementedError(
                    "quant != 'none' does not compose with "
                    "layer_pattern models yet")
            if cache_live:
                raise NotImplementedError(
                    "quant != 'none' decode must go through "
                    "models.generate (it strips quant — inference runs "
                    "in the compute dtype)")
            if (split_n is not None and cfg.scan_layers
                    and not cfg.overlap_fsdp):
                # overlap_fsdp forces the unrolled loop below, which
                # honors remat_cnt AND threads quant — only the
                # split-SCAN path cannot
                raise NotImplementedError(
                    "quant != 'none' with memory.gc_cnt requires "
                    "scan_layers=False (the split-scan path does not "
                    "thread the delayed-scaling state)")
        # FSDP overlap: force the unrolled loop with the in-fn param
        # gather (see the branch below); quant threads through it.
        # layer_pattern would silently skip the overlap branch — reject
        # loudly instead of letting a user benchmark a no-op (pp is
        # already rejected by Config.validate; decode skips silently by
        # design: a single-token step has no ladder to overlap)
        if (cfg.overlap_fsdp and cfg.layer_pattern
                and not self.is_initializing()):
            raise NotImplementedError(
                "perf.overlap_fsdp does not compose with layer_pattern "
                "models (the pattern's per-layer loop does not take "
                "the overlap path) — disable one of the two")
        overlap_active = (cfg.overlap_fsdp and not cache_live
                          and cfg.pp_size <= 1 and not cfg.layer_pattern)
        scan_mod = nn.scan(
            block_cls,
            variable_axes={"params": 0, "intermediates": 0, "cache": 0,
                           "quant": 0},
            split_rngs={"params": True},
            length=cfg.num_layers,
            metadata_params={nn.PARTITION_NAME: "layers"},
        )(cfg, name="layers")
        if self.is_initializing():
            (x, _, _), _ = scan_mod((x, positions, segment_ids), seeds_xs)
        elif cfg.layer_pattern and cfg.pp_size <= 1:
            # heterogeneous layers (gemma2-style sliding/global
            # alternation): the pattern is param-free, so params keep the
            # canonical stacked layout; execution is a per-layer python
            # loop with each layer's own static cfg (lax.scan cannot
            # vary a static window across iterations).  Composes with
            # GSPMD sharding (dp/fsdp/tp); under pp the pattern runs
            # through the unrolled stage body instead (the pp branch
            # below) and decode/cache goes through generate()'s pattern
            # path.
            if cache_live:
                raise NotImplementedError(
                    "layer_pattern decode must go through "
                    "models.generate (its pattern-aware cached path); "
                    "direct .apply with a mutable cache is unsupported")
            layer_params = self.variables["params"]["layers"]
            aux_total = jnp.zeros((), jnp.float32)
            carry = (x, positions, segment_ids)
            from torchacc_tpu.utils.remat import remat_policy as _rp
            for i in range(cfg.num_layers):
                fn = _raw_block_fn(pattern_cfg(cfg, i))
                if _block_remat(cfg):
                    fn = jax.checkpoint(fn, policy=_rp(cfg.remat_policy),
                                        prevent_cse=False)
                p_i = jax.tree.map(lambda a, i=i: a[i], layer_params)
                s_i = None if seeds_xs is None else seeds_xs[i]
                carry, aux = fn(p_i, carry, s_i)
                aux_total = aux_total + aux
            if cfg.num_experts > 0:
                self.sow("intermediates", "moe_aux_loss", aux_total)
            x = carry[0]
        elif cfg.pp_size > 1:
            # pipeline path: drive the stacked layer params through the
            # pp-stage pipeline (init traced scan_mod so params exist
            # with the stacked layout); scan_layers picks whether each
            # stage scans or unrolls its layer chunk
            if cache_live:
                # the raw in-region block apply never threads the flax
                # cache collection — prefill writes would silently drop.
                # pp decode has its own path (generate()'s stage ring /
                # pattern dispatch); keep the failure loud here.
                raise NotImplementedError(
                    "pipeline-parallel decode must go through "
                    "models.generate; direct .apply with a mutable "
                    "cache is unsupported under pp")
            from torchacc_tpu.parallel.pp import pipeline_blocks
            layer_params = self.variables["params"]["layers"]
            moe_on = cfg.num_experts > 0
            if seeds_xs is not None:
                # per-layer seeds ride the stacked pytree so each
                # pp stage sees its own layers' seeds
                stacked = {"p": layer_params, "s": seeds_xs}
                unpack = lambda ps: (ps["p"], ps["s"])
            else:
                stacked = layer_params
                unpack = lambda p: (p, None)

            aux_weighted = moe_on and moe_aux_row_weights is not None
            carry0 = (x, positions, segment_ids)
            if aux_weighted:
                # weight rider: travels the ring with its micro, so each
                # tick weights its aux by the RESIDENT micro's
                # valid-token share (rows of a micro share one value)
                carry0 = carry0 + (
                    moe_aux_row_weights.astype(jnp.float32),)

            def mk_apply(_block):
                def apply_one(ps, carry):
                    p, s = unpack(ps)
                    if aux_weighted:
                        new_carry, aux = _block(p, carry[:3], s)
                        return new_carry + (carry[3],), aux * carry[3][0]
                    new_carry, aux = _block(p, carry, s)
                    # aux_from_block=moe_on below: only then does the
                    # pipeline expect (carry, aux)
                    return (new_carry, aux) if moe_on else new_carry
                return apply_one

            apply_arg, unroll = pp_block_appliers(cfg, mk_apply)
            from torchacc_tpu.utils.remat import remat_policy
            res = pipeline_blocks(
                apply_arg, stacked, carry0,
                pp_size=cfg.pp_size, num_micro=cfg.pp_num_micro,
                virtual_stages=cfg.pp_virtual,
                remat=cfg.remat,
                remat_policy=(remat_policy(cfg.remat_policy)
                              if cfg.remat else None),
                aux_from_block=moe_on,
                unroll_stage=unroll)
            if moe_on:
                x, aux_total = res
                if aux_weighted:
                    # each tick already weighted its aux by the resident
                    # micro's count_m / count_total (the weight rider),
                    # so aux_total IS sum_m aux_m * count_m / count_tot
                    # — the trainer's aux_weight * aux * count term then
                    # equals the 1F1B / grad-accum convention exactly
                    self.sow("intermediates", "moe_aux_loss", aux_total)
                else:
                    # unweighted micro mean: equal to the weighted form
                    # whenever micros carry equal valid-token counts
                    # (packed/full batches); the trainer passes row
                    # weights whenever labels are available
                    self.sow("intermediates", "moe_aux_loss",
                             aux_total / cfg.pp_num_micro)
            else:
                x = res
        elif not use_scan_apply or overlap_active:
            # unrolled application from the stacked layout: static
            # per-layer slices keep each layer's policy-saved residuals
            # as SEPARATE buffers, so the step's autodiff carries no
            # [L, ...] DUS stacking (the scan-stacking tax — measured
            # ~7 MFU points on the v5e bench, docs/PERF.md).  Honors
            # remat_cnt: layers past split_n run without remat.
            #
            # overlap_fsdp rides this loop: each layer's block fn FIRST
            # constrains its param slice to REPLICATED (an explicit
            # all-gather under GSPMD — parallel/sharding.
            # fsdp_gather_params).  The gather's only operand is the
            # stacked param slice — data-independent of every other
            # layer's compute — so XLA's scheduler is free to overlap
            # layer i+1's all-gather with layer i's compute ladder (the
            # ASPLOS'23 decomposition; XLA schedules by data flow, not
            # program order).  The gather lives INSIDE the
            # jax.checkpoint region: residuals stay the fsdp-SHARDED
            # slices (remat re-gathers in backward — standard ZeRO-3
            # memory behavior), never a per-layer replicated copy.  The
            # backward mirror is each layer's weight cotangent
            # resharding back into the fsdp-sharded stack independently
            # of older layers' backward compute.
            from torchacc_tpu.utils.remat import remat_policy
            layer_params = self.variables["params"]["layers"]
            cfg_off = dataclasses.replace(cfg, remat=False)

            # block-level quant state exists only when an in-block site
            # ('attn'/'mlp') is quantized; a head-only quant_sites
            # leaves the blocks plain (the head's own QuantDenseGeneral
            # at the module tail threads through normal flax mutation)
            quant_blocks = quant_on and (
                quant_site_on(cfg, "attn") or quant_site_on(cfg, "mlp"))
            raw_gc = (_raw_block_fn_quant(cfg) if quant_blocks
                      else _raw_block_fn(cfg))
            raw_plain = (_raw_block_fn_quant(cfg_off) if quant_blocks
                         else _raw_block_fn(cfg_off))
            if overlap_active:
                from torchacc_tpu.parallel.sharding import (
                    DEFAULT_RULES,
                    fsdp_gather_params,
                    fsdp_gather_specs,
                )
                # per-leaf target specs = each weight's layout minus
                # its fsdp dim, so the gather unshard-s ONLY the ZeRO-3
                # axis and megatron tp/ep dims stay sharded; falls back
                # to fully-replicated for trees the axes rules don't
                # know (custom modules)
                try:
                    g_specs = fsdp_gather_specs(
                        jax.tree.map(lambda a: a[0], layer_params),
                        cfg.logical_axis_rules or DEFAULT_RULES)
                except ValueError as e:
                    # fully-replicated fallback also un-shards tp/ep
                    # dims — fine on fsdp/dp-only meshes, a per-layer
                    # memory+collective cost under tensor parallelism;
                    # say so instead of degrading silently
                    from torchacc_tpu.utils.logger import logger
                    logger.warning(
                        "overlap_fsdp: param tree has no axes-rule "
                        f"coverage ({e}); gathering layers to fully "
                        "replicated — under tensor parallelism this "
                        "also un-shards the megatron dims per layer")
                    g_specs = None

                def _gathered(fn):
                    def wrapped(p, *rest):
                        return fn(fsdp_gather_params(p, g_specs), *rest)
                    return wrapped
                raw_gc = _gathered(raw_gc)
                raw_plain = _gathered(raw_plain)
            if _block_remat(cfg):
                raw_gc = jax.checkpoint(
                    raw_gc, policy=remat_policy(cfg.remat_policy),
                    prevent_cse=False)
            layer_quant = None
            if quant_blocks:
                if "quant" not in self.variables \
                        or "layers" not in self.variables["quant"]:
                    raise ValueError(
                        "quant != 'none' but no 'quant' collection was "
                        "passed to apply() — thread TrainState.quant "
                        "(the Trainer does this automatically)")
                layer_quant = self.variables["quant"]["layers"]

            slice_i = lambda tree, i: jax.tree.map(
                lambda a, i=i: a[i], tree)
            carry = (x, positions, segment_ids)
            aux_total = jnp.zeros((), jnp.float32)
            new_quant = []
            n_gc = cfg.num_layers if split_n is None else split_n
            for i in range(cfg.num_layers):
                fn = raw_gc if (i < n_gc and cfg.remat) else raw_plain
                p_i = slice_i(layer_params, i)
                seed_i = None if seeds_xs is None else seeds_xs[i]
                if quant_blocks:
                    carry, aux, q_i = fn(p_i, slice_i(layer_quant, i),
                                         carry, seed_i)
                    new_quant.append(q_i)
                else:
                    carry, aux = fn(p_i, carry, seed_i)
                aux_total = aux_total + aux
            if quant_blocks and self.is_mutable_collection("quant"):
                self.put_variable(
                    "quant", "layers",
                    jax.tree.map(lambda *a: jnp.stack(a), *new_quant))
            if cfg.num_experts > 0:
                self.sow("intermediates", "moe_aux_loss", aux_total)
            x = carry[0]
        elif split_n is not None and not cache_live:
            # split the stacked params: first remat_cnt layers run with
            # remat semantics, the rest without.  cache_live falls
            # through to plain scan below: this path's raw .apply would
            # drop prefill cache writes (remat does not change values,
            # so eval/prefill under scan is correct regardless of
            # remat_cnt).
            from torchacc_tpu.utils.remat import remat_policy
            layer_params = self.variables["params"]["layers"]
            head = jax.tree.map(lambda p: p[:split_n], layer_params)
            tail = jax.tree.map(lambda p: p[split_n:], layer_params)
            cfg_off = dataclasses.replace(cfg, remat=False)

            _gc, _plain = _raw_block_fn(cfg), _raw_block_fn(cfg_off)
            apply_gc = lambda ps, carry: _gc(ps[0], carry, ps[1])
            apply_plain = lambda ps, carry: _plain(ps[0], carry, ps[1])
            if _block_remat(cfg):
                apply_gc = jax.checkpoint(
                    apply_gc, policy=remat_policy(cfg.remat_policy),
                    prevent_cse=False)

            def seg(fn, stack, lo, hi, carry):
                if seeds_xs is None:
                    return jax.lax.scan(
                        lambda c, p: fn((p, None), c), carry, stack)
                return jax.lax.scan(
                    lambda c, ps: fn(ps, c), carry,
                    (stack, seeds_xs[lo:hi]))

            carry = (x, positions, segment_ids)
            aux_total = jnp.zeros((), jnp.float32)
            if split_n > 0:
                carry, aux = seg(apply_gc, head, 0, split_n, carry)
                aux_total = aux_total + jnp.sum(aux)
            if split_n < cfg.num_layers:
                carry, aux = seg(apply_plain, tail, split_n,
                                 cfg.num_layers, carry)
                aux_total = aux_total + jnp.sum(aux)
            if cfg.num_experts > 0:
                self.sow("intermediates", "moe_aux_loss", aux_total)
            x = carry[0]
        else:
            (x, _, _), _ = scan_mod((x, positions, segment_ids),
                                    seeds_xs)

        x = scale_hidden(cfg, Norm(cfg, name="final_norm")(x))
        if return_hidden:
            # fused linear+CE path (ops/fused.py): the caller applies the
            # head matmul chunk-by-chunk inside the loss
            return x
        if cfg.tie_embeddings:
            if cfg.head_bias:
                # the tied path projects via emb.attend — no bias param
                # exists to apply; converting silently would drop it
                raise ValueError(
                    "head_bias does not compose with tie_embeddings "
                    "(the tied head has no bias parameter)")
            if quant_site_on(cfg, "head"):
                # the tied head projects through emb.attend — there is
                # no lm_head dense to quantize; a silent no-op would
                # let a user benchmark head quantization that never ran
                raise ValueError(
                    "quant_sites includes 'head' but tie_embeddings "
                    "projects through the embedding table — drop "
                    "'head' from quant_sites (the tied head stays in "
                    "the compute dtype)")
            logits = emb.attend(x)
        elif quant_site_on(cfg, "head"):
            # the MATERIALISED head only: the trainer's fused-CE path
            # computes the head inside the chunked loss and stays in
            # the compute dtype (docs/performance.md)
            logits = _quant_dense(cfg, "lm_head", cfg.vocab_size, -1,
                                  cfg.head_bias)(x)
        else:
            logits = nn.Dense(cfg.vocab_size, use_bias=cfg.head_bias,
                              name="lm_head",
                              dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                              kernel_init=nn.initializers.normal(0.02))(x)
        return softcap(logits.astype(jnp.float32), cfg.logit_softcap)


def loss_sum_count(logits: jax.Array, labels: jax.Array,
                   loss_mask: Optional[jax.Array] = None
                   ) -> Tuple[jax.Array, jax.Array]:
    """Next-token cross entropy: (sum over valid tokens, valid count).

    -100 labels are ignored (HF convention the reference benchmarks rely
    on).  Returning sum+count separately lets gradient accumulation
    weight micro-batches by token count — exact big-batch equivalence
    even when padding makes counts uneven.
    """
    valid = labels != -100
    if loss_mask is not None:
        valid = valid & (loss_mask != 0)
    safe_labels = jnp.where(valid, labels, 0)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    token_ll = jnp.take_along_axis(logp, safe_labels[..., None], axis=-1)[..., 0]
    total = jnp.sum(jnp.where(valid, -token_ll, 0.0))
    count = jnp.sum(valid).astype(jnp.float32)
    return total, count


def loss_fn(logits: jax.Array, labels: jax.Array,
            loss_mask: Optional[jax.Array] = None) -> jax.Array:
    """Mean next-token cross entropy (see loss_sum_count)."""
    total, count = loss_sum_count(logits, labels, loss_mask)
    return total / jnp.maximum(count, 1.0)


def _embed_extras(cfg: ModelConfig, x: jax.Array, positions: jax.Array,
                  pos_table) -> jax.Array:
    """Shared embedding front-end conventions (Gemma sqrt(hidden) scale
    in the compute dtype, learned position add) — one definition so the
    1F1B raw-params path cannot drift from TransformerLM.__call__."""
    if cfg.embed_scale:
        x = x * jnp.asarray(cfg.hidden_size ** 0.5, cfg.dtype)
    if cfg.pos_emb == "learned":
        x = x + pos_table.astype(cfg.dtype)[positions]
    return x


def pattern_cfg(cfg: ModelConfig, i: int) -> ModelConfig:
    """The effective per-layer config under ``cfg.layer_pattern``:
    layer i takes pattern[i % len] — 'sliding' keeps cfg.window,
    'global' lifts it to full attention.  Identity when no pattern."""
    if not cfg.layer_pattern:
        return cfg
    kind = cfg.layer_pattern[i % len(cfg.layer_pattern)]
    if kind == "sliding":
        # gemma3 dual rope: sliding layers use the local base frequency,
        # UNSCALED (HF applies rope_scaling to the global rotary only)
        if cfg.rope_local_theta is not None:
            return dataclasses.replace(cfg,
                                       rope_theta=cfg.rope_local_theta,
                                       rope_scale=1.0)
        return cfg
    if kind == "global":
        return dataclasses.replace(cfg, window=(-1, -1))
    raise ValueError(
        f"layer_pattern entries must be 'sliding' | 'global', got "
        f"{kind!r}")


def head_logits(cfg: ModelConfig, params, x: jax.Array) -> jax.Array:
    """Shared raw-params head tail: final norm -> vocab projection ->
    softcap, numerically identical to TransformerLM.__call__'s tail
    (Dense/attend both cast operands to cfg.dtype) — one definition so
    raw-params consumers (the pp decode path, models/generate.py)
    cannot drift from the module."""
    xn = scale_hidden(cfg, Norm(cfg).apply(
        {"params": params["final_norm"]}, x))
    w = (params["embed_tokens"]["embedding"].T if cfg.tie_embeddings
         else params["lm_head"]["kernel"])
    logits = jnp.einsum("bsh,hv->bsv", xn.astype(cfg.dtype),
                        w.astype(cfg.dtype))
    if cfg.head_bias:
        logits = logits + params["lm_head"]["bias"].astype(cfg.dtype)
    return softcap(logits.astype(jnp.float32), cfg.logit_softcap)


def _micro_seed(base, micro_idx):
    """Decorrelate dropout across pipeline micro-batches (a different odd
    constant than _layer_seed's so layer/micro mixes cannot collide)."""
    b = jnp.asarray(base, jnp.int32).astype(jnp.uint32)
    m = jnp.asarray(micro_idx, jnp.int32).astype(jnp.uint32)
    return (b + m * jnp.uint32(0x85EBCA6B)).astype(jnp.int32)


def _sown_aux_sum(vs) -> jax.Array:
    """Sum every sown '*aux_loss*' intermediate (MoE router load-balance,
    models/moe.py) out of a raw .apply's mutated variables."""
    paths = jax.tree_util.tree_flatten_with_path(
        vs.get("intermediates", {}))[0]
    vals = [jnp.sum(v) for path, v in paths
            if "aux_loss" in jax.tree_util.keystr(path)]
    return sum(vals) if vals else jnp.zeros((), jnp.float32)


class _MicroBatchView(dict):
    """Batch view handed to a custom Trainer loss inside the 1F1B last
    stage.  Only ``labels`` exists there — the other batch leaves never
    enter the pipeline region — so turn an unknown-key lookup into an
    actionable error instead of a bare trace-time KeyError."""

    def __missing__(self, key):
        raise KeyError(
            f"batch[{key!r}] is not available inside the 1f1b pipeline "
            "region: a custom loss under pp.schedule='1f1b' runs in the "
            "last stage and sees {'labels': ...} only.  Losses needing "
            "other batch leaves should use pp.schedule='gpipe', whose "
            "loss runs outside the region.")

    # dict.get() bypasses __missing__, so batch.get('attention_mask')
    # would silently hand a custom loss None; raise the same curated
    # error instead.  (`in` keeps plain membership so a loss can branch
    # on availability.)
    def get(self, key, default=None):
        if not dict.__contains__(self, key):
            self.__missing__(key)
        return dict.get(self, key, default)


def pp_1f1b_forward_sum_count(cfg: ModelConfig, params, input_ids,
                              positions=None, segment_ids=None,
                              labels=None, pp_axis: str = "pp",
                              dropout_seed=None, use_fused_ce=False,
                              custom_loss=None):
    """(loss_sum, count) for a zoo model under the 1F1B pipeline schedule.

    The 1F1B schedule (parallel/pp.py pipeline_loss_1f1b; reference
    pp/schedule.py:156-227) fuses final-norm + head + loss into the last
    stage so each micro-batch's backward starts as soon as its forward
    finishes.  That means the loss cannot be computed OUTSIDE model.apply
    the way the GPipe path does — this function replaces the trainer's
    forward for pp.schedule == '1f1b'.  Embedding (+ learned positions)
    runs outside the region, replicated over 'pp', exactly like the
    GPipe path; gradients flow into it through the pipeline's dx.

    Compositions:

    - ``use_fused_ce``: the last-stage head runs the chunked fused
      linear+CE (ops/fused.py) instead of materialising [mb, s, V] f32
      logits — the same memory win the non-PP trainer gets.
    - ``dropout_seed``: attention dropout inside the schedule.  Each
      micro-batch's seed rides the ppermute ring with its activations
      (so the B sub-tick's recompute regenerates the identical mask),
      mixed per micro (_micro_seed) and per layer (_layer_seed).
    - MoE: per-stage router aux losses fold into the loss with
      per-micro weights ``router_aux_weight * count_m`` — the same
      convention as the trainer's gradient-accumulation loop (each
      micro weighted by its valid-token count).
    """
    from torchacc_tpu.parallel.pp import pipeline_loss_1f1b
    from torchacc_tpu.train.trainer import shift_labels

    b, s = input_ids.shape
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(s), (b, s))
    emb_table = params["embed_tokens"]["embedding"]
    x = _embed_extras(cfg, emb_table[input_ids].astype(cfg.dtype),
                      positions, params.get("pos_embed"))
    if labels is None:
        labels = shift_labels(input_ids, segment_ids)

    stacked = params["layers"]
    head_params = {"final_norm": params["final_norm"]}
    if cfg.tie_embeddings:
        head_params["embed"] = emb_table
    else:
        head_params["lm_head"] = params["lm_head"]

    M = cfg.pp_num_micro
    dropout_on = cfg.attn_dropout > 0.0 and dropout_seed is not None
    moe_on = cfg.num_experts > 0

    riders = (positions, segment_ids)
    layer_xs = None
    if dropout_on:
        # seed rider: every row of micro-batch m carries _micro_seed(m);
        # the pipeline's [B] -> [M, mb] reshape makes it per-micro
        micro_of_row = jnp.arange(b, dtype=jnp.int32) // max(b // M, 1)
        riders = riders + (_micro_seed(dropout_seed, micro_of_row),)
        layer_xs = jnp.arange(cfg.num_layers, dtype=jnp.int32)

    aux_scale = None
    if moe_on:
        labels_m = labels.reshape((M, b // M) + labels.shape[1:])
        count_m = jnp.sum(labels_m != -100, axis=(1, 2)).astype(jnp.float32)
        aux_scale = cfg.router_aux_weight * count_m

    def mk_apply(raw):
        # raw = _raw_block_fn(per-layer cfg): one block apply returning
        # (carry, aux_sum); this wrapper adds the 1F1B-specific riders
        # (per-micro dropout seed travels the ring in the carry)
        def apply_block(p, carry, layer_idx=None):
            if dropout_on:
                inner, seed_row = carry[:-1], carry[-1]
                seed = _layer_seed(seed_row[0], layer_idx)
            else:
                inner, seed = carry, None
            new_c, aux = raw(p, inner, seed)
            if dropout_on:
                new_c = tuple(new_c) + (seed_row,)
            return (new_c, aux) if moe_on else new_c
        return apply_block

    # uniform models: one applier; layer_pattern: per-slot appliers with
    # each slot's static cfg (forces the unrolled stage body)
    apply_block, unroll_stage = pp_block_appliers(cfg, mk_apply)

    def _pin_logits(logits):
        """Pin the in-region [mb, s, V] logits' VOCAB dim un-sharded: a
        vocab dim GSPMD auto-shards over 'tp' puts tp collectives inside
        the pp-manual tick body, which trips an XLA SPMD-partitioner
        CHECK (spmd_partitioner_util.cc:495) whenever a data axis is
        also live (same issue as the head-weight pin in parallel/pp.py
        head_vjp).  Batch stays on the data axes; seq is left
        unconstrained (sp may shard it)."""
        from jax.sharding import PartitionSpec as _P

        from torchacc_tpu.config import DATA_AXES
        mesh = jax.sharding.get_abstract_mesh()
        data = tuple(a for a in DATA_AXES
                     if mesh is not None and a in getattr(mesh, "shape", {}))
        return jax.lax.with_sharding_constraint(
            logits, _P(data or None, _P.UNCONSTRAINED, None))

    # Vocab-parallel head: with a live tp axis the head weight, its grad
    # and the head matmul stay 1/tp per device via hand-written manual
    # collectives (ops/fused.py fused_linear_cross_entropy_tp) — the
    # GSPMD-auto alternative trips the SPMD-partitioner CHECK inside the
    # pp-manual region (see _pin_logits).  Falls back to the replicated
    # pin for custom losses (which need full logits) and non-divisible
    # vocabs.  cfg.tp_vocab_head is the escape hatch back to the pinned
    # (replicated) head.
    _mesh = jax.sharding.get_abstract_mesh()
    _tp_ext = int(getattr(_mesh, "shape", {}).get("tp", 1) or 1)
    # neither chunked-CE variant carries a bias term — head_bias models
    # (phi) take the materialised-logits paths below, mirroring the
    # trainer's fused-CE gate
    tp_head = (cfg.tp_vocab_head and _tp_ext > 1 and custom_loss is None
               and cfg.vocab_size % _tp_ext == 0 and not cfg.head_bias)
    use_fused_ce = use_fused_ce and not cfg.head_bias

    def head_loss(hp, y, lab):
        xn = scale_hidden(cfg, Norm(cfg).apply(
            {"params": hp["final_norm"]}, y))
        w = (hp["embed"].T if cfg.tie_embeddings
             else hp["lm_head"]["kernel"])
        hb = (hp["lm_head"]["bias"].astype(jnp.float32)
              if cfg.head_bias else None)
        if tp_head:
            from torchacc_tpu.ops.fused import fused_linear_cross_entropy_tp
            return fused_linear_cross_entropy_tp(
                xn, w, lab, tp_axis="tp",
                logit_softcap=cfg.logit_softcap)
        if custom_loss is not None:
            # user loss(logits, batch) -> (sum, count) | scalar, applied
            # per micro-batch in the last stage (reference: the PP
            # executor aggregates any stage-computed loss,
            # pp/executor.py:283-321).  The batch view here carries the
            # micro's labels; losses needing other batch leaves should
            # use the gpipe schedule, whose loss runs outside the region.
            logits = jnp.einsum("bsh,hv->bsv", xn.astype(jnp.float32),
                                w.astype(jnp.float32))
            logits = _pin_logits(logits if hb is None else logits + hb)
            res = custom_loss(softcap(logits, cfg.logit_softcap),
                              _MicroBatchView(labels=lab))
            if isinstance(res, tuple):
                return res
            return res, jnp.ones((), jnp.float32)
        if use_fused_ce:
            from torchacc_tpu.ops.fused import fused_linear_cross_entropy
            # scan_free: this runs inside the last-stage lax.cond, where
            # a lax.scan's WhileThunk would desynchronize XLA:CPU's
            # collective rendezvous (see ops/fused.py docstring)
            return fused_linear_cross_entropy(
                xn, w, lab, logit_softcap=cfg.logit_softcap,
                scan_free=True)
        logits = jnp.einsum("bsh,hv->bsv", xn.astype(jnp.float32),
                            w.astype(jnp.float32))
        logits = _pin_logits(logits if hb is None else logits + hb)
        return loss_sum_count(softcap(logits, cfg.logit_softcap), lab)

    # tells the 1F1B executor's head_vjp to SKIP its replicated-head pin:
    # the tp-aware head consumes the tp-sharded weight directly (a
    # replicated copy would force an all-gather each tick and a reshard
    # at the inner shard_map boundary)
    head_loss.tp_aware = tp_head

    return pipeline_loss_1f1b(
        apply_block, head_loss, stacked, head_params, x, riders, labels,
        layer_xs, aux_scale, cfg.pp_size, M, pp_axis, moe_on,
        unroll_stage, cfg.pp_virtual)
