"""Streamed HF checkpoint ingestion: safetensors -> sharded device params
with bounded host memory.

``models/hf.py::load_hf_model`` materialises the ENTIRE torch model in
host RAM (``AutoModelForCausalLM.from_pretrained``) before converting —
fine at 8B, a hard blocker at Llama-3-70B FSDP+TP (~140 GB host RSS).
The reference solves this with deferred fake-tensor init
(LOW_CPU_MEM_USAGE -> torchdistx, reference accelerate.py:13-17,114-119).
The TPU-native answer is streaming: read each safetensors shard
tensor-by-tensor, convert the one tensor, and ``jax.device_put`` it
straight into its target :class:`NamedSharding` slice of the (possibly
multi-host) mesh.  Peak host memory is the resident safetensors mmap
window plus a few copies of the single largest tensor — independent of
model size.

Mechanics:

- :func:`ingestion_plan` maps every expected HF tensor name to (pytree
  path, layer index, expected shape, transform) from the
  :class:`ModelConfig` alone — no weights touched.  The same plan
  validates a checkpoint header against the model abstractly (the
  70B-scale dryrun in tests uses exactly this).
- Scan-stacked leaves ([num_layers, ...]) are assembled on DEVICE: the
  buffer initialises as sharded zeros and each arriving layer lands via
  a donated ``buf.at[i].set(layer)`` jit, so no [L, ...] host array ever
  exists.
- ``load_hf_model_streamed`` is the drop-in counterpart of
  ``load_hf_model`` for checkpoint paths; ``train/accelerate.py`` routes
  string paths with safetensors through it automatically and falls back
  to the materialising path otherwise (.bin checkpoints, live torch
  modules).
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from torchacc_tpu.models.transformer import ModelConfig
from torchacc_tpu.utils.logger import logger

# non-parameter buffers some exporters leave in state dicts
_IGNORE = re.compile(
    r"(rotary_emb\.inv_freq|masked_bias|attn\.bias|\.num_batches_tracked)$")


class PlanEntry(NamedTuple):
    path: Tuple[str, ...]          # pytree path in TransformerLM params
    idx: Optional[Tuple[int, ...]]  # position in the leaf's leading dims
    lead: Tuple[int, ...]          # leading dims: (L,) stacked layers,
    #                                (L, E) stacked experts, () whole
    hf_shape: Tuple[int, ...]      # expected shape IN THE CHECKPOINT
    transform: Callable[[np.ndarray], np.ndarray]


def ingestion_plan(cfg: ModelConfig, *, packed_qkv: bool = False,
                   packed_mlp: bool = False, nongated_mlp: bool = False,
                   moe_style: str = "mixtral"
                   ) -> Dict[str, Tuple[PlanEntry, ...]]:
    """HF tensor name (without the ``model.`` prefix) -> tuple of
    PlanEntries for the llama/qwen2/qwen3/mistral/gemma/mixtral/olmo2/
    phi3 families (same mapping as hf.params_from_hf_state_dict,
    expressed per-tensor so it can run shard-by-shard and be checked
    against a header without data).

    A checkpoint tensor usually feeds ONE leaf; Phi-3's packed
    ``qkv_proj`` / ``gate_up_proj`` feed several (each entry slices its
    rows out of the same tensor) — hence the tuple values.
    ``packed_qkv`` / ``packed_mlp`` select those layouts; they are a
    property of the CHECKPOINT, detected from its tensor names
    (:func:`_detect_packed`), not of the model config."""
    h, L = cfg.hidden_size, cfg.num_layers
    nh, nk, d = cfg.num_heads, cfg.kv_heads, cfg.head_size
    inter, v = cfg.intermediate_size, cfg.vocab_size

    def qkv(heads):
        return lambda w: np.ascontiguousarray(w.T).reshape(h, heads, d)

    plan: Dict[str, Tuple[PlanEntry, ...]] = {}

    def add(name, path, layer, shape, tr, lead=None):
        idx = ((layer,) if isinstance(layer, int) else layer)
        if lead is None:
            lead = () if idx is None else (L,)
        ent = PlanEntry(tuple(path), idx, tuple(lead), tuple(shape), tr)
        plan[name] = plan.get(name, ()) + (ent,)

    add("embed_tokens.weight", ("embed_tokens", "embedding"), None,
        (v, h), lambda w: w)
    add("norm.weight", ("final_norm", "scale"), None, (h,), lambda w: w)
    # biased LayerNorms (StarCoder2, nemotron's layernorm1p); cohere's
    # layernorm is biasless
    ln_bias = cfg.norm in ("layernorm", "layernorm1p") and cfg.norm_bias
    if ln_bias:
        add("norm.bias", ("final_norm", "bias"), None, (h,), lambda b: b)
    if not cfg.tie_embeddings:
        add("lm_head.weight", ("lm_head", "kernel"), None, (v, h),
            lambda w: np.ascontiguousarray(w.T))
    else:
        # tied models have no lm_head leaf, but some exporters ship a
        # DE-ALIASED copy anyway (safetensors refuses aliased tensors):
        # map it to a discard so such checkpoints stream instead of
        # failing as unmappable
        add("lm_head.weight", (), None, (v, h), lambda w: w)

    for i in range(L):
        p = f"layers.{i}."
        a = ("layers", "block", "attn")
        if packed_qkv:
            # Phi-3: qkv_proj rows are [q | k | v]; three entries slice
            # the same tensor
            qr, kr = nh * d, nk * d
            nm = p + "self_attn.qkv_proj.weight"
            shp = (qr + 2 * kr, h)
            add(nm, a + ("q_proj", "kernel"), i, shp,
                lambda w: qkv(nh)(w[:qr]))
            add(nm, a + ("k_proj", "kernel"), i, shp,
                lambda w: qkv(nk)(w[qr:qr + kr]))
            add(nm, a + ("v_proj", "kernel"), i, shp,
                lambda w: qkv(nk)(w[qr + kr:]))
        else:
            add(p + "self_attn.q_proj.weight", a + ("q_proj", "kernel"), i,
                (nh * d, h), qkv(nh))
            add(p + "self_attn.k_proj.weight", a + ("k_proj", "kernel"), i,
                (nk * d, h), qkv(nk))
            add(p + "self_attn.v_proj.weight", a + ("v_proj", "kernel"), i,
                (nk * d, h), qkv(nk))
        add(p + "self_attn.o_proj.weight", a + ("o_proj", "kernel"), i,
            (h, nh * d),
            lambda w: np.ascontiguousarray(w.T).reshape(nh, d, h))
        if cfg.qkv_bias:
            for nm, heads in (("q_proj", nh), ("k_proj", nk),
                              ("v_proj", nk)):
                add(p + f"self_attn.{nm}.bias", a + (nm, "bias"), i,
                    (heads * d,),
                    lambda b, heads=heads: b.reshape(heads, d))
        if cfg.o_bias:
            add(p + "self_attn.o_proj.bias", a + ("o_proj", "bias"), i,
                (h,), lambda b: b)
        if cfg.qk_norm:
            # per-head-dim (gemma3/qwen3) vs flat-projection (OLMo2)
            qn = (nh * d,) if cfg.qk_norm_proj else (d,)
            kn = (nk * d,) if cfg.qk_norm_proj else (d,)
            add(p + "self_attn.q_norm.weight", a + ("q_norm", "scale"), i,
                qn, lambda w: w)
            add(p + "self_attn.k_norm.weight", a + ("k_norm", "scale"), i,
                kn, lambda w: w)
        if cfg.num_experts > 0:
            # Sparse-MoE block: router + per-(layer, expert) FFN
            # weights land in the [L, E, ...] stacked expert leaves.
            # moe_style picks the checkpoint naming (mixtral
            # block_sparse_moe.w1/w3/w2 vs qwen3_moe
            # mlp.gate_proj/up_proj/down_proj)
            E = cfg.num_experts
            moe = ("layers", "block", "moe")
            if moe_style == "qwen":
                mod, wg, wu, wd = ("mlp", "gate_proj", "up_proj",
                                   "down_proj")
            else:
                mod, wg, wu, wd = "block_sparse_moe", "w1", "w3", "w2"
            add(p + f"{mod}.gate.weight",
                moe + ("router", "kernel"), i, (E, h),
                lambda w: np.ascontiguousarray(w.T))
            for j in range(E):
                q = p + f"{mod}.experts.{j}."
                tT = lambda w: np.ascontiguousarray(w.T)
                add(q + f"{wg}.weight", moe + ("experts/gate",), (i, j),
                    (inter, h), tT, lead=(L, E))
                add(q + f"{wu}.weight", moe + ("experts/up",), (i, j),
                    (inter, h), tT, lead=(L, E))
                add(q + f"{wd}.weight", moe + ("experts/down",), (i, j),
                    (h, inter), tT, lead=(L, E))
        elif packed_mlp:
            # Phi-3: gate_up_proj rows are [gate | up]
            m = ("layers", "block", "mlp")
            nm = p + "mlp.gate_up_proj.weight"
            add(nm, m + ("gate_proj", "kernel"), i, (2 * inter, h),
                lambda w: np.ascontiguousarray(w[:inter].T))
            add(nm, m + ("up_proj", "kernel"), i, (2 * inter, h),
                lambda w: np.ascontiguousarray(w[inter:].T))
            add(p + "mlp.down_proj.weight", m + ("down_proj", "kernel"), i,
                (h, inter), lambda w: np.ascontiguousarray(w.T))
        elif nongated_mlp:
            # StarCoder2 NON-gated MLP: c_fc -> up_proj, c_proj ->
            # down_proj (activation='gelu' builds no gate_proj)
            m = ("layers", "block", "mlp")
            add(p + "mlp.c_fc.weight", m + ("up_proj", "kernel"), i,
                (inter, h), lambda w: np.ascontiguousarray(w.T))
            add(p + "mlp.c_proj.weight", m + ("down_proj", "kernel"), i,
                (h, inter), lambda w: np.ascontiguousarray(w.T))
            if cfg.mlp_bias:
                add(p + "mlp.c_fc.bias", m + ("up_proj", "bias"), i,
                    (inter,), lambda b: b)
                add(p + "mlp.c_proj.bias", m + ("down_proj", "bias"), i,
                    (h,), lambda b: b)
        else:
            m = ("layers", "block", "mlp")
            # non-gated models keeping the up/down names (nemotron
            # relu2) have no gate tensors
            if cfg.activation in ("swiglu", "geglu"):
                add(p + "mlp.gate_proj.weight", m + ("gate_proj", "kernel"),
                    i, (inter, h), lambda w: np.ascontiguousarray(w.T))
                if cfg.mlp_bias:
                    add(p + "mlp.gate_proj.bias", m + ("gate_proj", "bias"),
                        i, (inter,), lambda b: b)
            add(p + "mlp.up_proj.weight", m + ("up_proj", "kernel"), i,
                (inter, h), lambda w: np.ascontiguousarray(w.T))
            add(p + "mlp.down_proj.weight", m + ("down_proj", "kernel"), i,
                (h, inter), lambda w: np.ascontiguousarray(w.T))
            if cfg.mlp_bias:
                add(p + "mlp.up_proj.bias", m + ("up_proj", "bias"), i,
                    (inter,), lambda b: b)
                add(p + "mlp.down_proj.bias", m + ("down_proj", "bias"),
                    i, (h,), lambda b: b)
        b = ("layers", "block")
        if cfg.norm_placement == "post":
            # OLMo2: no input_layernorm; ln1/ln2 are post-sublayer norms
            add(p + "post_attention_layernorm.weight",
                b + ("ln1", "scale"), i, (h,), lambda w: w)
            add(p + "post_feedforward_layernorm.weight",
                b + ("ln2", "scale"), i, (h,), lambda w: w)
            continue
        add(p + "input_layernorm.weight", b + ("ln1", "scale"), i, (h,),
            lambda w: w)
        if cfg.parallel_block:
            # phi/cohere: one shared norm, no ln2 (phi is excluded from
            # streaming by layout, but cohere streams)
            if ln_bias:
                add(p + "input_layernorm.bias", b + ("ln1", "bias"), i,
                    (h,), lambda bb: bb)
            continue
        if ln_bias and not cfg.sandwich_norms:
            add(p + "input_layernorm.bias", b + ("ln1", "bias"), i, (h,),
                lambda bb: bb)
            add(p + "post_attention_layernorm.bias", b + ("ln2", "bias"),
                i, (h,), lambda bb: bb)
        if cfg.sandwich_norms:
            add(p + "post_attention_layernorm.weight",
                b + ("ln1_post", "scale"), i, (h,), lambda w: w)
            add(p + "pre_feedforward_layernorm.weight",
                b + ("ln2", "scale"), i, (h,), lambda w: w)
            add(p + "post_feedforward_layernorm.weight",
                b + ("ln2_post", "scale"), i, (h,), lambda w: w)
        else:
            add(p + "post_attention_layernorm.weight",
                b + ("ln2", "scale"), i, (h,), lambda w: w)
    return plan


def _detect_packed(names) -> Tuple[bool, bool]:
    """(packed_qkv, packed_mlp) from checkpoint tensor names — Phi-3
    ships fused qkv_proj / gate_up_proj; packing is a checkpoint
    property, not a model-config one."""
    pk = any(n.endswith("self_attn.qkv_proj.weight") for n in names)
    pm = any(n.endswith("mlp.gate_up_proj.weight") for n in names)
    return pk, pm


def _detect_nongated(names) -> bool:
    """StarCoder2's non-gated MLP naming (mlp.c_fc / mlp.c_proj)."""
    return any(n.endswith("mlp.c_fc.weight") for n in names)


def streamable_names(names) -> bool:
    """Whether the checkpoint uses the llama-family tensor layout the
    stream plan maps (separate or phi3-packed attention projections).
    GPT-2-style checkpoints (Conv1D ``h.N.attn.c_attn``) and phi-2's
    parallel-block layout (``self_attn.dense``, ``final_layernorm``)
    are NOT — the caller should fall back to the materialising
    converter (phi-2 tops out at 2.7B, comfortably materialisable)."""
    if any(n.endswith("self_attn.dense.weight") for n in names):
        return False
    return any(n.endswith(("self_attn.q_proj.weight",
                           "self_attn.qkv_proj.weight"))
               for n in names)


def _detect_moe_style(names) -> str:
    """'qwen' (mlp.experts.N.gate_proj) vs 'mixtral'
    (block_sparse_moe.experts.N.w1), from checkpoint tensor names."""
    if any(".mlp.experts." in n for n in names):
        return "qwen"
    return "mixtral"


def resolve_checkpoint_files(path: str) -> Optional[List[str]]:
    """safetensors shard files under ``path``, or None when the
    checkpoint has no safetensors (caller falls back to the
    materialising loader)."""
    idx = os.path.join(path, "model.safetensors.index.json")
    if os.path.exists(idx):
        with open(idx) as f:
            weight_map = json.load(f)["weight_map"]
        return sorted({os.path.join(path, v) for v in weight_map.values()})
    single = os.path.join(path, "model.safetensors")
    if os.path.exists(single):
        return [single]
    return None


def checkpoint_tensor_names(path: str) -> Optional[List[str]]:
    """All tensor names in the checkpoint: free from the index json
    when one exists (its weight_map keys ARE the names), else from the
    shard headers.  Layout resolution is shared with
    :func:`resolve_checkpoint_files` — one place knows what a
    safetensors checkpoint looks like."""
    idx = os.path.join(path, "model.safetensors.index.json")
    if os.path.exists(idx):
        with open(idx) as f:
            return sorted(json.load(f)["weight_map"])
    files = resolve_checkpoint_files(path)
    if files is None:
        return None
    from safetensors import safe_open
    names: List[str] = []
    for fpath in files:
        with safe_open(fpath, framework="pt") as f:
            names.extend(f.keys())
    return names


def _np_from_torch(t) -> np.ndarray:
    """torch tensor -> OWNED numpy array at checkpoint width.

    No f32 upcast (hf._t doubles bf16 tensors): bf16 round-trips through
    a uint16 view into ml_dtypes.bfloat16.  The final .copy() is
    essential, not defensive: safetensors tensors are views into the
    shard's mmap, and jax's CPU backend ZERO-COPY aliases numpy inputs —
    an identity-transform leaf (embed, norms) would otherwise pin the
    entire shard file mapping in RSS for the life of the params
    (measured: ~220 MB of phantom residency on a 360 MB checkpoint)."""
    import torch

    if t.dtype == torch.bfloat16:
        import ml_dtypes
        return t.view(torch.uint16).numpy().view(ml_dtypes.bfloat16).copy()
    return t.numpy().copy()


def _trim_host_heap() -> None:
    """Return freed heap pages to the OS (glibc retains them otherwise:
    measured 260 MB of dead arena on a 360 MB stream — at 70B scale the
    retention would be GBs of phantom host RSS).  Best-effort, linux
    glibc only; a no-op elsewhere."""
    try:
        import ctypes
        ctypes.CDLL("libc.so.6").malloc_trim(0)
    except Exception:  # noqa: BLE001 — non-glibc platforms
        pass


def _tree_get(tree, path):
    for k in path:
        tree = tree[k]
    return tree


def _tree_set(tree, path, val):
    for k in path[:-1]:
        tree = tree.setdefault(k, {})
    tree[path[-1]] = val


def stream_params(
    files: List[str],
    cfg: ModelConfig,
    *,
    shardings: Any = None,
    param_dtype=None,
    tensor_names: Optional[List[str]] = None,
) -> Dict[str, Any]:
    """Assemble TransformerLM params from safetensors shards, one tensor
    at a time.

    ``shardings``: optional pytree of NamedShardings matching the param
    tree (e.g. ``trainer.state_shardings.params``) — each tensor is
    placed into its shard as it is read.  Without it, leaves land on the
    default device.  ``tensor_names``: the checkpoint's tensor names if
    the caller already has them (``checkpoint_tensor_names`` reads them
    from the index json for free); otherwise a header-only pre-scan of
    the shard files collects them.
    """
    from safetensors import safe_open

    param_dtype = param_dtype or cfg.param_dtype
    names = tensor_names
    if names is None:
        # header-only pre-scan: which packed layouts this checkpoint
        # uses decides the plan shape
        names = []
        for fpath in files:
            with safe_open(fpath, framework="pt") as f:
                names.extend(f.keys())
    pk, pm = _detect_packed(names)
    plan = ingestion_plan(cfg, packed_qkv=pk, packed_mlp=pm,
                          nongated_mlp=_detect_nongated(names),
                          moe_style=_detect_moe_style(names))

    params: Dict[str, Any] = {}
    filled: Dict[Tuple[str, ...], np.ndarray] = {}  # stacked-leaf masks
    setters: Dict[Tuple[str, ...], Any] = {}
    seen = set()

    def leaf_sharding(path):
        if shardings is None:
            return None
        return _tree_get(shardings, path)

    np_dtype = np.dtype(param_dtype)

    def place(arr, sh):
        # cast on HOST, then device_put against the sharding: jax splits
        # a host array per-device and transfers only each device's
        # slice.  jnp.asarray first would commit the full tensor to
        # device 0 — a per-tensor HBM spike (~2 GB for a 70B embed) on
        # a device budgeted for 1/N of it.
        a = np.asarray(arr).astype(np_dtype, copy=False)
        return jax.device_put(a, sh) if sh is not None else jnp.asarray(a)

    def setter_for(path, sh):
        if path not in setters:
            def _set(buf, piece, *idx):
                return buf.at[idx].set(piece.astype(buf.dtype))
            kw = {} if sh is None else {"out_shardings": sh}
            setters[path] = jax.jit(_set, donate_argnums=0, **kw)
        return setters[path]

    def piece_sharding(sh, n_lead):
        # a single piece of a stacked leaf: same placement with the
        # leading (layer / layer,expert) dims dropped, so the
        # host->device transfer of each arriving piece is already
        # per-shard
        if sh is None:
            return None
        from jax.sharding import NamedSharding, PartitionSpec
        return NamedSharding(sh.mesh, PartitionSpec(*sh.spec[n_lead:]))

    for fpath in files:
        # framework="pt": numpy framework cannot decode bf16 shards;
        # torch is only used as a per-tensor decoder here
        with safe_open(fpath, framework="pt") as f:
            for name in f.keys():
                base = name[6:] if name.startswith("model.") else name
                if _IGNORE.search(base):
                    continue
                ents = plan.get(base)
                if ents is None:
                    raise KeyError(
                        f"checkpoint tensor {name!r} has no mapping for "
                        f"this ModelConfig (family unsupported by the "
                        f"streamed loader?)")
                if base in seen:
                    raise ValueError(f"duplicate tensor {name!r}")
                seen.add(base)
                if not ents[0].path:  # mapped-to-discard (tied head)
                    continue
                t = f.get_tensor(name)
                raw = _np_from_torch(t)
                del t
                if tuple(raw.shape) != ents[0].hf_shape:
                    raise ValueError(
                        f"{name}: checkpoint shape {tuple(raw.shape)} != "
                        f"expected {ents[0].hf_shape}")
                for ent in ents:  # packed tensors feed several leaves
                    arr = ent.transform(raw)
                    sh = leaf_sharding(ent.path)
                    if ent.idx is None:
                        _tree_set(params, ent.path, place(arr, sh))
                        continue
                    buf = None
                    try:
                        buf = _tree_get(params, ent.path)
                    except KeyError:
                        pass
                    if buf is None:
                        shape = ent.lead + arr.shape
                        mk = jax.jit(
                            lambda shape=shape: jnp.zeros(shape,
                                                          param_dtype),
                            **({} if sh is None
                               else {"out_shardings": sh}))
                        buf = mk()
                        filled[ent.path] = np.zeros(ent.lead, bool)
                    st = setter_for(ent.path, sh)
                    piece = place(arr, piece_sharding(sh, len(ent.lead)))
                    buf = st(buf, piece, *(jnp.int32(i) for i in ent.idx))
                    filled[ent.path][ent.idx] = True
                    _tree_set(params, ent.path, buf)
                del raw
                # per-tensor trim: the torch copy + transform buffer +
                # donated-out leaf all freed this iteration; without a
                # trim glibc's arenas retain them nondeterministically
                # (dynamic mmap-threshold growth), ratcheting RSS by
                # hundreds of MB on a 360 MB stream
                _trim_host_heap()
        # shard boundary: the mmap window just closed; hand its freed
        # heap back too
        _trim_host_heap()

    missing = sorted(set(plan) - seen)
    if missing:
        if cfg.tie_embeddings and missing == ["lm_head.weight"]:
            pass  # tied head: no separate tensor ships
        else:
            raise ValueError(
                f"checkpoint is missing {len(missing)} expected tensors, "
                f"first: {missing[:5]}")
    for path, mask in filled.items():
        if not mask.all():
            raise ValueError(
                f"leaf {'/'.join(path)}: positions "
                f"{np.argwhere(~mask).tolist()[:8]} never arrived")
    return params


def validate_checkpoint_header(
    shapes: Dict[str, Tuple[int, ...]], cfg: ModelConfig,
) -> None:
    """Abstract (no-data) validation of a checkpoint against a config:
    every expected tensor present with the right shape, nothing
    unmappable.  ``shapes``: HF tensor name -> shape, e.g. read from
    safetensors headers.  This is what the 70B ingestion dryrun runs —
    it needs only the index/header, never the 140 GB of weights."""
    pk, pm = _detect_packed(shapes)
    plan = ingestion_plan(cfg, packed_qkv=pk, packed_mlp=pm,
                          nongated_mlp=_detect_nongated(shapes),
                          moe_style=_detect_moe_style(shapes))
    seen = set()
    for name, shape in shapes.items():
        base = name[6:] if name.startswith("model.") else name
        if _IGNORE.search(base):
            continue
        ents = plan.get(base)
        if ents is None:
            raise KeyError(f"unmappable checkpoint tensor {name!r}")
        if tuple(shape) != ents[0].hf_shape:
            raise ValueError(f"{name}: shape {tuple(shape)} != expected "
                             f"{ents[0].hf_shape}")
        seen.add(base)
    missing = set(plan) - seen
    if cfg.tie_embeddings:
        missing.discard("lm_head.weight")
    if missing:
        raise ValueError(f"missing {len(missing)} tensors, first: "
                         f"{sorted(missing)[:5]}")


def load_hf_model_streamed(
    path: str,
    *,
    shardings: Any = None,
    dtype=None,
    param_dtype=None,
    **config_overrides,
) -> Tuple[ModelConfig, Dict[str, Any]]:
    """(ModelConfig, sharded params) from a local HF checkpoint dir with
    safetensors weights — the bounded-host-memory counterpart of
    hf.load_hf_model."""
    import transformers

    from torchacc_tpu.models.hf import config_from_hf

    files = resolve_checkpoint_files(path)
    if files is None:
        raise FileNotFoundError(
            f"{path}: no safetensors checkpoint (use hf.load_hf_model "
            f"for .bin checkpoints)")
    hf_cfg = transformers.AutoConfig.from_pretrained(path)
    overrides = dict(config_overrides)
    if dtype is not None:
        overrides.setdefault("dtype", dtype)
    if param_dtype is not None:
        overrides.setdefault("param_dtype", param_dtype)
    cfg = config_from_hf(hf_cfg, **overrides)
    logger.info(f"streaming {len(files)} safetensors shard(s) from {path}")
    params = stream_params(files, cfg, shardings=shardings,
                           param_dtype=param_dtype,
                           tensor_names=checkpoint_tensor_names(path))
    return cfg, params
