"""RL-style train↔generate loop on ONE pod, checkpoint-free
(docs/serving.md "Live weight handoff").

The shape of an RLHF/GRPO iteration — or of online eval/sampling
during pretraining — is:

    repeat:
        fit() a few policy steps
        generate rollouts/samples from the CURRENT weights
        score them, build the next batch

Before the layout-transfer engine (parallel/transfer.py) the only road
from a training ``TrainState`` to serving weights was a checkpoint
round-trip through orbax; this demo drives the in-memory road instead:
``Trainer.serving_params()`` reshards ``state.params`` from the train
layout (fsdp/tp) into the decode layout through ONE compiled
spec-to-spec program — compiled on the first handoff, a pure cache hit
on every later one — and ``ServeEngine.load_params`` swaps the weights
in place (no pool reallocation, no recompile of the decode programs).

Run (CPU; add devices to see a real reshard):

  python examples/rl_loop.py
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/rl_loop.py --fsdp 2 --tp 2

Prints per-phase wall times: watch ``handoff_ms`` collapse after the
first iteration while ``transfer compiles`` stays at 1.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--iterations", type=int, default=3,
                   help="train->generate alternations")
    p.add_argument("--fit-steps", type=int, default=3)
    p.add_argument("--rollouts", type=int, default=4)
    p.add_argument("--max-new", type=int, default=16)
    p.add_argument("--fsdp", type=int, default=1)
    p.add_argument("--tp", type=int, default=1)
    args = p.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import torchacc_tpu as ta
    from torchacc_tpu.models import get_preset
    from torchacc_tpu.parallel.transfer import cache_stats
    from torchacc_tpu.serve import Request, ServeEngine
    from torchacc_tpu.train import accelerate

    mc = get_preset("llama-tiny", dtype=jnp.float32, vocab_size=256,
                    hidden_size=64, num_layers=2, num_heads=4,
                    num_kv_heads=4, intermediate_size=128, max_seq_len=128)
    cfg = ta.Config()
    cfg.compute.dtype = "float32"
    cfg.dist.fsdp.size = args.fsdp
    cfg.dist.tp.size = args.tp
    cfg.serve.block_size = 8
    cfg.serve.num_blocks = 128
    cfg.serve.max_slots = 4
    cfg.serve.prefill_chunk = 8

    trainer, _ = accelerate(mc, None, cfg, optimizer=optax.adamw(1e-3))
    trainer.init()
    rng = np.random.default_rng(0)
    batch = {"input_ids": jnp.asarray(
        rng.integers(0, mc.vocab_size, size=(4, 32)), jnp.int32)}
    prompts = [rng.integers(1, mc.vocab_size, size=n).tolist()
               for n in (4, 7, 11, 5)][: args.rollouts]

    engine = None
    for it in range(args.iterations):
        # -- train phase (in an RL loop this consumes last round's
        # scored rollouts; here a fixed LM batch stands in) -----------
        t0 = time.perf_counter()
        for _ in range(args.fit_steps):
            m = trainer.step(batch)
        loss = float(m["loss"])
        fit_ms = (time.perf_counter() - t0) * 1e3

        # -- handoff: current weights -> serving layout, in memory ----
        t0 = time.perf_counter()
        if engine is None:
            engine = ServeEngine.from_train_state(trainer, cfg)
        else:
            engine.load_params(trainer.serving_params())
        handoff_ms = (time.perf_counter() - t0) * 1e3

        # -- generate phase (rollouts from the CURRENT policy) --------
        t0 = time.perf_counter()
        results = engine.generate(
            [Request(prompt_ids=pr, max_new_tokens=args.max_new)
             for pr in prompts])
        gen_ms = (time.perf_counter() - t0) * 1e3
        n_tok = sum(len(r.tokens) for r in results)
        for r in results:
            # ... score r.tokens and fold into the next train batch ...
            engine.discard(r.request_id)

        s = cache_stats()
        print(f"iter {it}: loss={loss:.4f}  fit={fit_ms:.0f}ms  "
              f"handoff={handoff_ms:.1f}ms  "
              f"generate({n_tok} tok)={gen_ms:.0f}ms  "
              f"[transfer compiles={s['compiles']} "
              f"hits={s['cache_hits']}]")
    return 0


if __name__ == "__main__":
    sys.exit(main())
