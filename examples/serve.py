"""Continuous-batching serving demo (docs/serving.md).

Drives ``torchacc_tpu.serve.ServeEngine`` — paged KV cache +
continuous-batching scheduler + request front-end — on a mixed-length
workload with STAGGERED arrivals: a second wave of requests is
submitted while the first wave is mid-decode, which is exactly the
case batch-synchronous ``generate()`` (examples/serve_generate.py)
cannot serve without head-of-line blocking.

Run (CPU works; tiny random model by default):

  python examples/serve.py
  python examples/serve.py --requests 12 --max-new 48 --policy sjf
  python examples/serve.py --temperature 0.8 --top-k 40 --top-p 0.95
  python examples/serve.py --prefix --policy priority   # shared system
        # prompt workload: prefix-cache hits + batched prefill +
        # deadline-aware admission, with request 0 streamed token by
        # token as the lagged ring resolves it

Prints one line per completed request (tokens + its SLO metrics) and
the aggregate p50/p95 table an operator would alert on.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--requests", type=int, default=8)
    p.add_argument("--max-new", type=int, default=24)
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--top-k", type=int, default=0)
    p.add_argument("--top-p", type=float, default=1.0)
    p.add_argument("--policy", default="fcfs",
                   choices=("fcfs", "sjf", "priority"))
    p.add_argument("--max-slots", type=int, default=4)
    p.add_argument("--prefix", action="store_true",
                   help="shared-system-prompt workload through the "
                        "prefix cache + batched prefill (+ streams "
                        "request 0's tokens as they resolve)")
    args = p.parse_args()

    import numpy as np
    import jax
    import jax.numpy as jnp

    import torchacc_tpu as ta
    from torchacc_tpu.models import TransformerLM, get_preset
    from torchacc_tpu.serve import Request, ServeEngine

    # tiny random llama so the demo runs anywhere; swap in a real
    # checkpoint exactly as examples/serve_generate.py does
    mc = get_preset("llama-tiny", dtype=jnp.float32, num_layers=2,
                    hidden_size=128, num_heads=4, num_kv_heads=2,
                    intermediate_size=512, vocab_size=4096)
    model = TransformerLM(mc)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]

    cfg = ta.Config()
    cfg.serve.block_size = 8
    cfg.serve.num_blocks = 256
    cfg.serve.max_slots = args.max_slots
    cfg.serve.prefill_chunk = 16
    cfg.serve.policy = args.policy
    if args.prefix:
        cfg.serve.prefix_cache = True        # shared-prefix KV reuse
        cfg.serve.prefill_batch = 4          # burst prefill, one dispatch
    engine = ServeEngine(model, params, cfg)

    rng = np.random.default_rng(0)
    if args.prefix:
        # real template traffic: every request = one shared system
        # prompt + a short unique turn.  Request 0 prefills the prefix
        # cold; everyone after it hits the cache
        system = rng.integers(1, mc.vocab_size, size=32).tolist()
        prompts = [system + rng.integers(1, mc.vocab_size,
                                         size=int(rng.integers(4, 12))
                                         ).tolist()
                   for _ in range(args.requests)]
    else:
        # prompt lengths spanning >8x, like real traffic
        lens = [int(rng.integers(4, 80)) for _ in range(args.requests)]
        prompts = [rng.integers(1, mc.vocab_size, size=n).tolist()
                   for n in lens]
    req = dict(max_new_tokens=args.max_new, temperature=args.temperature,
               top_k=args.top_k, top_p=args.top_p)
    if args.policy == "priority":
        # odd requests are latency-sensitive: higher class, tight ddl
        prio = lambda i: dict(priority=i % 2,  # noqa: E731
                              deadline_s=5.0 if i % 2 else 60.0)
    else:
        prio = lambda i: {}  # noqa: E731

    on_tok = ((lambda t, ts: print(f"  [stream req 0] token {t}",
                                   flush=True))
              if args.prefix else None)
    half = len(prompts) // 2
    ids = [engine.submit(Request(prompt_ids=pr, seed=i, **req, **prio(i)),
                         on_token=on_tok if i == 0 else None)
           for i, pr in enumerate(prompts[:half])]
    for _ in range(4):
        engine.step()                        # first wave is mid-decode…
    ids += [engine.submit(Request(prompt_ids=pr, seed=half + i, **req,
                                  **prio(half + i)))
            for i, pr in enumerate(prompts[half:])]   # …second wave lands
    engine.run()

    for rid in ids:
        r = engine.result(rid)
        print(f"req {rid:2d}  prompt={len(r.prompt_ids):3d}  "
              f"new={len(r.tokens):3d}  finish={r.finish_reason:6s}  "
              f"wait={r.queue_wait_s * 1e3:7.1f}ms  "
              f"ttft={r.ttft_s * 1e3:7.1f}ms  "
              f"tok/s={r.tokens_per_sec:6.1f}  "
              f"tokens={r.tokens[:8]}{'...' if len(r.tokens) > 8 else ''}")

    print("\naggregate:")
    for k, v in engine.stats().items():
        print(f"  {k:20s} {v:.4f}" if isinstance(v, float)
              else f"  {k:20s} {v}")
    engine.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
