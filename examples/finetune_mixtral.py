"""End-to-end MoE example: HF Mixtral/Qwen3-MoE checkpoint -> STREAMED
ingestion into an EP x FSDP mesh -> fine-tune -> generate.

The checkpoint streams tensor-by-tensor straight into the expert-
parallel shardings (models/hf_stream.py): host memory stays bounded by
one shard's mmap window — the 8x7B-scale path, where materialising the
torch model first would need ~180 GB of host RAM.

Run (single host; ep * fsdp must divide the device count):
  python examples/finetune_mixtral.py --hf_path /path/to/mixtral \
      --ep 8 --fsdp 2 --steps 100          # 16 devices
Without --hf_path a small randomly initialised Mixtral-architecture
model is used; to try the full EP x FSDP flow on an emulated 8-device
CPU mesh:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/finetune_mixtral.py --ep 4 --fsdp 2 --steps 10
"""

from __future__ import annotations

import argparse

import numpy as np


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--hf_path", default=None,
                   help="local dir with a safetensors Mixtral/Qwen3-MoE "
                        "checkpoint (hub ids are not streamed)")
    p.add_argument("--steps", type=int, default=50)
    p.add_argument("--seq", type=int, default=512)
    p.add_argument("--batch_rows", type=int, default=8)
    p.add_argument("--ep", type=int, default=1)
    p.add_argument("--fsdp", type=int, default=1)
    p.add_argument("--capacity_factor", type=float, default=None,
                   help="None = exact dense dispatch (small expert "
                        "counts); e.g. 1.25 = switch-style capacity "
                        "dispatch, FLOPs independent of expert count "
                        "(the 8x7B regime; 'sort' dispatch engages "
                        "automatically at scale)")
    args = p.parse_args()

    import jax.numpy as jnp

    import torchacc_tpu as ta
    from torchacc_tpu.models import generate, get_preset
    from torchacc_tpu.train import adamw, warmup_cosine

    config = ta.Config(
        memory=ta.MemoryConfig(gc=True, gc_policy="save_attn_mlp"),
        dist=ta.DistConfig(
            ep=ta.EPConfig(size=args.ep,
                           capacity_factor=args.capacity_factor),
            fsdp=ta.FSDPConfig(size=args.fsdp),
        ),
    )

    if args.hf_path:
        # STREAMED: config first, trainer resolves shardings, then the
        # safetensors shards place tensor-by-tensor into them
        trainer, _ = ta.accelerate(
            args.hf_path, None, config,
            optimizer=adamw(warmup_cosine(2e-5, args.steps, 10)))
        mc = trainer.model.cfg
    else:
        mc = get_preset("llama-tiny", vocab_size=1000, num_experts=8,
                        num_experts_per_tok=2)
        trainer, _ = ta.accelerate(
            mc, None, config,
            optimizer=adamw(warmup_cosine(3e-4, args.steps, 10)))
        trainer.init()

    spec = str(trainer.state.params["layers"]["block"]["moe"]
               ["experts/gate"].sharding.spec)
    print(f"expert weights sharded as {spec}")

    rng = np.random.default_rng(0)
    for step in range(args.steps):
        batch = {"input_ids": jnp.asarray(
            rng.integers(0, mc.vocab_size,
                         size=(args.batch_rows, args.seq)), jnp.int32)}
        metrics = trainer.step(batch)
        if step % 10 == 0 or step == args.steps - 1:
            print(f"step {step:4d}  loss {float(metrics['loss']):.4f}")

    import jax
    prompts = jnp.asarray(rng.integers(0, mc.vocab_size, size=(2, 16)),
                          jnp.int32)
    with jax.sharding.set_mesh(trainer.mesh):
        toks = generate(trainer.model, trainer.state.params, prompts,
                        max_new_tokens=32)
    print("generated:", np.asarray(toks)[:, 16:])
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
