"""End-to-end example: HF Llama -> sharded fine-tune -> checkpoint ->
generate.  (Reference examples/ equivalents show HF Trainer + torchacc
wrapping; here the whole flow is native.)

Run (single host, any device count):
  python examples/finetune_llama.py --hf_path /path/to/llama --steps 100
Without --hf_path a small randomly initialised Llama is used.
"""

from __future__ import annotations

import argparse

import numpy as np


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--hf_path", default=None)
    p.add_argument("--steps", type=int, default=50)
    p.add_argument("--seq", type=int, default=512)
    p.add_argument("--batch_rows", type=int, default=8)
    p.add_argument("--fsdp", type=int, default=1)
    p.add_argument("--ckpt", default="/tmp/torchacc_tpu_example_ckpt")
    args = p.parse_args()

    import jax.numpy as jnp

    import torchacc_tpu as ta
    from torchacc_tpu.data import AsyncLoader, PackedDataset
    from torchacc_tpu.models import generate, get_preset
    from torchacc_tpu.train import adamw, warmup_cosine

    config = ta.Config(
        memory=ta.MemoryConfig(gc=True, gc_policy="dots_with_no_batch_dims"),
        dist=ta.DistConfig(fsdp=ta.FSDPConfig(size=args.fsdp)),
    )

    if args.hf_path:
        # one call: convert + shard + initialise from the HF weights
        trainer, _ = ta.accelerate(
            args.hf_path, None, config,
            optimizer=adamw(warmup_cosine(2e-5, args.steps, 10)))
        mc = trainer.model.cfg
    else:
        mc = get_preset("llama-tiny", vocab_size=1000)
        trainer, _ = ta.accelerate(
            mc, None, config,
            optimizer=adamw(warmup_cosine(3e-4, args.steps, 10)))
        trainer.init()

    # toy corpus -> packed batches -> async sharded device feed
    rng = np.random.default_rng(0)
    docs = (rng.integers(1, mc.vocab_size,
                         size=rng.integers(20, args.seq)).astype(np.int32)
            for _ in range(args.steps * args.batch_rows))
    packed = PackedDataset(docs, seq_len=args.seq,
                           batch_rows=args.batch_rows)
    loader = AsyncLoader(packed, config, mesh=trainer.mesh)

    history = trainer.fit(loader, max_steps=args.steps, log_every=10,
                          checkpoint_dir=args.ckpt, checkpoint_every=25)
    print("final:", history[-1] if history else "no steps")

    out = generate(trainer.model, trainer.state.params,
                   jnp.asarray([[1, 2, 3]], jnp.int32), max_new_tokens=16)
    print("sample:", np.asarray(out)[0].tolist())
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
