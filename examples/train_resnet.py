"""Vision quick-start: ResNet classification through the custom-model path.

Reference parity: the reference's quick start trains torchvision
ResNet-50 through `torchacc.accelerate` (docs/source/quick_start.md:
119-134, ~+16% over native).  The TPU-native equivalent is the same
promise through the custom-model path: any flax module following the
``(inputs, positions=None, segment_ids=None)`` call convention trains
under the sharded Trainer with a custom loss and per-model axes rules.

This example builds a compact ResNet (GroupNorm instead of BatchNorm —
stateless, so the functional train step needs no mutable batch stats,
and it avoids BatchNorm's cross-replica stats traffic on pod slices)
and trains it on synthetic CIFAR-shaped data:

    python examples/train_resnet.py --steps 30 --dp -1

Batches use the framework's generic keys: ``input_ids`` carries the
NHWC image tensor, ``labels`` the class ids.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


class ResBlock(nn.Module):
    features: int
    stride: int = 1

    @nn.compact
    def __call__(self, x):
        y = nn.Conv(self.features, (3, 3), strides=(self.stride,) * 2,
                    use_bias=False, name="conv1")(x)
        y = nn.GroupNorm(num_groups=8, name="gn1")(y)
        y = nn.relu(y)
        y = nn.Conv(self.features, (3, 3), use_bias=False, name="conv2")(y)
        y = nn.GroupNorm(num_groups=8, name="gn2")(y)
        if x.shape[-1] != self.features or self.stride != 1:
            x = nn.Conv(self.features, (1, 1), strides=(self.stride,) * 2,
                        use_bias=False, name="proj")(x)
        return nn.relu(x + y)


class ResNet(nn.Module):
    """CIFAR-scale ResNet (GroupNorm); stages (2,2,2) ~ ResNet-14."""
    num_classes: int = 10
    width: int = 64

    @nn.compact
    def __call__(self, images, positions=None, segment_ids=None):
        x = images.astype(jnp.float32)
        x = nn.Conv(self.width, (3, 3), use_bias=False, name="stem")(x)
        x = nn.relu(nn.GroupNorm(num_groups=8, name="gn0")(x))
        for i, (feats, stride) in enumerate(
                [(self.width, 1), (self.width * 2, 2), (self.width * 4, 2)]):
            x = ResBlock(feats, stride, name=f"block{i}a")(x)
            x = ResBlock(feats, 1, name=f"block{i}b")(x)
        x = x.mean(axis=(1, 2))
        return nn.Dense(self.num_classes, name="head")(x)


# data-parallel-only axes: convs replicate, the head splits over tp if set
RESNET_AXES = (
    (r"conv\d/kernel$|proj/kernel$|stem/kernel$", (None, None, None, "mlp")),
    (r"gn\d/(scale|bias)$", (None,)),
    (r"head/kernel$", ("embed", "mlp")),
    (r"head/bias$", (None,)),
)


def xent(logits, batch):
    onehot = jax.nn.one_hot(batch["labels"], logits.shape[-1])
    return -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * onehot, -1))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--size", type=int, default=32)
    ap.add_argument("--classes", type=int, default=10)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--dp", type=int, default=-1)
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    import optax

    import torchacc_tpu as ta
    from torchacc_tpu.train import Trainer

    cfg = ta.Config(dist=ta.DistConfig(dp=ta.DPConfig(size=args.dp)))
    trainer = Trainer(ResNet(num_classes=args.classes), cfg,
                      optimizer=optax.adamw(args.lr),
                      axes_rules=RESNET_AXES, loss=xent)

    rng = np.random.default_rng(0)
    imgs = rng.normal(size=(args.batch, args.size, args.size, 3)
                      ).astype(np.float32)
    labels = rng.integers(0, args.classes, size=(args.batch,))
    batch = {"input_ids": jnp.asarray(imgs),
             "labels": jnp.asarray(labels, jnp.int32)}
    trainer.init(sample_input=batch["input_ids"])

    losses = []
    t0 = None
    for step in range(args.steps):
        m = trainer.step(batch)
        if step == 2:
            float(m["loss"])           # sync, then time steady state
            t0 = time.perf_counter()
        losses.append(float(m["loss"]))
    dt = (time.perf_counter() - t0) / max(args.steps - 3, 1)
    out = {"loss_first": round(losses[0], 4), "loss_last": round(losses[-1], 4),
           "samples_per_sec": round(args.batch / dt, 1)}
    print(json.dumps(out) if args.json else out)
    return 0 if losses[-1] < losses[0] else 1


if __name__ == "__main__":
    sys.exit(main())
