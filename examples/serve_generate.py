"""Serving example: load a checkpoint (HF safetensors dir, orbax dir, or
a fresh random model), cast to serving precision ONCE, and batch-decode
prompts through the jitted KV-cache path.

The reference defers all inference to vLLM; here decode is a product
surface: prefill + single-scan greedy/top-p decode, ragged LEFT-padded
batches, sliding-window/ALiBi/longrope models, pp stage-ring and
cp sharded-cache paths (models/generate.py).

Run:
  python examples/serve_generate.py                       # random tiny model
  python examples/serve_generate.py --hf_path /path/to/llama \
      --prompt "The capital of France is" --max_new 64

Serving precision (docs/PERF.md): training keeps f32 master weights, so
decoding against them reads twice the bytes per step.  The one-time
bf16 cast below measured +9% decode throughput at 468M on v5e (more at
larger models, where decode is purely parameter-bandwidth-bound).
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--hf_path", default=None,
                   help="HF checkpoint dir (safetensors stream-ingested)")
    p.add_argument("--prompt", nargs="*", default=["Once upon a time",
                                                   "The TPU is"])
    p.add_argument("--max_new", type=int, default=32)
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--top_p", type=float, default=1.0)
    args = p.parse_args()

    import jax.numpy as jnp
    import numpy as np

    import torchacc_tpu as ta
    from torchacc_tpu.models import TransformerLM, generate, get_preset
    from torchacc_tpu.train import accelerate

    if args.hf_path:
        # one-call ingestion: resolves shardings, streams safetensors
        # shard-by-shard into them, and initialises trainer.state.
        # optax.identity() keeps serving memory flat: the default adamw
        # would allocate two f32 moment trees decode never reads.
        import optax
        trainer, _ = accelerate(args.hf_path, None, ta.Config(),
                                optimizer=optax.identity())
        model, params = trainer.model, trainer.state.params
        from transformers import AutoTokenizer
        tok = AutoTokenizer.from_pretrained(args.hf_path)
        if tok.pad_token is None:
            # pad ids never reach the model (prompt_mask masks them)
            tok.pad_token = tok.eos_token
        tok.padding_side = "left"  # generate()'s decode convention
        enc = tok(args.prompt, return_tensors="np", padding=True)
        ids = jnp.asarray(enc["input_ids"], jnp.int32)
        mask = jnp.asarray(enc["attention_mask"], jnp.int32)
    else:
        mc = get_preset("llama-tiny", vocab_size=256, hidden_size=64,
                        num_layers=2, num_heads=4, num_kv_heads=2,
                        intermediate_size=128)
        model = TransformerLM(mc)
        rng = np.random.default_rng(0)
        ids = jnp.asarray(rng.integers(1, 256, (len(args.prompt), 8)),
                          jnp.int32)
        mask = None
        import jax
        params = model.init(jax.random.PRNGKey(0), ids)["params"]
        tok = None

    # serving-precision cast happens once, inside generate()
    out = generate(model, params, ids, prompt_mask=mask,
                   max_new_tokens=args.max_new,
                   temperature=args.temperature, top_p=args.top_p,
                   param_dtype=jnp.bfloat16)
    out = np.asarray(out)
    for i, row in enumerate(out):
        text = (tok.decode(row, skip_special_tokens=True)
                if tok is not None else row.tolist())
        print(f"[{i}] {text}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
