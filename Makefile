# Reference: Makefile `test` target (Makefile:7-9 — two pytest passes
# under PJRT_USE_TORCH_ALLOCATOR).  Here: one suite on an emulated
# 8-device CPU mesh; kernels run in interpret mode.

PYTHON ?= python
PYTEST ?= $(PYTHON) -m pytest

.PHONY: test test-all test-inproc bench chaos chaos-multihost chaos-elastic chaos-sdc chaos-replace serve-smoke serve-chaos router-chaos handoff-smoke ckpt-smoke obs-smoke supervisor-smoke fleet-smoke store-chaos lint dryrun tpu-watch

# Per-file subprocess isolation: XLA:CPU's in-process multi-device runtime
# can SIGABRT nondeterministically mid-suite (scripts/run_tests.py docstring);
# fresh interpreters per file + retry-on-signal keep the evidence intact.
test:
	$(PYTHON) scripts/run_tests.py -m "not slow"

test-all:
	$(PYTHON) scripts/run_tests.py

# direct in-process run (fastest when the runtime race doesn't bite)
test-inproc:
	$(PYTEST) tests/ -q

bench:
	python bench.py

# tier-1-adjacent regression gate: drive the REAL bench.py model path
# (accelerate + trainer.step + metrics) for a few steps on CPU — fast
# enough for every PR, catches hot-loop wiring breakage that unit tests
# with tiny ad-hoc models can miss.  Second leg: the same path with
# int8 quantized matmuls (xla impl on CPU) so the quant plumbing is
# gated per-PR too (docs/performance.md "Quantized matmuls")
bench-smoke:
	JAX_PLATFORMS=cpu python bench.py --fast --platform cpu --iters 2
	JAX_PLATFORMS=cpu python bench.py --fast --platform cpu --iters 2 \
		--quant int8 --no-decode --no-idle-probe

# serving gate (docs/serving.md): drive the continuous-batching engine
# on a mixed-length staggered workload on CPU, PLUS the shared-prefix
# leg (N requests over K system prompts through a prefix-cache +
# batched-prefill + priority engine, one request streamed, no-prefix
# control); reports tokens/s + TTFT and per-token latency percentiles
# + prefix_hit_rate / prefill_tokens_saved, and FAILS unless greedy
# outputs on EVERY leg are token-identical to batch-synchronous
# generate() AND the prefix cache actually fired (hit rate > 0,
# tokens saved > 0)
serve-smoke:
	JAX_PLATFORMS=cpu python bench.py --serve --fast --platform cpu

# train->serve handoff gate (docs/serving.md "Live weight handoff"):
# fit -> in-memory handoff -> serve -> fit -> handoff again on an
# emulated 8-device fsdp/tp mesh; FAILS unless the served tokens are
# identical to serving checkpoint-round-trip weights AND the second
# handoff is a pure transfer-cache hit (no recompile)
handoff-smoke:
	JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
		python bench.py --handoff --fast --platform cpu

# tiered-checkpointing gate (docs/resilience.md "Tiered
# checkpointing"): the same fit loop with blocking orbax saves vs
# tiered in-gap snapshots on 8 emulated CPU devices; FAILS unless the
# save-step stall (save_blocked_ms per save, dispatch_depth 2) drops
# >= 10x AND resume from every tier (host RAM, local disk, mirror) is
# bitwise identical to the blocking path
ckpt-smoke:
	JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
		python bench.py --checkpoint --fast --platform cpu

# telemetry gate (docs/observability.md): obs off-vs-on per-step
# overhead under a budget at dispatch_depth=2, /metrics Prometheus-
# parseable with non-zero step + serve series, /healthz flips to
# degraded under an injected watchdog stall and recovers, trainer +
# tiered-checkpoint + serving spans export as ONE valid Chrome-trace
# JSON, and an injected flip_bits SDC abort writes a flight-recorder
# bundle naming the flagged step
obs-smoke:
	JAX_PLATFORMS=cpu python bench.py --obs --fast --platform cpu

# supervisor gate (docs/resilience.md "Supervisor"): the full
# fault-tolerance loop with ZERO human intervention — (1) 2-process
# dp=2 chaos SDC flip on host 1 -> both workers abort SDCError ->
# supervisor restarts EXCLUDING host 1 -> shrunken dp=1 pod resumes
# from the newest valid tier and matches an uninterrupted reference
# trajectory, restart/exclusion counters scraped from the daemon's
# /metrics; (2) injected hang -> HangError -> restart full pod ->
# resumed completion; (3) induced crash loop through the `supervise`
# CLI -> bounded backoff, budget exhaustion, terminal give-up with a
# final flight bundle naming the reason
supervisor-smoke:
	JAX_PLATFORMS=cpu python scripts/supervisor_smoke.py

# fleet-observability gate (docs/observability.md "Fleet view"): a
# 2-process supervised run with an injected SDC flip must yield ONE
# aggregated scrape from the daemon's obs port — Prometheus-parseable
# with per-host labels, BOTH hosts' merged step_time_ms histogram, a
# goodput breakdown whose buckets sum to wall clock within 5%, and
# restart downtime attributed to the sdc-exclude policy rule — plus a
# serve request whose trace id appears on every span of its lifecycle
# in the exported Chrome-trace timeline
fleet-smoke:
	JAX_PLATFORMS=cpu python scripts/fleet_smoke.py

# serve-side fault-tolerance gate (docs/serving.md "Serving under the
# supervisor"): (1) a supervised serve worker is SIGKILLed mid-decode
# -> crash-backoff restart -> the request journal replays -> FAILS
# unless 100% of submitted requests end completed (greedy outputs
# token-identical to an uninterrupted reference) or explicitly
# shed/unserved, zero silent losses, with the restart downtime
# attributed to a down: bucket in the supervisor goodput ledger;
# (2) a 2-worker serve fleet with a sustained injected slowdown on
# host 1 -> fleet_straggler drift verdict -> the opt-in
# straggler-eviction rule excludes host 1 (elastic shrink) and
# attributes the downtime to down:straggler-evict
serve-chaos:
	JAX_PLATFORMS=cpu python scripts/serve_chaos_smoke.py

# routing-tier fault-tolerance gate (docs/serving.md "Router tier"):
# (A) SIGKILL a serve replica mid-decode behind the router -> the
# circuit breaker opens on consecutive probe failures, the journal-
# named remainder fails over to the survivor under the original rids
# (greedy tokens identical to a single-engine reference), and the
# router's breaker/failover/goodput series surface on the daemon's
# aggregated /metrics + /fleet; (B) SIGKILL the ROUTER mid-wave ->
# restart replays the assignment journal and reconciles against the
# workers' journals — 100% accounting, no duplicate completions;
# (C) a same-template wave pins the warm replica (prefix_hit_rate)
# vs a routing-off control that spreads it cold
router-chaos:
	JAX_PLATFORMS=cpu python scripts/router_chaos_smoke.py

# host-replacement gate (docs/resilience.md "Host replacement &
# grow-back"): (1) a 2-process dp=2 worker SIGKILLs itself (no flight
# bundle — the hardware-loss signature) -> crash-replace -> the hot-
# spare pool refills the slot -> the pod relaunches at FULL width and
# the post-rejoin loss trajectory is bitwise identical to an
# uninterrupted dp=2 reference; (2) provisioning is armed to fail ->
# replace-fallback-shrink (dp=1) -> a preemption boundary later the
# daemon's grow-back re-provisions the excluded slot, readmits it, and
# the run finishes back at world=2 — with the provisioning windows
# attributed to down:provisioning in a goodput ledger that still sums
# to wall clock, and the fleet-history CLI replaying the timeline
chaos-replace:
	JAX_PLATFORMS=cpu python scripts/chaos_replace_smoke.py

# fault-injection suite (docs/resilience.md) under 3 seeds: CHAOS_SEED
# shifts where the NaN losses / preemptions / I/O faults / injected
# hangs land, so three different fault schedules exercise the same
# guarantees.  test_watchdog.py rides along: deterministic fake-clock
# coverage of the hang-detection path the chaos runs trip for real.
chaos:
	for s in 0 1 2; do \
		echo "== chaos seed $$s =="; \
		CHAOS_SEED=$$s JAX_PLATFORMS=cpu $(PYTEST) tests/test_resilience.py \
			tests/test_watchdog.py tests/test_elastic.py \
			tests/test_sdc.py tests/test_perf.py \
			tests/test_serving.py tests/test_prefix_cache.py \
			tests/test_quant.py \
			tests/test_handoff.py tests/test_tiered.py \
			tests/test_obs.py tests/test_profiling.py \
			tests/test_supervisor.py tests/test_fleet.py \
			tests/test_serve_resilience.py \
			tests/test_router.py \
			-m "not slow" \
			-q || exit 1; \
	done
	$(MAKE) supervisor-smoke
	$(MAKE) fleet-smoke
	$(MAKE) serve-chaos
	$(MAKE) router-chaos
	$(MAKE) chaos-replace
	$(MAKE) data-chaos
	$(MAKE) store-chaos

# streaming-data-plane gate (docs/data.md): the full store/stream
# suite under 3 ChaosStore fault schedules — transient errors, 429
# throttles, torn reads, checksum corruption, dead sources.  Proves
# kill -9 mid-stream + restart yields bitwise-identical remaining
# batches under injected store faults, quarantine-at-encounter equals
# a pre-excluded run, a dead source sheds to survivors, and injected
# stalls land in the data_wait goodput bucket — never as HangError.
data-chaos:
	for s in 0 1 2; do \
		echo "== data chaos seed $$s =="; \
		CHAOS_SEED=$$s JAX_PLATFORMS=cpu $(PYTEST) \
			tests/test_datastream.py -m "not slow" -q || exit 1; \
	done

# unified object-store-plane gate (docs/resilience.md "Object-store
# tier-2"): the shared PUT/GET client + two-phase commit under 3
# write-side ChaosObjectStore fault schedules — transient 5xx, partial
# (torn-object) uploads, acknowledged-but-lost writes, lost commit
# markers, stale listings, dead destinations.  Proves kill -9
# mid-trickle under write faults restarts to a bitwise newest-tier
# restore, torn uploads stay invisible to restore_latest_valid, a
# breaker-open mirror degrades to tier-1-only, and a journal archive
# upload killed after rotation loses no record (union replay 100%).
# Runs the slow subprocess kill fixtures too — they ARE the gate.
store-chaos:
	for s in 0 1 2; do \
		echo "== store chaos seed $$s =="; \
		CHAOS_SEED=$$s JAX_PLATFORMS=cpu $(PYTEST) \
			tests/test_store.py -q || exit 1; \
	done

# multi-host robustness proof: 2-process jax.distributed fixtures
# (cross-host resume consensus with divergent quarantine, preemption
# sync, coordination primitives) — subprocess-based, so run separately
# from the in-process suites
chaos-multihost:
	JAX_PLATFORMS=cpu $(PYTEST) tests/ -m multihost -q

# elastic-resume proof: corrupt-batch quarantine + topology-change
# chaos scenarios under 3 seeds (fast, in-process), then the
# subprocess DP=2 <-> DP=1 save/restore fixtures
chaos-elastic:
	for s in 0 1 2; do \
		echo "== chaos-elastic seed $$s =="; \
		CHAOS_SEED=$$s JAX_PLATFORMS=cpu $(PYTEST) tests/test_elastic.py \
			-m "not slow" -q || exit 1; \
	done
	JAX_PLATFORMS=cpu $(PYTEST) tests/test_elastic.py -m "elastic and slow" -q

# SDC-defense proof: bit-flip chaos (cross-replica localization,
# recompute spot checks, deterministic replay) under 3 seeds, then the
# 2-process DP=2 fixture where a flip on host 1 is localized to host 1
chaos-sdc:
	for s in 0 1 2; do \
		echo "== chaos-sdc seed $$s =="; \
		CHAOS_SEED=$$s JAX_PLATFORMS=cpu $(PYTEST) tests/test_sdc.py \
			-m "not slow" -q || exit 1; \
	done
	JAX_PLATFORMS=cpu $(PYTEST) tests/test_sdc.py -m "sdc and slow" -q

dryrun:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	python -c "import jax; jax.config.update('jax_platforms','cpu'); \
	import __graft_entry__ as g; g.dryrun_multichip(8)"

lint:
	python -m compileall -q torchacc_tpu benchmarks bench.py __graft_entry__.py

# probe the TPU transport until it recovers, then capture a profiled
# bench run + the 8B-geometry row (writes docs/last_good_bench.json)
tpu-watch:
	nohup bash scripts/tpu_watch.sh >/dev/null 2>&1 &
