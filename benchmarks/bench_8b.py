"""Llama-3-8B-geometry benchmark on one chip (VERDICT round-2 next-3).

The full 8B model cannot fit a single 16 GB chip with f32 Adam, but its
per-layer arithmetic can be measured exactly: run as many TRUE 8B-geometry
layers as fit (h=4096, 32 heads / 8 kv heads (GQA 4:1), ffn=14336,
vocab=128256, seq 8192) at two depths and difference the step times to
isolate per-layer cost; the remainder is the embed + fused-CE head cost at
128k vocab.  Embeddings are tied (Llama-3's are not) purely to halve the
1.05B embed+head parameter footprint — the head matmul/CE FLOPs measured
are identical.

Reference bar: the reference's headline Llama-3-8B FSDP number
(docs/source/tutorials/hf_transformers.md:340-349, 4044.8 tok/s/GPU on
8xA100 ~= 62% MFU-equivalent); BASELINE.md north star >= 50% MFU.

Writes docs/bench_8b.json and prints one JSON line.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import Watchdog, peak_flops, _write_last_good  # noqa: E402,F401

_OUT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    "docs", "bench_8b.json")


def make_config(n_layers: int, seq: int, scan_layers: bool,
                smoke: bool = False):
    """The 8B-geometry ModelConfig — single source for both the timed
    trainer and the report's FLOPs math."""
    from torchacc_tpu.models import get_preset

    kw = dict(num_layers=n_layers, max_seq_len=seq, tie_embeddings=True,
              scan_layers=scan_layers)
    if smoke:  # CPU-sized stand-in exercising the same control flow
        kw.update(hidden_size=256, num_heads=4, num_kv_heads=2,
                  intermediate_size=1024, vocab_size=4096)
    return get_preset("llama3-8b", **kw)


def build_trainer(n_layers: int, seq: int, batch: int, gc_policy: str,
                  scan_layers: bool, smoke: bool = False,
                  shadow: bool = True):
    import optax

    import torchacc_tpu as ta
    from torchacc_tpu.train import accelerate

    mc = make_config(n_layers, seq, scan_layers, smoke)
    cfg = ta.Config()
    cfg.memory.gc = True
    cfg.memory.gc_policy = gc_policy
    # same main-params AMP as the headline bench (docs/PERF.md): at this
    # geometry the f32->bf16 cast it removes is ~3 GB/step for the
    # 525M-param embed/head alone.  --no-shadow reproduces the
    # pre-shadow baseline rows.
    cfg.compute.bf16_compute_params = shadow
    trainer, _ = accelerate(mc, None, cfg, optimizer=optax.adamw(1e-4))
    trainer.init()
    return trainer, mc


def time_step(trainer, batch_data, iters: int, warmup: int = 2) -> float:
    m = None
    for _ in range(warmup):
        m = trainer.step(batch_data)
    float(m["loss"])
    t0 = time.perf_counter()
    for _ in range(iters):
        m = trainer.step(batch_data)
    float(m["loss"])
    return (time.perf_counter() - t0) / iters


def run_depth(n_layers, seq, batch, iters, gc_policy, scan_layers, wd,
              smoke=False, shadow=True):
    import jax.numpy as jnp
    import numpy as np

    wd.stage(f"build_L{n_layers}", 180)
    trainer, mc = build_trainer(n_layers, seq, batch, gc_policy, scan_layers,
                                smoke, shadow)
    rng = np.random.default_rng(0)
    batch_data = {"input_ids": jnp.asarray(
        rng.integers(0, mc.vocab_size, size=(batch, seq)), jnp.int32)}
    wd.stage(f"compile_L{n_layers}", 1500)
    dt = time_step(trainer, batch_data, iters)
    del trainer
    return dt, mc


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, default=8192)
    ap.add_argument("--batch", type=int, default=1)
    ap.add_argument("--iters", type=int, default=5)
    ap.add_argument("--gc_policy", default="save_attn")
    ap.add_argument("--scan", action="store_true",
                    help="scan-stacked layers (default: unrolled)")
    ap.add_argument("--depths", type=int, nargs="+", default=[2, 1, 0],
                    help="layer depths to try, deepest first; first two "
                         "that fit are differenced.  Depth 0 (embed + "
                         "fused-CE head only) is a valid rung: L1-L0 "
                         "isolates exactly one true 8B layer.")
    ap.add_argument("--platform", default=None)
    ap.add_argument("--smoke", action="store_true",
                    help="tiny stand-in geometry for CPU control-flow tests "
                         "(never writes docs/bench_8b.json)")
    ap.add_argument("--no-shadow", action="store_true",
                    help="disable compute.bf16_compute_params (the "
                         "pre-shadow baseline precision mode)")
    ap.add_argument("--one-depth", type=int, default=None,
                    help="internal: time ONE depth in this process and "
                         "print {'_depth', 'dt'}; used by the parent loop "
                         "so an OOM'd depth's resident buffers (params + "
                         "opt state survive the failed compile) cannot "
                         "poison shallower attempts")
    args = ap.parse_args()

    if args.one_depth is not None:
        wd = Watchdog()
        jax = _setup_jax(args)
        try:
            wd.stage("device_init", 120)
            kind = getattr(jax.devices()[0], "device_kind", "")
            dt, _ = run_depth(args.one_depth, args.seq, args.batch,
                              args.iters, args.gc_policy, args.scan, wd,
                              args.smoke, shadow=not args.no_shadow)
        except Exception as e:  # noqa: BLE001
            print(json.dumps({"_depth": args.one_depth,
                              "error": f"{type(e).__name__}: {e}"}))
            return 1
        print(json.dumps({"_depth": args.one_depth, "dt": dt,
                          "device_kind": kind}))
        return 0

    wd = Watchdog()
    try:
        return _bench(args, wd)
    except Exception as e:  # noqa: BLE001
        out = {"metric": "llama3_8b_geometry_layer_mfu", "value": 0.0,
               "unit": "mfu_fraction", "vs_baseline": 0.0,
               "error": f"{type(e).__name__}: {e}"}
        print(json.dumps(out))
        return 1


def _setup_jax(args):
    cache_dir = os.path.expanduser("~/.cache/torchacc_tpu_bench")
    os.makedirs(cache_dir, exist_ok=True)
    import jax

    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    return jax


def _is_oom(msg: str) -> bool:
    # The remote-compile tunnel (axon) surfaces HBM OOM as a
    # JaxRuntimeError INTERNAL/HTTP-500 whose body says "Ran out of
    # memory in memory space hbm" — match case-insensitively.
    msg = msg.lower()
    return ("resource_exhausted" in msg or "out of memory" in msg
            or "exceeds the limit" in msg or "hbm capacity" in msg)


def _bench(args, wd: Watchdog) -> int:
    import subprocess

    # Deepest two depths that fit, each timed in a FRESH subprocess: a
    # depth whose compile OOMs leaves its params + opt state resident on
    # the chip (the failed trainer is unreachable but the device buffers
    # outlive the exception), which would turn every shallower attempt
    # into a runtime OOM.  Process isolation makes the attempts
    # independent; the persistent compile cache keeps retries cheap.
    # The parent deliberately never initialises a JAX backend: on a
    # locally-attached TPU (exclusive PJRT ownership, unlike the remote
    # tunnel) a parent holding the chip would make every child fail.
    results = {}
    device_kind = ""
    for L in args.depths:
        if len(results) == 2:
            break
        wd.stage(f"subproc_L{L}", 1900)
        cmd = [sys.executable, os.path.abspath(__file__),
               "--one-depth", str(L), "--seq", str(args.seq),
               "--batch", str(args.batch), "--iters", str(args.iters),
               "--gc_policy", args.gc_policy]
        if args.no_shadow:
            cmd.append("--no-shadow")
        if args.scan:
            cmd.append("--scan")
        if args.smoke:
            cmd.append("--smoke")
        if args.platform:
            cmd += ["--platform", args.platform]
        try:
            r = subprocess.run(cmd, capture_output=True, text=True,
                               timeout=1800)
        except subprocess.TimeoutExpired:
            raise RuntimeError(f"depth {L} subprocess hung (1800s)")
        rec = None
        for line in r.stdout.splitlines():
            try:
                cand = json.loads(line)
            except ValueError:
                continue
            if isinstance(cand, dict) and cand.get("_depth") == L:
                rec = cand
        if rec is not None and "dt" in rec:
            results[L] = rec["dt"]
            device_kind = rec.get("device_kind") or device_kind
            print(f"[bench8b] L={L}: {rec['dt']*1e3:.1f} ms/step "
                  f"({device_kind})", file=sys.stderr)
        elif rec is not None and _is_oom(rec.get("error", "")):
            print(f"[bench8b] L={L} OOM; trying shallower", file=sys.stderr)
        elif rec is None and _is_oom(r.stderr or ""):
            # OOM killed the child before it could print its JSON line
            # (libtpu fatal abort / watchdog exit mid-OOM-stall).
            print(f"[bench8b] L={L} OOM (child died); trying shallower",
                  file=sys.stderr)
        else:
            err = (rec or {}).get("error") or r.stderr[-2000:]
            raise RuntimeError(f"depth {L} subprocess failed: {err}")
    if len(results) < 2:
        raise RuntimeError(f"needed two depths, got {results}")
    peak = peak_flops(device_kind)
    mc = make_config(1, args.seq, args.scan, args.smoke)

    (L_hi, t_hi), (L_lo, t_lo) = sorted(results.items(), reverse=True)
    t_layer = (t_hi - t_lo) / (L_hi - L_lo)
    t_rest = t_hi - L_hi * t_layer  # embed + fused-CE head + step overhead

    h, v = mc.hidden_size, mc.vocab_size
    tokens = args.batch * args.seq
    # per-layer fwd+bwd flops: 6 * per-layer params + causal attention term
    # (qkvo with GQA kv width + swiglu mlp + 2 rmsnorms, matching num_params)
    d = mc.head_size
    layer_params = (h * mc.num_heads * d + 2 * h * mc.kv_heads * d
                    + mc.num_heads * d * h + 3 * h * mc.ffn_size + 2 * h)
    flops_layer = (6.0 * layer_params + 6.0 * h * args.seq) * tokens
    mfu_layer = flops_layer / t_layer / peak
    flops_head = 6.0 * h * v * tokens  # tied head matmul fwd+bwd
    mfu_head = flops_head / max(t_rest, 1e-9) / peak

    result = {
        "metric": "llama3_8b_geometry_layer_mfu",
        "value": round(float(mfu_layer), 4),
        "unit": "mfu_fraction",
        "vs_baseline": round(float(mfu_layer) / 0.50, 4),
        "detail": {
            "geometry": {"hidden": h, "heads": mc.num_heads,
                         "kv_heads": mc.num_kv_heads,
                         "ffn": mc.intermediate_size, "vocab": v,
                         "seq": args.seq, "batch": args.batch,
                         "tied_embeddings": True},
            "depths_measured": {str(k): round(v_, 4)
                                for k, v_ in results.items()},
            "per_layer_ms": round(t_layer * 1e3, 2),
            "embed_head_ce_ms": round(t_rest * 1e3, 2),
            "head_mfu_at_128k_vocab": round(float(mfu_head), 4),
            "gc_policy": args.gc_policy,
            "scan_layers": bool(args.scan),
            "bf16_compute_params": not args.no_shadow,
            "chip": device_kind,
        },
    }
    if not args.smoke:
        try:
            with open(_OUT, "w") as f:
                json.dump(result, f, indent=1)
        except Exception as e:  # noqa: BLE001
            print(f"[bench8b] could not write {_OUT}: {e}", file=sys.stderr)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
