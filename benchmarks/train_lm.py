"""LM training benchmark CLI.

Reference equivalent: ``benchmarks/transformer.py`` (GPT-2/HF CLM loop
with --dp/--fsdp/--pp/--gc/--fp16/--bf16/--profile flags,
transformer.py:33-220).  Trains a zoo preset on synthetic data and
reports tokens/s, step time, and MFU.

Examples:
  python benchmarks/train_lm.py --model llama-tiny --steps 20
  python benchmarks/train_lm.py --model gpt2 --fsdp 8 --gc
  python benchmarks/train_lm.py --model llama3-8b --fsdp 16 --tp 4 \
      --seq 4096 --batch 16 --profile /tmp/trace
  python benchmarks/train_lm.py --config my_config.json --json
"""

from __future__ import annotations

import argparse
import contextlib
import json
import os
import sys
import time

# repo root on sys.path so `bench` (peak_flops table) resolves when this
# script is run directly (sys.path[0] is benchmarks/ in that case)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="torchacc_tpu LM benchmark")
    p.add_argument("--model", default="llama-tiny")
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--seq", type=int, default=512)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--warmup", type=int, default=3)
    p.add_argument("--lr", type=float, default=1e-4)
    # parallelism (reference: --dp/--fsdp/--tp/--pp flags)
    p.add_argument("--dp", type=int, default=-1)
    p.add_argument("--fsdp", type=int, default=1)
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--pp", type=int, default=1)
    p.add_argument("--pp_microbatches", type=int, default=None)
    p.add_argument("--pp_schedule", default="gpipe",
                   choices=["gpipe", "1f1b"])
    p.add_argument("--pp_virtual", type=int, default=1,
                   help="interleaved virtual stages per device")
    p.add_argument("--sp", type=int, default=1)
    p.add_argument("--sp_mode", default="ulysses",
                   choices=["ulysses", "ring", "2d"])
    p.add_argument("--sp_intra", type=int, default=None)
    p.add_argument("--ep", type=int, default=1)
    # memory / numerics (reference: --gc/--fp16/--bf16)
    p.add_argument("--gc", action="store_true")
    p.add_argument("--gc_policy", default="nothing")
    p.add_argument("--gc_cnt", type=int, default=None,
                   help="remat only the first N layers")
    p.add_argument("--offload_activations", action="store_true")
    p.add_argument("--attn_dropout", type=float, default=0.0)
    p.add_argument("--fp16", action="store_true")
    p.add_argument("--fp32", action="store_true")
    p.add_argument("--bf16_shadow", action="store_true",
                   help="compute.bf16_compute_params: bf16 param shadow "
                        "in opt state (main-params AMP, docs/PERF.md)")
    p.add_argument("--no_flash", action="store_true")
    p.add_argument("--grad_accum", type=int, default=1)
    p.add_argument("--profile", default=None, metavar="LOGDIR")
    p.add_argument("--json", action="store_true", help="one JSON line out")
    p.add_argument("--config", default=None, metavar="JSON_FILE",
                   help="full ta.Config as JSON (overrides parallelism/"
                        "memory/numerics flags)")
    return p.parse_args(argv)


def _config_from_flags(args, dtype):
    import torchacc_tpu as ta
    return ta.Config(
        compute=ta.ComputeConfig(dtype=dtype,
                                 flash_attention=not args.no_flash,
                                 bf16_compute_params=args.bf16_shadow),
        memory=ta.MemoryConfig(gc=args.gc, gc_policy=args.gc_policy,
                               gc_cnt=args.gc_cnt,
                               offload_activations=args.offload_activations),
        dist=ta.DistConfig(
            dp=ta.DPConfig(size=args.dp),
            fsdp=ta.FSDPConfig(size=args.fsdp),
            tp=ta.TPConfig(size=args.tp),
            pp=ta.PPConfig(size=args.pp,
                           num_micro_batches=(args.pp_microbatches
                                              or max(1, 2 * args.pp)),
                           schedule=args.pp_schedule,
                           virtual_stages=args.pp_virtual),
            sp=ta.SPConfig(size=args.sp, mode=args.sp_mode,
                           intra_size=args.sp_intra),
            ep=ta.EPConfig(size=args.ep),
        ),
        grad_accum=args.grad_accum,
    )


def main(argv=None) -> int:
    args = parse_args(argv)

    import jax
    import numpy as np
    import optax

    import torchacc_tpu as ta
    from torchacc_tpu.models import get_preset
    from torchacc_tpu.train import accelerate

    if args.config:
        with open(args.config) as f:
            cfg = ta.Config.from_dict(json.load(f))
        dtype = cfg.compute.dtype
    else:
        dtype = ("float16" if args.fp16
                 else ("float32" if args.fp32 else "bfloat16"))
        cfg = _config_from_flags(args, dtype)

    mc = get_preset(args.model, max_seq_len=max(args.seq, 8),
                    attn_dropout=args.attn_dropout)
    trainer, _ = accelerate(mc, None, cfg, optimizer=optax.adamw(args.lr))
    trainer.init()

    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(
        0, mc.vocab_size, size=(args.batch, args.seq)).astype(np.int32)}

    m = None
    for _ in range(args.warmup):
        m = trainer.step(batch)
    if m is not None:
        float(m["loss"])  # drain warmup before timing

    if args.profile:
        from torchacc_tpu.utils.profiling import trace
        ctx = trace(args.profile)
    else:
        ctx = contextlib.nullcontext()
    # steps dispatch asynchronously, so wall time over the whole loop with
    # one final sync is the only honest per-step measure
    with ctx:
        t0 = time.perf_counter()
        for _ in range(args.steps):
            m = trainer.step(batch)
        loss = float(m["loss"])  # sync
        total = time.perf_counter() - t0
    dt = total / max(args.steps, 1)

    n_chips = len(jax.devices())
    tokens_per_sec = args.batch * args.seq / dt
    flops_per_token = (6.0 * mc.num_params()
                       + 6.0 * mc.num_layers * mc.hidden_size * args.seq)
    from bench import peak_flops  # repo-root bench helpers
    mfu = (flops_per_token * tokens_per_sec
           / (peak_flops(jax.devices()[0]) * n_chips))

    result = {
        "model": args.model,
        "loss": round(loss, 4),
        "step_time_s": round(dt, 4),
        "tokens_per_sec": round(tokens_per_sec, 1),
        "tokens_per_sec_per_chip": round(tokens_per_sec / n_chips, 1),
        "mfu": round(mfu, 4),
        "params_m": round(mc.num_params() / 1e6, 1),
        "mesh": dict(trainer.mesh.shape),
        "dtype": dtype,
    }
    if args.json:
        print(json.dumps(result))
    else:
        for k, v in result.items():
            print(f"{k:>24}: {v}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
