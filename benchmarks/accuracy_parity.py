"""Accuracy-parity harness: torch HF training vs the converted model.

Reference: the accuracy benchmark suite trains the SAME model under
torch and under torchacc on identical data/hyper-parameters and compares
loss curves (+ downstream eval) — benchmarks/accuracy/README.md:95-109,
.github/workflows/accuracy_benchmark.yml.  TPU-native equivalent: build
a small HF model in torch (CPU; --family llama or qwen2), fine-tune it
with a plain torch loop, convert the SAME initial weights through
models/hf.py and fine-tune with this framework's Trainer on the SAME
token stream and hyper-parameters, then require (a) the two loss curves
to agree step by step, (b) the tuned models' heldout losses to agree
(the downstream-eval leg), and (c) training to actually improve.

One command, one JSON verdict line::

    python benchmarks/accuracy_parity.py [--steps 20] [--tol 0.02] \
        [--family llama|qwen2]

Exit code 0 iff all three gates hold.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# CPU-only determinism for both frameworks (run before importing jax)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
# runnable as `python benchmarks/accuracy_parity.py` from a checkout
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def torch_curve(hf_model, ids, steps, lr, heldout):
    """Plain torch fine-tune loop: next-token CE, SGD, f32.  Returns the
    loss curve plus heldout perplexity of the TUNED model (the
    downstream-eval leg — reference scores the tuned model too,
    benchmarks/accuracy/README.md:103-105)."""
    import torch

    model = hf_model.train()
    opt = torch.optim.SGD(model.parameters(), lr=lr)
    losses = []
    for step in range(steps):
        batch = torch.from_numpy(ids[step])
        out = model(input_ids=batch, labels=batch)
        # HF computes shifted CE internally (mean over tokens)
        opt.zero_grad()
        out.loss.backward()
        opt.step()
        losses.append(float(out.loss.detach()))
    model.eval()
    with torch.no_grad():
        ev = [float(model(input_ids=torch.from_numpy(b),
                          labels=torch.from_numpy(b)).loss)
              for b in heldout]
    return losses, sum(ev) / len(ev)


def converted_curve(hf_model, ids, steps, lr, heldout):
    """Same initial weights via models/hf.py, trained by the Trainer;
    returns the curve plus heldout perplexity of the tuned model."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import optax

    import torchacc_tpu as ta
    from torchacc_tpu.models import load_hf_model
    from torchacc_tpu.train import accelerate

    mc, params = load_hf_model(hf_model, dtype=jnp.float32,
                               param_dtype=jnp.float32)
    cfg = ta.Config(compute=ta.ComputeConfig(
        dtype="float32", fused_kernels=False))
    trainer, _ = accelerate(mc, None, cfg, optimizer=optax.sgd(lr))
    trainer.init()
    trainer.state = trainer.state.replace(params=params)
    losses = []
    for step in range(steps):
        m = trainer.step({"input_ids": jnp.asarray(ids[step])})
        losses.append(float(m["loss"]))
    ev = [float(trainer.eval_step({"input_ids": jnp.asarray(b)}))
          for b in heldout]
    return losses, sum(ev) / len(ev)


def _build_hf(family: str, seq: int):
    import torch
    import transformers

    # the HF init draws from torch's GLOBAL rng: seed it or every run
    # trains a different model (and the `improved` gate on a short run
    # becomes a coin flip)
    torch.manual_seed(0)
    kw = dict(vocab_size=256, hidden_size=64, intermediate_size=128,
              num_hidden_layers=2, num_attention_heads=4,
              num_key_value_heads=2, max_position_embeddings=seq,
              rope_theta=10000.0)
    if family == "llama":
        return transformers.LlamaForCausalLM(
            transformers.LlamaConfig(**kw)).float()
    if family == "qwen2":  # qkv bias — the reference's Qwen patch target
        return transformers.Qwen2ForCausalLM(
            transformers.Qwen2Config(**kw)).float()
    raise ValueError(family)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--lr", type=float, default=3e-2)
    ap.add_argument("--tol", type=float, default=0.02,
                    help="max allowed relative loss deviation")
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--family", default="llama",
                    choices=["llama", "qwen2"])
    args = ap.parse_args(argv)

    import numpy as np

    hf_model = _build_hf(args.family, args.seq)

    rng = np.random.default_rng(0)
    # tokens from a quarter of the vocab: LEARNABLE data (the model
    # shifts mass onto the live tokens, loss falls toward log(64)), so
    # the `improved` gate checks that training actually trains instead
    # of flipping a coin on uniform noise
    ids = rng.integers(0, 64, size=(args.steps, args.batch, args.seq)
                       ).astype(np.int64)
    # heldout set for the downstream-eval leg: same distribution, never
    # trained on (reference also scores the tuned model,
    # benchmarks/accuracy/README.md:103-105; MT-bench itself needs
    # serving infra — heldout perplexity is the self-contained analogue)
    heldout = rng.integers(0, 64, size=(4, args.batch, args.seq)
                           ).astype(np.int64)

    ours, ev_ours = converted_curve(hf_model, ids, args.steps, args.lr,
                                    heldout)
    theirs, ev_torch = torch_curve(hf_model, ids, args.steps, args.lr,
                                   heldout)

    devs = [abs(a - b) / max(abs(b), 1e-6) for a, b in zip(ours, theirs)]
    max_dev = max(devs)
    # gate the downstream leg on heldout LOSS deviation (the same scale
    # as --tol); perplexity is exp(loss), so a rel-ppl gate would be
    # ~loss-magnitude-fold stricter than the curve gate next to it
    ev_dev = abs(ev_ours - ev_torch) / max(abs(ev_torch), 1e-6)
    import math
    ppl_ours, ppl_torch = math.exp(ev_ours), math.exp(ev_torch)
    improved = ours[-1] < ours[0]
    ok = bool(max_dev <= args.tol and ev_dev <= args.tol and improved)
    print(json.dumps({
        "metric": f"accuracy_parity_{args.family}_sft",
        "ok": ok,
        "max_rel_dev": round(max_dev, 5),
        "tol": args.tol,
        "loss_first": {"torch": round(theirs[0], 5),
                       "torchacc_tpu": round(ours[0], 5)},
        "loss_last": {"torch": round(theirs[-1], 5),
                      "torchacc_tpu": round(ours[-1], 5)},
        "heldout": {"loss_torch": round(ev_torch, 5),
                    "loss_torchacc_tpu": round(ev_ours, 5),
                    "loss_rel_dev": round(ev_dev, 5),
                    "ppl_torch": round(ppl_torch, 4),
                    "ppl_torchacc_tpu": round(ppl_ours, 4)},
        "steps": args.steps,
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
