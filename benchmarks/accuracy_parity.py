"""Accuracy-parity harness: torch HF training vs the converted model.

Reference: the accuracy benchmark suite trains the SAME model under
torch and under torchacc on identical data/hyper-parameters and compares
loss curves (+ downstream eval) — benchmarks/accuracy/README.md:95-109,
.github/workflows/accuracy_benchmark.yml.  TPU-native equivalent: build
a small HF model in torch (CPU; --family llama or qwen2), fine-tune it
with a plain torch loop, convert the SAME initial weights through
models/hf.py and fine-tune with this framework's Trainer on the SAME
token stream and hyper-parameters, then require (a) the two loss curves
to agree step by step, (b) the tuned models' heldout losses to agree
(the downstream-eval leg), and (c) training to actually improve.

One command, one JSON verdict line::

    python benchmarks/accuracy_parity.py [--steps 20] [--tol 0.02] \
        [--family llama|qwen2]

Exit code 0 iff all three gates hold.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# CPU-only determinism for both frameworks (run before importing jax)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
# runnable as `python benchmarks/accuracy_parity.py` from a checkout
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# AdamW hyper-parameters pinned EXPLICITLY on both sides: torch and
# optax have different defaults (weight_decay 1e-2 vs 1e-4), and the
# whole point of the AdamW leg is that moment/decay arithmetic agrees
# over hundreds of steps
_ADAMW = dict(b1=0.9, b2=0.999, eps=1e-8, weight_decay=0.01)


def torch_curve(hf_model, ids, steps, lr, heldout, optimizer="sgd",
                dtype="float32"):
    """Plain torch fine-tune loop: next-token CE.  ``dtype='bfloat16'``
    runs forward/backward under CPU autocast with f32 master weights —
    the same mixed-precision regime as the converted side (bf16 compute
    dtype, f32 param dtype).  Returns the loss curve plus heldout loss
    of the TUNED model (the downstream-eval leg — reference scores the
    tuned model too, benchmarks/accuracy/README.md:103-105)."""
    import contextlib

    import torch

    model = hf_model.train()
    if optimizer == "adamw":
        opt = torch.optim.AdamW(
            model.parameters(), lr=lr,
            betas=(_ADAMW["b1"], _ADAMW["b2"]), eps=_ADAMW["eps"],
            weight_decay=_ADAMW["weight_decay"])
    else:
        opt = torch.optim.SGD(model.parameters(), lr=lr)
    autocast = (torch.autocast("cpu", dtype=torch.bfloat16)
                if dtype == "bfloat16" else contextlib.nullcontext())
    losses = []
    for step in range(steps):
        batch = torch.from_numpy(ids[step])
        with autocast:
            out = model(input_ids=batch, labels=batch)
        # HF computes shifted CE internally (mean over tokens)
        opt.zero_grad()
        out.loss.backward()
        opt.step()
        losses.append(float(out.loss.detach()))
    model.eval()
    with torch.no_grad(), autocast:
        ev = [float(model(input_ids=torch.from_numpy(b),
                          labels=torch.from_numpy(b)).loss)
              for b in heldout]
    return losses, sum(ev) / len(ev)


def converted_curve(hf_model, ids, steps, lr, heldout, optimizer="sgd",
                    dtype="float32"):
    """Same initial weights via models/hf.py, trained by the Trainer;
    returns the curve plus heldout loss of the tuned model."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import optax

    import torchacc_tpu as ta
    from torchacc_tpu.models import load_hf_model
    from torchacc_tpu.train import accelerate

    compute_dtype = (jnp.bfloat16 if dtype == "bfloat16"
                     else jnp.float32)
    mc, params = load_hf_model(hf_model, dtype=compute_dtype,
                               param_dtype=jnp.float32)
    cfg = ta.Config(compute=ta.ComputeConfig(
        dtype=dtype, fused_kernels=False))
    opt = (optax.adamw(lr, **_ADAMW) if optimizer == "adamw"
           else optax.sgd(lr))
    trainer, _ = accelerate(mc, None, cfg, optimizer=opt)
    trainer.init()
    trainer.state = trainer.state.replace(params=params)
    losses = []
    for step in range(steps):
        m = trainer.step({"input_ids": jnp.asarray(ids[step])})
        losses.append(float(m["loss"]))
    ev = [float(trainer.eval_step({"input_ids": jnp.asarray(b)}))
          for b in heldout]
    return losses, sum(ev) / len(ev)


def generation_parity(hf_model, prompts, gen_tokens):
    """Generation-quality leg (reference: the accuracy benchmark scores
    the TUNED model with MT-bench via FastChat,
    benchmarks/accuracy/README.md:103-105 — needs serving infra; the
    self-contained analogue is greedy-decode agreement): the TUNED torch
    model is converted through models/hf.py and both sides greedy-decode
    the same prompts in f32.  Identical weights, so a mismatch means the
    conversion or KV-cache decode stack changed the model — training
    drift is gated separately by the curve and heldout legs, which keeps
    this leg deterministic (token-for-token) in CI.

    Returns (token_match_frac, logprob_dev): exact-agreement fraction
    over generated positions, and — teacher-forcing the torch
    continuation through both models — the max abs deviation of the
    next-token log-probs at torch's chosen tokens (the tight
    logit-divergence diagnostic)."""
    import jax.numpy as jnp
    import numpy as np
    import torch

    from torchacc_tpu.models.generate import generate
    from torchacc_tpu.models.hf import load_hf_model
    from torchacc_tpu.models.transformer import TransformerLM

    b, p = prompts.shape
    model = hf_model.eval()
    with torch.no_grad():
        # eos_token_id=None on the torch side + no eos_id on the jax
        # side: SYMMETRIC no-early-stop greedy decode.  (min_new_tokens
        # would instead suppress the eos LOGIT on the torch side only —
        # an asymmetry that flips tokens when the tuned argmax is eos.)
        # explicit all-ones mask: with pad_token_id set and no mask, HF
        # INFERS attention_mask = inputs.ne(pad) and would mask real
        # 0-tokens mid-prompt — an asymmetry the jax side doesn't have
        t_out = model.generate(
            torch.from_numpy(prompts),
            attention_mask=torch.ones_like(torch.from_numpy(prompts)),
            max_new_tokens=gen_tokens,
            do_sample=False, eos_token_id=None, pad_token_id=0)
    t_toks = t_out.numpy()                       # [b, p + G]

    mc, params = load_hf_model(model, dtype=jnp.float32,
                               param_dtype=jnp.float32)
    eval_model = TransformerLM(mc)
    ours = np.asarray(generate(eval_model, params,
                               jnp.asarray(prompts, jnp.int32),
                               max_new_tokens=gen_tokens))
    match = float((ours[:, p:] == t_toks[:, p:]).mean())

    # teacher-forced log-prob deviation on the torch continuation
    with torch.no_grad():
        t_logits = model(torch.from_numpy(t_toks)).logits.float().numpy()
    j_logits = np.asarray(eval_model.apply(
        {"params": params}, jnp.asarray(t_toks, jnp.int32)), np.float32)

    def logprob_at_next(logits):
        m = logits.max(axis=-1, keepdims=True)
        lp = logits - (m + np.log(np.exp(logits - m).sum(-1,
                                                         keepdims=True)))
        nxt = t_toks[:, 1:]
        return np.take_along_axis(lp[:, :-1], nxt[..., None], -1)[..., 0]

    lp_dev = float(np.max(np.abs(logprob_at_next(t_logits)[:, p - 1:]
                                 - logprob_at_next(j_logits)[:, p - 1:])))
    return match, lp_dev


def _build_hf(family: str, seq: int, hidden: int = 64, layers: int = 2,
              vocab: int = 256):
    import torch
    import transformers

    # the HF init draws from torch's GLOBAL rng: seed it or every run
    # trains a different model (and the `improved` gate on a short run
    # becomes a coin flip)
    torch.manual_seed(0)
    kw = dict(vocab_size=vocab, hidden_size=hidden,
              intermediate_size=2 * hidden,
              num_hidden_layers=layers,
              num_attention_heads=max(hidden // 16, 1),
              num_key_value_heads=max(hidden // 32, 1),
              max_position_embeddings=seq,
              rope_theta=10000.0)
    if family == "llama":
        return transformers.LlamaForCausalLM(
            transformers.LlamaConfig(**kw)).float()
    if family == "qwen2":  # qkv bias — the reference's Qwen patch target
        return transformers.Qwen2ForCausalLM(
            transformers.Qwen2Config(**kw)).float()
    if family == "gemma2":  # sandwich norms, layer pattern, soft-caps
        kw = dict(kw, head_dim=max(kw["hidden_size"]
                                   // kw["num_attention_heads"], 8),
                  sliding_window=max(seq // 4, 4),
                  query_pre_attn_scalar=16,
                  attn_logit_softcapping=50.0,
                  final_logit_softcapping=30.0,
                  tie_word_embeddings=True, rms_norm_eps=1e-6,
                  attn_implementation="eager")
        return transformers.Gemma2ForCausalLM(
            transformers.Gemma2Config(**kw)).float()
    raise ValueError(family)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--lr", type=float, default=3e-2)
    ap.add_argument("--tol", type=float, default=0.02,
                    help="max allowed relative loss deviation")
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--family", default="llama",
                    choices=["llama", "qwen2", "gemma2"])
    ap.add_argument("--optimizer", default="sgd",
                    choices=["sgd", "adamw"],
                    help="adamw = the long-horizon leg where moment "
                         "accumulation effects live (VERDICT r3)")
    ap.add_argument("--dtype", default="float32",
                    choices=["float32", "bfloat16"],
                    help="bfloat16 = bf16 compute + f32 params on both "
                         "sides (torch CPU autocast vs ComputeConfig)")
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--gen-tokens", type=int, default=24,
                    help="greedy-decode length for the generation-"
                         "quality leg (0 disables)")
    ap.add_argument("--gen-tol", type=float, default=2e-3,
                    help="accept a greedy-token mismatch iff the max "
                         "next-token log-prob deviation (teacher-forced "
                         "on the torch continuation) stays under this "
                         "bound.  Identical converted weights measure "
                         "~0 here, but a short-SFT model's near-flat "
                         "distribution has exact argmax ties that f32 "
                         "conversion rounding (~1e-7) can flip — token "
                         "equality alone is not a deterministic gate.  "
                         "0 = require token-for-token match.")
    args = ap.parse_args(argv)
    if args.gen_tokens > 0 and 16 + args.gen_tokens > args.seq:
        ap.error(f"--gen-tokens {args.gen_tokens} + 16-token prompts "
                 f"exceeds --seq {args.seq} (the position range both "
                 f"models are configured for)")

    import numpy as np

    hf_model = _build_hf(args.family, args.seq, hidden=args.hidden,
                         layers=args.layers, vocab=args.vocab)

    rng = np.random.default_rng(0)
    # tokens from a quarter of the vocab: LEARNABLE data (the model
    # shifts mass onto the live tokens, loss falls toward log(vocab/4)),
    # so the `improved` gate checks that training actually trains
    # instead of flipping a coin on uniform noise
    live = max(args.vocab // 4, 2)
    ids = rng.integers(0, live, size=(args.steps, args.batch, args.seq)
                       ).astype(np.int64)
    # heldout set for the downstream-eval leg: same distribution, never
    # trained on (reference also scores the tuned model,
    # benchmarks/accuracy/README.md:103-105; MT-bench itself needs
    # serving infra — heldout perplexity is the self-contained analogue)
    heldout = rng.integers(0, live, size=(4, args.batch, args.seq)
                           ).astype(np.int64)

    ours, ev_ours = converted_curve(
        hf_model, ids, args.steps, args.lr, heldout,
        optimizer=args.optimizer, dtype=args.dtype)
    theirs, ev_torch = torch_curve(
        hf_model, ids, args.steps, args.lr, heldout,
        optimizer=args.optimizer, dtype=args.dtype)

    gen = None
    if args.gen_tokens > 0:
        # prompts drawn from the trained token distribution, never seen
        prompts = heldout[0][:, :16].astype(np.int64)
        match, lp_dev = generation_parity(hf_model, prompts,
                                          args.gen_tokens)
        gen_ok = bool(match == 1.0 or lp_dev <= args.gen_tol)
        gen = {"token_match_frac": round(match, 4),
               "next_logprob_max_dev": round(lp_dev, 5),
               "gen_tokens": args.gen_tokens, "ok": gen_ok}

    devs = [abs(a - b) / max(abs(b), 1e-6) for a, b in zip(ours, theirs)]
    max_dev = max(devs)
    # gate the downstream leg on heldout LOSS deviation (the same scale
    # as --tol); perplexity is exp(loss), so a rel-ppl gate would be
    # ~loss-magnitude-fold stricter than the curve gate next to it
    ev_dev = abs(ev_ours - ev_torch) / max(abs(ev_torch), 1e-6)
    import math
    ppl_ours, ppl_torch = math.exp(ev_ours), math.exp(ev_torch)
    improved = ours[-1] < ours[0]
    ok = bool(max_dev <= args.tol and ev_dev <= args.tol and improved
              and (gen is None or gen["ok"]))
    print(json.dumps({
        "metric": (f"accuracy_parity_{args.family}_{args.optimizer}"
                   f"_{args.dtype}_sft"),
        "ok": ok,
        "max_rel_dev": round(max_dev, 5),
        "tol": args.tol,
        "loss_first": {"torch": round(theirs[0], 5),
                       "torchacc_tpu": round(ours[0], 5)},
        "loss_last": {"torch": round(theirs[-1], 5),
                      "torchacc_tpu": round(ours[-1], 5)},
        "heldout": {"loss_torch": round(ev_torch, 5),
                    "loss_torchacc_tpu": round(ev_ours, 5),
                    "loss_rel_dev": round(ev_dev, 5),
                    "ppl_torch": round(ppl_torch, 4),
                    "ppl_torchacc_tpu": round(ppl_ours, 4)},
        "generation": gen,
        "steps": args.steps,
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
