"""One-off: does unrolling the layer loop beat scan-over-layers on the
real chip?  The 56% profile shows ~49 ms of the 229 ms step in saved-
residual stacking (dynamic-update-slice fusions) that only exist because
nn.scan stacks each layer's saved residuals into [L, ...] buffers;
an unrolled loop keeps residuals as separate buffers.
"""
import json, subprocess, sys, os
os.makedirs(os.path.expanduser("~/.cache/torchacc_tpu_bench"), exist_ok=True)

RUN = """
import json, os, time
import jax
jax.config.update("jax_compilation_cache_dir", os.path.expanduser("~/.cache/torchacc_tpu_bench"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
import jax.numpy as jnp, numpy as np, optax
import torchacc_tpu as ta
from torchacc_tpu.models import get_preset
from torchacc_tpu.train import accelerate
pol, batch, scan = {pol!r}, {batch}, {scan}
seq = 2048
mc = get_preset("llama-tiny", hidden_size=1024, num_layers=24, num_heads=8,
                num_kv_heads=8, intermediate_size=4096, vocab_size=32000,
                max_seq_len=seq, scan_layers=scan)
cfg = ta.Config()
cfg.memory.gc = pol != "none"
if pol != "none":
    cfg.memory.gc_policy = pol
trainer, _ = accelerate(mc, None, cfg, optimizer=optax.adamw(1e-4))
trainer.init()
rng = np.random.default_rng(0)
bd = {{"input_ids": jnp.asarray(rng.integers(0, 32000, size=(batch, seq)), jnp.int32)}}
t_c0 = time.perf_counter()
for _ in range(3):
    m = trainer.step(bd)
float(m["loss"])
compile_s = time.perf_counter() - t_c0
iters = 10
t0 = time.perf_counter()
for _ in range(iters):
    m = trainer.step(bd)
float(m["loss"])
dt = (time.perf_counter() - t0) / iters
n = mc.num_params()
fpt = 6.0 * n + 6.0 * mc.num_layers * mc.hidden_size * seq
mfu = fpt * batch * seq / dt / 197e12
print(json.dumps({{"pol": pol, "batch": batch, "scan": scan,
                   "step_s": round(dt,4), "mfu": round(mfu,4),
                   "compile_s": round(compile_s,1),
                   "tok_s": round(batch*seq/dt,1)}}))
"""

GRID = [
    ("save_attn_mlp", 4, True),    # baseline: 0.229 s / 56.5%
    ("save_attn_mlp", 4, False),
    ("save_attn", 4, False),
    ("none", 4, False),
    ("save_attn", 8, False),
]
for pol, batch, scan in GRID:
    try:
        r = subprocess.run(
            [sys.executable, "-c", RUN.format(pol=pol, batch=batch, scan=scan)],
            capture_output=True, text=True, timeout=1500)
    except subprocess.TimeoutExpired:
        print(json.dumps({"pol": pol, "batch": batch, "scan": scan,
                          "error": "timeout (1500s)"}), flush=True)
        continue
    out = [l for l in r.stdout.splitlines() if l.startswith("{")]
    if out:
        print(out[-1], flush=True)
    else:
        err = (r.stderr or "")
        oom = "OOM" if "Ran out of memory" in err else err[-200:].replace("\n", " | ")
        print(json.dumps({"pol": pol, "batch": batch, "scan": scan,
                          "error": oom}), flush=True)
