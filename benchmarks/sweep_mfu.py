"""Exploratory MFU sweep on the real chip (not the driver bench)."""
import json, subprocess, sys, time, os
os.makedirs(os.path.expanduser("~/.cache/torchacc_tpu_bench"), exist_ok=True)

RUN = """
import json, os, time, sys
import jax
jax.config.update("jax_compilation_cache_dir", os.path.expanduser("~/.cache/torchacc_tpu_bench"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
import jax.numpy as jnp, numpy as np, optax
import torchacc_tpu as ta
from torchacc_tpu.models import get_preset
from torchacc_tpu.train import accelerate
pol, batch = {pol!r}, {batch}
seq = 2048
mc = get_preset("llama-tiny", hidden_size=1024, num_layers=24, num_heads=16,
                num_kv_heads=16, intermediate_size=4096, vocab_size=32000, max_seq_len=seq)
cfg = ta.Config()
cfg.memory.gc = pol != "none"
if pol != "none":
    cfg.memory.gc_policy = pol
trainer, _ = accelerate(mc, None, cfg, optimizer=optax.adamw(1e-4))
trainer.init()
rng = np.random.default_rng(0)
bd = {{"input_ids": jnp.asarray(rng.integers(0, 32000, size=(batch, seq)), jnp.int32)}}
for _ in range(3):
    m = trainer.step(bd)
float(m["loss"])
iters = 10
t0 = time.perf_counter()
for _ in range(iters):
    m = trainer.step(bd)
float(m["loss"])
dt = (time.perf_counter() - t0) / iters
n = mc.num_params()
fpt = 6.0 * n + 6.0 * mc.num_layers * mc.hidden_size * seq
mfu = fpt * batch * seq / dt / 197e12
print(json.dumps({{"pol": pol, "batch": batch, "step_s": round(dt,4), "mfu": round(mfu,4),
                   "tok_s": round(batch*seq/dt,1)}}))
"""

for pol, batch in [("save_attn", 4), ("save_attn_mlp", 4), ("save_attn", 8),
                   ("save_attn_mlp", 8), ("save_attn", 16)]:
    try:
        r = subprocess.run(
            [sys.executable, "-c", RUN.format(pol=pol, batch=batch)],
            capture_output=True, text=True, timeout=900)
    except subprocess.TimeoutExpired:
        print(json.dumps({"pol": pol, "batch": batch,
                          "error": "timeout (900s)"}), flush=True)
        continue
    out = [l for l in r.stdout.splitlines() if l.startswith("{")]
    if out:
        print(out[-1], flush=True)
    else:
        err = (r.stderr or "")
        oom = "OOM" if "Ran out of memory" in err else err[-200:].replace("\n"," | ")
        print(json.dumps({"pol": pol, "batch": batch, "error": oom}), flush=True)
