"""Unified telemetry plane tests (torchacc_tpu/obs/,
docs/observability.md).

The contracts under test:

- spans nest with thread-local parent propagation, live in a BOUNDED
  buffer, export as valid Chrome-trace JSON, and are exact no-ops while
  disabled;
- histograms bucket/merge/percentile correctly and export Prometheus
  cumulative-``le`` text;
- the HTTP endpoint serves parseable ``/metrics`` (counters + gauges +
  histograms) and a ``/healthz`` that flips ok -> degraded -> unhealthy
  (503) with the registered providers;
- the flight recorder keeps a bounded step ring with counter deltas and
  every typed-error fit exit (and preemption) dumps a strict-JSON
  postmortem bundle naming the failing step;
- with ``obs`` enabled the fit trajectory is BITWISE identical to the
  disabled run, and trainer/tiered-checkpoint/serving spans land in one
  exportable trace;
- the MetricsWriter satellites: non-finite floats serialise as null
  (counted), and a non-numeric value raises before EITHER sink wrote.
"""

import json
import math
import os
import threading
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import torchacc_tpu as ta
from torchacc_tpu.errors import AnomalyError, SDCError
from torchacc_tpu.models import TransformerLM, get_preset
from torchacc_tpu.obs import flight, hist, server, tracing
from torchacc_tpu.resilience import ChaosLoader, ChaosPlan, chaos_loss
from torchacc_tpu.train import accelerate
from torchacc_tpu.utils.metrics import MetricsWriter, counters

pytestmark = pytest.mark.obs

CHAOS_SEED = int(os.environ.get("CHAOS_SEED", "0"))


@pytest.fixture(autouse=True)
def _clean_obs_state():
    """Every obs seam is process-global (by design, like counters) —
    scrub them around each test."""
    counters.reset()
    tracing.configure(enabled=False)
    tracing.clear()
    hist.configure(enabled=False)
    hist.reset()
    server.stop()
    server.clear_registries()
    flight.recorder.clear()
    yield
    counters.reset()
    tracing.configure(enabled=False)
    tracing.clear()
    hist.configure(enabled=False)
    hist.reset()
    server.stop()
    server.clear_registries()
    flight.recorder.clear()


def _model():
    return get_preset("llama-tiny", vocab_size=64, hidden_size=32,
                      num_layers=1, num_heads=2, num_kv_heads=2,
                      intermediate_size=64, dtype=jnp.float32)


def _batches(n, seed=None):
    rng = np.random.default_rng(CHAOS_SEED if seed is None else seed)
    return [{"input_ids": rng.integers(0, 64, size=(8, 16)).astype(np.int32)}
            for _ in range(n)]


def _trainer(obs=None, loss=None, **res_kwargs):
    import optax
    cfg = ta.Config(resilience=ta.ResilienceConfig(**res_kwargs),
                    obs=obs or ta.ObsConfig())
    tr, _ = accelerate(_model(), None, cfg, optimizer=optax.adam(1e-3),
                       loss=loss)
    return tr


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.status, r.read().decode()


def _parse_prometheus(text):
    """Minimal Prometheus text parser: {name: {labels_str: value}} —
    raises on any malformed sample line, so parsing IS the validity
    check."""
    out = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name_labels, value = line.rsplit(" ", 1)
        if "{" in name_labels:
            name, rest = name_labels.split("{", 1)
            assert rest.endswith("}"), line
            labels = rest[:-1]
        else:
            name, labels = name_labels, ""
        out.setdefault(name, {})[labels] = float(value)
    return out


# -- tracing ------------------------------------------------------------------

def test_span_disabled_is_noop_singleton():
    s1 = tracing.span("x", a=1)
    s2 = tracing.span("y")
    assert s1 is s2                        # shared null object
    with s1:
        s1.set(b=2)                        # no-op, no error
    assert tracing.snapshot() == []


def test_span_nesting_and_parent_ids():
    tracing.configure(enabled=True)
    with tracing.span("outer", step=3):
        with tracing.span("inner"):
            pass
        with tracing.span("inner2"):
            pass
    spans = tracing.snapshot()
    assert [s["name"] for s in spans] == ["inner", "inner2", "outer"]
    outer = spans[2]
    assert outer["parent"] is None
    assert spans[0]["parent"] == outer["id"]
    assert spans[1]["parent"] == outer["id"]
    assert outer["attrs"] == {"step": 3}
    assert all(s["dur"] >= 0 for s in spans)


def test_span_thread_local_stacks_do_not_cross():
    tracing.configure(enabled=True)
    ready = threading.Event()
    release = threading.Event()

    def worker():
        with tracing.span("worker_span"):
            ready.set()
            release.wait(5)

    t = threading.Thread(target=worker)
    with tracing.span("main_span"):
        t.start()
        ready.wait(5)
        with tracing.span("main_child"):
            pass
    release.set()
    t.join(5)
    by_name = {s["name"]: s for s in tracing.snapshot()}
    # the worker's open span is NOT the parent of main's child (and
    # vice versa): stacks are per-thread
    assert by_name["main_child"]["parent"] == by_name["main_span"]["id"]
    assert by_name["worker_span"]["parent"] is None


def test_span_buffer_bounded():
    tracing.configure(enabled=True, buffer_size=16)
    for i in range(100):
        with tracing.span("s", i=i):
            pass
    spans = tracing.snapshot()
    assert len(spans) == 16
    assert spans[-1]["attrs"]["i"] == 99   # newest kept
    tracing.configure(buffer_size=4096)


def test_record_span_explicit_interval():
    tracing.configure(enabled=True)
    import time
    now = time.perf_counter()
    tracing.record_span("serve/queue", now - 0.25, now, sid=7)
    s = tracing.snapshot()[-1]
    assert s["name"] == "serve/queue"
    assert s["dur"] == pytest.approx(0.25)
    assert s["attrs"]["sid"] == 7


def test_chrome_trace_export_valid(tmp_path):
    tracing.configure(enabled=True)
    with tracing.span("train/dispatch", step=1):
        pass
    path = str(tmp_path / "trace.json")
    doc = tracing.export_chrome_trace(path)
    loaded = json.load(open(path))       # file round-trips as JSON
    assert loaded["traceEvents"]
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert len(xs) == 1
    e = xs[0]
    assert e["name"] == "train/dispatch" and e["cat"] == "train"
    assert e["dur"] >= 0 and e["ts"] > 0   # microseconds, wall anchor
    assert e["args"]["step"] == 1 and "span_id" in e["args"]
    # metadata rows name the process/threads for the viewer
    assert any(m["name"] == "thread_name" for m in doc["traceEvents"]
               if m["ph"] == "M")


def test_span_set_attaches_attrs():
    tracing.configure(enabled=True)
    with tracing.span("serve/admit", sid=1) as sp:
        sp.set(admitted=True)
    assert tracing.snapshot()[-1]["attrs"] == {"sid": 1, "admitted": True}


# -- histograms ---------------------------------------------------------------

def test_hist_percentiles_and_snapshot():
    h = hist.Histogram()
    for v in range(1, 101):
        h.observe(float(v))
    snap = h.snapshot()
    assert snap["count"] == 100
    assert snap["sum"] == pytest.approx(5050.0)
    # log-bucket resolution: estimates within one bucket ratio (1.5x)
    assert 50 / 1.5 <= snap["p50"] <= 50 * 1.5
    assert 95 / 1.5 <= snap["p95"] <= 95 * 1.5
    assert 99 / 1.5 <= snap["p99"] <= 99 * 1.5
    assert h.percentile(0) >= 0
    assert h.percentile(100) <= 100 * 1.5


def test_hist_empty_and_nan():
    h = hist.Histogram()
    assert h.percentile(50) == 0.0
    h.observe(float("nan"))               # never lands in a bucket
    assert h.count == 0


def test_hist_merge_matches_combined():
    a, b, c = hist.Histogram(), hist.Histogram(), hist.Histogram()
    rng = np.random.default_rng(0)
    xs, ys = rng.uniform(0.1, 50, 200), rng.uniform(10, 5000, 300)
    for x in xs:
        a.observe(x)
        c.observe(x)
    for y in ys:
        b.observe(y)
        c.observe(y)
    a.merge(b)
    assert a.count == c.count == 500
    assert a.counts == c.counts
    assert a.percentile(95) == c.percentile(95)


def test_hist_merge_bounds_mismatch_raises():
    a = hist.Histogram(bounds=[1.0, 2.0])
    b = hist.Histogram(bounds=[1.0, 3.0])
    with pytest.raises(ValueError):
        a.merge(b)


def test_hist_prometheus_lines_cumulative():
    h = hist.Histogram(bounds=[1.0, 10.0, 100.0])
    for v in (0.5, 5.0, 50.0, 500.0):
        h.observe(v)
    lines = h.prometheus_lines("m")
    assert lines[0] == "# TYPE m histogram"
    assert 'm_bucket{le="1"} 1' in lines
    assert 'm_bucket{le="10"} 2' in lines
    assert 'm_bucket{le="100"} 3' in lines
    assert 'm_bucket{le="+Inf"} 4' in lines
    assert "m_count 4" in lines


def test_hist_registry_gated_on_enabled():
    hist.observe("gated", 1.0)            # disabled: nothing records
    assert "gated" not in hist.all_histograms() \
        or hist.get("gated").count == 0
    hist.configure(enabled=True)
    hist.observe("gated", 1.0)
    assert hist.get("gated").count == 1


# -- HTTP server --------------------------------------------------------------

def test_metrics_endpoint_counters_gauges_hists():
    counters.inc("ckpt_retries", 3)
    hist.configure(enabled=True)
    hist.observe("step_time_ms", 12.0)
    server.register_gauge("train_inflight_depth", lambda: 2, help="ring")
    srv = server.start(0)
    code, text = _get(srv.url + "/metrics")
    assert code == 200
    metrics = _parse_prometheus(text)    # parsing IS the format gate
    assert metrics["torchacc_ckpt_retries_total"][""] == 3.0
    assert metrics["torchacc_train_inflight_depth"][""] == 2.0
    assert metrics["torchacc_step_time_ms_count"][""] == 1.0
    assert metrics["torchacc_step_time_ms_bucket"]['le="+Inf"'] == 1.0


def test_metrics_broken_gauge_skipped():
    server.register_gauge("broken", lambda: 1 / 0)
    server.register_gauge("fine", lambda: 5)
    srv = server.start(0)
    code, text = _get(srv.url + "/metrics")
    assert code == 200
    metrics = _parse_prometheus(text)
    assert "torchacc_broken" not in metrics
    assert metrics["torchacc_fine"][""] == 5.0


def test_healthz_ok_degraded_unhealthy():
    srv = server.start(0)
    code, body = _get(srv.url + "/healthz")
    assert code == 200 and json.loads(body)["status"] == "ok"
    server.register_health("a", lambda: ("ok", None))
    server.register_health("b", lambda: ("degraded", "slow"))
    code, body = _get(srv.url + "/healthz")
    h = json.loads(body)
    assert code == 200 and h["status"] == "degraded"
    assert h["checks"]["b"]["reason"] == "slow"
    server.register_health("c", lambda: ("unhealthy", "dead"))
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(srv.url + "/healthz")
    assert ei.value.code == 503
    assert json.loads(ei.value.read())["status"] == "unhealthy"


def test_healthz_raising_provider_degrades_not_500():
    server.register_health("boom", lambda: 1 / 0)
    srv = server.start(0)
    code, body = _get(srv.url + "/healthz")
    assert code == 200
    assert json.loads(body)["status"] == "degraded"


def test_server_singleton_and_stop():
    s1 = server.start(0)
    s2 = server.start(0)
    assert s1 is s2
    server.stop()
    assert server.get() is None
    with pytest.raises(urllib.error.URLError):
        _get(s1.url + "/metrics")


# -- flight recorder ----------------------------------------------------------

def test_flight_ring_bounded_with_counter_deltas():
    flight.recorder.configure(capacity=8)
    for i in range(20):
        counters.inc("resumes")
        flight.recorder.record_step(i, {"loss": float(i)})
    recs = flight.recorder.records()
    assert len(recs) == 8
    assert recs[-1]["step"] == 19
    # each step contributed exactly +1 to the counter — the delta is
    # attributed per step, not cumulative
    assert all(r["counter_delta"] == {"resumes": 1} for r in recs)


def test_flight_dump_strict_json(tmp_path):
    tracing.configure(enabled=True)
    with tracing.span("train/dispatch", step=4):
        pass
    flight.recorder.configure(capacity=8, dump_dir=str(tmp_path))
    flight.recorder.set_context("config", {"seed": 0})
    flight.recorder.record_step(4, {"loss": float("nan"),
                                    "grad_norm": float("inf")})
    err = SDCError("boom", step=4, kind="replica", hosts=[1])
    path = flight.recorder.dump("SDCError", error=err)
    assert os.path.basename(path) == "flight_4.json"
    raw = open(path).read()
    assert "NaN" not in raw and "Infinity" not in raw   # strict JSON
    b = json.loads(raw)
    assert b["step"] == 4 and b["reason"] == "SDCError"
    assert b["error"]["fields"]["hosts"] == [1]
    assert b["records"][0]["record"]["loss"] is None
    assert b["context"]["config"] == {"seed": 0}
    assert any(s["name"] == "train/dispatch" for s in b["spans"])


def test_flight_dump_without_dir_returns_none():
    assert flight.recorder.dump("HangError", step=1) is None


# -- MetricsWriter satellites -------------------------------------------------

class _FakeTB:
    def __init__(self):
        self.calls = []

    def add_scalar(self, k, v, step):
        self.calls.append((k, v, step))

    def flush(self):
        pass

    def close(self):
        pass


def test_metrics_writer_nonfinite_serialises_null(tmp_path):
    mw = MetricsWriter(str(tmp_path), tensorboard=False)
    mw.log(1, {"train/loss": float("nan"), "train/lr": 0.1,
               "train/gn": float("inf")})
    mw.close()
    line = open(os.path.join(str(tmp_path), "metrics.jsonl")).read()
    assert "NaN" not in line and "Infinity" not in line
    rec = json.loads(line)                # strict consumers parse it
    assert rec["train/loss"] is None
    assert rec["train/gn"] is None
    assert rec["train/lr"] == 0.1
    assert counters.get("metrics_nonfinite_values") == 2


def test_metrics_writer_validates_before_either_sink(tmp_path):
    mw = MetricsWriter(str(tmp_path), tensorboard=False)
    tb = _FakeTB()
    mw._tb = tb
    # a non-numeric value anywhere in the dict: NEITHER sink may have
    # written anything for this record (the old code wrote TB scalars
    # mid-validation and left the sinks inconsistent)
    with pytest.raises((TypeError, ValueError)):
        mw.log(1, {"a": 1.0, "b": "not-a-number", "c": 2.0})
    assert tb.calls == []
    mw.log(2, {"a": 3.0})
    mw.close()
    lines = open(os.path.join(str(tmp_path), "metrics.jsonl")).readlines()
    assert len(lines) == 1                # only the valid record landed
    assert json.loads(lines[0])["step"] == 2
    assert tb.calls == [("a", 3.0, 2)]


def test_metrics_writer_tb_gets_raw_nonfinite(tmp_path):
    mw = MetricsWriter(str(tmp_path), tensorboard=False)
    tb = _FakeTB()
    mw._tb = tb
    mw.log(3, {"x": float("nan")})
    mw.close()
    (k, v, step), = tb.calls
    assert k == "x" and math.isnan(v) and step == 3


# -- trainer e2e --------------------------------------------------------------

def test_fit_trajectory_bitwise_identical_obs_on_off(tmp_path):
    def run(obs_on, sub):
        counters.reset()
        tr = _trainer(obs=ta.ObsConfig(enabled=obs_on,
                                       flight_dir=str(tmp_path / sub)))
        hist_ = tr.fit(_batches(6), max_steps=6, log_every=1,
                       metrics_dir=str(tmp_path / sub))
        params = [np.asarray(x) for x in
                  jax.device_get(jax.tree.leaves(tr.state.params))]
        return [r["loss"] for r in hist_], params

    l_off, p_off = run(False, "off")
    l_on, p_on = run(True, "on")
    assert l_off == l_on
    for a, b in zip(p_off, p_on):
        np.testing.assert_array_equal(a, b)


def test_fit_emits_spans_hists_flight(tmp_path):
    tr = _trainer(obs=ta.ObsConfig(enabled=True))
    tr.fit(_batches(5), max_steps=5, log_every=1,
           metrics_dir=str(tmp_path))
    names = {s["name"] for s in tracing.snapshot()}
    assert {"train/dispatch", "train/resolve"} <= names
    assert hist.get("step_time_ms").count == 5
    assert hist.get("host_blocked_ms").count == 5
    assert len(flight.recorder.records()) == 5
    # session hygiene: gauges/health unregistered after fit returns
    assert server.health()["checks"] == {}
    code = server.prometheus_text()
    assert "torchacc_train_inflight_depth" not in code


def test_fit_save_and_tiered_spans(tmp_path):
    tracingnames = lambda: {s["name"] for s in tracing.snapshot()}  # noqa: E731
    tr = _trainer(obs=ta.ObsConfig(enabled=True),
                  tiered_checkpointing=True)
    tr.fit(_batches(4), max_steps=4, log_every=0,
           checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=2)
    names = tracingnames()
    assert "train/save" in names
    assert "ckpt/tier0_fetch" in names
    assert "ckpt/tier1_commit" in names


def test_fit_anomaly_abort_writes_flight_bundle(tmp_path):
    md = str(tmp_path / "run")
    tr = _trainer(obs=ta.ObsConfig(enabled=True), loss=chaos_loss(),
                  nan_guard=True, max_consecutive_anomalies=2)
    with pytest.raises(AnomalyError):
        tr.fit(ChaosLoader(_batches(8), nan_loss_steps={2, 3, 4, 5}),
               max_steps=8, log_every=1, metrics_dir=md)
    path = flight.recorder.last_dump_path
    assert path is not None and path.startswith(md)
    b = json.load(open(path))
    assert b["reason"] == "AnomalyError"
    assert b["error"]["fields"]["consecutive"] == 2
    assert b["context"]["config"]["resilience"]["nan_guard"] is True
    assert b["counters"]["anomalies_skipped"] == 2


def test_fit_sdc_abort_bundle_names_flagged_step(tmp_path):
    k = 1 + CHAOS_SEED % 2
    md = str(tmp_path / "run")
    tr = _trainer(obs=ta.ObsConfig(enabled=True),
                  sdc_recompute_interval_steps=1)
    with pytest.raises(SDCError) as ei:
        with ChaosPlan(seed=CHAOS_SEED).flip_bits(host=0, at=k,
                                                  where="recompute"):
            tr.fit(_batches(4), max_steps=4, log_every=1,
                   metrics_dir=md)
    b = json.load(open(flight.recorder.last_dump_path))
    assert b["step"] == ei.value.step == k
    assert b["error"]["type"] == "SDCError"
    assert b["error"]["fields"]["hosts"] == [0]


def test_fit_preemption_writes_bundle(tmp_path):
    ck = str(tmp_path / "ck")
    tr = _trainer(obs=ta.ObsConfig(enabled=True))
    tr.fit(ChaosLoader(_batches(8), preempt_after_step=3), max_steps=8,
           log_every=1, checkpoint_dir=ck, checkpoint_every=100)
    path = flight.recorder.last_dump_path
    assert path is not None
    b = json.load(open(path))
    assert b["reason"] == "preemption"
    assert b["step"] == 4                 # the emergency-saved step


def test_fit_health_providers_live_during_run(tmp_path):
    """While a fit is running, /healthz answers from the trainer's
    watchdog/guard/sdc state; a stalled heartbeat degrades it."""
    seen = []

    class Probe:
        def __iter__(self):
            for i, b in enumerate(_batches(4)):
                if i == 2:
                    seen.append(server.health())
                yield b

    tr = _trainer(obs=ta.ObsConfig(enabled=True,
                                   health_degraded_heartbeat_s=60.0),
                  step_deadline_s=30.0)
    tr.fit(Probe(), max_steps=4, log_every=0,
           metrics_dir=str(tmp_path))
    assert seen and seen[0]["status"] == "ok"
    assert set(seen[0]["checks"]) == {"watchdog_heartbeat",
                                      "guard_anomalies", "sdc"}
    # after fit: providers deregistered
    assert server.health()["checks"] == {}


def test_healthz_degrades_under_stalled_heartbeat():
    """Drive the heartbeat provider directly with a fake-clock watchdog
    — the exact signal the obs-smoke gate trips with a real injected
    hang."""
    from torchacc_tpu.obs.runtime import FitObs
    from torchacc_tpu.resilience.watchdog import Watchdog
    now = [0.0]
    tr = _trainer(obs=ta.ObsConfig(enabled=True,
                                   health_degraded_heartbeat_s=5.0,
                                   health_unhealthy_heartbeat_s=50.0))
    fo = FitObs(tr, tr.config.obs, run_dir=None)
    try:
        wd = Watchdog(poll_interval_s=None, clock=lambda: now[0])
        tr._watchdog = wd
        assert server.health()["status"] == "ok"
        now[0] = 10.0                      # heartbeat age 10s > 5s
        h = server.health()
        assert h["status"] == "degraded"
        assert "heartbeat" in h["checks"]["watchdog_heartbeat"]["reason"]
        now[0] = 100.0
        assert server.health()["status"] == "unhealthy"
        wd.beat()
        assert server.health()["status"] == "ok"
    finally:
        tr._watchdog = None
        fo.close()


# -- serving e2e --------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny_serve():
    cfg = get_preset(
        "llama-tiny", dtype=jnp.float32, num_layers=2, hidden_size=64,
        num_heads=4, num_kv_heads=2, intermediate_size=128,
        vocab_size=257, max_seq_len=128)
    model = TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def test_serve_engine_obs_gauges_hists_spans(tiny_serve):
    from torchacc_tpu.serve import Request, ServeEngine
    model, params = tiny_serve
    cfg = ta.Config(
        serve=ta.config.ServeConfig(block_size=8, num_blocks=64,
                                    max_slots=4, prefill_chunk=8,
                                    decode_depth=2),
        obs=ta.ObsConfig(enabled=True))
    engine = ServeEngine(model, params, cfg)
    # gauges live while the engine lives
    text = server.prometheus_text()
    m = _parse_prometheus(text)
    assert "torchacc_serve_queue_depth" in m
    assert "torchacc_kv_pool_free_blocks" in m
    assert m["torchacc_kv_pool_free_blocks"][""] == 63.0
    rng = np.random.default_rng(0)
    reqs = [Request(prompt_ids=rng.integers(1, 257, size=n).tolist(),
                    max_new_tokens=4) for n in (5, 9)]
    results = engine.generate(reqs)
    assert all(len(r.tokens) == 4 for r in results)
    assert hist.get("serve_ttft_ms").count == 2
    assert hist.get("serve_token_gap_ms").count == 2 * 3
    names = {s["name"] for s in tracing.snapshot()}
    assert {"serve/queue", "serve/admit", "serve/prefill",
            "serve/decode", "serve/deliver"} <= names
    engine.close()
    # gauges deregistered with the engine
    assert "torchacc_serve_queue_depth" not in server.prometheus_text()


def test_serve_obs_disabled_no_state(tiny_serve):
    from torchacc_tpu.serve import Request, ServeEngine
    model, params = tiny_serve
    cfg = ta.Config(serve=ta.config.ServeConfig(
        block_size=8, num_blocks=64, max_slots=4, prefill_chunk=8))
    engine = ServeEngine(model, params, cfg)
    engine.generate([Request(prompt_ids=[1, 2, 3], max_new_tokens=2)])
    engine.close()
    assert tracing.snapshot() == []
    assert hist.all_histograms() == {} or \
        all(h.count == 0 for h in hist.all_histograms().values())


def test_failed_admission_retries_record_no_spans(tiny_serve):
    """A saturated engine re-attempts its queue head every iteration;
    those failures must not evict useful spans from the bounded ring —
    serve/admit records successful admissions only."""
    from torchacc_tpu.serve import Request, ServeEngine
    model, params = tiny_serve
    cfg = ta.Config(
        serve=ta.config.ServeConfig(block_size=8, num_blocks=64,
                                    max_slots=1, prefill_chunk=8),
        obs=ta.ObsConfig(enabled=True))
    engine = ServeEngine(model, params, cfg)
    rng = np.random.default_rng(0)
    # 3 requests through 1 slot: #2 and #3 retry admission every
    # iteration while the predecessor decodes
    engine.generate([Request(prompt_ids=rng.integers(
        1, 257, size=6).tolist(), max_new_tokens=6) for _ in range(3)])
    engine.close()
    admits = [s for s in tracing.snapshot()
              if s["name"] == "serve/admit"]
    assert len(admits) == 3               # one per SUCCESSFUL admission
    assert all("cached_tokens" in s["attrs"] for s in admits)


def test_flight_ring_resets_when_new_fit_takes_ownership(tmp_path):
    """Fit #2's postmortem must not be dominated by fit #1's records:
    taking flight ownership starts a fresh ring."""
    tr = _trainer(obs=ta.ObsConfig(enabled=True))
    tr.fit(_batches(5), max_steps=5, log_every=1,
           metrics_dir=str(tmp_path / "run1"))
    assert len(flight.recorder.records()) == 5
    md2 = str(tmp_path / "run2")
    tr2 = _trainer(obs=ta.ObsConfig(enabled=True), loss=chaos_loss(),
                   nan_guard=True, max_consecutive_anomalies=2)
    with pytest.raises(AnomalyError):
        tr2.fit(ChaosLoader(_batches(6), nan_loss_steps={1, 2, 3}),
                max_steps=6, log_every=1, metrics_dir=md2)
    b = json.load(open(flight.recorder.last_dump_path))
    # only fit #2's records in the bundle — nothing from fit #1 (the
    # abort raises while RESOLVING step 2, so its record never emits:
    # steps 0 and 1 are the recorded history)
    assert [r["step"] for r in b["records"]] == [0, 1]
    assert b["context"]["run_dir"] == md2


def test_closing_old_engine_keeps_new_engines_gauges(tiny_serve):
    """Last-owner-wins cuts both ways: engine B replaces A's gauge
    registrations, and closing A afterwards must NOT delete B's."""
    from torchacc_tpu.serve import ServeEngine
    model, params = tiny_serve

    def mk():
        cfg = ta.Config(
            serve=ta.config.ServeConfig(block_size=8, num_blocks=64,
                                        max_slots=4, prefill_chunk=8),
            obs=ta.ObsConfig(enabled=True))
        return ServeEngine(model, params, cfg)

    a = mk()
    b = mk()                               # replaces a's registrations
    a.close()
    assert "torchacc_serve_queue_depth" in server.prometheus_text()
    b.close()
    assert "torchacc_serve_queue_depth" not in server.prometheus_text()


def test_flight_dump_dir_not_inherited_across_fits(tmp_path):
    """A fit WITHOUT any run dir must not misfile its postmortem into
    a previous fit's checkpoint dir."""
    ck1 = str(tmp_path / "run1")
    tr = _trainer(obs=ta.ObsConfig(enabled=True))
    tr.fit(_batches(2), max_steps=2, log_every=0, checkpoint_dir=ck1,
           checkpoint_every=100)
    assert flight.recorder.dump_dir == ck1
    tr2 = _trainer(obs=ta.ObsConfig(enabled=True), loss=chaos_loss(),
                   nan_guard=True, max_consecutive_anomalies=1)
    with pytest.raises(AnomalyError):
        tr2.fit(ChaosLoader(_batches(4), nan_loss_steps={0, 1, 2}),
                max_steps=4, log_every=0)   # no dirs at all
    # the bundle was NOT written into run1 (dump_dir honestly None ->
    # warned + skipped)
    assert flight.recorder.dump_dir is None
    assert flight.recorder.last_dump_path is None
    assert not [f for f in os.listdir(ck1)
                if f.startswith("flight_")]


# -- config -------------------------------------------------------------------

def test_obs_config_validation_and_roundtrip():
    with pytest.raises(ta.ConfigError):
        ta.Config(obs=ta.ObsConfig(trace_buffer=2)).validate()
    with pytest.raises(ta.ConfigError):
        ta.Config(obs=ta.ObsConfig(http_port=99999)).validate()
    with pytest.raises(ta.ConfigError):
        ta.Config(obs=ta.ObsConfig(
            health_degraded_heartbeat_s=10.0,
            health_unhealthy_heartbeat_s=5.0)).validate()
    cfg = ta.Config(obs=ta.ObsConfig(enabled=True, http_port=0,
                                     flight_capacity=32))
    d = cfg.to_dict()
    assert d["obs"]["enabled"] is True
    cfg2 = ta.Config.from_dict(d)
    assert cfg2.obs.flight_capacity == 32 and cfg2.obs.http_port == 0
