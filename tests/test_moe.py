"""MoE + expert parallelism tests (beyond the reference — SURVEY.md §2.3
notes TorchAcc has no MoE/EP; BASELINE lists Mixtral as a target)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import torchacc_tpu as ta
from torchacc_tpu.models import get_preset
from torchacc_tpu.train import accelerate


def _moe_model(**kw):
    return get_preset("llama-tiny", vocab_size=128, hidden_size=64,
                      num_layers=2, num_heads=4, num_kv_heads=2,
                      intermediate_size=128, num_experts=4,
                      num_experts_per_tok=2, **kw)


def _batches(n, seed=0):
    rng = np.random.default_rng(seed)
    data = rng.integers(0, 128, size=(4, 32))
    for _ in range(n):
        yield {"input_ids": data[rng.integers(0, 4, size=8)].astype(np.int32)}


def test_moe_forward_and_param_count():
    cfg = _moe_model(dtype=jnp.float32)
    from torchacc_tpu.models import TransformerLM
    model = TransformerLM(cfg)
    ids = jnp.zeros((2, 16), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    logits = model.apply({"params": params}, ids)
    assert logits.shape == (2, 16, 128)
    actual = sum(p.size for p in jax.tree.leaves(params))
    assert actual == cfg.num_params()


def test_expert_parallel_training(devices):
    """ep=4 x dp=2: experts sharded over 'ep', training converges."""
    import optax
    cfg = ta.Config(dist=ta.DistConfig(ep=ta.EPConfig(size=4),
                                       dp=ta.DPConfig(size=2)))
    trainer, loader = accelerate(_moe_model(), _batches(10), cfg,
                                 optimizer=optax.adam(3e-3))
    losses = [float(trainer.step(b)["loss"]) for b in loader]
    assert losses[-1] < losses[0], losses
    # expert weights sharded over ep
    w = trainer.state.params["layers"]["block"]["moe"]["experts/gate"]
    assert "ep" in str(w.sharding.spec), w.sharding.spec


def test_ep_matches_single_device(devices):
    import optax
    batches = list(_batches(4, seed=1))
    cfg_ep = ta.Config(dist=ta.DistConfig(ep=ta.EPConfig(size=4),
                                          dp=ta.DPConfig(size=2)))
    t1, _ = accelerate(_moe_model(), None, cfg_ep, optimizer=optax.adam(1e-3))
    t1.init()
    l1 = [float(t1.step(b)["loss"]) for b in batches]

    cfg_dp = ta.Config(dist=ta.DistConfig(dp=ta.DPConfig(size=8)))
    t2, _ = accelerate(_moe_model(), None, cfg_dp, optimizer=optax.adam(1e-3))
    t2.init()
    l2 = [float(t2.step(b)["loss"]) for b in batches]
    np.testing.assert_allclose(l1, l2, rtol=2e-4)


def test_moe_aux_loss_survives_gc_cnt(devices):
    """The gc_cnt split-scan path must still propagate the sow'd MoE
    load-balance loss (it runs blocks via raw .apply, which would
    silently drop intermediates without explicit handling)."""
    import dataclasses
    from torchacc_tpu.models import TransformerLM
    from torchacc_tpu.train.accelerate import apply_config_to_model

    base_cfg = _moe_model(dtype=jnp.float32)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 128, (2, 16)),
                      jnp.int32)

    def aux_of(mem):
        cfg = ta.Config(memory=mem)
        mc = apply_config_to_model(base_cfg, cfg)
        model = TransformerLM(mc)
        params = model.init(jax.random.PRNGKey(0), ids)["params"]
        _, mut = model.apply({"params": params}, ids,
                             mutable=["intermediates"])
        leaves = [jnp.sum(jnp.asarray(v)) for v in
                  jax.tree.leaves(mut.get("intermediates", {}))]
        assert leaves, "moe_aux_loss missing from intermediates"
        return float(sum(leaves))

    plain = aux_of(ta.MemoryConfig(gc=False))
    split = aux_of(ta.MemoryConfig(gc=True, gc_policy="dots", gc_cnt=1))
    np.testing.assert_allclose(split, plain, rtol=1e-5)


def test_moe_capacity_dispatch_matches_dense():
    """Ample capacity = no drops: the switch-style capacity path is the
    same math as exact dense dispatch (docs/PARITY.md gap: capacity-
    based sparse dispatch)."""
    import dataclasses
    from torchacc_tpu.models import TransformerLM

    dense_cfg = _moe_model(dtype=jnp.float32, param_dtype=jnp.float32)
    cap_cfg = dataclasses.replace(dense_cfg, moe_capacity_factor=4.0)
    ids = jnp.asarray(
        np.random.default_rng(0).integers(0, 128, (2, 16)), jnp.int32)
    params = TransformerLM(dense_cfg).init(jax.random.PRNGKey(0), ids)["params"]
    out_dense = TransformerLM(dense_cfg).apply({"params": params}, ids)
    out_cap = TransformerLM(cap_cfg).apply({"params": params}, ids)
    np.testing.assert_allclose(np.asarray(out_cap), np.asarray(out_dense),
                               atol=1e-4, rtol=1e-4)


@pytest.mark.parametrize("dispatch", ["einsum", "sort"])
def test_moe_capacity_tight_drops_but_trains(devices, dispatch):
    """Tight capacity drops over-capacity tokens (standard switch
    behaviour) yet stays finite, differentiable, and EP-shardable —
    under BOTH dispatch mechanisms."""
    import dataclasses
    import optax
    mc = dataclasses.replace(_moe_model(), moe_capacity_factor=1.0,
                             moe_dispatch=dispatch)
    cfg = ta.Config(dist=ta.DistConfig(ep=ta.EPConfig(size=4),
                                       dp=ta.DPConfig(size=2)))
    trainer, loader = accelerate(mc, _batches(8), cfg,
                                 optimizer=optax.adam(3e-3))
    losses = [float(trainer.step(b)["loss"]) for b in loader]
    assert all(np.isfinite(losses)) and losses[-1] < losses[0], losses


@pytest.fixture(scope="module")
def _dp8_moe_losses(devices):
    """Shared dp=8 baseline for the EP x PP parametrizations."""
    import optax

    batches = list(_batches(4, seed=2))
    cfg_dp = ta.Config(dist=ta.DistConfig(dp=ta.DPConfig(size=8)))
    t2, _ = accelerate(_moe_model(), None, cfg_dp, optimizer=optax.adam(1e-3))
    t2.init()
    return [float(t2.step(b)["loss"]) for b in batches]


@pytest.mark.parametrize("sched", ["gpipe", "1f1b"])
def test_ep_x_pp_composition(devices, sched, _dp8_moe_losses):
    """EP x PP (ep=2 inside the pipeline stages, pp=2, dp=2): experts
    stay ep-sharded while layers stage-shard over pp; losses match dp=8
    (reference has no EP at all — beyond-reference composition)."""
    import optax

    batches = list(_batches(4, seed=2))
    cfg_pp = ta.Config(dist=ta.DistConfig(
        pp=ta.PPConfig(size=2, num_micro_batches=2, schedule=sched),
        ep=ta.EPConfig(size=2),
        dp=ta.DPConfig(size=2)))
    t1, _ = accelerate(_moe_model(), None, cfg_pp, optimizer=optax.adam(1e-3))
    t1.init()
    l1 = [float(t1.step(b)["loss"]) for b in batches]
    w = t1.state.params["layers"]["block"]["moe"]["experts/gate"]
    spec = str(w.sharding.spec)
    assert "ep" in spec and "pp" in spec, spec

    np.testing.assert_allclose(l1, _dp8_moe_losses, rtol=2e-4)


def test_moe_sort_dispatch_matches_einsum():
    """The sort/scatter capacity dispatch (no [n, e, cap] one-hots —
    the Mixtral-scale answer, VERDICT r3 weak-4) is the SAME routing as
    the einsum path: identical outputs and gradients at both ample and
    tight capacity (tight exercises the slot-major drop priority)."""
    import dataclasses

    from torchacc_tpu.models import TransformerLM

    base = _moe_model(dtype=jnp.float32, param_dtype=jnp.float32)
    ids = jnp.asarray(
        np.random.default_rng(0).integers(0, 128, (2, 16)), jnp.int32)
    for cf in (4.0, 1.0):
        cfgs = {
            d: dataclasses.replace(base, moe_capacity_factor=cf,
                                   moe_dispatch=d)
            for d in ("einsum", "sort")
        }
        params = TransformerLM(cfgs["einsum"]).init(
            jax.random.PRNGKey(0), ids)["params"]
        outs, grads = {}, {}
        for d, cfg in cfgs.items():
            def loss(p, cfg=cfg):
                out = TransformerLM(cfg).apply({"params": p}, ids)
                return jnp.sum(out.astype(jnp.float32) ** 2)
            outs[d] = TransformerLM(cfg).apply({"params": params}, ids)
            grads[d] = jax.grad(loss)(params)
        np.testing.assert_allclose(np.asarray(outs["sort"]),
                                   np.asarray(outs["einsum"]),
                                   atol=1e-4, rtol=1e-4, err_msg=f"cf={cf}")
        for (pa, ga), (pb, gb) in zip(
                jax.tree_util.tree_flatten_with_path(grads["sort"])[0],
                jax.tree_util.tree_flatten_with_path(grads["einsum"])[0]):
            np.testing.assert_allclose(
                np.asarray(ga), np.asarray(gb), atol=1e-3, rtol=1e-3,
                err_msg=f"cf={cf} {jax.tree_util.keystr(pa)}")


def test_moe_sort_dispatch_memory_beats_einsum():
    """At Mixtral-ish geometry the einsum path's dispatch one-hots
    dominate temp memory; the sort path must compile to strictly less.
    (PERF.md records the measured numbers.)"""
    import dataclasses
    import math

    from torchacc_tpu.models.moe import MoEMlp

    # big enough that [n, e, cap] (f32) dwarfs everything else:
    # n=4096, e=8, cap=2048 -> 256 MiB for the dispatch tensor alone
    n, h, f, e, k = 4096, 256, 512, 8, 2
    base = dataclasses.replace(
        _moe_model(dtype=jnp.float32, param_dtype=jnp.float32),
        hidden_size=h, num_experts=e, intermediate_size=f,
        moe_capacity_factor=2.0)
    x = jnp.zeros((1, n, h), jnp.float32)
    mems = {}
    for d in ("einsum", "sort"):
        cfg = dataclasses.replace(base, moe_dispatch=d)
        mod = MoEMlp(cfg)
        params = mod.init(jax.random.PRNGKey(0), x)

        def loss(p, cfg=cfg):
            out, _ = MoEMlp(cfg).apply(p, x, mutable=["intermediates"])
            return jnp.sum(out.astype(jnp.float32) ** 2)

        compiled = jax.jit(jax.grad(loss)).lower(params).compile()
        mems[d] = compiled.memory_analysis().temp_size_in_bytes
    cap = max(math.ceil(2.0 * k * n / e), 1)
    onehot_bytes = n * e * cap * 4
    assert mems["sort"] < mems["einsum"], mems
    assert mems["sort"] < onehot_bytes, (mems, onehot_bytes)
