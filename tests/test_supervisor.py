"""Supervisor-daemon tests (torchacc_tpu/supervisor/,
docs/resilience.md "Supervisor").

The contracts under test:

- the declarative policy engine maps every typed error to its
  documented action: SDC/quarantine -> restart excluding the named
  hosts (idempotent — a host quarantined twice is excluded once),
  hang/probe-dead -> restart the same world, preemption ->
  wait-and-resume without consuming restart budget, anything else ->
  bounded jittered crash-loop backoff with terminal give-up;
- backoff growth, cap, and jitter bounds are exact under a seeded RNG
  (no wall clock in the engine — delays are returned, sleeps are
  injected);
- the probe client never declares a worker dead off a single bad
  sample: timeout-bounded requests, in-call jittered retry, and a
  consecutive-failure threshold;
- ``Trainer.fit`` emits the strict-JSON ``exit_disposition`` block
  (error type, flagged step, newest resumable step per tier,
  quarantine delta) on every typed-error exit and preemption — the
  field the policy engine parses instead of scraping logs;
- the daemon loop drives real subprocess workers: clean completion,
  SDC exclusion with elastic shrink, preemption resume, crash-loop
  give-up with a final flight bundle, probe-triggered kill;
- ``ServeEngine`` drains gracefully on preemption: admission stops,
  in-flight decodes finish, unserved request ids are reported.
"""

import json
import os
import socket
import sys
import time
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import torchacc_tpu as ta
from torchacc_tpu.errors import SDCError
from torchacc_tpu.models import TransformerLM, get_preset
from torchacc_tpu.obs import flight, hist, server, tracing
from torchacc_tpu.resilience import ChaosLoader, ChaosPlan
from torchacc_tpu.resilience.preemption import (
    clear_preemption,
    request_preemption,
)
from torchacc_tpu.serve import Request, ServeEngine
from torchacc_tpu.supervisor import (
    ExitDisposition,
    PolicyEngine,
    ProbeClient,
    RestartPolicy,
    Supervisor,
    WorkerHandle,
    WorkerProber,
    WorkerSpec,
    read_exit_disposition,
)
from torchacc_tpu.supervisor.worker import render_argv, valid_steps
from torchacc_tpu.train import accelerate
from torchacc_tpu.utils.metrics import counters

pytestmark = pytest.mark.supervisor

CHAOS_SEED = int(os.environ.get("CHAOS_SEED", "0"))


@pytest.fixture(autouse=True)
def _clean_state():
    counters.reset()
    clear_preemption()
    flight.recorder.clear()
    yield
    counters.reset()
    clear_preemption()
    tracing.configure(enabled=False)
    tracing.clear()
    hist.configure(enabled=False)
    hist.reset()
    server.stop()
    server.clear_registries()
    flight.recorder.clear()


class _SeqRng:
    """Deterministic 'random.Random' stand-in: yields the given
    fractions in order (jitter bounds become exact assertions)."""

    def __init__(self, vals):
        self.vals = list(vals)
        self.i = 0

    def random(self):
        v = self.vals[self.i % len(self.vals)]
        self.i += 1
        return v


def _d(**kw):
    return ExitDisposition(**kw)


def _sdc(hosts, delta=None, step=3):
    return _d(reason="SDCError", error_type="SDCError",
              flagged_step=step, hosts=list(hosts),
              quarantine_delta=list(delta if delta is not None
                                    else hosts))


# -- policy engine ------------------------------------------------------------

def test_policy_sdc_excludes_named_hosts():
    e = PolicyEngine(RestartPolicy(), 4)
    a = e.decide(_sdc([1]), exit_code=1)
    assert a.kind == "restart_excluding" and a.rule == "sdc-exclude"
    assert a.hosts == (1,)
    assert e.world == 3 and e.excluded == {1}


def test_policy_exclusion_idempotent():
    """A host quarantined twice is excluded once: the second SDC abort
    naming only already-excluded hosts falls through to crash-loop
    backoff (the exclusion did not fix it), and the world never
    double-shrinks."""
    e = PolicyEngine(RestartPolicy(backoff_jitter=0.0), 4)
    a1 = e.decide(_sdc([1]), exit_code=1)
    assert a1.kind == "restart_excluding" and e.world == 3
    a2 = e.decide(_sdc([1]), exit_code=1)
    assert a2.kind == "restart"
    assert a2.rule == "sdc-reoccurred-excluded"
    assert a2.hosts == ()
    assert e.world == 3 and e.excluded == {1}


def test_policy_quarantine_delta_excludes_without_error_hosts():
    """The quarantine file is the shared supervisor<->worker contract:
    a delta there excludes even when the error object names nobody
    (e.g. QuarantinedHostError on a pre-loop refusal)."""
    e = PolicyEngine(RestartPolicy(), 4)
    d = _d(reason="QuarantinedHostError",
           error_type="QuarantinedHostError", hosts=[],
           quarantine_delta=[2])
    a = e.decide(d, exit_code=1)
    assert a.kind == "restart_excluding" and a.hosts == (2,)


def test_policy_exclusion_below_min_world_gives_up():
    e = PolicyEngine(RestartPolicy(min_world=2), 2)
    a = e.decide(_sdc([1]), exit_code=1)
    assert a.kind == "give_up" and "min_world" in a.reason


def test_policy_hang_restarts_same_world():
    e = PolicyEngine(RestartPolicy(), 2)
    d = _d(reason="HangError", error_type="HangError", flagged_step=5)
    a = e.decide(d, exit_code=1)
    assert a.kind == "restart" and a.rule == "hang-restart"
    assert e.world == 2


def test_policy_probe_dead_restarts_same_world():
    e = PolicyEngine(RestartPolicy(), 2)
    a = e.decide(None, exit_code=None, probe_verdict="dead")
    assert a.kind == "restart" and a.rule == "probe-dead-restart"


def test_policy_preemption_resumes_without_budget():
    """Preemption-vs-crash disambiguation rides the disposition, not
    the exit code: a preempted worker exits 0 AND leaves a bundle —
    resume, never spend budget."""
    e = PolicyEngine(RestartPolicy(max_restarts=1,
                                   preempt_resume_delay_s=2.5), 1)
    d = _d(reason="preemption", preempted=True)
    for _ in range(5):
        a = e.decide(d, exit_code=0)
        assert a.kind == "resume" and a.rule == "preempt-resume"
        assert a.delay_s == 2.5
    assert e.restarts_used == 0
    # while a genuine crash with the same exit-code-0-impossible shape
    # still burns budget
    a = e.decide(_d(reason="CheckpointError",
                    error_type="CheckpointError"), exit_code=1)
    assert a.kind == "restart" and e.restarts_used == 1


def test_policy_clean_exit_done():
    e = PolicyEngine(RestartPolicy(), 2)
    a = e.decide(None, exit_code=0)
    assert a.kind == "done" and a.rule == "clean-exit"


def test_policy_crash_backoff_growth_and_cap():
    p = RestartPolicy(max_restarts=10, backoff_initial_s=1.0,
                      backoff_multiplier=2.0, backoff_max_s=5.0,
                      backoff_jitter=0.0)
    e = PolicyEngine(p, 1)
    crash = _d(reason="CheckpointError", error_type="CheckpointError")
    delays = [e.decide(crash, exit_code=1).delay_s for _ in range(5)]
    assert delays == [1.0, 2.0, 4.0, 5.0, 5.0]   # capped at max


def test_policy_backoff_jitter_bounds():
    p = RestartPolicy(max_restarts=10, backoff_initial_s=2.0,
                      backoff_multiplier=1.0, backoff_jitter=0.25)
    crash = _d(reason="unknown", error_type=None)
    # rng extremes: 0.0 -> -jitter, 1.0 -> +jitter, 0.5 -> exact base
    e = PolicyEngine(p, 1, rng=_SeqRng([0.0, 1.0, 0.5]))
    d1 = e.decide(crash, exit_code=1).delay_s
    d2 = e.decide(crash, exit_code=1).delay_s
    d3 = e.decide(crash, exit_code=1).delay_s
    assert d1 == pytest.approx(2.0 * 0.75)
    assert d2 == pytest.approx(2.0 * 1.25)
    assert d3 == pytest.approx(2.0)
    # and the documented invariant for ANY rng value
    e2 = PolicyEngine(p, 1, rng=_SeqRng([0.137, 0.86, 0.42]))
    for _ in range(3):
        d = e2.decide(crash, exit_code=1).delay_s
        assert 2.0 * 0.75 <= d <= 2.0 * 1.25


def test_policy_budget_exhaustion_gives_up():
    e = PolicyEngine(RestartPolicy(max_restarts=2, backoff_jitter=0.0), 1)
    crash = _d(reason="boom", error_type=None)
    assert e.decide(crash, exit_code=1).kind == "restart"
    assert e.decide(crash, exit_code=1).kind == "restart"
    a = e.decide(crash, exit_code=1)
    assert a.kind == "give_up" and "budget exhausted" in a.reason
    # terminal: every later failure also gives up
    assert e.decide(crash, exit_code=1).kind == "give_up"


def test_policy_progress_resets_backoff_streak():
    p = RestartPolicy(max_restarts=10, backoff_initial_s=1.0,
                      backoff_multiplier=2.0, backoff_jitter=0.0)
    e = PolicyEngine(p, 1)
    crash = _d(reason="x", error_type=None)
    assert e.decide(crash, exit_code=1).delay_s == 1.0
    assert e.decide(crash, exit_code=1).delay_s == 2.0
    e.note_progress()                 # a new durable step landed
    assert e.decide(crash, exit_code=1).delay_s == 1.0


def test_policy_supervisor_kill_never_reads_as_preemption():
    """The daemon's OWN SIGTERM makes workers write preemption bundles;
    with a probe verdict present, those must route to the hang rule and
    consume budget — never a budget-free resume loop."""
    e = PolicyEngine(RestartPolicy(max_restarts=2), 1)
    d = _d(reason="preemption", preempted=True)
    a = e.decide(d, exit_code=None, probe_verdict="dead")
    assert a.kind == "restart" and a.rule == "probe-dead-restart"
    assert e.restarts_used == 1
    # without the probe verdict the same bundle is a genuine eviction
    a2 = e.decide(d, exit_code=0)
    assert a2.kind == "resume" and e.restarts_used == 1


# -- exit disposition ---------------------------------------------------------

def test_exit_disposition_from_bundle_roundtrip(tmp_path):
    d = {"reason": "SDCError", "error_type": "SDCError",
         "flagged_step": 7, "hosts": [1, 2], "quarantine_delta": [2],
         "quarantine": {"2": {"step": 7}},
         "resumable": {"tier0": 6, "tier1": 4, "tier2": None},
         "preempted": False, "process_index": 0, "world_size": 4}
    parsed = ExitDisposition.from_bundle({"exit_disposition": d})
    assert parsed.error_type == "SDCError"
    assert parsed.hosts == [1, 2]
    assert parsed.quarantine_delta == [2]
    assert parsed.newest_resumable() == 6
    assert ExitDisposition.from_bundle({"reason": "x"}) is None


def _model():
    return get_preset("llama-tiny", vocab_size=64, hidden_size=32,
                      num_layers=1, num_heads=2, num_kv_heads=2,
                      intermediate_size=64, dtype=jnp.float32)


def _batches(n, seed=None):
    rng = np.random.default_rng(CHAOS_SEED if seed is None else seed)
    return [{"input_ids": rng.integers(0, 64, size=(8, 16)).astype(np.int32)}
            for _ in range(n)]


def _trainer(**res_kwargs):
    import optax
    cfg = ta.Config(resilience=ta.ResilienceConfig(**res_kwargs),
                    obs=ta.ObsConfig(enabled=True))
    tr, _ = accelerate(_model(), None, cfg, optimizer=optax.adam(1e-3))
    return tr


def test_fit_sdc_abort_emits_exit_disposition(tmp_path):
    """The satellite contract: the bundle's exit_disposition names the
    error type, the flagged step, the newest resumable step per tier,
    and the quarantine delta — machine-parseable by the policy
    engine's reader, end to end."""
    ck = str(tmp_path / "run")
    tr = _trainer(sdc_recompute_interval_steps=1)
    since = time.time()
    with pytest.raises(SDCError):
        with ChaosPlan(seed=CHAOS_SEED).flip_bits(host=0, at=3,
                                                  where="recompute"):
            tr.fit(_batches(6), max_steps=6, log_every=1,
                   checkpoint_dir=ck, checkpoint_every=2)
    b = json.load(open(flight.recorder.last_dump_path))
    d = b["exit_disposition"]
    assert d["reason"] == "SDCError"
    assert d["error_type"] == "SDCError"
    assert d["flagged_step"] == 3
    assert d["hosts"] == [0]
    assert d["quarantine_delta"] == [0]
    assert d["resumable"]["tier1"] == 2      # newest durable < flagged
    assert d["resumable"]["tier0"] is None   # tiered off
    assert d["preempted"] is False
    # the supervisor-side reader finds and parses the same bundle
    parsed = read_exit_disposition(ck, since)
    assert parsed is not None and parsed.error_type == "SDCError"
    assert parsed.flagged_step == 3 and parsed.newest_resumable() == 2


def test_fit_preemption_emits_exit_disposition(tmp_path):
    ck = str(tmp_path / "ck")
    tr = _trainer()
    since = time.time()
    tr.fit(ChaosLoader(_batches(8), preempt_after_step=3), max_steps=8,
           log_every=1, checkpoint_dir=ck, checkpoint_every=100)
    b = json.load(open(flight.recorder.last_dump_path))
    d = b["exit_disposition"]
    assert d["reason"] == "preemption" and d["preempted"] is True
    assert d["error_type"] is None
    assert d["flagged_step"] == 4            # the emergency-saved step
    assert d["resumable"]["tier1"] == 4      # ... which IS resumable
    parsed = read_exit_disposition(ck, since)
    assert parsed is not None and parsed.preempted
    # the policy engine disambiguates preemption from crash
    a = PolicyEngine(RestartPolicy(), 1).decide(parsed, exit_code=0)
    assert a.kind == "resume"


# -- probe client -------------------------------------------------------------

def test_probe_healthz_against_live_server():
    srv = server.start(port=0)
    c = ProbeClient(srv.url, timeout_s=5.0, retries=0)
    r = c.healthz()
    assert r.status == "ok" and r.reachable
    assert r.pid == os.getpid()              # restart-identity field
    server.register_health("x", lambda: ("degraded", "busy"))
    assert c.healthz().status == "degraded"
    server.register_health("y", lambda: ("unhealthy", "dead device"))
    assert c.healthz().status == "unhealthy"   # HTTP 503 is an answer
    counters.inc("supervisor_restarts", 3)
    assert c.counter("supervisor_restarts") == 3.0


def test_probe_unreachable_threshold_and_recovery():
    with socket.socket() as s:                 # a port nobody serves
        s.bind(("127.0.0.1", 0))
        dead_port = s.getsockname()[1]
    c = ProbeClient(f"http://127.0.0.1:{dead_port}", timeout_s=0.2,
                    retries=0, sleep=lambda _: None)
    pr = WorkerProber(c, unreachable_threshold=3)
    assert pr.observe().status == "unreachable"
    assert pr.verdict() == "alive"             # 1 sample is noise
    pr.observe()
    assert pr.verdict() == "alive"
    pr.observe()
    assert pr.verdict() == "dead"              # 3 consecutive = corpse
    # recovery resets the streak
    srv = server.start(port=0)
    pr.client = ProbeClient(srv.url, timeout_s=5.0, retries=0)
    pr.observe()
    assert pr.verdict() == "alive"
    assert pr.consecutive_unreachable == 0


def test_probe_unhealthy_threshold_degraded_stays_alive():
    srv = server.start(port=0)
    state = {"s": "degraded"}
    server.register_health("w", lambda: (state["s"], "r"))
    pr = WorkerProber(ProbeClient(srv.url, timeout_s=5.0, retries=0),
                      unhealthy_threshold=2)
    # degraded is NOT death — a GC pause/busy scrape must never kill
    for _ in range(5):
        pr.observe()
        assert pr.verdict() == "alive"
    state["s"] = "unhealthy"
    pr.observe()
    assert pr.verdict() == "alive"
    pr.observe()
    assert pr.verdict() == "unhealthy"


def test_probe_ever_reachable_flag():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        dead_port = s.getsockname()[1]
    pr = WorkerProber(ProbeClient(f"http://127.0.0.1:{dead_port}",
                                  timeout_s=0.2, retries=0,
                                  sleep=lambda _: None))
    pr.observe()
    assert pr.ever_reachable is False     # never answered yet
    srv = server.start(port=0)
    pr.client = ProbeClient(srv.url, timeout_s=5.0, retries=0)
    pr.observe()
    assert pr.ever_reachable is True


def test_probe_retry_backoff_jitter_bounds():
    slept = []
    c = ProbeClient("http://127.0.0.1:1", timeout_s=0.05, retries=3,
                    backoff_s=0.1, backoff_multiplier=2.0,
                    max_backoff_s=0.3, jitter=0.5,
                    rng=_SeqRng([0.0, 1.0, 0.5]),
                    sleep=slept.append)
    r = c.healthz()
    assert r.status == "unreachable"
    assert len(slept) == 3                   # retries, no sleep after last
    assert slept[0] == pytest.approx(0.1 * 0.5)    # rng 0.0 -> -50%
    assert slept[1] == pytest.approx(0.2 * 1.5)    # rng 1.0 -> +50%
    assert slept[2] == pytest.approx(0.3)          # capped, rng 0.5
    for d in slept:
        assert 0.0 <= d <= 0.3 * 1.5


# -- worker handle / disposition reader ---------------------------------------

def test_worker_handle_exit_code_and_log(tmp_path):
    h = WorkerHandle(0, [sys.executable, "-c",
                         "print('hello'); raise SystemExit(3)"],
                     log_path=str(tmp_path / "w.log"))
    h.start()
    assert h.wait(30.0) == 3
    h.close()
    assert "hello" in h.tail()


def test_worker_handle_terminate_escalates_to_kill(tmp_path):
    h = WorkerHandle(0, [sys.executable, "-c",
                         "import signal, time\n"
                         "signal.signal(signal.SIGTERM, signal.SIG_IGN)\n"
                         "print('armed', flush=True)\n"
                         "time.sleep(120)"],
                     log_path=str(tmp_path / "w.log"))
    h.start()
    deadline = time.time() + 30
    while "armed" not in h.tail() and time.time() < deadline:
        time.sleep(0.05)
    rc = h.terminate(grace_s=0.3)
    assert rc is not None and rc != 0        # SIGKILL'd
    assert not h.running()
    h.close()


def test_read_exit_disposition_newest_since(tmp_path):
    d = str(tmp_path)
    old = {"exit_disposition": {"reason": "HangError",
                                "error_type": "HangError"}}
    new = {"exit_disposition": {"reason": "SDCError",
                                "error_type": "SDCError", "hosts": [1]}}
    json.dump(old, open(os.path.join(d, "flight_2.json"), "w"))
    os.utime(os.path.join(d, "flight_2.json"), (1.0, 1.0))
    since = time.time() - 5
    json.dump(new, open(os.path.join(d, "flight_5.json"), "w"))
    got = read_exit_disposition(d, since)
    assert got is not None and got.error_type == "SDCError"
    # nothing newer than `since` -> None (stale bundles never re-fire)
    assert read_exit_disposition(d, time.time() + 60) is None
    # a bundle without the block is skipped
    json.dump({"reason": "x"},
              open(os.path.join(d, "flight_9.json"), "w"))
    assert read_exit_disposition(d, since).error_type == "SDCError"


def test_read_exit_disposition_error_outranks_newer_preemption(tmp_path):
    """When one worker aborts with a typed error and its SIGTERMed
    peers write NEWER preemption bundles, the error decides — a
    failure must never be misread as a scheduler eviction."""
    d = str(tmp_path)
    since = time.time() - 5
    json.dump({"exit_disposition": {"reason": "SDCError",
                                    "error_type": "SDCError",
                                    "hosts": [1]}},
              open(os.path.join(d, "flight_3.json"), "w"))
    time.sleep(0.02)
    json.dump({"exit_disposition": {"reason": "preemption",
                                    "preempted": True}},
              open(os.path.join(d, "flight_4.json"), "w"))
    got = read_exit_disposition(d, since)
    assert got.error_type == "SDCError"
    # with only preemption bundles, preemption is the verdict
    os.remove(os.path.join(d, "flight_3.json"))
    assert read_exit_disposition(d, since).preempted is True


def test_probe_pid_mismatch_is_stale_endpoint():
    srv = server.start(port=0)
    pr = WorkerProber(ProbeClient(srv.url, timeout_s=5.0, retries=0),
                      unreachable_threshold=2,
                      expect_pid=os.getpid() + 1)   # not our pid
    r = pr.observe()
    assert r.status == "unreachable" and "stale endpoint" in r.error
    assert pr.ever_reachable is False
    pr.observe()
    assert pr.verdict() == "dead"
    # matching pid is this worker answering
    pr2 = WorkerProber(ProbeClient(srv.url, timeout_s=5.0, retries=0),
                       expect_pid=os.getpid())
    assert pr2.observe().status == "ok"


def test_supervisor_sdc_reoccurrence_counts_as_crash_restart(tmp_path):
    sup = Supervisor(_spec(tmp_path, "raise SystemExit(0)"),
                     RestartPolicy())
    from torchacc_tpu.supervisor import Action
    sup._account(Action("restart", "sdc-reoccurred-excluded"))
    assert counters.get("supervisor_crash_restarts") == 1
    assert counters.get("supervisor_restarts") == 1


def test_render_argv_unknown_placeholder_raises():
    assert render_argv(["a", "{host}"], {"host": 2}) == ["a", "2"]
    with pytest.raises(ValueError):
        render_argv(["{wrold}"], {"world": 2})


def test_valid_steps_matches_commit_marker_rule(tmp_path):
    os.makedirs(tmp_path / "2")
    os.makedirs(tmp_path / "4")
    open(tmp_path / "2" / "_MANIFEST", "w").write("{}")
    assert valid_steps(str(tmp_path)) == [2]   # 4 has no marker


# -- the daemon loop (real subprocess workers, no jax) ------------------------

def _spec(tmp_path, script, world=1, **kw):
    """Workers are `python -c script` with argv [incarnation, world,
    run_dir, host] — tiny, jax-free, millisecond-fast."""
    kw.setdefault("exit_grace_s", 1.0)
    kw.setdefault("term_grace_s", 2.0)
    return WorkerSpec(
        run_dir=str(tmp_path), world_size=world,
        argv=[sys.executable, "-c", script, "{incarnation}", "{world}",
              "{run_dir}", "{host}"],
        **kw)


def test_supervisor_clean_run_completes(tmp_path):
    sup = Supervisor(_spec(tmp_path, "raise SystemExit(0)"),
                     RestartPolicy(max_restarts=1),
                     poll_interval_s=0.02)
    rep = sup.run()
    assert rep["status"] == "completed"
    assert rep["incarnations"] == 1
    assert rep["decisions"][0]["rule"] == "clean-exit"


_CRASH = "raise SystemExit(1)"


def test_supervisor_crash_loop_gives_up_with_final_bundle(tmp_path):
    slept = []
    sup = Supervisor(
        _spec(tmp_path, _CRASH),
        RestartPolicy(max_restarts=2, backoff_initial_s=0.05,
                      backoff_multiplier=2.0, backoff_jitter=0.0),
        poll_interval_s=0.02,
        sleep=lambda s: slept.append(s))
    rep = sup.run()
    assert rep["status"] == "gave_up"
    assert rep["incarnations"] == 3          # initial + 2 restarts
    assert rep["restarts_used"] == 2
    # the backoff schedule was actually slept (injected fake clock)
    backoffs = [s for s in slept if s >= 0.05]
    assert backoffs == [0.05, 0.1]
    # the terminal artefact: a final flight bundle naming the reason
    path = rep["final_bundle"]
    assert path is not None and os.path.basename(path) == \
        "flight_giveup.json"
    b = json.load(open(path))
    assert b["reason"] == "supervisor_give_up"
    assert "budget exhausted" in b["extra"]["reason"]
    assert len(b["extra"]["decisions"]) == 3
    assert b["context"]["supervisor"]["max_restarts"] == 2
    # give-up/restart counters ride /metrics (prometheus text)
    text = server.prometheus_text()
    assert "torchacc_supervisor_giveups_total 1" in text
    assert "torchacc_supervisor_restarts_total 2" in text
    assert "torchacc_supervisor_crash_restarts_total 2" in text


_PREEMPT = """
import json, sys
inc, world, run = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
if inc == 0:
    json.dump({"exit_disposition": {"reason": "preemption",
                                    "preempted": True,
                                    "flagged_step": 4,
                                    "resumable": {"tier1": 4}}},
              open(run + "/flight_4.json", "w"))
raise SystemExit(0)
"""


def test_supervisor_preemption_wait_and_resume(tmp_path):
    sup = Supervisor(_spec(tmp_path, _PREEMPT),
                     RestartPolicy(max_restarts=0,
                                   preempt_resume_delay_s=0.05),
                     poll_interval_s=0.02)
    rep = sup.run()
    assert rep["status"] == "completed"
    assert rep["incarnations"] == 2
    assert rep["decisions"][0]["rule"] == "preempt-resume"
    assert rep["restarts_used"] == 0         # resume never burns budget
    assert counters.get("supervisor_preempt_resumes") == 1


_SDC = """
import json, sys
inc, world, run, host = (int(sys.argv[1]), int(sys.argv[2]),
                         sys.argv[3], int(sys.argv[4]))
if inc == 0:
    if host == 0:
        json.dump({"exit_disposition": {
            "reason": "SDCError", "error_type": "SDCError",
            "flagged_step": 3, "hosts": [1],
            "quarantine_delta": [1], "resumable": {"tier1": 2}}},
            open(run + "/flight_3.json", "w"))
    raise SystemExit(1)
# the restarted pod must be the SHRUNKEN world
raise SystemExit(0 if world == 1 else 9)
"""


def test_supervisor_sdc_restart_excludes_and_shrinks(tmp_path):
    sup = Supervisor(_spec(tmp_path, _SDC, world=2),
                     RestartPolicy(max_restarts=2),
                     poll_interval_s=0.02)
    rep = sup.run()
    assert rep["status"] == "completed"
    assert rep["excluded"] == [1]
    assert rep["world"] == 1
    assert rep["decisions"][0]["rule"] == "sdc-exclude"
    assert rep["decisions"][0]["error_type"] == "SDCError"
    assert rep["decisions"][0]["hosts"] == [1]
    assert counters.get("supervisor_exclusions") == 1
    assert counters.get("supervisor_restarts") == 1


class _FakeProber:
    """Scripted prober: 'alive' for the first N observations, then the
    terminal verdict — the probe-sensing channel without HTTP."""

    def __init__(self, alive_for, then="dead"):
        self.n = 0
        self.alive_for = alive_for
        self.then = then
        self.last = None
        self.consecutive_unreachable = 3
        self.consecutive_unhealthy = 0

    def observe(self):
        self.n += 1
        return None

    def verdict(self):
        return "alive" if self.n <= self.alive_for else self.then


_HANG_THEN_OK = """
import sys, time
inc = int(sys.argv[1])
if inc == 0:
    time.sleep(120)
raise SystemExit(0)
"""


def test_supervisor_probe_dead_kills_and_restarts(tmp_path):
    spec = _spec(tmp_path, _HANG_THEN_OK, probe=True,
                 probe_interval_s=0.05)
    sup = Supervisor(spec, RestartPolicy(max_restarts=2),
                     poll_interval_s=0.02,
                     prober_factory=lambda h, p: _FakeProber(2))
    rep = sup.run()
    assert rep["status"] == "completed"
    assert rep["decisions"][0]["rule"] == "probe-dead-restart"
    assert rep["decisions"][0]["probe_verdict"] == "dead"
    assert counters.get("supervisor_probe_kills") == 1
    assert counters.get("supervisor_hang_restarts") == 1


def test_supervisor_probe_startup_grace_holds_fire(tmp_path):
    """A worker that has NEVER answered its endpoint is not killed
    inside the startup grace window — jax import + compile can take
    minutes before the server binds."""

    class _NeverReachable(_FakeProber):
        def __init__(self):
            super().__init__(0)          # verdict 'dead' immediately
            self.ever_reachable = False

    spec = _spec(tmp_path,
                 "import time; time.sleep(0.6); raise SystemExit(0)",
                 probe=True, probe_interval_s=0.05, probe_grace_s=30.0)
    sup = Supervisor(spec, RestartPolicy(max_restarts=0),
                     poll_interval_s=0.02,
                     prober_factory=lambda h, p: _NeverReachable())
    rep = sup.run()
    assert rep["status"] == "completed"       # never probe-killed
    assert counters.get("supervisor_probe_kills") == 0


def test_supervisor_incarnation_deadline_is_hang(tmp_path):
    spec = _spec(tmp_path, _HANG_THEN_OK, incarnation_timeout_s=1.0)
    sup = Supervisor(spec, RestartPolicy(max_restarts=2),
                     poll_interval_s=0.02)
    rep = sup.run()
    assert rep["status"] == "completed"
    assert rep["decisions"][0]["rule"] == "probe-dead-restart"


def test_cli_supervise_completed_and_giveup(tmp_path, capsys):
    from torchacc_tpu.checkpoint.cli import main as cli_main
    ok = cli_main(["supervise", "--run-dir", str(tmp_path / "a"),
                   "--max-restarts", "1", "--", sys.executable, "-c",
                   "raise SystemExit(0)"])
    assert ok == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["status"] == "completed"
    bad = cli_main(["supervise", "--run-dir", str(tmp_path / "b"),
                    "--max-restarts", "0", "--backoff-initial-s",
                    "0.01", "--", sys.executable, "-c",
                    "raise SystemExit(1)"])
    assert bad == 3
    rep = json.loads(capsys.readouterr().out)
    assert rep["status"] == "gave_up"
    assert os.path.exists(tmp_path / "b" / "flight_giveup.json")


# -- serve drain (the serving-side half of preemption) ------------------------

VOCAB = 64


@pytest.fixture(scope="module")
def tiny_serve():
    cfg = get_preset(
        "llama-tiny", dtype=jnp.float32, num_layers=1, hidden_size=32,
        num_heads=2, num_kv_heads=2, intermediate_size=64,
        vocab_size=VOCAB, max_seq_len=128)
    model = TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def _engine(tiny_serve, **kw):
    model, params = tiny_serve
    base = dict(block_size=8, num_blocks=64, max_slots=2,
                prefill_chunk=8, decode_depth=2)
    base.update(kw)
    return ServeEngine(model, params,
                       ta.Config(serve=ta.ServeConfig(**base)))


def test_serve_drain_finishes_inflight_reports_unserved(tiny_serve):
    eng = _engine(tiny_serve)
    rng = np.random.default_rng(CHAOS_SEED)
    rids = [eng.submit(Request(
        prompt_ids=rng.integers(1, VOCAB, size=6).tolist(),
        max_new_tokens=8)) for _ in range(5)]
    # let admission fill the 2 slots, then drain mid-flight
    eng.step()
    report0 = eng.drain_report()
    assert len(report0["in_flight"]) == 2
    eng.begin_drain("test")
    eng.run()
    rep = eng.drain_report()
    # every admitted request finished; the queued ones never started
    assert sorted(rep["in_flight"]) == []
    assert set(rep["unserved"]) == set(rids) - set(report0["in_flight"])
    assert len(rep["unserved"]) == 3
    for rid in report0["in_flight"]:
        assert len(eng.result(rid).tokens) == 8
    for rid in rep["unserved"]:
        with pytest.raises(RuntimeError):
            eng.result(rid)
    # admission stays stopped: more steps never admit the queue
    for _ in range(3):
        assert eng.step() is False
    assert set(eng.unserved_ids()) == set(rep["unserved"])
    assert counters.get("serve_requests_unserved") == 3
    # a second run() on the drained engine must not re-count the
    # already-reported unserved ids
    eng.run()
    assert counters.get("serve_requests_unserved") == 3


def test_serve_drain_on_preemption_signal(tiny_serve):
    eng = _engine(tiny_serve)
    rng = np.random.default_rng(CHAOS_SEED + 1)
    rids = [eng.submit(Request(
        prompt_ids=rng.integers(1, VOCAB, size=6).tolist(),
        max_new_tokens=4)) for _ in range(4)]
    try:
        request_preemption("test eviction")
        eng.run()                      # drains instead of serving all
        rep = eng.drain_report()
        assert rep["draining"] is True
        assert 0 < len(rep["unserved"]) <= 4
        assert rep["completed"] + len(rep["unserved"]) == 4
    finally:
        clear_preemption()
    assert counters.get("serve_drains") == 1
    assert rids


def test_serve_drain_off_serves_everything(tiny_serve):
    eng = _engine(tiny_serve, drain_on_preempt=False)
    rng = np.random.default_rng(CHAOS_SEED + 2)
    rids = [eng.submit(Request(
        prompt_ids=rng.integers(1, VOCAB, size=6).tolist(),
        max_new_tokens=4)) for _ in range(4)]
    try:
        request_preemption("ignored")
        eng.run()
    finally:
        clear_preemption()
    for rid in rids:
        assert len(eng.result(rid).tokens) == 4
    assert eng.drain_report()["draining"] is False
