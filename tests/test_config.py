"""Config validation + derivation tests (reference analogue: the implicit
contract of torchacc/config.py — validate(), dp-size inference
config.py:320-324, uniform buckets core/async_loader.py:14-17)."""

import pytest

from torchacc_tpu.config import (
    Config,
    ConfigError,
    DataConfig,
    DistConfig,
    DPConfig,
    FSDPConfig,
    PPConfig,
    SPConfig,
    TPConfig,
)


def test_default_config_validates():
    cfg = Config()
    cfg.validate()


def test_dp_inference():
    dist = DistConfig(dp=DPConfig(size=-1), fsdp=FSDPConfig(size=4))
    sizes = dist.axis_sizes(world_size=8)
    assert sizes["dp"] == 2 and sizes["fsdp"] == 4


def test_axis_product_mismatch_raises():
    dist = DistConfig(dp=DPConfig(size=2), fsdp=FSDPConfig(size=2))
    with pytest.raises(ConfigError):
        dist.axis_sizes(world_size=8)  # 2*2 != 8


def test_invalid_topology_raises():
    cfg = Config(dist=DistConfig(topology=("dp", "fsdp")))
    with pytest.raises(ConfigError):
        cfg.validate()


def test_pp_microbatch_divisibility():
    cfg = Config(dist=DistConfig(pp=PPConfig(size=4, num_micro_batches=6)))
    with pytest.raises(ConfigError):
        cfg.validate()
    Config(dist=DistConfig(pp=PPConfig(size=4, num_micro_batches=8))).validate()


def test_sp_2d_requires_intra():
    cfg = Config(dist=DistConfig(sp=SPConfig(size=4, mode="2d")))
    with pytest.raises(ConfigError):
        cfg.validate()
    Config(dist=DistConfig(sp=SPConfig(size=4, mode="2d", intra_size=2))).validate()


def test_uniform_buckets():
    # reference `_uniform_buckets` (async_loader.py:14-17)
    d = DataConfig(max_length=512, num_buckets=4)
    assert d.bucket_sizes() == [128, 256, 384, 512]
    d2 = DataConfig(buckets=[64, 128])
    assert d2.bucket_sizes() == [64, 128]
    assert DataConfig().bucket_sizes() is None


def test_from_dict_unknown_key_raises():
    with pytest.raises(ConfigError):
        Config.from_dict({"dist": {"fsdb": {"size": 4}}})
    with pytest.raises(ConfigError):
        Config.from_dict({"bogus": 1})


def test_roundtrip_dict():
    cfg = Config(dist=DistConfig(tp=TPConfig(size=2), fsdp=FSDPConfig(size=2)))
    d = cfg.to_dict()
    cfg2 = Config.from_dict(d)
    assert cfg2.dist.tp.size == 2
    assert cfg2.dist.fsdp.size == 2
    assert tuple(cfg2.dist.topology) == tuple(cfg.dist.topology)


def test_every_config_field_has_a_consumer():
    """Suite-enforced invariant (round-2 verdict weak-5): every validated
    config field must be READ somewhere outside config.py.  A field that
    only exists and validates is a lie to the user — wire it or delete it.
    """
    import dataclasses
    import pathlib
    import re

    import torchacc_tpu.config as cfg_mod

    pkg = pathlib.Path(cfg_mod.__file__).parent
    sources = []
    for p in pkg.rglob("*.py"):
        if p.name == "config.py":
            continue
        sources.append(p.read_text())
    blob = "\n".join(sources)

    def fields_of(tp, prefix):
        out = []
        for f in dataclasses.fields(tp):
            if f.name.startswith("_"):
                continue
            sub = cfg_mod._TYPE_MAP.get(f.name)
            if sub is not None:
                out += fields_of(sub, f"{prefix}{f.name}.")
            else:
                out.append((f"{prefix}{f.name}", f.name))
        return out

    # fields consumed through a derived accessor defined in config.py:
    # the ACCESSOR must then be consumed outside config.py
    indirect = {
        "max_length": "bucket_sizes",      # DataConfig.bucket_sizes()
        # intra_size -> SPConfig.ulysses_degree/ring_degree -> the 'spu' and
        # 'sp' extents in DistConfig.axis_sizes, which the mesh builder reads
        "intra_size": "axis_sizes",
        # backoff shape -> ResilienceConfig.retry_policy(), read by the
        # trainer's checkpoint manager and the async loader
        "retry_base_delay_s": "retry_policy",
        "retry_max_delay_s": "retry_policy",
        "retry_deadline_s": "retry_policy",
    }
    unread = []
    for path, name in fields_of(cfg_mod.Config, ""):
        probe = indirect.get(name, name)
        if not re.search(rf"\b{re.escape(probe)}\b", blob):
            unread.append(path)
    assert not unread, (
        f"config fields with no consumer outside config.py: {unread} — "
        f"wire them into a code path (and test it) or delete them")
