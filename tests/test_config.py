"""Config validation + derivation tests (reference analogue: the implicit
contract of torchacc/config.py — validate(), dp-size inference
config.py:320-324, uniform buckets core/async_loader.py:14-17)."""

import pytest

from torchacc_tpu.config import (
    Config,
    ConfigError,
    DataConfig,
    DistConfig,
    DPConfig,
    FSDPConfig,
    PPConfig,
    SPConfig,
    TPConfig,
)


def test_default_config_validates():
    cfg = Config()
    cfg.validate()


def test_dp_inference():
    dist = DistConfig(dp=DPConfig(size=-1), fsdp=FSDPConfig(size=4))
    sizes = dist.axis_sizes(world_size=8)
    assert sizes["dp"] == 2 and sizes["fsdp"] == 4


def test_axis_product_mismatch_raises():
    dist = DistConfig(dp=DPConfig(size=2), fsdp=FSDPConfig(size=2))
    with pytest.raises(ConfigError):
        dist.axis_sizes(world_size=8)  # 2*2 != 8


def test_invalid_topology_raises():
    cfg = Config(dist=DistConfig(topology=("dp", "fsdp")))
    with pytest.raises(ConfigError):
        cfg.validate()


def test_pp_microbatch_divisibility():
    cfg = Config(dist=DistConfig(pp=PPConfig(size=4, num_micro_batches=6)))
    with pytest.raises(ConfigError):
        cfg.validate()
    Config(dist=DistConfig(pp=PPConfig(size=4, num_micro_batches=8))).validate()


def test_sp_2d_requires_intra():
    cfg = Config(dist=DistConfig(sp=SPConfig(size=4, mode="2d")))
    with pytest.raises(ConfigError):
        cfg.validate()
    Config(dist=DistConfig(sp=SPConfig(size=4, mode="2d", intra_size=2))).validate()


def test_uniform_buckets():
    # reference `_uniform_buckets` (async_loader.py:14-17)
    d = DataConfig(max_length=512, num_buckets=4)
    assert d.bucket_sizes() == [128, 256, 384, 512]
    d2 = DataConfig(buckets=[64, 128])
    assert d2.bucket_sizes() == [64, 128]
    assert DataConfig().bucket_sizes() is None


def test_from_dict_unknown_key_raises():
    with pytest.raises(ConfigError):
        Config.from_dict({"dist": {"fsdb": {"size": 4}}})
    with pytest.raises(ConfigError):
        Config.from_dict({"bogus": 1})


def test_roundtrip_dict():
    cfg = Config(dist=DistConfig(tp=TPConfig(size=2), fsdp=FSDPConfig(size=2)))
    d = cfg.to_dict()
    cfg2 = Config.from_dict(d)
    assert cfg2.dist.tp.size == 2
    assert cfg2.dist.fsdp.size == 2
    assert tuple(cfg2.dist.topology) == tuple(cfg.dist.topology)
