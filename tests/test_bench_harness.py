"""The 8B-geometry bench's subprocess depth ladder.

`benchmarks/bench_8b.py` times each depth in a fresh subprocess (an
OOM'd depth's resident buffers would otherwise poison shallower
attempts — observed live on the v5e, see the module docstring) and
talks to the children over a one-JSON-line protocol.  These tests pin
the protocol and the OOM classifier off-chip; the smoke geometry runs
the REAL parent/child flow end-to-end on CPU.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH8B = os.path.join(REPO, "benchmarks", "bench_8b.py")
sys.path.insert(0, os.path.join(REPO, "benchmarks"))

from bench_8b import _is_oom  # noqa: E402


def _clean_env():
    # The suite's conftest exports XLA_FLAGS=--xla_force_host_platform_
    # device_count=8 for the emulated mesh; the bench runs single-device
    # (dp inference over 8 devices would reject batch 1), so children
    # here get the flag stripped — matching real bench invocation.
    env = dict(os.environ)
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "force_host_platform_device_count" not in f)
    return env


def test_is_oom_matches_tunnel_wrapped_oom():
    # The axon remote-compile tunnel wraps HBM OOM in an HTTP 500 whose
    # body says "Ran out of memory in memory space hbm" — lowercase
    # "out", so a capitalised substring match misses it (the round-5
    # regression this classifier fixes).
    tunnel = ("INTERNAL: http://127.0.0.1:8093/remote_compile: HTTP 500: "
              "compile: Internal: AOT PJRT error: XLA:TPU compile "
              "permanent error. Ran out of memory in memory space hbm. "
              "Used 23.38G of 15.75G hbm. Exceeded hbm capacity by 7.63G.")
    assert _is_oom(tunnel)
    assert _is_oom("RESOURCE_EXHAUSTED: allocation failed")
    assert _is_oom("Allocation 1.2G exceeds the limit")
    assert not _is_oom("Mosaic lowering failed: unsupported dtype")
    assert not _is_oom("connection reset by peer")


def test_one_depth_child_protocol():
    # A child run prints exactly one {"_depth", "dt", "device_kind"}
    # JSON line on success; the parent parses nothing else.
    r = subprocess.run(
        [sys.executable, BENCH8B, "--one-depth", "1", "--smoke",
         "--seq", "128", "--batch", "1", "--iters", "1",
         "--platform", "cpu"],
        capture_output=True, text=True, timeout=300, env=_clean_env())
    assert r.returncode == 0, r.stderr[-2000:]
    recs = []
    for line in r.stdout.splitlines():
        try:
            cand = json.loads(line)
        except ValueError:
            continue
        if isinstance(cand, dict) and "_depth" in cand:
            recs.append(cand)
    assert len(recs) == 1
    assert recs[0]["_depth"] == 1
    assert recs[0]["dt"] > 0
    assert recs[0]["device_kind"]


@pytest.mark.slow
def test_parent_ladder_end_to_end_smoke():
    # Full parent flow at smoke geometry: two child depths, differenced
    # report, no docs/bench_8b.json write (smoke never persists —
    # _OUT's mtime must not change).
    out_path = os.path.join(REPO, "docs", "bench_8b.json")
    before = os.stat(out_path).st_mtime if os.path.exists(out_path) else None
    r = subprocess.run(
        [sys.executable, BENCH8B, "--smoke", "--seq", "128",
         "--batch", "1", "--iters", "1", "--depths", "2", "1",
         "--platform", "cpu"],
        capture_output=True, text=True, timeout=600, env=_clean_env())
    assert r.returncode == 0, r.stderr[-2000:]
    line = r.stdout.strip().splitlines()[-1]
    out = json.loads(line)
    assert out["metric"] == "llama3_8b_geometry_layer_mfu"
    assert "error" not in out
    d = out["detail"]
    # Protocol, not perf: both depths timed (positive step times); the
    # DIFFERENCED value can round to 0.0 at smoke geometry.
    assert set(d["depths_measured"]) == {"2", "1"}
    assert all(v > 0 for v in d["depths_measured"].values())
    assert d["chip"] == "cpu"
    after = os.stat(out_path).st_mtime if os.path.exists(out_path) else None
    assert before == after
