"""Watchdog unit tests: deterministic expiry under a fake clock.

No monitor thread runs here (``poll_interval_s=None``); tests advance a
fake monotonic clock and call ``check_now()`` directly, so deadline
semantics are exact — no sleeps, no flaky timing.  The end-to-end path
(real monitor thread + chaos-injected hang through ``Trainer.fit``)
lives in tests/test_resilience.py.
"""

import os
import threading

import pytest

from torchacc_tpu.errors import HangError
from torchacc_tpu.resilience.watchdog import Watchdog, dump_stacks, trip_stall
from torchacc_tpu.utils.metrics import counters


@pytest.fixture(autouse=True)
def _reset_counters():
    counters.reset()
    yield
    counters.reset()


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _wd(tmp_path, **kw):
    kw.setdefault("dump_dir", str(tmp_path))
    kw.setdefault("poll_interval_s", None)  # no monitor thread
    clk = FakeClock()
    return Watchdog(clock=clk, **kw), clk


def test_deadline_fires_dumps_and_counts(tmp_path):
    wd, clk = _wd(tmp_path)
    wd.arm("train_step", 5.0)
    clk.advance(4.9)
    assert not wd.check_now()          # within deadline: nothing
    assert counters.get("watchdog_stalls") == 0
    clk.advance(0.2)
    assert wd.check_now()              # expired: trip
    assert counters.get("watchdog_stalls") == 1
    assert wd.stalls == 1
    # the stack dump was written and names this (the stalled) thread
    assert wd.last_dump_path and os.path.exists(wd.last_dump_path)
    text = open(wd.last_dump_path).read()
    assert "train_step" in text
    assert "test_watchdog" in text     # our own frame is in the dump
    # one trip per armed section, not one per poll
    clk.advance(100.0)
    assert not wd.check_now()
    assert counters.get("watchdog_stalls") == 1
    wd.disarm()                        # abort off: no raise
    wd.close()


def test_no_false_positive_on_slow_but_alive(tmp_path):
    wd, clk = _wd(tmp_path)
    wd.arm("train_step", 10.0)
    for _ in range(5):                 # 40s of wall time, beating at 8s
        clk.advance(8.0)
        assert not wd.check_now()
        wd.beat()                      # progress: deadline resets
    assert counters.get("watchdog_stalls") == 0
    assert wd.heartbeat_age_s() == 0.0
    clk.advance(3.0)
    assert wd.heartbeat_age_s() == pytest.approx(3.0)
    wd.disarm()
    wd.close()


def test_abort_on_hang_raises_at_next_boundary(tmp_path):
    wd, clk = _wd(tmp_path, abort_on_hang=True)
    wd.arm("train_step", 2.0)
    clk.advance(2.5)
    assert wd.check_now()
    with pytest.raises(HangError) as ei:
        wd.disarm()                    # the step boundary
    assert ei.value.label == "train_step"
    assert ei.value.deadline_s == 2.0
    assert ei.value.waited_s >= 2.5
    assert ei.value.dump_path and os.path.exists(ei.value.dump_path)
    # the pending error is consumed: the next section starts clean
    wd.arm("train_step", 2.0)
    wd.disarm()
    wd.close()


def test_pending_hang_raised_by_next_arm(tmp_path):
    # a hang during data fetch surfaces even if the caller re-arms for
    # the step instead of disarming
    wd, clk = _wd(tmp_path, abort_on_hang=True)
    wd.arm("data_fetch", 1.0)
    clk.advance(1.5)
    assert wd.check_now()
    with pytest.raises(HangError) as ei:
        wd.arm("train_step", 5.0)
    assert ei.value.label == "data_fetch"
    wd.close()


def test_disarm_before_trip_means_no_late_abort(tmp_path):
    # the section finished between deadline expiry and the monitor's
    # next poll: dump/count still happen on the poll, but no HangError
    # may ambush the (healthy) code that is now running
    wd, clk = _wd(tmp_path, abort_on_hang=True)
    wd.arm("train_step", 1.0)
    clk.advance(1.5)
    wd.disarm()                        # finished late, but finished
    assert not wd.check_now()          # disarmed: no trip at all
    wd.arm("train_step", 1.0)          # must not raise
    wd.disarm()
    wd.close()


def test_rearm_resets_deadline(tmp_path):
    wd, clk = _wd(tmp_path)
    wd.arm("data_fetch", 5.0)
    clk.advance(4.0)
    wd.arm("train_step", 5.0)          # new section, new deadline
    clk.advance(4.0)                   # 8s since first arm, 4s since re-arm
    assert not wd.check_now()
    assert counters.get("watchdog_stalls") == 0
    wd.disarm()
    wd.close()


def test_watch_context_manager(tmp_path):
    wd, clk = _wd(tmp_path, abort_on_hang=True)
    with wd.watch("ok_section", 10.0):
        clk.advance(1.0)
    assert counters.get("watchdog_stalls") == 0

    with pytest.raises(HangError):
        with wd.watch("slow_section", 1.0):
            clk.advance(2.0)
            wd.check_now()             # monitor would have fired here
    # a non-hang exception from the body is not masked by the pending
    with pytest.raises(ValueError):
        with wd.watch("failing_section", 1.0):
            clk.advance(2.0)
            wd.check_now()
            raise ValueError("body error")
    wd.close()


def test_monitor_thread_trips_real_clock(tmp_path):
    # integration of the daemon monitor: a genuinely slow section with a
    # tiny deadline trips without any manual check_now()
    wd = Watchdog(dump_dir=str(tmp_path), poll_interval_s=0.01).start()
    done = threading.Event()
    wd.arm("hang", 0.05)
    done.wait(0.3)                     # "hang" for 0.3s
    assert counters.get("watchdog_stalls") == 1
    wd.disarm()
    wd.close()


def test_close_is_safe_in_finally(tmp_path):
    wd, clk = _wd(tmp_path, abort_on_hang=True)
    wd.arm("s", 1.0)
    clk.advance(2.0)
    wd.check_now()
    wd.close()                         # pending dropped with a log, no raise


def test_trip_stall_helper(tmp_path):
    path = trip_stall("loader.fetch", 3.0, 1.0, dump_dir=str(tmp_path),
                      abort=False)
    assert path and os.path.exists(path)
    assert counters.get("watchdog_stalls") == 1
    with pytest.raises(HangError) as ei:
        trip_stall("loader.fetch", 3.0, 1.0, dump_dir=str(tmp_path),
                   abort=True)
    assert ei.value.label == "loader.fetch"
    assert counters.get("watchdog_stalls") == 2


def test_dump_stacks_stderr_fallback():
    # unwritable dir degrades to stderr and returns None, never raises
    assert dump_stacks("x", "/proc/definitely/not/writable") is None
