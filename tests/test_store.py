"""Unified object-store plane tests (torchacc_tpu/store/,
docs/resilience.md "Object-store tier-2") — `make store-chaos` runs
them under 3 seeds.

The contracts under test:

- write-side ChaosObjectStore fault plans are pure functions of
  ``(seed, key)``, consumed per attempt — deterministic under ANY
  put/retry interleaving, and independent of the read-plan stream;
- the ONE PUT path (verify-after-put inside the retried callable)
  survives transient 5xx, partial (torn-object-left-behind), and
  acknowledged-but-lost uploads;
- two-phase commit invariant: a reader NEVER sees payload objects
  without their ``_COMMIT`` marker (torn uploads are invisible by
  protocol), and a marker whose payloads fail checksum verification is
  quarantined typed, never read;
- kill -9 mid-trickle under write faults → restart → the newest tier
  restores bitwise and the torn mirror upload is never offered;
- a dead mirror store degrades to tier-1-only behind the destination
  breaker (``store_breaker_open``) instead of stalling the trickle;
- a journal archive upload killed between rotation and PUT loses
  nothing: the local segment/archive union replays 100%.
"""

import json
import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

from torchacc_tpu.errors import StoreCommitError, StoreError
from torchacc_tpu.store import (
    COMMIT_MARKER,
    ChaosObjectStore,
    GCSObjectStore,
    LocalObjectStore,
    ObjectStoreClient,
    commit_marker_key,
    list_commits,
    open_store,
    put_commit,
    read_commit,
    read_commit_marker,
    sha256_hex,
    verify_commit,
)
from torchacc_tpu.utils.metrics import counters
from torchacc_tpu.utils.retry import RetryPolicy

pytestmark = pytest.mark.store

CHAOS_SEED = int(os.environ.get("CHAOS_SEED", "0"))

_FAST = RetryPolicy(max_retries=4, base_delay_s=0.001, max_delay_s=0.002,
                    retry_on=(OSError,))


@pytest.fixture(autouse=True)
def _clean_counters():
    counters.reset()
    yield


@pytest.fixture(autouse=True)
def _clear_mirror_factory():
    import torchacc_tpu.checkpoint.tiered as tiered
    yield
    tiered.MIRROR_STORE_FACTORY = None


def _client(store, **kw):
    kw.setdefault("policy", _FAST)
    kw.setdefault("sleep", lambda s: None)
    return ObjectStoreClient(store, **kw)


def _payload(key):
    return (f"payload:{key}:{CHAOS_SEED}" * 7).encode()


# -- backends -----------------------------------------------------------------

def test_local_store_rejects_escaping_and_hidden_keys(tmp_path):
    s = LocalObjectStore(str(tmp_path))
    for bad in ("", "/abs", "a//b", "a/../b", ".hidden", "a/.tmp", "a/"):
        with pytest.raises(StoreError):
            s.put(bad, b"x")
    s.put("a/b/c", b"ok")
    assert s.get("a/b/c") == b"ok"
    # in-flight temp files are never listed as objects
    (tmp_path / "a" / ".c.tmp999").write_bytes(b"junk")
    assert s.list() == ["a/b/c"]
    s.delete("a/b/c")
    s.delete("a/b/c")        # idempotent
    assert not s.exists("a/b/c")


def test_open_store_dispatch_and_gcs_stub_typed(tmp_path):
    assert isinstance(open_store(str(tmp_path)), LocalObjectStore)
    g = open_store("gs://bucket/pre/fix")
    assert isinstance(g, GCSObjectStore)
    assert (g.bucket, g.prefix) == ("bucket", "pre/fix")
    with pytest.raises(NotImplementedError) as ei:
        g.put("k", b"x")
    assert "ObjectStore surface" in str(ei.value)
    with pytest.raises(StoreError):
        GCSObjectStore("s3://nope")


# -- write-side chaos plan determinism ----------------------------------------

def _drive(store, schedule):
    """One PUT attempt per schedule entry; per-key outcome strings
    ('raise' / 'ok' / 'swallowed') — the observable fault schedule."""
    out = {}
    for key in schedule:
        try:
            store.put(key, _payload(key))
        except OSError:
            out.setdefault(key, []).append("raise")
            continue
        stored = (store.inner.exists(key)
                  and store.inner.get(key) == _payload(key))
        out.setdefault(key, []).append("ok" if stored else "swallowed")
    return out


def test_write_plans_deterministic_under_any_put_order(tmp_path):
    keys = [f"step/{i}/obj" for i in range(12)]
    faults = dict(put_transient_rate=0.4, put_partial_rate=0.25,
                  put_lost_rate=0.2)
    # order A: each key retried to 4 attempts back to back; order B:
    # round-robin interleaved and reversed — same per-key schedules
    a = _drive(ChaosObjectStore(LocalObjectStore(str(tmp_path / "a")),
                                seed=CHAOS_SEED, **faults),
               [k for k in keys for _ in range(4)])
    b = _drive(ChaosObjectStore(LocalObjectStore(str(tmp_path / "b")),
                                seed=CHAOS_SEED, **faults),
               [k for _ in range(4) for k in reversed(keys)])
    assert a == b
    # the seed moves the schedule: at least one key draws a fault at
    # these rates (12 keys, ~85% fault probability each)
    assert any(o[0] != "ok" for o in a.values())


def test_write_faults_never_perturb_read_plans(tmp_path):
    """Read plans draw from ``crc32(seed|key)``, write plans from
    ``crc32(seed|put|key)`` — enabling write faults must not shift a
    read schedule a seed was chosen for."""
    quiet = ChaosObjectStore(LocalObjectStore(str(tmp_path)),
                             seed=CHAOS_SEED, transient_rate=0.4,
                             torn_rate=0.3)
    noisy = ChaosObjectStore(LocalObjectStore(str(tmp_path)),
                             seed=CHAOS_SEED, transient_rate=0.4,
                             torn_rate=0.3, put_transient_rate=0.9,
                             put_partial_rate=0.05)
    for i in range(20):
        assert quiet._plan(f"k{i}") == noisy._plan(f"k{i}")


def test_put_verify_retries_partial_lost_and_transient(tmp_path):
    """The one PUT path re-uploads everything the backend tore, lost,
    or 5xx'd — verify-after-put inside the retried callable."""
    for kind, faults in (
            ("transient", dict(put_transient_rate=1.0)),
            ("partial", dict(put_partial_rate=1.0)),
            ("lost", dict(put_lost_rate=1.0))):
        counters.reset()
        root = str(tmp_path / kind)
        store = ChaosObjectStore(LocalObjectStore(root), seed=CHAOS_SEED,
                                 **faults)
        cli = _client(store)
        data = _payload(kind)
        assert cli.put(f"{kind}/obj", data) == sha256_hex(data)
        assert store.inner.get(f"{kind}/obj") == data
        assert counters.get("store_put_retries") >= 1, kind
        assert counters.get("store_puts") == 1
        assert counters.get("store_put_bytes") == len(data)


# -- two-phase commit invariants ----------------------------------------------

def test_payload_without_marker_is_invisible(tmp_path):
    store = LocalObjectStore(str(tmp_path))
    cli = _client(store)
    cli.put("7/weights.bin", b"torn upload payload")
    cli.put("7/extra.bin", b"more bytes")          # no marker ever lands
    assert list_commits(store) == []
    with pytest.raises(StoreCommitError) as ei:
        read_commit(cli, "7")
    assert ei.value.torn and ei.value.prefix == "7"
    assert verify_commit(store, "7") == ["no commit marker (torn upload)"]


def test_commit_roundtrip_and_marker_last(tmp_path):
    store = LocalObjectStore(str(tmp_path))
    cli = _client(store)
    objs = {"a.bin": b"alpha" * 9, "b/nested.bin": b"beta" * 5}
    marker = put_commit(cli, "12", objs, meta={"step": 12})
    assert set(marker["objects"]) == set(objs)
    assert list_commits(store) == ["12"]
    assert read_commit(cli, "12") == objs
    assert verify_commit(store, "12") == []
    assert read_commit_marker(store, "12")["meta"] == {"step": 12}


def test_marker_without_verified_payload_quarantined(tmp_path):
    store = LocalObjectStore(str(tmp_path))
    cli = _client(store)
    put_commit(cli, "12", {"a.bin": b"sound bytes here"})
    # bit-rot one payload UNDER the marker (store-level, no re-commit)
    store.put("12/a.bin", b"Sound bytes here")
    with pytest.raises(StoreCommitError) as ei:
        read_commit(cli, "12")
    assert not ei.value.torn        # marked but damaged: the quarantine case
    assert "a.bin" in str(ei.value)
    problems = verify_commit(store, "12")
    assert any("sha256 mismatch" in p for p in problems)


def test_lost_marker_leaves_commit_invisible(tmp_path):
    """The commit-marker-lost write fault: payloads land, the marker
    PUT is swallowed forever — retries exhaust, the commit stays
    invisible, and the failure is typed + counted."""
    store = ChaosObjectStore(LocalObjectStore(str(tmp_path)),
                             seed=CHAOS_SEED,
                             lose_keys={commit_marker_key("9")})
    cli = _client(store)
    with pytest.raises(OSError):
        put_commit(cli, "9", {"w.bin": b"payload that made it"})
    assert counters.get("store_put_failures") == 1
    assert list_commits(store.inner) == []
    assert store.inner.get("9/w.bin") == b"payload that made it"
    assert store.injected.get("put_lost", 0) >= 1


def test_stale_listing_hides_then_reveals_commit(tmp_path):
    """gs:// listings are eventually consistent: a fresh commit may be
    absent from the first LIST and must appear on a later one."""
    store = ChaosObjectStore(LocalObjectStore(str(tmp_path)),
                             seed=CHAOS_SEED, stale_list_reads=1)
    put_commit(_client(store), "3", {"x.bin": b"bytes"})
    assert list_commits(store) == []          # stale read: not yet visible
    assert store.injected.get("stale_list") == 1
    assert list_commits(store) == ["3"]       # convergence


# -- breaker degradation ------------------------------------------------------

def test_dead_store_opens_breaker_without_stalling(tmp_path):
    clock = [0.0]
    store = ChaosObjectStore(LocalObjectStore(str(tmp_path)), dead=True)
    cli = _client(store, failure_budget=2, breaker_cooldown_s=5.0)
    cli.breaker._clock = lambda: clock[0]    # deterministic half-open
    for _ in range(2):
        assert cli.should_attempt()
        with pytest.raises(OSError):
            cli.put("k", b"x")
        cli.record_outcome(False)
    assert counters.get("store_breaker_open") == 1
    assert not cli.should_attempt()           # OPEN: skip cheaply
    clock[0] = 6.0
    assert cli.should_attempt()               # half-open probe granted
    store.dead = False
    cli.put("k", b"x")
    assert not cli.record_outcome(True)       # readmitted, no open edge
    assert cli.should_attempt()


# -- owner election -----------------------------------------------------------

def test_elect_upload_owners_round_robin():
    from torchacc_tpu.checkpoint.tiered import elect_upload_owners
    m = np.array([[True, True, False, True],
                  [True, False, True, True],
                  [True, True, True, False]])
    owners = elect_upload_owners(m)
    assert len(owners) == 4
    for r, o in enumerate(owners):
        assert m[o, r]                        # owners only ever hold
    # round-robin spreads the upload bytes across holders
    assert len(set(owners)) > 1
    none = np.array([[True, False], [True, False]])
    assert elect_upload_owners(none)[1] == -1


# -- tiered tier-2 integration ------------------------------------------------

def _model():
    import jax.numpy as jnp

    from torchacc_tpu.models import get_preset
    return get_preset("llama-tiny", vocab_size=64, hidden_size=32,
                      num_layers=1, num_heads=2, num_kv_heads=2,
                      intermediate_size=64, dtype=jnp.float32)


def _trainer(mirror):
    import optax

    import torchacc_tpu as ta
    from torchacc_tpu.train import accelerate
    cfg = ta.Config(resilience=ta.ResilienceConfig(
        tiered_checkpointing=True, tiered_mirror_dir=mirror))
    tr, _ = accelerate(_model(), None, cfg, optimizer=optax.adam(1e-3))
    return tr


def _batches(n):
    rng = np.random.default_rng(CHAOS_SEED)
    return [{"input_ids": rng.integers(0, 64, size=(8, 16)).astype(np.int32)}
            for _ in range(n)]


def _leaves(tree):
    import jax
    return [np.asarray(x) for x in jax.device_get(jax.tree.leaves(tree))]


def test_mirror_survives_write_faults_and_restores_bitwise(tmp_path):
    """Tier-2 uploads ride the verifying client: under transient /
    partial / lost write faults every committed step still lands
    bitwise-restorable on the mirror."""
    import torchacc_tpu.checkpoint.tiered as tiered
    chaos = []

    def factory(d):
        chaos.append(ChaosObjectStore(
            LocalObjectStore(d), seed=CHAOS_SEED, put_transient_rate=0.35,
            put_partial_rate=0.25, put_lost_rate=0.15))
        return chaos[-1]

    tiered.MIRROR_STORE_FACTORY = factory
    d, mirror = str(tmp_path / "ckpt"), str(tmp_path / "mirror")
    t = _trainer(mirror)
    t.fit(_batches(4), max_steps=4, log_every=0, checkpoint_dir=d,
          checkpoint_every=2)
    assert counters.get("mirror_writes") == 2
    want = _leaves(t.state)
    tiered.MIRROR_STORE_FACTORY = None
    assert tiered.TieredCheckpointManager._mirror_valid_steps(mirror) \
        == [2, 4]
    assert verify_commit(LocalObjectStore(mirror), "4") == []
    shutil.rmtree(d)                # local history gone: tier 2 serves
    mgr = tiered.TieredCheckpointManager(d, mirror_dir=mirror)
    try:
        state, step = mgr.restore_latest_valid(t.abstract_state())
    finally:
        mgr.shutdown()
    assert step == 4
    for x, y in zip(want, _leaves(state)):
        np.testing.assert_array_equal(x, y)
    assert counters.get("mirror_restores") == 1


def test_torn_mirror_upload_never_offered_for_restore(tmp_path):
    """Strip the newest mirror step's _COMMIT marker (the torn-upload
    signature): restore_latest_valid must fall to the older committed
    mirror step, never the torn one."""
    from torchacc_tpu.checkpoint.io import MANIFEST
    from torchacc_tpu.checkpoint.tiered import TieredCheckpointManager
    d, mirror = str(tmp_path / "ckpt"), str(tmp_path / "mirror")
    t = _trainer(mirror)
    t.fit(_batches(4), max_steps=4, log_every=0, checkpoint_dir=d,
          checkpoint_every=2)
    ref_mgr = TieredCheckpointManager(str(tmp_path / "scratch"),
                                      mirror_dir=mirror)
    try:
        abstract = t.abstract_state()
        store = LocalObjectStore(mirror)
        store.delete(commit_marker_key("4"))
        store.delete(f"4/{MANIFEST}")
        assert os.path.isdir(os.path.join(mirror, "4"))   # payloads remain
        assert TieredCheckpointManager._mirror_valid_steps(mirror) == [2]
        shutil.rmtree(d)
        state, step = ref_mgr.restore_latest_valid(abstract)
    finally:
        ref_mgr.shutdown()
    assert step == 2


def test_damaged_mirror_commit_read_repairs_to_tier1(tmp_path):
    """A marker blessing damaged payloads quarantines typed and the
    restore falls back to the older-but-sound tier-1 step, counted
    ``mirror_read_repairs``."""
    from torchacc_tpu.checkpoint.tiered import TieredCheckpointManager
    d, mirror = str(tmp_path / "ckpt"), str(tmp_path / "mirror")
    t = _trainer(mirror)
    t.fit(_batches(4), max_steps=4, log_every=0, checkpoint_dir=d,
          checkpoint_every=2)
    # tier 1 keeps only step 2; the mirror's newer step 4 is bit-rotted
    # UNDER its marker
    shutil.rmtree(os.path.join(d, "4"))
    store = LocalObjectStore(mirror)
    key = next(k for k in store.list("4/")
               if not k.endswith((COMMIT_MARKER, "_MANIFEST"))
               and k.startswith("4/default/d/"))
    buf = bytearray(store.get(key))
    buf[len(buf) // 2] ^= 0x10
    store.put(key, bytes(buf))
    counters.reset()
    mgr = TieredCheckpointManager(d, mirror_dir=mirror)
    try:
        state, step = mgr.restore_latest_valid(t.abstract_state())
    finally:
        mgr.shutdown()
    assert step == 2
    assert counters.get("mirror_read_repairs") == 1
    assert counters.get("mirror_restores") == 0


def test_dead_mirror_degrades_to_tier1_only(tmp_path):
    """A dead mirror destination must cost the trickle a breaker
    verdict, not a stall: failures open the breaker
    (``store_breaker_open``), later saves skip cheaply
    (``mirror_skips``), and every step stays durable on tier 1."""
    import torchacc_tpu.checkpoint.tiered as tiered
    tiered.MIRROR_STORE_FACTORY = lambda d: ChaosObjectStore(
        LocalObjectStore(d), dead=True)
    d, mirror = str(tmp_path / "ckpt"), str(tmp_path / "mirror")
    t = _trainer(mirror)
    t.fit(_batches(6), max_steps=6, log_every=0, checkpoint_dir=d,
          checkpoint_every=1)
    assert counters.get("mirror_writes") == 0
    assert counters.get("mirror_write_failures") >= 3
    assert counters.get("store_breaker_open") == 1
    assert counters.get("mirror_skips") >= 1          # post-open skips
    assert counters.get("tiered_write_failures") == 0  # tier 1 untouched
    from torchacc_tpu.checkpoint import CheckpointManager
    # tier 1 committed every step (retention keeps the newest window)
    assert CheckpointManager(d).valid_steps() == [4, 5, 6]


# -- kill -9 mid-trickle (the acceptance scenario) ----------------------------

_TIERED_KILL_WORKER = """
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
base, mode = sys.argv[1:3]
seed = int(os.environ.get("CHAOS_SEED", "0"))
import hashlib
import jax
import jax.numpy as jnp
import numpy as np
import optax
import torchacc_tpu as ta
import torchacc_tpu.checkpoint.tiered as tiered
from torchacc_tpu.models import get_preset
from torchacc_tpu.store import ChaosObjectStore, LocalObjectStore
from torchacc_tpu.train import accelerate


class KillStore(ChaosObjectStore):
    def put(self, name, data):
        if mode == "kill" and name.startswith("6/"):
            if sum(1 for k in self._put_attempts
                   if k.startswith("6/")) >= 2:
                os.kill(os.getpid(), 9)   # mid-upload: marker never lands
        ChaosObjectStore.put(self, name, data)


if mode == "kill":
    tiered.MIRROR_STORE_FACTORY = lambda d: KillStore(
        LocalObjectStore(d), seed=seed, put_transient_rate=0.3,
        put_partial_rate=0.2, put_lost_rate=0.1)

model = get_preset("llama-tiny", vocab_size=64, hidden_size=32,
                   num_layers=1, num_heads=2, num_kv_heads=2,
                   intermediate_size=64, dtype=jnp.float32)
cfg = ta.Config(resilience=ta.ResilienceConfig(
    tiered_checkpointing=True,
    tiered_mirror_dir=os.path.join(base, "mirror")))
tr, _ = accelerate(model, None, cfg, optimizer=optax.adam(1e-3))
rng = np.random.default_rng(seed)
bs = [{"input_ids": rng.integers(0, 64, size=(8, 16)).astype(np.int32)}
      for _ in range(6)]
tr.fit(bs, max_steps=6, log_every=0,
       checkpoint_dir=os.path.join(base, "ckpt"), checkpoint_every=2)
digs = [hashlib.sha256(np.asarray(x).tobytes()).hexdigest()
        for x in jax.device_get(jax.tree.leaves(tr.state))]
with open(os.path.join(base, "ref.json"), "w") as f:
    json.dump(digs, f)
print("ok", flush=True)
"""


@pytest.mark.slow
def test_kill9_mid_mirror_upload_restart_restores_newest_tier(tmp_path):
    """kill -9 in the middle of step 6's tier-2 upload, under write
    faults: the torn mirror prefix is invisible (no marker), a fresh
    process restores step 6 from tier 1 bitwise, and with tier 1 burned
    the mirror serves its newest COMMITTED step."""
    from torchacc_tpu.checkpoint.tiered import TieredCheckpointManager
    env = dict(os.environ, CHAOS_SEED=str(CHAOS_SEED),
               JAX_PLATFORMS="cpu")
    ref_base, kill_base = str(tmp_path / "ref"), str(tmp_path / "kill")
    os.makedirs(ref_base), os.makedirs(kill_base)
    p = subprocess.run(
        [sys.executable, "-c", _TIERED_KILL_WORKER, ref_base, "ref"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, timeout=600)
    assert p.returncode == 0, p.stdout[-3000:]
    ref_digs = json.load(open(os.path.join(ref_base, "ref.json")))

    p = subprocess.run(
        [sys.executable, "-c", _TIERED_KILL_WORKER, kill_base, "kill"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, timeout=600)
    assert p.returncode == -9, p.stdout[-3000:]   # died by SIGKILL

    d = os.path.join(kill_base, "ckpt")
    mirror = os.path.join(kill_base, "mirror")
    # the interrupted upload is torn: payload objects, no marker
    assert os.path.isdir(os.path.join(mirror, "6"))
    assert not os.path.exists(os.path.join(mirror, "6", COMMIT_MARKER))
    assert TieredCheckpointManager._mirror_valid_steps(mirror) == [2, 4]

    def digs(tree):
        import hashlib
        return [hashlib.sha256(x.tobytes()).hexdigest()
                for x in _leaves(tree)]

    t = _trainer(None)              # same model: the abstract target
    abstract = t.abstract_state()
    mgr = TieredCheckpointManager(d, mirror_dir=mirror)
    try:
        state, step = mgr.restore_latest_valid(abstract)
    finally:
        mgr.shutdown()
    assert step == 6 and digs(state) == ref_digs   # newest tier, bitwise
    shutil.rmtree(d)                # tier 1 burned: committed mirror only
    counters.reset()
    mgr = TieredCheckpointManager(d, mirror_dir=mirror)
    try:
        state, step = mgr.restore_latest_valid(abstract)
    finally:
        mgr.shutdown()
    assert step == 4                # torn step 6 never offered
    assert counters.get("mirror_restores") == 1


# -- journal archive uploads --------------------------------------------------

def _append_pair(j, rid):
    j.accepted(rid=rid, trace_id=f"t{rid}", prompt_ids=[1, 2, 3],
               max_new_tokens=4, temperature=0.0, top_k=0, top_p=1.0,
               eos_id=None, seed=0, priority=0, deadline_unix=None)
    j.completed(rid=rid, tokens=[5, 6], finish_reason="stop")


def test_journal_archives_upload_on_rotation(tmp_path):
    from torchacc_tpu.serve.journal import (
        RequestJournal,
        read_archived_terminals,
        read_journal,
        replay_state,
    )
    store = LocalObjectStore(str(tmp_path / "store"))
    j = RequestJournal(str(tmp_path / "journal"), rotate_bytes=600,
                       archive_store=store)
    for rid in range(12):
        _append_pair(j, rid)
    j.close()
    assert j.rotations >= 2 and j.archive_uploads == j.rotations
    assert counters.get("journal_archive_uploads") == j.rotations
    # one sound two-phase commit per rotation, monotone sequence —
    # NOT the recycled local segment name (which would overwrite)
    commits = list_commits(store, "journal-archive")
    assert commits == [f"journal-archive/{i + 1:05d}"
                       for i in range(j.rotations)]
    for p in commits:
        assert verify_commit(store, p) == []
    # archived terminals are a subset of (and consistent with) the
    # authoritative local union
    _, completed, _ = replay_state(read_journal(str(tmp_path / "journal")))
    archived = read_archived_terminals(store)
    assert archived and {r["rid"] for r in archived} <= set(completed)


def test_journal_dead_archive_store_never_fails_rotation(tmp_path):
    from torchacc_tpu.serve.journal import RequestJournal, read_journal
    j = RequestJournal(
        str(tmp_path / "journal"), rotate_bytes=600,
        archive_store=ChaosObjectStore(LocalObjectStore(
            str(tmp_path / "store")), dead=True))
    for rid in range(12):
        _append_pair(j, rid)       # never raises
    j.close()
    assert j.rotations >= 2 and j.archive_uploads == 0
    assert counters.get("journal_archive_upload_failures") >= 1
    # local durability is untouched by the dead store
    recs = read_journal(str(tmp_path / "journal"))
    assert {r["rid"] for r in recs} == set(range(12))


_JOURNAL_KILL_WORKER = """
import json, os, sys
base, mode = sys.argv[1:3]
from torchacc_tpu.serve.journal import RequestJournal
from torchacc_tpu.store import LocalObjectStore


class KillStore(LocalObjectStore):
    def put(self, name, data):
        if mode == "kill" and name.startswith("journal-archive/00002/"):
            os.kill(os.getpid(), 9)   # after rotation, before upload
        LocalObjectStore.put(self, name, data)


j = RequestJournal(os.path.join(base, "journal"), rotate_bytes=600,
                   archive_store=KillStore(os.path.join(base, "store")))
progress = os.path.join(base, "progress.json")
for rid in range(60):
    j.accepted(rid=rid, trace_id=f"t{rid}", prompt_ids=[1, 2, 3],
               max_new_tokens=4, temperature=0.0, top_k=0, top_p=1.0,
               eos_id=None, seed=0, priority=0, deadline_unix=None)
    j.completed(rid=rid, tokens=[5, 6], finish_reason="stop")
    with open(progress, "w") as f:
        json.dump(rid + 1, f)
        f.flush()
        os.fsync(f.fileno())
print("done", flush=True)
"""


def test_kill9_between_rotation_and_upload_union_replays_100pct(tmp_path):
    """SIGKILL lands after the second rotation completed locally but
    before its archive upload: the local segment/archive union still
    replays every record, and the store shows only commit-marked
    (whole) segments."""
    from torchacc_tpu.serve.journal import (
        read_archived_terminals,
        read_journal,
        replay_state,
    )
    base = str(tmp_path)
    p = subprocess.run(
        [sys.executable, "-c", _JOURNAL_KILL_WORKER, base, "kill"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"), timeout=300)
    assert p.returncode == -9, p.stdout[-3000:]
    done = json.load(open(os.path.join(base, "progress.json")))
    assert done >= 1               # at least one full pair acknowledged
    pending, completed, shed = replay_state(
        read_journal(os.path.join(base, "journal")))
    # union replay 100%: every acknowledged pair survives the kill
    assert set(range(done)) <= set(completed)
    assert not shed
    store = LocalObjectStore(os.path.join(base, "store"))
    commits = list_commits(store, "journal-archive")
    assert commits == ["journal-archive/00001"]           # second killed
    archived = {r["rid"] for r in read_archived_terminals(store)}
    assert archived and archived <= set(completed)


# -- operator surface ---------------------------------------------------------

def test_inspect_mirror_flags_torn_and_corrupt(tmp_path, capsys):
    """``inspect --mirror`` renders the commit-marked truth: committed
    steps verify clean, marker-less payloads print TORN, checksum
    mismatches print CORRUPT."""
    from torchacc_tpu.checkpoint.cli import _print_tiers
    mirror = str(tmp_path / "mirror")
    store = LocalObjectStore(mirror)
    cli = _client(store)
    put_commit(cli, "2", {"w.bin": b"sound"})
    put_commit(cli, "4", {"w.bin": b"sound"})
    store.put("4/w.bin", b"nosnd")             # bit-rot under the marker
    store.put("6/w.bin", b"torn payload")      # no marker at all
    _print_tiers(str(tmp_path / "ckpt"), [2, 4], mirror)
    out = capsys.readouterr().out
    assert "step 2: tier1=committed tier2=committed" in out
    assert "step 4: tier1=committed tier2=CORRUPT" in out
    assert "step 6: tier1=missing tier2=TORN (no commit marker)" in out
