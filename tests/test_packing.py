"""Native sequence packer tests (C++ core + NumPy fallback parity)."""

import numpy as np
import pytest

from torchacc_tpu.data import packing
from torchacc_tpu.data.packing import pack_sequences


def _docs(seed=0, n=20, max_len=50):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, 100, size=rng.integers(1, max_len)).astype(np.int32)
            for _ in range(n)]


def _verify(docs, out, seq_len):
    for d, doc in enumerate(docs):
        mask = out["segment_ids"] == d
        got = out["input_ids"][mask]
        ln = min(len(doc), seq_len)
        np.testing.assert_array_equal(got, doc[:ln])
        np.testing.assert_array_equal(out["positions"][mask], np.arange(ln))
    # every padding position (segment -1) holds the pad token and pos 0
    pad = out["segment_ids"] == -1
    assert (out["input_ids"][pad] == 0).all()
    assert (out["positions"][pad] == 0).all()
    # and the total token count is conserved
    assert (~pad).sum() == sum(min(len(d), seq_len) for d in docs)


def test_pack_correctness_native():
    docs = _docs()
    out = pack_sequences(docs, seq_len=64)
    _verify(docs, out, 64)
    # efficiency: no more rows than naive one-doc-per-row
    assert out["input_ids"].shape[0] <= len(docs)


def test_pack_truncates_long_docs():
    docs = [np.arange(100, dtype=np.int32)]
    out = pack_sequences(docs, seq_len=32)
    assert out["input_ids"].shape == (1, 32)
    np.testing.assert_array_equal(out["input_ids"][0], np.arange(32))


def test_numpy_fallback_matches_native():
    if packing._load_native() is None:
        pytest.skip("no C++ toolchain; parity test meaningless")
    docs = _docs(seed=3)
    native = pack_sequences(docs, seq_len=48)
    # force fallback
    lib, tried = packing._LIB, packing._LIB_TRIED
    packing._LIB, packing._LIB_TRIED = None, True
    try:
        fallback = pack_sequences(docs, seq_len=48)
    finally:
        packing._LIB, packing._LIB_TRIED = lib, tried
    for k in native:
        np.testing.assert_array_equal(native[k], fallback[k])


def test_packed_batch_trains(devices):
    """Packed rows (segment ids + positions) feed the varlen attention."""
    import jax.numpy as jnp
    import optax

    import torchacc_tpu as ta
    from torchacc_tpu.models import get_preset
    from torchacc_tpu.train import accelerate

    rng = np.random.default_rng(0)
    docs = [rng.integers(0, 100, size=rng.integers(5, 30)).astype(np.int32)
            for _ in range(30)]
    packed = pack_sequences(docs, seq_len=32)
    rows = packed["input_ids"].shape[0]
    pad = (-rows) % 8
    batch = {k: np.concatenate([v, np.zeros((pad,) + v.shape[1:], v.dtype)])
             for k, v in packed.items()}
    # padding rows: segment -1 everywhere, harmless labels
    cfg = ta.Config(dist=ta.DistConfig(dp=ta.DPConfig(size=8)))
    mc = get_preset("llama-tiny", vocab_size=100, hidden_size=64,
                    num_layers=2, num_heads=4, num_kv_heads=2,
                    intermediate_size=128, dtype=jnp.float32)
    trainer, _ = accelerate(mc, None, cfg, optimizer=optax.adam(3e-3))
    trainer.init()
    losses = [float(trainer.step(batch)["loss"]) for _ in range(5)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
