"""SDC-defense tests: per-replica gradient digests, bit-flip chaos
localization (majority + recompute-arbiter + dp=1 spot check),
deterministic replay, bad-host quarantine, and the StepGuard EW-stats
persistence satellite.

``CHAOS_SEED`` (``make chaos-sdc`` runs 0..2) shifts the batch data and
the injected flip step so three different schedules exercise the same
guarantees — in particular that injection-free runs NEVER flag
(``sdc_mismatches == 0``) and that replay digests are bitwise identical
across invocations.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import torchacc_tpu as ta
from torchacc_tpu.checkpoint import CheckpointManager
from torchacc_tpu.errors import SDCError
from torchacc_tpu.models import get_preset
from torchacc_tpu.resilience import ChaosPlan, read_quarantined_hosts
from torchacc_tpu.resilience.sdc import (
    compare_replicas,
    divergence_report,
    flip_operands,
    host_digests,
    record_quarantine,
    replica_digests,
    zero_flip,
)
from torchacc_tpu.train import accelerate
from torchacc_tpu.utils.metrics import counters

pytestmark = pytest.mark.sdc

CHAOS_SEED = int(os.environ.get("CHAOS_SEED", "0"))


@pytest.fixture(autouse=True)
def _clean_counters():
    counters.reset()
    yield


def _model():
    return get_preset("llama-tiny", vocab_size=64, hidden_size=32,
                      num_layers=1, num_heads=2, num_kv_heads=2,
                      intermediate_size=64, dtype=jnp.float32)


def _batches(n, seed=None):
    rng = np.random.default_rng(CHAOS_SEED if seed is None else seed)
    return [{"input_ids": rng.integers(0, 64, size=(8, 16)).astype(np.int32)}
            for _ in range(n)]


def _trainer(ndev=8, **res_kwargs):
    """Trainer on the first ``ndev`` emulated devices, all data
    parallel (dp=ndev -> ndev digest replicas / simulated hosts)."""
    import optax
    cfg = ta.Config(dist=ta.DistConfig(dp=ta.DPConfig(size=ndev)),
                    resilience=ta.ResilienceConfig(**res_kwargs))
    cfg.get_mesh(jax.devices()[:ndev])
    tr, _ = accelerate(_model(), None, cfg, optimizer=optax.adam(1e-3))
    return tr


# -- digest fold units --------------------------------------------------------

def test_replica_digest_fold_detects_targeted_bitflip(devices):
    cfg = ta.Config()
    mesh = cfg.get_mesh()
    tree = {"a": jnp.arange(12.0).reshape(3, 4) - 5.0,
            "b": {"c": jnp.full((2,), 0.5)}}

    def run(flip):
        with jax.sharding.set_mesh(mesh):
            return np.asarray(jax.jit(
                lambda f: replica_digests(tree, f, mesh=mesh))(flip))

    clean = run(zero_flip(8))
    assert clean.shape == (8, 2, 3) and clean.dtype == np.uint32
    # all replicas fold the same replicated values -> identical rows
    sus, tie = compare_replicas(clean)
    assert sus is None and not tie

    flip = zero_flip(8)
    flip["mask"][3] = 1
    flip["xor"] = np.uint32(0x00400000)
    flipped = run(flip)
    sus, tie = compare_replicas(flipped)
    assert sus == [3] and not tie
    # every other row is bitwise untouched by the (inactive) flip path
    keep = [r for r in range(8) if r != 3]
    np.testing.assert_array_equal(flipped[keep], clean[keep])

    # leaf-targeted: leaf 0 untouched, leaf 1 diverges
    flip["leaf"] = np.asarray(1, np.int32)
    f2 = run(flip)
    np.testing.assert_array_equal(f2[3, 0], clean[3, 0])
    assert (f2[3, 1] != clean[3, 1]).any()


def test_compare_replicas_majority_and_tie():
    base = np.arange(12, dtype=np.uint32).reshape(1, 4, 3)
    d = np.repeat(base, 5, axis=0)
    assert compare_replicas(d) == (None, False)
    d[2, 1, 0] ^= 0x40
    assert compare_replicas(d) == ([2], False)
    # 2-2 split plus a matching pair is still a strict majority of 3?
    # no — flip two rows the SAME way: groups sized 3 and 2 -> minority
    d[4] = d[2]
    assert compare_replicas(d) == ([2, 4], False)
    # 1-vs-1: a tie, every replica suspect
    d2 = np.repeat(base, 2, axis=0)
    d2[1, 0, 0] ^= 1
    assert compare_replicas(d2) == ([0, 1], True)


def test_f32_sum_word_is_report_only():
    # the f32-sum word is an order-dependent float reduction: a
    # difference confined to it must NEVER flag a divergence (the
    # exact xor/sum words are the verdict)
    base = np.arange(12, dtype=np.uint32).reshape(1, 4, 3)
    d = np.repeat(base, 4, axis=0)
    d[2, 1, 2] ^= 0x1
    assert compare_replicas(d) == (None, False)


def test_unlocalized_tie_raises_but_never_quarantines(devices, tmp_path):
    # dp >= 4 even split: no pre-step snapshot exists, so the verdict
    # names the whole divergent set — and must NOT shrink the pod by
    # quarantining hosts it could not localize
    from torchacc_tpu.resilience.sdc import SDCMonitor
    cfg = ta.Config(resilience=ta.ResilienceConfig(
        sdc_check_interval_steps=1))
    mesh = cfg.get_mesh()
    mon = SDCMonitor(cfg.resilience, mesh, ["a", "b"],
                     run_dir=str(tmp_path))
    d = np.repeat(np.arange(6, dtype=np.uint32).reshape(1, 2, 3),
                  8, axis=0)
    d[4:, 0, 0] ^= 0x40  # 4-4 split
    with pytest.raises(SDCError) as ei:
        mon.observe(5, d, check=True, spot=False, recompute=None)
    assert ei.value.hosts == list(range(8))
    assert "NOT localized" in str(ei.value)
    assert read_quarantined_hosts(str(tmp_path)) == {}
    assert counters.get("replica_divergences") == 1


def test_divergence_report_names_first_leaf():
    d = np.zeros((2, 3, 3), np.uint32)
    d[1, 1] = [0xdead, 2, 3]
    lines = divergence_report(d, d[0], [1], ["p/a", "p/b", "p/c"],
                              [[0], [1]])
    assert len(lines) == 1
    assert "replica 1 (host 1)" in lines[0]
    assert "'p/b'" in lines[0] and "0x0000dead" in lines[0]
    assert "1/3 leaves" in lines[0]


def _dp2_monitor(tmp_path):
    from torchacc_tpu.resilience.sdc import SDCMonitor
    cfg = ta.Config(dist=ta.DistConfig(dp=ta.DPConfig(size=2)),
                    resilience=ta.ResilienceConfig(
                        sdc_check_interval_steps=1))
    mesh = cfg.get_mesh(jax.devices()[:2])
    return SDCMonitor(cfg.resilience, mesh, ["a", "b"],
                      run_dir=str(tmp_path))


def test_dp2_tie_third_execution_localizes_flaky_replica(
        devices, tmp_path):
    # dp=2 even split where in-step digest and recompute AGREE per
    # replica (neither self-localizes): the third execution gives
    # three samples — the replica whose three runs are not unanimous
    # is the intermittently flaky one, majority-voted and quarantined
    mon = _dp2_monitor(tmp_path)
    d = np.repeat(np.arange(6, dtype=np.uint32).reshape(1, 2, 3),
                  2, axis=0)
    d[1, 0, 0] ^= 0x40                        # 1-vs-1 tie
    runs = [d.copy(), d.copy()]               # redo, then third
    runs[1][1, 0, 0] ^= 0x7                   # replica 1 flakes again
    calls = iter(runs)
    with pytest.raises(SDCError) as ei:
        mon.observe(5, d, check=True, spot=False,
                    recompute=lambda: next(calls))
    assert counters.get("sdc_third_executions") == 1
    assert ei.value.kind == "replica"
    assert ei.value.hosts == sorted({h for h in mon.replica_hosts[1]})
    assert read_quarantined_hosts(str(tmp_path))  # localized verdict


def test_dp2_tie_three_way_unanimous_stays_unlocalized(
        devices, tmp_path):
    # every execution of every replica reproduces its own digests:
    # persistent, unattributed corruption — named, never quarantined
    mon = _dp2_monitor(tmp_path)
    d = np.repeat(np.arange(6, dtype=np.uint32).reshape(1, 2, 3),
                  2, axis=0)
    d[1, 0, 0] ^= 0x40
    with pytest.raises(SDCError) as ei:
        mon.observe(5, d, check=True, spot=False,
                    recompute=lambda: d.copy())
    assert counters.get("sdc_third_executions") == 1
    assert ei.value.hosts == [0, 1]           # the whole divergent set
    assert "NOT localized" in str(ei.value)
    assert read_quarantined_hosts(str(tmp_path)) == {}


def test_flip_operands_inactive_without_plan():
    ops = flip_operands(3, 4, [[0], [1], [2], [3]], ["a", "b"], "step")
    assert not ops["mask"].any() and int(ops["leaf"]) == -1
    plan = ChaosPlan(seed=CHAOS_SEED).flip_bits(host=2, at=3, leaf="b")
    with plan:
        # wrong step / wrong where -> zeros
        assert not flip_operands(2, 4, [[0], [1], [2], [3]], ["a", "b"],
                                 "step")["mask"].any()
        assert not flip_operands(3, 4, [[0], [1], [2], [3]], ["a", "b"],
                                 "recompute")["mask"].any()
        ops = flip_operands(3, 4, [[0], [1], [2], [3]], ["a", "b"],
                            "step")
        assert list(ops["mask"]) == [0, 0, 1, 0]
        assert int(ops["leaf"]) == 1
    assert plan.stats()["sdc.flip_bits"]["hits"] == 1


def test_config_sdc_validation():
    with pytest.raises(ta.ConfigError):
        ta.Config.from_dict({"resilience": {"sdc_check_interval_steps": 0}})
    with pytest.raises(ta.ConfigError):
        ta.Config.from_dict(
            {"resilience": {"sdc_recompute_interval_steps": -1}})
    cfg = ta.Config.from_dict(
        {"resilience": {"sdc_check_interval_steps": 5, "sdc_abort": False}})
    assert cfg.resilience.sdc_check_interval_steps == 5
    assert cfg.to_dict()["resilience"]["sdc_abort"] is False


def test_quarantine_record_merges(tmp_path):
    d = str(tmp_path)
    record_quarantine(d, [3], step=10, kind="replica", report=["r3"])
    record_quarantine(d, [5], step=12, kind="recompute", report=["r5"])
    q = read_quarantined_hosts(d)
    assert set(q) == {3, 5}
    assert q[3]["step"] == 10 and q[5]["kind"] == "recompute"
    assert read_quarantined_hosts(str(tmp_path / "nope")) == {}


# -- end-to-end: clean runs never flag ----------------------------------------

def test_clean_run_no_mismatches(devices):
    t = _trainer(sdc_check_interval_steps=1,
                 sdc_recompute_interval_steps=2)
    t.fit(_batches(4), max_steps=4, log_every=0)
    assert counters.get("sdc_checks") == 4
    assert counters.get("sdc_mismatches") == 0
    assert counters.get("replica_divergences") == 0
    assert int(t.state.step) == 4


# -- end-to-end: bit-flip localization ----------------------------------------

def test_flip_bits_localized_by_majority(devices, tmp_path):
    k = 1 + CHAOS_SEED % 3
    host = 2 + CHAOS_SEED % 3
    md = str(tmp_path / "run")
    t = _trainer(sdc_check_interval_steps=1)
    with pytest.raises(SDCError) as ei:
        with ChaosPlan(seed=CHAOS_SEED).flip_bits(host=host, at=k):
            t.fit(_batches(6), max_steps=6, log_every=0, metrics_dir=md)
    e = ei.value
    assert e.hosts == [host]
    assert e.kind == "replica"
    assert e.step == k
    assert e.report and f"host {host}" in e.report[0]
    assert counters.get("replica_divergences") == 1
    assert counters.get("sdc_mismatches") == 1
    # the suspect is on file for the supervisor / the next restart
    q = read_quarantined_hosts(md)
    assert host in q and q[host]["step"] == k


def test_flip_bits_dp2_tie_arbitrated_by_recompute(devices):
    k = 1 + CHAOS_SEED % 2
    t = _trainer(ndev=2, sdc_check_interval_steps=1)
    with pytest.raises(SDCError) as ei:
        with ChaosPlan(seed=CHAOS_SEED).flip_bits(host=1, at=k):
            t.fit(_batches(4), max_steps=4, log_every=0)
    # a 1-vs-1 divergence cannot be localized by majority: the
    # redundant re-execution (clean bits) singles out host 1
    assert ei.value.hosts == [1]
    assert ei.value.step == k
    assert counters.get("replica_divergences") == 1


def test_optimizer_digest_clean_run_never_flags(devices):
    t = _trainer(sdc_check_interval_steps=1, sdc_digest_optimizer=True)
    t.fit(_batches(4), max_steps=4, log_every=0)
    assert counters.get("sdc_checks") == 4
    assert counters.get("sdc_mismatches") == 0
    # the digest matrix carries both regions, named apart
    paths = t._sdc_monitor.leaf_paths
    n = len(paths)
    assert n % 2 == 0
    assert all(p.startswith("grads/") for p in paths[:n // 2])
    assert all(p.startswith("params/") for p in paths[n // 2:])


def test_optimizer_digest_surfaces_post_apply_corruption_same_step(devices):
    """The carried-over PR-4 gap: corruption in the optimizer apply used
    to surface one step late (through the NEXT step's gradients).  With
    sdc_digest_optimizer the post-apply param rows ride the digest
    matrix, so a flip targeted at a params/ leaf is flagged at exactly
    the step it happens — with the report naming the params region."""
    k = 1 + CHAOS_SEED % 3
    host = 2 + CHAOS_SEED % 3
    t = _trainer(sdc_check_interval_steps=1, sdc_digest_optimizer=True)
    with pytest.raises(SDCError) as ei:
        with ChaosPlan(seed=CHAOS_SEED).flip_bits(
                host=host, at=k, leaf="params/"):
            t.fit(_batches(6), max_steps=6, log_every=0)
    e = ei.value
    assert e.hosts == [host]
    assert e.step == k                 # the step it happens, not k + 1
    assert e.report and "params/" in e.report[0]


def test_recompute_spot_check_catches_dp1_flakiness(devices):
    k = 1 + CHAOS_SEED % 2
    t = _trainer(ndev=1, sdc_recompute_interval_steps=1)
    with pytest.raises(SDCError) as ei:
        with ChaosPlan(seed=CHAOS_SEED).flip_bits(host=0, at=k,
                                                  where="recompute"):
            t.fit(_batches(4), max_steps=4, log_every=0)
    assert ei.value.kind == "recompute"
    assert ei.value.hosts == [0]
    assert counters.get("replica_divergences") == 0  # nothing to compare


def test_sdc_abort_off_counts_and_quarantines_only(devices, tmp_path):
    md = str(tmp_path / "run")
    t = _trainer(sdc_check_interval_steps=1, sdc_abort=False)
    with ChaosPlan(seed=CHAOS_SEED).flip_bits(host=4, at=1):
        hist = t.fit(_batches(4), max_steps=4, log_every=1,
                     metrics_dir=md)
    assert int(t.state.step) == 4  # the run was not aborted
    assert counters.get("sdc_mismatches") == 1
    assert 4 in read_quarantined_hosts(md)
    # counters ride the step records / metrics.jsonl
    assert hist[-1]["sdc_mismatches"] == 1
    assert hist[-1]["sdc_checks"] == 4
    rec = [json.loads(l) for l in
           open(os.path.join(md, "metrics.jsonl"))][-1]
    assert rec["train/sdc_mismatches"] == 1


def test_sdc_host_step_resyncs_after_restore(devices, tmp_path):
    """In-process supervisor pattern: a same-Trainer fit(resume='auto')
    must re-derive the SDC step index from the restored state — verdict
    steps and chaos `at=` indices stay aligned with real steps."""
    d = str(tmp_path / "ckpt")
    bs = _batches(4)
    t = _trainer(sdc_check_interval_steps=1)
    t.fit(bs, max_steps=2, log_every=0, checkpoint_dir=d,
          checkpoint_every=2)
    assert t._host_step == 2
    t._host_step = 99  # simulate a stale index from a failed run
    t.fit(bs, max_steps=4, log_every=0, checkpoint_dir=d,
          checkpoint_every=1000, resume="auto")
    assert t._host_step == 4  # re-derived from restored step 2
    assert counters.get("sdc_checks") == 4  # 2 + 2, no phantom indices


# -- deterministic replay -----------------------------------------------------

def test_replay_bitwise_equivalence(devices, tmp_path):
    d = str(tmp_path / "ckpt")
    bs = _batches(6)
    t = _trainer()
    t.fit(bs, max_steps=6, log_every=0, checkpoint_dir=d,
          checkpoint_every=2)

    tr_r = _trainer()
    r1 = tr_r.fit(bs, replay_step=2, checkpoint_dir=d, log_every=0)
    # the forced digest program is scoped to the replay: a later fit on
    # this trainer keeps its zero-overhead (digest-free) step program
    assert tr_r._sdc_on is False and tr_r._train_step is None
    r2 = _trainer().fit(bs, replay_step=2, checkpoint_dir=d, log_every=0)
    assert r1[0]["replay_step"] == 2 and r1[0]["step"] == 2
    assert r1[0]["deterministic"] and r2[0]["deterministic"]
    # same checkpoint + same loader position => identical digests
    assert r1[0]["digests"] == r2[0]["digests"]
    assert r1[0]["loss"] == r2[0]["loss"]
    # a different step replays different grads
    r3 = _trainer().fit(bs, replay_step=4, checkpoint_dir=d, log_every=0)
    assert r3[0]["digests"] != r1[0]["digests"]


def test_replay_requires_checkpoint(tmp_path):
    from torchacc_tpu.errors import (
        CheckpointNotFoundError,
        TrainerStateError,
    )
    t = _trainer()
    with pytest.raises(TrainerStateError):
        t.fit(_batches(2), replay_step=1)
    d = str(tmp_path / "ckpt")
    t2 = _trainer()
    t2.fit(_batches(2), max_steps=2, log_every=0, checkpoint_dir=d,
           checkpoint_every=2)
    t3 = _trainer()
    with pytest.raises(CheckpointNotFoundError):
        t3.fit(_batches(2), replay_step=7, checkpoint_dir=d)
    # the forced digest program must not leak past a FAILED replay
    assert t3._sdc_on is False


# -- CLI `replay` (offline checkpoint digests) --------------------------------

def test_cli_replay_digests(tmp_path, capsys):
    from torchacc_tpu.checkpoint.cli import main
    d = str(tmp_path / "mgr")
    mgr = CheckpointManager(d)
    state = {"a": jnp.arange(4.0), "b": {"c": jnp.ones((2, 2)) * 3}}
    mgr.save(1, state)
    mgr.close()
    assert main(["replay", d, "--step", "1"]) == 0
    out = capsys.readouterr().out
    assert "a: xor=0x" in out and "b/c: xor=0x" in out
    assert main(["replay", d, "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["step"] == 1
    assert set(payload["digests"]) == {"a", "b/c"}
    # order-independent content digest: identical values -> identical
    # words, a changed value -> different words
    again = host_digests(jax.device_get(state))
    assert {k: {w: v[w] for w in ("bits_xor", "bits_sum")}
            for k, v in again.items()} \
        == {k: {w: v[w] for w in ("bits_xor", "bits_sum")}
            for k, v in payload["digests"].items()}
    other = host_digests({"a": np.arange(4.0, dtype=np.float32) + 1,
                          "b": {"c": np.ones((2, 2), np.float32) * 3}})
    assert other["a"]["bits_xor"] != again["a"]["bits_xor"]


# -- satellite: StepGuard EW statistics survive resume ------------------------

def test_guard_statistics_persist_and_restore(tmp_path):
    d = str(tmp_path / "ckpt")
    bs = _batches(6)
    kw = dict(spike_guard=True, spike_warmup_steps=2)
    t = _trainer(**kw)
    t.fit(bs, max_steps=6, log_every=0, checkpoint_dir=d,
          checkpoint_every=2)
    want = jax.device_get(t._guard_state)
    assert int(want["count"]) == 6
    # the sidecar rides every committed step
    assert os.path.exists(os.path.join(d, "6", "guard_state.json"))

    t2 = _trainer(**kw)
    t2.fit(bs, max_steps=6, log_every=0, checkpoint_dir=d,
           checkpoint_every=1000, resume="auto")
    got = jax.device_get(t2._guard_state)
    # bit-exact restore: the spike guard does NOT re-warm
    assert int(got["count"]) == 6
    np.testing.assert_array_equal(np.asarray(want["mean"]),
                                  np.asarray(got["mean"]))
    np.testing.assert_array_equal(np.asarray(want["var"]),
                                  np.asarray(got["var"]))


def test_guard_restore_tolerates_missing_sidecar(tmp_path):
    d = str(tmp_path / "ckpt")
    bs = _batches(4)
    kw = dict(spike_guard=True, spike_warmup_steps=2)
    t = _trainer(**kw)
    t.fit(bs, max_steps=4, log_every=0, checkpoint_dir=d,
          checkpoint_every=2)
    os.remove(os.path.join(d, "4", "guard_state.json"))
    t2 = _trainer(**kw)
    t2.fit(bs, max_steps=4, log_every=0, checkpoint_dir=d,
           checkpoint_every=1000, resume="auto")  # re-warms, no crash
    assert int(t2.state.step) == 4


# -- 2-process DP=2 fixture (the acceptance proof) ----------------------------

_SDC_WORKER = """
import os, sys, time
port, pid, base = sys.argv[1], int(sys.argv[2]), sys.argv[3]
flip_at = int(sys.argv[4])
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
jax.config.update("jax_platforms", "cpu")
from torchacc_tpu.parallel.distributed import initialize_distributed
initialize_distributed(coordinator_address=f"localhost:{port}",
                       num_processes=2, process_id=pid)
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 2, len(jax.devices())

import numpy as np
import jax.numpy as jnp
import optax
import torchacc_tpu as ta
from torchacc_tpu.models import get_preset
from torchacc_tpu.train import accelerate
from torchacc_tpu.resilience import ChaosPlan, read_quarantined_hosts
from torchacc_tpu.errors import SDCError
from torchacc_tpu.utils.metrics import counters
from jax.experimental import multihost_utils
from jax.sharding import PartitionSpec as PS

cfg = ta.Config(dist=ta.DistConfig(dp=ta.DPConfig(size=2)),
                resilience=ta.ResilienceConfig(sdc_check_interval_steps=1))
mc = get_preset("llama-tiny", vocab_size=64, hidden_size=32, num_layers=1,
                num_heads=2, num_kv_heads=2, intermediate_size=64,
                dtype=jnp.float32)
trainer, _ = accelerate(mc, None, cfg, optimizer=optax.sgd(1e-2))
trainer.init()
trainer._sdc_run_dir = base  # quarantine records land here

def gbatch(i):
    # each process feeds its own dp shard (genuinely different data)
    local = np.random.default_rng(1000 * i + pid).integers(
        0, 64, (4, 16)).astype(np.int32)
    arr = multihost_utils.host_local_array_to_global_array(
        local, trainer.mesh, PS(("dp", "fsdp"), ("sp", "spu")))
    return {"input_ids": arr}

# injection-free steps: checked every step, never flagged
for i in range(flip_at):
    trainer.step(gbatch(i))
assert counters.get("sdc_checks") == flip_at, counters.snapshot()
assert counters.get("sdc_mismatches") == 0, counters.snapshot()

# flip bits on HOST 1 only: the 1-vs-1 replica divergence is
# arbitrated by the recompute and localized to host 1 on BOTH hosts
err = None
try:
    with ChaosPlan(seed=0).flip_bits(host=1, at=flip_at):
        trainer.step(gbatch(flip_at))
except SDCError as e:
    err = e
assert err is not None, "SDCError not raised"
assert err.hosts == [1], err.hosts
assert err.step == flip_at, err.step
assert counters.get("sdc_mismatches") == 1, counters.snapshot()

# the primary recorded the quarantine on the shared run dir
deadline = time.time() + 30
q = {}
while time.time() < deadline:
    q = read_quarantined_hosts(base)
    if q:
        break
    time.sleep(0.2)
assert 1 in q, q
print(f"proc {pid} ok sdc hosts={err.hosts} step={err.step}", flush=True)
"""


@pytest.mark.slow
@pytest.mark.multihost
def test_two_process_dp2_flip_localized_to_host1(tmp_path):
    """The acceptance fixture: two jax.distributed CPU processes form a
    DP=2 mesh (one replica per host).  Injection-free steps pass with
    ``sdc_mismatches == 0``; then ``flip_bits(host=1)`` corrupts host
    1's view of the grads and BOTH processes must raise ``SDCError``
    naming host 1 — localized through the recompute arbiter, with the
    quarantine record visible in the shared run dir."""
    import socket
    import subprocess
    import sys

    flip_at = 1 + CHAOS_SEED % 2
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    procs = [subprocess.Popen(
        [sys.executable, "-c", _SDC_WORKER, str(port), str(i),
         str(tmp_path / "shared_run"), str(flip_at)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for i in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    finally:
        for p in procs:
            p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-3000:]}"
        assert f"proc {i} ok sdc hosts=[1]" in out, out[-2000:]
