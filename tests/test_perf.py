"""Hot-loop desynchronization tests: dispatch pipelining
(``perf.dispatch_depth``), lagged guard/SDC verdicts, host_blocked_ms
accounting, the batched eval fetch, and the SDC digest subsample bound.

The contracts under test (docs/performance.md):

- ``dispatch_depth`` NEVER changes the math: step records (step, loss)
  and final params are bitwise identical at every depth;
- the guard still aborts — within N+k instead of after N — with the
  anomaly attributed to the step that produced it;
- SDC verdicts under lag name the same host and the same step as the
  unpipelined loop, and chaos injections still localize;
- every fit record carries ``host_blocked_ms`` + ``dispatch_depth``.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import torchacc_tpu as ta
from torchacc_tpu.errors import AnomalyError, SDCError
from torchacc_tpu.models import get_preset
from torchacc_tpu.resilience import ChaosLoader, ChaosPlan, chaos_loss
from torchacc_tpu.train import accelerate
from torchacc_tpu.utils.metrics import BlockedMeter, counters

pytestmark = pytest.mark.perf

CHAOS_SEED = int(os.environ.get("CHAOS_SEED", "0"))


@pytest.fixture(autouse=True)
def _clean_counters():
    counters.reset()
    yield


def _model():
    return get_preset("llama-tiny", vocab_size=64, hidden_size=32,
                      num_layers=1, num_heads=2, num_kv_heads=2,
                      intermediate_size=64, dtype=jnp.float32)


def _batches(n, seed=None):
    rng = np.random.default_rng(CHAOS_SEED if seed is None else seed)
    return [{"input_ids": rng.integers(0, 64, size=(8, 16)).astype(np.int32)}
            for _ in range(n)]


def _trainer(depth=1, dp=None, loss=None, **res_kwargs):
    import optax
    dist = (ta.DistConfig(dp=ta.DPConfig(size=dp)) if dp
            else ta.DistConfig())
    cfg = ta.Config(dist=dist,
                    resilience=ta.ResilienceConfig(**res_kwargs),
                    perf=ta.PerfConfig(dispatch_depth=depth))
    if dp:
        cfg.get_mesh(jax.devices()[:dp])
    tr, _ = accelerate(_model(), None, cfg, optimizer=optax.adam(1e-3),
                       loss=loss)
    return tr


def _det(history):
    """The deterministic projection of a record list."""
    return [(r["step"], r["loss"]) for r in history]


# -- config / units -----------------------------------------------------------

def test_perf_config_validation():
    with pytest.raises(ta.ConfigError):
        ta.Config(perf=ta.PerfConfig(dispatch_depth=0)).validate()
    ta.Config(perf=ta.PerfConfig(dispatch_depth=4)).validate()
    with pytest.raises(ta.ConfigError):
        ta.Config(resilience=ta.ResilienceConfig(
            sdc_digest_max_elems=0)).validate()


def test_blocked_meter_accumulates_and_takes():
    m = BlockedMeter()
    with m.blocked():
        pass
    with m.blocked():
        pass
    assert m.peek_ms() >= 0.0
    v = m.take_ms()
    assert v >= 0.0
    assert m.peek_ms() == 0.0 and m.take_ms() == 0.0


def test_micro_split_spec_natural_factorisations(devices):
    from jax.sharding import Mesh, PartitionSpec as P

    from torchacc_tpu.parallel.sharding import micro_split_spec
    mesh = Mesh(np.asarray(devices[:4]).reshape(2, 2), ("dp", "fsdp"))
    # M fully tiled by a leading run -> rows unsharded
    assert micro_split_spec(("dp", "fsdp"), mesh, 4, 2, 4) == \
        P(("dp", "fsdp"), None, None, None)
    # leading run covers M exactly, remainder tiles the rows
    assert micro_split_spec(("dp", "fsdp"), mesh, 2, 4, 3) == \
        P(("dp",), ("fsdp",), None)
    # no per-dim factorisation exists
    assert micro_split_spec(("dp", "fsdp"), mesh, 3, 4, 3) is None


def test_leaf_digest_subsample_deterministic_and_flip_sensitive():
    from torchacc_tpu.resilience.sdc import _leaf_digest
    x = jnp.asarray(np.random.default_rng(0).normal(size=(64, 64)),
                    jnp.float32)
    hit_no = jnp.zeros((), bool)
    hit_yes = jnp.ones((), bool)
    mask = jnp.asarray(0x00010000, jnp.uint32)
    full = _leaf_digest(x, hit_no, mask)
    a = _leaf_digest(x, hit_no, mask, max_elems=100)
    b = _leaf_digest(x, hit_no, mask, max_elems=100)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # a bound below the leaf size changes what is folded
    assert not np.array_equal(np.asarray(a), np.asarray(full))
    # element 0 (the chaos flip site) is always inside the subsample
    f = _leaf_digest(x, hit_yes, mask, max_elems=100)
    assert not np.array_equal(np.asarray(a)[:2], np.asarray(f)[:2])


# -- pipelining equivalence ---------------------------------------------------

def test_loss_trajectory_bitwise_unchanged_by_dispatch_depth(devices):
    hist = {}
    params = {}
    for depth in (1, 3):
        t = _trainer(depth=depth)
        hist[depth] = t.fit(_batches(7, seed=1), max_steps=7, log_every=1)
        params[depth] = jax.device_get(t.state.params)
        assert t.pending == 0  # fit drains the ring
    assert _det(hist[1]) == _det(hist[3])
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(a, b),
                 params[1], params[3])


def test_records_under_lag_cover_the_drained_tail(devices):
    t = _trainer(depth=4)
    h = t.fit(_batches(6, seed=2), max_steps=6, log_every=1)
    assert [r["step"] for r in h] == list(range(6))


def test_step_records_emit_host_blocked_ms_and_depth(devices):
    for depth in (1, 2):
        t = _trainer(depth=depth, nan_guard=True)
        h = t.fit(_batches(4, seed=3), max_steps=4, log_every=1)
        assert h, "no records logged"
        for rec in h:
            assert rec["host_blocked_ms"] >= 0.0
            assert rec["dispatch_depth"] == depth


def test_eval_losses_batched_fetch_match_scalar_path(devices):
    evs = _batches(3, seed=5)
    t1 = _trainer(depth=1)
    h1 = t1.fit(_batches(4, seed=4), max_steps=4, log_every=1,
                eval_loader=evs, eval_every=2)
    t2 = _trainer(depth=3)
    h2 = t2.fit(_batches(4, seed=4), max_steps=4, log_every=1,
                eval_loader=evs, eval_every=2)
    r1 = [r for r in h1 if "eval_loss" in r]
    r2 = [r for r in h2 if "eval_loss" in r]
    assert r1 and [r["step"] for r in r1] == [r["step"] for r in r2]
    # the manual mean of one scalar eval pass must agree with depth 1's
    # batched fetch (same state: eval at record step r ran on the state
    # after r+1 optimizer steps at depth 1)
    t3 = _trainer(depth=1)
    t3.fit(_batches(3, seed=4), max_steps=3, log_every=0)
    want = sum(float(t3.eval_step(b)) for b in evs) / len(evs)
    got = [r["eval_loss"] for r in r1 if r["step"] == 2][0]
    assert got == pytest.approx(want, abs=0.0)


# -- resilience guarantees under lag ------------------------------------------

def test_guard_aborts_within_n_plus_k_with_step_attribution(devices):
    """NaN injected from step 2 on, max_consecutive_anomalies=3: the
    abort names step 4 (the third consecutive anomaly) at EVERY depth;
    with k steps in flight the raise lands while step 4+k is already
    dispatched — abort-within-N+k, never missed."""
    for depth in (1, 3):
        counters.reset()
        bs = _batches(8, seed=6)
        t = _trainer(depth=depth, loss=chaos_loss(), nan_guard=True,
                     max_consecutive_anomalies=3)
        with pytest.raises(AnomalyError) as ei:
            t.fit(ChaosLoader(bs, nan_loss_steps={2, 3, 4, 5, 6, 7}),
                  max_steps=8, log_every=0)
        assert ei.value.step == 4
        assert ei.value.consecutive == 3
        assert counters.get("anomalies_skipped") == 3
        # the state really ran ahead of the verdict (the pipeline), but
        # never past the abort bound N+k
        assert 5 <= int(t.state.step) <= 5 + (depth - 1)


def test_sdc_flip_verdict_names_same_host_and_step_under_lag(devices):
    at = 1 + CHAOS_SEED % 3
    host = 2 + CHAOS_SEED % 3
    got = {}
    for depth in (1, 3):
        counters.reset()
        t = _trainer(depth=depth, dp=8, sdc_check_interval_steps=1)
        with pytest.raises(SDCError) as ei:
            with ChaosPlan(seed=CHAOS_SEED).flip_bits(host=host, at=at):
                t.fit(_batches(6), max_steps=6, log_every=0)
        got[depth] = (ei.value.hosts, ei.value.step, ei.value.kind)
        assert counters.get("sdc_mismatches") == 1
    assert got[1] == got[3] == ([host], at, "replica")


def test_sdc_clean_run_under_lag_never_flags(devices):
    t = _trainer(depth=3, dp=8, sdc_check_interval_steps=1,
                 sdc_recompute_interval_steps=2)
    t.fit(_batches(5), max_steps=5, log_every=0)
    assert counters.get("sdc_checks") == 5
    assert counters.get("sdc_mismatches") == 0


def test_sdc_digest_subsample_bound_still_localizes(devices):
    at = 1 + CHAOS_SEED % 2
    host = 3
    t = _trainer(depth=2, dp=8, sdc_check_interval_steps=1,
                 sdc_digest_max_elems=64)
    with pytest.raises(SDCError) as ei:
        with ChaosPlan(seed=CHAOS_SEED).flip_bits(host=host, at=at):
            t.fit(_batches(5), max_steps=5, log_every=0)
    assert ei.value.hosts == [host] and ei.value.step == at
    # and a clean bounded run never flags
    counters.reset()
    t2 = _trainer(depth=2, dp=8, sdc_check_interval_steps=1,
                  sdc_digest_max_elems=64)
    t2.fit(_batches(4), max_steps=4, log_every=0)
    assert counters.get("sdc_mismatches") == 0


def test_stale_ring_cleared_on_fit_entry(devices, tmp_path):
    """An exceptional exit (abort raise) leaves in-flight entries; a
    later fit on the same Trainer must not resolve them into its own
    timeline (phantom records / misattributed verdicts) — the ring is
    cleared at fit entry even when no restore runs."""
    t = _trainer(depth=3, loss=chaos_loss(), nan_guard=True,
                 max_consecutive_anomalies=1)
    with pytest.raises(AnomalyError):
        t.fit(ChaosLoader(_batches(8, seed=9), nan_loss_steps={2}),
              max_steps=8, log_every=0)
    assert t.pending > 0  # the abort left steps 3,4 unresolved
    # resume='auto' on an empty dir -> "starting fresh" (no restore,
    # so _adopt_restored never runs) — the documented supervisor path
    h = t.fit(_batches(4, seed=10), max_steps=4, log_every=1,
              checkpoint_dir=str(tmp_path / "ckpt"), resume="auto")
    # dispatch had reached step 5 when the abort raised; the new run's
    # records start there — no stale step-3/4 entries leak in
    assert [r["step"] for r in h] == [5, 6, 7, 8]
    assert t.pending == 0


def test_returned_metrics_dict_mutation_safe_under_lag(devices):
    """The pre-PR API let callers mutate the returned metrics dict
    freely (observation completed inside step()); under lag the ring
    keeps its own shallow copy, so caller mutation cannot corrupt the
    resolution k steps later."""
    t = _trainer(depth=2, nan_guard=True)
    for b in _batches(4, seed=11):
        t.step(b).clear()
    t.drain()  # would KeyError on the guard fetch if the entry aliased
    assert counters.get("anomalies_skipped") == 0


def test_rerun_closure_immune_to_batch_dict_reuse(devices):
    """A loader that reuses ONE batch dict per step (mutating it in
    place) must not change what a lagged recompute re-executes — the
    rerun closure captures a shallow copy, so a healthy run never
    raises a spurious SDC mismatch."""
    t = _trainer(depth=2, sdc_recompute_interval_steps=1)
    shared = {}
    for b in _batches(4, seed=12):
        shared.clear()
        shared.update(b)
        t.step(shared)
    t.drain()
    assert counters.get("sdc_checks") == 4
    assert counters.get("sdc_mismatches") == 0


def test_blocked_meter_reset_at_fit_entry(devices):
    """host_blocked_ms on the first fit record must not include time
    accrued before fit (warm-up steps, a previous run)."""
    import time as _t
    t = _trainer(depth=1)
    with t.blocked.blocked():
        _t.sleep(0.3)  # pre-fit blocked time: must be discarded
    h = t.fit(_batches(2, seed=13), max_steps=2, log_every=1)
    assert h and h[0]["host_blocked_ms"] < 250.0


def test_resolved_entry_releases_arbiter_snapshot(devices):
    """resolve_oldest must drop the rerun closure (which captures a
    state-sized dp<=2 arbiter snapshot) and the digest matrix once the
    verdict is recorded — last_resolved keeps the entry alive, and the
    documented memory budget peaks at the in-flight count only."""
    t = _trainer(depth=2, dp=2, sdc_check_interval_steps=1)
    t.fit(_batches(3), max_steps=3, log_every=0)
    assert counters.get("sdc_checks") == 3
    e = t.last_resolved
    assert e is not None and e.sdc_check
    assert e.rerun is None and e.digests is None


def test_checkpoint_never_commits_unverdicted_step(devices, tmp_path):
    """Verdict-before-durability: with k steps in flight, an interval
    save first drains the ring — so a step flagged by SDC can never
    become a durable checkpoint the quarantine->restart flow would
    resume from."""
    from torchacc_tpu.checkpoint.io import CheckpointManager
    at, host = 2, 3
    d = str(tmp_path / "ckpt")
    t = _trainer(depth=4, dp=8, sdc_check_interval_steps=1)
    with pytest.raises(SDCError) as ei:
        with ChaosPlan(seed=CHAOS_SEED).flip_bits(host=host, at=at):
            t.fit(_batches(8), max_steps=8, log_every=0,
                  checkpoint_dir=d, checkpoint_every=1)
    assert ei.value.step == at
    # saves are labelled step+1 (completed-step count): the newest
    # durable checkpoint is from BEFORE the flagged step's update, even
    # though the pipeline had dispatched well past it
    steps = CheckpointManager(d).valid_steps()
    assert steps and max(steps) <= at


def test_chaos_hang_still_trips_watchdog_under_lag(tmp_path):
    bs = _batches(6, seed=7)
    t = _trainer(depth=2, loss=chaos_loss(), step_deadline_s=0.15)
    with ChaosPlan(seed=CHAOS_SEED).hang("trainer.step", seconds=0.6):
        t.fit(ChaosLoader(bs), max_steps=6, log_every=0,
              metrics_dir=str(tmp_path))
    assert counters.get("watchdog_stalls") >= 1


def test_resume_resyncs_host_step_under_lag(devices, tmp_path):
    d = str(tmp_path / "ckpt")
    bs = _batches(6, seed=8)
    t = _trainer(depth=3, dp=8, sdc_check_interval_steps=1)
    t.fit(bs, max_steps=3, log_every=0, checkpoint_dir=d,
          checkpoint_every=3)
    assert t._host_step == 3 and t.pending == 0
    t.fit(bs, max_steps=6, log_every=0, checkpoint_dir=d,
          checkpoint_every=1000, resume="auto")
    assert t._host_step == 6
    assert counters.get("sdc_checks") == 6  # no phantom verdict steps
