"""Tensor-parallel correctness: tp=N training must match tp=1 exactly
(up to float reduction order), and params must actually shard on 'tp'.
Reference analogue: GSPMD TP via mark_sharding (tp.py) composed with
SPMD-FSDP mesh axis 'tensor' (spmd_fsdp.py:75-84)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import torchacc_tpu as ta
from torchacc_tpu.models import get_preset
from torchacc_tpu.train import accelerate


def _model():
    return get_preset("llama-tiny", vocab_size=128, hidden_size=64,
                      num_layers=2, num_heads=8, num_kv_heads=4,
                      intermediate_size=128, dtype=jnp.float32)


def _batches(n, seed=0):
    rng = np.random.default_rng(seed)
    data = rng.integers(0, 128, size=(4, 32))
    for _ in range(n):
        yield {"input_ids": data[rng.integers(0, 4, size=8)].astype(np.int32)}


def test_tp_matches_single_device(devices):
    import optax
    batches = list(_batches(5))

    cfg_tp = ta.Config(dist=ta.DistConfig(tp=ta.TPConfig(size=8)))
    t_tp, _ = accelerate(_model(), None, cfg_tp, optimizer=optax.adam(1e-3))
    t_tp.init()
    losses_tp = [float(t_tp.step(b)["loss"]) for b in batches]

    cfg_1 = ta.Config(dist=ta.DistConfig(dp=ta.DPConfig(size=8)))
    t_1, _ = accelerate(_model(), None, cfg_1, optimizer=optax.adam(1e-3))
    t_1.init()
    losses_1 = [float(t_1.step(b)["loss"]) for b in batches]

    np.testing.assert_allclose(losses_tp, losses_1, rtol=1e-4)


def test_tp_params_sharded(devices):
    cfg = ta.Config(dist=ta.DistConfig(tp=ta.TPConfig(size=4),
                                       fsdp=ta.FSDPConfig(size=2,
                                                          min_weight_size=0)))
    trainer, _ = accelerate(_model(), None, cfg)
    trainer.init()
    p = trainer.state.params
    # q kernel [layers, embed, heads, kv]: heads on tp, embed on fsdp
    qspec = str(p["layers"]["block"]["attn"]["q_proj"]["kernel"].sharding.spec)
    assert "tp" in qspec and "fsdp" in qspec
    # mlp gate [layers, embed, mlp]: mlp on tp
    gspec = str(p["layers"]["block"]["mlp"]["gate_proj"]["kernel"].sharding.spec)
    assert "tp" in gspec


def test_tp_with_cp_composition(devices):
    """tp x sp(2d) x fsdp all at once — the full long-context layout."""
    import optax
    cfg = ta.Config(dist=ta.DistConfig(
        tp=ta.TPConfig(size=2),
        sp=ta.SPConfig(size=2, mode="ulysses"),
        fsdp=ta.FSDPConfig(size=2, min_weight_size=0)))
    trainer, loader = accelerate(_model(), _batches(6, seed=1), cfg,
                                 optimizer=optax.adam(3e-3))
    losses = [float(trainer.step(b)["loss"]) for b in loader]
    assert losses[-1] < losses[0], losses
