"""Streamed safetensors ingestion (models/hf_stream.py): bounded host
memory, shard-by-shard conversion, direct placement into target
shardings.  Reference capability: LOW_CPU_MEM_USAGE deferred init
(reference accelerate.py:13-17,114-119 via torchdistx fake tensors) —
here the TPU-native answer is streaming straight to sharded device
arrays, no full-model materialisation ever."""

import json
import os
import subprocess
import sys
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

import transformers

from torchacc_tpu.models import TransformerLM
from torchacc_tpu.models.hf import config_from_hf, params_from_hf_state_dict
from torchacc_tpu.models.hf_stream import (
    ingestion_plan, load_hf_model_streamed, resolve_checkpoint_files,
    stream_params, validate_checkpoint_header)


def _tiny_llama_cfg(**kw):
    base = dict(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rope_theta=10000.0, rms_norm_eps=1e-5,
        tie_word_embeddings=False, attn_implementation="eager")
    base.update(kw)
    return transformers.LlamaConfig(**base)


def _save_sharded(hf_model, path, n_shards=3):
    """Write an HF-style multi-shard safetensors checkpoint (index json
    + shards), the exact on-disk layout real releases ship."""
    from safetensors.torch import save_file

    sd = {k: v.contiguous() for k, v in hf_model.state_dict().items()}
    os.makedirs(path, exist_ok=True)
    hf_model.config.save_pretrained(path)
    names = sorted(sd)
    weight_map = {}
    for s in range(n_shards):
        part = {n: sd[n] for n in names[s::n_shards]}
        fname = f"model-{s + 1:05d}-of-{n_shards:05d}.safetensors"
        save_file(part, os.path.join(path, fname))
        for n in part:
            weight_map[n] = fname
    with open(os.path.join(path, "model.safetensors.index.json"), "w") as f:
        json.dump({"metadata": {}, "weight_map": weight_map}, f)


def test_streamed_matches_materialised(tmp_path):
    """Tensor-for-tensor: streaming the shards reproduces exactly what
    the materialising converter builds from the same checkpoint."""
    torch.manual_seed(0)
    hf_model = transformers.LlamaForCausalLM(_tiny_llama_cfg()).eval()
    path = str(tmp_path / "ckpt")
    _save_sharded(hf_model, path, n_shards=3)

    cfg = config_from_hf(hf_model.config, dtype=jnp.float32,
                         param_dtype=jnp.float32)
    ref = params_from_hf_state_dict(hf_model.state_dict(), cfg)

    files = resolve_checkpoint_files(path)
    assert files is not None and len(files) == 3
    got = stream_params(files, cfg, param_dtype=jnp.float32)

    ref_flat = jax.tree_util.tree_flatten_with_path(ref)[0]
    got_flat = jax.tree_util.tree_flatten_with_path(got)[0]
    assert [k for k, _ in ref_flat] == [k for k, _ in got_flat]
    for (k, a), (_, b) in zip(ref_flat, got_flat):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=str(k))


def test_streamed_single_file_and_tied(tmp_path):
    """Single-file checkpoints and tied embeddings (no lm_head tensor on
    disk) both stream."""
    torch.manual_seed(1)
    hf_model = transformers.LlamaForCausalLM(
        _tiny_llama_cfg(tie_word_embeddings=True)).eval()
    from safetensors.torch import save_file
    path = str(tmp_path / "ckpt")
    os.makedirs(path)
    hf_model.config.save_pretrained(path)
    sd = {k: v.contiguous() for k, v in hf_model.state_dict().items()
          if k != "lm_head.weight"}
    save_file(sd, os.path.join(path, "model.safetensors"))

    cfg, params = load_hf_model_streamed(path, dtype=jnp.float32,
                                         param_dtype=jnp.float32)
    assert cfg.tie_embeddings and "lm_head" not in params

    ids = np.random.default_rng(0).integers(0, 128, size=(2, 16))
    ours = TransformerLM(cfg).apply({"params": params},
                                    jnp.asarray(ids, jnp.int32))
    with torch.no_grad():
        theirs = hf_model(torch.from_numpy(ids)).logits.float().numpy()
    np.testing.assert_allclose(np.asarray(ours), theirs, atol=2e-4)


def test_streamed_tied_with_dealiased_head(tmp_path):
    """Some exporters write a DE-ALIASED lm_head copy even for tied
    models (safetensors refuses aliased tensors): it must stream as a
    discard, exactly like the materialising path ignores it."""
    torch.manual_seed(4)
    hf_model = transformers.LlamaForCausalLM(
        _tiny_llama_cfg(tie_word_embeddings=True)).eval()
    path = str(tmp_path / "ckpt")
    os.makedirs(path)
    hf_model.config.save_pretrained(path)
    from safetensors.torch import save_file
    sd = {k: v.contiguous() for k, v in hf_model.state_dict().items()}
    sd["lm_head.weight"] = hf_model.model.embed_tokens.weight.detach().clone()
    save_file(sd, os.path.join(path, "model.safetensors"))

    cfg, params = load_hf_model_streamed(path, dtype=jnp.float32,
                                         param_dtype=jnp.float32)
    assert cfg.tie_embeddings and "lm_head" not in params
    # header validation accepts the same checkpoint abstractly
    validate_checkpoint_header({k: tuple(v.shape) for k, v in sd.items()},
                               cfg)


def test_streamed_bf16_checkpoint(tmp_path):
    """bf16 shards (what real llama3 releases ship) stream without the
    f32 upcast round-trip: values land bit-identical to the checkpoint."""
    torch.manual_seed(2)
    hf_model = transformers.LlamaForCausalLM(_tiny_llama_cfg()).to(
        torch.bfloat16)
    path = str(tmp_path / "ckpt")
    _save_sharded(hf_model, path, n_shards=2)

    cfg = config_from_hf(hf_model.config, param_dtype=jnp.bfloat16)
    got = stream_params(resolve_checkpoint_files(path), cfg,
                        param_dtype=jnp.bfloat16)
    want = hf_model.model.embed_tokens.weight.detach().view(
        torch.uint16).numpy()
    np.testing.assert_array_equal(
        np.asarray(got["embed_tokens"]["embedding"]).view(np.uint16), want)


def test_streamed_into_fsdp_shardings(tmp_path, devices):
    """accelerate(checkpoint_path) streams into the live FSDP shardings:
    params come back already sharded over the mesh and the model trains."""
    import optax

    import torchacc_tpu as ta
    from torchacc_tpu.train import accelerate

    torch.manual_seed(3)
    hf_model = transformers.LlamaForCausalLM(_tiny_llama_cfg()).eval()
    path = str(tmp_path / "ckpt")
    _save_sharded(hf_model, path, n_shards=2)

    cfg = ta.Config(dist=ta.DistConfig(
        fsdp=ta.FSDPConfig(size=8, min_weight_size=0)))
    cfg.compute.dtype = "float32"
    cfg.compute.param_dtype = "float32"
    trainer, _ = accelerate(path, None, cfg, optimizer=optax.adam(1e-3))

    # weights must match the checkpoint (spot-check embed) AND be sharded
    emb = trainer.state.params["embed_tokens"]["embedding"]
    np.testing.assert_allclose(
        np.asarray(emb),
        hf_model.model.embed_tokens.weight.detach().float().numpy(),
        atol=1e-6)
    sharded = [x for x in jax.tree.leaves(trainer.state.params)
               if "fsdp" in str(x.sharding.spec)]
    assert sharded, "no parameter landed sharded over fsdp"

    batch = {"input_ids": jnp.asarray(
        np.random.default_rng(0).integers(0, 128, size=(8, 32)), jnp.int32)}
    assert np.isfinite(float(trainer.step(batch)["loss"]))


def test_header_validation_catches_mismatch():
    cfg = config_from_hf(_tiny_llama_cfg())
    plan = ingestion_plan(cfg)
    shapes = {n: e[0].hf_shape for n, e in plan.items()}
    validate_checkpoint_header(shapes, cfg)  # clean header passes

    bad = dict(shapes)
    bad["layers.0.self_attn.q_proj.weight"] = (7, 7)
    with pytest.raises(ValueError, match="shape"):
        validate_checkpoint_header(bad, cfg)
    with pytest.raises(KeyError, match="unmappable"):
        validate_checkpoint_header({**shapes, "visual.patch_embed": (3, 3)},
                                   cfg)
    del shapes["layers.1.mlp.up_proj.weight"]
    with pytest.raises(ValueError, match="missing"):
        validate_checkpoint_header(shapes, cfg)


@pytest.mark.slow
def test_streamed_peak_rss_bounded(tmp_path):
    """THE point of streaming: peak host RSS while ingesting stays at
    resident-params + a transient bounded by a couple of stacked leaves
    — NOT the 2-3x full-model overhead of the materialising path (torch
    module + stacked numpy copies).  ~360 MB synthetic checkpoint keeps
    the signal far above allocator noise; measured in a subprocess so
    ru_maxrss is this load's peak and nothing else's."""
    from safetensors.numpy import save_file

    hf_cfg = _tiny_llama_cfg(
        vocab_size=4096, hidden_size=1024, intermediate_size=3072,
        num_hidden_layers=6, num_attention_heads=8, num_key_value_heads=8)
    mc = config_from_hf(hf_cfg, param_dtype=jnp.float32)
    plan = ingestion_plan(mc)
    path = str(tmp_path / "big")
    os.makedirs(path)
    hf_cfg.save_pretrained(path)
    rng = np.random.default_rng(0)
    names = sorted(plan)
    n_shards, weight_map = 3, {}
    for s in range(n_shards):
        part = {f"model.{n}": rng.standard_normal(
                    plan[n][0].hf_shape).astype(np.float32) * 0.02
                for n in names[s::n_shards]}
        fname = f"model-{s + 1:05d}-of-{n_shards:05d}.safetensors"
        save_file(part, os.path.join(path, fname))
        for n in part:
            weight_map[n] = fname
    with open(os.path.join(path, "model.safetensors.index.json"), "w") as f:
        json.dump({"metadata": {}, "weight_map": weight_map}, f)

    child = textwrap.dedent(f"""
        import ctypes, json, os, sys
        # fix glibc's dynamic mmap threshold at 1 MB so every large
        # buffer is mmap'd and returned to the OS on free — otherwise
        # arena retention adds a nondeterministic hundreds-of-MB floor
        # that has nothing to do with what the loader keeps alive
        try:
            ctypes.CDLL("libc.so.6").mallopt(-3, 1 << 20)
        except Exception:
            pass
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp
        import numpy as np
        from torchacc_tpu.models.hf import config_from_hf
        from torchacc_tpu.models.hf_stream import (
            resolve_checkpoint_files, stream_params)
        import transformers
        def _status(key):
            with open("/proc/self/status") as f:
                for line in f:
                    if line.startswith(key):
                        return int(line.split()[1]) * 1024
        rss = lambda: _status("VmRSS")
        # NOT getrusage ru_maxrss: on linux it survives execve, so a
        # subprocess inherits the pytest parent's high-water mark.
        # VmHWM belongs to this process's own mm and resets on exec.
        hwm = lambda: _status("VmHWM")
        jnp.ones((8, 8)).sum().item()  # backend warm before baseline
        hf_cfg = transformers.AutoConfig.from_pretrained({path!r})
        cfg = config_from_hf(hf_cfg, param_dtype=jnp.float32)
        baseline = rss()
        params = stream_params(resolve_checkpoint_files({path!r}), cfg,
                               param_dtype=jnp.float32)
        jax.block_until_ready(params)
        final = rss()
        peak = hwm()
        pbytes = sum(x.size * x.dtype.itemsize
                     for x in jax.tree.leaves(params))
        print(json.dumps({{"baseline": baseline, "final": final,
                           "peak": peak, "params_bytes": pbytes}}))
    """)
    r = subprocess.run([sys.executable, "-c", child], capture_output=True,
                       text=True, timeout=420,
                       cwd=os.path.dirname(os.path.dirname(
                           os.path.abspath(__file__))))
    assert r.returncode == 0, r.stderr[-2000:]
    m = json.loads(r.stdout.strip().splitlines()[-1])
    pb = m["params_bytes"]
    assert pb > 250e6  # the checkpoint is big enough to measure
    load_overhead = m["peak"] - m["baseline"]
    transient = m["peak"] - m["final"]
    # materialising path: torch state dict + stacked numpy copies =
    # >= 2x params on top of the resident arrays.  Streaming: resident
    # params + a transient bounded by ~2 stacked leaves + jit machinery.
    assert load_overhead < 1.5 * pb, (load_overhead, pb, m)
    assert transient < 0.6 * pb, (transient, pb, m)


def test_llama3_70b_abstract_ingestion_dryrun(devices):
    """The 70B-scale leg (BASELINE.json config 3) WITHOUT 140 GB of
    weights: HF's own meta-device module provides the checkpoint header
    (independent source of truth for every tensor name+shape), the plan
    validates it, and the FSDP+TP trainer's resolved shardings cover
    every stacked leaf at the real [80, ...] geometry."""
    from accelerate import init_empty_weights

    import torchacc_tpu as ta
    from torchacc_tpu.models.hf_stream import _tree_get
    from torchacc_tpu.train import accelerate as ta_accelerate
    from torchacc_tpu.train.accelerate import apply_config_to_model
    from torchacc_tpu.train.trainer import Trainer

    hf_cfg = transformers.LlamaConfig(
        vocab_size=128256, hidden_size=8192, intermediate_size=28672,
        num_hidden_layers=80, num_attention_heads=64,
        num_key_value_heads=8, max_position_embeddings=8192,
        rope_theta=500000.0, rms_norm_eps=1e-5, tie_word_embeddings=False)
    with init_empty_weights():
        meta = transformers.AutoModelForCausalLM.from_config(hf_cfg)
    shapes = {k: tuple(v.shape) for k, v in meta.state_dict().items()}

    mc = config_from_hf(hf_cfg, dtype=jnp.bfloat16,
                        param_dtype=jnp.bfloat16)
    validate_checkpoint_header(shapes, mc)

    cfg = ta.Config(dist=ta.DistConfig(
        fsdp=ta.FSDPConfig(size=4, min_weight_size=0),
        tp=ta.TPConfig(size=2)))
    model = TransformerLM(apply_config_to_model(mc, cfg))
    import optax
    trainer = Trainer(model, cfg, optimizer=optax.adamw(1e-4))
    trainer.resolve_shardings()  # abstract only: nothing materialises
    sh = trainer.state_shardings.params

    plan = ingestion_plan(mc)
    total = 0
    for name, ents in plan.items():
        for ent in ents:  # every plan path must resolve
            assert _tree_get(sh, ent.path) is not None, name
        total += int(np.prod(ents[0].hf_shape))
    assert total == 70_553_706_496  # llama-3-70b exact param count


def _tiny_mixtral_cfg(**kw):
    base = dict(
        vocab_size=128, hidden_size=64, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2,
        max_position_embeddings=64, rms_norm_eps=1e-5, sliding_window=None,
        tie_word_embeddings=False, attn_implementation="eager")
    base.update(kw)
    return transformers.MixtralConfig(**base)


def test_streamed_mixtral_matches_materialised(tmp_path):
    """Mixtral MoE leaves ([L, E, ...] stacked experts, two-level index)
    stream tensor-for-tensor identical to the materialising converter."""
    torch.manual_seed(5)
    hf_model = transformers.MixtralForCausalLM(_tiny_mixtral_cfg()).eval()
    path = str(tmp_path / "ckpt")
    _save_sharded(hf_model, path, n_shards=3)

    cfg = config_from_hf(hf_model.config, dtype=jnp.float32,
                         param_dtype=jnp.float32)
    ref = params_from_hf_state_dict(hf_model.state_dict(), cfg)
    got = stream_params(resolve_checkpoint_files(path), cfg,
                        param_dtype=jnp.float32)

    ref_flat = jax.tree_util.tree_flatten_with_path(ref)[0]
    got_flat = jax.tree_util.tree_flatten_with_path(got)[0]
    assert [k for k, _ in ref_flat] == [k for k, _ in got_flat]
    for (k, a), (_, b) in zip(ref_flat, got_flat):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=str(k))


def test_mixtral_8x7b_abstract_ingestion_dryrun(devices):
    """BASELINE config 5 (Mixtral-8x7B) abstractly: HF's meta-device
    module provides the header, the plan validates it, and an
    EP x PP x FSDP trainer's resolved shardings cover every leaf —
    including the [32, 8, ...] stacked-expert ones — without a byte of
    weight data."""
    from accelerate import init_empty_weights

    import optax

    import torchacc_tpu as ta
    from torchacc_tpu.models.hf_stream import _tree_get
    from torchacc_tpu.train.accelerate import apply_config_to_model
    from torchacc_tpu.train.trainer import Trainer

    hf_cfg = transformers.MixtralConfig(
        vocab_size=32000, hidden_size=4096, intermediate_size=14336,
        num_hidden_layers=32, num_attention_heads=32,
        num_key_value_heads=8, num_local_experts=8, num_experts_per_tok=2,
        max_position_embeddings=32768, rope_theta=1e6,
        tie_word_embeddings=False)
    with init_empty_weights():
        meta = transformers.AutoModelForCausalLM.from_config(hf_cfg)
    shapes = {k: tuple(v.shape) for k, v in meta.state_dict().items()}

    mc = config_from_hf(hf_cfg, dtype=jnp.bfloat16, param_dtype=jnp.bfloat16)
    validate_checkpoint_header(shapes, mc)

    cfg = ta.Config(dist=ta.DistConfig(
        pp=ta.PPConfig(size=2, num_micro_batches=2),
        ep=ta.EPConfig(size=2),
        fsdp=ta.FSDPConfig(size=2, min_weight_size=0)))
    model = TransformerLM(apply_config_to_model(mc, cfg))
    trainer = Trainer(model, cfg, optimizer=optax.adamw(1e-4))
    trainer.resolve_shardings()  # abstract only
    sh = trainer.state_shardings.params

    plan = ingestion_plan(mc)
    total = 0
    for name, ents in plan.items():
        for ent in ents:
            assert _tree_get(sh, ent.path) is not None, name
        total += int(np.prod(ents[0].hf_shape))
    assert total == 46_702_792_704  # mixtral-8x7b exact param count


def test_streamed_into_pp_shardings(tmp_path, devices):
    """Streaming into a PP x FSDP layout: the stacked LAYER dim is
    itself sharded over 'pp', so each arriving layer's piece transfer
    drops that leading spec entry and the donated set writes into a
    pp-sharded buffer.  Weights must land exactly and the pipeline must
    train from them."""
    import optax

    import torchacc_tpu as ta
    from torchacc_tpu.train import accelerate

    torch.manual_seed(6)
    hf_model = transformers.LlamaForCausalLM(
        _tiny_llama_cfg(num_hidden_layers=4)).eval()
    path = str(tmp_path / "ckpt")
    _save_sharded(hf_model, path, n_shards=2)

    cfg = ta.Config(dist=ta.DistConfig(
        pp=ta.PPConfig(size=2, num_micro_batches=2),
        fsdp=ta.FSDPConfig(size=2, min_weight_size=0),
        dp=ta.DPConfig(size=2)))
    cfg.compute.dtype = "float32"
    cfg.compute.param_dtype = "float32"
    trainer, _ = accelerate(path, None, cfg, optimizer=optax.adam(1e-3))

    k = trainer.state.params["layers"]["block"]["attn"]["q_proj"]["kernel"]
    assert "pp" in str(k.sharding.spec), k.sharding.spec
    # exact landing: compare the full stacked q kernel against the
    # materialising conversion
    from torchacc_tpu.models.hf import config_from_hf, params_from_hf_state_dict
    mc = config_from_hf(hf_model.config, dtype=jnp.float32,
                        param_dtype=jnp.float32)
    want = params_from_hf_state_dict(hf_model.state_dict(), mc)
    np.testing.assert_array_equal(
        np.asarray(k),
        np.asarray(want["layers"]["block"]["attn"]["q_proj"]["kernel"]))

    ids = np.random.default_rng(0).integers(0, 128, size=(8, 32))
    loss = float(trainer.step({"input_ids": jnp.asarray(ids, jnp.int32)})
                 ["loss"])
    assert np.isfinite(loss)


def test_streamed_qwen3(tmp_path):
    """Qwen3 (qk-norm family) streams: the q_norm/k_norm per-layer
    tensors are covered by the generic qk_norm plan entries."""
    hf_cfg = transformers.Qwen3Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=32, max_position_embeddings=64, rms_norm_eps=1e-6,
        tie_word_embeddings=False, attn_implementation="eager")
    torch.manual_seed(7)
    hf_model = transformers.Qwen3ForCausalLM(hf_cfg).eval()
    path = str(tmp_path / "ckpt")
    _save_sharded(hf_model, path, n_shards=2)

    cfg, params = load_hf_model_streamed(path, dtype=jnp.float32,
                                         param_dtype=jnp.float32)
    assert cfg.qk_norm
    ids = np.random.default_rng(7).integers(0, 128, size=(2, 16))
    ours = TransformerLM(cfg).apply({"params": params},
                                    jnp.asarray(ids, jnp.int32))
    with torch.no_grad():
        theirs = hf_model(torch.from_numpy(ids)).logits.float().numpy()
    np.testing.assert_allclose(np.asarray(ours), theirs, atol=2e-4)


def test_streamed_olmo2(tmp_path):
    """OLMo2 streams: post-norm ln1/ln2 mapping + flat-projection
    qk-norm shapes in the plan."""
    hf_cfg = transformers.Olmo2Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rms_norm_eps=1e-5,
        tie_word_embeddings=False, attn_implementation="eager")
    torch.manual_seed(8)
    hf_model = transformers.Olmo2ForCausalLM(hf_cfg).eval()
    path = str(tmp_path / "ckpt")
    _save_sharded(hf_model, path, n_shards=2)

    cfg, params = load_hf_model_streamed(path, dtype=jnp.float32,
                                         param_dtype=jnp.float32)
    assert cfg.norm_placement == "post"
    ids = np.random.default_rng(8).integers(0, 128, size=(2, 16))
    ours = TransformerLM(cfg).apply({"params": params},
                                    jnp.asarray(ids, jnp.int32))
    with torch.no_grad():
        theirs = hf_model(torch.from_numpy(ids)).logits.float().numpy()
    np.testing.assert_allclose(np.asarray(ours), theirs, atol=2e-4)


def test_streamed_phi3_packed(tmp_path):
    """Phi-3's packed qkv_proj / gate_up_proj: one checkpoint tensor
    feeds several leaves (multi-entry plan), detected from the header."""
    hf_cfg = transformers.Phi3Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, pad_token_id=0,
        tie_word_embeddings=False, attn_implementation="eager")
    torch.manual_seed(9)
    hf_model = transformers.Phi3ForCausalLM(hf_cfg).eval()
    path = str(tmp_path / "ckpt")
    _save_sharded(hf_model, path, n_shards=2)

    cfg, params = load_hf_model_streamed(path, dtype=jnp.float32,
                                         param_dtype=jnp.float32)
    ids = np.random.default_rng(9).integers(0, 128, size=(2, 16))
    ours = TransformerLM(cfg).apply({"params": params},
                                    jnp.asarray(ids, jnp.int32))
    with torch.no_grad():
        theirs = hf_model(torch.from_numpy(ids)).logits.float().numpy()
    np.testing.assert_allclose(np.asarray(ours), theirs, atol=2e-4)
    # abstract header validation sees the packed layout too
    validate_checkpoint_header(
        {k: tuple(v.shape) for k, v in hf_model.state_dict().items()}, cfg)


def test_streamed_qwen3_moe(tmp_path):
    """Qwen3-MoE streams: the qwen expert naming (mlp.experts.N.*)
    detected from the header feeds the [L, E, ...] stacked leaves."""
    hf_cfg = transformers.Qwen3MoeConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        moe_intermediate_size=96, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, head_dim=16,
        num_experts=4, num_experts_per_tok=2, norm_topk_prob=True,
        max_position_embeddings=64, rms_norm_eps=1e-6,
        tie_word_embeddings=False, attn_implementation="eager")
    torch.manual_seed(10)
    hf_model = transformers.Qwen3MoeForCausalLM(hf_cfg).eval()
    path = str(tmp_path / "ckpt")
    _save_sharded(hf_model, path, n_shards=2)

    cfg, params = load_hf_model_streamed(path, dtype=jnp.float32,
                                         param_dtype=jnp.float32)
    ids = np.random.default_rng(10).integers(0, 128, size=(2, 16))
    ours = TransformerLM(cfg).apply({"params": params},
                                    jnp.asarray(ids, jnp.int32))
    with torch.no_grad():
        theirs = hf_model(torch.from_numpy(ids)).logits.float().numpy()
    np.testing.assert_allclose(np.asarray(ours), theirs, atol=2e-4)


@pytest.mark.slow
def test_streamed_full_lifecycle(tmp_path, devices):
    """The complete big-model user journey in miniature: safetensors
    checkpoint -> STREAMED ingestion into FSDP shardings -> train ->
    orbax save -> restore into a DIFFERENT layout -> identical
    continuation.  Closes the loop between the two checkpoint systems
    (HF safetensors in, orbax out)."""
    import optax

    import torchacc_tpu as ta
    from torchacc_tpu.train import accelerate

    torch.manual_seed(11)
    hf_model = transformers.LlamaForCausalLM(
        _tiny_llama_cfg(num_hidden_layers=4)).eval()
    path = str(tmp_path / "hf_ckpt")
    _save_sharded(hf_model, path, n_shards=2)

    rng = np.random.default_rng(0)
    batches = [{"input_ids": jnp.asarray(
        rng.integers(0, 128, size=(8, 32)), jnp.int32)} for _ in range(4)]

    cfg = ta.Config(dist=ta.DistConfig(
        fsdp=ta.FSDPConfig(size=8, min_weight_size=0)))
    cfg.compute.dtype = "float32"
    cfg.compute.param_dtype = "float32"
    t, _ = accelerate(path, None, cfg, optimizer=optax.adam(1e-3))
    for b in batches[:2]:
        t.step(b)
    ck = str(tmp_path / "orbax")
    t.save(ck)
    cont = [float(t.step(b)["loss"]) for b in batches[2:]]

    # resume does NOT need the HF checkpoint again: the orbax save is
    # self-sufficient — build the trainer from the config and restore
    # into a DIFFERENT layout
    mc = config_from_hf(hf_model.config, dtype=jnp.float32,
                        param_dtype=jnp.float32)
    cfg2 = ta.Config(dist=ta.DistConfig(
        dp=ta.DPConfig(size=2),
        fsdp=ta.FSDPConfig(size=4, min_weight_size=0)))
    cfg2.compute.dtype = "float32"
    cfg2.compute.param_dtype = "float32"
    t2, _ = accelerate(mc, None, cfg2, optimizer=optax.adam(1e-3))
    t2.init()
    t2.restore(ck)
    assert int(t2.state.step) == 2
    resumed = [float(t2.step(b)["loss"]) for b in batches[2:]]
    np.testing.assert_allclose(cont, resumed, rtol=1e-6)


def test_streamed_llama_with_biases(tmp_path):
    """attention_bias + mlp_bias checkpoints stream (o_proj and mlp
    bias plan entries)."""
    hf_cfg = _tiny_llama_cfg(attention_bias=True, mlp_bias=True)
    torch.manual_seed(12)
    hf_model = transformers.LlamaForCausalLM(hf_cfg).eval()
    path = str(tmp_path / "ckpt")
    _save_sharded(hf_model, path, n_shards=2)

    cfg, params = load_hf_model_streamed(path, dtype=jnp.float32,
                                         param_dtype=jnp.float32)
    ids = np.random.default_rng(12).integers(0, 128, size=(2, 16))
    ours = TransformerLM(cfg).apply({"params": params},
                                    jnp.asarray(ids, jnp.int32))
    with torch.no_grad():
        theirs = hf_model(torch.from_numpy(ids)).logits.float().numpy()
    np.testing.assert_allclose(np.asarray(ours), theirs, atol=2e-4)


def test_streamed_starcoder2(tmp_path):
    """StarCoder2 streams: non-gated c_fc/c_proj MLP entries, biased
    LayerNorm entries (ln1/ln2/final_norm .bias leaves), biases on every
    projection, tied embeddings."""
    hf_cfg = transformers.Starcoder2Config(
        vocab_size=128, hidden_size=64, intermediate_size=256,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, norm_epsilon=1e-5,
        tie_word_embeddings=True, attn_implementation="eager",
        residual_dropout=0.0, embedding_dropout=0.0)
    torch.manual_seed(9)
    hf_model = transformers.Starcoder2ForCausalLM(hf_cfg).eval()
    path = str(tmp_path / "ckpt")
    _save_sharded(hf_model, path, n_shards=2)

    cfg, params = load_hf_model_streamed(path, dtype=jnp.float32,
                                         param_dtype=jnp.float32)
    assert cfg.norm == "layernorm" and cfg.activation == "gelu"
    assert "bias" in params["final_norm"]
    assert "gate_proj" not in params["layers"]["block"]["mlp"]
    ids = np.random.default_rng(9).integers(0, 128, size=(2, 16))
    ours = TransformerLM(cfg).apply({"params": params},
                                    jnp.asarray(ids, jnp.int32))
    with torch.no_grad():
        theirs = hf_model(torch.from_numpy(ids)).logits.float().numpy()
    np.testing.assert_allclose(np.asarray(ours), theirs, atol=2e-4)


def test_streamed_cohere(tmp_path):
    """Cohere streams: parallel-block plan (ln1 only, no ln2 entries),
    biasless LayerNorm, tied embeddings, logit_scale binding."""
    hf_cfg = transformers.CohereConfig(
        vocab_size=128, hidden_size=64, intermediate_size=256,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, logit_scale=0.0625,
        tie_word_embeddings=True, attn_implementation="eager")
    torch.manual_seed(14)
    hf_model = transformers.CohereForCausalLM(hf_cfg).eval()
    path = str(tmp_path / "ckpt")
    _save_sharded(hf_model, path, n_shards=2)

    cfg, params = load_hf_model_streamed(path, dtype=jnp.float32,
                                         param_dtype=jnp.float32)
    assert cfg.parallel_block and not cfg.norm_bias
    blk = params["layers"]["block"]
    assert "ln2" not in blk and "bias" not in blk["ln1"]
    ids = np.random.default_rng(14).integers(0, 128, size=(2, 16))
    ours = TransformerLM(cfg).apply({"params": params},
                                    jnp.asarray(ids, jnp.int32))
    with torch.no_grad():
        theirs = hf_model(torch.from_numpy(ids)).logits.float().numpy()
    np.testing.assert_allclose(np.asarray(ours), theirs, atol=2e-4)


def test_streamed_nemotron(tmp_path):
    """Nemotron streams: gate-free up/down plan entries + layernorm1p
    bias entries."""
    hf_cfg = transformers.NemotronConfig(
        vocab_size=128, hidden_size=64, intermediate_size=256,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, partial_rotary_factor=0.5,
        tie_word_embeddings=False, attn_implementation="eager")
    torch.manual_seed(16)
    hf_model = transformers.NemotronForCausalLM(hf_cfg).eval()
    path = str(tmp_path / "ckpt")
    _save_sharded(hf_model, path, n_shards=2)

    cfg, params = load_hf_model_streamed(path, dtype=jnp.float32,
                                         param_dtype=jnp.float32)
    blk = params["layers"]["block"]
    assert "gate_proj" not in blk["mlp"] and "bias" in blk["ln1"]
    ids = np.random.default_rng(16).integers(0, 128, size=(2, 16))
    ours = TransformerLM(cfg).apply({"params": params},
                                    jnp.asarray(ids, jnp.int32))
    with torch.no_grad():
        theirs = hf_model(torch.from_numpy(ids)).logits.float().numpy()
    np.testing.assert_allclose(np.asarray(ours), theirs, atol=2e-4)
