"""Fused linear+CE: numerical equivalence (loss AND grads) with the
naive logits path, plus trainer integration (reference analogue: Liger
fused-linear-cross-entropy parity, ops/liger.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import torchacc_tpu as ta
from torchacc_tpu.models import get_preset
from torchacc_tpu.models.transformer import loss_sum_count
from torchacc_tpu.ops.fused import fused_linear_cross_entropy
from torchacc_tpu.train import accelerate


def _naive(hidden, w, labels):
    logits = hidden.astype(jnp.float32) @ w.astype(jnp.float32)
    return loss_sum_count(logits, labels)


def test_fused_ce_matches_naive_loss_and_grads():
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    hidden = jax.random.normal(ks[0], (2, 24, 32))
    w = jax.random.normal(ks[1], (32, 101)) * 0.1
    labels = jax.random.randint(ks[2], (2, 24), 0, 101)
    labels = labels.at[:, -5:].set(-100)

    def f_fused(h, w):
        l, c = fused_linear_cross_entropy(h, w, labels, chunk_rows=16)
        return l / c

    def f_naive(h, w):
        l, c = _naive(h, w, labels)
        return l / c

    lf, ln = f_fused(hidden, w), f_naive(hidden, w)
    np.testing.assert_allclose(float(lf), float(ln), rtol=1e-6)

    gf = jax.grad(f_fused, argnums=(0, 1))(hidden, w)
    gn = jax.grad(f_naive, argnums=(0, 1))(hidden, w)
    for a, b, name in zip(gf, gn, ("dh", "dw")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6, err_msg=name)


def test_fused_ce_all_masked():
    hidden = jnp.ones((1, 8, 16))
    w = jnp.ones((16, 32))
    labels = jnp.full((1, 8), -100)
    l, c = fused_linear_cross_entropy(hidden, w, labels, chunk_rows=4)
    assert float(l) == 0.0 and float(c) == 0.0


@pytest.mark.parametrize("tie", [False, True])
def test_trainer_fused_matches_unfused(devices, tie):
    """fused_kernels on/off must produce identical training losses."""
    import optax
    mc = get_preset("llama-tiny", vocab_size=128, hidden_size=64,
                    num_layers=2, num_heads=4, num_kv_heads=2,
                    intermediate_size=128, tie_embeddings=tie,
                    dtype=jnp.float32)
    rng = np.random.default_rng(0)
    data = rng.integers(0, 128, size=(4, 32))
    batches = [{"input_ids": data[rng.integers(0, 4, size=8)].astype(np.int32)}
               for _ in range(3)]

    losses = {}
    for fused in (True, False):
        cfg = ta.Config(compute=ta.ComputeConfig(fused_kernels=fused))
        t, _ = accelerate(mc, None, cfg, optimizer=optax.adam(1e-3))
        t.init()
        losses[fused] = [float(t.step(b)["loss"]) for b in batches]
    np.testing.assert_allclose(losses[True], losses[False], rtol=2e-4)


def test_scan_free_chunk_never_unrolls_tiny_divisors():
    """ADVICE r3 medium: prime/near-prime row counts must not pick a tiny
    divisor (which would unroll n/d python chunks at trace time)."""
    from torchacc_tpu.ops.fused import _scan_free_chunk

    # prime n: only divisors are {1, n}; must fall back to n (one chunk),
    # never 1 (n chunks)
    assert _scan_free_chunk(4099, 2048) == 4099
    # 2 * prime: {1, 2, p, n}; 2 would unroll ~4k chunks — must pick >= n/2
    assert _scan_free_chunk(2 * 4099, 2048) in (4099, 2 * 4099)
    # composite n keeps the tuned size
    assert _scan_free_chunk(8192, 2048) == 2048
    # awkward-but-composite picks the nearest in-band divisor
    assert _scan_free_chunk(4106, 2048) == 2053
    # n smaller than the band floor: one chunk of n rows
    assert _scan_free_chunk(13, 2048) == 13
    # chunk count stays bounded in all cases
    for n in (4099, 2 * 4099, 3 * 1361, 8192, 4106, 13, 6 * 4099):
        d = _scan_free_chunk(n, 2048)
        assert n % d == 0 and n // d <= 64, (n, d)
