"""Model-zoo tests: forward shapes, axes resolution, param counts."""

import chex
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torchacc_tpu.models import (
    ModelConfig,
    TransformerLM,
    get_preset,
    loss_fn,
    param_axes,
)


@pytest.fixture(scope="module")
def tiny_cfg():
    return get_preset("llama-tiny", dtype=jnp.float32, num_layers=2)


def test_forward_shape(tiny_cfg):
    model = TransformerLM(tiny_cfg)
    ids = jnp.zeros((2, 16), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    logits = model.apply({"params": params}, ids)
    assert logits.shape == (2, 16, tiny_cfg.vocab_size)
    assert logits.dtype == jnp.float32


def test_gpt2_style_forward():
    cfg = get_preset("gpt2-tiny", dtype=jnp.float32, num_layers=2)
    model = TransformerLM(cfg)
    ids = jnp.zeros((2, 16), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    logits = model.apply({"params": params}, ids)
    assert logits.shape == (2, 16, cfg.vocab_size)


def test_param_axes_cover_all_params(tiny_cfg):
    model = TransformerLM(tiny_cfg)
    abstract = jax.eval_shape(
        lambda r: model.init(r, jnp.zeros((1, 8), jnp.int32))["params"],
        jax.random.PRNGKey(0))
    axes = param_axes(abstract)  # raises if any param unmatched
    flat_a = jax.tree.leaves(axes, is_leaf=lambda x: isinstance(x, tuple))
    flat_p = jax.tree.leaves(abstract)
    assert len(flat_a) == len(flat_p)
    for a, p in zip(flat_a, flat_p):
        assert len(a) == p.ndim, (a, p.shape)


def test_param_count_matches_analytic(tiny_cfg):
    model = TransformerLM(tiny_cfg)
    abstract = jax.eval_shape(
        lambda r: model.init(r, jnp.zeros((1, 8), jnp.int32))["params"],
        jax.random.PRNGKey(0))
    actual = sum(p.size for p in jax.tree.leaves(abstract))
    assert actual == tiny_cfg.num_params()


def test_gemma_style_model():
    """Gemma variant features: (1+w) RMSNorm, geglu, scaled embeddings,
    logit softcap, explicit head_dim; analytic param count stays exact
    and the loss is finite + differentiable."""
    cfg = get_preset("gemma-2b", dtype=jnp.float32, param_dtype=jnp.float32,
                     vocab_size=128, hidden_size=64, num_layers=2,
                     num_heads=4, num_kv_heads=1, head_dim=32,
                     intermediate_size=128, max_seq_len=64,
                     logit_softcap=30.0)
    model = TransformerLM(cfg)
    ids = jnp.asarray(
        np.random.default_rng(0).integers(0, 128, (2, 16)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    actual = sum(p.size for p in jax.tree.leaves(params))
    assert actual == cfg.num_params()
    # rmsnorm1p: fresh init must be zero-centred (effective scale 1)
    assert float(jnp.abs(params["final_norm"]["scale"]).max()) == 0.0

    def loss(p):
        return loss_fn(model.apply({"params": p}, ids), ids)

    l, g = jax.value_and_grad(loss)(params)
    assert np.isfinite(float(l))
    gnorm = sum(float(jnp.sum(x * x)) for x in jax.tree.leaves(g))
    assert np.isfinite(gnorm) and gnorm > 0
    # softcap bounds the logits
    logits = model.apply({"params": params}, ids)
    assert float(jnp.abs(logits).max()) <= 30.0


def test_causality(tiny_cfg):
    """Changing a future token must not change past logits."""
    model = TransformerLM(tiny_cfg)
    ids = jax.random.randint(jax.random.PRNGKey(1), (1, 12), 0, 100)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    out1 = model.apply({"params": params}, ids)
    ids2 = ids.at[0, 8].set(7)
    out2 = model.apply({"params": params}, ids2)
    assert jnp.allclose(out1[0, :8], out2[0, :8], atol=1e-5)
    assert not jnp.allclose(out1[0, 8:], out2[0, 8:], atol=1e-5)


def test_loss_fn_ignores_minus_100():
    logits = jnp.zeros((1, 4, 10))
    labels = jnp.array([[1, 2, -100, -100]])
    l = loss_fn(logits, labels)
    assert jnp.isclose(l, jnp.log(10.0), atol=1e-5)


def test_scan_vs_loop_equivalence():
    """scan_layers only picks the APPLICATION style; the param layout is
    the stacked [L, ...] tree either way, so the same params drive both
    paths and checkpoints are layout-portable."""
    cfg = get_preset("llama-tiny", dtype=jnp.float32, num_layers=2)
    ids = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0, 100)
    m_scan = TransformerLM(cfg)
    params = m_scan.init(jax.random.PRNGKey(0), ids)["params"]
    out_scan = m_scan.apply({"params": params}, ids)

    import dataclasses
    cfg_loop = dataclasses.replace(cfg, scan_layers=False)
    m_loop = TransformerLM(cfg_loop)
    loop_params = m_loop.init(jax.random.PRNGKey(0), ids)["params"]
    chex.assert_trees_all_equal_shapes(params, loop_params)
    out_loop = m_loop.apply({"params": params}, ids)
    assert jnp.allclose(out_scan, out_loop, atol=1e-5)
    # gradients agree too (the unrolled path autodiffs per layer)
    def l(m):
        def f(p):
            return jnp.mean(m.apply({"params": p}, ids) ** 2)
        return f
    g_scan = jax.grad(l(m_scan))(params)
    g_loop = jax.grad(l(m_loop))(params)
    chex.assert_trees_all_close(g_scan, g_loop, atol=2e-4, rtol=2e-4)


def test_alibi_pos_emb_model():
    """pos_emb='alibi': no rope/learned table, standard slope schedule."""
    import dataclasses
    from torchacc_tpu.models import TransformerLM, get_preset
    from torchacc_tpu.models.transformer import alibi_slopes

    assert np.allclose(alibi_slopes(8),
                       [2 ** (-i) for i in range(1, 9)])
    # non-power-of-two: paper interpolation
    assert len(alibi_slopes(6)) == 6

    mc = get_preset("llama-tiny", vocab_size=64, hidden_size=32,
                    num_layers=2, num_heads=4, num_kv_heads=4,
                    intermediate_size=64, pos_emb="alibi",
                    dtype=jnp.float32)
    model = TransformerLM(mc)
    ids = jnp.zeros((1, 16), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    assert "pos_embed" not in params
    logits = model.apply({"params": params}, ids)
    assert np.isfinite(np.asarray(logits)).all()


def test_attn_dropout_train_vs_eval():
    """Dropout active iff a seed is passed; per-layer + per-seed masks
    differ; eval (no seed) is deterministic."""
    from torchacc_tpu.models import TransformerLM, get_preset

    mc = get_preset("llama-tiny", vocab_size=64, hidden_size=32,
                    num_layers=2, num_heads=4, num_kv_heads=4,
                    intermediate_size=64, attn_dropout=0.5,
                    dtype=jnp.float32)
    model = TransformerLM(mc)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 64, (2, 16)),
                      jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    eval1 = model.apply({"params": params}, ids)
    eval2 = model.apply({"params": params}, ids)
    np.testing.assert_array_equal(np.asarray(eval1), np.asarray(eval2))
    tr1 = model.apply({"params": params}, ids, dropout_seed=jnp.int32(1))
    tr1b = model.apply({"params": params}, ids, dropout_seed=jnp.int32(1))
    tr2 = model.apply({"params": params}, ids, dropout_seed=jnp.int32(2))
    np.testing.assert_array_equal(np.asarray(tr1), np.asarray(tr1b))
    assert np.abs(np.asarray(tr1) - np.asarray(eval1)).max() > 1e-4
    assert np.abs(np.asarray(tr1) - np.asarray(tr2)).max() > 1e-4


def test_attn_dropout_trainer_end_to_end(devices):
    """Trainer passes the step-derived seed on train steps only; the
    deterministic flag disables it."""
    import optax
    import torchacc_tpu as ta
    from torchacc_tpu.models import get_preset
    from torchacc_tpu.train import accelerate

    mc = get_preset("llama-tiny", vocab_size=64, hidden_size=32,
                    num_layers=2, num_heads=4, num_kv_heads=4,
                    intermediate_size=64, attn_dropout=0.3)
    rng = np.random.default_rng(0)
    data = {"input_ids": rng.integers(0, 64, (8, 32)).astype(np.int32)}
    cfg = ta.Config()
    trainer, _ = accelerate(mc, None, cfg, optimizer=optax.sgd(1e-2))
    m = trainer.step(data)
    assert np.isfinite(float(m["loss"]))
    ev1 = float(trainer.eval_step(data))
    ev2 = float(trainer.eval_step(data))
    assert ev1 == ev2  # eval is deterministic

    cfg_det = ta.Config(compute=ta.ComputeConfig(deterministic=True))
    tr_det, _ = accelerate(mc, None, cfg_det, optimizer=optax.sgd(1e-2))
    assert not tr_det._attn_dropout_on


def test_attn_dropout_grad_accum_decorrelated(devices):
    """grad_accum micro-steps draw fresh dropout masks (seed advances per
    micro index); the run still trains and differs from accum=1."""
    import optax
    import torchacc_tpu as ta
    from torchacc_tpu.train import accelerate

    mc = get_preset("llama-tiny", vocab_size=64, hidden_size=32,
                    num_layers=2, num_heads=4, num_kv_heads=4,
                    intermediate_size=64, attn_dropout=0.4,
                    dtype=jnp.float32)
    data = {"input_ids": np.random.default_rng(0)
            .integers(0, 64, (8, 32)).astype(np.int32)}

    def one_loss(accum):
        cfg = ta.Config(grad_accum=accum)
        tr, _ = accelerate(mc, None, cfg, optimizer=optax.sgd(1e-2))
        tr.init(rng=jax.random.PRNGKey(0))
        tr.step(data)
        return float(tr.eval_step(data))

    l1, l4 = one_loss(1), one_loss(4)
    assert np.isfinite(l1) and np.isfinite(l4)
    # same data, same init — only the dropout masks (and accumulation
    # order) differ; with shared masks the two were bit-identical
    assert l1 != l4


def test_layer_pattern_trains_and_matches_uniform(devices):
    """layer_pattern ('sliding','global'): param layout is unchanged
    (pattern is param-free), an all-global pattern equals the uniform
    model exactly, and the pattern model trains sharded."""
    import dataclasses
    import optax

    import torchacc_tpu as ta
    from torchacc_tpu.models import TransformerLM, get_preset
    from torchacc_tpu.train import accelerate

    base = get_preset("llama-tiny", vocab_size=128, hidden_size=64,
                      num_layers=4, num_heads=4, num_kv_heads=2,
                      intermediate_size=128, dtype=jnp.float32)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 128, (2, 24)),
                      jnp.int32)
    params = TransformerLM(base).init(jax.random.PRNGKey(0), ids)["params"]

    # all-'global' pattern == uniform full-attention model, exactly
    pat_global = dataclasses.replace(base, layer_pattern=("global",))
    out_p = TransformerLM(pat_global).apply({"params": params}, ids)
    out_u = TransformerLM(base).apply({"params": params}, ids)
    np.testing.assert_allclose(np.asarray(out_p), np.asarray(out_u),
                               atol=2e-5, rtol=2e-5)

    # sliding/global alternation differs from uniform once seq > window
    pat = dataclasses.replace(base, window=(7, -1),
                              layer_pattern=("sliding", "global"))
    out_sg = TransformerLM(pat).apply({"params": params}, ids)
    assert not np.allclose(np.asarray(out_sg), np.asarray(out_u),
                           atol=1e-3)

    # trains under fsdp x tp sharding (the per-layer loop is GSPMD-auto)
    cfg = ta.Config(dist=ta.DistConfig(
        fsdp=ta.FSDPConfig(size=4, min_weight_size=0),
        tp=ta.TPConfig(size=2)))
    t, _ = accelerate(pat, None, cfg, optimizer=optax.adam(3e-3))
    t.init()
    rng = np.random.default_rng(0)
    data = rng.integers(0, 128, size=(4, 32))
    losses = [float(t.step({"input_ids": data[rng.integers(0, 4, size=8)]
                            .astype(np.int32)})["loss"]) for _ in range(4)]
    assert all(np.isfinite(losses)) and losses[-1] < losses[0], losses


def test_layer_pattern_generate_cached_matches_recompute(devices):
    """Pattern models decode through the pattern-aware cached path —
    same greedy tokens as full-prefix recompute."""
    import dataclasses

    from torchacc_tpu.models import TransformerLM, generate, get_preset

    mc = dataclasses.replace(
        get_preset("llama-tiny", vocab_size=97, hidden_size=64,
                   num_layers=4, num_heads=4, num_kv_heads=2,
                   intermediate_size=128, max_seq_len=64,
                   dtype=jnp.float32),
        window=(5, -1), layer_pattern=("sliding", "global"),
        sandwich_norms=True, attn_logit_softcap=50.0)
    model = TransformerLM(mc)
    prompt = jnp.asarray(np.random.default_rng(0).integers(1, 97, (2, 9)),
                         jnp.int32)
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]
    fast = generate(model, params, prompt, max_new_tokens=10)
    slow = generate(model, params, prompt, max_new_tokens=10,
                    use_cache=False)
    np.testing.assert_array_equal(np.asarray(fast), np.asarray(slow))


def test_generate_param_dtype_cast(devices):
    """generate(param_dtype=bf16) == manually pre-cast params: the cast
    is exactly one tree-wide storage cast (serving precision), applied
    before dispatch so every decode path sees the same weights."""
    from torchacc_tpu.models import TransformerLM, generate, get_preset

    mc = get_preset("llama-tiny", vocab_size=97, hidden_size=64,
                    num_layers=2, num_heads=4, num_kv_heads=2,
                    intermediate_size=128, max_seq_len=64)
    model = TransformerLM(mc)
    prompt = jnp.asarray(np.random.default_rng(1).integers(1, 97, (2, 7)),
                         jnp.int32)
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]
    auto = generate(model, params, prompt, max_new_tokens=8,
                    param_dtype=jnp.bfloat16)
    pre = jax.tree.map(
        lambda x: x.astype(jnp.bfloat16)
        if jnp.issubdtype(x.dtype, jnp.floating) else x, params)
    manual = generate(model, pre, prompt, max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(auto), np.asarray(manual))


@pytest.mark.slow
def test_longrope_composes_with_parallelism(devices):
    """Phi-3.5-style longrope's traced factor switch (jnp.max over
    positions, a reduction that lowers to a small collective when
    positions shard) must compile and run under pp x dp, 1f1b and
    cp-ring, with identical losses — the regression guard for the
    sharding-hazard analysis in _rope's docstring."""
    import optax

    import torchacc_tpu as ta
    from torchacc_tpu.train import accelerate

    d2 = 8
    mc = get_preset(
        "llama-tiny", vocab_size=128, hidden_size=64, num_layers=4,
        num_heads=4, num_kv_heads=2, intermediate_size=128,
        dtype=jnp.float32, max_seq_len=128,
        rope_longrope=(tuple(1.0 + 0.1 * i for i in range(d2)),
                       tuple(2.0 + 0.3 * i for i in range(d2)), 32.0, None))
    ids = np.random.default_rng(0).integers(0, 128, size=(8, 48)).astype(np.int32)

    losses = {}
    for name, dist in (
        ("pp_dp", ta.DistConfig(pp=ta.PPConfig(size=2, num_micro_batches=2),
                                dp=ta.DPConfig(size=2),
                                fsdp=ta.FSDPConfig(size=2,
                                                   min_weight_size=0))),
        ("1f1b", ta.DistConfig(pp=ta.PPConfig(size=2, num_micro_batches=2,
                                              schedule="1f1b"),
                               fsdp=ta.FSDPConfig(size=4,
                                                  min_weight_size=0))),
        ("cp", ta.DistConfig(sp=ta.SPConfig(size=4, mode="ring"),
                             dp=ta.DPConfig(size=2))),
    ):
        cfg = ta.Config(dist=dist)
        cfg.compute.dtype = "float32"
        cfg.compute.param_dtype = "float32"
        t, _ = accelerate(mc, None, cfg, optimizer=optax.adam(1e-3))
        t.init()
        losses[name] = float(t.step({"input_ids": jnp.asarray(ids)})["loss"])
    vals = list(losses.values())
    np.testing.assert_allclose(vals, [vals[0]] * len(vals), rtol=2e-4)
