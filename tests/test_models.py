"""Model-zoo tests: forward shapes, axes resolution, param counts."""

import jax
import jax.numpy as jnp
import pytest

from torchacc_tpu.models import (
    ModelConfig,
    TransformerLM,
    get_preset,
    loss_fn,
    param_axes,
)


@pytest.fixture(scope="module")
def tiny_cfg():
    return get_preset("llama-tiny", dtype=jnp.float32, num_layers=2)


def test_forward_shape(tiny_cfg):
    model = TransformerLM(tiny_cfg)
    ids = jnp.zeros((2, 16), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    logits = model.apply({"params": params}, ids)
    assert logits.shape == (2, 16, tiny_cfg.vocab_size)
    assert logits.dtype == jnp.float32


def test_gpt2_style_forward():
    cfg = get_preset("gpt2-tiny", dtype=jnp.float32, num_layers=2)
    model = TransformerLM(cfg)
    ids = jnp.zeros((2, 16), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    logits = model.apply({"params": params}, ids)
    assert logits.shape == (2, 16, cfg.vocab_size)


def test_param_axes_cover_all_params(tiny_cfg):
    model = TransformerLM(tiny_cfg)
    abstract = jax.eval_shape(
        lambda r: model.init(r, jnp.zeros((1, 8), jnp.int32))["params"],
        jax.random.PRNGKey(0))
    axes = param_axes(abstract)  # raises if any param unmatched
    flat_a = jax.tree.leaves(axes, is_leaf=lambda x: isinstance(x, tuple))
    flat_p = jax.tree.leaves(abstract)
    assert len(flat_a) == len(flat_p)
    for a, p in zip(flat_a, flat_p):
        assert len(a) == p.ndim, (a, p.shape)


def test_param_count_matches_analytic(tiny_cfg):
    model = TransformerLM(tiny_cfg)
    abstract = jax.eval_shape(
        lambda r: model.init(r, jnp.zeros((1, 8), jnp.int32))["params"],
        jax.random.PRNGKey(0))
    actual = sum(p.size for p in jax.tree.leaves(abstract))
    assert actual == tiny_cfg.num_params()


def test_causality(tiny_cfg):
    """Changing a future token must not change past logits."""
    model = TransformerLM(tiny_cfg)
    ids = jax.random.randint(jax.random.PRNGKey(1), (1, 12), 0, 100)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    out1 = model.apply({"params": params}, ids)
    ids2 = ids.at[0, 8].set(7)
    out2 = model.apply({"params": params}, ids2)
    assert jnp.allclose(out1[0, :8], out2[0, :8], atol=1e-5)
    assert not jnp.allclose(out1[0, 8:], out2[0, 8:], atol=1e-5)


def test_loss_fn_ignores_minus_100():
    logits = jnp.zeros((1, 4, 10))
    labels = jnp.array([[1, 2, -100, -100]])
    l = loss_fn(logits, labels)
    assert jnp.isclose(l, jnp.log(10.0), atol=1e-5)


def test_scan_vs_loop_equivalence():
    cfg = get_preset("llama-tiny", dtype=jnp.float32, num_layers=2)
    ids = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0, 100)
    m_scan = TransformerLM(cfg)
    params = m_scan.init(jax.random.PRNGKey(0), ids)["params"]
    out_scan = m_scan.apply({"params": params}, ids)

    import dataclasses
    cfg_loop = dataclasses.replace(cfg, scan_layers=False)
    m_loop = TransformerLM(cfg_loop)
    loop_params = m_loop.init(jax.random.PRNGKey(0), ids)["params"]
    # copy scanned params (leading layer dim) into per-layer trees
    for i in range(cfg.num_layers):
        loop_params[f"layers_{i}"] = jax.tree.map(
            lambda x: x[i], params["layers"])
    out_loop = m_loop.apply({"params": loop_params}, ids)
    assert jnp.allclose(out_scan, out_loop, atol=1e-5)
