"""End-to-end training tests on the 8-device emulated mesh:
DP, FSDP, DP x FSDP, grad accumulation, loss decreases, loader feed.
(Reference analogue: tests/standalone/ta_accelerate.py smoke matrix.)"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import torchacc_tpu as ta
from torchacc_tpu.models import get_preset
from torchacc_tpu.train import accelerate


def _toy_batches(n, batch=8, seq=16, vocab=100, seed=0):
    rng = np.random.default_rng(seed)
    # fixed tiny dataset so the model can overfit
    data = rng.integers(0, vocab, size=(4, seq))
    for i in range(n):
        idx = rng.integers(0, 4, size=batch)
        yield {"input_ids": data[idx].astype(np.int32)}


def _tiny_model(vocab=100):
    return get_preset("llama-tiny", vocab_size=vocab, dtype=jnp.float32,
                      num_layers=2, hidden_size=64, num_heads=4,
                      num_kv_heads=2, intermediate_size=128)


@pytest.mark.parametrize("dist_kwargs", [
    dict(dp=ta.DPConfig(size=8)),
    dict(fsdp=ta.FSDPConfig(size=8, min_weight_size=0)),
    dict(dp=ta.DPConfig(size=2), fsdp=ta.FSDPConfig(size=4, min_weight_size=0)),
])
def test_train_loss_decreases(devices, dist_kwargs):
    cfg = ta.Config(dist=ta.DistConfig(**dist_kwargs))
    import optax
    trainer, loader = accelerate(_tiny_model(), _toy_batches(30), cfg,
                                 optimizer=optax.adam(3e-3))
    losses = [float(trainer.step(b)["loss"]) for b in loader]
    assert losses[-1] < losses[0] * 0.7, losses[:3] + losses[-3:]


def test_fsdp_params_are_sharded(devices):
    cfg = ta.Config(dist=ta.DistConfig(fsdp=ta.FSDPConfig(size=8, min_weight_size=0)))
    trainer, _ = accelerate(_tiny_model(), None, cfg)
    trainer.init()
    # embedding table must be sharded over fsdp (embed dim or vocab dim)
    emb = trainer.state.params["embed_tokens"]["embedding"]
    assert "fsdp" in str(emb.sharding.spec)
    # optimizer state mirrors param sharding
    leaves = [x for x in jax.tree.leaves(trainer.state.opt_state)
              if hasattr(x, "sharding") and x.ndim > 0]
    assert any("fsdp" in str(l.sharding.spec) for l in leaves)


def test_grad_accum_matches_big_batch(devices):
    model = _tiny_model()
    import optax
    batches = list(_toy_batches(1, batch=8))
    cfg1 = ta.Config()
    t1, _ = accelerate(model, None, cfg1, optimizer=optax.sgd(0.1))
    t1.init()
    m1 = t1.step(batches[0])

    cfg2 = ta.Config(grad_accum=4)
    t2, _ = accelerate(model, None, cfg2, optimizer=optax.sgd(0.1))
    t2.init()
    m2 = t2.step(batches[0])
    assert np.isclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)
    p1 = jax.tree.leaves(t1.state.params)
    p2 = jax.tree.leaves(t2.state.params)
    for a, b in zip(p1, p2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=5e-4)


def test_dp_replicas_stay_in_sync(devices):
    cfg = ta.Config(dist=ta.DistConfig(dp=ta.DPConfig(size=8)))
    trainer, loader = accelerate(_tiny_model(), _toy_batches(3), cfg)
    for b in loader:
        trainer.step(b)
    # params are replicated: every shard identical
    p = trainer.state.params["embed_tokens"]["embedding"]
    shards = [np.asarray(s.data) for s in p.addressable_shards]
    for s in shards[1:]:
        np.testing.assert_array_equal(shards[0], s)


def test_grad_accum_uneven_token_counts(devices):
    """Micro-batches with different valid-token counts must still match the
    big-batch step exactly (token-weighted accumulation)."""
    import optax
    model = _tiny_model()
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 100, size=(8, 16)).astype(np.int32)
    labels = ids.copy()
    labels[:4, 4:] = -100  # first half mostly masked
    batch = {"input_ids": ids, "labels": labels}

    t1, _ = accelerate(model, None, ta.Config(), optimizer=optax.sgd(0.1))
    t1.init()
    m1 = t1.step(batch)
    t2, _ = accelerate(model, None, ta.Config(grad_accum=2),
                       optimizer=optax.sgd(0.1))
    t2.init()
    m2 = t2.step(batch)
    assert np.isclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(t1.state.params),
                    jax.tree.leaves(t2.state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=2e-4)


def test_moe_aux_loss_contributes(devices):
    """The router load-balance loss must reach the training objective."""
    cfg_model = _tiny_model()
    import dataclasses
    moe_high = dataclasses.replace(cfg_model, num_experts=4,
                                   router_aux_weight=100.0)
    moe_zero = dataclasses.replace(cfg_model, num_experts=4,
                                   router_aux_weight=0.0)
    batch = next(_toy_batches(1))
    t_hi, _ = accelerate(moe_high, None, ta.Config())
    t_hi.init()
    t_zero, _ = accelerate(moe_zero, None, ta.Config())
    t_zero.init()
    l_hi = float(t_hi.step(batch)["loss"])
    l_zero = float(t_zero.step(batch)["loss"])
    assert l_hi > l_zero + 1.0, (l_hi, l_zero)


def test_async_loader_early_break_no_leak(devices):
    cfg = ta.Config(data=ta.DataConfig(prefetch=1))
    loader = ta.data.AsyncLoader(_toy_batches(100), cfg)
    import threading
    before = threading.active_count()
    for i, b in enumerate(loader):
        if i == 1:
            break
    import time
    time.sleep(1.0)
    assert threading.active_count() <= before + 1


def test_pad_batch_keeps_1d_features():
    from torchacc_tpu.data import pad_batch
    out = pad_batch({"input_ids": np.zeros((4, 5), np.int32),
                     "weight": np.ones((4,), np.float32)}, buckets=[8])
    assert out["input_ids"].shape == (4, 8)
    assert out["weight"].shape == (4,)


def test_fit_loop(devices, tmp_path):
    import optax
    cfg = ta.Config()
    trainer, loader = accelerate(_tiny_model(), _toy_batches(12), cfg,
                                 optimizer=optax.adam(3e-3))
    history = trainer.fit(loader, max_steps=10, log_every=2,
                          eval_loader=list(_toy_batches(2, seed=9)),
                          eval_every=4,
                          checkpoint_dir=str(tmp_path / "run"),
                          checkpoint_every=5)
    assert len(history) == 5
    assert history[-1]["loss"] < history[0]["loss"]
    assert any("eval_loss" in h for h in history)
    from torchacc_tpu.checkpoint import CheckpointManager
    mgr = CheckpointManager(str(tmp_path / "run"))
    assert mgr.latest_step() is not None
    mgr.close()


def test_async_loader_buckets_and_shards(devices):
    cfg = ta.Config(
        dist=ta.DistConfig(dp=ta.DPConfig(size=8)),
        data=ta.DataConfig(buckets=[8, 16, 32]),
    )
    def ragged():
        for n in (5, 9, 17, 40):
            yield {"input_ids": np.zeros((8, n), np.int32)}
    loader = ta.data.AsyncLoader(ragged(), cfg)
    shapes = [b["input_ids"].shape for b in loader]
    assert shapes == [(8, 8), (8, 16), (8, 32), (8, 32)]
