"""Quantized-matmul tests (compute.quant; ops/quantized_matmul.py) and
the FSDP overlap path (perf.overlap_fsdp).

Contracts under test (docs/performance.md "Quantized matmuls" /
"FSDP overlap"):

- int8: the fused Pallas kernel (interpret mode on CPU) and the XLA
  dot agree BITWISE (both accumulate exact int32); both track the f32
  dequantize-then-matmul reference within the documented tolerance.
- Delayed scaling: scales come from the amax HISTORY (previous steps),
  falling back to just-in-time on an empty history; the history state
  rides TrainState.quant, persists through checkpoints, and a resumed
  run continues bitwise-identically to an uninterrupted one.
- ``quant='none'`` (default) changes nothing: no quant state exists
  and the param layout is identical to the pre-quant model.
- A short int8 train run loss-tracks the bf16 run within 2%.
- ``perf.dispatch_depth`` stays trajectory-invariant with quant on.
- ``overlap_fsdp``: forward (and first-step loss) bitwise-identical to
  the non-overlapped unrolled path; multi-step trajectories agree to
  reduction-order tolerance on an fsdp mesh and bitwise without one.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import torchacc_tpu as ta
from torchacc_tpu.models import get_preset
from torchacc_tpu.train import accelerate

pytestmark = pytest.mark.quant

CHAOS_SEED = int(os.environ.get("CHAOS_SEED", "0"))


def _model(**kw):
    base = dict(vocab_size=128, hidden_size=32, num_layers=2, num_heads=2,
                num_kv_heads=2, intermediate_size=64, max_seq_len=64)
    base.update(kw)
    return get_preset("llama-tiny", **base)


def _batches(n, seed=None, rows=8, vocab=128):
    rng = np.random.default_rng(CHAOS_SEED if seed is None else seed)
    return [{"input_ids": rng.integers(0, vocab,
                                       size=(rows, 16)).astype(np.int32)}
            for _ in range(n)]


def _trainer(quant="none", model=None, depth=1, overlap=False,
             dp=None, fsdp=None, lr=1e-2, grad_accum=1, **ckw):
    import optax
    cfg = ta.Config()
    cfg.compute.quant = quant
    for k, v in ckw.items():
        setattr(cfg.compute, k, v)
    cfg.grad_accum = grad_accum
    cfg.perf.dispatch_depth = depth
    cfg.perf.overlap_fsdp = overlap
    if dp or fsdp:
        cfg.dist.dp.size = dp or 1
        cfg.dist.fsdp.size = fsdp or 1
        cfg.dist.fsdp.min_weight_size = 1
        cfg.get_mesh(jax.devices()[: (dp or 1) * (fsdp or 1)])
    tr, _ = accelerate(model or _model(), None, cfg,
                       optimizer=optax.adam(lr))
    return tr


def _run(tr, batches):
    losses = []
    for b in batches:
        losses.append(tr.step(b)["loss"])
    tr.drain()
    jax.block_until_ready(tr.state.params)
    return [float(l) for l in losses]


def _tree_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb))


# -- config -------------------------------------------------------------------

def test_quant_config_validation():
    with pytest.raises(ta.ConfigError):
        ta.Config.from_dict({"compute": {"quant": "int4"}})
    with pytest.raises(ta.ConfigError):
        ta.Config.from_dict({"compute": {"quant": "int8",
                                         "quant_sites": ["attn", "conv"]}})
    with pytest.raises(ta.ConfigError):
        ta.Config.from_dict({"compute": {"quant_amax_history_len": 0}})
    # quant x pp rejected up front (the pipeline regions don't thread
    # the delayed-scaling state)
    with pytest.raises(ta.ConfigError):
        ta.Config.from_dict({"compute": {"quant": "int8"},
                             "dist": {"pp": {"size": 2,
                                             "num_micro_batches": 2}}})
    ta.Config.from_dict({"compute": {"quant": "fp8",
                                     "quant_sites": ["mlp", "head"]}})


# -- op-level numerics --------------------------------------------------------

def test_quantize_dequantize_roundtrip():
    from torchacc_tpu.ops.quantized_matmul import (
        compute_scale, dequantize, quantize,
    )
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(64, 32)) * 3.0, jnp.float32)
    amax = float(jnp.max(jnp.abs(x)))
    for fmt in ("int8", "fp8"):
        s = compute_scale(jnp.max(jnp.abs(x)), fmt)
        xd = dequantize(quantize(x, s, fmt), s)
        err = float(jnp.max(jnp.abs(xd - x)))
        if fmt == "int8":
            # uniform grid: error <= half a quantization step
            assert err <= float(s) * 0.5 + 1e-6
        else:
            # e4m3 is a FLOAT format: error is relative (3 mantissa
            # bits -> <= 2^-4 of the value's magnitude)
            assert err <= amax * 2.0 ** -4 + 1e-6


def test_scale_guard_zero_amax():
    from torchacc_tpu.ops.quantized_matmul import compute_scale
    assert float(compute_scale(jnp.zeros(()), "int8")) == 1.0


def test_kernel_vs_xla_bitwise_and_f32_reference():
    from torchacc_tpu.ops.quantized_matmul import (
        quantized_dot, quantized_matmul_reference,
    )
    rng = np.random.default_rng(CHAOS_SEED)
    x = jnp.asarray(rng.normal(size=(4, 33, 48)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(48, 40)) * 0.05, jnp.float32)
    for fmt in ("int8", "fp8"):
        y_xla = quantized_dot(x, w, 1, fmt=fmt, impl="xla")
        y_pal = quantized_dot(x, w, 1, fmt=fmt, impl="pallas")
        # int8 accumulates exact int32 on both paths; fp8 f32 on both —
        # kernel (interpret mode) and XLA dot agree bitwise
        np.testing.assert_array_equal(np.asarray(y_xla),
                                      np.asarray(y_pal), err_msg=fmt)
        y_ref = quantized_matmul_reference(x, w, 1, fmt=fmt)
        # reference differs only by accumulation order (f32 sums);
        # documented tolerance relative to the output scale
        scale = float(jnp.max(jnp.abs(y_ref))) + 1e-9
        rel = float(jnp.max(jnp.abs(y_xla - y_ref))) / scale
        assert rel < 5e-3, (fmt, rel)


def test_quantized_dot_contract_two_dims():
    from torchacc_tpu.ops.quantized_matmul import (
        quantized_dot, quantized_matmul_reference,
    )
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(2, 5, 2, 16)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(2, 16, 24)) * 0.1, jnp.float32)
    y = quantized_dot(x, w, 2, fmt="int8", impl="xla")
    r = quantized_matmul_reference(x, w, 2, fmt="int8")
    assert y.shape == (2, 5, 24)
    assert float(jnp.max(jnp.abs(y - r))) < 5e-3 * float(
        jnp.max(jnp.abs(r)) + 1e-9)


def test_quantized_dot_grads_flow():
    from torchacc_tpu.ops.quantized_matmul import quantized_dot
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(8, 16)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(16, 8)) * 0.1, jnp.float32)

    def loss(w, x):
        return jnp.sum(quantized_dot(x, w, 1, fmt="int8", impl="xla") ** 2)
    gw, gx = jax.grad(loss, argnums=(0, 1))(w, x)
    # straight-through backward: compute-dtype matmuls on the saved
    # unquantized operands — finite, nonzero
    assert bool(jnp.all(jnp.isfinite(gw))) and float(
        jnp.max(jnp.abs(gw))) > 0
    assert bool(jnp.all(jnp.isfinite(gx)))


def test_delayed_scaling_history_semantics():
    from torchacc_tpu.ops.quantized_matmul import (
        amax_history_init, compute_scale, delayed_scale,
        update_amax_history,
    )
    h = amax_history_init(4)
    # empty history -> just-in-time fallback on the current amax
    s0 = delayed_scale(h, jnp.asarray(2.0), "int8")
    assert float(s0) == float(compute_scale(jnp.asarray(2.0), "int8"))
    h = update_amax_history(h, jnp.asarray(2.0))
    assert np.asarray(h).tolist() == [2.0, 0.0, 0.0, 0.0]
    # the delayed scale reads the HISTORY max, not the current amax
    s1 = delayed_scale(h, jnp.asarray(100.0), "int8")
    assert float(s1) == float(compute_scale(jnp.asarray(2.0), "int8"))
    # the window rolls: 4 more updates evict the 2.0
    for a in (1.0, 1.0, 1.0, 1.0):
        h = update_amax_history(h, jnp.asarray(a))
    assert float(jnp.max(h)) == 1.0


# -- trainer integration ------------------------------------------------------

def test_quant_none_is_legacy_layout():
    tr = _trainer("none")
    tr.init()
    assert tr.state.quant is None
    trq = _trainer("int8")
    trq.init()
    assert trq.state.quant is not None
    # identical param trees (same names, shapes, init stream)
    assert jax.tree.structure(tr.state.params) == \
        jax.tree.structure(trq.state.params)
    assert _tree_equal(tr.state.params, trq.state.params)


def test_quant_histories_advance_and_eval_reads_only(tmp_path):
    tr = _trainer("int8")
    batches = _batches(3)
    _run(tr, batches)
    h0 = jax.device_get(tr.state.quant)
    leaves = jax.tree.leaves(h0)
    assert leaves and all(np.asarray(l).shape[-1] == 16 for l in leaves)
    # 3 steps recorded 3 amax observations
    assert all((np.asarray(l) > 0).sum(axis=-1).max() == 3
               for l in leaves)
    # eval does not mutate the histories
    tr.eval_step(batches[0])
    assert _tree_equal(h0, jax.device_get(tr.state.quant))


def test_int8_loss_tracks_bf16_within_2pct():
    steps = 50
    batches = _batches(steps, seed=7)
    l_bf16 = _run(_trainer("none", lr=5e-3), batches)
    l_int8 = _run(_trainer("int8", lr=5e-3), batches)
    final_ref = np.mean(l_bf16[-5:])
    final_q = np.mean(l_int8[-5:])
    assert abs(final_q - final_ref) / final_ref < 0.02, (final_q, final_ref)


def test_dispatch_depth_invariant_with_quant():
    runs = {}
    for depth in (1, 3):
        tr = _trainer("int8", depth=depth)
        losses = _run(tr, _batches(5, seed=3))
        runs[depth] = (losses, jax.device_get(tr.state.params),
                       jax.device_get(tr.state.quant))
    assert runs[1][0] == runs[3][0]
    assert _tree_equal(runs[1][1], runs[3][1])
    assert _tree_equal(runs[1][2], runs[3][2])


def test_quant_with_grad_accum_threads_history():
    # single-device mesh: grad-accum on the 8-device emulated dp mesh
    # NaNs on the PRE-PR tree too (the known amp/accum env drift —
    # test_bf16_compute_params_matches_baseline sits in the same
    # pre-existing failure set); the quant threading under test is
    # mesh-independent
    tr = _trainer("int8", grad_accum=2, dp=1)
    losses = _run(tr, _batches(2, rows=16))
    assert all(np.isfinite(losses))
    # 2 optimizer steps x 2 micro-steps = 4 observations per site
    leaves = jax.tree.leaves(jax.device_get(tr.state.quant))
    assert all((np.asarray(l) > 0).sum(axis=-1).max() == 4
               for l in leaves)


def test_quant_state_resume_bitwise(tmp_path):
    batches = _batches(8, seed=11)

    def fit(tr, ckdir, max_steps, resume=None):
        return tr.fit(list(batches), max_steps=max_steps,
                      checkpoint_dir=str(ckdir), checkpoint_every=2,
                      log_every=1, resume=resume)

    # uninterrupted 8 steps
    t_full = _trainer("int8")
    h_full = fit(t_full, tmp_path / "full", 8)
    # interrupted at 4, resumed to 8 in a FRESH trainer
    t_a = _trainer("int8")
    fit(t_a, tmp_path / "split", 4)
    t_b = _trainer("int8")
    h_b = fit(t_b, tmp_path / "split", 8, resume="auto")
    proj = lambda h: [(r["step"], r["loss"]) for r in h]  # noqa: E731
    assert proj(h_b) == proj(h_full)[4:]
    assert _tree_equal(jax.device_get(t_full.state.params),
                       jax.device_get(t_b.state.params))
    # the delayed-scaling histories came back bit-exact too — elastic
    # resume stays exact with quant on
    assert _tree_equal(jax.device_get(t_full.state.quant),
                       jax.device_get(t_b.state.quant))


def test_save_blocked_ms_in_records(tmp_path):
    tr = _trainer("none", depth=2)
    hist = tr.fit(list(_batches(4)), max_steps=4,
                  checkpoint_dir=str(tmp_path), checkpoint_every=2,
                  log_every=1)
    assert all("save_blocked_ms" in r for r in hist)
    # a writing step paid a nonzero save path; the checkpoint is valid
    assert any(r["save_blocked_ms"] > 0 for r in hist)
    from torchacc_tpu.checkpoint import CheckpointManager
    mgr = CheckpointManager(str(tmp_path))
    try:
        assert 4 in mgr.valid_steps()
    finally:
        mgr.close()


def test_generate_strips_quant():
    from torchacc_tpu.models.generate import generate
    tr = _trainer("int8")
    _run(tr, _batches(1))
    prompts = jnp.asarray(_batches(1, seed=5)[0]["input_ids"][:2, :8])
    out = generate(tr.model, tr.state.params, prompts, max_new_tokens=4)
    assert out.shape == (2, 12)


def test_head_only_quant_sites_on_unrolled_path():
    # quant_sites=('head',) leaves the BLOCKS plain — the unrolled /
    # overlap loops must not look for per-layer quant state that was
    # never created (regression: KeyError 'layers')
    import dataclasses
    model = dataclasses.replace(_model(), scan_layers=False)
    tr = _trainer("int8", model=model, quant_sites=("head",),
                  fused_kernels=False)
    losses = _run(tr, _batches(2))
    assert all(np.isfinite(losses))
    leaves = jax.tree_util.tree_flatten_with_path(
        jax.device_get(tr.state.quant))[0]
    paths = [jax.tree_util.keystr(p) for p, _ in leaves]
    assert paths == ["['lm_head']['amax_history']"], paths
    assert (np.asarray(leaves[0][1]) > 0).sum() == 2


def test_head_site_with_fused_ce_rejected():
    # the fused-CE loss never reaches the lm_head module — a 'head'
    # quant site would be silently inert; the Trainer rejects it
    from torchacc_tpu.errors import TrainerStateError
    with pytest.raises(TrainerStateError):
        _trainer("int8", quant_sites=("attn", "mlp", "head"))
    # with the materialised head it is accepted
    _trainer("int8", quant_sites=("head",), fused_kernels=False)


def test_head_site_with_tied_embeddings_rejected():
    # the tied head projects through emb.attend — no lm_head dense
    # exists to quantize; a silent no-op would lie to the user
    import dataclasses
    from torchacc_tpu.models.transformer import TransformerLM
    mc = dataclasses.replace(_model(), quant="int8",
                             quant_sites=("head",), tie_embeddings=True)
    with pytest.raises(ValueError, match="tie_embeddings"):
        TransformerLM(mc).init(jax.random.PRNGKey(0),
                               jnp.zeros((1, 8), jnp.int32))


def test_overlap_fsdp_layer_pattern_rejected():
    import dataclasses
    from torchacc_tpu.models.transformer import TransformerLM
    mc = dataclasses.replace(
        _model(), overlap_fsdp=True,
        layer_pattern=("sliding", "global"), window=(4, 0))
    m = TransformerLM(mc)
    v = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    with pytest.raises(NotImplementedError):
        m.apply(v, jnp.zeros((1, 8), jnp.int32))


def test_fsdp_gather_specs_keep_tp_drop_fsdp():
    from jax.sharding import PartitionSpec as P
    from torchacc_tpu.parallel.sharding import (
        DEFAULT_RULES, fsdp_gather_specs,
    )
    tree = {"block": {"attn": {"q_proj": {
        "kernel": jnp.zeros((32, 2, 16))}},
        "mlp": {"up_proj": {"kernel": jnp.zeros((32, 64))}}}}
    specs = fsdp_gather_specs(tree, DEFAULT_RULES)
    # q_proj kernel: ('embed','heads','kv') -> fsdp dropped, tp kept
    assert specs["block"]["attn"]["q_proj"]["kernel"] == P(None, "tp", None)
    # up_proj kernel: ('embed','mlp') -> fsdp dropped, tp kept
    assert specs["block"]["mlp"]["up_proj"]["kernel"] == P(None, "tp")


def test_quant_unsupported_compositions_raise():
    import dataclasses
    from torchacc_tpu.models.transformer import TransformerLM
    mc = _model()
    # layer_pattern x quant
    mcq = dataclasses.replace(
        mc, quant="int8", layer_pattern=("sliding", "global"),
        window=(4, 0))
    m = TransformerLM(mcq)
    v = m.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    with pytest.raises(NotImplementedError):
        m.apply(v, jnp.zeros((1, 8), jnp.int32), mutable=["quant"])


# -- overlap_fsdp -------------------------------------------------------------

def _overlap_pair(devices, quant="none", scan=False, steps=3):
    import dataclasses
    batches = _batches(steps, seed=21)
    out = {}
    for overlap in (False, True):
        model = dataclasses.replace(_model(), scan_layers=scan)
        tr = _trainer(quant, model=model, overlap=overlap, dp=2, fsdp=4)
        out[overlap] = (_run(tr, batches),
                        jax.device_get(tr.state.params))
    return out


def test_overlap_fsdp_first_step_bitwise_and_close(devices):
    out = _overlap_pair(devices)
    l_off, l_on = out[False][0], out[True][0]
    # forward is bitwise-identical: the very first loss (computed before
    # any backward-perturbed params) matches exactly
    assert l_off[0] == l_on[0]
    # later steps agree to reduction-order tolerance (backward weight
    # grads all-reduce vs reduce-scatter in a different order)
    np.testing.assert_allclose(l_off, l_on, rtol=2e-2)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a, np.float32), np.asarray(b, np.float32),
        rtol=0.1, atol=5e-2), out[False][1], out[True][1])


def test_overlap_fsdp_no_fsdp_axis_fully_bitwise():
    # without a live fsdp extent the gather constraint is a no-op and
    # the overlap loop must be bitwise-identical to the unrolled path
    import dataclasses
    batches = _batches(3, seed=23)
    out = {}
    for overlap in (False, True):
        model = dataclasses.replace(_model(), scan_layers=False)
        tr = _trainer("none", model=model, overlap=overlap)
        out[overlap] = (_run(tr, batches),
                        jax.device_get(tr.state.params))
    assert out[False][0] == out[True][0]
    assert _tree_equal(out[False][1], out[True][1])


def test_overlap_fsdp_with_remat_first_step_bitwise(devices):
    # the gather sits INSIDE the remat region (residuals stay
    # fsdp-sharded; backward re-gathers) — values must still match the
    # non-overlapped remat path.  The remat+unrolled base path itself
    # is broken on this flax/jax combo (JaxTransformError — the same
    # PRE-EXISTING env drift that fails test_gc_cnt_nonscan_path, with
    # or without overlap), so skip when the BASELINE cannot run.
    import dataclasses
    import flax.errors
    import optax
    batches = _batches(2, seed=29)
    out = {}
    for overlap in (False, True):
        cfg = ta.Config()
        cfg.memory.gc = True
        cfg.memory.gc_policy = "dots"
        cfg.perf.overlap_fsdp = overlap
        cfg.dist.dp.size = 2
        cfg.dist.fsdp.size = 4
        cfg.dist.fsdp.min_weight_size = 1
        cfg.get_mesh(jax.devices()[:8])
        model = dataclasses.replace(_model(), scan_layers=False)
        tr, _ = accelerate(model, None, cfg, optimizer=optax.adam(1e-2))
        try:
            out[overlap] = _run(tr, batches)
        except flax.errors.JaxTransformError:
            assert not overlap, \
                "overlap broke a remat path the baseline can run"
            pytest.skip("remat + unrolled layers unrunnable on this "
                        "flax/jax (pre-existing env drift — see "
                        "test_gc_cnt_nonscan_path)")
    assert out[False][0] == out[True][0]
    np.testing.assert_allclose(out[False], out[True], rtol=2e-2)


def test_overlap_fsdp_composes_with_quant(devices):
    out = _overlap_pair(devices, quant="int8", steps=2)
    assert out[False][0][0] == out[True][0][0]
    np.testing.assert_allclose(out[False][0], out[True][0], rtol=2e-2)


# -- shard-local digest subsample ---------------------------------------------

def test_subsample_strides_prefer_unsharded_dims():
    from torchacc_tpu.resilience.sdc import _subsample_strides
    # dim1 sharded: the whole bound lands on dim0
    s = _subsample_strides((1024, 64), 256, [False, True])
    assert s[1] == 1 and s[0] >= 256
    kept = -(-1024 // s[0]) * 64
    assert kept <= 256 * 2  # ~bound (per-dim ceil slack)
    # no sharding info: largest dim strided first
    s2 = _subsample_strides((8, 4096), 128, [False, False])
    assert s2[1] > 1


def test_leaf_digest_spec_steered_subsample_properties():
    from jax.sharding import PartitionSpec as P
    from torchacc_tpu.resilience.sdc import _leaf_digest
    x = jnp.asarray(np.random.default_rng(CHAOS_SEED).normal(
        size=(64, 64)), jnp.float32)
    hit_no, hit_yes = jnp.zeros((), bool), jnp.ones((), bool)
    mask = jnp.asarray(0x00010000, jnp.uint32)
    spec = P(None, "fsdp")
    a = _leaf_digest(x, hit_no, mask, max_elems=128, spec=spec)
    b = _leaf_digest(x, hit_no, mask, max_elems=128, spec=spec)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # bound below the leaf size changes what is folded
    full = _leaf_digest(x, hit_no, mask)
    assert not np.array_equal(np.asarray(a), np.asarray(full))
    # element 0 (the chaos flip site) stays covered under the
    # spec-steered per-dim strides
    f = _leaf_digest(x, hit_yes, mask, max_elems=128, spec=spec)
    assert not np.array_equal(np.asarray(a)[:2], np.asarray(f)[:2])


def test_sdc_check_with_bounded_digests_and_quant(devices):
    # per-step SDC digests with the bounded (per-dim-stride) fold +
    # quant: clean run never flags, losses finite.  dp-only mesh: the
    # digest shard_map on a live-fsdp CPU mesh trips a PRE-EXISTING
    # jax-0.4.37 SPMD PartitionId limitation unrelated to the bound
    # (verified identical on the pre-PR tree); the shard-local stride
    # property itself is unit-tested above.
    import optax
    cfg = ta.Config()
    cfg.compute.quant = "int8"
    cfg.dist.dp.size = 2
    cfg.resilience.sdc_check_interval_steps = 1
    cfg.resilience.sdc_digest_max_elems = 64
    cfg.get_mesh(jax.devices()[:2])
    tr, _ = accelerate(_model(), None, cfg, optimizer=optax.adam(1e-3))
    losses = _run(tr, _batches(3))
    assert all(np.isfinite(losses))
